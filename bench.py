"""Benchmark suite: the five BASELINE configs, on the local accelerator.

Prints ONE cumulative JSON line after EACH finished config — the LAST
stdout line is always the complete result so far (kill-safe):
  {"metric": ..., "value": <config-1 examples/sec/chip>, "unit": ...,
   "vs_baseline": <config-1 loss-parity ratio>, "detail": {"configs": {...}}}

Configs (BASELINE.json; reference procedure examples/run_photon_ml_driver.sh
+ dev-scripts/libsvm_text_to_trainingexample_avro.py):
  1. a1a logistic regression, L2, LBFGS
  2. a1a linear + Poisson with L1 / elastic-net, TRON vs LBFGS
  3. a1a smoothed-hinge linear SVM with box-constrained coefficients
  4. GLMix fixed-effect + per-user random-effect logistic, MovieLens-1M shape
  5. full GAME FE + per-user RE + per-item RE + factored-MF, MovieLens-20M shape

Data: zero network egress, so every corpus is a seeded statistically-matched
synthetic replica (photon_ml_tpu/data/synthetic_bench.py documents the
matched statistics); every config is labelled "synthetic-replica".

Reference-NLL capture ("x64 parity mode", VERDICT r2 item 1):
  - configs 1-3: scipy L-BFGS-B optimum in float64 on the identical data
    (L1/elastic-net via the positive/negative-part smooth reformulation,
    box constraints via L-BFGS-B bounds).  nll_rel_gap compares the full
    regularized objective, evaluated in float64 at our coefficients,
    against that optimum.
  - configs 4-5: the same GAME fit re-run in float64 on CPU in a
    subprocess (JAX_ENABLE_X64=1 JAX_PLATFORMS=cpu) with the reference's
    default optimizer settings — the stand-in for the JVM double-precision
    baseline.  nll_rel_gap = (our_obj - ref_obj) / |ref_obj|.

Phase timings: GAME entries carry the contiguous span breakdown
(phase_timings_s) and phase_coverage = sum(spans)/fit_s.  On THIS rig the
"build/coordinates" and "init/*" spans are dominated by host->device
transfer over the ~5 MB/s accelerator tunnel (e.g. ~30s for ~150 MB of
shard data); on directly-attached hardware that cost is bandwidth-trivial.

Throughput accounting: examples/sec/chip counts one example per full data
pass; LBFGS/OWLQN report their EXACT fused value+gradient evaluation count
(initial eval + first trial + every line-search backtrack — tracked by the
solver as fg_count); TRON counts outer iterations PLUS its actual
Hessian-vector CG passes.  No pass is free in this accounting.  GAME fits count n_train * outer_iterations /
fit_wall.  HBM traffic estimate (config 1): 2 reads of X per pass
(margin + gradient assembly) -> achieved GB/s and fraction of v5e peak
(819 GB/s) when running on a v5e-class chip.
"""
from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time

import numpy as np

V5E_HBM_GBPS = 819.0  # public v5e spec; used only for the utilization frac

_SCALE = float(os.environ.get("BENCH_SCALE", "1.0"))
_CONFIGS = os.environ.get("BENCH_CONFIGS", "1,2,3,4,5,6,7").split(",")


# --------------------------------------------------------------------------
# float64 host-side objective (parity oracle)
# --------------------------------------------------------------------------

def _np_loss(task: str):
    """(z, y) -> per-row loss + d/dz, mirroring photon_ml_tpu/ops/losses.py."""
    if task == "logistic_regression":
        def f(z, y):
            yy = np.where(y > 0.5, 1.0, -1.0)
            return np.logaddexp(0.0, -yy * z)

        def df(z, y):
            from scipy.special import expit
            yy = np.where(y > 0.5, 1.0, -1.0)
            return -yy * expit(-yy * z)
    elif task == "linear_regression":
        f = lambda z, y: 0.5 * (z - y) ** 2
        df = lambda z, y: z - y
    elif task == "poisson_regression":
        f = lambda z, y: np.exp(z) - y * z
        df = lambda z, y: np.exp(z) - y
    elif task == "smoothed_hinge_loss_linear_svm":
        def f(z, y):
            t = np.where(y > 0.5, 1.0, -1.0) * z
            return np.where(t < 0, 0.5 - t,
                            np.where(t < 1, 0.5 * (1 - t) ** 2, 0.0))

        def df(z, y):
            yy = np.where(y > 0.5, 1.0, -1.0)
            t = yy * z
            return yy * np.where(t < 0, -1.0, np.where(t < 1, t - 1.0, 0.0))
    else:
        raise ValueError(task)
    return f, df


def _is_sparse(x) -> bool:
    import scipy.sparse as sp
    return sp.issparse(x)


def _as_f64(x):
    """float64 view/copy, sparse-preserving."""
    if _is_sparse(x):
        return x.astype(np.float64)
    return np.asarray(x).astype(np.float64, copy=False)


def np_objective_value(task, x64, y64, w, l1=0.0, l2=0.0) -> float:
    """Full regularized objective in float64 at coefficients w."""
    f, _ = _np_loss(task)
    z = x64 @ np.asarray(w, np.float64)
    v = float(f(z, y64).sum())
    if l1:
        v += l1 * float(np.abs(w).sum())
    if l2:
        v += 0.5 * l2 * float(w @ w)
    return v


def scipy_ref(task, x, y, l1=0.0, l2=0.0, bounds=None):
    """float64 reference optimum.  L1 > 0 uses the w = p - q smooth
    reformulation (exact); bounds is an optional (lo, hi) box.  x/y may
    already be float64 (astype with copy=False avoids a second copy)."""
    from scipy.optimize import minimize
    x64 = _as_f64(x)
    y64 = np.asarray(y).astype(np.float64, copy=False)
    f, df = _np_loss(task)
    d = x64.shape[1]
    opts = {"maxiter": 3000, "ftol": 1e-15, "gtol": 1e-10}
    if l1 == 0.0:
        def fg(w):
            z = x64 @ w
            g = x64.T @ df(z, y64) + l2 * w
            return float(f(z, y64).sum() + 0.5 * l2 * (w @ w)), g

        b = None if bounds is None else [bounds] * d
        r = minimize(fg, np.zeros(d), jac=True, method="L-BFGS-B",
                     bounds=b, options=opts)
        w = r.x
    else:
        assert bounds is None

        def fg(pq):
            p, q = pq[:d], pq[d:]
            w = p - q
            z = x64 @ w
            g = x64.T @ df(z, y64) + l2 * w
            val = f(z, y64).sum() + l1 * (p.sum() + q.sum()) + 0.5 * l2 * (w @ w)
            return float(val), np.concatenate([g + l1, -g + l1])

        r = minimize(fg, np.zeros(2 * d), jac=True, method="L-BFGS-B",
                     bounds=[(0, None)] * (2 * d), options=opts)
        w = r.x[:d] - r.x[d:]
    return w, np_objective_value(task, x64, y64, w, l1, l2)


# --------------------------------------------------------------------------
# single-GLM solve benchmark (configs 1-3)
# --------------------------------------------------------------------------

def time_glm_solve(task, x_np, y_np, opt_cfg, reg, lam, reps=3,
                   feature_dtype=None):
    """jit solve() once, then time `reps` runs with distinct starts (the
    accelerator tunnel memoizes bit-identical executions)."""
    import jax
    import jax.numpy as jnp
    from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
    from photon_ml_tpu.optim import solve

    if _is_sparse(x_np):
        from photon_ml_tpu.ops.features import PaddedSparse, as_feature_matrix
        # same selection production makes (CSC only at >= CSC_MIN_COLS):
        # the bench must measure the shipped code path
        x = as_feature_matrix(x_np, with_csc=True)
        if feature_dtype is not None:
            # scipy cannot hold bf16; cast the padded values on the way in
            x = PaddedSparse(
                x.indices, x.values.astype(feature_dtype), x.num_cols,
                x.csc_row,
                None if x.csc_val is None else x.csc_val.astype(feature_dtype),
                x.csc_end)
    else:
        x = (jnp.asarray(x_np) if feature_dtype is None
             else jnp.asarray(x_np, feature_dtype))
    y = jnp.asarray(y_np)
    obj = GLMObjective(TASK_LOSSES[task], x, y)
    run = jax.jit(lambda o, x0, lam_: solve(o, x0, opt_cfg, reg, lam_))
    d = x.shape[1]
    # solver state (coefficients, step sizes) stays float32 even when
    # features are stored bf16 (speed mode)
    state_dt = y.dtype if y.dtype in (jnp.float32, jnp.float64) else jnp.float32
    lam_j = jnp.asarray(lam, state_dt)
    # the tunnel memoizes bit-identical executions ACROSS runs too, so the
    # start point must be unique per rep AND per process — a fixed salt
    # schedule re-served from cache once made this bench report absurd
    # numbers on its second invocation
    salt = (time.time_ns() % 997) * 1e-9
    t0 = time.perf_counter()
    res = run(obj, jnp.full((d,), salt, state_dt), lam_j)
    float(res.value)  # device->host readback: the only true sync point —
    # over the tunnel, block_until_ready returns before execution finishes
    compile_s = time.perf_counter() - t0
    # pipelined measurement: dispatch all reps (distinct, run-unique
    # starts), then read every result back.  The readbacks sync the whole
    # chain, so wall/reps is steady-state per-solve time with the tunnel's
    # ~60ms dispatch latency amortized — the shape a real lambda sweep has.
    t0 = time.perf_counter()
    results = [run(obj, jnp.full((d,), 1e-6 * (r + 1) + salt, state_dt),
                   lam_j) for r in range(reps)]
    for rr in results:
        float(rr.value)
    wall = (time.perf_counter() - t0) / reps
    return results[-1], wall, compile_s


def glm_entry(task, x_np, y_np, opt_cfg, reg, lam, l1, l2, label, reps=3,
              feature_dtype=None, data_seed=0):
    """One measured solve + float64 parity vs the scipy optimum.  The scipy
    optimum is deterministic in (task, data seed, shape, lambdas, box) — the timing
    salt only perturbs OUR start point, never the data — so it is cached in
    bench_ref_cache.json alongside the GAME references."""
    res, wall, compile_s = time_glm_solve(task, x_np, y_np, opt_cfg, reg,
                                          lam, reps,
                                          feature_dtype=feature_dtype)
    w = np.asarray(res.x, np.float64)
    x64, y64 = _as_f64(x_np), y_np.astype(np.float64)
    t0 = time.perf_counter()
    bounds = (None if opt_cfg.box_lower is None else
              (opt_cfg.box_lower[0], opt_cfg.box_upper[0]))
    # keyed by the PROBLEM (task/data/lambdas), not the display label:
    # entries that share a problem (tron-vs-lbfgs, f32-vs-bf16) share the
    # reference optimum.  The data fingerprint makes a generator change
    # invalidate the entry instead of silently reusing a stale optimum.
    key = (f"scipy:{task}:seed{data_seed}:{x_np.shape[0]}x{x_np.shape[1]}"
           f":l1={l1}:l2={l2}:box={bounds}"
           f":fp={_data_fingerprint(x_np, y_np)}")
    cached = _ref_cache_get_raw(key)
    if cached is not None and "ref_s" in cached:
        # the cached CPU solve time keeps the TPU-vs-CPU wall-clock ratio in
        # the entry even when the optimum itself is served from cache
        ref_nll, ref_s = cached["ref_nll"], cached["ref_s"]
    else:
        _, ref_nll = scipy_ref(task, x64, y64, l1=l1, l2=l2, bounds=bounds)
        ref_s = time.perf_counter() - t0
        _ref_cache_put_raw(key, {"ref_nll": ref_nll, "ref_s": round(ref_s, 2)})
    our_nll = np_objective_value(task, x64, y64, w, l1, l2)
    n = x_np.shape[0]
    iters = int(res.iterations)
    # one "pass" = a fused value+gradient sweep.  LBFGS/OWLQN report their
    # exact fused-evaluation count (initial eval + first trial + every
    # line-search backtrack); TRON pays one pass per iteration plus one per
    # Hessian-vector CG step.  Nothing is "free" in this accounting.
    if res.fg_count is not None:
        passes = int(res.fg_count)
    else:
        passes = iters
    if res.hv_count is not None:
        passes += int(res.hv_count)
    entry_passes = max(passes, 1)
    return {
        "name": label, "task": task, "n": n, "d": x_np.shape[1],
        "data": "synthetic-replica",
        "optimizer": opt_cfg.optimizer.value, "iterations": iters,
        "data_passes": entry_passes,
        "examples_per_sec_per_chip": round(n * entry_passes / wall, 1),
        "wall_s": round(wall, 4), "compile_s": round(compile_s, 2),
        "ref_s": round(ref_s, 2),
        "final_nll": our_nll, "ref_nll": ref_nll,
        "nll_rel_gap": round((our_nll - ref_nll) / abs(ref_nll), 9),
    }


def bench_config1():
    from photon_ml_tpu.data.synthetic_bench import make_a1a_like
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    replicas = max(int(1024 * _SCALE), 1)
    x, y = make_a1a_like(replicas, "logistic", seed=42)
    lam = 1.0
    entry = glm_entry(
        "logistic_regression", x, y,
        OptimizerConfig(max_iterations=100, tolerance=1e-9),
        RegularizationContext(RegularizationType.L2), lam, 0.0, lam,
        "a1a_logistic_lbfgs_l2", reps=10, data_seed=42)
    # HBM traffic estimate: X read twice per fused value+grad pass
    bytes_moved = 2 * entry["n"] * entry["d"] * 4 * max(entry["iterations"], 1)
    gbps = bytes_moved / entry["wall_s"] / 1e9
    entry["achieved_gbps_est"] = round(gbps, 1)
    entry["hbm_frac_of_v5e_peak"] = round(gbps / V5E_HBM_GBPS, 3)

    # speed mode: features stored bf16 (a1a features are 0/1, EXACT in
    # bf16, so this is lossless here; solver state stays f32) — halves the
    # bandwidth term of each pass
    import jax.numpy as jnp
    bf16 = glm_entry(
        "logistic_regression", x, y,
        OptimizerConfig(max_iterations=100, tolerance=1e-9),
        RegularizationContext(RegularizationType.L2), lam, 0.0, lam,
        "a1a_logistic_lbfgs_l2_bf16_features", reps=10,
        feature_dtype=jnp.bfloat16, data_seed=42)
    bf16["note"] = ("features stored bfloat16 (exact for a1a's binary "
                    "features); solver state float32")
    return [entry, bf16]


def bench_config2():
    from photon_ml_tpu.data.synthetic_bench import make_a1a_like
    from photon_ml_tpu.optim import (OptimizerConfig, OptimizerType,
                                     RegularizationContext, RegularizationType)
    replicas = max(int(256 * _SCALE), 1)
    out = []
    for task_key, task in (("linear", "linear_regression"),
                           ("poisson", "poisson_regression")):
        x, y = make_a1a_like(replicas, task_key, seed=52)
        # L1 / elastic-net via OWLQN-LBFGS (the reference pairs L1 with OWLQN)
        lam = 0.1
        en = RegularizationContext(RegularizationType.ELASTIC_NET,
                                   elastic_net_alpha=0.5)
        out.append(glm_entry(
            task, x, y, OptimizerConfig(max_iterations=200, tolerance=1e-10),
            en, lam, 0.5 * lam, 0.5 * lam, f"a1a_{task_key}_owlqn_elastic_net", data_seed=52))
        l1 = RegularizationContext(RegularizationType.L1)
        out.append(glm_entry(
            task, x, y, OptimizerConfig(max_iterations=200, tolerance=1e-10),
            l1, lam, lam, 0.0, f"a1a_{task_key}_owlqn_l1", data_seed=52))
        # TRON vs LBFGS on the smooth L2 problem (reference pairs TRON w/ L2)
        lam2 = 1.0
        l2 = RegularizationContext(RegularizationType.L2)
        for opt in (OptimizerType.TRON, OptimizerType.LBFGS):
            out.append(glm_entry(
                task, x, y,
                OptimizerConfig(optimizer=opt,
                                max_iterations=(30 if opt == OptimizerType.TRON
                                                else 200),
                                tolerance=1e-10),
                l2, lam2, 0.0, lam2, f"a1a_{task_key}_{opt.value}_l2",
                data_seed=52))
    return out


def bench_config3():
    from photon_ml_tpu.data.synthetic_bench import make_a1a_like
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    replicas = max(int(256 * _SCALE), 1)
    x, y = make_a1a_like(replicas, "hinge", seed=62)
    d = x.shape[1]
    lam = 1.0
    lo, hi = -0.5, 0.5
    cfg = OptimizerConfig(max_iterations=200, tolerance=1e-10,
                          box_lower=(lo,) * d, box_upper=(hi,) * d)
    entry = glm_entry(
        "smoothed_hinge_loss_linear_svm", x, y, cfg,
        RegularizationContext(RegularizationType.L2), lam, 0.0, lam,
        "a1a_smoothed_hinge_box_lbfgs_l2", data_seed=62)
    entry["box"] = [lo, hi]
    return [entry]


# --------------------------------------------------------------------------
# GAME fits (configs 4-5)
# --------------------------------------------------------------------------

def _game_setup(scale: str, n_rows, seed: int, dtype, mode: str,
                salt: float = 0.0, hbm_budget=None):
    """Build the (train, val) GameDataset pair + training config.

    `mode`: "glmix" = FE + per-user RE (config 4); "convex" adds the
    per-item RE (config 5's hard-gated convex subset); "full" adds the
    non-convex factored-MF coordinate on top (config 5).
    `salt` scales features by (1 + salt): a per-invocation value applied
    identically to both sides of the parity pair, so array VALUES are
    run-unique (defeating the tunnel's cross-run execution memoization)
    while shapes — and therefore the warm compile cache — are stable.
    `hbm_budget` (bytes) enables out-of-core mode: FE shards over budget
    chunk-stream and inactive coordinates evict between visits — what lets
    config 5 run MORE corpus rows than fit in HBM resident."""
    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.data.synthetic_bench import (make_movielens_like,
                                                    movielens_shards)
    from photon_ml_tpu.game import (FactoredRandomEffectCoordinateConfig,
                                    FixedEffectCoordinateConfig,
                                    GameTrainingConfig, GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)

    if scale == "yahoo":
        return _yahoo_setup(n_rows, seed, dtype, salt)
    with_item = mode in ("convex", "full")
    ml = make_movielens_like(scale, seed=seed, n_rows=n_rows)
    shards = {k: (v * (1.0 + salt)).astype(dtype)
              for k, v in movielens_shards(ml).items()}
    if not with_item:
        shards.pop("per_item")
    entity_ids = {"userId": ml.user_ids}
    if with_item:
        entity_ids["itemId"] = ml.item_ids
    ds = build_game_dataset(ml.response.astype(dtype), shards,
                            entity_ids=entity_ids)
    # deterministic 95/5 split shared by the f32 run and the f64 ref run
    rng = np.random.default_rng(seed + 99)
    val_mask = rng.uniform(size=ds.num_rows) < 0.05
    train = ds.subset(np.flatnonzero(~val_mask))
    val = ds.subset(np.flatnonzero(val_mask))

    l2 = RegularizationContext(RegularizationType.L2)
    opt = lambda w, it: GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=it),
        regularization=l2, regularization_weight=w)
    coords = {
        "fixed": FixedEffectCoordinateConfig("global", opt(1.0, 100)),
        "perUser": RandomEffectCoordinateConfig(
            "userId", "per_user", opt(1.0, 100),
            active_data_upper_bound=512),
    }
    seq = ["fixed", "perUser"]
    if with_item:
        coords["perItem"] = RandomEffectCoordinateConfig(
            "itemId", "per_item", opt(1.0, 100),
            active_data_upper_bound=512)
        seq = ["fixed", "perUser", "perItem"]
    if mode == "full":
        coords["perUserMF"] = FactoredRandomEffectCoordinateConfig(
            "userId", "per_user", latent_dim=8,
            optimization=opt(1.0, 50), latent_optimization=opt(1.0, 50),
            active_data_upper_bound=256)
        seq = ["fixed", "perUser", "perItem", "perUserMF"]
    cfg = GameTrainingConfig(task_type="logistic_regression",
                             coordinates=coords, updating_sequence=seq,
                             num_outer_iterations=2, seed=seed,
                             hbm_budget_bytes=hbm_budget)
    return train, val, cfg


def _yahoo_setup(n_rows, seed, dtype, salt):
    """Yahoo-integration-fixture shape (reference: DriverTest.scala:96-98
    asserts 14,983 fixed-effect coefficients): WIDE sparse FE + per-user +
    per-item random effects."""
    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.data.synthetic_bench import make_yahoo_like
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameTrainingConfig, GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)

    yl = make_yahoo_like(n_rows, seed=seed)
    shards = {"global": (yl.x_global * (1.0 + salt)).astype(dtype),
              "per_user": ((yl.x_user * (1.0 + salt)).astype(dtype)),
              "per_item": ((yl.x_item * (1.0 + salt)).astype(dtype))}
    ds = build_game_dataset(yl.response.astype(dtype), shards,
                            entity_ids={"userId": yl.user_ids,
                                        "itemId": yl.item_ids})
    rng = np.random.default_rng(seed + 99)
    val_mask = rng.uniform(size=ds.num_rows) < 0.05
    train = ds.subset(np.flatnonzero(~val_mask))
    val = ds.subset(np.flatnonzero(val_mask))

    l2 = RegularizationContext(RegularizationType.L2)
    opt = lambda w, it: GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=it),
        regularization=l2, regularization_weight=w)
    cfg = GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", opt(1.0, 100)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", opt(1.0, 100),
                active_data_upper_bound=512),
            "perItem": RandomEffectCoordinateConfig(
                "itemId", "per_item", opt(1.0, 100),
                active_data_upper_bound=512),
        },
        updating_sequence=["fixed", "perUser", "perItem"],
        num_outer_iterations=2, seed=seed)
    return train, val, cfg


def _embed_telemetry(result: dict) -> dict:
    """Attach the process-wide telemetry snapshot to a bench result so
    every BENCH_*.json entry carries retrace counts, host-blocked
    fractions, stream/mesh transfer totals, and checkpoint/quarantine
    counters — perf trajectories with causes attached, not just wall
    clock."""
    try:
        from photon_ml_tpu import telemetry
        result.setdefault("detail", {})["telemetry"] = telemetry.snapshot()
    except Exception as e:  # a broken snapshot must not kill a bench run
        result.setdefault("detail", {})["telemetry"] = {
            "error": f"{type(e).__name__}: {e}"}
    return result


def _log(msg):
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def run_game(scale, n_rows, seed, dtype, mode, with_validation=True,
             salt=0.0, hbm_budget=None, outer=None, scheduled=False):
    from photon_ml_tpu.game import GameEstimator
    t0 = time.perf_counter()
    train, val, cfg = _game_setup(scale, n_rows, seed, dtype, mode, salt,
                                  hbm_budget=hbm_budget)
    if outer is not None or scheduled:
        import dataclasses as _dc
        cfg = _dc.replace(
            cfg,
            num_outer_iterations=(outer if outer is not None
                                  else cfg.num_outer_iterations),
            solver_schedule=(_inexact_schedule() if scheduled
                             else cfg.solver_schedule))
    build_s = time.perf_counter() - t0
    _log(f"game[{scale}/{n_rows}/{dtype().dtype}]: dataset built in "
         f"{build_s:.0f}s; fitting")
    t0 = time.perf_counter()
    est = GameEstimator(cfg)
    result = est.fit(train,
                     validation_dataset=val if with_validation else None,
                     evaluator_specs=["AUC"] if with_validation else None)
    fit_s = time.perf_counter() - t0
    _log(f"game[{scale}/{n_rows}/{dtype().dtype}]: fit done in {fit_s:.0f}s")
    return result, train.num_rows, cfg.num_outer_iterations, build_s, fit_s


_REF_CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_ref_cache.json")


_COMPILE_TRACKER = None


def _global_compile_tracker():
    global _COMPILE_TRACKER
    if _COMPILE_TRACKER is None:
        from photon_ml_tpu.utils.jax_cache import CompileTimeTracker
        _COMPILE_TRACKER = CompileTimeTracker().install()
    return _COMPILE_TRACKER


_FP_CACHE: dict = {}


def _data_fingerprint(x_np, y_np) -> str:
    """Short content hash of a generated (x, y) pair, memoized per array
    identity (the bench reuses one dataset across several entries)."""
    import hashlib

    from photon_ml_tpu.data.synthetic_bench import GENERATOR_VERSION
    memo_key = (id(x_np), id(y_np))
    if memo_key not in _FP_CACHE:
        h = hashlib.blake2b(digest_size=8)
        if _is_sparse(x_np):
            csr = x_np.tocsr()
            for part in (csr.data, csr.indices, csr.indptr):
                h.update(np.ascontiguousarray(part).data)
        else:
            h.update(np.ascontiguousarray(x_np).data)
        h.update(np.ascontiguousarray(y_np).data)
        # pin the arrays: an id()-keyed memo without a reference would hand a
        # recycled address the previous dataset's fingerprint
        _FP_CACHE[memo_key] = (x_np, y_np,
                               f"{GENERATOR_VERSION}-{h.hexdigest()}")
    return _FP_CACHE[memo_key][2]


def _ref_cache_key(scale, n_rows, seed, mode, outer=None,
                   scheduled=False) -> str:
    # the GAME data is generated inside run_game, so the key carries the
    # generator version (bumped on any generator change) instead of a hash.
    # `outer`/`scheduled` suffix keys for --inexact reference fits (custom
    # outer count / default-schedule fit); the defaults keep every existing
    # key unchanged
    from photon_ml_tpu.data.synthetic_bench import GENERATOR_VERSION
    suffix = "" if outer is None else f":outer{outer}"
    suffix += ":sched" if scheduled else ""
    return f"{scale}:{n_rows}:{seed}:{mode}{suffix}:v={GENERATOR_VERSION}"


def _ref_cache_get_raw(key: str):
    try:
        with open(_REF_CACHE_PATH) as f:
            return json.load(f).get(key)
    except (OSError, ValueError):
        return None


def _ref_cache_put_raw(key: str, entry) -> None:
    try:
        with open(_REF_CACHE_PATH) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        cache = {}
    cache[key] = entry
    with open(_REF_CACHE_PATH, "w") as f:
        json.dump(cache, f, indent=1, sort_keys=True)


def _ref_cache_get(scale, n_rows, seed, mode, outer=None, scheduled=False):
    """Cached float64-CPU reference NLL (computed at salt=0; the run salt
    perturbs the objective by ~1e-8 relative — far below the 1e-4 parity
    gate).  The cache is committed so a bench invocation does not pay the
    ~30-minute single-core float64 refit; regenerate any entry by deleting
    it (the subprocess path recomputes and re-saves)."""
    return _ref_cache_get_raw(_ref_cache_key(scale, n_rows, seed, mode,
                                             outer, scheduled))


def _ref_cache_put(scale, n_rows, seed, mode, entry, outer=None,
                   scheduled=False) -> None:
    _ref_cache_put_raw(_ref_cache_key(scale, n_rows, seed, mode, outer,
                                      scheduled), entry)


def _start_ref_game(scale, n_rows, seed, mode, salt, outer=None,
                    scheduled=False) -> subprocess.Popen:
    """Launch the float64 CPU reference fit concurrently (it uses the host
    CPU while the f32 run uses the accelerator).  `scheduled` re-runs the
    SAME fit under the default inexactness schedule — the f64 reference
    for a scheduled measured leg, per the existing same-fit-at-f64
    methodology."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1")
    env.pop("XLA_FLAGS", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--game-ref", scale,
           "--n-rows", str(n_rows), "--seed", str(seed),
           "--salt", repr(salt), "--mode", mode]
    if outer is not None:
        cmd += ["--outer", str(outer)]
    if scheduled:
        cmd += ["--schedule"]
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=os.path.dirname(os.path.abspath(__file__)))


def _join_ref_game(p: subprocess.Popen) -> dict:
    try:
        out, err = p.communicate(timeout=3600)
    except subprocess.TimeoutExpired:
        p.kill()
        return {"error": "reference fit timed out"}
    if p.returncode != 0:
        return {"error": (err or out)[-500:]}
    return json.loads(out.strip().splitlines()[-1])


def _game_ref_main(argv):
    """--game-ref mode: float64 CPU fit, print one JSON line."""
    # the site customization pins JAX_PLATFORMS to the tunneled TPU; the
    # reference fit must NOT land there (it would contend with — and OOM —
    # the measured run).  jax.config wins over the env pin when set before
    # backend init.
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from photon_ml_tpu.utils.jax_cache import enable_persistent_cache
    enable_persistent_cache()
    scale = argv[0]
    n_rows = int(argv[argv.index("--n-rows") + 1])
    seed = int(argv[argv.index("--seed") + 1])
    salt = float(argv[argv.index("--salt") + 1]) if "--salt" in argv else 0.0
    mode = argv[argv.index("--mode") + 1] if "--mode" in argv else "glmix"
    outer = (int(argv[argv.index("--outer") + 1]) if "--outer" in argv
             else None)
    result, _, _, _, fit_s = run_game(scale, n_rows, seed, np.float64, mode,
                                      with_validation=False, salt=salt,
                                      outer=outer,
                                      scheduled="--schedule" in argv)
    print(json.dumps({"ref_nll": float(result.objective_history[-1]),
                      "ref_fit_s": round(fit_s, 1)}))


def _steady_rate(result, n_train):
    """n / wall of the LAST outer iteration (all programs already compiled;
    counts every phase of that iteration — solve, objective, validation,
    checkpoint)."""
    timings = getattr(result.descent, "timings", {})
    iters = [int(k.split("/")[0]) for k in timings
             if k.split("/")[0].isdigit()]
    if not iters:
        return None
    last = max(iters)
    t = sum(v for k, v in timings.items()
            if k.split("/")[0].isdigit() and int(k.split("/")[0]) == last)
    return round(n_train / max(t, 1e-9), 1)


def game_entry(label, scale, n_rows, seed, mode, parity_rows=None,
               parity_gate=None, reps=1, hbm_budget=None):
    """f32 accelerator fit + f64 CPU reference fit -> one bench entry.
    `parity_gate` records a hard |nll_rel_gap| bound in the entry
    (parity_ok false = regression, no waiver).  `reps` > 1 refits with
    fresh salts and keeps the FASTEST fit: host->device staging latency
    over the tunneled chip varies several-fold run to run (measured
    0.8s..60s on one phase), and the repeated fit is the steady-state
    number a persistent training service would see.  `hbm_budget` applies
    out-of-core mode to the MEASURED fit only (the f64 reference and the
    reduced-rows parity pair stay resident — both sides of every parity
    comparison see identical execution modes)."""
    reduced_parity = parity_rows is not None and parity_rows != n_rows
    ref_rows = parity_rows if reduced_parity else n_rows
    salt = (time.time_ns() % 997) * 1e-10
    cached = _ref_cache_get(scale, ref_rows, seed, mode)
    # the reference fit runs at salt=0 (cacheable); see _ref_cache_get
    ref_proc = (None if cached
                else _start_ref_game(scale, ref_rows, seed, mode, 0.0))
    tracker = _global_compile_tracker()
    try:
        best = None
        for r in range(max(reps, 1)):
            compile0 = tracker.seconds
            try:
                attempt = run_game(scale, n_rows, seed, np.float32, mode,
                                   salt=salt + 1e-7 * r,
                                   hbm_budget=hbm_budget)
            except Exception:
                # a transient failure on a LATER rep must not discard the
                # successful fit already in hand (retries exist to absorb
                # exactly this flakiness); only rep 0 failures propagate
                if best is None:
                    raise
                _log(f"game[{label}]: rep {r} failed; keeping the "
                     "completed earlier fit")
                break
            attempt_compile = tracker.seconds - compile0
            if best is None or attempt[4] < best[0][4]:
                best = (attempt, attempt_compile)
        (result, n_train, outer, build_s, fit_s), compile_s = best
        par_result = (run_game(scale, parity_rows, seed, np.float32, mode,
                               salt=salt)[0] if reduced_parity else None)
    except BaseException:
        if ref_proc is not None:
            ref_proc.kill()  # no orphaned float64 reference fit
            ref_proc.communicate()
        raise
    our_nll = float(result.objective_history[-1])
    entry = {
        "name": label, "task": "logistic_regression",
        "data": "synthetic-replica", "n_train": n_train,
        "outer_iterations": outer,
        "examples_per_sec_per_chip": round(n_train * outer / fit_s, 1),
        "build_s": round(build_s, 1), "fit_s": round(fit_s, 1),
        # real XLA backend-compile seconds inside fit_s (near zero when the
        # persistent cache is warm — the driver runs bench in-repo, so the
        # committed .jax_cache workflow keeps this small)
        "compile_s": round(compile_s, 1),
        # last outer iteration reuses every compiled program -> the
        # compile-free per-iteration rate (fit_s includes XLA compiles)
        "steady_state_examples_per_sec": _steady_rate(result, n_train),
        "phase_timings_s": {k: round(v, 2) for k, v in
                            getattr(result.descent, "timings", {}).items()},
        # phase spans are contiguous over the fit; coverage < 1 means an
        # untimed stage crept in (round-3 verdict: 65% unattributed)
        "phase_coverage": round(
            sum(getattr(result.descent, "timings", {}).values())
            / max(fit_s, 1e-9), 3),
        "validation_auc": (round(float(result.validation["AUC"]), 4)
                           if "AUC" in result.validation else None),
        "final_nll": our_nll,
        "coordinates": list(result.config.updating_sequence),
    }
    if hbm_budget is not None:
        # out-of-core accounting: which coordinates streamed/evicted and the
        # tracked peak vs budget (memory_stats() stand-in on the tunnel)
        entry["hbm_residency"] = getattr(result, "residency", None)
    # parity pair: same fit at f64 on CPU (possibly at reduced rows for
    # config 5 — both sides of the pair always see identical data)
    if reduced_parity:
        our_par = float(par_result.objective_history[-1])
        entry["parity_n"] = parity_rows
    else:
        our_par = our_nll
    ref = cached if cached is not None else _join_ref_game(ref_proc)
    if "ref_nll" in ref:
        if cached is None:
            _ref_cache_put(scale, ref_rows, seed, mode, ref)
        entry["ref_nll"] = ref["ref_nll"]
        entry["ref_fit_s"] = ref.get("ref_fit_s")
        entry["ref_cached"] = cached is not None
        entry["nll_rel_gap"] = round(
            (our_par - ref["ref_nll"]) / abs(ref["ref_nll"]), 9)
        if parity_gate is not None:
            entry["parity_gate"] = parity_gate
            entry["parity_ok"] = bool(
                abs(entry["nll_rel_gap"]) <= parity_gate)
    else:
        entry["ref_error"] = ref.get("error", "unknown")
    return entry


def bench_config4():
    n_rows = max(int(1_000_209 * _SCALE), 2000)
    entry = game_entry("glmix_fe_peruser_movielens1m_shape", "1m", n_rows,
                       seed=11, mode="glmix", parity_gate=1e-4, reps=2)
    entry["avro_ingest"] = _measure_avro_ingest(min(n_rows, 200_000))
    return [entry]


def _measure_avro_ingest(n_rows):
    """Reference-format ingest rate through the merged multi-bag reader +
    native decoder (VERDICT r4 item 1: 'bench config 4 gains an ingest_s
    entry through this path').  The write is fixture prep, not the
    measurement."""
    import tempfile

    from photon_ml_tpu.data.avro_game import (read_game_examples,
                                              write_game_examples)
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.synthetic_bench import (make_movielens_like,
                                                    movielens_shards)
    ml = make_movielens_like("1m", seed=11, n_rows=n_rows)
    shards = movielens_shards(ml)
    maps = {k: IndexMap.from_keys(
        [feature_key(f"{k}{j:04d}") for j in range(shards[k].shape[1] - 1)])
        for k in ("global", "per_user")}
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.avro")
        write_game_examples(
            path, ml.response,
            bags={"globalBag": (shards["global"], maps["global"]),
                  "userBag": (shards["per_user"], maps["per_user"])},
            id_values={"userId": ml.user_ids})
        size_mb = os.path.getsize(path) / 1e6
        t0 = time.perf_counter()
        res = read_game_examples(
            [path], {"global": ["globalBag"], "per_user": ["userBag"]},
            id_columns=["userId"])
        ingest_s = time.perf_counter() - t0
        assert res.dataset.num_rows == n_rows
    return {"rows": n_rows, "ingest_s": round(ingest_s, 2),
            "rows_per_sec": round(n_rows / ingest_s, 1),
            "mb_per_sec": round(size_mb / ingest_s, 1),
            "path": "TrainingExampleAvro-shaped multi-bag -> native block "
                    "decoder -> vectorized merge (data/avro_game.py)"}


def bench_config5():
    # 25% of the corpus rows at FULL entity cardinality (138,493 users,
    # 26,744 items — the axis that stresses the RE machinery).  Before
    # out-of-core mode this ran at 10%: 5M rows exhausted the single
    # tunneled chip's HBM with all four coordinates resident.  The
    # HBM-budgeted measured fit (FE shards chunk-stream, inactive
    # coordinates evict between visits) lifts the residency cap; the full
    # 20M-row TRANSFER still stalls the tunnel, which now bounds the row
    # count.  Row count and corpus size are both recorded so the scale is
    # explicit.
    n_rows = max(int(5_000_000 * _SCALE), 4000)
    # the f64 reference + f32 parity pair run at the OLD row count,
    # resident on both sides (identical data and execution mode; also keeps
    # the committed ref-cache entries valid)
    parity_rows = max(int(2_000_000 * _SCALE), 4000)
    budget = int(float(os.environ.get("BENCH_HBM_BUDGET", 6e9)))
    # convex subset FIRST, hard-gated at 1e-4: FE + 2xRE has a unique
    # optimum, so a real regression in the RE tower at this scale can no
    # longer hide behind the MF waiver (VERDICT r3 weak #4)
    convex = game_entry("game_fe_2re_movielens20m_shape_convex", "20m",
                        n_rows, seed=13, mode="convex", parity_gate=1e-4,
                        parity_rows=parity_rows, hbm_budget=budget)
    convex["corpus_rows"] = 20_000_263
    convex["hbm_budget_bytes"] = budget
    entry = game_entry("game_fe_2re_mf_movielens20m_shape", "20m", n_rows,
                       seed=13, mode="full", parity_rows=parity_rows,
                       hbm_budget=budget)
    entry["corpus_rows"] = 20_000_263
    entry["hbm_budget_bytes"] = budget
    entry["note"] = ("factored-MF coordinate is non-convex: the float32 "
                     "accelerator fit and the float64 CPU reference can land "
                     "in different optima, so nll_rel_gap may exceed 1e-4 in "
                     "magnitude; negative = the accelerator fit is LOWER "
                     "(better); the convex entry above is the hard parity "
                     "gate for this scale")
    return [convex, entry]


def bench_config6():
    """Wide-regime sparse fixed effect on the chip (VERDICT r4 item 5a):
    >=200k features through PaddedSparse, float64 parity hard-gated, plus
    the bf16-feature-storage measurement at wide d (binary features are
    exact in bf16, so the pair isolates the bandwidth effect)."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.synthetic_bench import make_wide_sparse_logistic
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    n = max(int(200_000 * _SCALE), 2000)
    d, nnz = 250_000, 64
    x, y = make_wide_sparse_logistic(n, d=d, nnz=nnz, seed=77)
    lam = 1.0
    l2 = RegularizationContext(RegularizationType.L2)
    cfg = OptimizerConfig(max_iterations=200, tolerance=1e-9)
    out = []
    for label, fdt in (("wide_sparse_250k_logistic_lbfgs_l2", None),
                       ("wide_sparse_250k_logistic_lbfgs_l2_bf16_values",
                        jnp.bfloat16)):
        e = glm_entry("logistic_regression", x, y, cfg, l2, lam, 0.0, lam,
                      label, reps=5, feature_dtype=fdt, data_seed=77)
        e["parity_gate"] = 1e-4
        e["parity_ok"] = bool(abs(e["nll_rel_gap"]) <= 1e-4)
        e["nnz_per_row"] = nnz
        e["note"] = (
            "csc prefix-scan gradient path (no scatter): 3.9x the r04 "
            "per-pass rate. Decomposed on-chip (in-loop, 20 iters): the "
            "12.8M-element random gather costs ~95ms (~135M elem/s) while "
            "the same-size cumsum is 6ms and elementwise 7ms; a fused pass "
            "needs two such gathers (margin + gradient), so the "
            "gather-bound ceiling is ~1.1 GB/s of nominal sparse traffic "
            "and this entry sits within ~20% of it. Fine-grained random "
            "access defeats the TPU's vector memory lanes; Mosaic cannot "
            "express table-lookup gathers (measured round 3), so the "
            "remaining gap to HBM peak is a hardware bound for this "
            "formulation, not a scheduling artifact.")
        # padded-ELL traffic: indices int32 + values, read twice per fused
        # pass (margin gather + gradient scatter)
        k = int(np.diff(x.indptr).max())
        vsize = 2 if fdt is not None else 4
        moved = 2 * e["n"] * k * (4 + vsize) * e["data_passes"]
        if e["wall_s"]:
            e["achieved_gbps_est"] = round(moved / e["wall_s"] / 1e9, 1)
            e["hbm_frac_of_v5e_peak"] = round(
                e["achieved_gbps_est"] / V5E_HBM_GBPS, 3)
        out.append(e)
    return out


def bench_config7():
    """Yahoo-fixture-shaped GAME (VERDICT r4 item 5b): 14,983-coefficient
    sparse FE + 2 narrow random effects, float64 parity hard-gated."""
    n_rows = max(int(300_000 * _SCALE), 4000)
    entry = game_entry("game_yahoo_fe14983_2re", "yahoo", n_rows,
                       seed=23, mode="yahoo", parity_gate=1e-4, reps=2)
    entry["fe_coefficients"] = 14_983
    return [entry]


# --------------------------------------------------------------------------
# pipelined coordinate descent benchmark (--pipeline): strict vs pipelined
# --------------------------------------------------------------------------

def _pipeline_dataset(n, d_global, n_users, d_user, seed,
                      n_items=0, d_item=0):
    """Seeded GLMix-shaped synthetic data with CONTROLLED entity geometry:
    round-robin entity assignment gives every entity exactly n/n_users
    rows (one S-bucket, no ragged tail), so the strict-vs-pipelined pair
    measures the loop structure, not bucketing noise.  Arrays stay numpy
    float64 — the device copies follow jax's ambient default dtype (f32 in
    a bench invocation, f64 under the x64 test fixture), keeping every
    descent-internal array one consistent dtype."""
    from photon_ml_tpu.data.game_data import build_game_dataset
    rng = np.random.default_rng(seed)
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = np.arange(n) % n_users
    w_g = rng.normal(size=d_global)
    w_u = rng.normal(size=(n_users, d_user)) * 0.5
    z = xg @ w_g + np.einsum("nd,nd->n", xu, w_u[users])
    shards = {"global": xg, "per_user": xu}
    entity_ids = {"userId": np.asarray([f"u{u:06d}" for u in users])}
    if n_items:
        xi = rng.normal(size=(n, d_item)); xi[:, -1] = 1.0
        items = np.arange(n) % n_items
        w_i = rng.normal(size=(n_items, d_item)) * 0.5
        z = z + np.einsum("nd,nd->n", xi, w_i[items])
        shards["per_item"] = xi
        entity_ids["itemId"] = np.asarray([f"i{i:06d}" for i in items])
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-z))).astype(float)
    ds = build_game_dataset(y, shards, entity_ids=entity_ids)
    rows = np.arange(n)
    return ds.subset(rows[: int(n * 0.95)]), ds.subset(rows[int(n * 0.95):])


def _pipeline_config(outer, solver_iters, with_item, seed=3, history=10,
                     projector="index_map"):
    """GAME config for the pipeline pair.  The tuned entries use ONE
    quasi-Newton step per coordinate update (inexact block coordinate
    descent — the regime where the loop structure, not the inner solver,
    dominates) and projector="identity" (dense synthetic shards: the
    per-entity local space equals the global space, so the index-map
    scatter buys nothing)."""
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameTrainingConfig, GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    l2 = RegularizationContext(RegularizationType.L2)
    opt = lambda w: GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=solver_iters,
                                  history=history),
        regularization=l2, regularization_weight=w)
    coords = {"fixed": FixedEffectCoordinateConfig("global", opt(1.0)),
              "perUser": RandomEffectCoordinateConfig(
                  "userId", "per_user", opt(1.0), projector=projector)}
    seq = ["fixed", "perUser"]
    if with_item:
        coords["perItem"] = RandomEffectCoordinateConfig(
            "itemId", "per_item", opt(1.0), projector=projector)
        seq.append("perItem")
    return GameTrainingConfig(task_type="logistic_regression",
                              coordinates=coords, updating_sequence=seq,
                              num_outer_iterations=outer, seed=seed)


def _run_descent_mode(coords, cfg, train, val, specs, mode, ckpt_dir):
    """One timed descent-loop run (coordinates pre-built: both modes share
    the same device-resident data and compiled programs, so the pair
    isolates the loop structure itself)."""
    from photon_ml_tpu.game.coordinate_descent import (PhaseTimings,
                                                       run_coordinate_descent)
    spans = PhaseTimings()
    t0 = time.perf_counter()
    res = run_coordinate_descent(
        coords, cfg.updating_sequence, cfg.num_outer_iterations, train,
        cfg.task_type, validation_dataset=val, validation_specs=specs,
        checkpoint_dir=ckpt_dir, timings=spans, timing_mode=mode)
    wall = time.perf_counter() - t0
    ckpt_s = sum(v for k, v in spans.items()
                 if k.endswith("/checkpoint") or k == "checkpoint/join")
    return res, {"fit_s": round(wall, 3),
                 "host_blocked_s": round(spans.host_blocked_total(), 3),
                 "host_blocked_frac": round(
                     spans.host_blocked_total() / max(wall, 1e-9), 4),
                 "checkpoint_spans_s": round(ckpt_s, 3)}


def _models_bit_identical(model_a, model_b, tmp_root) -> bool:
    """Save both GameModels and compare every persisted array bit-for-bit
    (the acceptance gate: strict and pipelined model DIRECTORIES match)."""
    import glob as _glob

    from photon_ml_tpu.models.io import save_game_model
    dirs = []
    for tag, m in (("a", model_a), ("b", model_b)):
        d = os.path.join(tmp_root, tag)
        save_game_model(m, d)
        dirs.append(d)
    files_a = sorted(_glob.glob(os.path.join(dirs[0], "**", "*.npz"),
                                recursive=True))
    files_b = sorted(_glob.glob(os.path.join(dirs[1], "**", "*.npz"),
                                recursive=True))
    if [os.path.relpath(f, dirs[0]) for f in files_a] != \
            [os.path.relpath(f, dirs[1]) for f in files_b]:
        return False
    for fa, fb in zip(files_a, files_b):
        with np.load(fa, allow_pickle=True) as za, \
                np.load(fb, allow_pickle=True) as zb:
            if sorted(za.files) != sorted(zb.files):
                return False
            for k in za.files:
                a, b = za[k], zb[k]
                if a.dtype == object or b.dtype == object:
                    if not np.array_equal(a, b):
                        return False
                elif a.tobytes() != b.tobytes():  # BIT-identical, not approx
                    return False
    return True


def _pipeline_entry(name, n, d_global, n_users, d_user, outer, solver_iters,
                    seed, n_items=0, d_item=0, history=10,
                    projector="index_map"):
    """strict-vs-pipelined pair for one GAME shape.  Warmup first (1 outer
    iteration, pipelined — compiles every program both modes use), then
    pipelined, then strict, so any residual cache warming favors STRICT
    (the conservative direction for the reported speedup)."""
    import tempfile

    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent

    train, val = _pipeline_dataset(n, d_global, n_users, d_user, seed,
                                   n_items=n_items, d_item=d_item)
    cfg = _pipeline_config(outer, solver_iters, with_item=n_items > 0,
                           seed=seed, history=history, projector=projector)
    est = GameEstimator(cfg)
    t0 = time.perf_counter()
    coords = est._build_coordinates(train)
    build_s = time.perf_counter() - t0
    specs = est._validation_specs(["AUC"])
    _log(f"pipeline[{name}]: coordinates built in {build_s:.1f}s; warmup")
    with tempfile.TemporaryDirectory() as tmp:
        # warmup: compile everything once, prime the page cache
        warm_cfg = _pipeline_config(1, solver_iters, with_item=n_items > 0,
                                    seed=seed, history=history,
                                    projector=projector)
        run_coordinate_descent(
            coords, warm_cfg.updating_sequence, 1, train, warm_cfg.task_type,
            validation_dataset=val, validation_specs=specs,
            checkpoint_dir=os.path.join(tmp, "warm"),
            timing_mode="pipelined")
        modes = {}
        results = {}
        for mode in ("pipelined", "strict"):
            _log(f"pipeline[{name}]: timing {mode}")
            results[mode], modes[mode] = _run_descent_mode(
                coords, cfg, train, val, specs, mode,
                os.path.join(tmp, mode))
        gap = max((abs(a - b) for a, b in
                   zip(results["strict"].objective_history,
                       results["pipelined"].objective_history)), default=0.0)
        bit_identical = _models_bit_identical(
            results["strict"].model, results["pipelined"].model,
            os.path.join(tmp, "cmp"))
    speedup = modes["strict"]["fit_s"] / max(modes["pipelined"]["fit_s"], 1e-9)
    return {
        "name": name, "task": "logistic_regression",
        "data": "synthetic-replica", "n_train": train.num_rows,
        "n_validation": val.num_rows, "outer_iterations": outer,
        "entities": {"userId": n_users, **({"itemId": n_items}
                                           if n_items else {})},
        "model_mb": round((n_users * d_user + n_items * d_item
                           + d_global) * 4 / 1e6, 1),
        "build_s": round(build_s, 2),
        "strict": modes["strict"], "pipelined": modes["pipelined"],
        "speedup": round(speedup, 3),
        "objective_history_max_abs_gap": float(gap),
        "final_model_bit_identical": bit_identical,
        "parity_ok": bool(gap <= 1e-9 and bit_identical),
    }


def pipeline_bench(out_path="BENCH_pipeline.json"):
    """Strict-vs-pipelined wall-clock on GAME shapes where the loop
    structure matters: a checkpoint-heavy per-user shape (big [E, d] model,
    quick solves — the async writer's coalescing carries the win) and a
    three-coordinate convex shape (per-update syncs/readbacks scale with
    coordinate count).  Each entry reports the host-blocked fraction and a
    hard parity gate (identical objective history to 1e-9 + bit-identical
    final model directories)."""
    # long-tail GLMix regime (GLMix's raison d'etre: very many entities,
    # a handful of rows each, inexact one-step coordinate updates): the
    # per-iteration checkpoint — [E, d]-scale model serialization — rivals
    # the device work, which is exactly where strict mode's synchronous
    # write blocks the loop and the async writer's keep-latest coalescing
    # pays.  On a 1-core CPU host the concurrency is time-sliced, so the
    # measured speedup is the ELIMINATED work (coalesced writes, batched
    # readbacks), a lower bound on what an accelerator-attached host sees.
    entries = [
        _pipeline_entry("glmix_longtail_100k_users_ckpt",
                        n=max(int(100_000 * _SCALE), 4000), d_global=16,
                        n_users=max(int(100_000 * _SCALE), 4000), d_user=192,
                        outer=10, solver_iters=1, history=1, seed=3,
                        projector="identity"),
        _pipeline_entry("game_fe_2re_three_coordinate_ckpt",
                        n=max(int(100_000 * _SCALE), 4000), d_global=16,
                        n_users=max(int(100_000 * _SCALE), 4000), d_user=64,
                        outer=10, solver_iters=1, history=1, seed=5,
                        n_items=max(int(50_000 * _SCALE), 2000), d_item=64,
                        projector="identity"),
    ]
    fast_enough = sum(e["speedup"] >= 1.2 for e in entries)
    result = {
        "metric": "pipelined_vs_strict_speedup",
        "value": max(e["speedup"] for e in entries),
        "unit": "x",
        "detail": {
            "entries": entries,
            "configs_at_or_above_1p2x": fast_enough,
            "all_parity_ok": all(e["parity_ok"] for e in entries),
        },
    }
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# out-of-core streaming benchmark (--stream): resident vs HBM-budgeted
# --------------------------------------------------------------------------

def _device_peak_bytes():
    """device.memory_stats() peak where the backend exposes it (real TPU
    plugins do; CPU and some tunneled devices return None -> the bench
    falls back to the ResidencyManager's transfer-size accounting)."""
    import jax
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")


def _stream_config(outer, solver_iters, budget, seed=3):
    """GLMix FE + per-user RE shape for the resident-vs-streamed pair.
    The FE shard is made the dominant block (wide d_global vs narrow
    d_user) so the HBM budget forces it into chunk streaming while the RE
    coordinate rides the eviction rotation."""
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameTrainingConfig, GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    l2 = RegularizationContext(RegularizationType.L2)
    opt = lambda w: GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=solver_iters),
        regularization=l2, regularization_weight=w)
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", opt(1.0)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", opt(1.0), projector="identity"),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=outer, seed=seed,
        hbm_budget_bytes=budget)


def _stream_entry(name, n, d_global, n_users, d_user, outer, solver_iters,
                  seed, budget_frac=0.5, parity_gate=1e-5, gated=True,
                  note=None):
    """One resident-vs-streamed pair.  The budget is set to `budget_frac`
    of the measured resident footprint, so by construction the streamed fit
    trains a config whose total coordinate data EXCEEDS the budget — the
    capability that did not exist before out-of-core mode.  Parity gates on
    the relative objective-history gap; peak device memory comes from
    device.memory_stats() where available, ResidencyManager transfer-size
    accounting otherwise."""
    from photon_ml_tpu.game import GameEstimator

    train, val = _pipeline_dataset(n, d_global, n_users, d_user, seed)
    runs = {}
    for mode, budget in (("resident", None), ("streamed", 0)):
        if mode == "streamed":
            acct = runs["resident"].residency
            resident_total = (acct["resident_block_total"]
                              + acct["flat_vector_bytes"])
            # the budget floor: rotation bounds residency at the LARGEST
            # single coordinate's blocks + the flat vectors (RE blocks
            # rotate, they don't chunk-stream), so a budget below that is
            # infeasible by construction — streaming lifts the FE-shard
            # term, eviction lifts the SUM, neither shrinks one RE block
            floor = int((max(acct["resident_block_bytes"].values())
                         + acct["flat_vector_bytes"]) * 1.05)
            budget = max(int(resident_total * budget_frac), floor)
            assert budget < resident_total, (
                "stream bench shape cannot demonstrate out-of-core: one "
                "coordinate alone nearly fills the resident footprint")
        cfg = _stream_config(outer, solver_iters, budget, seed=seed)
        est = GameEstimator(cfg)
        # warmup fit compiles every program this mode uses (1 outer
        # iteration), so the timed fit is steady-state for BOTH modes
        warm = _stream_config(1, solver_iters, budget, seed=seed)
        GameEstimator(warm).fit(train, val, evaluator_specs=["AUC"])
        _log(f"stream[{name}]: timing {mode} (budget={budget})")
        t0 = time.perf_counter()
        res = est.fit(train, val, evaluator_specs=["AUC"])
        wall = time.perf_counter() - t0
        res.fit_s = wall
        res.device_peak = _device_peak_bytes()
        runs[mode] = res

    r, s = runs["resident"], runs["streamed"]
    gaps = [abs(a - b) / max(abs(a), 1e-12)
            for a, b in zip(r.objective_history, s.objective_history)]
    max_gap = max(gaps) if gaps else 0.0
    budget = s.config.hbm_budget_bytes
    acct = s.residency
    data_bytes = (r.residency["resident_block_total"]
                  + r.residency["flat_vector_bytes"])
    rate = lambda res: n * outer / max(res.fit_s, 1e-9)
    entry = {
        "name": name, "task": "logistic_regression",
        "data": "synthetic-replica", "n_train": train.num_rows,
        "n_validation": val.num_rows, "outer_iterations": outer,
        "entities": {"userId": n_users},
        "d_global": d_global, "d_user": d_user,
        "hbm_budget_bytes": budget,
        "coordinate_data_bytes": data_bytes,
        "data_exceeds_budget": bool(data_bytes > budget),
        "resident": {
            "fit_s": round(r.fit_s, 3),
            "examples_per_sec": round(rate(r), 1),
            "resident_block_bytes": r.residency["resident_block_bytes"],
            "peak_tracked_bytes": r.residency["peak_tracked_bytes"],
            "device_peak_bytes": r.device_peak,
        },
        "streamed": {
            "fit_s": round(s.fit_s, 3),
            "examples_per_sec": round(rate(s), 1),
            "streamed_coordinates": list(acct["streamed_chunk_bytes"]),
            "streamed_chunk_bytes": acct["streamed_chunk_bytes"],
            "evictions": acct["evictions"],
            "peak_tracked_bytes": acct["peak_tracked_bytes"],
            "under_budget": acct["under_budget"],
            "device_peak_bytes": s.device_peak,
        },
        "throughput_ratio": round(rate(s) / max(rate(r), 1e-9), 3),
        "objective_history_max_rel_gap": float(max_gap),
        "validation_auc": {
            "resident": (round(float(r.validation.get("AUC", float("nan"))), 5)
                         if r.validation else None),
            "streamed": (round(float(s.validation.get("AUC", float("nan"))), 5)
                         if s.validation else None)},
        "parity_gate": parity_gate,
        "parity_ok": bool(max_gap <= parity_gate
                          and len(r.objective_history)
                          == len(s.objective_history)),
        # gated=False entries report but do not enter the 0.7x throughput
        # gate (with `note` saying why) — never a silent exclusion
        "throughput_gated": bool(gated),
    }
    if note:
        entry["note"] = note
    return entry


def stream_bench(out_path="BENCH_stream.json", smoke=False):
    """Out-of-core GAME training (ISSUE 3): resident vs streamed wall time
    + peak device memory, parity-gated.  The streamed leg runs under an HBM
    budget smaller than the coordinate data (FE shard chunk-streams through
    ChunkedGLMObjective, the RE coordinate evicts/re-streams between
    visits) — a fit shape that was IMPOSSIBLE before this mode.  The
    acceptance bar for full mode is >= 0.7x resident throughput; smoke mode
    (tier-1 tests/test_bench_smoke.py::test_stream_smoke) gates parity and
    the under-budget accounting only, since seconds-scale CPU timing is
    noise."""
    if smoke:
        entries = [_stream_entry("smoke_stream_glmix", n=6000, d_global=24,
                                 n_users=300, d_user=4, outer=2,
                                 solver_iters=8, seed=17)]
    else:
        serialized_note = (
            "pure-FE worst case, reported ungated: the fit is ~one chunk "
            "stream, and on this host every staged byte is time stolen from "
            "compute (1 CPU core: the prefetch thread time-slices instead "
            "of overlapping), so the ratio floors at compute/(compute+"
            "staging) ~= 2/3.  On an accelerator-attached host the staging "
            "thread overlaps DMA with device compute; the gated entries "
            "below have concurrent coordinate work and meet the floor even "
            "serialized.")
        entries = [
            # FE-dominant GLMix: the budget forces the wide global shard out
            # of core; nearly all wall time is the chunk stream itself —
            # the serialized-staging worst case (reported, ungated)
            _stream_entry("stream_glmix_fe_dominant",
                          n=max(int(400_000 * _SCALE), 8000), d_global=96,
                          n_users=max(int(20_000 * _SCALE), 500), d_user=16,
                          outer=4, solver_iters=20, seed=17,
                          gated=False, note=serialized_note),
            # balanced shape: the FE shard streams while the per-user
            # coordinate carries comparable device work
            _stream_entry("stream_glmix_balanced",
                          n=max(int(250_000 * _SCALE), 8000), d_global=64,
                          n_users=max(int(25_000 * _SCALE), 600), d_user=24,
                          outer=4, solver_iters=12, seed=23),
            # long-tail shape: RE blocks rival the FE shard, so the rotation
            # (not just FE streaming) carries the budget
            _stream_entry("stream_glmix_longtail",
                          n=max(int(200_000 * _SCALE), 8000), d_global=64,
                          n_users=max(int(50_000 * _SCALE), 1000), d_user=48,
                          outer=4, solver_iters=10, seed=19),
        ]
    gated = [e for e in entries if e["throughput_gated"]]
    ratios = [e["throughput_ratio"] for e in gated]
    result = {
        "metric": "streamed_vs_resident_throughput_ratio",
        "value": min(ratios),
        "unit": "x",
        "detail": {
            "entries": entries,
            "all_parity_ok": all(e["parity_ok"] for e in entries),
            "all_data_exceeds_budget": all(e["data_exceeds_budget"]
                                           for e in entries),
            "all_under_budget": all(e["streamed"]["under_budget"]
                                    for e in entries),
            "throughput_floor": 0.7,
            "throughput_gated_entries": [e["name"] for e in gated],
            "throughput_ok": all(rt >= 0.7 for rt in ratios),
            "smoke": smoke,
        },
    }
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# stochastic streaming solver benchmark (--stoch): per-chunk local epochs
# vs the host-stepped LBFGS mirror, work-per-staged-byte gated
# --------------------------------------------------------------------------

def _stoch_problem(n, d, seed):
    """Dense logistic shape for the solver-level legs (f64: the parity
    gate is a fixed-point comparison)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d) * 0.5
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    return x, y


def _stoch_objective(x, y, budget=None, row_multiple=1, mesh=None):
    from photon_ml_tpu.data.streaming import ChunkPlan
    from photon_ml_tpu.ops.chunked import ChunkedGLMObjective
    from photon_ml_tpu.ops.losses import LOGISTIC
    n, d = x.shape
    if budget is not None:
        plan = ChunkPlan.build(n, hbm_budget_bytes=budget,
                               bytes_per_row=(d + 3) * x.dtype.itemsize,
                               row_multiple=row_multiple)
    else:
        plan = ChunkPlan.build(n, chunk_rows=max(n // 8, 256),
                               row_multiple=row_multiple)
    return ChunkedGLMObjective(LOGISTIC, x, y, plan, mesh=mesh)


def _stoch_out_of_core_leg(n, d, passes, local_epochs, solver_iters, seed):
    """The headline pair: strict host-stepped LBFGS vs stochastic-early +
    LBFGS-polish on an out-of-core shape (data > budget, peak < budget),
    sharing one plan.  HARD gates: examples_per_staged_byte >= 1.5x the
    strict mirror, and f64 fixed-point parity <= 1e-6."""
    import jax.numpy as jnp
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType, StochasticPlan,
                                     solve_streamed)
    l2 = RegularizationContext(RegularizationType.L2)
    cfg = OptimizerConfig(max_iterations=solver_iters, tolerance=1e-9)
    x, y = _stoch_problem(n, d, seed)
    data_bytes = x.nbytes + 3 * y.nbytes      # x + labels + mask (+weights)
    budget = data_bytes // 4

    def run(stochastic):
        obj = _stoch_objective(x, y, budget=budget)
        t0 = time.perf_counter()
        if stochastic is None:
            res = solve_streamed(obj, jnp.zeros(d), cfg, l2, 1.0)
        else:
            coarse = solve_streamed(obj, jnp.zeros(d), cfg, l2, 1.0,
                                    stochastic=stochastic)
            res = solve_streamed(obj, coarse.x, cfg, l2, 1.0)
        wall = time.perf_counter() - t0
        snap = obj.stats.snapshot()
        return res, snap, wall

    _log(f"stoch[out_of_core]: strict mirror (n={n}, d={d}, "
         f"budget={budget / 1e6:.1f}MB)")
    strict_res, strict_snap, strict_wall = run(None)
    _log(f"stoch[out_of_core]: stochastic {passes}x{local_epochs} + polish")
    plan = StochasticPlan(passes=passes, local_epochs=local_epochs, seed=seed)
    stoch_res, stoch_snap, stoch_wall = run(plan)

    v_strict, v_stoch = float(strict_res.value), float(stoch_res.value)
    parity = abs(v_stoch - v_strict) / max(abs(v_strict), 1e-12)
    ratio = (stoch_snap["examples_per_staged_byte"]
             / max(strict_snap["examples_per_staged_byte"], 1e-12))
    side = lambda snap, wall: {
        "fit_s": round(wall, 3),
        "staged_bytes": snap["total_bytes"],
        "chunks_staged": snap["chunks_staged"],
        "passes": snap["passes"],
        "local_epochs": snap["local_epochs"],
        "examples_processed": snap["examples_processed"],
        "examples_per_staged_byte": snap["examples_per_staged_byte"],
        "examples_per_sec": round(snap["examples_processed"]
                                  / max(wall, 1e-9), 1),
        "peak_resident_bytes": snap["peak_resident_bytes"],
        "peak_resident_chunks": snap["peak_resident_chunks"],
    }
    return {
        "name": "stoch_out_of_core",
        "task": "logistic_regression",
        "n": n, "d": d,
        "stochastic_passes": passes, "local_epochs": local_epochs,
        "lbfgs_max_iterations": solver_iters,
        "data_bytes": int(data_bytes),
        "hbm_budget_bytes": int(budget),
        "data_exceeds_budget": bool(data_bytes > budget),
        "under_budget": bool(
            max(strict_snap["peak_resident_bytes"],
                stoch_snap["peak_resident_bytes"]) <= budget),
        "strict": side(strict_snap, strict_wall)
        | {"final_value": v_strict,
           "iterations": int(strict_res.iterations)},
        "stochastic_polish": side(stoch_snap, stoch_wall)
        | {"final_value": v_stoch,
           "polish_iterations": int(stoch_res.iterations)},
        "examples_per_staged_byte_ratio": round(ratio, 3),
        "ratio_gate": 1.5,
        "ratio_ok": bool(ratio >= 1.5),
        "fixed_point_rel_gap": parity,
        "parity_gate": 1e-6,
        "parity_ok": bool(parity <= 1e-6),
    }


def _stoch_trace_leg(n, d, passes, local_epochs, seed):
    """Zero fresh XLA traces across warm epochs: after one warm-up round
    (cold compiles + the carried-iterate sharding), further stochastic
    passes AND a grown dataset of the same chunk shape trace nothing."""
    import jax.numpy as jnp
    from photon_ml_tpu.optim import StochasticPlan, solve_stochastic
    x, y = _stoch_problem(n, d, seed)
    obj = _stoch_objective(x, y)
    plan = StochasticPlan(passes=passes, local_epochs=local_epochs,
                          seed=seed)
    res = solve_stochastic(obj, jnp.zeros(d), plan)
    res = solve_stochastic(obj, res.x, plan)          # warm carried iterate
    chunk = obj.plan.chunk_rows
    x2 = np.concatenate([x, x[: 2 * chunk]])
    y2 = np.concatenate([y, y[: 2 * chunk]])
    from photon_ml_tpu.data.streaming import ChunkPlan
    from photon_ml_tpu.ops.chunked import ChunkedGLMObjective
    from photon_ml_tpu.ops.losses import LOGISTIC
    obj2 = ChunkedGLMObjective(
        LOGISTIC, x2, y2, ChunkPlan.build(len(y2), chunk_rows=chunk))
    with _trace_counting() as counter:
        solve_stochastic(obj, res.x, plan)
        solve_stochastic(obj2, jnp.zeros(d), plan)
    return {
        "name": "stoch_warm_traces",
        "warm_passes": plan.passes, "grown_chunks": obj2.plan.num_chunks,
        "fresh_traces": counter.count,
        "traces_ok": bool(counter.count == 0),
    }


def _stoch_mesh_leg(n, d, passes, local_epochs, seed, devices=8):
    """Objective-history parity vs single-device: the SAME plan + seed on
    one device and sharded over the mesh "data" axis must produce the
    same per-pass streaming objective (float-summation-order residual
    only) and the same final coefficients."""
    import jax.numpy as jnp
    from photon_ml_tpu.optim import StochasticPlan, solve_stochastic
    from photon_ml_tpu.parallel import make_mesh
    x, y = _stoch_problem(n, d, seed)
    plan = StochasticPlan(passes=passes, local_epochs=local_epochs,
                          seed=seed)
    single = solve_stochastic(
        _stoch_objective(x, y, row_multiple=devices), jnp.zeros(d), plan)
    mesh = solve_stochastic(
        _stoch_objective(x, y, row_multiple=devices,
                         mesh=make_mesh(devices, 1)),
        jnp.zeros(d), plan)
    h1 = np.asarray(single.loss_history)
    h2 = np.asarray(mesh.loss_history)
    finite = np.isfinite(h1)
    hist_gap = float(np.max(np.abs(h2[finite] - h1[finite])
                            / np.maximum(np.abs(h1[finite]), 1e-12)))
    x_gap = float(np.max(np.abs(np.asarray(mesh.x)
                                - np.asarray(single.x))))
    return {
        "name": "stoch_mesh_parity",
        "mesh": f"{devices}x1", "n": n, "d": d,
        "objective_history_max_rel_gap": hist_gap,
        "history_gate": 1e-8,
        "final_x_max_abs_gap": x_gap,
        "mesh_parity_ok": bool(hist_gap <= 1e-8),
    }


def _stoch_game_leg(n, d_global, n_users, d_user, outer, seed):
    """End-to-end wiring demonstration (reported, ungated on numbers the
    solver legs already gate): a streamed-FE GLMix fit whose schedule runs
    the stochastic lane on early outer iterations and polishes the final
    one; solver_diagnostics carries the per-coordinate
    examples_per_staged_byte both ways."""
    import dataclasses

    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.optim import SolverSchedule
    train, val = _pipeline_dataset(n, d_global, n_users, d_user, seed)
    budget = int(train.feature_shards["global"].nbytes * 0.5)

    def run(schedule):
        cfg = _stream_config(outer, 40, budget, seed=seed)
        cfg = dataclasses.replace(cfg, solver_schedule=schedule)
        est = GameEstimator(cfg)
        t0 = time.perf_counter()
        res = est.fit(train, val, evaluator_specs=["AUC"])
        wall = time.perf_counter() - t0
        stream = res.descent.solver_diagnostics()["fixed"].get("stream", {})
        return {"fit_s": round(wall, 3),
                "final_objective": res.objective_history[-1],
                "auc": round(float(res.validation.get("AUC", float("nan"))),
                             5),
                "stream": stream}

    _log(f"stoch[game]: strict streamed GLMix fit (n={n})")
    strict = run(None)
    _log("stoch[game]: scheduled stochastic-early fit")
    sched = SolverSchedule(stochastic_passes=2, stochastic_local_epochs=6,
                           stochastic_seed=seed)
    stoch = run(sched)
    ratio = (stoch["stream"].get("examples_per_staged_byte", 0.0)
             / max(strict["stream"].get("examples_per_staged_byte", 0.0),
                   1e-12))
    return {
        "name": "stoch_game_glmix", "n": n,
        "hbm_budget_bytes": budget,
        "strict": strict, "scheduled": stoch,
        "examples_per_staged_byte_ratio": round(ratio, 3),
        "objective_rel_gap": abs(stoch["final_objective"]
                                 - strict["final_objective"])
        / max(abs(strict["final_objective"]), 1e-12),
        "note": ("reported ungated: fit-level objectives contract at the "
                 "outer-CD rate (the <= 1e-6 fixed-point gate is the "
                 "solver leg's); the ratio here shows the lane engaging "
                 "inside a full GAME fit"),
    }


def stoch_bench(out_path="BENCH_stoch.json", smoke=False, max_wall=None):
    """Stochastic single-pass solver lane (ISSUE 15): one staged chunk,
    one full epoch of work.  HARD gates: (1) examples_per_staged_byte >=
    1.5x the host-stepped LBFGS mirror on the out-of-core leg (data >
    budget, peak < budget); (2) f64 fixed-point parity <= 1e-6
    (stochastic-early + LBFGS-polish vs strict streamed LBFGS); (3) zero
    fresh XLA traces across warm epochs; (4) mesh-leg objective-history
    parity vs single-device.  Wall-clock is reported ungated (1-core CPU:
    staging and compute time-slice instead of overlapping)."""
    ndev = _ensure_virtual_devices(8)
    suite_t0 = time.perf_counter()
    if smoke:
        oc = dict(n=16384, d=16, passes=2, local_epochs=6, solver_iters=80,
                  seed=7)
        tr = dict(n=8192, d=12, passes=2, local_epochs=3, seed=7)
        me = dict(n=8192, d=12, passes=2, local_epochs=3, seed=7)
        game = None
    else:
        oc = dict(n=max(int(120_000 * _SCALE), 16384), d=48, passes=3,
                  local_epochs=8, solver_iters=150, seed=7)
        tr = dict(n=16384, d=16, passes=2, local_epochs=4, seed=7)
        me = dict(n=max(int(32_768 * _SCALE), 8192), d=16, passes=3,
                  local_epochs=4, seed=7)
        game = dict(n=max(int(60_000 * _SCALE), 8000), d_global=64,
                    n_users=max(int(3_000 * _SCALE), 300), d_user=8,
                    outer=4, seed=17)

    entries = [_stoch_out_of_core_leg(**oc), _stoch_trace_leg(**tr)]
    if ndev >= 8:
        entries.append(_stoch_mesh_leg(**me))
    if game is not None and (max_wall is None
                             or time.perf_counter() - suite_t0 < max_wall):
        entries.append(_stoch_game_leg(**game))
    by_name = {e["name"]: e for e in entries}
    oc_e = by_name["stoch_out_of_core"]
    result = {
        "metric": "stoch_examples_per_staged_byte_ratio",
        "value": oc_e["examples_per_staged_byte_ratio"],
        "unit": "x",
        "detail": {
            "entries": entries,
            "ratio_ok": oc_e["ratio_ok"],
            "parity_ok": oc_e["parity_ok"],
            "data_exceeds_budget": oc_e["data_exceeds_budget"],
            "under_budget": oc_e["under_budget"],
            "traces_ok": by_name["stoch_warm_traces"]["traces_ok"],
            "mesh_parity_ok": by_name.get(
                "stoch_mesh_parity", {}).get("mesh_parity_ok"),
            "all_gates_ok": bool(
                oc_e["ratio_ok"] and oc_e["parity_ok"]
                and oc_e["data_exceeds_budget"] and oc_e["under_budget"]
                and by_name["stoch_warm_traces"]["traces_ok"]
                and by_name.get("stoch_mesh_parity",
                                {"mesh_parity_ok": True})["mesh_parity_ok"]),
            "devices": ndev,
            "smoke": smoke,
        },
    }
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# feature-axis consensus-ADMM benchmark (--admm): transpose-reduction
# solve over the mesh's feature axis
# --------------------------------------------------------------------------

def _admm_problem(n, d, loss_name, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d) * 0.5
    z = x @ w
    if loss_name == "logistic":
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    else:
        y = z + 0.1 * rng.normal(size=n)
    return x, y


def _admm_submesh(num_data, num_feature):
    import jax
    from photon_ml_tpu.parallel import make_mesh
    return make_mesh(num_data, num_feature,
                     devices=jax.devices()[:num_data * num_feature])


def _admm_parity_leg(n, d, max_iterations, seed):
    """f64 parity of the PURE consensus solve (polish off) against the
    monolithic host-stepped LBFGS, across mesh shapes 1x1 / 1x2 / 2x2 /
    4x2 and both curvatures.  HARD gate: penalized-objective rel gap
    <= 1e-6 on every cell."""
    import jax.numpy as jnp
    from photon_ml_tpu.ops.losses import LOGISTIC, SQUARED
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim import (ADMMConfig, OptimizerConfig,
                                     RegularizationContext,
                                     RegularizationType)
    from photon_ml_tpu.parallel.fixed_effect import (fit_fixed_effect,
                                                     fit_fixed_effect_admm)
    l2 = RegularizationContext(RegularizationType.L2)
    cells = []
    for loss_name, loss in (("logistic", LOGISTIC), ("squared", SQUARED)):
        x, y = _admm_problem(n, d, loss_name, seed)
        obj = GLMObjective(loss, x, y)
        value = lambda c: (float(obj.value(jnp.asarray(c)))
                           + 0.15 * float(np.asarray(c) @ np.asarray(c)))
        ref = fit_fixed_effect(
            obj, np.zeros(d), _admm_submesh(8, 1),
            OptimizerConfig(max_iterations=500, tolerance=1e-12),
            reg=l2, reg_weight=0.3)
        v_ref = value(ref.x)
        for shape in ((1, 1), (1, 2), (2, 2), (4, 2)):
            _log(f"admm[parity]: {loss_name} mesh "
                 f"{shape[0]}x{shape[1]} (n={n}, d={d})")
            t0 = time.perf_counter()
            res = fit_fixed_effect_admm(
                obj, np.zeros(d), _admm_submesh(*shape),
                ADMMConfig(max_iterations=max_iterations, tolerance=1e-10,
                           polish=False),
                reg=l2, reg_weight=0.3,
                residency_key=("bench-admm-parity", loss_name, shape))
            gap = abs(value(res.x) - v_ref) / max(abs(v_ref), 1e-12)
            cells.append({
                "loss": loss_name, "mesh": f"{shape[0]}x{shape[1]}",
                "admm_iterations": int(res.iterations),
                "fit_s": round(time.perf_counter() - t0, 3),
                "rel_gap": gap, "parity_ok": bool(gap <= 1e-6),
            })
    return {
        "name": "admm_parity", "n": n, "d": d,
        "max_iterations": max_iterations,
        "cells": cells,
        "worst_rel_gap": max(c["rel_gap"] for c in cells),
        "parity_gate": 1e-6,
        "parity_ok": bool(all(c["parity_ok"] for c in cells)),
    }


def _admm_memory_leg(n, d, widths, iters, seed):
    """Per-device aggregator memory vs feature-axis width: the transpose-
    reduction eigenbasis is [F, d_F, d_F] sharded over "feature", so
    per-device bytes fall ~quadratically in F (>= the near-LINEAR gate).
    The budget sub-gate is the wide-model story: a d whose monolithic
    d^2 aggregator busts a per-device budget trains under a data x
    feature mesh with every per-device aggregate inside it."""
    import jax.numpy as jnp
    from photon_ml_tpu.ops.losses import SQUARED
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim import (ADMMConfig, RegularizationContext,
                                     RegularizationType)
    from photon_ml_tpu.parallel.fixed_effect import fit_fixed_effect_admm
    l2 = RegularizationContext(RegularizationType.L2)
    x, y = _admm_problem(n, d, "squared", seed)
    obj = GLMObjective(SQUARED, x, y)
    v0 = float(obj.value(jnp.zeros(d)))
    # per-device budget sized so the F=1 (monolithic-layout) aggregator
    # busts it and the widest mesh fits with room
    budget = d * d * 8 // 4
    entries = {}
    for f_axis in widths:
        key = ("bench-admm-mem", f_axis)
        mesh = _admm_submesh(8 // f_axis, f_axis)
        _log(f"admm[memory]: d={d} feature axis {f_axis} "
             f"(mesh {8 // f_axis}x{f_axis})")
        t0 = time.perf_counter()
        res = fit_fixed_effect_admm(
            obj, np.zeros(d), mesh,
            ADMMConfig(max_iterations=iters, tolerance=1e-9, polish=False),
            reg=l2, reg_weight=0.3, residency_key=key)
        wall = time.perf_counter() - t0
        # read the staged aggregates back out of the residency layer via
        # a second stage call (memoized: returns the pinned arrays)
        from photon_ml_tpu.parallel.fixed_effect import _stage_admm_operands
        staged, _, _, _ = _stage_admm_operands(obj, mesh, key)
        agg_dev = max(s.data.nbytes
                      for s in staged["q_eig"].addressable_shards)
        grid_dev = max(s.data.nbytes
                       for s in staged["x_grid"].addressable_shards)
        entries[f_axis] = {
            "mesh": f"{8 // f_axis}x{f_axis}",
            "per_device_aggregator_bytes": int(agg_dev),
            "per_device_design_bytes": int(grid_dev),
            "fit_s": round(wall, 3),
            "final_value": float(res.value),
            "objective_decreased": bool(float(res.value) < v0),
        }
    base = entries[widths[0]]["per_device_aggregator_bytes"]
    widest = widths[-1]
    near_linear_ok = all(
        entries[f]["per_device_aggregator_bytes"] <= (base / f) * 1.15
        for f in widths[1:])
    wide = entries[widest]
    return {
        "name": "admm_memory", "n": n, "d": d,
        "feature_widths": list(widths),
        "per_device_budget_bytes": int(budget),
        "entries": {str(k): v for k, v in entries.items()},
        "reduction_x": round(
            base / max(wide["per_device_aggregator_bytes"], 1), 2),
        "near_linear_ok": bool(near_linear_ok),
        "monolithic_busts_budget": bool(base > budget),
        "wide_fits_budget": bool(
            wide["per_device_aggregator_bytes"] <= budget),
        "wide_trains": bool(wide["objective_decreased"]),
        "memory_ok": bool(near_linear_ok and base > budget
                          and wide["per_device_aggregator_bytes"] <= budget
                          and wide["objective_decreased"]),
    }


def _admm_trace_leg(n, d, seed):
    """Zero fresh XLA traces across warm consensus solves: rho sweeps,
    tolerance/budget changes, warm starts and in-loop adaptive rho all
    re-dispatch the one compiled while_loop."""
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim import (ADMMConfig, RegularizationContext,
                                     RegularizationType)
    from photon_ml_tpu.parallel.fixed_effect import fit_fixed_effect_admm
    l2 = RegularizationContext(RegularizationType.L2)
    x, y = _admm_problem(n, d, "logistic", seed)
    obj = GLMObjective(LOGISTIC, x, y)
    mesh = _admm_submesh(2, 2)

    def run(cfg, x0):
        return fit_fixed_effect_admm(obj, x0, mesh, cfg, reg=l2,
                                     reg_weight=0.3,
                                     residency_key=("bench-admm-trace",))

    base = dict(max_iterations=120, polish=False)
    first = run(ADMMConfig(tolerance=1e-8, **base), np.zeros(d))
    run(ADMMConfig(tolerance=1e-8, **base), first.x)  # warm device x0 path
    sweeps = [(0.25, 1e-6), (1.0, 1e-8), (4.0, 1e-10)]
    with _trace_counting() as counter:
        warm = run(ADMMConfig(tolerance=1e-8, **base), np.zeros(d))
        for rho, tol in sweeps:
            run(ADMMConfig(rho=rho, tolerance=tol, **base), warm.x)
    return {
        "name": "admm_warm_traces",
        "warm_solves": 1 + len(sweeps),
        "rho_sweep": [s[0] for s in sweeps],
        "fresh_traces": counter.count,
        "traces_ok": bool(counter.count == 0),
    }


def _admm_collective_leg(n, d, seed):
    """Byte/collective accounting on the compiled iteration body: lower
    the exact while_loop step with production shardings on a 2x4 mesh and
    classify every all-reduce in the HLO against the device grid.  HARD
    gate: exactly ONE [n_local] vector all-reduce over the FEATURE groups
    and one [F_local, d_F] block all-reduce over DATA per iteration —
    everything else is scalar residual bookkeeping."""
    import jax
    import jax.numpy as jnp
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim.admm import (ADMMOperands, cached_step_probe,
                                          collective_summary, make_init)
    from photon_ml_tpu.parallel.fixed_effect import _stage_admm_operands
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, feature_sharding
    x, y = _admm_problem(n, d, "logistic", seed)
    obj = GLMObjective(LOGISTIC, x, y)
    mesh = _admm_submesh(2, 4)
    staged, _, _, bw = _stage_admm_operands(obj, mesh, ("bench-admm-hlo",))
    dtype = staged["x_grid"].dtype
    ops = ADMMOperands(
        x_grid=staged["x_grid"], q_eig=staged["q_eig"],
        lam_eig=staged["lam_eig"], labels=staged["labels"],
        kappa=staged["mask"], offsets=staged["offsets"],
        l1_weight=jnp.asarray(0.0, dtype), l2_weight=jnp.asarray(0.3, dtype))
    with mesh:
        w0 = jax.device_put(jnp.zeros((4, bw), dtype),
                            feature_sharding(mesh, 2))
        carry = make_init(obj.loss, False, ops, w0,
                          jnp.asarray(1.0, dtype), 8)
        txt = cached_step_probe(obj.loss, False, True, 8).lower(
            ops, carry).compile().as_text()
    summary = collective_summary(txt, mesh)
    n_local = staged["labels"].shape[0] // mesh.shape[DATA_AXIS]
    feat_vec = [e for e in summary["feature"] if e[0] >= 1]
    data_blk = [e for e in summary["data"] if e[0] >= 1]
    scalars = sum(1 for lane in summary.values()
                  for e in lane if e[0] == 0)
    ok = (feat_vec == [(1, n_local * dtype.itemsize)]
          and len(data_blk) == 1 and data_blk[0][0] >= 2
          and not summary["other"]
          and all(e[0] == 0 for e in summary["global"]))
    return {
        "name": "admm_collectives", "n": n, "d": d, "mesh": "2x4",
        "feature_vector_allreduces": len(feat_vec),
        "feature_vector_bytes": int(feat_vec[0][1]) if feat_vec else 0,
        "data_block_allreduces": len(data_blk),
        "data_block_bytes": int(data_blk[0][1]) if data_blk else 0,
        "scalar_allreduces": scalars,
        "collectives_ok": bool(ok),
    }


def admm_bench(out_path="BENCH_admm.json", smoke=False, max_wall=None):
    """Feature-axis consensus-ADMM lane (optim/admm.py).  HARD gates:
    (1) f64 parity <= 1e-6 of the pure consensus solve vs the monolithic
    LBFGS on 1x1 / 1x2 / 2x2 / 4x2 meshes; (2) near-linear per-device
    aggregator memory reduction as the feature axis widens, with a d
    whose monolithic aggregator busts the per-device budget training
    under a data x feature mesh; (3) zero fresh XLA traces across warm
    solves including rho sweeps and adaptive rho; (4) exactly one
    feature-axis vector all-reduce (+ one data-axis block all-reduce)
    per compiled iteration, by HLO collective accounting."""
    ndev = _ensure_virtual_devices(8)
    if ndev < 8:
        raise SystemExit("--admm needs 8 (virtual) devices")
    if smoke:
        par = dict(n=768, d=24, max_iterations=400, seed=7)
        mem = dict(n=1024, d=256, widths=(1, 2, 4, 8), iters=25, seed=7)
        tr = dict(n=512, d=16, seed=7)
        col = dict(n=512, d=32, seed=7)
    else:
        par = dict(n=max(int(4096 * _SCALE), 768), d=48,
                   max_iterations=800, seed=7)
        mem = dict(n=max(int(4096 * _SCALE), 1024), d=1024,
                   widths=(1, 2, 4, 8), iters=30, seed=7)
        tr = dict(n=2048, d=24, seed=7)
        col = dict(n=1024, d=64, seed=7)
    entries = [_admm_parity_leg(**par), _admm_memory_leg(**mem),
               _admm_trace_leg(**tr), _admm_collective_leg(**col)]
    by_name = {e["name"]: e for e in entries}
    mem_e = by_name["admm_memory"]
    result = {
        "metric": "admm_per_device_aggregator_reduction",
        "value": mem_e["reduction_x"],
        "unit": "x",
        "detail": {
            "entries": entries,
            "parity_ok": by_name["admm_parity"]["parity_ok"],
            "memory_ok": mem_e["memory_ok"],
            "traces_ok": by_name["admm_warm_traces"]["traces_ok"],
            "collectives_ok": by_name["admm_collectives"]["collectives_ok"],
            "all_gates_ok": bool(
                by_name["admm_parity"]["parity_ok"]
                and mem_e["memory_ok"]
                and by_name["admm_warm_traces"]["traces_ok"]
                and by_name["admm_collectives"]["collectives_ok"]),
            "devices": ndev,
            "smoke": smoke,
        },
    }
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# vectorized hyperparameter sweep benchmark (--sweep): K candidates, one
# compiled program
# --------------------------------------------------------------------------

def _sweep_game_data(n, d, users, d_user, seed):
    from photon_ml_tpu.data import build_game_dataset
    rng = np.random.default_rng(seed)
    xg = rng.normal(size=(n, d))
    xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user))
    u = rng.integers(0, users, size=n)
    z = xg @ rng.normal(size=d) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(users, d_user))[u] * 0.7)
    y = z + 0.15 * rng.normal(size=n)
    ds = build_game_dataset(
        y, {"g": xg, "u": xu},
        entity_ids={"userId": np.asarray([f"u{i}" for i in u])})
    rows = np.arange(n)
    cut = int(n * 0.8)
    return ds.subset(rows[:cut]), ds.subset(rows[cut:])


def _sweep_config(w_fe, w_re, outer):
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameTrainingConfig, GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import RegularizationContext, RegularizationType
    l2 = RegularizationContext(RegularizationType.L2)
    return GameTrainingConfig(
        "linear_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "g", GLMOptimizationConfig(regularization=l2,
                                           regularization_weight=w_fe)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "u", GLMOptimizationConfig(
                    regularization=l2, regularization_weight=w_re)),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=outer)


def _sweep_vmap_leg(n, d, users, d_user, K, outer, seed):
    """vmap lane: K candidates ride a leading axis through the compiled
    FE/RE updates, so each coordinate visit is ONE device program against
    ONE staged copy of the data.  Gates: zero fresh traces across a
    K-point sweep after warmup (lambda is a traced operand); per-candidate
    objective parity <= 1e-6 vs isolated f64 fits; sweep wall <= (K/2)x
    one warm isolated fit."""
    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.hyperparameter import SweepEvaluator
    train, val = _sweep_game_data(n, d, users, d_user, seed)
    lams = np.logspace(1.5, -2, K)
    cands = [_sweep_config(lam, 2.0 * lam, outer) for lam in lams]
    warmups = [_sweep_config(0.7 * lam, 1.3 * lam, outer) for lam in lams]
    sweep = SweepEvaluator(GameEstimator(_sweep_config(1.0, 1.0, outer)),
                           train, validation_data=val)
    eligible, why = sweep.vmap_eligible()
    if not eligible:
        raise RuntimeError(f"sweep vmap leg ineligible: {why}")
    _log(f"sweep[vmap]: warmup {K}-candidate sweep (n={n}, d={d})")
    sweep.evaluate_vmapped(warmups)
    with _trace_counting() as tc:
        t0 = time.perf_counter()
        results = sweep.evaluate_vmapped(cands)
        sweep_wall = time.perf_counter() - t0
    _log(f"sweep[vmap]: {K} candidates in {sweep_wall:.3f}s, "
         f"{tc.count} fresh traces; running {K} isolated fits")
    # the pre-sweep cost model: one fresh estimator per candidate (its own
    # coordinate build + staging pass), compile caches warm
    GameEstimator(cands[0]).fit(train, validation_dataset=val)
    iso_walls, iso_objs = [], []
    for cand in cands:
        t0 = time.perf_counter()
        iso = GameEstimator(cand).fit(train, validation_dataset=val)
        iso_walls.append(time.perf_counter() - t0)
        iso_objs.append(float(iso.objective_history[-1]))
    iso_wall = float(np.median(iso_walls))
    objs = [float(r.objective_history[-1]) for r in results]
    parity = max(abs(a - b) / max(abs(b), 1e-12)
                 for a, b in zip(objs, iso_objs))
    ratio = sweep_wall / max(iso_wall, 1e-9)
    return {
        "name": "sweep_vmap", "n": n, "candidates": K,
        "sweep_wall_s": round(sweep_wall, 4),
        "isolated_fit_wall_s": round(iso_wall, 4),
        "wall_ratio_vs_one_fit": round(ratio, 3),
        "fresh_traces_after_warmup": tc.count,
        "objective_parity_rel": parity,
        "traces_ok": tc.count == 0,
        "parity_ok": parity <= 1e-6,
        "sublinear_ok": ratio <= K / 2.0,
        "note": ("the wall gate measures dispatch/staging amortization: a "
                 "1-core CPU still serializes per-lane FLOPs, so the gate "
                 "sits where per-fit overhead is a real fraction of the "
                 "fit — exactly the many-small-refits regime a GP sweep "
                 "dispatches"),
    }


def _sweep_path_leg(n, d, users, d_user, K, outer, seed):
    """warm-start path lane (the sequential / out-of-core fallback):
    candidates run strong-to-weak with each x0 = the neighbor's solution.
    Gate: after the first candidate compiles, the remaining K-1 re-dispatch
    the same programs with lambda as a traced operand — zero fresh traces.
    Warm-start quality is a sanity bound (final objective <= 1.02x the
    cold-start fit), NOT a parity gate: a different x0 changes the
    finite-iteration descent trajectory."""
    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.hyperparameter import SweepEvaluator
    train, val = _sweep_game_data(n, d, users, d_user, seed)
    lams = np.logspace(1.0, -2, K)
    cands = [_sweep_config(lam, 2.0 * lam, outer) for lam in lams]
    sweep = SweepEvaluator(GameEstimator(_sweep_config(1.0, 1.0, outer)),
                           train, validation_data=val)
    _log(f"sweep[path]: warmup candidate, then {K}-point path (n={n})")
    sweep.evaluate_path(cands[:1])
    with _trace_counting() as tc:
        t0 = time.perf_counter()
        warm = sweep.evaluate_path(cands)
        wall = time.perf_counter() - t0
    cold = sweep.evaluate_path(cands, warm_start=False)
    quality_ok = all(
        float(w.objective_history[-1])
        <= float(c.objective_history[-1]) * 1.02
        for w, c in zip(warm, cold))
    return {
        "name": "sweep_path", "n": n, "candidates": K,
        "path_wall_s": round(wall, 4),
        "fresh_traces_after_first_candidate": tc.count,
        "path_traces_ok": tc.count == 0,
        "warm_start_quality_ok": quality_ok,
    }


def sweep_bench(out_path="BENCH_sweep.json", smoke=False, max_wall=None):
    """Vectorized hyperparameter sweeps (ISSUE 17): K candidates, one
    compiled program.  HARD gates (vmap leg): (1) zero fresh XLA traces
    across a 16-point sweep after warmup — lambda and the elastic-net mix
    are traced operands of the compiled solvers; (2) per-candidate
    objective parity <= 1e-6 vs isolated f64 fits; (3) sublinear
    wall-clock — 16 candidates <= 8x one warm isolated fit.  The path leg
    gates zero fresh traces after the first candidate and sanity-bounds
    warm-start quality."""
    ndev = _ensure_virtual_devices(8)
    suite_t0 = time.perf_counter()
    if smoke:
        vm = dict(n=1024, d=12, users=40, d_user=4, K=16, outer=2, seed=17)
        pa = dict(n=512, d=8, users=24, d_user=3, K=6, outer=2, seed=18)
    else:
        vm = dict(n=max(int(4096 * _SCALE), 1024), d=24, users=100,
                  d_user=6, K=16, outer=2, seed=17)
        pa = dict(n=2048, d=12, users=48, d_user=4, K=12, outer=2, seed=18)

    entries = [_sweep_vmap_leg(**vm)]
    if max_wall is None or time.perf_counter() - suite_t0 < max_wall:
        entries.append(_sweep_path_leg(**pa))
    by_name = {e["name"]: e for e in entries}
    vm_e = by_name["sweep_vmap"]
    pa_e = by_name.get("sweep_path")
    result = {
        "metric": "sweep_wall_ratio_vs_one_fit",
        "value": vm_e["wall_ratio_vs_one_fit"],
        "unit": "x",
        "detail": {
            "entries": entries,
            "traces_ok": vm_e["traces_ok"],
            "parity_ok": vm_e["parity_ok"],
            "sublinear_ok": vm_e["sublinear_ok"],
            "path_traces_ok": (pa_e or {}).get("path_traces_ok"),
            "all_gates_ok": bool(
                vm_e["traces_ok"] and vm_e["parity_ok"]
                and vm_e["sublinear_ok"]
                and (pa_e or {"path_traces_ok": True})["path_traces_ok"]),
            "devices": ndev,
            "smoke": smoke,
        },
    }
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# inexact coordinate descent benchmark (--inexact): strict vs scheduled
# --------------------------------------------------------------------------

def _inexact_schedule():
    from photon_ml_tpu.optim import SolverSchedule
    return SolverSchedule(initial_iterations=4, iteration_growth=2.0,
                          initial_tolerance_factor=1e3, tolerance_decay=0.1)


def _run_descent_scheduled(coords, cfg, train, val, specs, schedule):
    """One timed descent run, optionally under an inexactness schedule
    (schedule=None = strict full solves).  Coordinates are pre-built and
    shared across legs, as in --pipeline: the pair isolates the solve
    budgets, not data prep or compile time."""
    from photon_ml_tpu.game.coordinate_descent import (PhaseTimings,
                                                       run_coordinate_descent)
    schedules = ({n: schedule for n in cfg.updating_sequence}
                 if schedule is not None else None)
    spans = PhaseTimings()
    t0 = time.perf_counter()
    res = run_coordinate_descent(
        coords, cfg.updating_sequence, cfg.num_outer_iterations, train,
        cfg.task_type, validation_dataset=val, validation_specs=specs,
        timings=spans, timing_mode="pipelined", solver_schedules=schedules)
    return res, time.perf_counter() - t0, spans


def _inexact_leg_stats(res, wall, spans, cfg):
    diag = res.solver_diagnostics()
    return {
        "fit_s": round(wall, 3),
        "final_nll": float(res.objective_history[-1]),
        "solver_iterations": res.total_iterations(),
        "first_visit_solve_s": {
            name: round(spans.get(f"0/{name}/solve", 0.0), 3)
            for name in cfg.updating_sequence},
        "iterations_by_coordinate": {k: v["iterations"]
                                     for k, v in diag.items()},
        "iteration_caps": {k: v["iteration_caps"] for k, v in diag.items()},
        "reasons": {k: v["reasons"] for k, v in diag.items()},
    }


def _inexact_pair(name, train, val, cfg, parity_gate=None, ref_nll=None,
                  sched_ref_nll=None, ref_extra=None, schedule=None):
    """Warm both program variants (1-outer fits compile the static AND the
    budget-operand solver programs), then time scheduled first and strict
    LAST so residual cache warming favors strict — the conservative
    direction for the reported speedup."""
    import dataclasses as _dc

    from photon_ml_tpu.game import GameEstimator

    est = GameEstimator(cfg)
    t0 = time.perf_counter()
    coords = est._build_coordinates(train)
    build_s = time.perf_counter() - t0
    specs = est._validation_specs(["AUC"])
    schedule = schedule or _inexact_schedule()
    _log(f"inexact[{name}]: coordinates built in {build_s:.1f}s; warmup")
    warm_cfg = _dc.replace(cfg, num_outer_iterations=1)
    _run_descent_scheduled(coords, warm_cfg, train, val, specs, schedule)
    _run_descent_scheduled(coords, warm_cfg, train, val, specs, None)
    legs = {}
    for leg, sched in (("scheduled", schedule), ("strict", None)):
        _log(f"inexact[{name}]: timing {leg}")
        res, wall, spans = _run_descent_scheduled(coords, cfg, train, val,
                                                  specs, sched)
        legs[leg] = _inexact_leg_stats(res, wall, spans, cfg)
    speedup = legs["strict"]["fit_s"] / max(legs["scheduled"]["fit_s"], 1e-9)
    final_gap = abs(legs["scheduled"]["final_nll"]
                    - legs["strict"]["final_nll"]) / max(
        abs(legs["strict"]["final_nll"]), 1e-12)
    entry = {
        "name": name, "task": cfg.task_type, "data": "synthetic-replica",
        "n_train": train.num_rows, "n_validation": val.num_rows,
        "outer_iterations": cfg.num_outer_iterations,
        "coordinates": list(cfg.updating_sequence),
        "schedule": schedule.to_dict(),
        "build_s": round(build_s, 2),
        "strict": legs["strict"], "scheduled": legs["scheduled"],
        "speedup": round(speedup, 3),
        "iterations_saved": (legs["strict"]["solver_iterations"]
                             - legs["scheduled"]["solver_iterations"]),
        # scheduled-vs-strict final objective gap, REPORTED (not the gate
        # at this scale): the movielens convex shape's OUTER loop converges
        # slowly (sweep deltas decay ~0.8x), so at a bench-sized outer
        # count both trajectories are still approaching the fixed point
        # and this gap measures outer-loop tail, not solver error.  The
        # fixed-point equivalence (final full-tolerance visit lands
        # scheduled on the strict optimum) is gated in the float64 test
        # suite on a shape that converges (tests/test_inexact.py) and in
        # the --inexact smoke entry
        "final_rel_gap_vs_strict": float(final_gap),
    }
    if ref_nll is not None:
        # existing same-fit-at-f64 methodology, hard-gated per leg: each
        # leg's f32 fit vs the IDENTICAL fit (same budgets) re-run in
        # float64 on CPU — the strict gate matches bench config 5's convex
        # gate, the scheduled gate proves the traced-budget machinery is
        # numerically faithful
        entry["ref_nll"] = ref_nll
        entry["sched_ref_nll"] = sched_ref_nll
        if ref_extra:
            entry.update(ref_extra)
        entry["nll_rel_gap_strict"] = round(
            (legs["strict"]["final_nll"] - ref_nll) / abs(ref_nll), 9)
        if sched_ref_nll is not None:
            entry["nll_rel_gap_scheduled"] = round(
                (legs["scheduled"]["final_nll"] - sched_ref_nll)
                / abs(sched_ref_nll), 9)
    if parity_gate is not None:
        entry["parity_gate"] = parity_gate
        gaps = [final_gap] if ref_nll is None else [
            abs(entry["nll_rel_gap_strict"])] + (
            [abs(entry["nll_rel_gap_scheduled"])]
            if sched_ref_nll is not None else [])
        entry["parity_ok"] = bool(max(gaps) <= parity_gate)
    return entry


def _inexact_smoke_dataset(with_mf):
    """Tiny GLMix (optionally + factored-MF) shape in the AMBIENT dtype
    (the tier-1 suite runs this under the x64 fixture, like the pipeline
    smoke).  The convex no-MF variant is the parity-gated one — a unique
    optimum makes the gate meaningful; the MF variant carries the
    budget/iterations accounting with the usual non-convex caveat."""
    import dataclasses as _dc

    from photon_ml_tpu.game import FactoredRandomEffectCoordinateConfig
    train, val = _pipeline_dataset(4000, d_global=8, n_users=150, d_user=6,
                                   seed=29)
    # enough outer iterations that BOTH trajectories reach the block-
    # coordinate fixed point: the final full-tolerance visit then lands
    # strict and scheduled on the same optimum (the parity gate measures
    # outer-loop convergence, not float precision)
    cfg = _pipeline_config(5, 25, with_item=False, seed=29,
                           projector="identity")
    if with_mf:
        coords = dict(cfg.coordinates)
        coords["perUserMF"] = FactoredRandomEffectCoordinateConfig(
            "userId", "per_user", latent_dim=2,
            optimization=coords["perUser"].optimization,
            latent_optimization=coords["perUser"].optimization)
        cfg = _dc.replace(cfg, coordinates=coords,
                          updating_sequence=[*cfg.updating_sequence,
                                             "perUserMF"])
    return train, val, cfg


def inexact_bench(out_path="BENCH_inexact.json", smoke=False,
                  max_wall=None):
    """Inexact coordinate descent (ISSUE 4): strict full-solve vs
    scheduled-budget fits on GAME shapes with a factored-MF coordinate,
    sharing pre-built coordinates and warmed programs (identical
    methodology to --pipeline).  The convex leg (FE + 2 RE, unique optimum)
    is hard parity-gated against a float64 CPU reference fit at the
    existing 1e-4 gate; the factored-MF leg carries the speed claim.  Smoke
    mode (tier-1 tests/test_bench_smoke.py::test_inexact_smoke) gates
    parity and the iterations-saved accounting only — seconds-scale CPU
    timing is noise."""
    t_suite = time.perf_counter()
    entries = []
    truncated = []
    if smoke:
        train, val, cfg = _inexact_smoke_dataset(with_mf=False)
        entries.append(_inexact_pair("smoke_inexact_glmix_convex", train,
                                     val, cfg, parity_gate=1e-4))
        train, val, cfg = _inexact_smoke_dataset(with_mf=True)
        entries.append(_inexact_pair("smoke_inexact_glmix_mf", train, val,
                                     cfg))
    else:
        import dataclasses as _dc

        from photon_ml_tpu.optim import SolverSchedule
        n_rows = max(int(400_000 * _SCALE), 8000)
        legs = [
            # convex movielens-shape config (FE + perUser + perItem): the
            # hard parity gate — f64 CPU reference fit, unique optimum.
            # 8 outer iterations so both trajectories reach the block-
            # coordinate fixed point the final full-tolerance visit lands
            # on (the gate measures outer-loop convergence, not precision)
            ("inexact_convex_fe_2re_movielens_shape", "1m", n_rows, 31,
             "convex", 8, True, None),
            # the factored-MF movielens-shape config (ISSUE 4 motivation:
            # BENCH_r05's cold MF solve dominating the fit): the >= 2x
            # speed claim — strict pays full-tolerance convergence on every
            # early visit the next coordinate update then perturbs.
            # Slower cap growth keeps the pre-final visits genuinely cheap
            # (growth 2.0 reaches near-full caps by the third visit)
            ("inexact_full_fe_2re_mf_movielens_shape", "1m", n_rows, 31,
             "full", 4, False,
             SolverSchedule(initial_iterations=4, iteration_growth=1.5,
                            initial_tolerance_factor=1e3,
                            tolerance_decay=0.1)),
        ]
        for name, scale, n_rows, seed, mode, outer, with_ref, sched in legs:
            if max_wall is not None and \
                    time.perf_counter() - t_suite > max_wall:
                truncated.append(name)
                continue
            # two f64 CPU references for the gated leg — the strict fit
            # AND the scheduled fit (same budgets) — joined BEFORE the
            # timed legs run, so on a single-core host the reference work
            # never contends with the measured wall clocks
            procs = {}
            refs = {}
            try:
                if with_ref:
                    for variant, scheduled in (("strict", False),
                                               ("scheduled", True)):
                        cached = _ref_cache_get(scale, n_rows, seed, mode,
                                                outer=outer,
                                                scheduled=scheduled)
                        if cached is not None:
                            refs[variant] = dict(cached, cached=True)
                        else:
                            procs[variant] = _start_ref_game(
                                scale, n_rows, seed, mode, 0.0, outer=outer,
                                scheduled=scheduled)
                train, val, cfg = _game_setup(scale, n_rows, seed,
                                              np.float32, mode, salt=0.0)
                cfg = _dc.replace(cfg, num_outer_iterations=outer)
                ref_nll = sched_ref_nll = ref_extra = None
                if with_ref:
                    for variant, proc in procs.items():
                        ref = _join_ref_game(proc)
                        if "ref_nll" in ref:
                            _ref_cache_put(scale, n_rows, seed, mode, ref,
                                           outer=outer,
                                           scheduled=variant == "scheduled")
                        refs[variant] = ref
                    procs = {}
                    ref_extra = {}
                    for variant, ref in refs.items():
                        if "ref_nll" not in ref:
                            ref_extra[f"ref_error_{variant}"] = ref.get(
                                "error", "unknown")
                    ref_nll = refs.get("strict", {}).get("ref_nll")
                    sched_ref_nll = refs.get("scheduled", {}).get("ref_nll")
                    ref_extra["ref_fit_s"] = refs.get("strict", {}).get(
                        "ref_fit_s")
                    ref_extra["sched_ref_fit_s"] = refs.get(
                        "scheduled", {}).get("ref_fit_s")
                    ref_extra["ref_cached"] = bool(
                        refs.get("strict", {}).get("cached"))
                entries.append(_inexact_pair(
                    name, train, val, cfg,
                    parity_gate=1e-4 if with_ref else None,
                    ref_nll=ref_nll, sched_ref_nll=sched_ref_nll,
                    ref_extra=ref_extra, schedule=sched))
            except BaseException:
                for proc in procs.values():
                    proc.kill()
                    proc.communicate()
                raise
    mf_speedups = [e["speedup"] for e in entries
                   if any("MF" in c for c in e["coordinates"])]
    gated = [e for e in entries if "parity_ok" in e]
    result = {
        "metric": "scheduled_vs_strict_speedup",
        "value": max(mf_speedups) if mf_speedups else 0.0,
        "unit": "x",
        "detail": {
            "entries": entries,
            "speedup_floor": 2.0,
            "speedup_ok": bool(mf_speedups
                               and max(mf_speedups) >= 2.0),
            "all_parity_ok": all(e["parity_ok"] for e in gated),
            "all_iterations_saved": all(e["iterations_saved"] > 0
                                        for e in entries),
            "smoke": smoke,
        },
    }
    if truncated:
        result["detail"]["truncated"] = truncated
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# fault-containment chaos benchmark (--faults): injected faults, gated
# recovery (ISSUE 5)
# --------------------------------------------------------------------------

def _staging_fault_entry(smoke: bool) -> dict:
    """Leg 1: transient chunk-staging faults under a streamed FE fit.  The
    Prefetcher's bounded-retry/backoff loop must absorb every injected
    fault WITHOUT changing the math — the faulted fit's objective history
    must equal the fault-free one's exactly (retries re-stage the same
    chunk bytes), so the gate is the strictest in the suite."""
    import dataclasses as _dc

    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameEstimator)
    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
    from photon_ml_tpu.utils import faults

    n = 4096 if smoke else max(int(100_000 * _SCALE), 16384)
    d = 16 if smoke else 64
    outer, iters = (2, 8) if smoke else (3, 15)
    rng = np.random.default_rng(11)
    x = rng.normal(size=(n, d)); x[:, -1] = 1.0
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-(x @ rng.normal(size=d)
                                                     * 0.5)))).astype(float)
    base_cfg = _stream_config(outer, iters, budget=None, seed=11)
    fe = _dc.replace(base_cfg.coordinates["fixed"], memory_mode="streamed",
                     chunk_rows=max(n // 8, 256))
    cfg = _dc.replace(base_cfg, coordinates={"fixed": fe},
                      updating_sequence=["fixed"])

    def one_run(plan):
        train = build_game_dataset(y, {"global": x})
        est = GameEstimator(cfg)
        coords = est._build_coordinates(train)
        t0 = time.perf_counter()
        if plan is None:
            res = run_coordinate_descent(coords, cfg.updating_sequence,
                                         outer, train, cfg.task_type)
        else:
            with faults.injected(plan):
                res = run_coordinate_descent(coords, cfg.updating_sequence,
                                             outer, train, cfg.task_type)
        wall = time.perf_counter() - t0
        stats = coords["fixed"]._stream.stats.snapshot()
        return res, wall, stats

    _log("faults[staging]: fault-free streamed reference")
    ref, ref_wall, ref_stats = one_run(None)
    plan = faults.FaultPlan([
        {"site": "stage.fetch", "action": "transient", "hits": [1, 4, 7]},
        {"site": "stage.transfer", "action": "transient", "hits": [2]},
    ], seed=11)
    _log("faults[staging]: injected transient staging faults")
    faulted, faulted_wall, stats = one_run(plan)
    gap = max((abs(a - b) for a, b in zip(ref.objective_history,
                                          faulted.objective_history)),
              default=float("inf"))
    rel = gap / max(abs(ref.objective_history[-1]), 1e-12)
    return {
        "name": "staging_transient_faults", "n": n, "d": d,
        "outer_iterations": outer,
        "injected": plan.report(),
        "retries": stats["retries"],
        "retries_fault_free": ref_stats["retries"],
        "gave_up": stats["gave_up"],
        "chunks_staged": stats["chunks_staged"],
        "fault_free_fit_s": round(ref_wall, 3),
        "faulted_fit_s": round(faulted_wall, 3),
        "objective_history_max_abs_gap": float(gap),
        "objective_history_max_rel_gap": float(rel),
        "parity_gate": 1e-4,
        "parity_ok": bool(rel <= 1e-4
                          and len(ref.objective_history)
                          == len(faulted.objective_history)
                          and stats["retries"] >= 4
                          and stats["gave_up"] == 0),
    }


def _run_faults_child(n, outer, iters, seed, ckpt=None, plan=None,
                      timing_mode="pipelined", expect_kill=False):
    """One f64 CPU subprocess fit (--faults-child): the chaos legs need
    true process death (SIGKILL mid-fsync) and the float64 trajectory
    methodology the other benches' references use."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1")
    env.pop("XLA_FLAGS", None)
    env.pop("PHOTON_FAULT_PLAN", None)
    if plan is not None:
        env["PHOTON_FAULT_PLAN"] = json.dumps(plan)
    cmd = [sys.executable, os.path.abspath(__file__), "--faults-child",
           "--n", str(n), "--outer", str(outer), "--iters", str(iters),
           "--seed", str(seed), "--timing-mode", timing_mode]
    if ckpt:
        cmd += ["--ckpt", ckpt]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=1800,
                       cwd=os.path.dirname(os.path.abspath(__file__)))
    if expect_kill:
        return {"returncode": p.returncode, "stderr_tail": p.stderr[-400:]}
    if p.returncode != 0:
        raise RuntimeError(f"faults child failed rc={p.returncode}: "
                           f"{p.stderr[-800:]}")
    return json.loads(p.stdout.strip().splitlines()[-1])


def _faults_child_main(argv):
    """--faults-child mode: one seeded GLMix fit (float64, CPU), optional
    checkpoint dir, fault plan armed via PHOTON_FAULT_PLAN; prints one
    JSON line with the history + containment accounting."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from photon_ml_tpu.utils.jax_cache import enable_persistent_cache
    enable_persistent_cache()
    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.utils import faults
    plan = faults.install_from_env()
    get = lambda flag, default=None: (argv[argv.index(flag) + 1]
                                      if flag in argv else default)
    n = int(get("--n", 2000))
    outer = int(get("--outer", 3))
    iters = int(get("--iters", 8))
    seed = int(get("--seed", 23))
    ckpt = get("--ckpt")
    timing_mode = get("--timing-mode", "pipelined")
    train, _val = _pipeline_dataset(n, d_global=8, n_users=50, d_user=6,
                                    seed=seed)
    cfg = _pipeline_config(outer, iters, with_item=False, seed=seed,
                           projector="identity")
    res = GameEstimator(cfg).fit(train, checkpoint_dir=ckpt,
                                 timing_mode=timing_mode)
    print(json.dumps({
        "objective_history": [float(v) for v in res.objective_history],
        "final": float(res.objective_history[-1]),
        "containment_events": res.descent.containment_events,
        "frozen_coordinates": res.descent.frozen_coordinates,
        "checkpoint_recovery": res.checkpoint_recovery,
        "fault_report": plan.report() if plan is not None else None,
    }))


def _kill_resume_entry(smoke: bool, ref: dict, shape: dict) -> dict:
    """Leg 2: SIGKILL mid-checkpoint-fsync (the torn-write crash), then
    resume.  The killed run dies with state.json.tmp on disk and the new
    record sealed-but-unreferenced; resume must prune the stale tmp,
    restart from the newest verified record, and reproduce the fault-free
    f64 trajectory."""
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "ckpt")
        plan = {"seed": 0, "faults": [
            {"site": "checkpoint.fsync", "action": "kill", "hits": [2]}]}
        _log("faults[kill_resume]: killing a strict-mode fit at the "
             "iteration-1 checkpoint fsync")
        killed = _run_faults_child(ckpt=ckpt, plan=plan,
                                   timing_mode="strict", expect_kill=True,
                                   **shape)
        stale_tmp = os.path.exists(os.path.join(ckpt, "state.json.tmp"))
        _log(f"faults[kill_resume]: killed rc={killed['returncode']}; "
             "resuming")
        resumed = _run_faults_child(ckpt=ckpt, **shape)
    gap = max((abs(a - b) for a, b in zip(ref["objective_history"],
                                          resumed["objective_history"])),
              default=float("inf"))
    rel = gap / max(abs(ref["final"]), 1e-12)
    recovery = resumed["checkpoint_recovery"] or {}
    return {
        "name": "kill_during_checkpoint_then_resume", **shape,
        "killed_returncode": killed["returncode"],
        "stale_tmp_left_by_kill": bool(stale_tmp),
        "checkpoint_recovery": recovery,
        "resumed_from_iteration": recovery.get("resumed_from_iteration"),
        "pruned_on_resume": len(recovery.get("pruned", [])),
        "objective_history_max_abs_gap": float(gap),
        "objective_history_max_rel_gap": float(rel),
        "parity_gate": 1e-4,
        "parity_ok": bool(killed["returncode"] != 0 and rel <= 1e-4
                          and len(ref["objective_history"])
                          == len(resumed["objective_history"])),
    }


def _poisoned_entry(smoke: bool, ref: dict, shape: dict) -> dict:
    """Leg 3: one poisoned coordinate solve (NaN coefficients injected at
    site solve.poison).  The device-side quarantine guard must roll the
    coordinate back, re-run it once at the tightened budget, and land the
    recovered fit's FINAL objective on the fault-free f64 reference (the
    poisoned visit itself logs the rolled-back objective by design, so
    mid-history entries differ at that slot; the gate is the recovered
    final objective, per the same-fit-at-f64 methodology)."""
    plan = {"seed": 0, "faults": [
        {"site": "solve.poison", "action": "poison", "hits": [2],
         "match": {"coordinate": "perUser"}}]}
    _log("faults[poisoned]: poisoning the iteration-1 perUser solve")
    poisoned = _run_faults_child(plan=plan, **shape)
    final_rel = (abs(poisoned["final"] - ref["final"])
                 / max(abs(ref["final"]), 1e-12))
    actions = [e["action"] for e in poisoned["containment_events"]]
    return {
        "name": "poisoned_coordinate_quarantine", **shape,
        "injected": poisoned["fault_report"],
        "containment_events": poisoned["containment_events"],
        "frozen_coordinates": poisoned["frozen_coordinates"],
        "history_finite": bool(np.all(np.isfinite(
            poisoned["objective_history"]))),
        "final_objective": poisoned["final"],
        "ref_final_objective": ref["final"],
        "final_rel_gap_vs_fault_free": float(final_rel),
        "parity_gate": 1e-4,
        "parity_ok": bool(final_rel <= 1e-4
                          and "rolled_back" in actions
                          and np.all(np.isfinite(
                              poisoned["objective_history"]))
                          and len(poisoned["objective_history"])
                          == len(ref["objective_history"])),
    }


def faults_bench(out_path="BENCH_faults.json", smoke=False, max_wall=None):
    """Fault-contained training chaos suite (ISSUE 5): every leg injects a
    committed FaultPlan and GATES that the recovered fit matches the
    fault-free float64 trajectory within the existing 1e-4 gate —
    ≥3 transient staging faults (retry/backoff), one SIGKILL mid-checkpoint
    (manifest-verified fallback resume), one poisoned coordinate solve
    (device-side quarantine + tightened-budget retry).  Retry / quarantine
    / fallback counts are recorded per leg.  Smoke mode runs the same legs
    at tiny shapes for tier-1 (tests/test_bench_smoke.py::
    test_faults_smoke)."""
    t_suite = time.perf_counter()
    shape = (dict(n=1600, outer=3, iters=8, seed=23) if smoke
             else dict(n=max(int(50_000 * _SCALE), 8000), outer=4, iters=12,
                       seed=23))
    entries = []
    truncated = []

    def over_budget(next_leg):
        if max_wall is not None and \
                time.perf_counter() - t_suite > max_wall:
            _log(f"--max-wall {max_wall}s exceeded; skipping {next_leg}")
            truncated.append(next_leg)
            return True
        return False

    if not over_budget("staging"):
        entries.append(_staging_fault_entry(smoke))
    ref = None
    if not over_budget("kill_resume"):
        _log("faults: fault-free f64 reference fit")
        ref = _run_faults_child(**shape)
        entries.append(_kill_resume_entry(smoke, ref, shape))
    if not over_budget("poisoned"):
        if ref is None:
            ref = _run_faults_child(**shape)
        entries.append(_poisoned_entry(smoke, ref, shape))

    gaps = [e.get("objective_history_max_rel_gap",
                  e.get("final_rel_gap_vs_fault_free", 0.0))
            for e in entries]
    result = {
        "metric": "fault_recovery_max_rel_gap",
        "value": float(max(gaps)) if gaps else None,
        "unit": "relative",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            "all_parity_ok": all(e["parity_ok"] for e in entries),
            "parity_gate": 1e-4,
            # no-plan hot paths are gated separately: the compile-count
            # regression (tests/test_faults.py) and the pipelined-timing
            # smoke both run WITHOUT a FaultPlan and must be unchanged
            "injection_inactive_overhead": "none (module-global None "
                                           "check per site)",
        },
    }
    if truncated:
        result["detail"]["truncated"] = truncated
        result["detail"]["max_wall_s"] = max_wall
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# smoke benchmark (--smoke): tiny, seconds, CPU-safe, no reference solves
# --------------------------------------------------------------------------

# --------------------------------------------------------------------------
# multi-chip mesh benchmark (--mesh): 1-vs-N virtual devices, hard gates on
# f64 parity, warm-iteration transfer bytes, and zero fresh traces
# --------------------------------------------------------------------------

def _ensure_virtual_devices(n: int) -> int:
    """Best-effort: n virtual CPU devices + float64 (the tests/conftest.py
    pattern).  Standalone `bench.py --mesh` runs set the XLA flag before
    jax initializes; under the tier-1 suite the conftest already did."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax
    for key, val in (("jax_platforms", "cpu"), ("jax_num_cpu_devices", n)):
        try:
            jax.config.update(key, val)
        except Exception:
            pass  # older jax / backend already initialized with the flag
    jax.config.update("jax_enable_x64", True)   # f64 parity gates
    return len(jax.devices())


class _TraceCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _trace_counting:
    """Counts fresh XLA traces via jax_log_compiles (a persistent-cache hit
    still logs the trace, so this gates TRACING, not backend compiles)."""

    def __enter__(self):
        import jax
        self._jax = jax
        self.handler = _TraceCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        self._jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def _mesh_config(outer, iters, *, with_re=True, with_mf=False, budget=None,
                 seed=11):
    from photon_ml_tpu.game import (FactoredRandomEffectCoordinateConfig,
                                    FixedEffectCoordinateConfig,
                                    GameTrainingConfig, GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    l2 = RegularizationContext(RegularizationType.L2)
    opt = lambda w: GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=iters),
        regularization=l2, regularization_weight=w)
    coords = {"fixed": FixedEffectCoordinateConfig("global", opt(1.0))}
    seq = ["fixed"]
    if with_re:
        coords["perUser"] = RandomEffectCoordinateConfig(
            "userId", "per_user", opt(1.0), projector="identity")
        seq.append("perUser")
    if with_mf:
        coords["perUserMF"] = FactoredRandomEffectCoordinateConfig(
            "userId", "per_user", latent_dim=2, num_inner_iterations=1,
            optimization=opt(1.0), latent_optimization=opt(0.5))
        seq.append("perUserMF")
    return GameTrainingConfig(task_type="logistic_regression",
                              coordinates=coords, updating_sequence=seq,
                              num_outer_iterations=outer, seed=seed,
                              hbm_budget_bytes=budget)


def _warm_operand_bound(coords, cfg, mesh) -> dict:
    """Per-coordinate byte bound of what a WARM mesh visit may stage:
    coefficients (x0) + residual offsets, padded to the mesh multiple, with
    50% slack — the dataset (d x bigger) cannot hide inside it."""
    from photon_ml_tpu.parallel.mesh import DATA_AXIS
    D = int(mesh.shape[DATA_AXIS])
    item = 8  # f64
    ceil_mult = lambda v: -(-int(v) // D) * D
    bounds = {}
    for name in cfg.updating_sequence:
        c = coords[name]
        if hasattr(c, "red"):
            cells = sum(ceil_mult(b.num_entities)
                        * (b.samples_per_entity + b.dim)
                        for b in c.red.buckets)
        else:
            cells = ceil_mult(c.labels.shape[0]) + c.dim
        bounds[name] = int(cells * item * 1.5)
    return bounds


def _mesh_leg(name, n, d_global, n_users, d_user, outer, iters, seed,
              with_re=True, with_mf=False, parity_gate=1e-4):
    """One mesh-vs-single-device leg.  The single-device fit is the parity
    reference; the mesh fit runs TWICE over shared pre-built coordinates —
    the cold run stages the static data, the warm run gates the
    steady-state contract: identical history (determinism), ZERO cold bytes
    staged, per-visit warm bytes bounded by coefficients+offsets, and zero
    fresh XLA traces.  Factored coordinates re-project their latent blocks
    every visit (P is refit), so their per-visit re-stage is exempt from
    the warm-bytes gate and reported instead."""
    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.mesh_residency import (TransferStats,
                                                       transfer_snapshot)

    train, val = _pipeline_dataset(n, d_global, n_users, d_user, seed)
    cfg = _mesh_config(outer, iters, with_re=with_re, with_mf=with_mf,
                       seed=seed)
    _log(f"mesh[{name}]: single-device reference fit")
    t0 = time.perf_counter()
    ref = GameEstimator(cfg).fit(train, val, evaluator_specs=["AUC"])
    ref_s = time.perf_counter() - t0

    mesh = make_mesh()
    est = GameEstimator(cfg, mesh=mesh)
    t0 = time.perf_counter()
    coords = est._build_coordinates(train)
    build_s = time.perf_counter() - t0
    specs = est._validation_specs(["AUC"])

    def one_run():
        t0 = time.perf_counter()
        r = run_coordinate_descent(
            coords, cfg.updating_sequence, cfg.num_outer_iterations, train,
            cfg.task_type, validation_dataset=val, validation_specs=specs,
            residency=est._residency_manager(coords, train))
        return r, time.perf_counter() - t0

    snap0 = transfer_snapshot()
    _log(f"mesh[{name}]: mesh cold fit ({dict(mesh.shape)})")
    res_cold, cold_s = one_run()
    snap1 = transfer_snapshot()
    _log(f"mesh[{name}]: mesh warm fit (transfer + trace gates)")
    with _trace_counting() as traces:
        res_warm, warm_s = one_run()
    snap2 = transfer_snapshot()

    gaps = [abs(a - b) / max(abs(a), 1e-12)
            for a, b in zip(ref.objective_history, res_cold.objective_history)]
    max_gap = max(gaps) if gaps else 0.0
    warm_identical = (res_warm.objective_history
                      == res_cold.objective_history)

    # warm-visit transfer gate: every tracked visit of a non-factored
    # coordinate staged ZERO cold bytes and warm bytes within the
    # coefficients+offsets bound
    bounds = _warm_operand_bound(coords, cfg, mesh)
    gated_coords = [c for c in cfg.updating_sequence if c != "perUserMF"]
    warm_visits = []
    warm_ok = True
    for key, t in sorted(res_warm.trackers.items()):
        coord = key.split("/", 1)[1]
        sb = t.staged_bytes or {"cold": 0, "warm": 0}
        entry = {"visit": key, "cold": sb["cold"], "warm": sb["warm"],
                 "bound": bounds.get(coord)}
        if coord in gated_coords:
            entry["ok"] = sb["cold"] == 0 and sb["warm"] <= bounds[coord]
            warm_ok = warm_ok and entry["ok"]
        warm_visits.append(entry)
    cold_delta = TransferStats.delta(snap0, snap1)
    warm_delta = TransferStats.delta(snap1, snap2)

    return {
        "name": name, "task": "logistic_regression",
        "data": "synthetic-replica", "n_train": train.num_rows,
        "n_validation": val.num_rows, "outer_iterations": outer,
        "entities": {"userId": n_users},
        "d_global": d_global, "d_user": d_user,
        "mesh_shape": dict(mesh.shape),
        "coordinates": list(cfg.updating_sequence),
        "single_device_fit_s": round(ref_s, 3),
        "mesh_build_s": round(build_s, 3),
        "mesh_cold_fit_s": round(cold_s, 3),
        "mesh_warm_fit_s": round(warm_s, 3),
        # wall-clock is reported UNGATED: virtual CPU devices time-slice
        # one host's cores, so the honest CPU-CI gates are parity,
        # transfer behavior, and compile stability — not speedup
        "objective_history_max_rel_gap": float(max_gap),
        "parity_gate": parity_gate,
        "parity_ok": bool(max_gap <= parity_gate
                          and len(ref.objective_history)
                          == len(res_cold.objective_history)),
        "warm_run_bit_identical_history": bool(warm_identical),
        "cold_run_staged": cold_delta,
        "warm_run_staged": warm_delta,
        "warm_visits": warm_visits,
        "warm_transfer_gated_coordinates": gated_coords,
        "warm_transfer_ok": bool(warm_ok),
        "fresh_traces_warm_run": traces.count,
        "zero_fresh_traces_ok": traces.count == 0,
        "validation_auc": {
            "single": round(float(ref.validation["AUC"]), 5),
            "mesh": round(float(res_cold.validation_history["AUC"][-1]), 5),
        },
    }


def _mesh_stream_leg(name, n, d_global, n_users, d_user, outer, iters, seed,
                     parity_gate=1e-4):
    """Mesh x out-of-core: a config whose PER-DEVICE coordinate data
    exceeds the per-device budget trains on the mesh (FE shard chunk-
    streamed, rows sharded over "data", GSPMD psums in the accumulators),
    parity-gated against the RESIDENT single-device reference."""
    import dataclasses as _dc

    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.mesh import DATA_AXIS

    train, val = _pipeline_dataset(n, d_global, n_users, d_user, seed)
    cfg0 = _mesh_config(outer, iters, seed=seed)
    _log(f"mesh[{name}]: resident single-device reference fit")
    t0 = time.perf_counter()
    ref = GameEstimator(cfg0).fit(train, val, evaluator_specs=["AUC"])
    ref_s = time.perf_counter() - t0

    mesh = make_mesh()
    D = int(mesh.shape[DATA_AXIS])
    acct = ref.residency
    fe_b = acct["resident_block_bytes"]["fixed"]
    re_b = sum(b for c, b in acct["resident_block_bytes"].items()
               if c != "fixed")
    flat = acct["flat_vector_bytes"]
    # per-device floor: flat [n] vectors (undivided — they may replicate)
    # + RE blocks/D + the chunk double buffer (<= budget/2 by plan
    # construction), so budget = 2.2x the non-chunk floor holds it all;
    # streaming engages iff fe/D > budget/2
    floor = flat + -(-re_b // D)
    stream_cap = 2 * fe_b // D
    budget = int(floor * 2.2)
    assert budget < stream_cap, (
        f"mesh stream leg shape cannot force streaming: budget {budget} >= "
        f"2*fe/D {stream_cap}; widen d_global or grow n")
    cfg = _dc.replace(cfg0, hbm_budget_bytes=budget)
    _log(f"mesh[{name}]: mesh-streamed fit (per-device budget {budget})")
    t0 = time.perf_counter()
    res = GameEstimator(cfg, mesh=mesh).fit(train, val,
                                            evaluator_specs=["AUC"])
    mesh_s = time.perf_counter() - t0

    gaps = [abs(a - b) / max(abs(a), 1e-12)
            for a, b in zip(ref.objective_history, res.objective_history)]
    max_gap = max(gaps) if gaps else 0.0
    racct = res.residency
    per_dev_data = -(-(fe_b + re_b) // D) + flat
    return {
        "name": name, "task": "logistic_regression",
        "data": "synthetic-replica", "n_train": train.num_rows,
        "n_validation": val.num_rows, "outer_iterations": outer,
        "entities": {"userId": n_users},
        "d_global": d_global, "d_user": d_user,
        "mesh_shape": dict(mesh.shape),
        "hbm_budget_bytes_per_device": budget,
        "per_device_data_bytes": per_dev_data,
        "data_exceeds_budget": bool(per_dev_data > budget),
        "single_device_resident_fit_s": round(ref_s, 3),
        "mesh_streamed_fit_s": round(mesh_s, 3),
        "streamed_coordinates": list(racct["streamed_chunk_bytes"]),
        "per_device_accounting": {
            "per_device": racct["per_device"],
            "data_devices": racct["data_devices"],
            "peak_tracked_bytes": racct["peak_tracked_bytes"],
            "under_budget": racct["under_budget"],
        },
        "mesh_transfer": res.mesh_transfer,
        "objective_history_max_rel_gap": float(max_gap),
        "parity_gate": parity_gate,
        "parity_ok": bool(max_gap <= parity_gate
                          and len(ref.objective_history)
                          == len(res.objective_history)),
        "streamed_engaged_ok": bool(racct["streamed_chunk_bytes"]),
        "under_budget_ok": bool(racct["under_budget"]),
    }


def mesh_bench(out_path="BENCH_mesh.json", smoke=False, max_wall=None,
               devices=8):
    """Multi-chip SPMD GAME training (ISSUE 6): 1-vs-N virtual CPU devices
    with HARD gates on f64 objective-history parity (every leg: FE, RE,
    factored-MF, mesh-streamed), warm-iteration staged bytes (cold == 0,
    warm <= coefficients+offsets — no per-update dataset re-transfer), and
    zero fresh XLA traces across warm outer iterations.  Wall-clock is
    reported ungated: virtual CPU devices share one host's cores, so the
    honest CPU-CI gate is transfer/compile behavior, not speedup."""
    ndev = _ensure_virtual_devices(devices)
    if ndev < 2:
        raise RuntimeError(
            f"mesh bench needs >= 2 devices, have {ndev}: set "
            "--xla_force_host_platform_device_count (or run under the test "
            "fixture) before jax initializes")
    suite_t0 = time.perf_counter()
    if smoke:
        specs = [
            ("fe", dict(n=2500, d_global=16, n_users=0, d_user=4, outer=2,
                        iters=6, seed=11, with_re=False)),
            ("re", dict(n=2500, d_global=16, n_users=125, d_user=5, outer=2,
                        iters=6, seed=13)),
            ("factored", dict(n=2500, d_global=12, n_users=125, d_user=5,
                              outer=2, iters=5, seed=17, with_mf=True)),
        ]
        stream_spec = dict(n=6000, d_global=96, n_users=200, d_user=4,
                           outer=2, iters=6, seed=19)
    else:
        specs = [
            ("fe", dict(n=max(int(120_000 * _SCALE), 8000), d_global=64,
                        n_users=0, d_user=4, outer=3, iters=15, seed=11,
                        with_re=False)),
            ("re", dict(n=max(int(80_000 * _SCALE), 8000), d_global=48,
                        n_users=max(int(8_000 * _SCALE), 400), d_user=12,
                        outer=3, iters=12, seed=13)),
            ("factored", dict(n=max(int(40_000 * _SCALE), 6000), d_global=32,
                              n_users=max(int(4_000 * _SCALE), 300),
                              d_user=10, outer=3, iters=8, seed=17,
                              with_mf=True)),
        ]
        stream_spec = dict(n=max(int(100_000 * _SCALE), 8000), d_global=96,
                           n_users=max(int(5_000 * _SCALE), 300), d_user=8,
                           outer=3, iters=12, seed=19)

    entries = []
    truncated = []
    for leg_name, kw in specs:
        if max_wall is not None and \
                time.perf_counter() - suite_t0 > max_wall:
            truncated.append(f"mesh_{leg_name}")
            continue
        # the dataset's entity column needs >= 1 user even on the FE-only
        # leg (the builder requires ids); give it a degenerate column
        if kw.get("n_users", 0) == 0:
            kw["n_users"] = 50
        entries.append(_mesh_leg(f"mesh_{leg_name}", **kw))
    if max_wall is not None and time.perf_counter() - suite_t0 > max_wall:
        truncated.append("mesh_streamed")
    else:
        entries.append(_mesh_stream_leg("mesh_streamed", **stream_spec))

    gaps = [e["objective_history_max_rel_gap"] for e in entries]
    result = {
        "metric": "mesh_vs_single_device_max_rel_objective_gap",
        "value": max(gaps) if gaps else None,
        "unit": "rel",
        "detail": {
            "devices": ndev,
            "entries": entries,
            "all_parity_ok": all(e["parity_ok"] for e in entries),
            "all_warm_transfer_ok": all(e.get("warm_transfer_ok", True)
                                        for e in entries),
            "all_zero_fresh_traces": all(e.get("zero_fresh_traces_ok", True)
                                         for e in entries),
            "streamed_under_budget": all(e.get("under_budget_ok", True)
                                         for e in entries),
            "smoke": smoke,
        },
    }
    if truncated:
        result["detail"]["truncated"] = truncated
        result["detail"]["max_wall_s"] = max_wall
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


def _mh_free_port():
    import socket
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _mh_write_inputs(root, n, d, outer, seed=3):
    from photon_ml_tpu.data import build_game_dataset
    from photon_ml_tpu.data.game_data import save_game_dataset

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-x @ w))).astype(
        np.float64)
    data = os.path.join(root, "data.npz")
    if not os.path.exists(data):
        save_game_dataset(build_game_dataset(y, {"global": x}), data)
    config = os.path.join(root, f"game-{outer}.json")
    with open(config, "w") as f:
        json.dump({
            "task_type": "logistic_regression",
            "coordinates": {
                "fixed": {
                    "kind": "fixed_effect",
                    "feature_shard": "global",
                    "optimization": {
                        "optimizer": {"optimizer": "lbfgs",
                                      "max_iterations": 3},
                        "regularization": {"type": "l2"},
                        "regularization_weight": 1.0,
                    },
                }
            },
            "updating_sequence": ["fixed"],
            "num_outer_iterations": outer,
        }, f)
    return data, config


_MH_HEARTBEAT_ENV = {
    "PHOTON_HEARTBEAT_INTERVAL": "0.2",
    "PHOTON_HEARTBEAT_TIMEOUT": "2",
    "PHOTON_HEARTBEAT_ESCALATE": "5",
}


def _mh_spawn(data, config, out_dir, *, devices, coordinator=None,
              num_processes=None, process_id=None):
    """One cli.train worker subprocess (its own jax runtime: multi-process
    meshes cannot share the bench's)."""
    cmd = [sys.executable, "-m", "photon_ml_tpu.cli.train",
           "--train-data", data, "--config", config, "--x64",
           "--mesh", "auto", "--no-compile-cache",
           "--checkpoint-dir", os.path.join(out_dir, "ckpt"),
           "--output-dir", out_dir]
    if coordinator is not None:
        cmd += ["--coordinator", coordinator,
                "--num-processes", str(num_processes),
                "--process-id", str(process_id)]
    env = dict(os.environ)
    for k in ("PHOTON_COORDINATOR", "PHOTON_NUM_PROCESSES",
              "PHOTON_PROCESS_ID"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env.update(_MH_HEARTBEAT_ENV)
    tag = "" if process_id is None else f".proc{process_id}"
    out_path = os.path.join(out_dir, f"worker{tag}.out")
    out = open(out_path, "w")
    err = open(os.path.join(out_dir, f"worker{tag}.err"), "w")
    proc = subprocess.Popen(cmd, cwd=os.path.dirname(
        os.path.abspath(__file__)), env=env, stdout=out, stderr=err)
    proc._mh_streams = (out, err)
    proc._mh_out_path = out_path
    return proc


def _mh_finish(proc, timeout=240):
    try:
        rc = proc.wait(timeout=timeout)
    finally:
        for h in proc._mh_streams:
            h.close()
    return rc


def _mh_last_json(path):
    for ln in reversed([x for x in open(path).read().splitlines()
                        if x.strip()]):
        try:
            return json.loads(ln)
        except ValueError:
            continue
    raise RuntimeError(f"no JSON summary line in {path}")


def _mh_run_pair(data, config, out_dir):
    os.makedirs(out_dir, exist_ok=True)
    port = _mh_free_port()
    workers = [_mh_spawn(data, config, out_dir, devices=1,
                         coordinator=f"localhost:{port}", num_processes=2,
                         process_id=pid) for pid in (0, 1)]
    return [(_mh_finish(w), w._mh_out_path) for w in workers]


def _mh_model_bytes(out_dir):
    best = os.path.join(out_dir, "best")
    out = {}
    for root, _, names in os.walk(best):
        for fn in names:
            if fn == "model-metadata.json":  # carries timestamps
                continue
            p = os.path.join(root, fn)
            with open(p, "rb") as f:
                out[os.path.relpath(p, best)] = f.read()
    return out


def multihost_bench(out_path="BENCH_multihost.json", smoke=False,
                    max_wall=None):
    """Multi-host data-mesh training (ISSUE 19): jax.distributed
    bring-up on 2 subprocess workers (1 virtual CPU device each) against
    a 1-process x 2-device mirror of the SAME global mesh, with hard
    gates on (1) f64 objective-history parity <= 1e-8 across process
    counts (expected: bit-exact — same mesh shape => same GSPMD
    program), (2) zero fresh XLA traces across warm outer iterations on
    BOTH processes, (3) per-process staging: cold bytes symmetric across
    hosts (each stages ~1/P of the rows) and warm per-iteration bytes
    bounded by vector traffic, (4) lost-worker containment: SIGKILL one
    worker mid-run -> the survivor exits 75 with checkpoint-consistent
    state -> a 1-process relaunch resumes bit-exactly vs an
    uninterrupted reference.  Wall-clock is reported ungated (virtual
    CPU devices share one host's cores)."""
    import shutil
    import signal
    import tempfile

    suite_t0 = time.perf_counter()
    n, d = (512, 8) if smoke else (max(int(20_000 * _SCALE), 2048), 16)
    outer_long, outer_short = (6, 3) if smoke else (10, 4)
    root = tempfile.mkdtemp(prefix="bench_multihost_")
    detail = {"processes": 2, "n": n, "d": d,
              "outer_iterations": outer_long, "smoke": smoke}
    truncated = []
    try:
        data, config = _mh_write_inputs(root, n, d, outer_long)
        _, config_short = _mh_write_inputs(root, n, d, outer_short)

        # -- leg 1+2+3: the 2-process pair, its 1-process mirror, and a
        # shorter pair for the warm-trace differential
        two = os.path.join(root, "two")
        ref = os.path.join(root, "ref")
        t0 = time.perf_counter()
        pair = _mh_run_pair(data, config, two)
        pair_wall = time.perf_counter() - t0
        os.makedirs(ref, exist_ok=True)
        t0 = time.perf_counter()
        rp = _mh_spawn(data, config, ref, devices=2)
        ref_rc = _mh_finish(rp)
        ref_wall = time.perf_counter() - t0
        if any(rc != 0 for rc, _ in pair) or ref_rc != 0:
            raise RuntimeError(
                f"multihost bench run failed: pair rc="
                f"{[rc for rc, _ in pair]} ref rc={ref_rc}")

        with open(os.path.join(two, "ckpt", "state.json")) as f:
            h2 = np.asarray(json.load(f)["objective_history"], np.float64)
        with open(os.path.join(ref, "ckpt", "state.json")) as f:
            h1 = np.asarray(json.load(f)["objective_history"], np.float64)
        parity_gap = float(np.max(np.abs(h2 - h1))) \
            if h2.shape == h1.shape else float("inf")
        m2, m1 = _mh_model_bytes(two), _mh_model_bytes(ref)
        model_bit_identical = bool(m2) and m2 == m1

        s0 = _mh_last_json(pair[0][1])
        s1 = _mh_last_json(pair[1][1])
        cold = [s["mesh_transfer"]["cold_bytes"] for s in (s0, s1)]
        warm = [s["mesh_transfer"]["warm_bytes"] for s in (s0, s1)]
        warm_bound = 8 * (n // 2 + d) * 8  # vectors + slack, per iteration
        staging_ok = (min(cold) > 0
                      and max(cold) / max(1, min(cold)) <= 1.5
                      and all(w / outer_long <= warm_bound for w in warm))

        if max_wall is not None and \
                time.perf_counter() - suite_t0 > max_wall:
            truncated.append("multihost_traces")
            traces_ok = None
            compile_counts = None
        else:
            short_dir = os.path.join(root, "short")
            short_pair = _mh_run_pair(data, config_short, short_dir)
            if any(rc != 0 for rc, _ in short_pair):
                raise RuntimeError("multihost short pair failed")
            compile_counts = {
                "long": [_mh_last_json(p)["compile_count"]
                         for _, p in pair],
                "short": [_mh_last_json(p)["compile_count"]
                          for _, p in short_pair],
            }
            traces_ok = compile_counts["long"] == compile_counts["short"]

        # -- leg 4: lost-worker containment + bit-exact resume
        if max_wall is not None and \
                time.perf_counter() - suite_t0 > max_wall:
            truncated.append("multihost_kill_resume")
            kill = None
        else:
            kout = os.path.join(root, "kill")
            os.makedirs(kout, exist_ok=True)
            port = _mh_free_port()
            w0 = _mh_spawn(data, config, kout, devices=1,
                           coordinator=f"localhost:{port}",
                           num_processes=2, process_id=0)
            w1 = _mh_spawn(data, config, kout, devices=1,
                           coordinator=f"localhost:{port}",
                           num_processes=2, process_id=1)
            state = os.path.join(kout, "ckpt", "state.json")
            deadline = time.time() + 240
            while not os.path.exists(state) and time.time() < deadline:
                time.sleep(0.1)
            os.kill(w1.pid, signal.SIGKILL)
            _mh_finish(w1)
            survivor_rc = _mh_finish(w0)
            payload = _mh_last_json(w0._mh_out_path)
            rproc = _mh_spawn(data, config, kout, devices=2)
            resume_rc = _mh_finish(rproc)
            resumed = _mh_last_json(rproc._mh_out_path)
            reference = _mh_last_json(rp._mh_out_path)
            mk = _mh_model_bytes(kout)
            kill = {
                "survivor_rc": survivor_rc,
                "survivor_rc_ok": survivor_rc == 75,
                "lost_worker": payload.get("lost_worker"),
                "resume_rc": resume_rc,
                "resumed_from_iteration": resumed.get(
                    "checkpoint_recovery", {}).get(
                        "resumed_from_iteration"),
                "final_objective_bit_equal": (
                    resumed.get("final_objective")
                    == reference.get("final_objective")),
                "model_bit_identical": bool(mk) and mk == m1,
            }
            kill["resume_ok"] = (kill["survivor_rc_ok"]
                                 and resume_rc == 0
                                 and kill["final_objective_bit_equal"]
                                 and kill["model_bit_identical"])

        detail.update({
            "parity_gap_abs": parity_gap,
            "parity_ok": parity_gap <= 1e-8,
            "model_bit_identical": model_bit_identical,
            "cold_bytes_per_process": cold,
            "warm_bytes_per_process": warm,
            "warm_per_iter_bound_bytes": warm_bound,
            "staging_ok": staging_ok,
            "compile_counts": compile_counts,
            "zero_fresh_traces_ok": traces_ok,
            "kill_resume": kill,
            "two_process_wall_s": round(pair_wall, 3),
            "one_process_wall_s": round(ref_wall, 3),
            "gates_green": bool(
                parity_gap <= 1e-8 and model_bit_identical and staging_ok
                and (traces_ok is not False)
                and (kill is None or kill["resume_ok"])),
        })
    finally:
        shutil.rmtree(root, ignore_errors=True)

    result = {
        "metric": "multihost_vs_single_process_objective_gap",
        "value": detail.get("parity_gap_abs"),
        "unit": "abs",
        "detail": detail,
    }
    if truncated:
        detail["truncated"] = truncated
        detail["max_wall_s"] = max_wall
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


def smoke_bench(out_path="BENCH_smoke.json"):
    """One tiny GLM solve + one tiny strict-vs-pipelined GAME pair: the
    bench harness end-to-end in seconds, CPU-safe, no scipy/f64 reference
    fits and no shared-cache writes — so bench-harness regressions surface
    in the tier-1 suite (tests/test_bench_smoke.py) instead of only at
    bench time.  Speed numbers here are smoke signals, not benchmarks."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.synthetic_bench import make_a1a_like
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    t_suite = time.perf_counter()
    x, y = make_a1a_like(1, "logistic", seed=42)
    res, wall, compile_s = time_glm_solve(
        "logistic_regression", x, y,
        OptimizerConfig(max_iterations=25, tolerance=1e-7),
        RegularizationContext(RegularizationType.L2), 1.0, reps=1)
    glm = {"name": "smoke_a1a_logistic", "n": int(x.shape[0]),
           "d": int(x.shape[1]), "wall_s": round(wall, 3),
           "compile_s": round(compile_s, 2),
           "final_value_finite": bool(np.isfinite(float(res.value)))}

    game = _pipeline_entry("smoke_glmix_pipeline", n=3000, d_global=8,
                           n_users=150, d_user=4, outer=2, solver_iters=10,
                           seed=9)
    result = {
        "metric": "bench_smoke_wall_s",
        "value": round(time.perf_counter() - t_suite, 2),
        "unit": "s",
        "detail": {"glm": glm, "game_pipeline": game,
                   "parity_ok": game["parity_ok"]},
    }
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# telemetry benchmark (--trace): disarmed overhead + timeline validity
# --------------------------------------------------------------------------

def _span_overhead_per_call(reps: int = 50_000) -> float:
    """Median-of-3 per-call cost of a DISARMED telemetry.span() with-block
    (module-global None check + shared no-op singleton)."""
    from photon_ml_tpu import telemetry
    assert not telemetry.armed()
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(reps):
            with telemetry.span("bench_probe"):
                pass
        samples.append((time.perf_counter() - t0) / reps)
    samples.sort()
    return samples[1]


def _trace_tree_checks(payload: dict, outer: int, coords: int) -> dict:
    """Validate the exported Chrome trace's span TREE (not just its keys):
    the fit nests outer iterations -> coordinate visits -> solves, using
    the args.span/args.parent ids the exporter embeds."""
    events = payload["traceEvents"]
    spans = {e["args"]["span"]: e for e in events
             if e.get("ph") == "X" and "span" in e.get("args", {})}
    by_name = {}
    for e in spans.values():
        by_name.setdefault(e["name"], []).append(e)

    def parent_name(e):
        p = spans.get(e["args"].get("parent"))
        return p["name"] if p else None

    checks = {
        "outer_iteration_spans": len(by_name.get("outer_iteration", ())),
        "coordinate_visit_spans": len(by_name.get("coordinate_visit", ())),
        "solve_spans": len(by_name.get("solve", ())),
        "outer_count_ok":
            len(by_name.get("outer_iteration", ())) == outer,
        "visit_count_ok":
            len(by_name.get("coordinate_visit", ())) == outer * coords,
        "visits_nest_in_outer": all(
            parent_name(e) == "outer_iteration"
            for e in by_name.get("coordinate_visit", ())),
        "solves_nest_in_visits": all(
            parent_name(e) == "coordinate_visit"
            for e in by_name.get("solve", ())),
        "checkpoints_present": bool(by_name.get("checkpoint_write")
                                    or by_name.get("checkpoint")),
    }
    checks["nesting_ok"] = bool(
        checks["outer_count_ok"] and checks["visit_count_ok"]
        and checks["visits_nest_in_outer"]
        and checks["solves_nest_in_visits"]
        and checks["checkpoints_present"])
    return checks


def _overhead_entry(smoke: bool) -> dict:
    """Disarmed-overhead + zero-fresh-traces leg.

    The acceptance bar is "disarmed telemetry within 1% wall-clock of the
    pre-PR baseline".  The pre-PR binary is not runnable here, so the gate
    is the measurable equivalent: (disarmed per-span-call cost x the
    number of span call sites an armed fit actually hits) must be <= 1%
    of the disarmed fit's wall clock — the instrumentation's worst-case
    contribution, measured, not assumed.  Plus the hard trace gates: a
    warm fit stays at ZERO fresh XLA traces with telemetry disarmed AND
    armed."""
    import tempfile

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent

    n = 3000 if smoke else max(int(60_000 * _SCALE), 6000)
    outer = 2 if smoke else 4
    train, val = _pipeline_dataset(n, 8, max(n // 20, 50), 4, seed=9)
    cfg = _pipeline_config(outer, 10, with_item=False, seed=9)
    est = GameEstimator(cfg)
    coords = est._build_coordinates(train)
    specs = est._validation_specs(["AUC"])

    def one_fit(ckpt):
        t0 = time.perf_counter()
        run_coordinate_descent(
            coords, cfg.updating_sequence, outer, train, cfg.task_type,
            validation_dataset=val, validation_specs=specs,
            checkpoint_dir=ckpt, timing_mode="pipelined")
        return time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as tmp:
        one_fit(os.path.join(tmp, "warm"))  # compile everything
        with _trace_counting() as tc_dis:
            wall_disarmed = one_fit(os.path.join(tmp, "dis"))
        fresh_disarmed = tc_dis.count
        # armed leg: watch_compiles=False so the independent
        # _trace_counting harness owns jax_log_compiles
        with _trace_counting() as tc_arm:
            with telemetry.enabled(watch_compiles=False) as tracer:
                wall_armed = one_fit(os.path.join(tmp, "arm"))
                span_calls = len(tracer.spans) + tracer.dropped
        fresh_armed = tc_arm.count
    per_call = _span_overhead_per_call(5_000 if smoke else 50_000)
    overhead_frac = per_call * span_calls / max(wall_disarmed, 1e-9)
    return {
        "name": "disarmed_overhead",
        "n_train": train.num_rows, "outer_iterations": outer,
        "fresh_traces_disarmed_warm": fresh_disarmed,
        "fresh_traces_armed_warm": fresh_armed,
        "zero_fresh_traces_ok": fresh_disarmed == 0 and fresh_armed == 0,
        "disarmed_span_call_ns": round(per_call * 1e9, 1),
        "span_calls_per_fit": span_calls,
        "fit_s_disarmed": round(wall_disarmed, 3),
        "fit_s_armed": round(wall_armed, 3),  # reported, ungated (1-core
        # CPU noise; the armed delta is dominated by the same noise)
        "overhead_frac_estimate": round(overhead_frac, 6),
        "overhead_gate": 0.01,
        "overhead_ok": overhead_frac <= 0.01,
    }


def _cli_trace_entry(smoke: bool) -> dict:
    """The acceptance-criterion leg: cli.train --trace-out on a
    2-coordinate GAME fit emits valid Chrome-trace JSON whose span tree
    nests outer iterations -> coordinate visits -> inner solves, with an
    injected fault and its quarantine containment attached to the correct
    spans (checked through the JSONL run log's span-id chain)."""
    import tempfile

    from photon_ml_tpu.cli.train import main as train_main
    from photon_ml_tpu.data.game_data import save_game_dataset
    from photon_ml_tpu.telemetry import validate_chrome_trace

    n = 1600 if smoke else max(int(20_000 * _SCALE), 4000)
    outer = 2 if smoke else 3
    train, _ = _pipeline_dataset(n, 6, max(n // 20, 40), 4, seed=17)
    cfg = _pipeline_config(outer, 5, with_item=False, seed=17)
    # hit 2 = the FIRST perUser visit (sites fire fixed, perUser per
    # iteration in sequence order): the poisoned solve must be rolled
    # back, retried, and the whole episode must land on perUser's spans
    plan = json.dumps({"faults": [{"site": "solve.poison",
                                   "action": "poison", "hits": [2]}]})
    with tempfile.TemporaryDirectory() as tmp:
        data = os.path.join(tmp, "train.npz")
        save_game_dataset(train, data)
        cfg_path = os.path.join(tmp, "game.json")
        with open(cfg_path, "w") as f:
            f.write(cfg.to_json())
        out_dir = os.path.join(tmp, "out")
        trace_path = os.path.join(out_dir, "trace.json")
        run_log = os.path.join(out_dir, "run-log.jsonl")
        rc = train_main([
            "--train-data", data, "--task", "logistic_regression",
            "--config", cfg_path, "--output-dir", out_dir,
            "--mesh", "none", "--trace-out", trace_path,
            "--run-log", run_log, "--fault-plan", plan,
            "--checkpoint-dir", os.path.join(tmp, "ckpt")])
        with open(trace_path) as f:
            payload = json.load(f)
        problems = validate_chrome_trace(payload)
        tree = _trace_tree_checks(payload, outer, coords=2)
        records = [json.loads(line) for line in open(run_log)]
        spans = {r["span"]: r for r in records if r["kind"] == "span"}

        def visit_coordinate(record):
            """Walk the run-log parent chain to the enclosing
            coordinate_visit's coordinate attr."""
            sid = record["span"]
            while sid is not None and sid in spans:
                s = spans[sid]
                if s["name"] == "coordinate_visit":
                    return s["attrs"].get("coordinate")
                sid = s["parent"]
            return None

        faults_logged = [r for r in records
                         if r["kind"] == "event" and r["name"] == "fault"]
        quarantines = [r for r in records
                       if r["kind"] == "event" and r["name"] == "quarantine"]
        emitted = [r for r in records if r["name"].startswith("emitted.")]
        fault_coords = [visit_coordinate(r) for r in faults_logged]
        with open(os.path.join(out_dir, "training-summary.json")) as f:
            summary = json.load(f)
    containment = summary["solver_diagnostics"]["perUser"]["containment"]
    return {
        "name": "cli_trace",
        "n_train": train.num_rows, "outer_iterations": outer,
        "returncode": rc,
        "trace_problems": problems[:5],
        "trace_valid": not problems,
        "trace_events": len(payload["traceEvents"]),
        **tree,
        "fault_events": len(faults_logged),
        "quarantine_events": len(quarantines),
        "fault_attributed_coordinates": fault_coords,
        "fault_attributed_ok": fault_coords == ["perUser"],
        "quarantine_recovered": "retry_ok" in containment,
        "run_log_records": len(records),
        "summary_retraces": {
            c: d.get("retraces")
            for c, d in summary["solver_diagnostics"].items()},
        "ok": bool(rc == 0 and not problems and tree["nesting_ok"]
                   and fault_coords == ["perUser"]
                   and "retry_ok" in containment),
    }


def trace_bench(out_path="BENCH_trace.json", smoke=False, max_wall=None):
    """Telemetry gate (--trace): (1) disarmed instrumentation costs <= 1%
    of fit wall-clock and a warm fit stays at zero fresh XLA traces armed
    or disarmed; (2) cli.train --trace-out emits a valid, correctly
    NESTED Chrome trace with fault/quarantine events attached to the
    right spans.  Both legs are hard-gated; `value` is the measured
    disarmed overhead fraction."""
    t0 = time.perf_counter()
    entries = [_overhead_entry(smoke)]
    if max_wall is None or time.perf_counter() - t0 < max_wall:
        entries.append(_cli_trace_entry(smoke))
        truncated = False
    else:
        truncated = True
    overhead = entries[0]
    cli = entries[1] if len(entries) > 1 else None
    result = {
        "metric": "disarmed_telemetry_overhead_frac",
        "value": overhead["overhead_frac_estimate"],
        "unit": "fraction",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            "zero_fresh_traces_ok": overhead["zero_fresh_traces_ok"],
            "overhead_ok": overhead["overhead_ok"],
            "trace_ok": cli["ok"] if cli else None,
            "all_ok": bool(overhead["zero_fresh_traces_ok"]
                           and overhead["overhead_ok"]
                           and (cli is None or cli["ok"])),
            "truncated": truncated,
        },
    }
    _embed_telemetry(result)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# serving benchmark (--serve): online-inference latency trajectory
# --------------------------------------------------------------------------

def serve_bench(out_path="BENCH_serve.json"):
    """Synthetic request stream through the full serving pipeline
    (CompiledScorer + MicroBatcher + registry): concurrent clients fire
    mixed-size requests, and the result records throughput + latency
    percentiles + batch occupancy so future PRs have a serving latency
    trajectory to regress against.  Includes an under-load hot swap so the
    zero-downtime path is exercised (and timed) every run."""
    from concurrent.futures import ThreadPoolExecutor

    import jax.numpy as jnp

    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import model_for_task
    from photon_ml_tpu.serving import ScoringService, ServingConfig

    d_g, d_u, E = 32, 16, 20_000
    rng = np.random.default_rng(31)

    def make_model(scale):
        fe = FixedEffectModel(
            model_for_task("logistic_regression", Coefficients(
                jnp.asarray(scale * rng.normal(size=d_g), jnp.float32))),
            "global")
        re = RandomEffectModel(
            random_effect_type="userId", feature_shard="per_user",
            task_type="logistic_regression",
            coefficients=jnp.asarray(
                scale * rng.normal(size=(E, d_u)), jnp.float32),
            entity_ids=np.asarray([f"u{i}" for i in range(E)], dtype=object),
            projection=None, global_dim=d_u)
        return GameModel({"fixed": fe, "perUser": re}, "logistic_regression")

    n_requests = max(int(2000 * _SCALE), 200)
    threads = 16
    sizes = np.minimum(1 + rng.geometric(0.25, size=n_requests), 16)
    seen = rng.uniform(size=sizes.sum()) < 0.9  # 10% unseen -> FE fallback
    ent = np.where(seen, rng.integers(0, E, size=sizes.sum()),
                   rng.integers(E, 2 * E, size=sizes.sum()))
    bounds = np.concatenate([[0], np.cumsum(sizes)])
    requests = []
    for r in range(n_requests):
        lo, hi = bounds[r], bounds[r + 1]
        requests.append((
            {"global": rng.normal(size=(hi - lo, d_g)).astype(np.float32),
             "per_user": rng.normal(size=(hi - lo, d_u)).astype(np.float32)},
            {"userId": np.asarray([f"u{i}" for i in ent[lo:hi]],
                                  dtype=object)}))

    svc = ScoringService(model=make_model(1.0), config=ServingConfig(
        max_batch=256, min_bucket=8, max_wait_s=0.002, max_queue=4096))
    try:
        t0 = time.perf_counter()
        warm_compiles = svc.registry.scorer.bucket_compiles
        errors = []

        def one(req):
            try:
                svc.score(*req)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        # swap under load halfway through the stream (background build,
        # atomic cutover — in-flight batches finish on the old scorer)
        swap_s = [None]

        def swapper():
            from photon_ml_tpu.serving import CompiledScorer
            s0 = time.perf_counter()
            scorer = CompiledScorer(make_model(1.1), max_batch=256,
                                    min_bucket=8, version="v2")
            scorer.warmup()
            svc.registry.install(scorer, "v2")
            swap_s[0] = time.perf_counter() - s0

        with ThreadPoolExecutor(max_workers=threads) as pool:
            futs = [pool.submit(one, r) for r in requests[:n_requests // 2]]
            sw = pool.submit(swapper)
            futs += [pool.submit(one, r) for r in requests[n_requests // 2:]]
            for f in futs:
                f.result()
            sw.result()
        wall = time.perf_counter() - t0
        snap = svc.metrics_snapshot()
        entry = {
            "metric": "serving_rows_per_sec",
            "value": round(int(sizes.sum()) / wall, 1),
            "unit": "rows/sec",
            "detail": {
                "requests": n_requests, "rows": int(sizes.sum()),
                "threads": threads, "wall_s": round(wall, 3),
                "requests_per_sec": round(n_requests / wall, 1),
                "failed_requests": len(errors),
                "first_errors": errors[:3],
                "hot_swap_s": (None if swap_s[0] is None
                               else round(swap_s[0], 3)),
                "recompiles_after_warmup":
                    snap["bucket_compiles"] - 0,  # warmup precedes traffic
                "warm_bucket_programs": warm_compiles,
                "metrics": snap,
            },
        }
    finally:
        svc.close()
    _embed_telemetry(entry)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(entry, f, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(entry), flush=True)
    return entry


# --------------------------------------------------------------------------
# online learning benchmark (--online): per-entity delta swaps into the
# live scorer
# --------------------------------------------------------------------------

def _online_model(rng, d_g, d_u, E, scale=1.0):
    import jax.numpy as jnp

    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import model_for_task
    fe = FixedEffectModel(
        model_for_task("logistic_regression", Coefficients(
            jnp.asarray(scale * rng.normal(size=d_g)))), "global")
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type="logistic_regression",
        coefficients=jnp.asarray(scale * rng.normal(size=(E, d_u))),
        entity_ids=np.asarray([f"u{i}" for i in range(E)], dtype=object),
        projection=None, global_dim=d_u)
    return GameModel({"fixed": fe, "perUser": re}, "logistic_regression")


def _feedback_batch(rng, d_g, d_u, entities, rows):
    feats = {"global": rng.normal(size=(rows, d_g)),
             "per_user": rng.normal(size=(rows, d_u))}
    ids = {"userId": np.asarray(
        [entities[rng.integers(0, len(entities))] for _ in range(rows)],
        dtype=object)}
    labels = (rng.uniform(size=rows) < 0.5).astype(float)
    return feats, ids, labels


def _online_parity_entry(smoke: bool) -> dict:
    """Gate 1: online-updated entity coefficients match an OFFLINE refit of
    the same entities (training-side block build, f64) at <= 1e-6 rel, plus
    an independent scipy L-BFGS-B oracle spot-check of the anchored
    objective."""
    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.game.anchored import (anchored_objective_np,
                                             offline_anchored_refit)
    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.ops import losses as PL
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    from scipy.optimize import minimize

    rng = np.random.default_rng(41)
    d_g, d_u = 16, 8
    E = 500 if smoke else 5000
    touched = [f"u{i}" for i in rng.choice(E, size=24, replace=False)]
    anchor = 0.7
    model = _online_model(rng, d_g, d_u, E)
    svc = ScoringService(
        model=model, config=ServingConfig(max_batch=256, min_bucket=4),
        updates=OnlineUpdateConfig(micro_batch=8, anchor_weight=anchor,
                                   max_iterations=200, tolerance=1e-12),
        start_updater=False)
    try:
        scorer = svc.registry.scorer
        n = 24 * (4 if smoke else 8)
        feats = {"global": rng.normal(size=(n, d_g)),
                 "per_user": rng.normal(size=(n, d_u))}
        ids = {"userId": np.asarray([touched[i % len(touched)]
                                     for i in range(n)], dtype=object)}
        labels = (rng.uniform(size=n) < 0.5).astype(float)
        table = np.asarray(scorer.re_table("perUser"))
        prior = {u: table[scorer.entity_row("perUser", u)].copy()
                 for u in touched}
        margins = scorer.score(feats, ids).scores  # pre-update residuals
        svc.feedback(feats, ids, labels)
        flush = svc.updater.flush()
        table_new = np.asarray(scorer.re_table("perUser"))
        online = {u: table_new[scorer.entity_row("perUser", u)]
                  for u in touched}

        ds = build_game_dataset(
            labels, {"global": feats["global"], "per_user": feats["per_user"]},
            offsets=margins, entity_ids={"userId": ids["userId"]})
        offline = offline_anchored_refit(
            ds, "userId", "per_user", prior,
            PL.TASK_LOSSES["logistic_regression"],
            OptimizerConfig(max_iterations=200, tolerance=1e-12),
            anchor_weight=anchor)
        rels = []
        for u in touched:
            denom = max(float(np.max(np.abs(offline[u]))), 1e-12)
            rels.append(float(np.max(np.abs(online[u] - offline[u])) / denom))
        worst = max(rels)

        # independent oracle: scipy minimizes the anchored objective on the
        # raw feedback rows of 3 entities (no shared solver code at all)
        scipy_rels = []
        for u in touched[:3]:
            rows = [i for i in range(n) if ids["userId"][i] == u]
            f = lambda c: anchored_objective_np(
                feats["per_user"][rows], labels[rows], None, margins[rows],
                c, prior[u], "logistic_regression", anchor)
            res = minimize(f, prior[u], method="L-BFGS-B", tol=1e-14)
            denom = max(float(np.max(np.abs(res.x))), 1e-12)
            scipy_rels.append(
                float(np.max(np.abs(online[u] - res.x)) / denom))
        gate = 1e-6
        return {
            "name": "online_parity", "entities": len(touched),
            "feedback_rows": n, "deltas": flush["deltas"],
            "max_rel_gap_vs_offline_refit": worst,
            "scipy_oracle_rel_gaps": [round(r, 9) for r in scipy_rels],
            "parity_gate": gate,
            "parity_ok": bool(worst <= gate
                              and max(scipy_rels) <= 1e-4),
        }
    finally:
        svc.close()


def _online_latency_entry(smoke: bool) -> dict:
    """Gate 2: scoring p99 while a concurrent feedback stream drives
    sustained delta publishes stays <= 1.5x the no-update baseline; also
    the sustained update throughput (entities/sec) this run achieved."""
    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig

    rng = np.random.default_rng(43)
    d_g, d_u = 16, 8
    E = 1000 if smoke else 20_000
    n_requests = 200 if smoke else max(int(1500 * _SCALE), 300)
    threads = 8
    entities = [f"u{i}" for i in range(E)]
    # latency ring sized to ONE stream: a per-rep p99 read then covers
    # exactly the newest rep, so best-of-reps compares clean windows
    cfg = ServingConfig(max_batch=256, min_bucket=8, max_wait_s=0.002,
                        max_queue=4096, latency_window=n_requests)

    requests = []
    for _ in range(n_requests):
        k = int(rng.integers(1, 9))
        requests.append((
            {"global": rng.normal(size=(k, d_g)),
             "per_user": rng.normal(size=(k, d_u))},
            {"userId": np.asarray(
                [entities[rng.integers(0, E)] for _ in range(k)],
                dtype=object)}))

    def run_stream(svc):
        errors = []

        def one(req):
            try:
                svc.score(*req)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=threads) as pool:
            list(pool.map(one, requests))
        return time.perf_counter() - t0, errors

    def p99_of(svc):
        return svc.metrics_snapshot()["latency_ms"]["p99"]

    reps = 1 if smoke else 2

    # phase A: no-update baseline.  Each phase runs `reps` streams and
    # keeps its BEST p99 (the latency ring holds the newest window, so a
    # per-rep read isolates each stream): on a shared-core box a single
    # rep's p99 is scheduler roulette, and the gate should compare steady
    # states, not which rep caught a cron tick.
    svc_a = ScoringService(model=_online_model(rng, d_g, d_u, E), config=cfg)
    try:
        run_stream(svc_a)  # warm
        p99s_a, walls_a, err_a = [], [], []
        for _ in range(reps):
            wall, errs = run_stream(svc_a)
            walls_a.append(wall)
            err_a += errs
            p99s_a.append(p99_of(svc_a))
        wall_a = min(walls_a)
        snap_a = svc_a.metrics_snapshot()
    finally:
        svc_a.close()

    # phase B: identical scoring stream with the updater live and a
    # feedback pump publishing deltas the whole time
    # freshness-tuned solver: a 25-iteration/1e-7 anchored solve moves the
    # rows to within noise of the full solve (the anchor keeps steps small)
    # while keeping each device dispatch short enough that scoring batches
    # interleave — the single-device twin of the inexact-solve schedules
    svc_b = ScoringService(
        model=_online_model(rng, d_g, d_u, E), config=cfg,
        updates=OnlineUpdateConfig(micro_batch=16, interval_s=0.005,
                                   max_iterations=25, tolerance=1e-7,
                                   max_pending_rows=32768))
    try:
        # the background loop warms the update path's compiled shapes
        # before its first drain; measuring while those compiles hog the
        # core would charge one-time costs to steady-state p99
        deadline = time.time() + 120
        while not svc_b.updater.warmed and time.time() < deadline:
            time.sleep(0.05)
        run_stream(svc_b)  # warm scoring buckets
        f_rng = np.random.default_rng(47)
        feats, ids, labels = _feedback_batch(f_rng, d_g, d_u, entities, 64)
        svc_b.feedback(feats, ids, labels)
        svc_b.updater.flush()
        stop = _threading.Event()
        pumped = [0]

        def pump():
            # rate-limit to roughly the updater's drain capacity: a pile-up
            # would measure queue depth, not sustained feedback-to-publish
            while not stop.is_set():
                if svc_b.updater.buffer.pending_rows > 128:
                    time.sleep(0.002)
                    continue
                f, i, l = _feedback_batch(f_rng, d_g, d_u, entities, 32)
                try:
                    svc_b.feedback(f, i, l)
                    pumped[0] += 32
                except Exception:
                    time.sleep(0.005)  # backpressure: let the updater drain
                time.sleep(0.002)

        pumper = _threading.Thread(target=pump, daemon=True)
        pumper.start()
        t0 = time.perf_counter()
        p99s_b, walls_b, err_b = [], [], []
        for _ in range(reps):
            wall, errs = run_stream(svc_b)
            walls_b.append(wall)
            err_b += errs
            p99s_b.append(p99_of(svc_b))
        wall_b = min(walls_b)
        stop.set()
        pumper.join(timeout=5)
        svc_b.updater.flush()
        update_wall = time.perf_counter() - t0
        snap_b = svc_b.metrics_snapshot()
    finally:
        svc_b.close()

    p99_a = min(p99s_a)
    p99_b = min(p99s_b)
    entities_updated = snap_b["online"]["entities_updated"]
    ratio = p99_b / max(p99_a, 1e-9)
    return {
        "name": "online_latency",
        "requests": n_requests, "threads": threads, "reps": reps,
        "baseline": {"p99_ms": p99_a, "p99_ms_reps": p99s_a,
                     "p50_ms": snap_a["latency_ms"]["p50"],
                     "wall_s": round(wall_a, 3), "errors": len(err_a)},
        "under_updates": {
            "p99_ms": p99_b, "p99_ms_reps": p99s_b,
            "p50_ms": snap_b["latency_ms"]["p50"],
            "wall_s": round(wall_b, 3), "errors": len(err_b),
            "feedback_rows_pumped": pumped[0],
            "entities_updated": entities_updated,
            "deltas_published": snap_b["online"]["deltas_published"],
            "update_entities_per_sec": round(
                entities_updated / update_wall, 1),
            "feedback_to_publish_ms":
                snap_b["online"]["feedback_to_publish_ms"],
            "model_age_s": snap_b["model_age_s"],
        },
        "p99_ratio": round(ratio, 3),
        "latency_gate": 1.5,
        "latency_ok": bool(ratio <= 1.5 and not err_a and not err_b),
    }


def _online_traces_entry(smoke: bool) -> dict:
    """Gate 3: a WARM serve loop absorbing a stream of deltas while
    scoring runs traces NOTHING new — scorer buckets, the anchored batched
    solver, fold/gather/scatter programs all stay cached."""
    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig

    rng = np.random.default_rng(53)
    d_g, d_u, E = 16, 8, 400
    entities = [f"u{i}" for i in range(64)]
    svc = ScoringService(
        model=_online_model(rng, d_g, d_u, E),
        config=ServingConfig(max_batch=64, min_bucket=4),
        updates=OnlineUpdateConfig(micro_batch=8), start_updater=False)

    def one_round(seed):
        r = np.random.default_rng(seed)
        f, i, l = _feedback_batch(r, d_g, d_u, entities, 32)
        svc.feedback(f, i, l)
        svc.updater.flush()
        svc.score({"global": r.normal(size=(5, d_g)),
                   "per_user": r.normal(size=(5, d_u))},
                  {"userId": np.asarray(entities[:5], dtype=object)})

    try:
        # explicit warmup (what the background loop runs before its first
        # drain) + one real round for the device_put paths
        warmup_s = svc.updater.warmup()
        warm_rounds = 1
        for s in range(warm_rounds):
            one_round(s)
        steady_rounds = 3 if smoke else 12
        with _trace_counting() as counter:
            for s in range(warm_rounds, warm_rounds + steady_rounds):
                one_round(s)
        deltas = svc.registry.scorer.deltas_applied
        return {
            "name": "online_steady_state_traces",
            "updater_warmup_s": round(warmup_s, 3),
            "warm_rounds": warm_rounds, "steady_rounds": steady_rounds,
            "deltas_absorbed": deltas,
            "fresh_traces_steady_state": counter.count,
            "zero_traces_ok": bool(counter.count == 0
                                   and deltas >= steady_rounds),
        }
    finally:
        svc.close()


def _online_rollback_entry(smoke: bool, tmp_dir: str) -> dict:
    """Gate 4: delta-aware rollback round-trips bit-exact after N delta
    swaps, and a persisted delta survives a durable save/load round trip
    byte-identically."""
    from photon_ml_tpu.models.io import load_model_delta, save_model_delta
    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig

    rng = np.random.default_rng(59)
    d_g, d_u, E = 16, 8, 400
    entities = [f"u{i}" for i in range(48)]
    svc = ScoringService(
        model=_online_model(rng, d_g, d_u, E),
        config=ServingConfig(max_batch=64, min_bucket=4),
        updates=OnlineUpdateConfig(micro_batch=8), start_updater=False)
    try:
        table0 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
        rounds = 3 if smoke else 6
        for s in range(rounds):
            r = np.random.default_rng(100 + s)
            f, i, l = _feedback_batch(r, d_g, d_u, entities, 32)
            svc.feedback(f, i, l)
            svc.updater.flush()
        n_deltas = svc.registry.pending_deltas()
        # durability: persist the newest delta, reload, byte-compare
        delta = svc.registry.applied_deltas()[-1]
        ddir = os.path.join(tmp_dir, "delta")
        save_model_delta(delta, ddir)
        loaded = load_model_delta(ddir)
        cd, lcd = delta.coordinates["perUser"], loaded.coordinates["perUser"]
        durable_ok = bool(
            loaded.base_version == delta.base_version
            and loaded.seq == delta.seq
            and np.array_equal(cd.rows, lcd.rows)
            and np.array_equal(cd.values, lcd.values)
            and np.array_equal(cd.prior, lcd.prior))
        changed = int(np.sum(np.any(
            np.asarray(svc.registry.scorer.re_table("perUser")) != table0,
            axis=1)))
        svc.rollback()
        table_rb = np.asarray(svc.registry.scorer.re_table("perUser"))
        return {
            "name": "online_rollback",
            "deltas_applied": n_deltas, "rows_changed": changed,
            "delta_durable_roundtrip_ok": durable_ok,
            "rollback_bit_exact": bool(np.array_equal(table_rb, table0)),
            "rollback_ok": bool(np.array_equal(table_rb, table0)
                                and n_deltas >= rounds and changed > 0
                                and durable_ok),
        }
    finally:
        svc.close()


def online_bench(out_path="BENCH_online.json", smoke=False, max_wall=None):
    """Online-learning gate (--online): (1) online-updated entity rows
    match an offline refit of the same entities in f64 (<= 1e-6 rel, plus
    a scipy oracle); (2) scoring p99 under sustained concurrent update
    load <= 1.5x the no-update baseline; (3) zero fresh XLA traces across
    steady-state delta application; (4) delta-aware rollback round-trips
    bit-exact and deltas persist durably.  `value` is the sustained
    update throughput (entities/sec) concurrent with scoring traffic."""
    import tempfile

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 parity gates
    t0 = time.perf_counter()
    entries = []
    truncated = []
    legs = [
        ("online_parity", lambda: _online_parity_entry(smoke)),
        ("online_traces", lambda: _online_traces_entry(smoke)),
        ("online_rollback", None),  # needs tmp dir, handled below
        ("online_latency", lambda: _online_latency_entry(smoke)),
    ]
    with tempfile.TemporaryDirectory() as tmp:
        for name, fn in legs:
            if max_wall is not None and time.perf_counter() - t0 > max_wall:
                truncated.append(name)
                continue
            if name == "online_rollback":
                entries.append(_online_rollback_entry(smoke, tmp))
            else:
                entries.append(fn())
    by_name = {e["name"]: e for e in entries}
    parity = by_name.get("online_parity", {})
    latency = by_name.get("online_latency", {})
    traces = by_name.get("online_steady_state_traces", {})
    rollback = by_name.get("online_rollback", {})
    gates = {
        "parity_ok": parity.get("parity_ok"),
        "latency_ok": latency.get("latency_ok"),
        "zero_traces_ok": traces.get("zero_traces_ok"),
        "rollback_ok": rollback.get("rollback_ok"),
    }
    # smoke runs under the tier-1 suite on shared CPUs: latency is a smoke
    # signal there, a HARD gate on the full (committed) bench run
    hard = ["parity_ok", "zero_traces_ok", "rollback_ok"]
    if not smoke:
        hard.append("latency_ok")
    result = {
        "metric": "online_update_entities_per_sec",
        "value": (latency.get("under_updates", {})
                  .get("update_entities_per_sec", 0.0)),
        "unit": "entities/sec",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            **gates,
            "all_ok": all(bool(gates[g]) for g in hard),
            "hard_gates": hard,
            "truncated": truncated or False,
            "suite_wall_s": round(time.perf_counter() - t0, 1),
        },
    }
    _embed_telemetry(result)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# --health: live model-health observability (photon_ml_tpu/health/)
# --------------------------------------------------------------------------

def _health_config(smoke: bool, **kw):
    from photon_ml_tpu.health import HealthConfig
    kw.setdefault("window_labels", 128 if smoke else 256)
    kw.setdefault("window_scores", 512 if smoke else 2048)
    kw.setdefault("baseline_scores", 512 if smoke else 2048)
    kw.setdefault("sustain_windows", 2)
    kw.setdefault("recovery_windows", 2)
    kw.setdefault("calibration_p_min", 1e-4)
    kw.setdefault("psi_max", 0.25)
    kw.setdefault("ks_max", 0.2)
    return HealthConfig(**kw)


def _health_service(rng, *, smoke, health, updates=True, E=None, **hc_kw):
    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    E = E if E is not None else (400 if smoke else 2000)
    svc = ScoringService(
        model=_online_model(rng, 16, 8, E),
        config=ServingConfig(max_batch=256, min_bucket=8),
        updates=OnlineUpdateConfig(micro_batch=8) if updates else None,
        start_updater=False,
        health=_health_config(smoke, **hc_kw) if health else None)
    return svc, [f"u{i}" for i in range(E)]


def _calibrated_batch(svc, rng, entities, n, flip=False, scale=1.0):
    """Feedback whose labels are drawn from the LIVE model's own
    probabilities — calibrated by construction; `flip` inverts them
    (the label-flip drift injection), `scale` shifts the covariates
    (the covariate-shift injection)."""
    d_g, d_u = 16, 8
    feats = {"global": scale * rng.normal(size=(n, d_g)),
             "per_user": scale * rng.normal(size=(n, d_u))}
    ids = {"userId": np.asarray(
        [entities[rng.integers(0, len(entities))] for _ in range(n)],
        dtype=object)}
    z = svc.registry.scorer.score(feats, ids).scores
    p = 0.5 * (1.0 + np.tanh(0.5 * z))
    y = (rng.uniform(size=n) < p).astype(float)
    if flip:
        y = 1.0 - y
    return feats, ids, y


def _health_stationary_entry(smoke: bool) -> dict:
    """Gate: ZERO gate trips across a stationary leg — calibrated labels,
    unshifted covariates, live delta publishes the whole time (the
    false-alarm bound of the whole service path, not just the
    detectors)."""
    rng = np.random.default_rng(71)
    svc, entities = _health_service(rng, smoke=smoke, health=True)
    cfg = svc.health.config
    label_windows = 4 if smoke else 6
    score_windows = 3 if smoke else 4
    try:
        # drift baseline + score windows (scoring traffic only)
        rows = cfg.baseline_scores + score_windows * cfg.window_scores
        for lo in range(0, rows, 256):
            f, i, _ = _calibrated_batch(svc, rng, entities,
                                        min(256, rows - lo))
            svc.score(f, i)
        for _ in range(label_windows):
            f, i, y = _calibrated_batch(svc, rng, entities,
                                        cfg.window_labels)
            svc.feedback(f, i, y)
            svc.updater.flush()
        snap = svc.metrics_snapshot()
        v = svc.health.verdict()
        gate_values = {name: g["value"] for name, g in v["gates"].items()}
        return {
            "name": "health_stationary",
            "label_windows": snap["health"]["label_windows"],
            "score_windows": snap["health"]["score_windows"],
            "deltas_published": snap["online"]["deltas_published"],
            "gate_trips": snap["health"]["gate_trips"],
            "breaches": snap["health"]["breaches"],
            "last_gate_values": gate_values,
            "status": v["status"],
            "stationary_ok": bool(
                snap["health"]["gate_trips"] == 0
                and v["status"] == "ok"
                and snap["health"]["label_windows"] >= label_windows
                and snap["health"]["score_windows"] >= score_windows
                and snap["online"]["deltas_published"] > 0),
        }
    finally:
        svc.close()


def _health_label_flip_entry(smoke: bool) -> dict:
    """Gate: injected label-flip drift trips the calibration gate within
    <= 3 evaluation windows, pauses the updater, flips /healthz to
    degraded — and the paused updater stops publishing while intake keeps
    buffering."""
    rng = np.random.default_rng(73)
    svc, entities = _health_service(rng, smoke=smoke, health=True,
                                    rollback_on=("calibration",))
    cfg = svc.health.config
    try:
        # the pre-delta state a health rollback must restore bit-exactly
        table0 = np.asarray(
            svc.registry.scorer.re_table("perUser")).copy()
        # clean warmup: 2 calibrated windows + deltas pending for rollback
        for _ in range(2):
            f, i, y = _calibrated_batch(svc, rng, entities,
                                        cfg.window_labels)
            svc.feedback(f, i, y)
            svc.updater.flush()
        deltas_before = svc.registry.pending_deltas()
        assert svc.healthz()["status"] == "ok"
        windows_before = svc.health.verdict()["windows_evaluated"]
        windows_to_trip = None
        for w in range(1, 7):
            f, i, y = _calibrated_batch(svc, rng, entities,
                                        cfg.window_labels, flip=True)
            svc.feedback(f, i, y)
            if svc.healthz()["status"] == "degraded":
                windows_to_trip = (svc.health.verdict()["windows_evaluated"]
                                   - windows_before)
                break
        hz = svc.healthz()
        published_paused = svc.updater.flush()["deltas"]
        rolled_back = bool(
            svc.registry.pending_deltas() == 0 and deltas_before > 0
            and np.array_equal(
                np.asarray(svc.registry.scorer.re_table("perUser")),
                table0))
        return {
            "name": "health_label_flip",
            "detection_gate_windows": 3,
            "windows_to_trip": windows_to_trip,
            "status": hz["status"],
            "updater_paused": bool(svc.updater.paused),
            "deltas_published_while_paused": published_paused,
            "deltas_rolled_back": deltas_before,
            "rollback_restored_pre_delta_rows": rolled_back,
            "calibration_p_value":
                hz["health"]["gates"]["calibration"]["value"],
            "label_flip_ok": bool(
                windows_to_trip is not None and windows_to_trip <= 3
                and hz["status"] == "degraded" and svc.updater.paused
                and published_paused == 0 and rolled_back),
        }
    finally:
        svc.close()


def _health_covariate_entry(smoke: bool) -> dict:
    """Gate: injected covariate shift moves the score distribution and
    trips a drift gate (PSI/KS vs the install baseline) within <= 3
    evaluation windows — labels never needed."""
    rng = np.random.default_rng(79)
    svc, entities = _health_service(rng, smoke=smoke, health=True,
                                    updates=False)
    cfg = svc.health.config
    try:
        rows = cfg.baseline_scores + cfg.window_scores   # baseline + clean
        for lo in range(0, rows, 256):
            f, i, _ = _calibrated_batch(svc, rng, entities,
                                        min(256, rows - lo))
            svc.score(f, i)
        assert svc.health.verdict()["baseline_ready"]
        windows_before = svc.health.verdict()["windows_evaluated"]
        windows_to_trip = None
        for w in range(1, 7):
            for lo in range(0, cfg.window_scores, 256):
                f, i, _ = _calibrated_batch(
                    svc, rng, entities,
                    min(256, cfg.window_scores - lo), scale=2.5)
                svc.score(f, i)
            if svc.healthz()["status"] == "degraded":
                windows_to_trip = (svc.health.verdict()["windows_evaluated"]
                                   - windows_before)
                break
        v = svc.health.verdict()
        return {
            "name": "health_covariate_shift",
            "detection_gate_windows": 3,
            "windows_to_trip": windows_to_trip,
            "psi": v["gates"]["drift_psi"]["value"],
            "ks": v["gates"]["drift_ks"]["value"],
            "tripped_gates": [n for n, g in v["gates"].items()
                              if g["tripped"]],
            "covariate_ok": bool(windows_to_trip is not None
                                 and windows_to_trip <= 3
                                 and v["status"] == "degraded"),
        }
    finally:
        svc.close()


def _health_latency_entry(smoke: bool) -> dict:
    """Gate: scoring p99 with health ARMED <= 1.1x disarmed.  Same
    best-of-reps methodology as the online-latency leg: the armed run
    pays one histogram add per batch plus the window evaluations that
    close DURING the stream."""
    from concurrent.futures import ThreadPoolExecutor

    from photon_ml_tpu.serving import ScoringService, ServingConfig

    rng = np.random.default_rng(83)
    d_g, d_u = 16, 8
    E = 1000 if smoke else 20_000
    n_requests = 200 if smoke else max(int(1500 * _SCALE), 300)
    threads = 8
    entities = [f"u{i}" for i in range(E)]
    cfg = ServingConfig(max_batch=256, min_bucket=8, max_wait_s=0.002,
                        max_queue=4096, latency_window=n_requests)
    requests = []
    for _ in range(n_requests):
        k = int(rng.integers(1, 9))
        requests.append((
            {"global": rng.normal(size=(k, d_g)),
             "per_user": rng.normal(size=(k, d_u))},
            {"userId": np.asarray(
                [entities[rng.integers(0, E)] for _ in range(k)],
                dtype=object)}))

    def run_stream(svc):
        errors = []

        def one(req):
            try:
                svc.score(*req)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=threads) as pool:
            list(pool.map(one, requests))
        return time.perf_counter() - t0, errors

    reps = 1 if smoke else 3
    results = {}
    for mode, health in (("disarmed", None),
                         ("armed", _health_config(
                             smoke, window_scores=256,
                             baseline_scores=256))):
        svc = ScoringService(model=_online_model(rng, d_g, d_u, E),
                             config=cfg, health=health)
        try:
            run_stream(svc)  # warm buckets (and the drift baseline)
            p99s, walls, errs = [], [], []
            for _ in range(reps):
                wall, e = run_stream(svc)
                walls.append(wall)
                errs += e
                p99s.append(svc.metrics_snapshot()["latency_ms"]["p99"])
            results[mode] = {
                "p99_ms": min(p99s), "p99_ms_reps": p99s,
                "wall_s": round(min(walls), 3), "errors": len(errs)}
            if health is not None:
                snap = svc.metrics_snapshot()["health"]
                results[mode]["score_windows"] = snap["score_windows"]
                results[mode]["gate_trips"] = snap["gate_trips"]
        finally:
            svc.close()
    ratio = results["armed"]["p99_ms"] / max(results["disarmed"]["p99_ms"],
                                             1e-9)
    return {
        "name": "health_latency",
        "requests": n_requests, "threads": threads, "reps": reps,
        "disarmed": results["disarmed"], "armed": results["armed"],
        "p99_ratio": round(ratio, 3),
        "latency_gate": 1.1,
        "latency_ok": bool(ratio <= 1.1
                           and not results["disarmed"]["errors"]
                           and not results["armed"]["errors"]
                           and results["armed"]["score_windows"] > 0),
    }


def _health_traces_entry(smoke: bool) -> dict:
    """Gate: zero fresh XLA traces steady-state with health ARMED and
    DISARMED — window closes and gate evaluations included in the
    counted region (all health math is host numpy/scipy)."""
    rng = np.random.default_rng(89)
    out = {"name": "health_steady_state_traces"}
    for mode, health in (("disarmed", False), ("armed", True)):
        svc, entities = _health_service(
            rng, smoke=smoke, health=health, E=400,
            **({"window_labels": 32, "window_scores": 64,
                "baseline_scores": 64, "sustain_windows": 1000}
               if health else {}))
        try:
            svc.updater.warmup()

            def one_round(seed):
                r = np.random.default_rng(seed)
                f, i, y = _calibrated_batch(svc, r, entities[:64], 32)
                svc.feedback(f, i, y)
                svc.updater.flush()
                f2, i2, _ = _calibrated_batch(svc, r, entities, 64)
                svc.score(f2, i2)

            for s in range(2):
                one_round(s)
            steady = 3 if smoke else 8
            with _trace_counting() as counter:
                for s in range(2, 2 + steady):
                    one_round(s)
            snap = svc.metrics_snapshot()
            out[mode] = {
                "steady_rounds": steady,
                "fresh_traces": counter.count,
                "deltas_absorbed": svc.registry.scorer.deltas_applied,
                "label_windows": snap["health"]["label_windows"],
                "score_windows": snap["health"]["score_windows"],
            }
        finally:
            svc.close()
    out["zero_traces_ok"] = bool(
        out["armed"]["fresh_traces"] == 0
        and out["disarmed"]["fresh_traces"] == 0
        and out["armed"]["label_windows"] >= 3
        and out["armed"]["score_windows"] >= 1)
    return out


def health_bench(out_path="BENCH_health.json", smoke=False, max_wall=None):
    """Model-health gate (--health): (1) injected label-flip drift
    detected (calibration gate tripped, updater paused, delta rollback)
    within <= 3 evaluation windows; (2) injected covariate-shift drift
    detected within <= 3 windows; (3) ZERO gate trips across the
    stationary leg; (4) scoring p99 with health armed <= 1.1x disarmed;
    (5) zero fresh XLA traces steady-state armed and disarmed.  `value`
    is the worst detection latency in windows."""
    import jax
    jax.config.update("jax_enable_x64", True)
    t0 = time.perf_counter()
    entries = []
    truncated = []
    legs = [
        ("health_stationary", _health_stationary_entry),
        ("health_label_flip", _health_label_flip_entry),
        ("health_covariate_shift", _health_covariate_entry),
        ("health_traces", _health_traces_entry),
        ("health_latency", _health_latency_entry),
    ]
    for name, fn in legs:
        if max_wall is not None and time.perf_counter() - t0 > max_wall:
            truncated.append(name)
            continue
        entries.append(fn(smoke))
    by_name = {e["name"]: e for e in entries}
    stationary = by_name.get("health_stationary", {})
    flip = by_name.get("health_label_flip", {})
    covariate = by_name.get("health_covariate_shift", {})
    traces = by_name.get("health_steady_state_traces", {})
    latency = by_name.get("health_latency", {})
    gates = {
        "stationary_ok": stationary.get("stationary_ok"),
        "label_flip_ok": flip.get("label_flip_ok"),
        "covariate_ok": covariate.get("covariate_ok"),
        "zero_traces_ok": traces.get("zero_traces_ok"),
        "latency_ok": latency.get("latency_ok"),
    }
    # latency is a smoke SIGNAL under the tier-1 suite (shared cores), a
    # HARD gate on the committed full run — same policy as --online
    hard = ["stationary_ok", "label_flip_ok", "covariate_ok",
            "zero_traces_ok"]
    if not smoke:
        hard.append("latency_ok")
    detections = [w for w in (flip.get("windows_to_trip"),
                              covariate.get("windows_to_trip"))
                  if w is not None]
    result = {
        "metric": "health_worst_detection_latency_windows",
        "value": max(detections) if detections else None,
        "unit": "evaluation windows",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            **gates,
            "all_ok": all(bool(gates[g]) for g in hard),
            "hard_gates": hard,
            "truncated": truncated or False,
            "suite_wall_s": round(time.perf_counter() - t0, 1),
        },
    }
    _embed_telemetry(result)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# --refit: continuous training loop (photon_ml_tpu/refit/)
# --------------------------------------------------------------------------

def _refit_service(rng, tmp, *, smoke, health=False, E=None,
                   latency_window=None, **hc_kw):
    """Serving stack with the durable feedback lane armed — every
    admitted feedback batch lands in tmp/fb before intake returns."""
    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    E = E if E is not None else (200 if smoke else 1000)
    cfg_kw = {"max_batch": 256, "min_bucket": 8}
    if latency_window is not None:
        cfg_kw["latency_window"] = latency_window
    svc = ScoringService(
        model=_online_model(rng, 16, 8, E),
        config=ServingConfig(**cfg_kw),
        updates=OnlineUpdateConfig(micro_batch=8),
        start_updater=False,
        health=_health_config(smoke, **hc_kw) if health else None,
        feedback_log_dir=os.path.join(tmp, "fb"))
    return svc, [f"u{i}" for i in range(E)]


def _refit_driver(svc, tmp, *, smoke, **cfg_kw):
    """Compactor (registered on the lane for bounded retention) + warm
    refit driver over the service's own registry."""
    from photon_ml_tpu.refit import (CompactorConfig, LogCompactor,
                                     RefitConfig, RefitDriver)
    comp = LogCompactor(svc.feedback_log, os.path.join(tmp, "chunks"),
                        CompactorConfig(chunk_rows=128 if smoke else 512))
    svc.feedback_log.register_consumer("refit-compactor",
                                       comp.checkpoint_seq)
    cfg_kw.setdefault("outer_iterations", 1 if smoke else 2)
    cfg_kw.setdefault("fe_iterations", 20 if smoke else 50)
    cfg_kw.setdefault("re_iterations", 30 if smoke else 80)
    driver = RefitDriver(svc.registry, comp, os.path.join(tmp, "models"),
                         RefitConfig(**cfg_kw), metrics=svc.metrics)
    return driver, comp


def _refit_parity_entry(smoke: bool, tmp: str) -> dict:
    """Gate: a refit FROM THE LOG is the same fit as one from the
    identical rows in memory — f64 objective histories and final
    coefficients agree to <= 1e-6 (the log -> chunk -> dataset path adds
    nothing and loses nothing; array transport is raw-byte exact)."""
    rng = np.random.default_rng(211)
    tmp = os.path.join(tmp, "parity")
    svc, entities = _refit_service(rng, tmp, smoke=smoke)
    try:
        n_batches, rows = (5, 96) if smoke else (10, 256)
        batches = []
        for _ in range(n_batches):
            f, i, y = _calibrated_batch(svc, rng, entities, rows,
                                        flip=True)
            svc.feedback(f, i, y)
            batches.append((f, i, y))
        driver, comp = _refit_driver(svc, tmp, smoke=smoke)
        comp.compact()
        fit_log = driver.fit_candidate(driver.gather_rows())
        n = n_batches * rows
        rows_mem = {
            "features": {s: np.concatenate([b[0][s] for b in batches])
                         for s in batches[0][0]},
            "ids": {"userId": np.concatenate(
                [b[1]["userId"] for b in batches])},
            "labels": np.concatenate([b[2] for b in batches]),
            "weights": np.ones(n), "offsets": np.zeros(n),
            "wall": np.zeros(n)}
        fit_mem = driver.fit_candidate(rows_mem)
        hist_log = np.asarray(fit_log.objective_history, np.float64)
        hist_mem = np.asarray(fit_mem.objective_history, np.float64)
        same_len = hist_log.shape == hist_mem.shape
        hist_diff = (float(np.max(np.abs(hist_log - hist_mem)))
                     if same_len else float("inf"))
        fe_diff = float(np.max(np.abs(
            np.asarray(fit_log.model.coordinates["fixed"]
                       .glm.coefficients.means, np.float64)
            - np.asarray(fit_mem.model.coordinates["fixed"]
                         .glm.coefficients.means, np.float64))))
        re_diff = float(np.max(np.abs(
            np.asarray(fit_log.model.coordinates["perUser"].coefficients,
                       np.float64)
            - np.asarray(fit_mem.model.coordinates["perUser"].coefficients,
                         np.float64))))
        manifest = comp.manifest()
        return {
            "name": "refit_parity",
            "log_rows": n, "sealed_rows": int(manifest["sealed_rows"]),
            "sealed_chunks": len(manifest["chunks"]),
            "history_len": [int(hist_log.size), int(hist_mem.size)],
            "history_max_abs_diff": hist_diff,
            "fe_max_abs_diff": fe_diff, "re_max_abs_diff": re_diff,
            "parity_gate": 1e-6,
            "parity_ok": bool(same_len and hist_diff <= 1e-6
                              and fe_diff <= 1e-6 and re_diff <= 1e-6),
        }
    finally:
        svc.close()


def _refit_loop_entry(smoke: bool, tmp: str) -> dict:
    """Gate: the closed loop end to end — injected label-flip drift trips
    a health gate (updater pauses), the on-trip trigger fires a cycle
    (compact -> warm refit -> tail validation -> swap), the swap resets
    every gate and resumes the updater, and a post-swap stationary window
    records ZERO fresh trips (the refit actually fixed the model)."""
    from photon_ml_tpu.refit import RefitTrigger, TriggerConfig
    rng = np.random.default_rng(223)
    tmp = os.path.join(tmp, "loop")
    svc, entities = _refit_service(
        rng, tmp, smoke=smoke, health=True,
        window_labels=64 if smoke else 128,
        window_scores=256, baseline_scores=256)
    try:
        cfg = svc.health.config
        for lo in range(0, cfg.baseline_scores + cfg.window_scores, 256):
            f, i, _ = _calibrated_batch(svc, rng, entities, 256)
            svc.score(f, i)
        for _ in range(2):
            f, i, y = _calibrated_batch(svc, rng, entities,
                                        cfg.window_labels)
            svc.feedback(f, i, y)
            svc.updater.flush()
        incumbent_version = svc.registry.version
        assert svc.healthz()["status"] == "ok"
        windows_to_trip = None
        for w in range(1, 8):
            f, i, y = _calibrated_batch(svc, rng, entities,
                                        cfg.window_labels, flip=True)
            svc.feedback(f, i, y)
            if svc.healthz()["status"] == "degraded":
                windows_to_trip = w
                break
        tripped = windows_to_trip is not None
        paused = bool(svc.updater.paused)
        driver, _comp = _refit_driver(svc, tmp, smoke=smoke)
        trigger = RefitTrigger(driver, health=svc.health,
                               config=TriggerConfig(mode="on_trip",
                                                    trip_polls=2,
                                                    cooloff_s=0.0))
        t_cycle = time.perf_counter()
        result = None
        polls = 0
        while result is None and polls < 4:
            polls += 1
            result = trigger.poll()
        cycle_wall_s = time.perf_counter() - t_cycle
        swapped = bool(result is not None and result.swapped)
        post = svc.health.verdict()
        gates_reset = bool(
            post["status"] == "ok"
            and not post["updates_paused_by_health"]
            and not any(g["tripped"] for g in post["gates"].values()))
        resumed = not svc.updater.paused
        # post-swap stationary window: fresh drift baseline + calibrated
        # traffic against the NEW model — zero trips means the candidate
        # is calibrated to the drifted world it was trained on
        trips_before = svc.metrics_snapshot()["health"]["gate_trips"]
        for lo in range(0, cfg.baseline_scores + cfg.window_scores, 256):
            f, i, _ = _calibrated_batch(svc, rng, entities, 256)
            svc.score(f, i)
        for _ in range(2):
            f, i, y = _calibrated_batch(svc, rng, entities,
                                        cfg.window_labels)
            svc.feedback(f, i, y)
            svc.updater.flush()
        post_trips = (svc.metrics_snapshot()["health"]["gate_trips"]
                      - trips_before)
        refit_snap = svc.metrics_snapshot()["refit"]
        return {
            "name": "refit_loop",
            "windows_to_trip": windows_to_trip,
            "updater_paused_on_trip": paused,
            "trigger_polls": polls,
            "swapped": swapped,
            "incumbent_version": incumbent_version,
            "candidate_version": None if result is None else result.version,
            "candidate": None if result is None else result.candidate,
            "incumbent": None if result is None else result.incumbent,
            "cycle_wall_s": round(cycle_wall_s, 3),
            "gates_reset": gates_reset,
            "updater_resumed": resumed,
            "post_swap_trips": int(post_trips),
            "post_swap_status": svc.healthz()["status"],
            "refit_metrics": refit_snap,
            "loop_ok": bool(tripped and paused and swapped and gates_reset
                            and resumed and post_trips == 0
                            and refit_snap["swaps"] >= 1),
        }
    finally:
        svc.close()


def _refit_latency_entry(smoke: bool, tmp: str) -> dict:
    """Gate: scoring p99 while a refit runs <= 1.2x the no-refit
    baseline (multi-core hosts; on one core the ratio is measured and
    reported ungated — the fleet_scaling policy — because the child and
    the scoring threads timeshare the only core no matter how nice the
    child is).  The refit runs where a latency-sensitive fleet runs it:
    OUT of the serving process, as the cli.refit batch job at nice 19.
    (In-process, scoring and training share one XLA intra-op threadpool,
    so the fit's large kernels head-of-line-block every scoring request
    — measured at >20x p99 here; the in-process trigger trades that for
    orchestration simplicity and the loop leg exercises it.  A separate
    low-priority process is the standard posture: the OS preempts the
    batch job whenever a request needs a core.)  Median-of-reps both
    sides (one quiet or one noisy rep must not decide the verdict on a
    shared-core host); the child keeps refit cycles in flight
    (--interval) across every measured stream."""
    import signal
    from concurrent.futures import ThreadPoolExecutor

    from photon_ml_tpu.models.io import save_game_model

    rng = np.random.default_rng(227)
    tmp = os.path.join(tmp, "lat")
    d_g, d_u = 16, 8
    n_requests = 150 if smoke else max(int(1000 * _SCALE), 800)
    threads = 8
    svc, entities = _refit_service(rng, tmp, smoke=smoke,
                                   E=400 if smoke else 2000,
                                   latency_window=n_requests)
    E = len(entities)
    requests = []
    for _ in range(n_requests):
        k = int(rng.integers(1, 9))
        requests.append((
            {"global": rng.normal(size=(k, d_g)),
             "per_user": rng.normal(size=(k, d_u))},
            {"userId": np.asarray(
                [entities[rng.integers(0, E)] for _ in range(k)],
                dtype=object)}))

    def run_stream():
        errors = []

        def one(req):
            try:
                svc.score(*req)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        with ThreadPoolExecutor(max_workers=threads) as pool:
            list(pool.map(one, requests))
        return svc.metrics_snapshot()["latency_ms"]["p99"], errors

    proc = None
    try:
        for _ in range(4 if smoke else 8):
            f, i, y = _calibrated_batch(svc, rng, entities,
                                        128 if smoke else 512, flip=True)
            svc.feedback(f, i, y)
        incumbent_dir = os.path.join(tmp, "incumbent")
        model_root = os.path.join(tmp, "models")
        save_game_model(svc.registry.scorer.model, incumbent_dir)
        run_stream()                                   # warm buckets
        reps = 2 if smoke else 3
        base_p99s, base_errs = [], []
        for _ in range(reps):
            p99, e = run_stream()
            base_p99s.append(p99)
            base_errs += e
        here = os.path.dirname(os.path.abspath(__file__))
        out_log = os.path.join(tmp, "refit-cli.log")
        cmd = ["nice", "-n", "19",
               sys.executable, "-m", "photon_ml_tpu.cli.refit",
               "--model-dir", incumbent_dir,
               "--feedback-log", os.path.join(tmp, "fb"),
               "--chunks", os.path.join(tmp, "chunks"),
               "--model-root", model_root,
               "--chunk-rows", "128" if smoke else "512",
               "--outer-iterations", "1" if smoke else "2",
               "--fe-iterations", "20" if smoke else "50",
               "--re-iterations", "30" if smoke else "80",
               "--interval", "0.2", "--poll", "0.05"]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        with open(out_log, "w") as log_f:
            proc = subprocess.Popen(cmd, env=env, cwd=here, stdout=log_f,
                                    stderr=subprocess.STDOUT)
        # hold until the child's FIRST cycle lands a candidate (imports,
        # compaction, and the training path's XLA compiles all happen
        # there) — the measured streams then overlap warm steady-state
        # cycles, which --interval keeps continuously in flight
        deadline = time.perf_counter() + 300
        while time.perf_counter() < deadline and proc.poll() is None:
            if os.path.isdir(model_root) and os.listdir(model_root):
                break
            time.sleep(0.2)
        first_cycle = os.path.isdir(model_root) and bool(
            os.listdir(model_root))
        during_p99s, during_errs = [], []
        overlapped = 0
        for _ in range(reps):
            alive_before = proc.poll() is None
            p99, e = run_stream()
            during_p99s.append(p99)
            during_errs += e
            overlapped += int(alive_before and proc.poll() is None)
        proc.send_signal(signal.SIGINT)
        try:
            child_rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            child_rc = proc.wait()
        with open(out_log) as log_f:
            cycles = sum(1 for line in log_f if '"swapped"' in line)
        # a cycle that was still publishing at SIGINT shows up as its
        # swap's version directory rather than a printed result line
        swap_dirs = (len(os.listdir(model_root))
                     if os.path.isdir(model_root) else 0)
        # median-of-reps, not min: one quiet (or one noisy) rep must not
        # decide the verdict on a shared-core host
        base_p99 = float(np.median(base_p99s))
        during_p99 = float(np.median(during_p99s))
        ratio = during_p99 / max(base_p99, 1e-9)
        cores = os.cpu_count() or 1
        latency_gated = cores >= 2
        out = {
            "name": "refit_latency",
            "requests": n_requests, "threads": threads, "reps": reps,
            "baseline_p99_ms": base_p99,
            "baseline_p99_ms_reps": base_p99s,
            "during_p99_ms": during_p99,
            "during_p99_ms_reps": during_p99s,
            "refit_cycles": cycles,
            "refit_swap_dirs": swap_dirs,
            "first_cycle_before_measurement": first_cycle,
            "child_rc": child_rc,
            "overlapped_reps": overlapped,
            "host_cores": cores,
            "p99_ratio": round(ratio, 3),
            "latency_gate": 1.2,
            "latency_gated": latency_gated,
        }
        if not latency_gated:
            out["latency_gate_waived"] = (
                f"single-core host (os.cpu_count()={cores}): the refit "
                "child and the scoring threads timeshare ONE core, so "
                "even at nice 19 the child's scheduler slices inflate "
                "scoring tails — the ratio is measured and reported "
                "ungated; it arms as a hard gate on any multi-core "
                "host, where the preempted child costs serving nothing")
        out["latency_ok"] = bool(
            not base_errs and not during_errs and first_cycle
            and (cycles >= 1 or swap_dirs >= 1) and child_rc == 0
            and overlapped == reps
            and (ratio <= 1.2 or not latency_gated))
        return out
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        svc.close()


def _refit_traces_entry(smoke: bool, tmp: str) -> dict:
    """Gate: ZERO fresh XLA traces in the serving path across the swap —
    scoring rounds before the cycle and scoring rounds against the
    freshly installed candidate both trace nothing (install warms the
    candidate's bucket programs OFF the request path, the same
    discipline every other swap leg gates)."""
    rng = np.random.default_rng(229)
    tmp = os.path.join(tmp, "traces")
    svc, entities = _refit_service(rng, tmp, smoke=smoke)
    try:
        for _ in range(4 if smoke else 6):
            f, i, y = _calibrated_batch(svc, rng, entities, 96,
                                        flip=True)
            svc.feedback(f, i, y)
        driver, _comp = _refit_driver(svc, tmp, smoke=smoke)

        def score_round(seed):
            r = np.random.default_rng(seed)
            f, i, _ = _calibrated_batch(svc, r, entities, 64)
            svc.score(f, i)

        for s in range(2):                       # warm bucket programs
            score_round(s)
        rounds = 3 if smoke else 8
        with _trace_counting() as before:
            for s in range(10, 10 + rounds):
                score_round(s)
        version_before = svc.registry.version
        result = driver.run_once()
        with _trace_counting() as after:
            for s in range(20, 20 + rounds):
                score_round(s)
        return {
            "name": "refit_traces",
            "rounds_per_side": rounds,
            "swapped": bool(result.swapped),
            "version_before": version_before,
            "version_after": svc.registry.version,
            "fresh_traces_before_swap": before.count,
            "fresh_traces_after_swap": after.count,
            "zero_traces_ok": bool(before.count == 0 and after.count == 0
                                   and result.swapped
                                   and svc.registry.version
                                   != version_before),
        }
    finally:
        svc.close()


def refit_bench(out_path="BENCH_refit.json", smoke=False, max_wall=None):
    """Continuous-training gate (--refit): (1) f64 refit-from-log parity
    <= 1e-6 vs the identical rows in memory; (2) the closed loop —
    drift trip -> compact -> warm refit -> tail validation -> swap ->
    gates reset -> zero trips across a post-swap stationary window;
    (3) scoring p99 during an out-of-process (cli.refit, nice 19) refit
    <= 1.2x baseline on multi-core hosts (measured, ungated on one
    core); (4) zero fresh XLA traces in the serving path across the
    swap.  `value` is the end-to-end trip-to-recovery cycle wall."""
    import tempfile

    import jax
    jax.config.update("jax_enable_x64", True)
    t0 = time.perf_counter()
    entries = []
    truncated = []
    with tempfile.TemporaryDirectory() as tmp:
        legs = [
            ("refit_parity", _refit_parity_entry),
            ("refit_loop", _refit_loop_entry),
            ("refit_traces", _refit_traces_entry),
            ("refit_latency", _refit_latency_entry),
        ]
        for name, fn in legs:
            if max_wall is not None and time.perf_counter() - t0 > max_wall:
                truncated.append(name)
                continue
            entries.append(fn(smoke, tmp))
    by_name = {e["name"]: e for e in entries}
    parity = by_name.get("refit_parity", {})
    loop = by_name.get("refit_loop", {})
    traces = by_name.get("refit_traces", {})
    latency = by_name.get("refit_latency", {})
    gates = {
        "parity_ok": parity.get("parity_ok"),
        "loop_ok": loop.get("loop_ok"),
        "zero_traces_ok": traces.get("zero_traces_ok"),
        "latency_ok": latency.get("latency_ok"),
    }
    # latency is a smoke SIGNAL under the tier-1 suite (shared cores), a
    # HARD gate on the committed full run — same policy as --online
    hard = ["parity_ok", "loop_ok", "zero_traces_ok"]
    if not smoke:
        hard.append("latency_ok")
    result = {
        "metric": "refit_trip_to_recovery_wall_s",
        "value": loop.get("cycle_wall_s"),
        "unit": "seconds",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            **gates,
            "all_ok": all(bool(gates[g]) for g in hard),
            "hard_gates": hard,
            "truncated": truncated or False,
            "suite_wall_s": round(time.perf_counter() - t0, 1),
        },
    }
    _embed_telemetry(result)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# --fleet: replicated serving (photon_ml_tpu/fleet/)
# --------------------------------------------------------------------------

def _fleet_save_model(tmp, seed, d_g=16, d_u=8, E=400):
    from photon_ml_tpu.models.io import save_game_model
    rng = np.random.default_rng(seed)
    mdir = os.path.join(tmp, "model")
    save_game_model(_online_model(rng, d_g, d_u, E), mdir)
    return mdir


def _fleet_publisher(mdir, log_dir, micro_batch=8, shard_spec=None):
    """In-process publisher: service + replication log + ordered hook.
    A non-None `shard_spec` anchors the log with a shard_map record
    (entity-sharded fleet — fleet/shards.py)."""
    from photon_ml_tpu.fleet import FleetPublisher, ReplicationLog
    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    svc = ScoringService(
        model_dir=mdir, config=ServingConfig(max_batch=64, min_bucket=4),
        updates=OnlineUpdateConfig(micro_batch=micro_batch),
        start_updater=False)
    log = ReplicationLog(log_dir)
    publisher = FleetPublisher(svc, log, model_dir=mdir,
                               shard_spec=shard_spec)
    return svc, log, publisher


def _fleet_follower(mdir, log, state_dir):
    from photon_ml_tpu.fleet import Replica, ReplicaConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    svc = ScoringService(model_dir=mdir,
                         config=ServingConfig(max_batch=64, min_bucket=4))
    rep = Replica(svc, log, state_dir, ReplicaConfig())
    rep.join()
    return rep


def _fleet_feedback(svc, seed, entities, rows, d_g=16, d_u=8):
    r = np.random.default_rng(seed)
    f, i, l = _feedback_batch(r, d_g, d_u, entities, rows)
    svc.feedback(f, i, l)
    svc.updater.flush()


def _fleet_audits_equal(audits) -> bool:
    """Bit-identical convergence: every audit's version vector AND table
    hashes agree."""
    first = audits[0]
    return all(a["version_vector"] == first["version_vector"]
               and a["table_hashes"] == first["table_hashes"]
               for a in audits[1:])


def _fleet_traces_entry(smoke: bool, tmp: str) -> dict:
    """Gate (d): zero fresh XLA traces on a replica during steady-state
    delta replay — the join-time `warmup_delta` pre-compiled every pow-2
    scatter shape, so tailing the log touches only cached programs."""
    mdir = _fleet_save_model(os.path.join(tmp, "traces"), seed=101)
    log_dir = os.path.join(tmp, "traces", "log")
    svc, log, _pub = _fleet_publisher(mdir, log_dir)
    rep = _fleet_follower(mdir, log, os.path.join(tmp, "traces", "s0"))
    entities = [f"u{i}" for i in range(64)]
    try:
        svc.updater.warmup()
        for s in range(2):  # warm: publisher programs + replica replay
            _fleet_feedback(svc, 1000 + s, entities, 24)
            rep.poll_once()
        steady = 4 if smoke else 12
        fresh = 0
        applied = 0
        for s in range(steady):
            _fleet_feedback(svc, 2000 + s, entities, 24)
            with _trace_counting() as counter:
                applied += rep.poll_once()
            fresh += counter.count
        audits = [svc.audit(), rep.service.audit()]
        return {
            "name": "fleet_replay_traces",
            "steady_rounds": steady, "records_applied": applied,
            "fresh_traces_replay": fresh,
            "converged": _fleet_audits_equal(audits),
            "zero_traces_ok": bool(fresh == 0 and applied >= steady
                                   and _fleet_audits_equal(audits)),
        }
    finally:
        svc.close()
        rep.service.close()


def _fleet_rollback_entry(smoke: bool, tmp: str) -> dict:
    """Gate (b): a mid-stream delta-aware rollback rides the log and
    every replica converges to the identical post-rollback state — the
    restored rows travel IN the record, so even a replica with no local
    undo history lands bit-exactly."""
    mdir = _fleet_save_model(os.path.join(tmp, "rb"), seed=103)
    log_dir = os.path.join(tmp, "rb", "log")
    svc, log, _pub = _fleet_publisher(mdir, log_dir)
    reps = [_fleet_follower(mdir, log, os.path.join(tmp, "rb", f"s{k}"))
            for k in range(2)]
    entities = [f"u{i}" for i in range(64)]
    table0 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
    try:
        rounds = 2 if smoke else 4
        for s in range(rounds):
            _fleet_feedback(svc, 3000 + s, entities, 24)
        deltas_before = svc.registry.pending_deltas()
        svc.rollback()                      # mid-stream: deltas pending
        restored_exact = bool(np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")), table0))
        for s in range(rounds):             # stream continues post-revert
            _fleet_feedback(svc, 4000 + s, entities, 24)
        for rep in reps:
            rep.poll_once()
        audits = [svc.audit()] + [r.service.audit() for r in reps]
        vv = svc.version_vector()
        return {
            "name": "fleet_rollback_convergence",
            "deltas_rolled_back": deltas_before,
            "publisher_restored_pre_delta_rows": restored_exact,
            "post_rollback_deltas": vv["delta_seq"],
            "replicas": len(reps),
            "version_vectors": [a["version_vector"] for a in audits],
            "rollback_ok": bool(deltas_before >= rounds and restored_exact
                                and vv["delta_seq"] > 0
                                and _fleet_audits_equal(audits)),
        }
    finally:
        svc.close()
        for rep in reps:
            rep.service.close()


def _fleet_fault_parity_entry(smoke: bool, tmp: str) -> dict:
    """Gate (e): injected transient faults at replog.append, replog.read
    and replica.apply are absorbed by the retry/backoff discipline with
    EXACT-trajectory parity — the faulted run's final audits (version
    vectors + table hashes, publisher AND replica) equal the fault-free
    run's bit-for-bit."""
    from photon_ml_tpu.utils import faults as F

    def run(label, plan):
        root = os.path.join(tmp, f"fp_{label}")
        mdir = _fleet_save_model(root, seed=107)
        svc, log, _pub = _fleet_publisher(mdir, os.path.join(root, "log"))
        rep = _fleet_follower(mdir, log, os.path.join(root, "s0"))
        entities = [f"u{i}" for i in range(64)]
        rounds = 3 if smoke else 6
        try:
            with (F.injected(plan) if plan is not None
                  else _null_ctx()):
                for s in range(rounds):
                    _fleet_feedback(svc, 5000 + s, entities, 24)
                    rep.poll_once()
                svc.rollback()
                _fleet_feedback(svc, 6000, entities, 24)
                rep.poll_once()
            snap = rep.service.metrics_snapshot()
            return {
                "audits": [svc.audit(), rep.service.audit()],
                "apply_retries": snap["fleet"]["apply_retries"],
                "records": snap["fleet"]["records_applied"],
                "injected": plan.report() if plan is not None else None,
            }
        finally:
            svc.close()
            rep.service.close()

    base = run("base", None)
    plan = F.FaultPlan([
        {"site": "replog.append", "action": "transient", "hits": [2, 4]},
        {"site": "replog.read", "action": "transient", "hits": [2]},
        {"site": "replica.apply", "action": "transient", "hits": [3, 6]},
    ], seed=11)
    faulted = run("faulted", plan)
    parity = bool(
        base["audits"][0]["version_vector"]
        == faulted["audits"][0]["version_vector"]
        and base["audits"][0]["table_hashes"]
        == faulted["audits"][0]["table_hashes"]
        and _fleet_audits_equal(faulted["audits"])
        and _fleet_audits_equal(base["audits"]))
    fired = faulted["injected"]["total_fired"]
    return {
        "name": "fleet_fault_parity",
        "faults_fired": fired,
        "apply_retries": faulted["apply_retries"],
        "injected": faulted["injected"],
        "fault_free_vv": base["audits"][0]["version_vector"],
        "faulted_vv": faulted["audits"][0]["version_vector"],
        "fault_parity_ok": bool(parity and fired >= 4),
    }


class _null_ctx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


# -- subprocess fleet helpers ------------------------------------------------

def _fleet_spawn(args, env_extra=None):
    """Start a cli.serve subprocess; returns (proc, base_url, info)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.cli.serve"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    line = proc.stdout.readline()
    if not line:
        proc.wait(timeout=10)
        raise RuntimeError(
            f"serve child exited rc={proc.returncode} before its "
            "startup line")
    info = json.loads(line)
    return proc, info["serving"], info


def _fleet_http(url, path, body=None, timeout=15.0, headers=None):
    import urllib.error
    import urllib.request
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url + path, data=data,
        method="POST" if data is not None else "GET",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _fleet_http_text(url, path, timeout=15.0) -> str:
    import urllib.request
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


def _fleet_wait_healthy(url, timeout=150.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        try:
            status, _ = _fleet_http(url, "/healthz", timeout=3.0)
            if status == 200:
                return True
        except Exception:
            pass
        time.sleep(0.2)
    return False


def _fleet_crash_entry(smoke: bool, tmp: str) -> dict:
    """Gate (a): sustained mixed scoring+feedback load through a front
    over real replica PROCESSES, one follower SIGKILLed mid-stream and
    restarted from its durable applied-seq — after the stream, every
    replica reports a bit-identical version vector AND table hashes."""
    import signal as _signal
    import threading as _threading

    from photon_ml_tpu.fleet import Front, FrontConfig

    root = os.path.join(tmp, "crash")
    mdir = _fleet_save_model(root, seed=109, E=200)
    log_dir = os.path.join(root, "log")
    n_followers = 1 if smoke else 2
    common = ["--model-dir", mdir, "--port", "0", "--max-batch", "64",
              "--min-bucket", "4", "--replication-log", log_dir]
    pub_proc, pub_url, _ = _fleet_spawn(
        common + ["--replica", "--publish", "--enable-updates",
                  "--update-interval-ms", "5",
                  "--replica-state", os.path.join(root, "pub")])
    followers = []
    for k in range(n_followers):
        followers.append(_fleet_spawn(
            common + ["--replica", "--replica-poll-ms", "20",
                      "--replica-state", os.path.join(root, f"f{k}")]))
    urls = [pub_url] + [u for _, u, _ in followers]
    assert all(_fleet_wait_healthy(u) for u in urls), "fleet not healthy"
    front = Front(urls, publisher_url=pub_url,
                  config=FrontConfig(probe_interval_s=0.05,
                                     hedge_after_s=1.0, max_attempts=3))
    rng = np.random.default_rng(71)
    entities = [f"u{i}" for i in range(200)]
    stop = _threading.Event()
    score_errors, scored, fed = [], [0], [0]

    def score_loop():
        r = np.random.default_rng(73)
        while not stop.is_set():
            k = int(r.integers(1, 6))
            body = {"features": {
                "global": r.normal(size=(k, 16)).tolist(),
                "per_user": r.normal(size=(k, 8)).tolist()},
                "ids": {"userId": [entities[r.integers(0, 200)]
                                   for _ in range(k)]}}
            try:
                status, _ = front.route("/score", body, timeout=10.0)
                if status == 200:
                    scored[0] += k
                else:
                    score_errors.append(f"http {status}")
            except Exception as e:
                score_errors.append(f"{type(e).__name__}")
            time.sleep(0.002)

    def feed_loop():
        r = np.random.default_rng(79)
        while not stop.is_set():
            n = 16
            body = {"features": {
                "global": r.normal(size=(n, 16)).tolist(),
                "per_user": r.normal(size=(n, 8)).tolist()},
                "ids": {"userId": [entities[r.integers(0, 200)]
                                   for _ in range(n)]},
                "labels": (r.uniform(size=n) < 0.5).astype(float).tolist()}
            try:
                status, _, _hdrs = front.route_publisher(
                    "POST", "/feedback", body)
                if status == 202:
                    fed[0] += n
            except Exception:
                pass
            time.sleep(0.02)

    threads = [_threading.Thread(target=score_loop, daemon=True)
               for _ in range(2)] + \
              [_threading.Thread(target=feed_loop, daemon=True)]
    kill_proc, kill_url, _ = followers[0]
    kill_port = kill_url.rsplit(":", 1)[1]
    restarted = None
    try:
        for t in threads:
            t.start()
        phase_s = 2.0 if smoke else 4.0
        time.sleep(phase_s)                     # phase 1: steady stream
        kill_proc.send_signal(_signal.SIGKILL)  # mid-stream crash
        kill_proc.wait(timeout=10)
        killed_rc = kill_proc.returncode
        time.sleep(phase_s)                     # phase 2: degraded fleet
        restarted = _fleet_spawn(               # same durable state dir
            common + ["--replica", "--replica-poll-ms", "20",
                      "--replica-state", os.path.join(root, "f0"),
                      "--host", "127.0.0.1"]
            + ["--port", kill_port])
        rejoined = _fleet_wait_healthy(restarted[1])
        time.sleep(phase_s)                     # phase 3: healed fleet
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    # quiesce: let the updater drain, then wait for log convergence
    deadline = time.perf_counter() + 90
    audits = None
    while time.perf_counter() < deadline:
        all_urls = [pub_url] + [u for _, u, _ in followers[1:]] \
            + [restarted[1]]
        try:
            audits = [_fleet_http(u, "/fleet/audit", timeout=5.0)[1]
                      for u in all_urls]
        except Exception:
            time.sleep(0.3)
            continue
        if _fleet_audits_equal(audits):
            break
        time.sleep(0.3)
    front.close()
    snap = _fleet_http(pub_url, "/metrics.json")[1]
    for proc in [pub_proc] + [p for p, _, _ in followers[1:]] \
            + ([restarted[0]] if restarted else []):
        proc.send_signal(_signal.SIGTERM)
    for proc in [pub_proc] + [p for p, _, _ in followers[1:]] \
            + ([restarted[0]] if restarted else []):
        try:
            proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
    converged = bool(audits and _fleet_audits_equal(audits))
    return {
        "name": "fleet_crash_catchup",
        "followers": n_followers,
        "killed_returncode": killed_rc,
        "rejoined_ready": bool(restarted and rejoined),
        "rows_scored": scored[0], "feedback_rows": fed[0],
        "score_errors": len(score_errors),
        "deltas_published": snap["online"]["deltas_published"],
        "version_vectors": ([a["version_vector"] for a in audits]
                            if audits else None),
        "bit_identical": converged,
        "convergence_ok": bool(
            converged and killed_rc not in (0, 1) and rejoined
            and scored[0] > 0 and fed[0] > 0
            and snap["online"]["deltas_published"] > 0),
    }


def _fleet_scaling_entry(smoke: bool, tmp: str) -> dict:
    """Gate (c): front aggregate throughput scales >= 1.6x from 1 -> 2
    replica processes with p99 within the single-replica SLO.  The
    throughput half of the gate needs >= 2 cores (two replica processes
    on one core share the same silicon — aggregate scoring capacity is
    core-bound, exactly the bottleneck a fleet exists to escape); on a
    single-core host the ratio is measured and reported UNGATED (the
    mesh-bench wall-clock policy) while the p99-SLO and zero-error
    halves stay hard."""
    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    from photon_ml_tpu.fleet import Front, FrontConfig
    from photon_ml_tpu.telemetry.timings import clock as _clock

    root = os.path.join(tmp, "scale")
    mdir = _fleet_save_model(root, seed=113, E=200)
    log_dir = os.path.join(root, "log")
    common = ["--model-dir", mdir, "--port", "0", "--max-batch", "64",
              "--min-bucket", "4", "--replication-log", log_dir,
              "--max-wait-ms", "2"]
    # a publisher so the log exists; followers serve the scoring load
    pub_proc, pub_url, _ = _fleet_spawn(
        common + ["--replica", "--publish",
                  "--replica-state", os.path.join(root, "pub")])
    followers = [_fleet_spawn(
        common + ["--replica", "--replica-poll-ms", "50",
                  "--replica-state", os.path.join(root, f"f{k}")])
        for k in range(2)]
    urls = [u for _, u, _ in followers]
    assert _fleet_wait_healthy(pub_url) and \
        all(_fleet_wait_healthy(u) for u in urls), "fleet not healthy"

    rng = np.random.default_rng(127)
    entities = [f"u{i}" for i in range(200)]
    n_requests = 120 if smoke else 400
    threads = 8
    rows_per_req = 4
    requests = []
    for _ in range(n_requests):
        requests.append({
            "features": {
                "global": rng.normal(size=(rows_per_req, 16)).tolist(),
                "per_user": rng.normal(size=(rows_per_req, 8)).tolist()},
            "ids": {"userId": [entities[rng.integers(0, 200)]
                               for _ in range(rows_per_req)]}})

    def phase(phase_urls):
        front = Front(phase_urls, config=FrontConfig(
            probe_interval_s=0.05, hedge_after_s=2.0,
            request_timeout_s=20.0, max_inflight=512))
        try:
            t0 = _clock()
            while not all(front.probe_once().values()) \
                    and _clock() - t0 < 10:
                time.sleep(0.05)
            lat, errors = [], []
            lock = _threading.Lock()

            def one(body):
                s = _clock()
                try:
                    status, _ = front.route("/score", body)
                    if status != 200:
                        raise RuntimeError(f"http {status}")
                except Exception as e:
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    return
                with lock:
                    lat.append(_clock() - s)

            with ThreadPoolExecutor(max_workers=threads) as pool:
                list(pool.map(one, requests[:n_requests // 4]))  # warm
            lat.clear()
            errors.clear()
            t0 = _clock()
            with ThreadPoolExecutor(max_workers=threads) as pool:
                list(pool.map(one, requests))
            wall = _clock() - t0
            return {
                "replicas": len(phase_urls),
                "rows_per_sec": round(n_requests * rows_per_req / wall, 1),
                "requests_per_sec": round(n_requests / wall, 1),
                "wall_s": round(wall, 3),
                "p50_ms": round(1e3 * float(np.percentile(lat, 50)), 2)
                if lat else None,
                "p99_ms": round(1e3 * float(np.percentile(lat, 99)), 2)
                if lat else None,
                "errors": len(errors), "first_errors": errors[:3],
            }
        finally:
            front.close()

    try:
        one_rep = phase(urls[:1])
        two_rep = phase(urls)
    finally:
        import signal as _signal
        for proc in [pub_proc] + [p for p, _, _ in followers]:
            proc.send_signal(_signal.SIGTERM)
        for proc in [pub_proc] + [p for p, _, _ in followers]:
            try:
                proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
    ratio = (two_rep["rows_per_sec"] / one_rep["rows_per_sec"]
             if one_rep["rows_per_sec"] else 0.0)
    # the single-replica SLO: the 2-replica p99 must stay within 1.25x
    # of the single-replica baseline p99
    slo_p99_ms = (None if one_rep["p99_ms"] is None
                  else round(1.25 * one_rep["p99_ms"], 2))
    slo_ok = bool(one_rep["p99_ms"] is not None
                  and two_rep["p99_ms"] is not None
                  and two_rep["p99_ms"] <= slo_p99_ms)
    cores = os.cpu_count() or 1
    scaling_gated = cores >= 2
    out = {
        "name": "fleet_scaling",
        "requests": n_requests, "threads": threads,
        "rows_per_request": rows_per_req,
        "one_replica": one_rep, "two_replicas": two_rep,
        "throughput_ratio": round(ratio, 3),
        "throughput_gate": 1.6,
        "host_cores": cores,
        "slo_p99_ms": slo_p99_ms,
        "p99_within_slo": slo_ok,
        "scaling_gated": scaling_gated,
    }
    if not scaling_gated:
        out["scaling_gate_waived"] = (
            f"single-core host (os.cpu_count()={cores}): two replica "
            "processes share one core, so aggregate capacity is "
            "core-bound and the extra process only ADDS contention — "
            "the throughput ratio and p99-vs-SLO comparison are "
            "measured and reported ungated; both arm as hard gates on "
            "any multi-core host")
    out["scaling_ok"] = bool(
        one_rep["errors"] == 0 and two_rep["errors"] == 0
        and one_rep["rows_per_sec"] > 0 and two_rep["rows_per_sec"] > 0
        and ((ratio >= 1.6 and slo_ok) or not scaling_gated))
    return out


def fleet_bench(out_path="BENCH_fleet.json", smoke=False, max_wall=None):
    """Replicated-serving gate (--fleet): (a) mixed scoring+feedback load
    over replica processes with one follower SIGKILLed mid-stream and
    restarted — every replica converges to bit-identical version vectors
    and table hashes; (b) a mid-stream rollback converges identically on
    every replica; (c) front throughput scales >= 1.6x from 1 -> 2
    replicas (multi-core hosts; reported ungated on one core) with p99
    within the single-replica SLO; (d) zero fresh XLA traces on replicas
    during steady-state delta replay; (e) injected transient
    replog/replica faults absorbed with exact-trajectory parity.
    `value` is the 1 -> 2 replica throughput ratio."""
    import tempfile

    import jax
    jax.config.update("jax_enable_x64", True)
    t0 = time.perf_counter()
    entries = []
    truncated = []
    with tempfile.TemporaryDirectory() as tmp:
        legs = [
            ("fleet_replay_traces", _fleet_traces_entry),
            ("fleet_rollback_convergence", _fleet_rollback_entry),
            ("fleet_fault_parity", _fleet_fault_parity_entry),
            ("fleet_crash_catchup", _fleet_crash_entry),
            ("fleet_scaling", _fleet_scaling_entry),
        ]
        for name, fn in legs:
            if max_wall is not None and \
                    time.perf_counter() - t0 > max_wall:
                truncated.append(name)
                continue
            entries.append(fn(smoke, tmp))
    by_name = {e["name"]: e for e in entries}
    gates = {
        "zero_traces_ok": by_name.get("fleet_replay_traces",
                                      {}).get("zero_traces_ok"),
        "rollback_ok": by_name.get("fleet_rollback_convergence",
                                   {}).get("rollback_ok"),
        "fault_parity_ok": by_name.get("fleet_fault_parity",
                                       {}).get("fault_parity_ok"),
        "convergence_ok": by_name.get("fleet_crash_catchup",
                                      {}).get("convergence_ok"),
        "scaling_ok": by_name.get("fleet_scaling", {}).get("scaling_ok"),
    }
    hard = ["zero_traces_ok", "rollback_ok", "fault_parity_ok",
            "convergence_ok"]
    # scaling runs on real subprocesses: a hard gate on the full run,
    # a smoke signal under the tier-1 suite (shared-core CI) — the
    # --online/--health latency policy
    if not smoke:
        hard.append("scaling_ok")
    scaling = by_name.get("fleet_scaling", {})
    result = {
        "metric": "fleet_1_to_2_replica_throughput_ratio",
        "value": scaling.get("throughput_ratio"),
        "unit": "x",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            **gates,
            "all_ok": all(bool(gates[g]) for g in hard),
            "hard_gates": hard,
            "truncated": truncated or False,
            "suite_wall_s": round(time.perf_counter() - t0, 1),
        },
    }
    _embed_telemetry(result)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# --shards: entity-sharded serving (fleet/shards.py + sharded replicas)
# --------------------------------------------------------------------------

def _shards_service(mdir, shard_index=None, shard_count=None,
                    store_budget=None, store_dir=None):
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    return ScoringService(model_dir=mdir, config=ServingConfig(
        max_batch=64, min_bucket=4,
        shard_index=shard_index, shard_count=shard_count,
        store_budget_rows=store_budget, store_dir=store_dir))


def _shards_map_entry(smoke: bool) -> dict:
    """Gate (a): the shard map is a pure function of
    (salt, version, num_shards) — deterministic across constructions,
    TOTAL (every entity owned by exactly one shard), round-trips through
    its log-record dict with a content-hash spec_id that rejects
    incompatible builds, and re-salting/re-versioning actually moves
    entities (the rebalance lever)."""
    from photon_ml_tpu.fleet import ShardSpec
    n_ids = 512 if smoke else 4096
    n_shards = 4
    ids = [f"u{i}" for i in range(n_ids)]
    spec = ShardSpec(num_shards=n_shards)
    assign = [spec.shard_of(e) for e in ids]
    deterministic = assign == [ShardSpec(num_shards=n_shards).shard_of(e)
                               for e in ids]
    owners = np.zeros(n_ids, np.int64)
    for k in range(n_shards):
        owners += spec.owned_mask(ids, k).astype(np.int64)
    total = bool(np.all(owners == 1))
    rt = ShardSpec.from_dict(spec.to_dict())
    roundtrip = bool(rt == spec
                     and [rt.shard_of(e) for e in ids] == assign)
    try:
        ShardSpec.from_dict(dict(spec.to_dict(), salt="other"))
        mismatch_rejected = False
    except ValueError:
        mismatch_rejected = True
    moved_salt = sum(
        ShardSpec(num_shards=n_shards, salt="s2").shard_of(e) != assign[i]
        for i, e in enumerate(ids))
    moved_ver = sum(
        ShardSpec(num_shards=n_shards, version=2).shard_of(e) != assign[i]
        for i, e in enumerate(ids))
    loads = np.bincount(np.asarray(assign), minlength=n_shards)
    balance = float(loads.max() / (n_ids / n_shards))
    return {
        "name": "shards_map",
        "entities": n_ids, "shards": n_shards,
        "deterministic": deterministic, "total": total,
        "roundtrip": roundtrip,
        "spec_id_mismatch_rejected": mismatch_rejected,
        "moved_by_resalt": int(moved_salt),
        "moved_by_reversion": int(moved_ver),
        "loads": loads.tolist(),
        "max_load_over_mean": round(balance, 3),
        "map_ok": bool(deterministic and total and roundtrip
                       and mismatch_rejected and moved_salt > 0
                       and moved_ver > 0 and balance <= 1.3),
    }


def _shards_parity_entry(smoke: bool, tmp: str) -> dict:
    """Gate (b): fan-out over per-shard margin legs re-folds to the
    monolithic scorer's scores EXACTLY (same f64 bytes, every round,
    under every choice of primary leg), and the steady-state fan-out path
    compiles nothing fresh — the legs' score_margins programs and the
    host-side merge are all warm."""
    from photon_ml_tpu.fleet import ShardSpec, merge_margins
    root = os.path.join(tmp, "parity")
    mdir = _fleet_save_model(root, seed=211)
    n_shards = 3
    spec = ShardSpec(num_shards=n_shards)
    mono = _shards_service(mdir)
    svcs = [_shards_service(mdir, k, n_shards) for k in range(n_shards)]
    meta = svcs[0].registry.scorer.coordinate_meta()
    rng = np.random.default_rng(223)
    entities = [f"u{i}" for i in range(400)]
    rounds = 4 if smoke else 12
    n_rows = 12

    def request():
        # one unseen id per request: scores with a zero RE contribution
        # on every leg AND on the monolithic scorer
        users = [entities[rng.integers(0, len(entities))]
                 for _ in range(n_rows - 1)] + ["ghost"]
        feats = {"global": rng.normal(size=(n_rows, 16)),
                 "per_user": rng.normal(size=(n_rows, 8))}
        return feats, {"userId": np.asarray(users, dtype=object)}

    def fanout(feats, ids, primary=0):
        legs = {k: svcs[k].score_margins(feats, ids)["margins"]
                for k in range(n_shards)}
        return merge_margins(spec, meta, ids, legs, primary=primary)

    try:
        for _ in range(2):                  # warm every compiled bucket
            feats, ids = request()
            fanout(feats, ids)
            mono.score(feats, ids)
        exact = fresh = 0
        for _ in range(rounds):
            feats, ids = request()
            with _trace_counting() as counter:
                out = fanout(feats, ids)
            fresh += counter.count
            got = np.asarray(out["scores"], np.float64)
            expected = np.asarray(mono.score(feats, ids), np.float64)
            exact += int(got.tobytes() == expected.tobytes()
                         and out["partial_rows"] == []
                         and out["missing_shards"] == [])
        # FE/MF replicate everywhere: any healthy primary gives the bits
        feats, ids = request()
        expected = np.asarray(mono.score(feats, ids), np.float64)
        primaries_exact = all(
            np.asarray(fanout(feats, ids, primary=p)["scores"],
                       np.float64).tobytes() == expected.tobytes()
            for p in range(n_shards))
        owned = [sum(svcs[k].registry.scorer.shard_info()
                     ["owned_rows"].values()) for k in range(n_shards)]
        return {
            "name": "shards_parity",
            "shards": n_shards, "rounds": rounds,
            "rows_per_request": n_rows,
            "rounds_bit_exact": exact,
            "fresh_traces_fanout": fresh,
            "all_primaries_exact": primaries_exact,
            "owned_rows": owned,
            "parity_ok": bool(exact == rounds and fresh == 0
                              and primaries_exact
                              and sum(owned) == 400),
        }
    finally:
        mono.close()
        for s in svcs:
            s.close()


def _shards_replay_entry(smoke: bool, tmp: str) -> dict:
    """Gate (c): sharded replicas tail the SAME replication log as the
    rest of the fleet but apply only their owned slice — steady-state
    shard-filtered delta replay compiles nothing fresh, and after the
    stream each replica's full-table audit is sha256-IDENTICAL to the
    publisher's per-shard filter of its full model (the
    /fleet/audit?shard=K contract)."""
    from photon_ml_tpu.fleet import Replica, ReplicaConfig, ShardSpec
    root = os.path.join(tmp, "replay")
    mdir = _fleet_save_model(root, seed=227)
    n_shards = 2
    spec = ShardSpec(num_shards=n_shards)
    svc, log, pub = _fleet_publisher(mdir, os.path.join(root, "log"),
                                     shard_spec=spec)
    reps = []
    for k in range(n_shards):
        s = _shards_service(mdir, k, n_shards)
        rep = Replica(s, log, os.path.join(root, f"s{k}"),
                      ReplicaConfig())
        rep.join()
        reps.append(rep)
    entities = [f"u{i}" for i in range(64)]
    try:
        svc.updater.warmup()
        for s_ in range(2):     # warm: publisher solve + replica scatter
            _fleet_feedback(svc, 7000 + s_, entities, 24)
            for rep in reps:
                rep.poll_once()
        steady = 4 if smoke else 12
        fresh = applied = 0
        for s_ in range(steady):
            _fleet_feedback(svc, 8000 + s_, entities, 24)
            with _trace_counting() as counter:
                for rep in reps:
                    applied += rep.poll_once()
            fresh += counter.count
        pub_vv = svc.version_vector()
        audits_exact = all(
            reps[k].service.audit()["table_hashes"]
            == pub.shard_audit(k)["table_hashes"]
            and reps[k].service.version_vector() == pub_vv
            for k in range(n_shards))
        return {
            "name": "shards_replay",
            "shards": n_shards, "steady_rounds": steady,
            "records_applied": applied,
            "fresh_traces_replay": fresh,
            "per_shard_audits_sha256_exact": audits_exact,
            "replay_ok": bool(fresh == 0 and applied >= steady
                              and audits_exact),
        }
    finally:
        svc.close()
        for rep in reps:
            rep.service.close()


def _shards_capacity_entry(smoke: bool, tmp: str) -> dict:
    """Gate (d): the capacity claim — a 4-shard fleet serves a
    random-effect table 4x ONE replica's device store budget,
    bit-identically.  Every sharded service gets a tiered store whose hot
    set holds E/4 rows (its owned slice, give or take the hash split);
    the monolithic reference holds the full table unbudgeted; fan-out
    merges must still reproduce its bytes exactly."""
    from photon_ml_tpu.fleet import ShardSpec, merge_margins
    root = os.path.join(tmp, "cap")
    E = 512 if smoke else 1024
    n_shards = 4
    budget = E // n_shards
    mdir = _fleet_save_model(root, seed=229, E=E)
    spec = ShardSpec(num_shards=n_shards)
    mono = _shards_service(mdir)
    svcs = [_shards_service(mdir, k, n_shards, store_budget=budget,
                            store_dir=os.path.join(root, f"store{k}"))
            for k in range(n_shards)]
    meta = svcs[0].registry.scorer.coordinate_meta()
    rng = np.random.default_rng(233)
    entities = [f"u{i}" for i in range(E)]
    rounds = 3 if smoke else 8
    n_rows = 16
    try:
        exact = 0
        for r in range(rounds + 1):
            users = [entities[rng.integers(0, E)] for _ in range(n_rows)]
            feats = {"global": rng.normal(size=(n_rows, 16)),
                     "per_user": rng.normal(size=(n_rows, 8))}
            ids = {"userId": np.asarray(users, dtype=object)}
            legs = {k: svcs[k].score_margins(feats, ids)["margins"]
                    for k in range(n_shards)}
            got = np.asarray(
                merge_margins(spec, meta, ids, legs, primary=0)["scores"],
                np.float64)
            expected = np.asarray(mono.score(feats, ids), np.float64)
            if r > 0:                       # round 0 is the warm round
                exact += int(got.tobytes() == expected.tobytes())
        owned = [sum(svcs[k].registry.scorer.shard_info()
                     ["owned_rows"].values()) for k in range(n_shards)]
        ratio = E / budget
        return {
            "name": "shards_capacity",
            "shards": n_shards, "re_rows": E,
            "per_replica_store_budget_rows": budget,
            "re_rows_over_one_replica_budget": round(ratio, 2),
            "owned_rows": owned,
            "rounds": rounds, "rounds_bit_exact": exact,
            "capacity_ok": bool(exact == rounds and ratio >= 4.0
                                and sum(owned) == E),
        }
    finally:
        mono.close()
        for s in svcs:
            s.close()


def _shards_failover_entry(smoke: bool, tmp: str) -> dict:
    """Gate (e): the robustness core over real replica PROCESSES — a
    2-shard fleet (publisher + one replica per shard) takes online
    deltas, audits sha256-exact per shard, then loses shard 0's ONLY
    replica to SIGKILL: requests confined to the surviving shard stay
    bit-exact with p99 within 1.2x the all-up baseline, requests
    touching the dead shard degrade (and ONLY those), and the respawned
    replica catches up from the shard-filtered log to a sha256-exact
    audit, after which the degraded request scores exactly again."""
    import signal as _signal

    from photon_ml_tpu.fleet import (Front, FrontConfig, Replica,
                                     ReplicaConfig, ReplicationLog,
                                     ShardSpec)

    root = os.path.join(tmp, "failover")
    E = 200
    mdir = _fleet_save_model(root, seed=239, E=E)
    log_dir = os.path.join(root, "log")
    spec = ShardSpec(num_shards=2)
    # the bench process runs x64 (jax_enable_x64 above); the spawned
    # fleet must score in the same compute dtype or bit-parity against
    # the in-process monolithic reference is impossible by construction
    x64 = {"JAX_ENABLE_X64": "1"}
    common = ["--model-dir", mdir, "--port", "0", "--max-batch", "64",
              "--min-bucket", "4", "--replication-log", log_dir]

    def spawn_replica(k):
        return _fleet_spawn(
            common + ["--replica", "--shard", f"{k}/2",
                      "--replica-state", os.path.join(root, f"s{k}"),
                      "--replica-poll-ms", "25"], env_extra=x64)

    pub_proc, pub_url, _ = _fleet_spawn(
        common + ["--replica", "--publish", "--shard-count", "2",
                  "--replica-state", os.path.join(root, "sp"),
                  "--enable-updates", "--update-interval-ms", "50",
                  # cheap updater warmup: 2 small solver buckets
                  "--update-micro-batch", "4",
                  "--update-max-rows-per-entity", "8"], env_extra=x64)
    procs = {"pub": pub_proc}
    urls = {"pub": pub_url}
    for k in range(2):
        p, u, info = spawn_replica(k)
        procs[k], urls[k] = p, u
        assert info["shard"]["index"] == k
    front = Front([urls["pub"], urls[0], urls[1]],
                  publisher_url=urls["pub"],
                  config=FrontConfig(probe_interval_s=0.05,
                                     unhealthy_after=1,
                                     request_timeout_s=30.0,
                                     hedge_after_s=10.0),
                  start_probes=False)
    rng = np.random.default_rng(241)
    mono = None

    def wait(cond, budget_s, what):
        deadline = time.perf_counter() + budget_s
        while time.perf_counter() < deadline:
            if cond():
                return
            time.sleep(0.1)
        raise RuntimeError(f"shards_failover: {what} "
                           f"(waited {budget_s}s)")

    def req_body(users):
        n = len(users)
        feats = {"global": rng.normal(size=(n, 16)),
                 "per_user": rng.normal(size=(n, 8))}
        ids = {"userId": np.asarray(users, dtype=object)}
        body = {"features": {k: v.tolist() for k, v in feats.items()},
                "ids": {"userId": users}}
        return feats, ids, body

    try:
        wait(lambda: all(front.probe_once().values()), 150,
             "fleet never became ready")
        # online deltas through the publisher: the replicas converge on
        # shard-FILTERED log state, not just the base swap
        n = 16
        fb = {"features": {
            "global": rng.normal(size=(n, 16)).tolist(),
            "per_user": rng.normal(size=(n, 8)).tolist()},
            "ids": {"userId": [f"u{i % E}" for i in range(n)]},
            "labels": [0.0] * n}
        status, _p, _h = front.route_publisher("POST", "/feedback", fb)
        assert status == 202, f"feedback got http {status}"

        def drained():
            _s, snap = _fleet_http(urls["pub"], "/metrics.json")
            online = snap.get("online") or {}
            return (online.get("pending_rows") == 0
                    and online.get("deltas_published", 0) > 0)
        wait(drained, 120, "publisher never drained its updater")
        # pending_rows zeroes BEFORE the last cycle's delta lands on the
        # log: wait for a full settle window of head stability with
        # every replica caught up
        state = {"head": None, "since": time.perf_counter()}

        def settled():
            front.probe_once()
            lag = front._fleet_lag()
            if lag["publisher_head_seq"] != state["head"]:
                state["head"] = lag["publisher_head_seq"]
                state["since"] = time.perf_counter()
                return False
            return (state["head"] is not None and state["head"] >= 3
                    and time.perf_counter() - state["since"] > 1.0
                    and all(st["lag_records"] == 0
                            for st in lag["replicas"].values()))
        wait(settled, 90, "replicas never caught up")
        # the bit-parity oracle: a monolithic follower of the SAME log
        mono = _shards_service(mdir)
        rep = Replica(mono, ReplicationLog(log_dir),
                      os.path.join(root, "s_mono"), ReplicaConfig())
        rep.join()
        # per-shard audits while everything is up
        audits_up = all(
            _fleet_http(urls[k], "/fleet/audit")[1]["table_hashes"]
            == _fleet_http(urls["pub"],
                           f"/fleet/audit?shard={k}")[1]["table_hashes"]
            for k in (0, 1))
        # the measured workload: requests CONFINED to shard 1 (the
        # survivor) — identical fan-out shape before and after the kill
        survivors = [e for e in (f"u{i}" for i in range(E))
                     if spec.shard_of(e) == 1][:32]
        n_req = 60 if smoke else 200
        reqs = []
        for _ in range(n_req):
            users = [survivors[rng.integers(0, len(survivors))]
                     for _ in range(4)]
            feats, ids, body = req_body(users)
            reqs.append((body, None))
        warm = 10 if smoke else 25

        def run_phase():
            lat, errors, inexact = [], 0, 0
            for i, (body, expected) in enumerate(reqs):
                t0 = time.perf_counter()
                try:
                    status, payload = front.route("/score", body)
                except Exception:
                    errors += 1
                    continue
                dt = time.perf_counter() - t0
                if status != 200 or "degraded" in payload:
                    errors += 1
                    continue
                if i >= warm:
                    lat.append(dt)
                if expected is not None and np.asarray(
                        payload["scores"],
                        np.float64).tobytes() != expected:
                    inexact += 1
            p99 = (round(1e3 * float(np.percentile(lat, 99)), 2)
                   if lat else None)
            return {"p99_ms": p99, "errors": errors, "inexact": inexact}

        # pin each request's expected bytes from the monolithic oracle
        for i, (body, _) in enumerate(reqs):
            feats = {k: np.asarray(v) for k, v in
                     body["features"].items()}
            ids = {"userId": np.asarray(body["ids"]["userId"],
                                        dtype=object)}
            reqs[i] = (body, np.asarray(mono.score(feats, ids),
                                        np.float64).tobytes())
        baseline = run_phase()
        # SIGKILL shard 0's only replica: the shard is GONE
        procs[0].send_signal(_signal.SIGKILL)
        procs[0].wait(timeout=30)
        killed_rc = procs[0].returncode
        wait(lambda: (front.probe_once(),
                      front.status()["shards"]["shards_down"] == [0]
                      )[-1], 30, "front never noticed the lost shard")
        degraded = run_phase()
        # errors confined: a request touching shard 0 degrades with
        # exactly that shard reported missing; surviving rows exact
        touch0 = [e for e in (f"u{i}" for i in range(E))
                  if spec.shard_of(e) == 0][:2] + survivors[:2]
        mfeats, mids, mbody = req_body(touch0)
        status, payload = front.route("/score", mbody)
        mexp = np.asarray(mono.score(mfeats, mids), np.float64)
        confined = bool(
            status == 200 and payload.get("degraded") is True
            and payload["missing_shards"] == [0]
            and payload["partial_rows"] == [0, 1]
            and np.asarray(payload["scores"],
                           np.float64)[2:].tobytes()
            == mexp[2:].tobytes())
        # rejoin: catch up from the shard-filtered log, audit exact
        procs[0], urls[0], _info = spawn_replica(0)
        front.attach(urls[0])
        wait(lambda: (front.probe_once(),
                      front.status()["shards"]["shards_down"] == []
                      )[-1], 150, "rejoined replica never became ready")
        audit_rejoin = bool(
            _fleet_http(urls[0], "/fleet/audit")[1]["table_hashes"]
            == _fleet_http(urls["pub"],
                           "/fleet/audit?shard=0")[1]["table_hashes"])
        status, payload = front.route("/score", mbody)
        healed = bool(status == 200 and "degraded" not in payload
                      and np.asarray(payload["scores"],
                                     np.float64).tobytes()
                      == mexp.tobytes())
        ratio = (degraded["p99_ms"] / baseline["p99_ms"]
                 if baseline["p99_ms"] and degraded["p99_ms"] else None)
        # the latency half of the gate is a smoke SIGNAL (shared-core
        # CI: three replica processes + the bench share the silicon, so
        # a p99 percentile is scheduler noise); the full run gates hard
        p99_gated = not smoke
        out = {
            "name": "shards_failover",
            "killed_returncode": killed_rc,
            "requests_per_phase": n_req,
            "baseline": baseline, "one_shard_down": degraded,
            "p99_ratio": round(ratio, 3) if ratio else None,
            "p99_gate": 1.2, "p99_gated": p99_gated,
            "audits_sha256_exact_all_up": audits_up,
            "errors_confined_to_lost_shard": confined,
            "rejoin_audit_sha256_exact": audit_rejoin,
            "rejoin_heals_degraded_request": healed,
        }
        out["failover_ok"] = bool(
            killed_rc not in (0, 1) and audits_up and confined
            and audit_rejoin and healed
            and baseline["errors"] == 0 and baseline["inexact"] == 0
            and degraded["errors"] == 0 and degraded["inexact"] == 0
            and (not p99_gated or (ratio is not None and ratio <= 1.2)))
        return out
    finally:
        front.close()
        if mono is not None:
            mono.close()
        live = [p for p in procs.values() if p.poll() is None]
        for p in live:
            p.send_signal(_signal.SIGTERM)
        for p in live:
            try:
                p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


def shards_bench(out_path="BENCH_shards.json", smoke=False,
                 max_wall=None):
    """Entity-sharded serving gate (--shards): (a) the shard map is
    deterministic, total, and round-trips with a spec_id that rejects
    incompatible builds; (b) fan-out over per-shard margin legs re-folds
    to the monolithic scorer's bytes exactly with zero fresh traces in
    steady state; (c) shard-filtered delta replay compiles nothing fresh
    and converges to sha256-exact per-shard audits; (d) a 4-shard fleet
    serves a random-effect table 4x one replica's store budget,
    bit-identically; (e) SIGKILLing one shard's only replica degrades
    ONLY that shard (surviving p99 within 1.2x baseline on the full run)
    and the respawned replica catches up to a sha256-exact audit.
    `value` is the capacity ratio (RE rows / one replica's budget)."""
    import tempfile

    import jax
    jax.config.update("jax_enable_x64", True)
    t0 = time.perf_counter()
    entries = []
    truncated = []
    with tempfile.TemporaryDirectory() as tmp:
        legs = [
            ("shards_map", lambda s, t: _shards_map_entry(s)),
            ("shards_parity", _shards_parity_entry),
            ("shards_replay", _shards_replay_entry),
            ("shards_capacity", _shards_capacity_entry),
            ("shards_failover", _shards_failover_entry),
        ]
        for name, fn in legs:
            if max_wall is not None and \
                    time.perf_counter() - t0 > max_wall:
                truncated.append(name)
                continue
            entries.append(fn(smoke, tmp))
    by_name = {e["name"]: e for e in entries}
    gates = {
        "map_ok": by_name.get("shards_map", {}).get("map_ok"),
        "parity_ok": by_name.get("shards_parity", {}).get("parity_ok"),
        "replay_ok": by_name.get("shards_replay", {}).get("replay_ok"),
        "capacity_ok": by_name.get("shards_capacity",
                                   {}).get("capacity_ok"),
        "failover_ok": by_name.get("shards_failover",
                                   {}).get("failover_ok"),
    }
    hard = list(gates)
    capacity = by_name.get("shards_capacity", {})
    result = {
        "metric": "shard_fleet_re_rows_over_one_replica_budget",
        "value": capacity.get("re_rows_over_one_replica_budget"),
        "unit": "x",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            **gates,
            "all_ok": all(bool(gates[g]) for g in hard),
            "hard_gates": hard,
            "truncated": truncated or False,
            "suite_wall_s": round(time.perf_counter() - t0, 1),
        },
    }
    _embed_telemetry(result)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------
# --fleetobs: fleet-wide observability (telemetry/distributed + flight)
# --------------------------------------------------------------------------

def _fleetobs_wait(predicate, timeout_s=60.0, step_s=0.2):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout_s:
        try:
            if predicate():
                return True
        except Exception:
            pass
        time.sleep(step_s)
    return False


def _fleetobs_fleet_entry(smoke: bool, tmp: str) -> dict:
    """One live fleet session (front + publisher + follower processes,
    every process tracing to its own run log with the flight recorder
    armed), three gate families:

      (a) TRACE MERGE — client-stamped X-Photon-Trace ids on /score and
          /feedback requests come back from `merge_run_logs` as ONE
          connected tree each; the feedback tree crosses front ->
          publisher -> online update -> replication record -> follower
          apply; children stay inside their parents after clock-probe
          alignment.
      (b) FEDERATED METRICS — the front's /metrics exposes per-replica
          instance-labelled series and probe-derived per-replica lag
          that is 0 when converged, > 0 while the SIGKILLed follower is
          down (the publisher keeps appending), and back to 0 after the
          restarted follower catches up.
      (c) FLIGHT RECORDER — the front marking the killed follower
          unhealthy dumps its own ring AND broadcasts the trigger, so
          bundles with the SAME trigger id from every live process are
          on disk, each covering the kill window.
    """
    import signal as _signal

    from photon_ml_tpu.telemetry.distributed import (TRACE_HEADER,
                                                     merge_run_logs)

    root = os.path.join(tmp, "obsfleet")
    mdir = _fleet_save_model(root, seed=131, E=200)
    log_dir = os.path.join(root, "replog")
    logdir = os.path.join(root, "runlogs")
    flightdir = os.path.join(root, "flight")
    os.makedirs(logdir, exist_ok=True)
    common = ["--model-dir", mdir, "--port", "0", "--max-batch", "64",
              "--min-bucket", "4", "--replication-log", log_dir,
              "--flight-dir", flightdir]

    def runlog(name):
        return os.path.join(logdir, name + ".jsonl")

    pub_proc, pub_url, _ = _fleet_spawn(
        common + ["--replica", "--publish", "--enable-updates",
                  "--update-interval-ms", "10",
                  "--replica-state", os.path.join(root, "pub"),
                  "--run-log", runlog("pub")])
    f0_proc, f0_url, _ = _fleet_spawn(
        common + ["--replica", "--replica-poll-ms", "20",
                  "--replica-state", os.path.join(root, "f0"),
                  "--run-log", runlog("f0")])
    assert _fleet_wait_healthy(pub_url) and _fleet_wait_healthy(f0_url), \
        "fleet not healthy"
    front_proc, front_url, _ = _fleet_spawn(
        ["--front", "--replica-url", pub_url, "--replica-url", f0_url,
         "--port", "0", "--probe-interval-ms", "100",
         "--run-log", runlog("front"), "--flight-dir", flightdir])
    assert _fleet_wait_healthy(front_url), "front not healthy"

    rng = np.random.default_rng(137)
    n_score = 6 if smoke else 16
    score_ids = [f"{k:016x}" for k in range(1, n_score + 1)]
    for rid in score_ids:
        k = 2
        body = {"features": {
            "global": rng.normal(size=(k, 16)).tolist(),
            "per_user": rng.normal(size=(k, 8)).tolist()},
            "ids": {"userId": [f"u{rng.integers(0, 200)}"
                               for _ in range(k)]}}
        status, _ = _fleet_http(front_url, "/score", body,
                                headers={TRACE_HEADER: rid})
        assert status == 200, f"score http {status}"
    fb_rid = "feedf10f" * 2

    def feedback(rid=None, n=16):
        body = {"features": {
            "global": rng.normal(size=(n, 16)).tolist(),
            "per_user": rng.normal(size=(n, 8)).tolist()},
            "ids": {"userId": [f"u{rng.integers(0, 200)}"
                               for _ in range(n)]},
            "labels": (rng.uniform(size=n) < 0.5).astype(float).tolist()}
        return _fleet_http(front_url, "/feedback", body,
                           headers={TRACE_HEADER: rid} if rid else None)

    status, _ = feedback(fb_rid)
    assert status == 202, f"feedback http {status}"

    def front_lag(url):
        _, fed = _fleet_http(front_url, "/metrics.json")
        return (fed.get("fleet", {}).get("replicas", {})
                .get(url, {}))

    # converged: the follower applied the delta and reports zero lag
    converged = _fleetobs_wait(
        lambda: front_lag(f0_url).get("lag_records") == 0
        and front_lag(f0_url).get("applied_seq", 0) >= 2)
    fed_text_converged = _fleet_http_text(front_url, "/metrics")
    lag_at_converged = front_lag(f0_url)

    # -- kill the follower; the publisher keeps advancing ------------------
    f0_proc.send_signal(_signal.SIGKILL)
    f0_proc.wait(timeout=10)
    killed_rc = f0_proc.returncode
    kill_wall = time.time()
    for _ in range(2):
        feedback()
    # the front notices (probe failures) and the probe-derived lag for
    # the dead follower goes positive against the advancing head
    lagged = _fleetobs_wait(
        lambda: (front_lag(f0_url).get("ready") == 0
                 and (front_lag(f0_url).get("lag_records") or 0) > 0))
    lag_while_down = front_lag(f0_url)

    # flight bundles: the front's replica.unhealthy trigger fans out —
    # front + publisher bundles share ONE trigger id
    def unhealthy_bundles():
        out = []
        if not os.path.isdir(flightdir):
            return out
        for name in os.listdir(flightdir):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(flightdir, name)) as f:
                    b = json.load(f)
            except ValueError:
                continue
            if b.get("reason") == "replica.unhealthy":
                out.append(b)
        return out

    def correlated():
        by_id = {}
        for b in unhealthy_bundles():
            by_id.setdefault(b["trigger_id"], set()).add(b["proc"])
        return any(len(procs) >= 2 for procs in by_id.values())

    flight_correlated = _fleetobs_wait(correlated, timeout_s=30.0)
    bundles = unhealthy_bundles()
    bundle_procs = sorted({b["proc"] for b in bundles})
    # each bundle's ring window must cover the moments before the kill
    windows_cover = bool(bundles) and all(
        b.get("window_s") and b["window_s"][0] <= kill_wall + 5.0
        and b["window_s"][1] >= kill_wall - 60.0 for b in bundles)

    # -- restart the follower from its durable state; lag converges to 0 --
    f0b_proc, f0b_url, _ = _fleet_spawn(
        common + ["--replica", "--replica-poll-ms", "20",
                  "--replica-state", os.path.join(root, "f0"),
                  "--run-log", runlog("f0b")])
    # the follower restarts on a NEW ephemeral port, so the catch-up
    # check reads the restarted replica's own metric surface (lag_seq
    # back to 0 past the records appended while it was down)
    caught_up = _fleetobs_wait(
        lambda: _fleet_http(f0b_url, "/metrics.json")[1]
        .get("fleet", {}).get("lag_seq") == 0
        and _fleet_http(f0b_url, "/metrics.json")[1]
        .get("fleet", {}).get("applied_seq", 0)
        >= (lag_while_down.get("applied_seq") or 0) + 1)
    f0b_snap = _fleet_http(f0b_url, "/metrics.json")[1].get("fleet", {})

    # -- graceful drain everything, then merge --------------------------------
    for proc in (front_proc, pub_proc, f0b_proc):
        proc.send_signal(_signal.SIGTERM)
    rcs = []
    for proc in (front_proc, pub_proc, f0b_proc):
        try:
            proc.communicate(timeout=60)
            rcs.append(proc.returncode)
        except subprocess.TimeoutExpired:
            proc.kill()
            rcs.append(None)
    report = merge_run_logs(
        [runlog(n) for n in ("front", "pub", "f0", "f0b")],
        out_path=os.path.join(root, "fleet-trace.json"))
    reqs = report["requests"]
    score_trees = [reqs.get(rid) for rid in score_ids]
    fb_tree = reqs.get(fb_rid)
    score_trees_ok = bool(score_trees) and all(
        t is not None and t["connected"] and len(t["processes"]) >= 2
        for t in score_trees)
    fb_names = set(fb_tree["span_names"]) if fb_tree else set()
    feedback_tree_ok = bool(
        fb_tree and fb_tree["connected"]
        and len(fb_tree["processes"]) >= 3
        and {"front_request", "serve_request", "online_update",
             "replica_apply"} <= fb_names)
    containment = report["containment"]
    federated_ok = bool(
        converged and lag_at_converged.get("lag_records") == 0
        and lagged and (lag_while_down.get("lag_records") or 0) > 0
        and caught_up and f0b_snap.get("lag_seq") == 0
        and f'instance="{f0_url}"' in fed_text_converged
        and f'instance="{pub_url}"' in fed_text_converged
        and "photon_fleet_replica_lag_records" in fed_text_converged
        and "photon_front_requests_total" in fed_text_converged)
    flight_ok = bool(flight_correlated and len(bundle_procs) >= 2
                     and "front" in bundle_procs and windows_cover)
    return {
        "name": "fleetobs_fleet",
        "scoring_requests": len(score_ids),
        "merge_problems": report["problems"][:5],
        "merge_valid": not report["problems"],
        "processes_merged": len(report["processes"]),
        "clock_offsets": report["clock_offsets"],
        "score_trees_ok": score_trees_ok,
        "score_tree_sample": score_trees[0] if score_trees else None,
        "feedback_tree": fb_tree,
        "feedback_tree_ok": feedback_tree_ok,
        "containment": {k: v for k, v in containment.items()
                        if k != "violations"},
        "containment_violations": len(containment["violations"]),
        "containment_ok": containment["ok"],
        "killed_returncode": killed_rc,
        "lag_at_converged": lag_at_converged,
        "lag_while_down": lag_while_down,
        "lag_after_catchup": f0b_snap,
        "federated_ok": federated_ok,
        "flight_bundles": len(bundles),
        "flight_bundle_procs": bundle_procs,
        "flight_ok": flight_ok,
        "drain_returncodes": rcs,
        "fleet_ok": bool(not report["problems"] and score_trees_ok
                         and feedback_tree_ok and containment["ok"]
                         and federated_ok and flight_ok),
    }


def _fleetobs_health_flight_entry(smoke: bool, tmp: str) -> dict:
    """Gate: a health-gate trip dumps a flight bundle whose ring holds
    the triggering window — the health_gate_tripped event and the
    evaluation spans that led to it are IN the bundle, on disk, before
    any operator attaches."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry import flight as F

    dump_dir = os.path.join(tmp, "health_flight")
    rng = np.random.default_rng(139)
    trips = 0
    with telemetry.enabled(watch_compiles=False):
        with F.enabled(dump_dir, proc="serve"):
            svc, entities = _health_service(rng, smoke=True, health=True)
            cfg = svc.health.config
            try:
                for _ in range(2):  # calibrated warmup windows
                    f, i, y = _calibrated_batch(svc, rng, entities,
                                                cfg.window_labels)
                    svc.feedback(f, i, y)
                    svc.updater.flush()
                for _ in range(6):  # flipped labels until the gate trips
                    f, i, y = _calibrated_batch(svc, rng, entities,
                                                cfg.window_labels,
                                                flip=True)
                    svc.feedback(f, i, y)
                    svc.updater.flush()
                    trips = svc.metrics_snapshot()["health"]["gate_trips"]
                    if trips:
                        break
            finally:
                svc.close()
    bundles = []
    if os.path.isdir(dump_dir):
        for name in sorted(os.listdir(dump_dir)):
            if name.endswith(".json"):
                with open(os.path.join(dump_dir, name)) as f:
                    bundles.append(json.load(f))
    health_bundles = [b for b in bundles
                      if b["reason"] == "health.gate_trip"]
    has_trip_event = any(
        r.get("name") == "health_gate_tripped"
        for b in health_bundles for r in b["records"])
    has_eval_span = any(
        r.get("kind") == "span" and r.get("name") == "health_evaluate"
        for b in health_bundles for r in b["records"])
    return {
        "name": "fleetobs_health_flight",
        "gate_trips": trips,
        "bundles": len(bundles),
        "health_bundles": len(health_bundles),
        "bundle_records": (len(health_bundles[0]["records"])
                           if health_bundles else 0),
        "trip_event_in_bundle": has_trip_event,
        "evaluate_span_in_bundle": has_eval_span,
        "health_flight_ok": bool(trips >= 1 and health_bundles
                                 and has_trip_event and has_eval_span),
    }


def _fleetobs_overhead_entry(smoke: bool, tmp: str) -> dict:
    """Gate: armed fleet observability (tracer + flight ring + per-
    request server_span context) costs <= 1.1x the disarmed scoring p99,
    with ZERO fresh XLA traces armed and disarmed.  Alternating
    disarmed/armed rounds, best p99 per arm (single-core noise
    hygiene)."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry import distributed
    from photon_ml_tpu.telemetry import flight as F

    rng = np.random.default_rng(149)
    svc, entities = _health_service(rng, smoke=smoke, health=False,
                                    updates=False, E=200)
    n_requests = 200 if smoke else 1000
    rows = 4
    requests = []
    for _ in range(n_requests):
        requests.append((
            {"global": rng.normal(size=(rows, 16)),
             "per_user": rng.normal(size=(rows, 8))},
            {"userId": np.asarray(
                [entities[rng.integers(0, len(entities))]
                 for _ in range(rows)], dtype=object)}))

    def one_round(armed):
        lat = []
        for k, (feats, ids) in enumerate(requests):
            if armed:
                t0 = time.perf_counter()
                with distributed.server_span("serve_request",
                                             {"X-Photon-Trace":
                                              f"{k:016x}"},
                                             path="/score"):
                    svc.score(feats, ids)
                lat.append(time.perf_counter() - t0)
            else:
                t0 = time.perf_counter()
                svc.score(feats, ids)
                lat.append(time.perf_counter() - t0)
        return float(np.percentile(lat, 99))

    try:
        for feats, ids in requests[:32]:
            svc.score(feats, ids)           # warm every bucket
        dis_p99, arm_p99 = [], []
        fresh_disarmed = fresh_armed = 0
        rounds = 2 if smoke else 3
        for _ in range(rounds):
            with _trace_counting() as tc:
                dis_p99.append(one_round(False))
            fresh_disarmed += tc.count
            with telemetry.enabled(watch_compiles=False):
                with F.enabled(None, proc="serve"):
                    with _trace_counting() as tc:
                        arm_p99.append(one_round(True))
            fresh_armed += tc.count
    finally:
        svc.close()
    best_dis, best_arm = min(dis_p99), min(arm_p99)
    ratio = best_arm / best_dis if best_dis > 0 else float("inf")
    gated = not smoke
    out = {
        "name": "fleetobs_overhead",
        "requests_per_round": n_requests, "rounds": rounds,
        "disarmed_p99_ms": [round(1e3 * v, 3) for v in dis_p99],
        "armed_p99_ms": [round(1e3 * v, 3) for v in arm_p99],
        "p99_ratio_armed_vs_disarmed": round(ratio, 3),
        "ratio_gate": 1.1,
        "ratio_gated": gated,
        "fresh_traces_disarmed": fresh_disarmed,
        "fresh_traces_armed": fresh_armed,
        "zero_traces_ok": fresh_disarmed == 0 and fresh_armed == 0,
    }
    if not gated:
        out["ratio_gate_waived"] = (
            "smoke mode on shared-core CI: the p99 ratio is measured "
            "and reported; the full bench run gates it at 1.1x")
    out["overhead_ok"] = bool(out["zero_traces_ok"]
                              and (ratio <= 1.1 or not gated))
    return out


def fleetobs_bench(out_path="BENCH_fleetobs.json", smoke=False,
                   max_wall=None):
    """Fleet-observability gate (--fleetobs): (a) a front-routed scoring
    request and a feedback -> delta -> replica-apply flow each render as
    ONE connected span tree in the merged Perfetto export, children
    inside parents after clock alignment; (b) the front's federated
    /metrics exposes per-replica instance-labelled series and per-replica
    lag that goes 0 -> >0 (follower SIGKILLed, publisher advancing) ->
    0 (restart + catch-up); (c) flight-recorder bundles from the injected
    replica crash (fleet-correlated trigger id) and from a health-gate
    trip contain the triggering window; (d) armed observability <= 1.1x
    disarmed scoring p99 (full runs; reported in smoke) with zero fresh
    XLA traces armed and disarmed.  `value` is the armed/disarmed p99
    ratio."""
    import tempfile

    import jax
    jax.config.update("jax_enable_x64", True)
    t0 = time.perf_counter()
    entries = []
    truncated = []
    with tempfile.TemporaryDirectory() as tmp:
        legs = [
            ("fleetobs_fleet", _fleetobs_fleet_entry),
            ("fleetobs_health_flight", _fleetobs_health_flight_entry),
            ("fleetobs_overhead", _fleetobs_overhead_entry),
        ]
        for name, fn in legs:
            if max_wall is not None and \
                    time.perf_counter() - t0 > max_wall:
                truncated.append(name)
                continue
            entries.append(fn(smoke, tmp))
    by_name = {e["name"]: e for e in entries}
    fleet = by_name.get("fleetobs_fleet", {})
    health = by_name.get("fleetobs_health_flight", {})
    overhead = by_name.get("fleetobs_overhead", {})
    gates = {
        "merge_valid": fleet.get("merge_valid"),
        "score_trees_ok": fleet.get("score_trees_ok"),
        "feedback_tree_ok": fleet.get("feedback_tree_ok"),
        "containment_ok": fleet.get("containment_ok"),
        "federated_ok": fleet.get("federated_ok"),
        "flight_ok": fleet.get("flight_ok"),
        "health_flight_ok": health.get("health_flight_ok"),
        "zero_traces_ok": overhead.get("zero_traces_ok"),
        "overhead_ok": overhead.get("overhead_ok"),
    }
    result = {
        "metric": "fleetobs_armed_vs_disarmed_scoring_p99_ratio",
        "value": overhead.get("p99_ratio_armed_vs_disarmed"),
        "unit": "x",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            **gates,
            "all_ok": all(bool(v) for v in gates.values()),
            "truncated": truncated or False,
            "suite_wall_s": round(time.perf_counter() - t0, 1),
        },
    }
    _embed_telemetry(result)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)
    return result


# --------------------------------------------------------------------------

def warm_ref_cache():
    """Compute every GLM config's float64 CPU reference (optimum + solve
    time) OUTSIDE the measured suite, so bench runs always serve the
    scipy references — including their wall-clock — from cache.  Safe to
    re-run: entries that already carry ref_s are skipped."""
    from photon_ml_tpu.data.synthetic_bench import (make_a1a_like,
                                                    make_wide_sparse_logistic)

    def ensure(task, x, y, data_seed, l1, l2, bounds, label):
        key = (f"scipy:{task}:seed{data_seed}:{x.shape[0]}x{x.shape[1]}"
               f":l1={l1}:l2={l2}:box={bounds}"
               f":fp={_data_fingerprint(x, y)}")
        cached = _ref_cache_get_raw(key)
        if cached is not None and "ref_s" in cached:
            _log(f"warm-ref: {label} already warm (ref_s={cached['ref_s']})")
            return
        t0 = time.perf_counter()
        _, ref_nll = scipy_ref(task, _as_f64(x), y.astype(np.float64),
                               l1=l1, l2=l2, bounds=bounds)
        ref_s = time.perf_counter() - t0
        if cached is not None and abs(ref_nll - cached["ref_nll"]) > \
                1e-6 * abs(cached["ref_nll"]):
            _log(f"warm-ref: WARNING {label} recomputed optimum "
                 f"{ref_nll} != cached {cached['ref_nll']}")
        _ref_cache_put_raw(key, {"ref_nll": ref_nll,
                                 "ref_s": round(ref_s, 2)})
        _log(f"warm-ref: {label} solved in {ref_s:.1f}s")

    # config 1
    x, y = make_a1a_like(max(int(1024 * _SCALE), 1), "logistic", seed=42)
    ensure("logistic_regression", x, y, 42, 0.0, 1.0, None, "c1 logistic l2")
    # config 2
    for task_key, task in (("linear", "linear_regression"),
                           ("poisson", "poisson_regression")):
        x, y = make_a1a_like(max(int(256 * _SCALE), 1), task_key, seed=52)
        ensure(task, x, y, 52, 0.05, 0.05, None, f"c2 {task_key} en")
        ensure(task, x, y, 52, 0.1, 0.0, None, f"c2 {task_key} l1")
        ensure(task, x, y, 52, 0.0, 1.0, None, f"c2 {task_key} l2")
    # config 3
    x, y = make_a1a_like(max(int(256 * _SCALE), 1), "hinge", seed=62)
    ensure("smoothed_hinge_loss_linear_svm", x, y, 62, 0.0, 1.0,
           (-0.5, 0.5), "c3 hinge box")
    # config 6
    n = max(int(200_000 * _SCALE), 2000)
    x, y = make_wide_sparse_logistic(n, d=250_000, nnz=64, seed=77)
    ensure("logistic_regression", x, y, 77, 0.0, 1.0, None, "c6 wide sparse")


def measure_dispatch_floor(reps: int = 12) -> dict:
    """Per-dispatch overhead of the device link: one tiny jitted op, timed
    dispatch->readback with salted inputs (the tunnel memoizes bit-identical
    executions).  GAME steady-state phase spans sit on a few multiples of
    this floor (VERDICT r4 weak #6) — reporting it lets a reader split
    tunnel latency from compute in every phase table."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda v: (v * 1.0000001).sum())
    base = (time.time_ns() % 997) * 1e-9
    # distinct inputs prepared BEFORE timing: the loop then measures exactly
    # one program dispatch + one scalar readback per rep
    xs = [jnp.full((8,), base + 1e-9 * r, jnp.float32) for r in range(reps)]
    float(f(xs[0]))  # compile
    times = []
    for x in xs:
        t0 = time.perf_counter()
        float(f(x))
        times.append(time.perf_counter() - t0)
    times.sort()
    return {"median_s": round(times[len(times) // 2], 4),
            "min_s": round(times[0], 4), "max_s": round(times[-1], 4),
            "reps": reps}


# --------------------------------------------------------------------------
# --store: tiered entity store (photon_ml_tpu/store/) — serve 10M+ entity
# models on a ~1M-entity device hot-tier budget
# --------------------------------------------------------------------------

def _store_model(rng, E, d_g, d_u, dtype=np.float32):
    """Synthetic GAME model with INTEGER 0..E-1 entity ids — the store's
    identity fast path: no E-entry python dict anywhere, so E=10M is a
    160MB table, not a gigabyte of hash map."""
    import jax.numpy as jnp

    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import model_for_task
    fe = FixedEffectModel(
        model_for_task("logistic_regression", Coefficients(
            jnp.asarray(rng.normal(size=d_g).astype(dtype)))), "global")
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type="logistic_regression",
        coefficients=jnp.asarray(
            rng.standard_normal((E, d_u), dtype=np.float32).astype(dtype)),
        entity_ids=np.arange(E, dtype=np.int64),
        projection=None, global_dim=d_u)
    return GameModel({"fixed": fe, "perUser": re}, "logistic_regression")


def _store_traffic(rng, n, E, head, p_head, d_g, d_u, dtype=np.float32,
                   tail_conc=4.0):
    """One request batch: p_head of the ids from the hot working set,
    the rest from a zipf-like tail over ALL E entities (`u^tail_conc`
    skews the tail toward its own head the way real user traffic does —
    the host warm tier earns its keep on the repeated part, and the
    genuinely-rare part faults segments off the cold tier)."""
    feats = {"global": rng.standard_normal((n, d_g)).astype(dtype),
             "per_user": rng.standard_normal((n, d_u)).astype(dtype)}
    tail = rng.random(n) >= p_head
    ids = rng.integers(0, head, size=n)
    k = int(tail.sum())
    if k:
        ids[tail] = np.minimum(
            (E * rng.random(k) ** tail_conc).astype(np.int64), E - 1)
    return feats, {"userId": ids}


def _store_prewarm(st, n: int) -> None:
    """Pin rows [0, n) hot in overlay-sized chunks + one forced flush."""
    step = st.overlay_rows
    for lo in range(0, n, step):
        st.lookup_slots(np.arange(lo, min(lo + step, n)))
    st.promote_pending()


def _store_serving_entry(smoke: bool, tmp: str) -> dict:
    """THE gate: a synthetic 10M-entity model served on a ~1M-entity
    hot-tier budget at p99 <= 2x the all-resident scorer with >= 90%
    hot hit rate.  Both sides run the identical compiled programs; the
    all-resident side pins every row hot (preload_all), the budgeted
    side promotes misses through warm/cold."""
    import jax

    from photon_ml_tpu.serving import CompiledScorer
    from photon_ml_tpu.store import StoreConfig

    rng = np.random.default_rng(14)
    d_g, d_u = 8, 4
    if smoke:
        E, hot, head = 250_000, 32_768, 8_000
        seg_rows, warm_segs, flush = 16_384, 12, 4_096
        n_warm_req, n_meas, batch = 60, 120, 512
    else:
        # 10M entities, a 1M-row device hot tier, a ~145MB host warm
        # tier (DRAM is the hierarchy's second tier — Snap ML's shape:
        # the DEVICE budget is the scarce one; the PalDB analog likewise
        # kept every entity host-local), and the full durable table cold
        # on disk
        E, hot, head = 10_000_000, 1 << 20, 150_000
        seg_rows, warm_segs, flush = 16_384, 550, 16_384
        n_warm_req, n_meas, batch = 150, 600, 512
    p_head = 0.97
    model = _store_model(rng, E, d_g, d_u)

    def build(hot_rows, sub):
        t0 = time.perf_counter()
        scorer = CompiledScorer(
            model, max_batch=batch, min_bucket=batch,
            store=StoreConfig(hot_rows=hot_rows, warm_segments=warm_segs,
                              seg_rows=seg_rows, overlay_rows=batch,
                              flush_rows=flush),
            store_dir=os.path.join(tmp, sub))
        scorer.warmup()
        return scorer, time.perf_counter() - t0

    def drive(scorer, prewarm_head):
        st = scorer.entity_store("perUser")
        if prewarm_head == "all":
            st.preload_all()
        else:
            # operator pre-warm: pin the known-hot working set
            _store_prewarm(st, prewarm_head)
        r = np.random.default_rng(99)
        for _ in range(n_warm_req):     # LFU/warm stabilization
            feats, ids = _store_traffic(r, batch, E, head, p_head,
                                        d_g, d_u)
            scorer.score(feats, ids)
        # best-of-reps clean windows (the --online latency methodology:
        # a 1-core shared box injects multi-ms scheduler noise into any
        # single window); pending promotions drain BEFORE each window so
        # the amortized flush lands between windows, the way a production
        # deployment paces it off-peak
        import gc
        windows = []
        for _rep in range(3):
            st.promote_pending()
            gc.collect()        # keep collector pauses out of the window
            before = st.stats.snapshot()
            times = []
            for _ in range(n_meas):
                feats, ids = _store_traffic(r, batch, E, head, p_head,
                                            d_g, d_u)
                t0 = time.perf_counter()
                scorer.score(feats, ids)
                times.append(time.perf_counter() - t0)
            after = st.stats.snapshot()
            times.sort()
            d = {k: after[k] - before[k] for k in after}
            windows.append({
                "p50_ms": round(1e3 * times[len(times) // 2], 3),
                "p99_ms": round(1e3 * times[int(len(times) * 0.99)], 3),
                "window_counters": d,
            })
        best = min(windows, key=lambda w: w["p99_ms"])
        d = best["window_counters"]
        lookups = d["hot_hits"] + d["warm_hits"] + d["cold_misses"]
        return {
            "p50_ms": best["p50_ms"], "p99_ms": best["p99_ms"],
            "requests": n_meas, "rows_per_request": batch,
            "reps_p99_ms": [w["p99_ms"] for w in windows],
            "window_counters": d,
            "hit_rate": round(d["hot_hits"] / lookups, 4) if lookups
            else None,
            "residency": {k: v for k, v in st.residency().items()
                          if not isinstance(v, dict)},
        }

    resident_scorer, res_build_s = build(E, "resident")
    resident = drive(resident_scorer, "all")
    del resident_scorer
    budget_scorer, bud_build_s = build(hot, "budgeted")
    budgeted = drive(budget_scorer, head)
    budget_scorer.flush_stores()
    del budget_scorer
    import gc
    gc.collect()
    p99_ratio = (budgeted["p99_ms"] / resident["p99_ms"]
                 if resident["p99_ms"] else None)
    latency_ok = p99_ratio is not None and p99_ratio <= 2.0
    hit_ok = (budgeted["hit_rate"] is not None
              and budgeted["hit_rate"] >= 0.90)
    return {
        "name": "store_serving",
        "entities": E, "hot_rows": hot, "d_user": d_u,
        "hot_fraction": round(hot / E, 4),
        "head_entities": head, "p_head": p_head,
        "build_s": {"resident": round(res_build_s, 1),
                    "budgeted": round(bud_build_s, 1)},
        "resident": resident, "budgeted": budgeted,
        "p99_ratio_vs_all_resident": (round(p99_ratio, 3)
                                      if p99_ratio else None),
        "latency_ok": latency_ok, "hit_rate_ok": hit_ok,
        "serving_ok": latency_ok and hit_ok,
    }


def _store_delta_entry(smoke: bool, tmp: str) -> dict:
    """Gate: online delta swaps landing concurrently in hot AND warm
    tiers under live scoring traffic, with bit-exact rollback (the
    logical table returns to the exact pre-delta bytes) and a durable
    round trip (flush + reopen reproduces the post-delta state)."""
    import threading

    from photon_ml_tpu.online.delta import CoordinateDelta, ModelDelta
    from photon_ml_tpu.serving import CompiledScorer
    from photon_ml_tpu.serving.registry import ModelRegistry
    from photon_ml_tpu.store import StoreConfig, TieredEntityStore

    rng = np.random.default_rng(23)
    d_g, d_u = 8, 4
    E = 20_000 if smoke else 120_000
    hot = 2_048 if smoke else 8_192
    model = _store_model(rng, E, d_g, d_u, dtype=np.float64)
    scorer = CompiledScorer(
        model, max_batch=128, min_bucket=128,
        store=StoreConfig(hot_rows=hot, warm_segments=4,
                          seg_rows=max(E // 16, 1), overlay_rows=128,
                          flush_rows=256),
        store_dir=os.path.join(tmp, "delta"))
    scorer.warmup()
    registry = ModelRegistry(lambda d, v: scorer)
    registry.install(scorer, "v1")
    st = scorer.entity_store("perUser")
    # make a head hot so deltas land in BOTH tiers
    _store_prewarm(st, hot // 2)
    pre = st.full_table().copy()
    stop = threading.Event()
    errors = []

    def score_loop():
        r = np.random.default_rng(7)
        while not stop.is_set():
            feats, ids = _store_traffic(r, 128, E, hot // 2, 0.9,
                                        d_g, d_u, dtype=np.float64)
            try:
                scorer.score(feats, ids)
            except Exception as e:  # pragma: no cover
                errors.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=score_loop, daemon=True)
    t.start()
    hot_rows_touched = warm_rows_touched = 0
    n_deltas = 6 if smoke else 12
    try:
        for seq in range(1, n_deltas + 1):
            # half the rows from the hot head, half from the cold tail
            rows = np.unique(np.concatenate([
                rng.integers(0, hot // 2, size=12),
                rng.integers(hot // 2, E, size=12)]))
            prior = np.asarray(scorer.gather_rows("perUser", rows))
            vals = rng.normal(size=(len(rows), d_u))
            out = registry.apply_delta(ModelDelta(
                base_version="v1", seq=seq, coordinates={
                    "perUser": CoordinateDelta(rows=rows, values=vals,
                                               prior=prior)}))
            assert out["delta_seq"] == seq
            in_hot = int((np.asarray(rows) < hot // 2).sum())
            hot_rows_touched += in_hot
            warm_rows_touched += len(rows) - in_hot
        post = st.full_table().copy()
        changed = int((post != pre).any(axis=1).sum())
        # delta-aware rollback UNDER live scoring traffic
        registry.rollback()            # newest-first
        rollback_exact = bool(np.array_equal(st.full_table(), pre))
    finally:
        stop.set()
        t.join(timeout=5)
    # durable round trip (quiesced: concurrent spill write-backs done):
    # after flush the cold directory alone reproduces the logical table
    st.flush()
    reopened = TieredEntityStore.open(os.path.join(tmp, "delta",
                                                   "perUser"))
    durable_exact = bool(np.array_equal(reopened.full_table(),
                                        st.full_table()))
    return {
        "name": "store_delta",
        "entities": E, "hot_rows": hot, "deltas": n_deltas,
        "delta_rows_hot_tier": hot_rows_touched,
        "delta_rows_warm_tier": warm_rows_touched,
        "rows_changed_by_deltas": changed,
        "scoring_errors": errors[:3],
        "durable_round_trip_exact": durable_exact,
        "rollback_bit_exact": rollback_exact,
        "delta_ok": (rollback_exact and durable_exact and not errors
                     and hot_rows_touched > 0 and warm_rows_touched > 0),
    }


def _store_training_entry(smoke: bool) -> dict:
    """Gate: a budgeted GAME fit whose residency rotation runs through
    the store's block handles matches the all-resident f64 objective
    history <= 1e-10."""
    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameEstimator, GameTrainingConfig,
                                    GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (RegularizationContext,
                                     RegularizationType)

    L2 = RegularizationContext(RegularizationType.L2)
    rng = np.random.default_rng(31)
    n = 3_000 if smoke else 12_000
    num_users = 60 if smoke else 300
    d_g, d_u = 12, 4
    xg = rng.normal(size=(n, d_g)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_u)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    z = xg @ rng.normal(size=d_g) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(num_users, d_u))[users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ds = build_game_dataset(y, {"global": xg, "per_user": xu},
                            entity_ids={"userId": users.astype(str)})
    rows = np.arange(n)
    train = ds.subset(rows[: int(n * 0.9)])
    val = ds.subset(rows[int(n * 0.9):])

    def config(budget=None):
        return GameTrainingConfig(
            task_type="logistic_regression",
            coordinates={
                "fixed": FixedEffectCoordinateConfig(
                    "global", GLMOptimizationConfig(
                        regularization=L2, regularization_weight=0.1)),
                "perUser": RandomEffectCoordinateConfig(
                    "userId", "per_user", GLMOptimizationConfig(
                        regularization=L2, regularization_weight=1.0)),
            },
            updating_sequence=["fixed", "perUser"],
            num_outer_iterations=3,
            hbm_budget_bytes=budget)

    t0 = time.perf_counter()
    resident = GameEstimator(config()).fit(train, val)
    resident_s = time.perf_counter() - t0
    acct = resident.residency
    data_bytes = acct["resident_block_total"] + acct["flat_vector_bytes"]
    fe_bytes = acct["resident_block_bytes"]["fixed"]
    # above the FE shard (no auto-stream), below the total (rotation on)
    budget = max(int(data_bytes * 0.8),
                 int((fe_bytes + acct["flat_vector_bytes"]) * 1.05))
    t0 = time.perf_counter()
    budgeted = GameEstimator(config(budget=budget)).fit(train, val)
    budgeted_s = time.perf_counter() - t0
    b_acct = budgeted.residency
    gap = float(np.max(np.abs(
        np.asarray(budgeted.objective_history)
        - np.asarray(resident.objective_history))
        / np.maximum(np.abs(np.asarray(resident.objective_history)),
                     1e-300)))
    store = b_acct["store"]
    return {
        "name": "store_training",
        "rows": n, "users": num_users,
        "budget_bytes": budget, "data_bytes": data_bytes,
        "evict_rotation_active": bool(b_acct["evict_inactive"]),
        "evictions": b_acct["evictions"],
        "store_fetches": store["fetches"],
        "store_evictions": store["evictions"],
        "resident_fit_s": round(resident_s, 2),
        "budgeted_fit_s": round(budgeted_s, 2),
        "objective_history_max_rel_gap": gap,
        "parity_gate": 1e-10,
        "training_ok": (gap <= 1e-10 and b_acct["evictions"] > 0
                        and store["fetches"] > 0),
    }


def _store_traces_entry(smoke: bool, tmp: str) -> dict:
    """Gate: ZERO fresh XLA traces across steady-state fetch / stage /
    promote / spill / delta-swap on the SERVING path and across a warm
    budgeted refit (rotation evicting + re-fetching) on the TRAINING
    path."""
    from photon_ml_tpu.online.delta import CoordinateDelta, ModelDelta
    from photon_ml_tpu.serving import CompiledScorer
    from photon_ml_tpu.serving.registry import ModelRegistry
    from photon_ml_tpu.store import StoreConfig

    rng = np.random.default_rng(47)
    d_g, d_u = 8, 4
    E, hot = 30_000, 1_024
    model = _store_model(rng, E, d_g, d_u)
    scorer = CompiledScorer(
        model, max_batch=128, min_bucket=128,
        store=StoreConfig(hot_rows=hot, warm_segments=2,
                          seg_rows=2_048, overlay_rows=128,
                          flush_rows=128),
        store_dir=os.path.join(tmp, "traces"))
    scorer.warmup()
    registry = ModelRegistry(lambda d, v: scorer)
    registry.install(scorer, "v1")
    st = scorer.entity_store("perUser")

    def serving_round(seed, seq):
        r = np.random.default_rng(seed)
        feats, ids = _store_traffic(r, 128, E, hot // 2, 0.7, d_g, d_u)
        scorer.score(feats, ids)
        rows = np.unique(r.integers(0, E, size=16))
        prior = np.asarray(scorer.gather_rows("perUser", rows))
        registry.apply_delta(ModelDelta(
            base_version="v1", seq=seq, coordinates={
                "perUser": CoordinateDelta(
                    rows=rows, values=r.normal(
                        size=(len(rows), d_u)).astype(np.float32),
                    prior=prior)}))

    serving_round(0, 1)            # settle device_put paths
    before = st.stats.snapshot()
    with _trace_counting() as serve_counter:
        for s in range(1, 6):
            serving_round(s, s + 1)
    d = {k: v - before[k] for k, v in st.stats.snapshot().items()}
    training = _store_training_traces(smoke)
    return {
        "name": "store_traces",
        "serving_fresh_traces": serve_counter.count,
        "serving_window_counters": d,
        "serving_exercised": bool(d["promotions"] > 0
                                  and d["warm_hits"] + d["cold_misses"] > 0
                                  and d["spills"] > 0),
        **training,
        "zero_traces_ok": (serve_counter.count == 0
                           and training["training_fresh_traces"] == 0
                           and d["promotions"] > 0 and d["spills"] > 0),
    }


def _store_training_traces(smoke: bool) -> dict:
    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameEstimator, GameTrainingConfig,
                                    GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (RegularizationContext,
                                     RegularizationType)

    L2 = RegularizationContext(RegularizationType.L2)
    rng = np.random.default_rng(53)
    n, num_users, d_g, d_u = 1_500, 30, 12, 4
    xg = rng.normal(size=(n, d_g)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_u)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    y = (rng.uniform(size=n) < 0.5).astype(float)
    ds = build_game_dataset(y, {"global": xg, "per_user": xu},
                            entity_ids={"userId": users.astype(str)})
    rows = np.arange(n)
    train, val = ds.subset(rows[:1350]), ds.subset(rows[1350:])

    def config(budget=None):
        return GameTrainingConfig(
            task_type="logistic_regression",
            coordinates={
                "fixed": FixedEffectCoordinateConfig(
                    "global", GLMOptimizationConfig(
                        regularization=L2, regularization_weight=0.1)),
                "perUser": RandomEffectCoordinateConfig(
                    "userId", "per_user", GLMOptimizationConfig(
                        regularization=L2, regularization_weight=1.0)),
            },
            updating_sequence=["fixed", "perUser"],
            num_outer_iterations=2,
            hbm_budget_bytes=budget)

    resident = GameEstimator(config()).fit(train, val)
    acct = resident.residency
    data_bytes = acct["resident_block_total"] + acct["flat_vector_bytes"]
    fe_bytes = acct["resident_block_bytes"]["fixed"]
    budget = max(int(data_bytes * 0.8),
                 int((fe_bytes + acct["flat_vector_bytes"]) * 1.05))
    GameEstimator(config(budget=budget)).fit(train, val)   # warm
    with _trace_counting() as counter:
        res = GameEstimator(config(budget=budget)).fit(train, val)
    return {"training_fresh_traces": counter.count,
            "training_evictions": res.residency["evictions"]}


def store_bench(out_path="BENCH_store.json", smoke=False, max_wall=None):
    """Tiered-entity-store gate (--store): (1) a synthetic 10M-entity
    model served on a ~1M-entity hot-tier budget at p99 <= 2x the
    all-resident scorer with >= 90% hot hit rate; (2) online delta swaps
    landing concurrently in hot AND warm tiers with bit-exact rollback
    and a durable round trip; (3) a budgeted GAME fit through the store
    matching the all-resident f64 objective history <= 1e-10; (4) zero
    fresh XLA traces across steady-state fetch/promote/spill on both the
    serving and training paths.  `value` is the budgeted scorer's
    steady-state p99 ratio vs all-resident."""
    import tempfile

    import jax
    jax.config.update("jax_enable_x64", True)   # f64 parity legs
    t0 = time.perf_counter()
    entries = []
    truncated = []
    with tempfile.TemporaryDirectory() as tmp:
        legs = [
            ("store_serving", lambda: _store_serving_entry(smoke, tmp)),
            ("store_delta", lambda: _store_delta_entry(smoke, tmp)),
            ("store_training", lambda: _store_training_entry(smoke)),
            ("store_traces", lambda: _store_traces_entry(smoke, tmp)),
        ]
        for name, fn in legs:
            if max_wall is not None and time.perf_counter() - t0 > max_wall:
                truncated.append(name)
                continue
            entries.append(fn())
    by_name = {e["name"]: e for e in entries}
    serving = by_name.get("store_serving", {})
    gates = {
        "serving_ok": serving.get("serving_ok"),
        "delta_ok": by_name.get("store_delta", {}).get("delta_ok"),
        "training_ok": by_name.get("store_training", {}).get("training_ok"),
        "zero_traces_ok": by_name.get("store_traces",
                                      {}).get("zero_traces_ok"),
    }
    # smoke runs under the tier-1 suite on shared CPUs: the latency half
    # of the serving gate is a smoke signal there, HARD on the committed
    # full run — same policy as --online / --health
    hard = ["delta_ok", "training_ok", "zero_traces_ok"]
    if not smoke:
        hard.append("serving_ok")
    result = {
        "metric": "store_p99_ratio_vs_all_resident",
        "value": serving.get("p99_ratio_vs_all_resident", 0.0),
        "unit": "x (budgeted hot tier / all-resident)",
        "detail": {
            "smoke": smoke,
            "entries": entries,
            **gates,
            "all_ok": all(bool(gates[g]) for g in hard),
            "hard_gates": hard,
            "truncated": truncated or False,
            "suite_wall_s": round(time.perf_counter() - t0, 1),
        },
    }
    _embed_telemetry(result)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)
    return result


def main(max_wall=None):
    import jax
    import logging
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(message)s")
    from photon_ml_tpu.utils.jax_cache import enable_persistent_cache
    enable_persistent_cache()
    dev = jax.devices()[0]
    dispatch_floor = measure_dispatch_floor()
    suite_t0 = time.perf_counter()
    configs = {}
    truncated = []
    runners = {"1": bench_config1, "2": bench_config2, "3": bench_config3,
               "4": bench_config4, "5": bench_config5, "6": bench_config6,
               "7": bench_config7}
    def cumulative():
        c1 = (configs.get("config1", {}).get("entries") or [{}])[0]
        parity = (c1["ref_nll"] / c1["final_nll"]
                  if c1.get("final_nll") else 0.0)
        gaps = [e.get("nll_rel_gap") for c in configs.values()
                for e in c.get("entries", [])
                if e.get("nll_rel_gap") is not None]
        out = {
            "metric": "a1a_logistic_lbfgs_l2_examples_per_sec_per_chip",
            "value": c1.get("examples_per_sec_per_chip", 0.0),
            "unit": "examples/sec/chip",
            "vs_baseline": round(parity, 6),
            "detail": {
                "device": str(getattr(dev, "device_kind", dev)),
                "dispatch_floor": dispatch_floor,
                "suite_wall_s": round(time.perf_counter() - suite_t0, 1),
                "max_abs_nll_rel_gap": (max(abs(g) for g in gaps) if gaps
                                        else None),
                "configs": configs,
            },
        }
        if truncated:
            # partial-but-complete result: the wall budget ran out, the
            # named configs were SKIPPED, and the process exits 0 — the
            # harness-timeout alternative (rc=124, JSON lost to a log tail)
            # is what BENCH_r05 suffered
            out["detail"]["truncated"] = truncated
            out["detail"]["max_wall_s"] = max_wall
        return _embed_telemetry(out)

    def write_cumulative():
        result = cumulative()
        tmp = "BENCH.json.tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=1)
        os.replace(tmp, "BENCH.json")
        print(json.dumps(result), flush=True)
        return result

    for key in _CONFIGS:
        key = key.strip()
        if key not in runners:
            continue
        if max_wall is not None and \
                time.perf_counter() - suite_t0 > max_wall:
            _log(f"--max-wall {max_wall}s exceeded; skipping config {key}")
            truncated.append(f"config{key}")
            continue
        try:
            t0 = time.perf_counter()
            entries = runners[key]()
            configs[f"config{key}"] = {
                "entries": entries,
                "wall_s": round(time.perf_counter() - t0, 1)}
        except Exception as e:  # keep the suite alive; report the failure
            configs[f"config{key}"] = {"error": f"{type(e).__name__}: {e}"}
        # the fingerprint memo pins each config's datasets (config 1 alone
        # is ~800MB); carrying them across configs pushed the 1-core host
        # into memory pressure and inflated later configs' host-side build
        # phases several-fold (r04: 9.6s coordinate builds that take 1.1s
        # standalone)
        _FP_CACHE.clear()
        import gc
        gc.collect()
        # one cumulative line per finished config: if the harness kills the
        # suite mid-run, the LAST stdout line is still a complete result
        # for everything finished so far.  The same dict also lands in
        # BENCH.json (atomic replace) because harness logs keep only the
        # TAIL of stdout — r04's config 1-5 results were lost to truncation
        write_cumulative()
    if truncated:
        # the skip decisions happen after the last finished config's write:
        # one more write records the truncated marker in the final JSON
        return write_cumulative()
    return cumulative()


def _parse_max_wall(argv):
    """--max-wall SECONDS (or env BENCH_MAX_WALL): suite wall budget.  When
    exceeded, remaining legs are SKIPPED, the partial JSON carries a
    "truncated" marker, and the process exits 0 — instead of the harness
    timeout killing the run at rc=124 with the JSON lost to a log tail."""
    if "--max-wall" in argv:
        return float(argv[argv.index("--max-wall") + 1])
    env = os.environ.get("BENCH_MAX_WALL")
    return float(env) if env else None


def _dispatch():
    if len(sys.argv) > 1 and sys.argv[1] == "--game-ref":
        _game_ref_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "--faults-child":
        _faults_child_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "--faults":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        faults_bench(*(paths[:1] or ["BENCH_faults.json"]), smoke=smoke,
                     max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--warm-ref-cache":
        warm_ref_cache()
    elif len(sys.argv) > 1 and sys.argv[1] == "--serve":
        serve_bench(*sys.argv[2:3])
    elif len(sys.argv) > 1 and sys.argv[1] == "--online":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        online_bench(*(paths[:1] or ["BENCH_online.json"]), smoke=smoke,
                     max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--fleet":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        fleet_bench(*(paths[:1] or ["BENCH_fleet.json"]), smoke=smoke,
                    max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--shards":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        shards_bench(*(paths[:1] or ["BENCH_shards.json"]), smoke=smoke,
                     max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--fleetobs":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        fleetobs_bench(*(paths[:1] or ["BENCH_fleetobs.json"]),
                       smoke=smoke,
                       max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--store":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        store_bench(*(paths[:1] or ["BENCH_store.json"]), smoke=smoke,
                    max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--health":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        health_bench(*(paths[:1] or ["BENCH_health.json"]), smoke=smoke,
                     max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--refit":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        refit_bench(*(paths[:1] or ["BENCH_refit.json"]), smoke=smoke,
                    max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--pipeline":
        pipeline_bench(*sys.argv[2:3])
    elif len(sys.argv) > 1 and sys.argv[1] == "--stream":
        smoke = "--smoke" in sys.argv[2:]
        paths = [a for a in sys.argv[2:] if not a.startswith("--")]
        stream_bench(*(paths[:1] or ["BENCH_stream.json"]), smoke=smoke)
    elif len(sys.argv) > 1 and sys.argv[1] == "--stoch":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        stoch_bench(*(paths[:1] or ["BENCH_stoch.json"]), smoke=smoke,
                    max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--admm":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        admm_bench(*(paths[:1] or ["BENCH_admm.json"]), smoke=smoke,
                   max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--sweep":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        sweep_bench(*(paths[:1] or ["BENCH_sweep.json"]), smoke=smoke,
                    max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--mesh":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        mesh_bench(*(paths[:1] or ["BENCH_mesh.json"]), smoke=smoke,
                   max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--inexact":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        inexact_bench(*(paths[:1] or ["BENCH_inexact.json"]), smoke=smoke,
                      max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--trace":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        trace_bench(*(paths[:1] or ["BENCH_trace.json"]), smoke=smoke,
                    max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--multihost":
        smoke = "--smoke" in sys.argv[2:]
        rest = sys.argv[2:]
        paths = [a for i, a in enumerate(rest) if not a.startswith("--")
                 and (i == 0 or rest[i - 1] != "--max-wall")]
        multihost_bench(*(paths[:1] or ["BENCH_multihost.json"]),
                        smoke=smoke,
                        max_wall=_parse_max_wall(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--smoke":
        smoke_bench(*sys.argv[2:3])
    else:
        main(max_wall=_parse_max_wall(sys.argv[1:]))


if __name__ == "__main__":
    # --trace-out TRACE.json works on EVERY bench mode: arm the telemetry
    # tracer around the whole invocation and export the timeline at exit
    # (bench legs that arm their own scoped tracer — --trace — replace it
    # for their scope; the export covers whatever finished last).
    _trace_out = None
    if "--trace-out" in sys.argv:
        _i = sys.argv.index("--trace-out")
        _trace_out = sys.argv[_i + 1]
        del sys.argv[_i:_i + 2]
        from photon_ml_tpu import telemetry as _telemetry
        _telemetry.install()
    try:
        _dispatch()
    finally:
        if _trace_out is not None:
            _telemetry.shutdown()
            _info = _telemetry.write_chrome_trace(_trace_out)
            print(f"trace written to {_trace_out} "
                  f"({_info['events']} events) — open at "
                  "https://ui.perfetto.dev", file=sys.stderr)
