"""Benchmark: GLM training throughput + loss parity on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": examples/sec/chip, "unit": ..., "vs_baseline": ...}

Config mirrors BASELINE config #1 (a1a-shaped logistic regression, LBFGS,
L2 — reference: examples/run_photon_ml_driver.sh); the dataset is a
seeded synthetic replica at a1a's exact shape x32 replicas (no network egress
to fetch the real file).  `vs_baseline` is loss parity: scipy_optimum_nll /
our_nll (1.0 == exact parity; the reference publishes no throughput numbers —
BASELINE.md — so parity is the baseline-anchored scalar).

examples/sec/chip counts one example per full data pass (LBFGS iteration
passes counted from the tracker), conservative: line-search extra value
passes are free in this accounting.
"""
from __future__ import annotations

import json
import time

import numpy as np


def make_a1a_like(replicas: int = 1024, seed: int = 42):
    """a1a: n=1605, d=123 binary features (+intercept)."""
    rng = np.random.default_rng(seed)
    n, d = 1605 * replicas, 124
    x = (rng.uniform(size=(n, d)) < 0.087).astype(np.float32)  # a1a density
    x[:, -1] = 1.0
    w = (rng.normal(size=d) * 0.7).astype(np.float32)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(np.float32)
    return x, y


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops import LOGISTIC, GLMObjective
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType, solve)

    x_np, y_np = make_a1a_like()
    n, d = x_np.shape
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)
    obj = GLMObjective(LOGISTIC, x, y)
    reg = RegularizationContext(RegularizationType.L2)
    cfg = OptimizerConfig(max_iterations=100, tolerance=1e-9)
    lam = 1.0

    run = jax.jit(lambda o, x0: solve(o, x0, cfg, reg, lam))
    res = jax.block_until_ready(run(obj, jnp.zeros(d, x.dtype)))  # compile+warm
    t0 = time.perf_counter()
    reps = 5
    for r in range(reps):
        # distinct x0 per rep: the accelerator tunnel memoizes identical
        # executions, so a repeated bit-identical call returns instantly
        x0 = jnp.full((d,), 1e-6 * (r + 1), x.dtype)
        res = jax.block_until_ready(run(obj, x0))
    dt = (time.perf_counter() - t0) / reps

    iters = int(res.iterations)
    examples_per_sec = n * iters / dt
    nll = float(res.value)

    # loss parity vs an independent float64 CPU optimum (pure numpy/scipy)
    from scipy.optimize import minimize
    xf, yf = x_np.astype(np.float64), y_np.astype(np.float64)

    def f(c):
        z = xf @ c
        l = np.logaddexp(0.0, -np.where(yf > 0.5, 1.0, -1.0) * z).sum() \
            + 0.5 * lam * c @ c
        s = 1 / (1 + np.exp(-z))
        g = xf.T @ (s - yf) + lam * c
        return l, g

    ref = minimize(f, np.zeros(d), jac=True, method="L-BFGS-B",
                   options={"ftol": 1e-15, "gtol": 1e-10, "maxiter": 3000})
    vs_baseline = float(ref.fun / nll)  # 1.0 == parity with reference optimum

    print(json.dumps({
        "metric": "a1a_logistic_lbfgs_l2_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs_baseline, 6),
        "detail": {"n": n, "d": d, "iterations": iters,
                   "wall_s": round(dt, 4), "final_nll": round(nll, 6),
                   "ref_nll": round(float(ref.fun), 6),
                   "nll_rel_gap": round(abs(nll - ref.fun) / abs(ref.fun), 9),
                   "device": str(jax.devices()[0])},
    }))


if __name__ == "__main__":
    main()
