"""Model-health tier tests (photon_ml_tpu/health/).

Covers the ISSUE 11 acceptance scenarios: streaming-calibration parity
against the batch `diagnostics/hl.py` oracle on identical replayed
traffic (f64), the drift detector's false-positive bound on stationary
traffic, the health-gate -> pause -> resume -> rollback lifecycle under
concurrent scoring with the runtime lock tracker armed, metric-surface
parity between the Prometheus text and JSON snapshot (the SNAPSHOT_PATHS
contract), the compile-count regression (warm serve+update loop with
health armed traces NOTHING new), and the `health.evaluate` fault site.
"""
import logging
import re
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import photon_ml_tpu  # noqa: F401  (conftest configures the backend)

from photon_ml_tpu.diagnostics.hl import hosmer_lemeshow
from photon_ml_tpu.health import (DriftDetector, HealthConfig, HealthMonitor,
                                  StreamingCalibration)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.online import OnlineUpdateConfig
from photon_ml_tpu.serving import ScoringService, ServingConfig
from photon_ml_tpu.serving.metrics import SNAPSHOT_PATHS, ServingMetrics
from photon_ml_tpu.utils import faults, locktrace

D_G, D_U, N_ENT = 6, 4, 30
TASK = "logistic_regression"


def _make_model(rng, coef_scale=1.0):
    fe = FixedEffectModel(
        model_for_task(TASK, Coefficients(
            jnp.asarray(coef_scale * rng.normal(size=D_G)))), "global")
    re_ = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=TASK,
        coefficients=jnp.asarray(coef_scale * rng.normal(size=(N_ENT, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(N_ENT)], dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re_}, TASK)


def _service(rng, *, health=None, updates=None, **kw):
    kw.setdefault("config", ServingConfig(max_batch=64, min_bucket=4))
    return ScoringService(model=_make_model(rng), health=health,
                          updates=updates, start_updater=False, **kw)


def _requests(rng, n, scale=1.0):
    feats = {"global": scale * rng.normal(size=(n, D_G)),
             "per_user": scale * rng.normal(size=(n, D_U))}
    ids = {"userId": np.asarray(
        [f"u{rng.integers(0, N_ENT)}" for _ in range(n)], dtype=object)}
    return feats, ids


def _calibrated_feedback(svc, rng, n, flip=False):
    """Labels drawn from the live model's own probabilities — perfectly
    calibrated by construction; `flip` inverts them (maximal
    miscalibration, the label-flip drift of the bench)."""
    feats, ids = _requests(rng, n)
    z = svc.registry.scorer.score(feats, ids).scores
    p = 0.5 * (1.0 + np.tanh(0.5 * z))
    y = (rng.uniform(size=n) < p).astype(float)
    if flip:
        y = 1.0 - y
    return feats, ids, y


# -- streaming calibration vs the batch oracle -------------------------------

def test_streaming_hl_matches_batch_oracle(rng):
    """ISSUE 11 satellite: identical replayed traffic through the
    streaming accumulator and through `diagnostics/hl.py` lands on the
    same chi^2 / p-value / per-bin counts in f64 (1e-12 — only float
    summation order differs)."""
    n, bins = 2000, 10
    p = rng.uniform(size=n)
    y = (rng.uniform(size=n) < p).astype(float)
    # dims such that the batch heuristic picks exactly `bins` bins:
    # by_data (~40 at n=2000) > bins, so min(by_data, dims+2) = dims+2
    report = hosmer_lemeshow(p, y, num_dimensions=bins - 2)
    assert report.degrees_of_freedom == bins - 2

    cal = StreamingCalibration(bins)
    for lo in range(0, n, 137):   # deliberately ragged chunking
        cal.update(p[lo:lo + 137], y[lo:lo + 137])
    win = cal.report()
    assert win.count == n
    assert win.chi_squared == pytest.approx(report.chi_squared, rel=1e-12)
    assert win.prob_at_chi_square == pytest.approx(
        report.prob_at_chi_square, rel=1e-12, abs=1e-15)
    assert win.p_value == pytest.approx(report.p_value, rel=1e-9, abs=1e-15)
    for b in range(bins):
        ref = report.bins[b]
        assert win.expected_pos[b] == pytest.approx(ref.expected_pos,
                                                    rel=1e-12, abs=1e-12)
        assert win.expected_neg[b] == pytest.approx(ref.expected_neg,
                                                    rel=1e-12, abs=1e-12)
        assert win.observed_pos[b] == ref.observed_pos
        assert win.observed_neg[b] == ref.observed_neg


def test_streaming_hl_chunking_invariant(rng):
    """Any chunking of the same stream produces the same verdict."""
    n = 1500
    p = rng.uniform(size=n)
    y = (rng.uniform(size=n) < 0.4).astype(float)
    outs = []
    for step in (1500, 251, 17):
        cal = StreamingCalibration(10)
        for lo in range(0, n, step):
            cal.update(p[lo:lo + step], y[lo:lo + step])
        outs.append(cal.take())
    assert outs[0].chi_squared == pytest.approx(outs[1].chi_squared,
                                                rel=1e-12)
    assert outs[1].chi_squared == pytest.approx(outs[2].chi_squared,
                                                rel=1e-12)
    # take() reset the accumulators: a fresh window starts at zero
    cal.update(p[:10], y[:10])
    assert cal.count == 10


def test_streaming_hl_flipped_labels_collapse_p_value(rng):
    n = 1000
    p = rng.uniform(size=n)
    y_cal = (rng.uniform(size=n) < p).astype(float)
    good, bad = StreamingCalibration(10), StreamingCalibration(10)
    good.update(p, y_cal)
    bad.update(p, 1.0 - y_cal)
    assert good.report().p_value > 1e-6
    assert bad.report().p_value < 1e-12


# -- drift detector -----------------------------------------------------------

def test_drift_stationary_false_positive_bound(rng):
    """ISSUE 11 satellite: 30 windows of stationary traffic stay well
    under the PSI/KS gates (the stationary leg of the bench gates the
    full service path; this bounds the detector itself)."""
    det = DriftDetector(bins=10, baseline_size=2048)
    det.observe(rng.normal(size=2048))
    assert det.baseline_ready
    worst_psi = worst_ks = 0.0
    for _ in range(30):
        det.observe(rng.normal(size=2048))
        win = det.take()
        worst_psi = max(worst_psi, win.psi)
        worst_ks = max(worst_ks, win.ks)
    assert worst_psi < 0.25, worst_psi
    assert worst_ks < 0.2, worst_ks


def test_drift_detects_covariate_shift(rng):
    det = DriftDetector(bins=10, baseline_size=2048)
    det.observe(rng.normal(size=2048))
    det.observe(1.5 + 1.2 * rng.normal(size=2048))   # shifted + widened
    win = det.take()
    assert win.psi > 0.25
    assert win.ks > 0.2


def test_drift_baseline_not_ready_yields_no_window(rng):
    det = DriftDetector(bins=10, baseline_size=256)
    det.observe(rng.normal(size=100))
    assert not det.baseline_ready
    assert det.take() is None
    det.observe(rng.normal(size=200))    # crosses the threshold mid-batch
    assert det.baseline_ready
    assert det.window_count == 44        # 300 - 256 landed in the window


# -- config -------------------------------------------------------------------

def test_health_config_roundtrip_and_validation():
    cfg = HealthConfig(window_labels=64, rollback_on=("calibration",),
                       psi_max=0.3)
    again = HealthConfig.from_dict(cfg.to_dict())
    assert again == cfg
    with pytest.raises(ValueError, match="unknown key"):
        HealthConfig.from_dict({"psi_threshold": 0.3})
    with pytest.raises(ValueError, match="unknown gate"):
        HealthConfig(rollback_on=("nonsense",))
    with pytest.raises(ValueError, match="must be >= 1"):
        HealthConfig(window_labels=0)


def test_serve_cli_health_config_flag(tmp_path):
    from photon_ml_tpu.cli.serve import build_parser
    args = build_parser().parse_args(
        ["--model-dir", "m", "--health-config", '{"psi_max": 0.5}'])
    from photon_ml_tpu.cli.train import _load_json_arg
    cfg = HealthConfig.from_dict(_load_json_arg(args.health_config))
    assert cfg.psi_max == 0.5
    f = tmp_path / "health.json"
    f.write_text('{"window_labels": 99}')
    cfg2 = HealthConfig.from_dict(_load_json_arg("@" + str(f)))
    assert cfg2.window_labels == 99


# -- the gate lifecycle -------------------------------------------------------

def _lifecycle_config(**kw):
    kw.setdefault("window_labels", 64)
    kw.setdefault("window_scores", 128)
    kw.setdefault("baseline_scores", 128)
    kw.setdefault("sustain_windows", 2)
    kw.setdefault("recovery_windows", 2)
    kw.setdefault("calibration_p_min", 1e-4)
    # drift gates off: the tiny windows this test uses would trip them
    # on sampling noise (the detector's own bound is tested above)
    kw.setdefault("psi_max", None)
    kw.setdefault("ks_max", None)
    return HealthConfig(**kw)


def test_health_gate_pause_resume_rollback_lifecycle(rng):
    """ISSUE 11 acceptance: a sustained calibration breach pauses the
    updater, flips /healthz to degraded, and (rollback_on) restores the
    pre-delta rows — all without disturbing concurrent scoring; sustained
    recovery resumes updates.  The runtime lock tracker is ARMED for the
    whole lifecycle and cross-validated against the static graph."""
    from photon_ml_tpu.analysis.concurrency import lock_order_edges
    import os
    pkg_dir = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))
    with locktrace.enabled() as tracker:
        svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8),
                       health=_lifecycle_config(
                           rollback_on=("calibration",)))
        stop = threading.Event()
        errors = []

        def scorer_loop(seed):
            r = np.random.default_rng(seed)
            while not stop.is_set():
                feats, ids = _requests(r, 3)
                try:
                    svc.score(feats, ids)
                except Exception as e:  # pragma: no cover
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=scorer_loop, args=(s,),
                                    daemon=True) for s in (11, 13)]
        for t in threads:
            t.start()
        try:
            table0 = np.asarray(
                svc.registry.scorer.re_table("perUser")).copy()
            # phase 1: calibrated feedback -> deltas publish, all gates ok
            for s in range(2):
                f, i, y = _calibrated_feedback(
                    svc, np.random.default_rng(20 + s), 64)
                svc.feedback(f, i, y)
                svc.updater.flush()
            assert svc.registry.pending_deltas() >= 1
            assert svc.healthz()["status"] == "ok"
            assert not svc.updater.paused
            # phase 2: label-flip -> 2 consecutive breaches trip the gate
            for s in range(2):
                f, i, y = _calibrated_feedback(
                    svc, np.random.default_rng(30 + s), 64, flip=True)
                svc.feedback(f, i, y)
            hz = svc.healthz()
            assert hz["status"] == "degraded"
            assert hz["health"]["gates"]["calibration"]["tripped"] is True
            assert svc.updater.paused
            assert "health" in (svc.updater.pause_reason or "")
            # the rollback restored the exact pre-delta rows
            assert svc.registry.pending_deltas() == 0
            assert np.array_equal(
                np.asarray(svc.registry.scorer.re_table("perUser")), table0)
            assert hz["health"]["rollbacks"] == 1
            # paused updater buffers but does not publish
            f, i, y = _calibrated_feedback(svc, np.random.default_rng(40),
                                           32)
            svc.feedback(f, i, y)
            assert svc.updater.flush()["deltas"] == 0
            # phase 3: clean windows -> recovery resumes updates
            for s in range(2):
                f, i, y = _calibrated_feedback(
                    svc, np.random.default_rng(50 + s), 64)
                svc.feedback(f, i, y)
            assert svc.healthz()["status"] == "ok"
            assert not svc.updater.paused
            assert svc.updater.flush()["deltas"] >= 1
            snap = svc.metrics_snapshot()
            assert snap["health"]["gate_trips"] >= 1
            assert snap["health"]["recoveries"] >= 1
            assert snap["health"]["rollbacks"] == 1
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            svc.close()
            locktrace.shutdown()
    assert errors == []
    tracker.assert_consistent(lock_order_edges([pkg_dir]))
    assert tracker.acquisitions().get("HealthMonitor._lock", 0) > 0


def test_drift_gate_trips_without_labels(rng):
    """Covariate shift is detected from scores alone (no feedback, no
    updater): PSI/KS gates run on pure scoring traffic."""
    svc = _service(rng, health=HealthConfig(
        window_scores=256, baseline_scores=256, sustain_windows=2,
        calibration_p_min=None, psi_max=0.25, ks_max=0.2))
    try:
        r = np.random.default_rng(3)
        for _ in range(3):   # baseline + one clean window
            f, i = _requests(r, 128)
            svc.score(f, i)
        assert svc.health.verdict()["baseline_ready"]
        assert svc.healthz()["status"] == "ok"
        windows_before = svc.health.verdict()["windows_evaluated"]
        tripped_after = None
        for w in range(6):
            for _ in range(2):
                f, i = _requests(r, 128, scale=3.0)   # shifted traffic
                svc.score(f, i)
            if svc.healthz()["status"] == "degraded":
                tripped_after = (svc.health.verdict()["windows_evaluated"]
                                 - windows_before)
                break
        assert tripped_after is not None and tripped_after <= 3
        gates = svc.healthz()["health"]["gates"]
        assert gates["drift_psi"]["tripped"] or gates["drift_ks"]["tripped"]
    finally:
        svc.close()


def test_baseline_resets_on_swap_carried_across_deltas(rng):
    """ISSUE 11 tentpole semantics: the drift baseline belongs to the
    installed full model — a delta publish keeps it, a full swap resets
    it (and clears gate state / resumes a health-paused updater)."""
    from photon_ml_tpu.serving import CompiledScorer
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8),
                   health=_lifecycle_config(sustain_windows=1))
    try:
        r = np.random.default_rng(5)
        f, i = _requests(r, 128)
        svc.score(f, i)   # fills baseline (128)
        assert svc.health.verdict()["baseline_ready"]
        # delta publish: baseline carried
        f, i, y = _calibrated_feedback(svc, r, 32)
        svc.feedback(f, i, y)
        svc.updater.flush()
        assert svc.registry.pending_deltas() >= 1
        assert svc.health.verdict()["baseline_ready"]
        # trip the calibration gate, then swap a new full model in
        f, i, y = _calibrated_feedback(svc, r, 64, flip=True)
        svc.feedback(f, i, y)
        assert svc.healthz()["status"] == "degraded"
        assert svc.updater.paused
        scorer2 = CompiledScorer(_make_model(np.random.default_rng(7)),
                                 max_batch=64, min_bucket=4)
        scorer2.warmup()
        svc.registry.install(scorer2, "v2")
        v = svc.health.verdict()
        assert v["status"] == "ok"                 # fresh start
        assert v["model_version"] == "v2"
        assert not v["baseline_ready"]             # re-collecting
        assert not svc.updater.paused              # health pause released
    finally:
        svc.close()


def test_pause_landing_mid_cycle_requeues_instead_of_publishing(rng):
    """A pause that lands between drain and publish (the health monitor
    pausing from another thread while a cycle is in flight) must NOT
    publish rows solved against the pre-pause state — they requeue and
    re-solve after recovery."""
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    try:
        f, i, y = _calibrated_feedback(svc, rng, 8)
        svc.feedback(f, i, y)
        drained = svc.updater.buffer.drain("perUser", 8)
        assert drained
        table0 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
        svc.updater.pause(reason="mid-cycle")
        out = svc.updater._solve_and_publish(svc.registry.scorer, "perUser",
                                             "per_user", drained)
        assert out is None
        assert svc.registry.pending_deltas() == 0
        assert np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")), table0)
        svc.updater.resume()
        assert svc.updater.flush()["deltas"] >= 1   # requeued rows drain
    finally:
        svc.close()


def test_updater_pause_resume_buffering(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    try:
        svc.updater.pause(reason="operator")
        f, i, y = _calibrated_feedback(svc, rng, 16)
        out = svc.feedback(f, i, y)
        assert out["accepted"] > 0                 # intake keeps working
        assert svc.updater.flush()["deltas"] == 0  # but nothing publishes
        assert svc.updater.stats()["paused"] is True
        svc.updater.resume()
        assert svc.updater.flush()["deltas"] >= 1  # buffered rows drain
        assert svc.updater.last_cycle_age_s() is not None
    finally:
        svc.close()


# -- /healthz detail (satellite) ---------------------------------------------

def test_healthz_updater_vitals_and_gate_detail(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8),
                   health=_lifecycle_config())
    try:
        f, i, y = _calibrated_feedback(svc, rng, 16)
        svc.feedback(f, i, y)
        svc.updater.flush()
        hz = svc.healthz()
        assert hz["status"] == "ok"
        assert hz["updates_enabled"] and hz["health_enabled"]
        up = hz["updater"]
        assert up["alive"] is False        # manual driving: no loop thread
        assert up["frozen"] == 0
        assert up["paused"] is False
        assert up["last_cycle_age_s"] >= 0.0
        assert up["pending_rows"] == 0
        gates = hz["health"]["gates"]
        assert set(gates) == {"calibration", "drift_psi", "drift_ks", "auc",
                              "loss", "delta_l2", "freeze_rate"}
        for g in gates.values():
            assert {"threshold", "value", "breaches", "tripped", "windows",
                    "trips"} <= set(g)
    finally:
        svc.close()


def test_healthz_without_updates_or_health(rng):
    svc = _service(rng)
    try:
        hz = svc.healthz()
        assert hz["status"] == "ok"
        assert "updater" not in hz and "health" not in hz
    finally:
        svc.close()


# -- metric-surface parity (satellite) ---------------------------------------

def _flatten_paths(d, prefix=()):
    out = set()
    for k, v in d.items():
        out.add(prefix + (k,))
        if isinstance(v, dict):
            out |= _flatten_paths(v, prefix + (k,))
    return out


def test_metric_surface_parity_prometheus_vs_json():
    """ISSUE 11 satellite: the Prometheus text and the JSON snapshot
    expose the SAME metric set — every registered instrument has a
    declared JSON path (SNAPSHOT_PATHS), every path resolves in a
    rendered snapshot, and every instrument renders in the text
    exposition.  A metric added to one surface only fails here."""
    m = ServingMetrics()
    names = set(m.registry.names())
    assert names == set(SNAPSHOT_PATHS), (
        "every ServingMetrics instrument needs a SNAPSHOT_PATHS entry "
        f"(missing: {sorted(names - set(SNAPSHOT_PATHS))}, stale: "
        f"{sorted(set(SNAPSHOT_PATHS) - names)})")
    snap = m.snapshot()
    paths = _flatten_paths(snap)
    for name, path in SNAPSHOT_PATHS.items():
        assert path in paths, (f"instrument {name!r} declares JSON path "
                               f"{path} but snapshot() has no such key")
    reg = m.registry.snapshot()
    prom = m.prometheus()
    prom_series = set(re.findall(r"^photon_[a-zA-Z0-9_]+", prom,
                                 flags=re.M))
    clean = lambda n: "photon_" + re.sub(r"[^a-zA-Z0-9_]", "_", n)
    for name in reg["counters"]:
        assert clean(name) + "_total" in prom_series, name
    for name in reg["gauges"]:
        assert clean(name) in prom_series, name
    for name in reg["histograms"]:
        assert clean(name) in prom_series, name


def test_refresh_semantics_match_on_both_render_paths():
    """model_age_s and the updater-vitals gauges refresh at RENDER on
    both surfaces (a scrape and a snapshot can never disagree about
    staleness because one path forgot the refresh)."""
    m = ServingMetrics()
    vitals = {"frozen": 3, "alive": True, "paused": False,
              "last_cycle_age_s": 1.5}
    m.set_online_probe(lambda: dict(vitals))
    snap = m.snapshot()
    assert snap["online"]["frozen_entities"] == 3
    assert snap["online"]["updater_alive"] == 1
    assert snap["online"]["last_cycle_age_s"] == 1.5
    vitals.update(frozen=7, last_cycle_age_s=9.25, alive=False)
    prom = m.prometheus()
    assert "photon_online_frozen_entities 7" in prom
    assert "photon_online_last_cycle_age_s 9.25" in prom
    assert "photon_online_updater_alive 0" in prom
    # age before the first cycle renders as the -1 sentinel
    vitals["last_cycle_age_s"] = None
    assert m.snapshot()["online"]["last_cycle_age_s"] == -1.0


# -- fault site ---------------------------------------------------------------

def test_health_evaluate_transient_fault_skips_window(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8),
                   health=_lifecycle_config(sustain_windows=1))
    try:
        plan = faults.FaultPlan([{"site": "health.evaluate",
                                  "action": "transient", "hits": [1]}])
        with faults.injected(plan):
            f, i, y = _calibrated_feedback(svc, rng, 64, flip=True)
            svc.feedback(f, i, y)   # window closes, evaluation faulted
        assert plan.report()["total_fired"] == 1
        v = svc.health.verdict()
        assert v["windows_skipped"] == 1
        assert v["status"] == "ok"          # the verdict was dropped
        assert svc.metrics_snapshot()["health"]["evaluate_skipped"] == 1
        # the next window evaluates normally
        f, i, y = _calibrated_feedback(svc, rng, 64, flip=True)
        svc.feedback(f, i, y)
        assert svc.healthz()["status"] == "degraded"
    finally:
        svc.close()


# -- compile-count regression (satellite) ------------------------------------

class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0
        self.messages = []

    def emit(self, record):
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            self.count += 1
            self.messages.append(msg[:120])


class _compile_counting:
    def __enter__(self):
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_zero_fresh_traces_warm_serve_update_loop_health_armed(rng):
    """ISSUE 11 satellite: a WARM serve+update loop with health ARMED —
    including drift and calibration WINDOW CLOSES inside the counted
    region — traces nothing new.  All health accumulation and evaluation
    is host numpy/scipy; the only device programs involved are the
    already-warm scorer buckets."""
    svc = _service(rng, updates=OnlineUpdateConfig(
        micro_batch=4, max_rows_per_entity=8),
        health=HealthConfig(window_labels=16, window_scores=32,
                            baseline_scores=32, sustain_windows=100))
    try:
        svc.updater.warmup()

        def one_round(seed):
            r = np.random.default_rng(seed)
            f, i, y = _calibrated_feedback(svc, r, 16)  # closes a window
            svc.feedback(f, i, y)
            svc.updater.flush()
            f2, i2 = _requests(r, 32)                   # closes a window
            svc.score(f2, i2)

        one_round(0)
        one_round(1)   # baseline complete + first windows evaluated
        before = svc.metrics_snapshot()["health"]
        assert before["label_windows"] >= 1
        with _compile_counting() as counter:
            for s in range(2, 8):
                one_round(s)
        after = svc.metrics_snapshot()["health"]
        # windows really closed (and evaluated) inside the counted region
        assert after["label_windows"] >= before["label_windows"] + 6
        assert after["score_windows"] > before["score_windows"]
        assert counter.count == 0, counter.messages
        assert svc.registry.scorer.deltas_applied >= 6
    finally:
        svc.close()
