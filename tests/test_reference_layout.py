"""Reading and writing the Scala reference's own on-disk model layout.

reference: ModelProcessingUtils.scala:71-135 (save), :136-238 (load),
:517-559 (metadata) — fixed-effect/<name>/coefficients/part-*.avro,
random-effect/<name>/coefficients/part-*.avro (+ _SUCCESS), id-info files,
and a model-metadata.json holding "modelType".  VERDICT r4 missing #1: a
model trained by actual photon-ml must load, score, and warm-start here.
"""
import json
import os

import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset, build_index_map
from photon_ml_tpu.data.avro_codec import write_container
from photon_ml_tpu.data.avro_io import BAYESIAN_LINEAR_MODEL_AVRO
from photon_ml_tpu.game import GameEstimator
from photon_ml_tpu.models.io import (load_game_model, load_model_index_maps,
                                     save_game_model,
                                     save_game_model_reference_layout)
from tests.test_game import _config, _dataset

_LOGISTIC = ("com.linkedin.photon.ml.supervised.classification."
             "LogisticRegressionModel")


def _rec(model_id, means, variances=None):
    return {"modelId": model_id, "modelClass": _LOGISTIC,
            "means": [{"name": n, "term": t, "value": v}
                      for (n, t), v in means],
            "variances": (None if variances is None else
                          [{"name": n, "term": t, "value": v}
                           for (n, t), v in variances]),
            "lossFunction": None}


def _write_reference_fixture(root):
    """A model directory shaped exactly like the Scala reference writes it:
    partitioned RE files, _SUCCESS markers, id-info, reference metadata."""
    fe = os.path.join(root, "fixed-effect", "fixed")
    os.makedirs(os.path.join(fe, "coefficients"))
    with open(os.path.join(fe, "id-info"), "w") as f:
        f.write("globalShard\n")
    write_container(
        os.path.join(fe, "coefficients", "part-00000.avro"),
        BAYESIAN_LINEAR_MODEL_AVRO,
        [_rec("fixed-effect",
              [(("f_a", ""), 0.5), (("f_b", "t1"), -1.25),
               (("(INTERCEPT)", ""), 2.0)])])

    re = os.path.join(root, "random-effect", "perUser")
    os.makedirs(os.path.join(re, "coefficients"))
    with open(os.path.join(re, "id-info"), "w") as f:
        f.write("userId\nuserShard\n")
    # entities split across two Spark partition files, plus a _SUCCESS
    # marker and a hidden checksum file the loader must skip
    write_container(
        os.path.join(re, "coefficients", "part-00000.avro"),
        BAYESIAN_LINEAR_MODEL_AVRO,
        [_rec("u1", [(("u_x", ""), 1.0), (("(INTERCEPT)", ""), 0.25)]),
         _rec("u2", [(("u_y", ""), -2.0)])])
    write_container(
        os.path.join(re, "coefficients", "part-00001.avro"),
        BAYESIAN_LINEAR_MODEL_AVRO,
        [_rec("u3", [(("u_x", ""), 3.0), (("u_y", ""), 0.5)])])
    open(os.path.join(re, "coefficients", "_SUCCESS"), "w").close()
    open(os.path.join(re, "coefficients", ".part-00000.avro.crc"),
         "w").close()

    with open(os.path.join(root, "model-metadata.json"), "w") as f:
        json.dump({"modelType": "LOGISTIC_REGRESSION",
                   "modelName": "fixture",
                   "fixedEffectOptimizationConfigurations": {},
                   "randomEffectOptimizationConfigurations": {}}, f)


def test_reference_fixture_loads_and_scores(tmp_path):
    root = str(tmp_path / "gameModel")
    _write_reference_fixture(root)
    model, config = load_game_model(root)
    assert config is None
    assert model.task_type == "logistic_regression"
    assert set(model.coordinates) == {"fixed", "perUser"}

    fe = model.coordinates["fixed"]
    assert fe.feature_shard == "globalShard"
    maps = load_model_index_maps(root)
    gm, um = maps["globalShard"], maps["userShard"]
    means = np.asarray(fe.glm.coefficients.means)
    assert means[gm.index_of("f_a")] == 0.5
    assert means[gm.index_of("f_b", "t1")] == -1.25
    assert means[gm.intercept_index] == 2.0

    re = model.coordinates["perUser"]
    assert re.random_effect_type == "userId"
    assert list(re.entity_ids) == ["u1", "u2", "u3"]
    coefs = np.asarray(re.coefficients)
    assert coefs[0, um.index_of("u_x")] == 1.0
    assert coefs[0, um.intercept_index] == 0.25
    assert coefs[2, um.index_of("u_y")] == 0.5

    # scoring end-to-end: margin = fixed + per-user, unseen user scores 0
    xg = np.zeros((2, gm.size))
    xg[:, gm.index_of("f_a")] = 1.0
    xg[:, gm.intercept_index] = 1.0
    xu = np.zeros((2, um.size))
    xu[:, um.index_of("u_x")] = 1.0
    ds = build_game_dataset(
        np.zeros(2), {"globalShard": xg, "userShard": xu},
        entity_ids={"userId": np.asarray(["u1", "unseen"])})
    s = np.asarray(model.score_dataset(ds))
    np.testing.assert_allclose(s[0], (0.5 + 2.0) + 1.0, rtol=1e-6)
    np.testing.assert_allclose(s[1], 0.5 + 2.0, rtol=1e-6)


def test_reference_fixture_without_metadata(tmp_path):
    """Pre-metadata reference models: task comes from the records'
    modelClass (reference defaults taskType to NONE and trusts submodels)."""
    root = str(tmp_path / "gameModel")
    _write_reference_fixture(root)
    os.remove(os.path.join(root, "model-metadata.json"))
    model, _ = load_game_model(root)
    assert model.task_type == "logistic_regression"
    assert set(model.coordinates) == {"fixed", "perUser"}


def test_reference_fixture_warm_starts(tmp_path, rng):
    """A reference-layout model warm-starts GameEstimator.fit: training
    resumed from it must start at (and improve on) its objective."""
    ds, _ = _dataset(rng, n=400, task="logistic")
    cfg = _config(task="logistic_regression", iters=1)
    first = GameEstimator(cfg).fit(ds)
    # write the trained model in the REFERENCE layout, reload, warm-start
    root = str(tmp_path / "refModel")
    save_game_model_reference_layout(
        first.model, root,
        index_maps={"global": build_index_map(
            [(f"g{i}", "") for i in range(ds.feature_shards["global"].shape[1] - 1)]),
            "per_user": build_index_map(
            [(f"u{i}", "") for i in range(ds.feature_shards["per_user"].shape[1] - 1)])})
    loaded, _ = load_game_model(root)
    warm = GameEstimator(cfg).fit(ds, initial_model=loaded)
    assert warm.objective_history[-1] <= first.objective_history[-1] + 1e-6


def test_reference_layout_roundtrip_partitioned(tmp_path, rng):
    """save_game_model_reference_layout -> load_game_model is exact, with
    the random effect split across several part files."""
    ds, _ = _dataset(rng, n=300)
    res = GameEstimator(_config(iters=1)).fit(ds)
    d_glob = ds.feature_shards["global"].shape[1] - 1
    d_user = ds.feature_shards["per_user"].shape[1] - 1
    imaps = {"global": build_index_map([(f"g{i}", "") for i in range(d_glob)]),
             "per_user": build_index_map([(f"u{i}", "")
                                          for i in range(d_user)])}
    root = str(tmp_path / "refModel")
    save_game_model_reference_layout(res.model, root, index_maps=imaps,
                                     num_re_partitions=3)
    parts = [p for p in os.listdir(
        os.path.join(root, "random-effect", "perUser", "coefficients"))
        if p.startswith("part-")]
    assert len(parts) == 3
    loaded, _ = load_game_model(root)
    np.testing.assert_allclose(np.asarray(loaded.score_dataset(ds)),
                               np.asarray(res.model.score_dataset(ds)),
                               atol=1e-5)


def test_reference_layout_keeps_feature_space_with_zero_coefficients(
        tmp_path, rng):
    """Avro records drop zero coefficients; with index_maps provided at
    save, the reload must keep the FULL feature space (columns stable), not
    a compacted one."""
    import jax.numpy as jnp
    from photon_ml_tpu.models.game import FixedEffectModel, GameModel
    from photon_ml_tpu.models.glm import model_for_task
    from photon_ml_tpu.models.coefficients import Coefficients
    imap = build_index_map([(f"g{i}", "") for i in range(4)])
    means = np.asarray([0.7, 0.0, -1.2, 0.0, 0.5])  # g1, g3 exactly zero
    model = GameModel(
        {"fixed": FixedEffectModel(
            model_for_task("linear_regression",
                           Coefficients(jnp.asarray(means))), "global")},
        "linear_regression")
    root = str(tmp_path / "refModel")
    save_game_model_reference_layout(model, root,
                                     index_maps={"global": imap})
    maps = load_model_index_maps(root)
    assert maps["global"].size == imap.size
    loaded, _ = load_game_model(root)
    got = np.asarray(loaded.coordinates["fixed"].glm.coefficients.means)
    np.testing.assert_allclose(got, means)


def test_our_avro_model_also_readable_as_before(tmp_path, rng):
    """Detection must not break this package's own avro format."""
    ds, _ = _dataset(rng, n=300)
    res = GameEstimator(_config(iters=1)).fit(ds)
    d = str(tmp_path / "own")
    save_game_model(res.model, d, config=res.config, format="avro")
    loaded, cfg = load_game_model(d)
    assert cfg == res.config
    np.testing.assert_allclose(np.asarray(loaded.score_dataset(ds)),
                               np.asarray(res.model.score_dataset(ds)),
                               rtol=1e-6)


_REAL_FIXTURE = ("/root/reference/photon-client/src/integTest/resources/"
                 "GameIntegTest/gameModel")


@pytest.mark.skipif(not os.path.isdir(_REAL_FIXTURE),
                    reason="reference checkout not present")
def test_loads_actual_scala_written_fixture():
    """The GENUINE artifact: the reference repo's checked-in GAME model
    directory (written by the Scala implementation itself, used by its
    scoring DriverTest) must load here directly."""
    model, config = load_game_model(_REAL_FIXTURE)
    assert config is None
    assert model.task_type == "linear_regression"
    fe = model.coordinates["globalShard"]
    assert fe.feature_shard == "globalShard"
    means = np.asarray(fe.glm.coefficients.means)
    assert means.ndim == 1 and len(means) > 1
    assert np.isfinite(means).all() and (means != 0).any()
    maps = load_model_index_maps(_REAL_FIXTURE)
    m = maps["globalShard"]
    assert m.size == len(means)
    # feature identity survives: every nonzero coefficient resolves back to
    # the (name, term) key the Scala writer recorded
    j = int(np.flatnonzero(means)[0])
    name, term = m.name_term(j)
    assert means[m.index_of(name, term)] == means[j]
    # scoring runs end-to-end against a synthetic dataset in its space
    rngl = np.random.default_rng(0)
    x = rngl.normal(size=(5, m.size))
    ds = build_game_dataset(np.zeros(5), {"globalShard": x})
    s = np.asarray(model.score_dataset(ds))
    np.testing.assert_allclose(s, x @ means, rtol=1e-6)


def test_cli_initial_model_dir_warm_start(tmp_path, rng):
    """--initial-model-dir warm-starts a training job from a saved model
    directory — here one in the REFERENCE layout — and must not end worse
    than the job that produced it."""
    from tests.test_io_cli import _run_cli
    from photon_ml_tpu.data.game_data import save_game_dataset

    ds, _ = _dataset(rng, n=400, task="logistic")
    cfg = _config(task="logistic_regression", iters=1)
    first = GameEstimator(cfg).fit(ds)
    model_dir = str(tmp_path / "prev")
    save_game_model_reference_layout(first.model, model_dir)
    ds_p = str(tmp_path / "ds.npz")
    save_game_dataset(ds, ds_p)
    cfg_p = str(tmp_path / "game.json")
    with open(cfg_p, "w") as f:
        f.write(cfg.to_json())
    out_dir = str(tmp_path / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", ds_p, "--task", "logistic_regression",
                  "--config", cfg_p, "--output-dir", out_dir,
                  "--initial-model-dir", model_dir])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["final_objective"] <= first.objective_history[-1] + 1e-4


def test_warm_start_rekeys_different_feature_space(tmp_path, rng):
    """A model whose feature space differs from the training data's (the
    reference layout stores a COMPACT space — zeros dropped) re-keys by
    (name, term) on warm start instead of misaligning columns."""
    import jax.numpy as jnp
    from photon_ml_tpu.data import build_game_dataset
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import FixedEffectModel, GameModel
    from photon_ml_tpu.models.glm import model_for_task
    from photon_ml_tpu.models.io import (align_game_model_to_dataset,
                                         load_model_index_maps)

    # model space: {a, c} + intercept (b unseen, exact zero dropped)
    m_map = build_index_map([("a", ""), ("c", "")])
    means = np.asarray([0.5, -1.0, 2.0])  # a, c, intercept
    model = GameModel(
        {"fixed": FixedEffectModel(
            model_for_task("linear_regression",
                           Coefficients(jnp.asarray(means))), "global")},
        "linear_regression")
    root = str(tmp_path / "m")
    save_game_model_reference_layout(model, root,
                                     index_maps={"global": m_map})
    loaded, _ = load_game_model(root)

    # training space: {a, b, c} + intercept, different column layout
    t_map = build_index_map([("a", ""), ("b", ""), ("c", "")])
    ds = build_game_dataset(np.zeros(4),
                            {"global": np.zeros((4, t_map.size))},
                            index_maps={"global": t_map})
    aligned = align_game_model_to_dataset(
        loaded, load_model_index_maps(root), ds)
    got = np.asarray(aligned.coordinates["fixed"].glm.coefficients.means)
    assert got.shape == (t_map.size,)
    assert got[t_map.index_of("a")] == 0.5
    assert got[t_map.index_of("b")] == 0.0   # unseen feature starts at 0
    assert got[t_map.index_of("c")] == -1.0
    assert got[t_map.intercept_index] == 2.0

    # dimension mismatch without maps on both sides is a hard error
    ds_nomaps = build_game_dataset(np.zeros(4), {"global": np.zeros((4, 7))})
    with pytest.raises(ValueError, match="re-key"):
        align_game_model_to_dataset(loaded, None, ds_nomaps)


def test_reference_layout_scoring_cli(tmp_path, rng):
    """The scoring CLI accepts a reference-layout model directory directly:
    index maps are rebuilt from the records, so Avro scoring data resolves
    into the model's feature space."""
    from photon_ml_tpu.cli import score as score_cli
    from photon_ml_tpu.data.avro_game import write_game_examples

    root = str(tmp_path / "gameModel")
    _write_reference_fixture(root)
    maps = load_model_index_maps(root)
    n = 10
    rngl = np.random.default_rng(5)
    xg = (rngl.uniform(size=(n, maps["globalShard"].size)) < 0.5).astype(float)
    xu = (rngl.uniform(size=(n, maps["userShard"].size)) < 0.5).astype(float)
    data_p = str(tmp_path / "score.avro")
    write_game_examples(
        data_p, np.ones(n),
        bags={"features": (xg, maps["globalShard"]),
              "userFeatures": (xu, maps["userShard"])},
        id_values={"userId": np.asarray(["u1", "u2", "u3", "nope"] * 2 +
                                        ["u1", "u2"])})
    out_p = str(tmp_path / "scores.npz")
    rc = score_cli.main(
        ["--model-dir", root, "--data", data_p, "--output", out_p,
         "--feature-shard-map",
         json.dumps({"globalShard": ["features"],
                     "userShard": ["userFeatures"]}),
         "--mesh", "none"])
    assert rc == 0
    scores = np.load(out_p)["scores"]
    assert scores.shape == (n,)
    assert np.isfinite(scores).all()
