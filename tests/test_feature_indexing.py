"""Standalone feature indexing / frozen shared feature spaces.

reference: FeatureIndexingJob.scala:56-307 (offline index-map build) +
PalDBIndexMapLoader (jobs consuming prebuilt maps) — VERDICT r4 missing #3:
two jobs on different data slices must share one feature space.
"""
import json

import numpy as np
import pytest

from photon_ml_tpu.cli import index as index_cli
from photon_ml_tpu.data import build_index_map
from photon_ml_tpu.data.avro_game import write_game_examples
from photon_ml_tpu.data.index_map import IndexMapCollection


def _write_slice(path, rng, keys, n=60, users=8):
    imap = build_index_map(keys)
    x = (rng.uniform(size=(n, imap.size)) < 0.5).astype(float)
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    ids = np.asarray([f"u{i % users}" for i in range(n)])
    write_game_examples(path, y, bags={"features": (x, imap)},
                        id_values={"userId": ids})
    return imap


def test_index_cli_builds_union_maps(tmp_path, rng, capsys):
    """The indexing job scans ALL files and produces the sorted union
    vocabulary per shard."""
    _write_slice(str(tmp_path / "a.avro"), rng,
                 [("alpha", ""), ("beta", "t")])
    _write_slice(str(tmp_path / "b.avro"), rng,
                 [("beta", "t"), ("gamma", "")])
    out = str(tmp_path / "maps")
    rc = index_cli.main(["--data", str(tmp_path / "*.avro"),
                         "--output", out])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["files_scanned"] == 2
    maps = IndexMapCollection.load(out).shards
    m = maps["global"]
    # union of both slices + intercept, deterministic sorted layout
    assert m.size == 4
    assert m.index_of("alpha") >= 0
    assert m.index_of("beta", "t") >= 0
    assert m.index_of("gamma") >= 0
    assert m.intercept_index == m.size - 1


def test_index_cli_python_fallback_parity(tmp_path, rng, monkeypatch):
    from photon_ml_tpu.data import avro_native
    _write_slice(str(tmp_path / "a.avro"), rng,
                 [("alpha", ""), ("beta", "t")])
    maps_native = index_cli.scan_feature_shards(
        [str(tmp_path / "a.avro")], {"g": ["features"]})
    monkeypatch.setattr(avro_native, "read_columnar", lambda p, **kw: None)
    maps_py = index_cli.scan_feature_shards(
        [str(tmp_path / "a.avro")], {"g": ["features"]})
    assert list(maps_native["g"].index_to_key) == \
        list(maps_py["g"].index_to_key)


def test_two_jobs_share_frozen_feature_space(tmp_path, rng):
    """Train on slice A, then on slice B with the prebuilt maps: identical
    feature dimension and key->column assignment (the PalDB loader
    guarantee), even though the slices' vocabularies differ."""
    from tests.test_io_cli import _run_cli
    from photon_ml_tpu.models.io import load_game_model, load_model_index_maps

    _write_slice(str(tmp_path / "a.avro"), rng,
                 [("alpha", ""), ("beta", "t"), ("only_a", "")])
    _write_slice(str(tmp_path / "b.avro"), rng,
                 [("alpha", ""), ("beta", "t"), ("only_b", "")])
    maps_dir = str(tmp_path / "maps")
    rc = index_cli.main(["--data", str(tmp_path / "*.avro"),
                         "--output", maps_dir])
    assert rc == 0
    frozen = IndexMapCollection.load(maps_dir).shards["global"]

    outs = {}
    for s in ("a", "b"):
        out_dir = str(tmp_path / f"out-{s}")
        r = _run_cli("photon_ml_tpu.cli.train",
                     ["--train-data", str(tmp_path / f"{s}.avro"),
                      "--task", "logistic_regression",
                      "--index-map-dir", maps_dir,
                      "--output-dir", out_dir, "--reg-weights", "1.0"])
        assert r.returncode == 0, r.stderr[-2000:]
        outs[s] = out_dir
    dims = {}
    for s, out_dir in outs.items():
        model, _ = load_game_model(out_dir + "/best")
        maps = load_model_index_maps(out_dir + "/best")
        m = maps["global"]
        dims[s] = len(np.asarray(
            model.coordinates["fixed"].glm.coefficients.means))
        assert list(m.index_to_key) == list(frozen.index_to_key)
    # identical dimension AND assignment: feature absent from a slice still
    # owns its column
    assert dims["a"] == dims["b"] == frozen.size


def test_index_map_dir_rejects_non_avro(tmp_path, rng):
    from tests.test_io_cli import _run_cli
    from photon_ml_tpu.data.game_data import save_game_dataset
    from tests.test_game import _dataset
    ds, _ = _dataset(rng, n=50)
    npz_p = str(tmp_path / "ds.npz")
    save_game_dataset(ds, npz_p)
    maps_dir = str(tmp_path / "maps")
    _write_slice(str(tmp_path / "a.avro"), rng, [("alpha", "")])
    assert index_cli.main(["--data", str(tmp_path / "a.avro"),
                           "--output", maps_dir]) == 0
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", npz_p, "--task", "linear_regression",
                  "--index-map-dir", maps_dir,
                  "--output-dir", str(tmp_path / "out")])
    assert r.returncode != 0
    assert "requires Avro training input" in (r.stderr + r.stdout)
