"""Sparse and wide fixed-effect models as PRODUCT paths (VERDICT r2 item 4).

The reference's wide regime: SparseVector feature columns from
AvroDataReader (AvroDataReader.scala:332-440) and the >200k-feature
treeAggregate depth switch (GameEstimator.scala:667-669), with a design
envelope of ~1e11 coefficients.  Here scipy.sparse shards flow through
GameEstimator into PaddedSparse (ELL) batches (ops/features.py), shard over
the mesh data axis like dense rows, and — for wide models — shard
coefficients over the mesh feature axis.
"""
import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig,
)
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, RegularizationType,
)
from photon_ml_tpu.parallel import make_mesh

L2 = RegularizationContext(RegularizationType.L2)


def _sparse_logistic(rng, n=2000, d=400, nnz_per_row=12):
    cols = rng.integers(0, d - 1, size=(n, nnz_per_row))
    vals = rng.normal(size=(n, nnz_per_row))
    rows = np.repeat(np.arange(n), nnz_per_row)
    x = sp.csr_matrix((vals.ravel(), (rows, cols.ravel())), shape=(n, d))
    x[:, d - 1] = 1.0  # intercept column
    x = x.tocsr()
    w = rng.normal(size=d) * 0.5
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    return x, y


def _fe_config(shard_features=None, iters=40):
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={"fixed": FixedEffectCoordinateConfig(
            "global",
            GLMOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=iters),
                regularization=L2, regularization_weight=0.1),
            shard_features=shard_features)},
        updating_sequence=["fixed"])


def test_sparse_fe_matches_dense_on_mesh(rng):
    """scipy.sparse shard -> PaddedSparse -> distributed fit == dense fit."""
    x, y = _sparse_logistic(rng)
    mesh = make_mesh()
    ds_sparse = build_game_dataset(y, {"global": x})
    ds_dense = build_game_dataset(y, {"global": x.toarray()})
    assert sp.issparse(ds_sparse.feature_shards["global"])

    res_s = GameEstimator(_fe_config(), mesh=mesh).fit(ds_sparse)
    res_d = GameEstimator(_fe_config(), mesh=mesh).fit(ds_dense)
    np.testing.assert_allclose(res_s.objective_history,
                               res_d.objective_history, rtol=1e-6)
    w_s = np.asarray(
        res_s.model.coordinates["fixed"].glm.coefficients.means)
    w_d = np.asarray(
        res_d.model.coordinates["fixed"].glm.coefficients.means)
    np.testing.assert_allclose(w_s, w_d, rtol=1e-4, atol=1e-6)


def test_sparse_subset_and_scoring(rng):
    """Train/validation splits and model scoring work on sparse shards."""
    x, y = _sparse_logistic(rng)
    ds = build_game_dataset(y, {"global": x})
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:1500]), ds.subset(rows[1500:])
    res = GameEstimator(_fe_config(), mesh=make_mesh()).fit(
        train, val, evaluator_specs=["AUC"])
    # d=400 coefficients from 1500 rows: recovery is partial by design;
    # the gate is "clearly better than chance", not model quality
    assert res.validation["AUC"] > 0.65


@pytest.mark.slow
def test_wide_model_feature_sharded(rng):
    """>=200k-feature model (the reference's depth-switch regime): sparse
    rows + coefficients sharded over a 2-wide feature axis must reproduce
    the data-parallel solve."""
    n, d = 1500, 200_128
    x, y = _sparse_logistic(rng, n=n, d=d, nnz_per_row=16)
    ds = build_game_dataset(y, {"global": x})

    res_fs = GameEstimator(_fe_config(shard_features=True, iters=15),
                           mesh=make_mesh(4, 2)).fit(ds)
    res_dp = GameEstimator(_fe_config(shard_features=False, iters=15),
                           mesh=make_mesh()).fit(ds)
    np.testing.assert_allclose(res_fs.objective_history,
                               res_dp.objective_history, rtol=1e-5)
    hist = res_fs.objective_history
    assert hist[-1] < hist[0] if len(hist) > 1 else True
    w = np.asarray(res_fs.model.coordinates["fixed"].glm.coefficients.means)
    assert w.shape == (d,)


def test_sparse_validate_and_roundtrip(rng, tmp_path):
    """Validators and npz persistence must handle sparse shards (review
    findings: np.asarray on csr gives a useless 0-d object array)."""
    from photon_ml_tpu.data.game_data import (load_game_dataset,
                                              save_game_dataset)
    from photon_ml_tpu.data.validators import (DataValidationError,
                                               validate_game_dataset)
    x, y = _sparse_logistic(rng, n=80, d=30, nnz_per_row=5)
    ds = build_game_dataset(y, {"global": x})
    validate_game_dataset(ds, "logistic_regression", "full")
    validate_game_dataset(ds, "logistic_regression", "sample")

    p = str(tmp_path / "sp_ds.npz")
    save_game_dataset(ds, p)
    ds2 = load_game_dataset(p)
    assert sp.issparse(ds2.feature_shards["global"])
    assert (ds2.feature_shards["global"] != x).nnz == 0

    bad = x.copy()
    bad.data[2] = np.inf
    ds3 = build_game_dataset(y, {"global": bad})
    with pytest.raises(DataValidationError, match="non-finite feature"):
        validate_game_dataset(ds3, "logistic_regression", "full")


def test_bf16_values_accumulate_f32_gradient():
    """bf16 feature storage must not round the gradient through a bf16
    accumulator: rmatvec/sq_rmatvec promote to the solver dtype."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.ops.features import PaddedSparse, rmatvec, sq_rmatvec

    rng = np.random.default_rng(3)
    dense = (rng.uniform(size=(60, 20)) < 0.4).astype(np.float32)
    u = jnp.asarray(rng.normal(size=60).astype(np.float32))
    x32 = PaddedSparse.from_dense(dense)
    x16 = PaddedSparse(x32.indices, x32.values.astype(jnp.bfloat16),
                       x32.num_cols)
    g16, g32 = rmatvec(x16, u), rmatvec(x32, u)
    assert g16.dtype == jnp.float32
    # binary features are exact in bf16, so the results must agree to f32
    np.testing.assert_allclose(np.asarray(g16), np.asarray(g32), rtol=1e-6)
    assert sq_rmatvec(x16, u).dtype == jnp.float32


def test_csc_segment_sum_multi_chunk(rng, monkeypatch):
    """The chunked prefix-scan's MULTI-chunk machinery (chunk_pref gather,
    cross-chunk differencing, the r==0 select at chunk boundaries, the
    c==C clamp at an exact-multiple stream length) against a float64
    reference — _CSC_CHUNK shrunk so a small stream spans many chunks."""
    from photon_ml_tpu.ops import features as fops

    monkeypatch.setattr(fops, "_CSC_CHUNK", 16)
    import jax.numpy as jnp

    d = 40
    for nnz in (16 * 7,          # exact chunk multiple: end[-1] hits c == C
                16 * 7 + 5,      # ragged tail
                3):              # sub-chunk degenerate
        cols = np.sort(rng.integers(0, d, size=nnz).astype(np.int32))
        # force boundary-aligned column ends: make one column end exactly
        # at a chunk edge
        if nnz >= 32:
            cols[:16] = 0
            cols[16:] = np.sort(rng.integers(1, d, size=nnz - 16))
        rows = rng.integers(0, 50, size=nnz).astype(np.int32)
        vals = rng.normal(size=nnz).astype(np.float32)
        end = np.zeros(d + 1, np.int32)
        end[1:] = np.cumsum(np.bincount(cols, minlength=d))
        u = rng.normal(size=50).astype(np.float32)
        out = np.asarray(fops._csc_segment_sum(
            jnp.asarray(vals), jnp.asarray(rows), jnp.asarray(end),
            jnp.asarray(u)))
        truth = np.zeros(d)
        np.add.at(truth, cols, vals.astype(np.float64)
                  * u[rows].astype(np.float64))
        np.testing.assert_allclose(out, truth, atol=1e-4)
