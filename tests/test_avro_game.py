"""Merged multi-bag Avro ingest (reference: AvroDataReader.readMerged +
GameConverters id-tag extraction) — round trips, error semantics, the
sparse wide regime, and the CLI e2e that trains the full GAME config from
Avro files on the 8-device mesh and matches the npz-path result."""
import json
import os

import numpy as np
import pytest

import photon_ml_tpu.data.avro_native as avro_native
from photon_ml_tpu.data.avro_game import (
    game_example_schema, read_game_examples, write_game_examples,
)
from photon_ml_tpu.data.game_data import save_game_dataset
from photon_ml_tpu.data.index_map import build_index_map


def _bag_matrix(rng, n, keys, density=0.6):
    imap = build_index_map(keys)
    x = np.zeros((n, imap.size), np.float32)
    x[:, :-1] = ((rng.uniform(size=(n, len(keys))) < density)
                 * rng.normal(size=(n, len(keys)))).astype(np.float32)
    x[:, -1] = 1.0
    return x, imap


def _write_two_files(tmp_path, rng, n=300, with_meta_ids=False):
    xg, g_map = _bag_matrix(rng, n, [(f"g{i}", "") for i in range(5)])
    x1, b1_map = _bag_matrix(rng, n, [(f"u{i}", "t") for i in range(3)])
    x2, b2_map = _bag_matrix(rng, n, [(f"p{i}", "") for i in range(4)])
    users = np.asarray([f"user{u:02d}" for u in rng.integers(0, 12, n)])
    items = np.asarray([f"it{u}" for u in rng.integers(0, 7, n)])
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    w = rng.uniform(0.5, 2.0, n)
    paths = [str(tmp_path / "part1.avro"), str(tmp_path / "part2.avro")]
    half = n // 2
    for p, sl in zip(paths, (slice(0, half), slice(half, None))):
        ids = {} if with_meta_ids else {"userId": users[sl]}
        meta = [{"itemId": it, **({"userId": u} if with_meta_ids else {})}
                for it, u in zip(items[sl], users[sl])]
        write_game_examples(
            p, y[sl],
            bags={"features": (xg[sl], g_map),
                  "userBag1": (x1[sl], b1_map),
                  "userBag2": (x2[sl], b2_map)},
            id_values=ids, weights=w[sl], metadata=meta)
    shard_map = {"global": ["features"], "per_user": ["userBag1", "userBag2"]}
    return paths, shard_map, dict(xg=xg, x1=x1, x2=x2, users=users,
                                  items=items, y=y, w=w, maps=(g_map, b1_map,
                                                               b2_map))


def _merged_expected(truth, read_map):
    """Manually merge the two user bags into the read-side map's layout."""
    _, b1_map, b2_map = truth["maps"]
    n = truth["x1"].shape[0]
    merged = np.zeros((n, read_map.size), np.float32)
    for src, smap in ((truth["x1"], b1_map), (truth["x2"], b2_map)):
        for j in range(smap.size):
            if j == smap.intercept_index:
                continue
            merged[:, read_map.index_of(*smap.name_term(j))] = src[:, j]
    merged[:, read_map.intercept_index] = 1.0
    return merged


def test_read_merged_round_trip(tmp_path, rng):
    paths, shard_map, truth = _write_two_files(tmp_path, rng)
    res = read_game_examples(paths, shard_map,
                             id_columns=["userId", "itemId"])
    ds = res.dataset
    n = len(truth["y"])
    assert ds.num_rows == n
    np.testing.assert_allclose(ds.response, truth["y"])
    np.testing.assert_allclose(ds.weights, truth["w"])
    # the global shard's sorted-key map matches the writer's layout exactly
    np.testing.assert_allclose(ds.feature_shards["global"], truth["xg"],
                               rtol=1e-6)
    np.testing.assert_allclose(
        ds.feature_shards["per_user"],
        _merged_expected(truth, ds.index_maps["per_user"]), rtol=1e-6)
    # ids: userId from a top-level column, itemId from metadataMap
    assert (ds.entity_vocabs["userId"][ds.entity_indices["userId"]]
            == truth["users"]).all()
    assert (ds.entity_vocabs["itemId"][ds.entity_indices["itemId"]]
            == truth["items"]).all()


def test_read_merged_python_fallback_parity(tmp_path, rng, monkeypatch):
    paths, shard_map, truth = _write_two_files(tmp_path, rng, n=120)
    native = read_game_examples(paths, shard_map,
                                id_columns=["userId", "itemId"])
    monkeypatch.setattr(avro_native, "read_columnar",
                        lambda p, **kw: None)
    fallback = read_game_examples(paths, shard_map,
                                  id_columns=["userId", "itemId"])
    for shard in shard_map:
        np.testing.assert_allclose(
            np.asarray(native.dataset.feature_shards[shard]),
            np.asarray(fallback.dataset.feature_shards[shard]), rtol=1e-6)
    np.testing.assert_allclose(native.dataset.response,
                               fallback.dataset.response)
    for tag in ("userId", "itemId"):
        assert (native.dataset.entity_vocabs[tag][
                    native.dataset.entity_indices[tag]]
                == fallback.dataset.entity_vocabs[tag][
                    fallback.dataset.entity_indices[tag]]).all()


def test_ids_from_metadata_map_only(tmp_path, rng):
    paths, shard_map, truth = _write_two_files(tmp_path, rng, n=80,
                                               with_meta_ids=True)
    res = read_game_examples(paths, shard_map, id_columns=["userId"])
    assert (res.dataset.entity_vocabs["userId"][
                res.dataset.entity_indices["userId"]]
            == truth["users"]).all()


def test_missing_id_raises(tmp_path, rng):
    paths, shard_map, _ = _write_two_files(tmp_path, rng, n=40)
    with pytest.raises(ValueError, match="cannot find id"):
        read_game_examples(paths, shard_map, id_columns=["nonexistentId"])


def test_duplicate_feature_raises(tmp_path, rng):
    """The same (name, term) in two bags merged into one shard is an error
    (reference: readFeatureVectorFromRecord duplicate-features require)."""
    n = 30
    x1, m1 = _bag_matrix(rng, n, [("a", ""), ("b", "")], density=1.0)
    x2, m2 = _bag_matrix(rng, n, [("b", ""), ("c", "")], density=1.0)
    p = str(tmp_path / "dup.avro")
    y = np.zeros(n)
    write_game_examples(p, y, bags={"bag1": (x1, m1), "bag2": (x2, m2)})
    with pytest.raises(ValueError, match="duplicate feature"):
        read_game_examples([p], {"merged": ["bag1", "bag2"]})
    # pure-Python path enforces the same contract
    import photon_ml_tpu.data.avro_native as an
    orig = an.read_columnar
    an.read_columnar = lambda _, **kw: None
    try:
        with pytest.raises(ValueError, match="duplicate feature"):
            read_game_examples([p], {"merged": ["bag1", "bag2"]})
    finally:
        an.read_columnar = orig


def test_wide_shard_assembles_sparse(tmp_path, rng):
    """Above dense_threshold the shard comes back as scipy CSR (the wide
    regime that downstream turns into PaddedSparse on device), with values
    identical to the dense assembly."""
    import scipy.sparse as sp
    n, k = 60, 40
    x, imap = _bag_matrix(rng, n, [(f"f{i:03d}", "") for i in range(k)],
                          density=0.15)
    p = str(tmp_path / "wide.avro")
    write_game_examples(p, np.zeros(n), bags={"features": (x, imap)})
    dense = read_game_examples([p], {"g": ["features"]},
                               dense_threshold=1000)
    sparse = read_game_examples([p], {"g": ["features"]}, dense_threshold=8)
    assert sp.issparse(sparse.dataset.feature_shards["g"])
    np.testing.assert_allclose(
        sparse.dataset.feature_shards["g"].toarray(),
        np.asarray(dense.dataset.feature_shards["g"]), rtol=1e-6)


def test_provided_index_map_drops_unseen(tmp_path, rng):
    """With a supplied index map, unseen features drop (reference IndexMap
    miss -> -1) instead of growing the space."""
    n = 25
    x, imap = _bag_matrix(rng, n, [("a", ""), ("b", ""), ("c", "")],
                          density=1.0)
    p = str(tmp_path / "d.avro")
    write_game_examples(p, np.zeros(n), bags={"features": (x, imap)})
    small = build_index_map([("a", ""), ("b", "")])
    res = read_game_examples([p], {"g": ["features"]},
                             index_maps={"g": small})
    assert res.dataset.feature_shards["g"].shape == (n, small.size)
    np.testing.assert_allclose(
        res.dataset.feature_shards["g"][:, small.index_of("a")],
        x[:, imap.index_of("a")], rtol=1e-6)


def test_scoring_input_without_response(tmp_path, rng):
    """require_response=False fills NaN (reference isResponseRequired)."""
    n = 20
    x, imap = _bag_matrix(rng, n, [("a", "")])
    schema = game_example_schema(["features"], [])
    schema["fields"] = [f for f in schema["fields"]
                        if f["name"] != "response"]
    from photon_ml_tpu.data.avro_codec import write_container
    recs = [{"uid": None, "weight": None, "offset": None,
             "metadataMap": None,
             "features": [{"name": "a", "term": "", "value": 1.0}]}
            for _ in range(n)]
    p = str(tmp_path / "noresp.avro")
    write_container(p, schema, recs)
    res = read_game_examples([p], {"g": ["features"]},
                             require_response=False)
    assert np.isnan(res.dataset.response).all()
    with pytest.raises(ValueError, match="no response column"):
        read_game_examples([p], {"g": ["features"]})


@pytest.mark.slow
def test_cli_game_from_avro_matches_npz(tmp_path, rng):
    """The flagship e2e: the SAME dataset fed once as merged-bag Avro and
    once as npz through the full GAME config (FE + per-user RE) on the
    8-device mesh must produce the same final objective (VERDICT r3
    missing #1)."""
    from tests.test_game import _config
    from tests.test_io_cli import _run_cli

    paths, shard_map, truth = _write_two_files(tmp_path, rng, n=400)
    # canonical dataset = the Avro read itself; the npz copy is bit-identical
    res = read_game_examples(paths, shard_map, id_columns=["userId"])
    npz_p = str(tmp_path / "ds.npz")
    save_game_dataset(res.dataset, npz_p)

    cfg = _config(task="logistic_regression", iters=2)
    cfg_p = str(tmp_path / "game.json")
    with open(cfg_p, "w") as f:
        f.write(cfg.to_json())

    outs = {}
    for label, argv in (
            ("avro", ["--train-data", str(tmp_path / "*.avro"),
                      "--feature-shard-map", json.dumps(shard_map),
                      "--id-columns", "userId"]),
            ("npz", ["--train-data", npz_p])):
        out_dir = str(tmp_path / f"out-{label}")
        r = _run_cli("photon_ml_tpu.cli.train",
                     argv + ["--task", "logistic_regression",
                             "--config", cfg_p, "--output-dir", out_dir])
        assert r.returncode == 0, r.stderr[-2000:]
        outs[label] = json.loads(r.stdout.strip().splitlines()[-1])
    assert outs["avro"]["train_rows"] == outs["npz"]["train_rows"] == 400
    np.testing.assert_allclose(outs["avro"]["final_objective"],
                               outs["npz"]["final_objective"], rtol=1e-6)


def test_validation_read_pinned_to_training_spaces(tmp_path, rng):
    """A validation file with extra/missing features must be read in the
    TRAINING index-map and entity-vocab spaces (CLI passes them through),
    not its own sorted vocabularies."""
    n = 50
    x, imap = _bag_matrix(rng, n, [("a", ""), ("b", ""), ("c", "")],
                          density=1.0)
    users = np.asarray([f"u{i % 5}" for i in range(n)])
    p_tr = str(tmp_path / "tr.avro")
    write_game_examples(p_tr, np.zeros(n), bags={"features": (x, imap)},
                        id_values={"userId": users})
    train = read_game_examples([p_tr], {"g": ["features"]},
                               id_columns=["userId"]).dataset

    # validation: only {a, d} features, one unseen user
    xv, imv = _bag_matrix(rng, 10, [("a", ""), ("d", "")], density=1.0)
    vusers = np.asarray(["u0"] * 9 + ["unseen"])
    p_v = str(tmp_path / "v.avro")
    write_game_examples(p_v, np.zeros(10), bags={"features": (xv, imv)},
                        id_values={"userId": vusers})
    val = read_game_examples(
        [p_v], {"g": ["features"]}, id_columns=["userId"],
        index_maps=train.index_maps,
        entity_vocabs=train.entity_vocabs).dataset
    tm = train.index_maps["g"]
    assert val.feature_shards["g"].shape[1] == tm.size
    np.testing.assert_allclose(val.feature_shards["g"][:, tm.index_of("a")],
                               xv[:, imv.index_of("a")], rtol=1e-6)
    # unseen feature 'd' dropped, unseen entity -> -1
    assert (val.entity_vocabs["userId"] == train.entity_vocabs["userId"]).all()
    assert val.entity_indices["userId"][-1] == -1
    assert (val.entity_indices["userId"][:9] >= 0).all()


def test_non_nullable_uid_passthrough(tmp_path, rng):
    """A schema with a plain (non-union) string uid must still pass uids
    through the native decode path — it decodes as a StrColumn with no
    #present companion (ADVICE r4 finding)."""
    n = 8
    schema = game_example_schema(["features"], [])
    for f in schema["fields"]:
        if f["name"] == "uid":
            f["type"] = "string"
            f.pop("default", None)
    from photon_ml_tpu.data.avro_codec import write_container
    recs = [{"uid": f"row{i}", "response": 1.0, "weight": None,
             "offset": None, "metadataMap": None,
             "features": [{"name": "a", "term": "", "value": 1.0}]}
            for i in range(n)]
    p = str(tmp_path / "uid.avro")
    write_container(p, schema, recs)
    res = read_game_examples([p], {"g": ["features"]})
    assert res.uids == [f"row{i}" for i in range(n)]


def test_null_response_rejected_for_training(tmp_path, rng):
    n = 6
    x, imap = _bag_matrix(rng, n, [("a", "")])
    schema = game_example_schema(["features"], [])
    for f in schema["fields"]:
        if f["name"] == "response":
            f["type"] = ["null", "double"]
            f["default"] = None
    from photon_ml_tpu.data.avro_codec import write_container
    recs = [{"uid": None, "response": None if i == 3 else 1.0,
             "weight": None, "offset": None, "metadataMap": None,
             "features": [{"name": "a", "term": "", "value": 1.0}]}
            for i in range(n)]
    p = str(tmp_path / "nullresp.avro")
    write_container(p, schema, recs)
    with pytest.raises(ValueError, match="null response at row 3"):
        read_game_examples([p], {"g": ["features"]})
    res = read_game_examples([p], {"g": ["features"]},
                             require_response=False)
    assert np.isnan(res.dataset.response[3])
    assert res.dataset.response[0] == 1.0


def test_explicit_intercept_key_in_data(tmp_path, rng):
    """A record carrying the literal '(INTERCEPT)' feature key must land in
    the LAST column (IndexMap layout), not corrupt the sorted identity."""
    from photon_ml_tpu.data.index_map import INTERCEPT_NAME
    n = 10
    schema = game_example_schema(["features"], [])
    from photon_ml_tpu.data.avro_codec import write_container
    recs = [{"uid": None, "response": 0.0, "weight": None, "offset": None,
             "metadataMap": None,
             "features": [{"name": "zz", "term": "", "value": 2.0},
                          {"name": INTERCEPT_NAME, "term": "", "value": 1.0},
                          {"name": "aa", "term": "", "value": 3.0}]}
            for _ in range(n)]
    p = str(tmp_path / "icpt.avro")
    write_container(p, schema, recs)
    res = read_game_examples([p], {"g": ["features"]})
    m = res.dataset.index_maps["g"]
    x = np.asarray(res.dataset.feature_shards["g"])
    assert m.intercept_index == m.size - 1
    np.testing.assert_allclose(x[:, m.index_of("aa")], 3.0)
    np.testing.assert_allclose(x[:, m.index_of("zz")], 2.0)
    np.testing.assert_allclose(x[:, m.intercept_index], 1.0)


def test_empty_avro_dir_is_explicit_error(tmp_path):
    from photon_ml_tpu.cli.train import resolve_avro_paths
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit, match="no .avro files"):
        resolve_avro_paths(str(empty))
    with pytest.raises(SystemExit, match="matched no"):
        resolve_avro_paths(str(tmp_path / "nope-*.avro"))
    assert resolve_avro_paths("data.npz") is None


def test_offsets_round_trip(tmp_path, rng, monkeypatch):
    """Offsets survive the merged write->read (the residual-exchange
    input; reference: GameDatum offset field)."""
    n = 40
    x, imap = _bag_matrix(rng, n, [("a", ""), ("b", "")])
    off = rng.normal(size=n)
    p = str(tmp_path / "off.avro")
    write_game_examples(p, np.zeros(n), bags={"features": (x, imap)},
                        offsets=off)
    res = read_game_examples([p], {"g": ["features"]})
    np.testing.assert_allclose(res.dataset.offsets, off, rtol=1e-12)
    # python fallback parity
    monkeypatch.setattr(avro_native, "read_columnar", lambda p, **kw: None)
    res2 = read_game_examples([p], {"g": ["features"]})
    np.testing.assert_allclose(res2.dataset.offsets, off, rtol=1e-12)


def test_scoring_avro_against_model_without_index_maps_errors(tmp_path, rng):
    """A model saved without index maps cannot resolve Avro scoring data
    into its feature space; the scoring CLI must hard-error, not silently
    misalign columns."""
    from tests.test_game import _config, _dataset
    from tests.test_io_cli import _run_cli
    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.models.io import save_game_model

    ds, _ = _dataset(rng, n=200, task="logistic")
    res = GameEstimator(_config(task="logistic_regression", iters=1)).fit(ds)
    model_dir = str(tmp_path / "m")
    save_game_model(res.model, model_dir)  # no index_maps recorded

    n = 20
    x, imap = _bag_matrix(rng, n, [("a", "")])
    data_p = str(tmp_path / "score.avro")
    write_game_examples(data_p, np.zeros(n), bags={"features": (x, imap)},
                        id_values={"userId": np.asarray(["u0"] * n)})
    r = _run_cli("photon_ml_tpu.cli.score",
                 ["--model-dir", model_dir, "--data", data_p,
                  "--output", str(tmp_path / "s.avro"), "--format", "avro"])
    assert r.returncode != 0
    assert "records no saved index map" in (r.stderr + r.stdout)


def test_scoring_avro_with_partial_index_map_coverage_errors(tmp_path, rng):
    """Maps covering only SOME requested shards are the same silent
    misalignment hazard as none at all (ADVICE r4 finding): the scoring CLI
    must name the uncovered shard and exit."""
    import pytest
    from photon_ml_tpu.cli import score as score_cli
    from tests.test_game import _config, _dataset
    from photon_ml_tpu.game import GameEstimator
    from photon_ml_tpu.models.io import save_game_model

    ds, _ = _dataset(rng, n=200, task="logistic")
    res = GameEstimator(_config(task="logistic_regression", iters=1)).fit(ds)
    model_dir = str(tmp_path / "m")
    save_game_model(res.model, model_dir,
                    index_maps={"global": build_index_map(
                        [(f"g{i}", "") for i in range(7)])})

    n = 20
    xg, gm = _bag_matrix(rng, n, [("a", "")])
    xu, um = _bag_matrix(rng, n, [("u", "")])
    data_p = str(tmp_path / "score.avro")
    write_game_examples(data_p, np.zeros(n),
                        bags={"features": (xg, gm), "userFeatures": (xu, um)},
                        id_values={"userId": np.asarray(["u0"] * n)})
    argv = ["--model-dir", model_dir, "--data", data_p,
            "--output", str(tmp_path / "s.npz"),
            "--feature-shard-map",
            json.dumps({"global": ["features"], "per_user": ["userFeatures"]})]
    with pytest.raises(SystemExit) as ei:
        score_cli.main(argv)
    assert "per_user" in str(ei.value)


def test_avro_validation_without_training_index_maps_errors(tmp_path, rng):
    """libsvm/npz training input carries no index maps; pairing it with an
    Avro validation set must be a hard error, not silent misalignment
    (ADVICE r4 finding)."""
    import pytest
    from photon_ml_tpu.cli.train import _load_dataset
    from photon_ml_tpu.data import build_game_dataset

    train = build_game_dataset(np.zeros(10),
                               {"global": rng.normal(size=(10, 3))})
    assert not train.index_maps
    n = 5
    x, imap = _bag_matrix(rng, n, [("a", "")])
    p = str(tmp_path / "val.avro")
    write_game_examples(p, np.zeros(n), bags={"features": (x, imap)})
    with pytest.raises(SystemExit, match="index maps"):
        _load_dataset(p, "linear_regression", None, train_dataset=train)


def test_input_column_remap_through_cli(tmp_path, rng):
    """--input-columns remaps response/weight names (reference:
    InputColumnsNames remappable columns)."""
    from photon_ml_tpu.cli.train import parse_input_columns
    from photon_ml_tpu.data.avro_codec import write_container
    from photon_ml_tpu.data.game_data import InputColumnNames
    from tests.test_io_cli import _run_cli

    cols = parse_input_columns('{"response": "target", "weight": "wgt"}')
    assert cols.response == "target" and cols.weight == "wgt"
    assert cols.offset == "offset"  # unremapped fields keep defaults
    with pytest.raises(SystemExit, match="unknown keys"):
        parse_input_columns('{"label_col": "x"}')

    n = 60
    x, imap = _bag_matrix(rng, n, [("a", ""), ("b", "")], density=1.0)
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    w = rng.uniform(0.5, 2.0, n)
    schema = {"name": "Remapped", "type": "record", "fields": [
        {"name": "target", "type": "double"},
        {"name": "wgt", "type": ["null", "double"], "default": None},
        {"name": "features", "type": {"type": "array", "items": {
            "name": "FeatureAvro", "type": "record", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string"},
                {"name": "value", "type": "double"}]}}},
    ]}
    recs = [{"target": float(y[i]), "wgt": float(w[i]),
             "features": [{"name": "a", "term": "", "value": float(x[i, 0])},
                          {"name": "b", "term": "", "value": float(x[i, 1])}]}
            for i in range(n)]
    p = str(tmp_path / "remap.avro")
    write_container(p, schema, recs)

    res = read_game_examples([p], {"g": ["features"]},
                             columns=InputColumnNames(response="target",
                                                      weight="wgt"))
    np.testing.assert_allclose(res.dataset.response, y)
    np.testing.assert_allclose(res.dataset.weights, w)

    out_dir = str(tmp_path / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", p, "--task", "logistic_regression",
                  "--input-columns", '{"response": "target", "weight": "wgt"}',
                  "--reg-weights", "1.0", "--output-dir", out_dir])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["train_rows"] == n


def test_remapped_response_typo_errors(tmp_path, rng):
    """An explicitly remapped response name that is absent must error, not
    silently fall back to 'label' (which could be a different column)."""
    from photon_ml_tpu.data.game_data import InputColumnNames
    n = 20
    x, imap = _bag_matrix(rng, n, [("a", "")])
    p = str(tmp_path / "t.avro")
    write_game_examples(p, np.zeros(n), bags={"features": (x, imap)})
    with pytest.raises(ValueError, match="remapped response column 'taget'"):
        read_game_examples([p], {"g": ["features"]},
                           columns=InputColumnNames(response="taget"))
    # python fallback: same contract
    import photon_ml_tpu.data.avro_native as an
    orig = an.read_columnar
    an.read_columnar = lambda p_, **kw: None
    try:
        with pytest.raises(ValueError,
                           match="remapped response column 'taget'"):
            read_game_examples([p], {"g": ["features"]},
                               columns=InputColumnNames(response="taget"))
    finally:
        an.read_columnar = orig
