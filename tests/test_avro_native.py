"""Native (C) Avro block decoder vs the pure-Python codec.

VERDICT r2 item 9: corpus-scale ingest must not bottleneck in the
per-record Python decode.  The contract tested here: identical results to
the pure-Python codec on every supported schema shape, graceful fallback on
unsupported shapes, and a decode rate far above the Python path.
"""
import time

import numpy as np
import pytest

from photon_ml_tpu.data.avro_codec import read_container, write_container
from photon_ml_tpu.data.avro_io import (
    TRAINING_EXAMPLE_AVRO, read_training_examples, write_training_examples,
)
from photon_ml_tpu.data.avro_native import compile_schema, read_columnar
from photon_ml_tpu.data.index_map import build_index_map


def _write_tricky(path, n=60, seed=3):
    """Records exercising null unions, empty feature lists, and both codecs'
    varint edge cases (negative longs via zigzag doubles etc.)."""
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        feats = [{"name": f"f{j}", "term": "t" if j % 2 else "",
                  "value": float(rng.normal())}
                 for j in range(int(rng.integers(0, 5)))]
        recs.append({
            "uid": None if i % 3 == 0 else f"uid-{i}",
            "label": float(rng.normal()) * (10 ** int(rng.integers(-3, 4))),
            "features": feats,
            "metadataMap": None if i % 2 else {"k": "v", "x": "y"},
            "weight": None if i % 4 else float(rng.uniform(0.1, 5)),
            "offset": None if i % 5 else float(rng.normal()),
        })
    write_container(path, TRAINING_EXAMPLE_AVRO, recs)
    return recs


def test_native_matches_python_codec(tmp_path):
    p = str(tmp_path / "tricky.avro")
    recs = _write_tricky(p)
    cols = read_columnar(p)
    assert cols is not None, "native decoder unavailable"
    py = list(read_container(p))
    assert py == recs

    np.testing.assert_allclose(cols["label"], [r["label"] for r in recs],
                               rtol=0, atol=0)
    np.testing.assert_array_equal(
        cols["uid#present"], [0 if r["uid"] is None else 1 for r in recs])
    assert [u for u in cols["uid"].to_list() if u] == \
        [r["uid"] for r in recs if r["uid"] is not None]
    np.testing.assert_array_equal(cols["features#count"],
                                  [len(r["features"]) for r in recs])
    flat = [f for r in recs for f in r["features"]]
    assert cols["features.name"].to_list() == [f["name"] for f in flat]
    assert cols["features.term"].to_list() == [f["term"] for f in flat]
    np.testing.assert_allclose(cols["features.value"],
                               [f["value"] for f in flat], rtol=0, atol=0)
    w = [r["weight"] for r in recs]
    np.testing.assert_array_equal(cols["weight#present"],
                                  [0 if v is None else 1 for v in w])
    got_w = cols["weight"][cols["weight#present"] == 1]
    np.testing.assert_allclose(got_w, [v for v in w if v is not None])


def test_reader_native_equals_fallback(tmp_path, rng, monkeypatch):
    """read_training_examples must give identical output with the native
    path disabled (the pure-Python fallback is the reference semantics)."""
    imap = build_index_map([("a", ""), ("b", ""), ("c", "t")])
    n = 50
    x = np.zeros((n, imap.size))
    x[:, :3] = rng.normal(size=(n, 3)) * (rng.uniform(size=(n, 3)) > 0.5)
    x[:, imap.intercept_index] = 1.0
    y = rng.normal(size=n)
    p = str(tmp_path / "t.avro")
    write_training_examples(p, x, y, imap,
                            uids=[f"u{i}" for i in range(n)])

    fast = read_training_examples(p, imap)
    import photon_ml_tpu.data.avro_io as aio
    monkeypatch.setattr(aio, "_read_training_examples_native",
                        lambda *a: None)
    slow = read_training_examples(p, imap)
    np.testing.assert_allclose(fast[0], slow[0])
    np.testing.assert_allclose(fast[1], slow[1])
    assert fast[2] is None and slow[2] is None
    assert fast[4] == slow[4]


def test_unsupported_schema_falls_back():
    # union with two non-null branches: not compilable -> None
    schema = {"type": "record", "name": "R", "fields": [
        {"name": "v", "type": ["string", "long"]}]}
    assert compile_schema(schema) is None
    # multi-branch union
    schema2 = {"type": "record", "name": "R2", "fields": [
        {"name": "v", "type": ["null", "string", "long"]}]}
    assert compile_schema(schema2) is None


def test_decode_throughput(tmp_path):
    """The C decoder must beat the Python codec by a wide margin; the
    absolute rate is printed for the bench record."""
    rng = np.random.default_rng(9)
    n = 20_000
    recs = [{"uid": f"uid-{i}", "label": float(rng.normal()),
             "features": [{"name": f"f{j}", "term": "", "value": 1.0}
                          for j in range(10)],
             "metadataMap": None, "weight": None, "offset": None}
            for i in range(n)]
    p = str(tmp_path / "big.avro")
    # codec null: the rate should measure decode, not zlib on synthetic
    # highly-compressible data
    write_container(p, TRAINING_EXAMPLE_AVRO, recs, codec="null")
    nbytes = __import__("os").path.getsize(p)

    cols = read_columnar(p)  # warm-up: compiles/loads the C library
    assert cols is not None and len(cols["label"]) == n
    t0 = time.perf_counter()
    cols = read_columnar(p)
    native_s = time.perf_counter() - t0
    assert len(cols["label"]) == n

    t0 = time.perf_counter()
    n_py = sum(1 for _ in read_container(p))
    python_s = time.perf_counter() - t0
    assert n_py == n

    rate = nbytes / native_s / 1e6
    print(f"native: {rate:.0f} MB/s, python: {nbytes / python_s / 1e6:.1f} "
          f"MB/s, speedup {python_s / native_s:.0f}x")
    assert native_s * 3 < python_s, (native_s, python_s)


def test_recursive_schema_rejected():
    """A self-referential record has no flat op program; compile_schema must
    return None (fallback) instead of recursing unboundedly (ADVICE r3)."""
    from photon_ml_tpu.data.avro_native import compile_schema
    schema = {"type": "record", "name": "Node", "fields": [
        {"name": "value", "type": "long"},
        {"name": "next", "type": ["null", "Node"]},
    ]}
    assert compile_schema(schema) is None


def test_named_record_reuse_compiles():
    """Non-recursive reuse of a named record type must compile (each use
    site gets its own columns), not crash."""
    from photon_ml_tpu.data.avro_native import compile_schema
    schema = {"type": "record", "name": "Outer", "fields": [
        {"name": "a", "type": {"type": "record", "name": "Inner", "fields": [
            {"name": "v", "type": "long"}]}},
        {"name": "b", "type": "Inner"},
    ]}
    plan = compile_schema(schema)
    assert plan is not None
    assert [c for c, _ in plan.columns] == ["a.v", "b.v"]


def test_native_decoder_survives_corrupt_blocks(tmp_path, rng):
    """Fuzz: random byte corruptions of a valid container must produce a
    clean Python error (or a successful parse of still-valid bytes) —
    never a crash of the C decoder (bounds checks, varint limits,
    recursion guard)."""
    import photon_ml_tpu.data.avro_native as an
    from photon_ml_tpu.data.avro_io import write_training_examples
    from photon_ml_tpu.data.index_map import build_index_map

    imap = build_index_map([(f"f{i}", "") for i in range(6)])
    n = 50
    x = np.zeros((n, imap.size), np.float32)
    x[:, :-1] = (rng.uniform(size=(n, 6)) < 0.5).astype(np.float32)
    x[:, -1] = 1.0
    y = rng.uniform(size=n)
    base = tmp_path / "clean.avro"
    write_training_examples(str(base), x, y, imap,
                            uids=[f"r{i}" for i in range(n)])
    raw = bytearray(base.read_bytes())

    survived = 0
    for trial in range(150):
        buf = bytearray(raw)
        for _ in range(rng.integers(1, 6)):
            pos = int(rng.integers(16, len(buf)))  # keep the magic intact
            buf[pos] = int(rng.integers(0, 256))
        p = tmp_path / "fuzz.avro"
        p.write_bytes(bytes(buf))
        try:
            an.read_columnar(str(p))
        except Exception:
            pass  # clean Python error is fine; a segfault would kill pytest
        survived += 1
    assert survived == 150
