"""Tiered entity store (ISSUE 14): one residency layer for training,
mesh staging, and serving.

Covers the tier-lifecycle satellite: deterministic evict -> spill ->
fetch -> promote round-trips bit-exact in f64; delta-apply-to-warm-row +
rollback restores exact pre-delta bytes; the concurrent
score/fetch/promote stress test runs with the locktrace tracker ARMED
and validated against the static lock graph; and the compile-count
regression (steady-state misses and promotions = zero fresh XLA traces)
on both the serving and training paths.
"""
import logging
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import photon_ml_tpu
from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.game.config import (FixedEffectCoordinateConfig,
                                       GameTrainingConfig,
                                       GLMOptimizationConfig,
                                       RandomEffectCoordinateConfig)
from photon_ml_tpu.game.estimator import GameEstimator
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.online.delta import CoordinateDelta, ModelDelta
from photon_ml_tpu.optim import RegularizationContext, RegularizationType
from photon_ml_tpu.serving import ScoringService, ServingConfig
from photon_ml_tpu.serving.registry import ModelRegistry
from photon_ml_tpu.serving.scorer import CompiledScorer
from photon_ml_tpu.store import (BlockStore, ColdStore, StoreConfig,
                                 StoreError, TieredEntityStore)
from photon_ml_tpu.utils import faults, locktrace

TASK = "logistic_regression"
D_G, D_U, N_ENT = 6, 4, 300
L2 = RegularizationContext(RegularizationType.L2)

PACKAGE_DIR = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))
_STATIC_EDGES = None


def lock_order_edges_cached():
    global _STATIC_EDGES
    if _STATIC_EDGES is None:
        from photon_ml_tpu.analysis.concurrency import lock_order_edges
        _STATIC_EDGES = lock_order_edges([PACKAGE_DIR])
    return _STATIC_EDGES


def _small_store(tmp_path, rng, *, E=512, d=6, hot=64, warm=2, seg=100,
                 name="perUser"):
    table = rng.normal(size=(E, d))          # f64 under the test config
    st = TieredEntityStore.create(
        str(tmp_path / name), table,
        StoreConfig(hot_rows=hot, warm_segments=warm, seg_rows=seg,
                    overlay_rows=64, flush_rows=32,
                    scatter_chunk=64, lfu_sample=128), name=name)
    st.warmup()
    return st, table.copy()


def _served(slots, stage, table, staged_values):
    """Values the scoring gather would see: each row from exactly one of
    the main hot table / the per-batch staging window."""
    t = np.asarray(table)
    o = np.asarray(staged_values)
    if not len(o):
        o = np.zeros((1, t.shape[1]), t.dtype)
    assert not ((slots >= 0) & (stage >= 0)).any(), "row in BOTH lanes"
    return np.where((slots >= 0)[:, None], t[np.maximum(slots, 0)],
                    o[np.maximum(stage, 0)])


def _make_model(rng, E=N_ENT):
    fe = FixedEffectModel(
        model_for_task(TASK, Coefficients(
            jnp.asarray(rng.normal(size=D_G)))), "global")
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=TASK,
        coefficients=jnp.asarray(rng.normal(size=(E, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(E)], dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re}, TASK)


def _requests(rng, n, E=N_ENT, unseen=0.05):
    feats = {"global": rng.normal(size=(n, D_G)),
             "per_user": rng.normal(size=(n, D_U))}
    ids = np.asarray(
        [f"u{rng.integers(0, int(E * (1 + unseen)))}" for _ in range(n)],
        dtype=object)
    return feats, {"userId": ids}


# -- tier lifecycle ----------------------------------------------------------

def test_tier_lifecycle_round_trip_bit_exact_f64(rng, tmp_path):
    """Deterministic evict -> spill -> fetch -> promote cycles against a
    host numpy reference: every value served from any tier is bit-exact
    in f64, and after flush the cold directory alone reproduces the
    table."""
    st, ref = _small_store(tmp_path, rng)
    E = len(ref)
    for it in range(30):
        rows = rng.integers(0, E, size=40)
        slots, stage, table, overlay = st.lookup_slots(rows)
        assert np.array_equal(_served(slots, stage, table, overlay),
                              ref[rows]), it
        if it % 3 == 0:
            # deltas land in whatever tier the rows live in
            upd = np.unique(rng.integers(0, E, size=8))
            vals = rng.normal(size=(len(upd), st.dim))
            st.update_rows(upd, vals, promote=(it % 6 == 0))
            ref[upd] = vals
        assert np.array_equal(st.gather_rows(rows), ref[rows])
    snap = st.stats.snapshot()
    # every tier transition actually happened
    assert snap["hot_hits"] > 0 and snap["warm_hits"] > 0
    assert snap["cold_misses"] > 0 and snap["promotions"] > 0
    assert snap["spills"] > 0 and snap["evictions"] > 0
    assert np.array_equal(st.full_table(), ref)
    st.flush()
    reopened = TieredEntityStore.open(str(tmp_path / "perUser"))
    assert np.array_equal(reopened.full_table(), ref)


def test_cold_segment_tamper_refused(rng, tmp_path):
    st, ref = _small_store(tmp_path, rng, name="t")
    st.flush()
    cold = ColdStore.open(str(tmp_path / "t"))
    seg_path = os.path.join(str(tmp_path / "t"), "seg-00002.bin")
    raw = bytearray(open(seg_path, "rb").read())
    raw[13] ^= 0xFF
    with open(seg_path, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(Exception) as ei:
        cold.read_segment(2)
    assert "sha256" in str(ei.value)
    # through the store: surfaces as a FATAL store.fetch (named block)
    fresh = TieredEntityStore.open(str(tmp_path / "t"), name="t")
    with pytest.raises(StoreError) as ei:
        fresh.gather_rows(np.asarray([250]))   # row in segment 2
    assert "t/seg-2" in str(ei.value)


def test_unique_rows_required_shapes_checked(rng, tmp_path):
    st, _ = _small_store(tmp_path, rng, name="v")
    with pytest.raises(ValueError):
        st.update_rows(np.asarray([0, 1]), np.zeros((3, st.dim)))
    with pytest.raises(ValueError):
        st.update_rows(np.asarray([0, 10**9]), np.zeros((2, st.dim)))


# -- serving tenant ----------------------------------------------------------

def test_tiered_scorer_parity_with_resident(rng, tmp_path):
    """A store-backed scorer (hot tier ~1/5 of the table) returns
    bit-identical scores to the fully resident scorer, including unseen
    ids (fixed-effect-only fallback)."""
    model = _make_model(rng)
    resident = CompiledScorer(model, max_batch=64, min_bucket=8)
    resident.warmup()
    tiered = CompiledScorer(
        model, max_batch=64, min_bucket=8,
        store=StoreConfig(hot_rows=64, warm_segments=2, seg_rows=64,
                          overlay_rows=64, flush_rows=64,
                          scatter_chunk=64, lfu_sample=64),
        store_dir=str(tmp_path / "store"))
    tiered.warmup()
    assert tiered.tiered and not resident.tiered
    for it in range(10):
        feats, ids = _requests(rng, 48)
        a = resident.score(feats, ids)
        b = tiered.score(feats, ids)
        assert np.array_equal(a.scores, b.scores), it
        assert a.entity_lookups == b.entity_lookups
        assert a.entity_hits == b.entity_hits
    totals = tiered.store_totals()
    assert totals["promotions"] > 0 and totals["hot_hits"] > 0
    # the logical table hash matches the resident scorer's device hash
    assert tiered.table_hashes() == resident.table_hashes()


def test_delta_to_warm_row_rollback_restores_exact_bytes(rng, tmp_path):
    """ISSUE 14 satellite: a delta landing on rows living in the hot,
    warm AND cold tiers, followed by rollback, restores the exact
    pre-delta bytes everywhere (full_table comparison is byte-exact)."""
    model = _make_model(rng)
    tiered = CompiledScorer(
        model, max_batch=64, min_bucket=8,
        store=StoreConfig(hot_rows=64, warm_segments=2, seg_rows=64,
                          overlay_rows=64, flush_rows=64,
                          scatter_chunk=64, lfu_sample=64),
        store_dir=str(tmp_path / "store"))
    tiered.warmup()
    registry = ModelRegistry(lambda d, v: tiered)
    registry.install(tiered, "v1")
    # make rows 0..40 hot; rows ~200+ stay warm/cold
    feats, _ids = _requests(rng, 41)
    tiered.score(feats, {"userId": np.asarray(
        [f"u{i}" for i in range(41)], dtype=object)})
    st = tiered.entity_store("perUser")
    pre = st.full_table().copy()
    rows = np.asarray([0, 40, 200, 299])     # hot, hot, beyond-hot
    prior = np.asarray(tiered.gather_rows("perUser", rows))
    assert np.array_equal(prior, pre[rows])
    vals = rng.normal(size=(4, D_U))
    registry.apply_delta(ModelDelta(base_version="v1", seq=1, coordinates={
        "perUser": CoordinateDelta(rows=rows, values=vals, prior=prior)}))
    post = st.full_table()
    assert np.array_equal(post[rows], vals)
    # feedback-for-cold-entities: the delta PROMOTED the cold rows hot
    slots, _stage, table, _ovl = st.lookup_slots(rows)
    assert (slots >= 0).all()
    assert np.array_equal(np.asarray(table)[slots], vals)
    registry.rollback()
    assert np.array_equal(st.full_table(), pre), \
        "rollback did not restore exact pre-delta bytes across tiers"


def test_store_metrics_on_both_surfaces_and_healthz(rng, tmp_path):
    svc = ScoringService(
        model=_make_model(rng),
        config=ServingConfig(max_batch=64, min_bucket=4,
                             store_budget_rows=64,
                             store_dir=str(tmp_path / "store"),
                             store_warm_segments=2, store_seg_rows=64))
    try:
        for _ in range(6):
            feats, ids = _requests(rng, 32)
            svc.score(feats, ids)
        snap = svc.metrics_snapshot()
        store = snap["store"]
        assert store["warm_hits"] + store["cold_misses"] > 0
        lookups = (store["hot_hits"] + store["warm_hits"]
                   + store["cold_misses"])
        assert lookups > 0 and store["hit_rate"] is not None
        prom = svc.prometheus_metrics()
        for name in ("store_hot_hits", "store_warm_hits",
                     "store_cold_misses", "store_promotions",
                     "store_spills"):
            assert name in prom, name
        hz = svc.healthz()
        assert "store" in hz and hz["store"]["hit_rate"] is not None
        assert "spills" in hz["store"]
    finally:
        svc.close()


def test_store_disabled_surfaces_stay_zero(rng):
    svc = ScoringService(model=_make_model(rng),
                         config=ServingConfig(max_batch=64, min_bucket=4))
    try:
        feats, ids = _requests(rng, 8)
        svc.score(feats, ids)
        snap = svc.metrics_snapshot()
        assert snap["store"]["hit_rate"] is None
        assert snap["store"]["promotions"] == 0
        assert "store" not in svc.healthz()
    finally:
        svc.close()


# -- fault sites -------------------------------------------------------------

def test_store_fetch_transient_absorbed_bit_exact(rng, tmp_path):
    st, ref = _small_store(tmp_path, rng, name="f")
    plan = faults.FaultPlan([{"site": "store.fetch", "action": "transient",
                              "hits": [1, 2]}])
    with faults.injected(plan):
        rows = np.arange(120, 160)
        slots, stage, table, overlay = st.lookup_slots(rows)
    assert np.array_equal(_served(slots, stage, table, overlay),
                          ref[rows])
    assert plan.report()["total_fired"] == 2
    assert st.stats.snapshot()["retries"] >= 2


def test_store_promote_transient_absorbed_bit_exact(rng, tmp_path):
    st, ref = _small_store(tmp_path, rng, name="p")
    plan = faults.FaultPlan([{"site": "store.promote",
                              "action": "transient", "hits": [1]}])
    with faults.injected(plan):
        rows = np.arange(40)
        slots, stage, table, overlay = st.lookup_slots(rows)
    assert np.array_equal(_served(slots, stage, table, overlay),
                          ref[rows])
    assert plan.report()["total_fired"] == 1


def test_store_spill_transient_absorbed_fatal_names_block(rng, tmp_path):
    st, ref = _small_store(tmp_path, rng, name="s", warm=1)
    # touch 3 segments, dirty them, force warm evictions -> spills
    st.update_rows(np.asarray([5]), rng.normal(size=(1, st.dim)))
    plan = faults.FaultPlan([{"site": "store.spill", "action": "transient",
                              "hits": [1]}])
    with faults.injected(plan):
        st.update_rows(np.asarray([150]), rng.normal(size=(1, st.dim)))
        st.update_rows(np.asarray([250]), rng.normal(size=(1, st.dim)))
        st.flush()
    assert plan.report()["total_fired"] == 1
    # fatal spill names the entity block and loses nothing (write-back
    # buffer still holds the bytes)
    st2, ref2 = _small_store(tmp_path, rng, name="s2", warm=1)
    vals = rng.normal(size=(1, st2.dim))
    st2.update_rows(np.asarray([10]), vals)
    ref2[10] = vals
    plan = faults.FaultPlan([{"site": "store.spill", "action": "fatal",
                              "hits": [1]}])
    with faults.injected(plan):
        with pytest.raises(StoreError) as ei:
            st2.update_rows(np.asarray([150]),
                            rng.normal(size=(1, st2.dim)))
            st2.flush()
    assert "s2/seg-" in str(ei.value)
    assert np.array_equal(st2.gather_rows(np.asarray([10])), ref2[[10]])


# -- concurrency -------------------------------------------------------------

def test_concurrent_score_fetch_promote_stress_locktrace_armed(rng,
                                                               tmp_path):
    """ISSUE 14 satellite: concurrent scoring (misses promoting through
    the tiers), deltas landing hot AND warm, rollback, and metric renders
    under the ARMED lock tracker — every observed acquisition order must
    be consistent with the static lock-order graph."""
    with locktrace.enabled() as tracker:
        svc = ScoringService(
            model=_make_model(rng),
            config=ServingConfig(max_batch=64, min_bucket=4,
                                 store_budget_rows=64,
                                 store_dir=str(tmp_path / "store"),
                                 store_warm_segments=2,
                                 store_seg_rows=64))
        stop = threading.Event()
        errors = []

        def scorer_loop(seed):
            r = np.random.default_rng(seed)
            while not stop.is_set():
                feats, ids = _requests(r, 24)
                try:
                    svc.score(feats, ids)
                except Exception as e:  # pragma: no cover
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=scorer_loop, args=(s,),
                                    daemon=True) for s in (11, 13)]
        for t in threads:
            t.start()
        try:
            st = svc.registry.scorer.entity_store("perUser")
            pre = st.full_table().copy()
            for seq in range(1, 4):
                rows = np.unique(rng.integers(0, N_ENT, size=12))
                prior = np.asarray(
                    svc.registry.scorer.gather_rows("perUser", rows))
                vals = rng.normal(size=(len(rows), D_U))
                svc.registry.apply_delta(ModelDelta(
                    base_version=svc.model_version, seq=seq, coordinates={
                        "perUser": CoordinateDelta(rows=rows, values=vals,
                                                   prior=prior)}))
                svc.metrics_snapshot()
            svc.prometheus_metrics()
            svc.rollback()  # delta-aware: reverts ALL pending, newest-first
            assert np.array_equal(st.full_table(), pre)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            svc.close()
            locktrace.shutdown()
    assert errors == []
    tracker.assert_consistent(lock_order_edges_cached())
    # the store lock must actually have been exercised under load (the
    # test proves nothing if no store acquisition was ever observed)
    assert tracker.acquisitions().get("TieredEntityStore._lock", 0) > 0


# -- compile-count regression ------------------------------------------------

class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0
        self.messages = []

    def emit(self, record):
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            self.count += 1
            self.messages.append(msg[:120])


class _compile_counting:
    def __enter__(self):
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_zero_fresh_traces_steady_state_serving(rng, tmp_path):
    """Steady-state misses, promotions, spills AND delta swaps through a
    warmed tiered scorer trace nothing new."""
    model = _make_model(rng)
    tiered = CompiledScorer(
        model, max_batch=64, min_bucket=8,
        store=StoreConfig(hot_rows=64, warm_segments=2, seg_rows=64,
                          overlay_rows=64, flush_rows=64,
                          scatter_chunk=64, lfu_sample=64),
        store_dir=str(tmp_path / "store"))
    tiered.warmup()
    registry = ModelRegistry(lambda d, v: tiered)
    registry.install(tiered, "v1")

    def one_round(seed, seq):
        r = np.random.default_rng(seed)
        feats, ids = _requests(r, 48)
        tiered.score(feats, ids)
        rows = np.unique(r.integers(0, N_ENT, size=8))
        prior = np.asarray(tiered.gather_rows("perUser", rows))
        registry.apply_delta(ModelDelta(
            base_version="v1", seq=seq, coordinates={
                "perUser": CoordinateDelta(
                    rows=rows, values=r.normal(size=(len(rows), D_U)),
                    prior=prior)}))

    one_round(0, 1)     # device_put paths
    with _compile_counting() as counter:
        for s in range(1, 6):
            one_round(s, s + 1)
    assert counter.count == 0, counter.messages
    totals = tiered.store_totals()
    assert totals["promotions"] > 0


# -- training tenant ---------------------------------------------------------

def _glmix(rng, n=3000, d_global=12, num_users=60, d_user=4):
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    z = xg @ rng.normal(size=d_global) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(num_users, d_user))[users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ds = build_game_dataset(y, {"global": xg, "per_user": xu},
                            entity_ids={"userId": np.asarray(
                                [f"u{u:03d}" for u in users])})
    rows = np.arange(n)
    return ds.subset(rows[: int(n * 0.9)]), ds.subset(rows[int(n * 0.9):])


def _config(iters=3, budget=None):
    return GameTrainingConfig(
        task_type=TASK,
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=0.1)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=iters,
        hbm_budget_bytes=budget)


def test_budgeted_fit_through_store_matches_resident_f64(rng):
    """The training tenant: a budgeted fit whose residency rotation runs
    through the store's block handles reproduces the all-resident f64
    objective history <= 1e-10 (bit-exact here: eviction + re-stage moves
    the same host bytes)."""
    train, val = _glmix(rng)
    resident = GameEstimator(_config()).fit(train, val)
    acct = resident.residency
    data_bytes = acct["resident_block_total"] + acct["flat_vector_bytes"]
    fe_bytes = acct["resident_block_bytes"]["fixed"]
    # above the FE shard (no auto-stream), below the total (rotation on)
    budget = max(int(data_bytes * 0.8),
                 int((fe_bytes + acct["flat_vector_bytes"]) * 1.05))
    assert budget < data_bytes
    budgeted = GameEstimator(_config(budget=budget)).fit(train, val)
    b_acct = budgeted.residency
    assert b_acct["evict_inactive"] is True
    assert b_acct["evictions"] > 0
    store = b_acct["store"]
    assert store["evictions"] > 0 and store["fetches"] > 0
    assert any(b["evictions"] > 0 for b in store["blocks"].values())
    np.testing.assert_allclose(budgeted.objective_history,
                               resident.objective_history,
                               rtol=1e-10, atol=0)


def test_training_rotation_store_fetch_site_fires(rng):
    train, val = _glmix(rng, n=1500, num_users=30)
    resident = GameEstimator(_config(iters=2)).fit(train, val)
    acct = resident.residency
    data_bytes = acct["resident_block_total"] + acct["flat_vector_bytes"]
    fe_bytes = acct["resident_block_bytes"]["fixed"]
    budget = max(int(data_bytes * 0.8),
                 int((fe_bytes + acct["flat_vector_bytes"]) * 1.05))
    plan = faults.FaultPlan([{"site": "store.fetch", "action": "transient",
                              "hits": [1]},
                             {"site": "store.fetch", "action": "fatal",
                              "hits": [4], "match": {"tier": "device"}}])
    with faults.injected(plan):
        with pytest.raises(StoreError) as ei:
            GameEstimator(_config(iters=4, budget=budget)).fit(train, val)
    assert "block" in str(ei.value)
    assert plan.report()["total_fired"] == 2


def test_zero_fresh_traces_warm_budgeted_refit(rng):
    """Training-path compile regression: a second budgeted fit (same
    shapes) whose rotation keeps evicting/re-fetching through the store
    traces NOTHING new — steady-state fetch/evict is pure data movement."""
    train, val = _glmix(rng, n=1500, num_users=30)
    resident = GameEstimator(_config(iters=2)).fit(train, val)
    acct = resident.residency
    data_bytes = acct["resident_block_total"] + acct["flat_vector_bytes"]
    fe_bytes = acct["resident_block_bytes"]["fixed"]
    budget = max(int(data_bytes * 0.8),
                 int((fe_bytes + acct["flat_vector_bytes"]) * 1.05))
    GameEstimator(_config(iters=2, budget=budget)).fit(train, val)
    with _compile_counting() as counter:
        res = GameEstimator(_config(iters=2, budget=budget)).fit(train, val)
    assert res.residency["evictions"] > 0
    assert counter.count == 0, counter.messages


# -- block store unit --------------------------------------------------------

def test_blockstore_touch_evict_accounting():
    evicted = []
    bs = BlockStore()
    bs.register("fixed", evict=lambda: evicted.append("fixed"),
                block_bytes=100)
    bs.register("stream", evict=lambda: evicted.append("stream"),
                streamed=True)
    assert bs.touch("fixed") is True          # initial cold fetch
    assert bs.touch("fixed") is False         # already resident
    bs.evict("fixed")
    assert evicted == ["fixed"]
    bs.evict("fixed")                         # idempotent
    assert evicted == ["fixed"]
    assert bs.touch("fixed") is True          # re-fetch after eviction
    assert bs.touch("stream") is False        # streamed: never managed
    bs.evict("stream")
    assert evicted == ["fixed"]
    snap = bs.snapshot()
    assert snap["fetches"] == 2 and snap["evictions"] == 1
    assert snap["blocks"]["fixed"]["fetches"] == 2
