"""Pipelined coordinate descent: strict-vs-pipelined parity, compile-count
regression, async checkpointer semantics, host-blocked accounting.

The pipelining contract (ISSUE 2): timing_mode changes WHEN the host reads
device results and writes checkpoints — never WHAT is computed.  Strict and
pipelined fits must therefore agree bit-for-bit on objective history and
final coefficients, including across a checkpoint/resume boundary with the
async checkpointer, and the jit caches must stop growing after the first
outer iteration.
"""
import glob
import json
import logging
import os
import time

import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig, RandomEffectCoordinateConfig,
)
from photon_ml_tpu.game.coordinate_descent import (
    AsyncCheckpointer, PhaseTimings, read_checkpoint,
)
from photon_ml_tpu.models.io import save_game_model
from photon_ml_tpu.optim import RegularizationContext, RegularizationType

L2 = RegularizationContext(RegularizationType.L2)


def _glmix(rng, n=1000, d_global=6, num_users=25, d_user=3):
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    z = xg @ rng.normal(size=d_global) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(num_users, d_user))[users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ids = np.asarray([f"u{u:03d}" for u in users])
    ds = build_game_dataset(y, {"global": xg, "per_user": xu},
                            entity_ids={"userId": ids})
    rows = np.arange(n)
    return ds.subset(rows[:800]), ds.subset(rows[800:])


def _config(iters=2):
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=0.1)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=iters)


def _model_dir_arrays(directory):
    """{relative npz path: {key: array}} for bit-exact comparison."""
    out = {}
    for path in sorted(glob.glob(os.path.join(directory, "**", "*.npz"),
                                 recursive=True)):
        with np.load(path, allow_pickle=True) as z:
            out[os.path.relpath(path, directory)] = {k: z[k] for k in z.files}
    return out


def _assert_model_dirs_bit_identical(dir_a, dir_b):
    a, b = _model_dir_arrays(dir_a), _model_dir_arrays(dir_b)
    assert sorted(a) == sorted(b)
    for rel in a:
        assert sorted(a[rel]) == sorted(b[rel]), rel
        for k in a[rel]:
            va, vb = a[rel][k], b[rel][k]
            if va.dtype == object:
                assert np.array_equal(va, vb), (rel, k)
            else:
                assert va.tobytes() == vb.tobytes(), (rel, k)


def test_strict_pipelined_parity(rng, tmp_path):
    """Identical objective history (1e-9, in practice exact) and
    bit-identical saved model directories across timing modes."""
    train, val = _glmix(rng)
    results = {}
    for mode in ("strict", "pipelined"):
        results[mode] = GameEstimator(_config(iters=3)).fit(
            train, val, checkpoint_dir=str(tmp_path / f"ckpt-{mode}"),
            timing_mode=mode)
    s, p = results["strict"], results["pipelined"]
    assert len(s.objective_history) == len(p.objective_history) == 6
    np.testing.assert_allclose(p.objective_history, s.objective_history,
                               rtol=0, atol=1e-9)
    for tag, (ma, mb) in (("final", (s.descent.model, p.descent.model)),
                          ("best", (s.model, p.model))):
        da, db = tmp_path / f"{tag}-s", tmp_path / f"{tag}-p"
        save_game_model(ma, str(da))
        save_game_model(mb, str(db))
        _assert_model_dirs_bit_identical(str(da), str(db))
    # both modes tracked validation for every update
    for name, hist in s.descent.validation_history.items():
        assert len(p.descent.validation_history[name]) == len(hist)
        np.testing.assert_allclose(p.descent.validation_history[name], hist,
                                   rtol=1e-6)


def test_resume_parity_with_async_checkpointer(rng, tmp_path):
    """A pipelined fit interrupted after one outer iteration and resumed
    (async checkpointer on both legs) matches the straight strict run to
    1e-9 — histories are continuous across the checkpoint boundary and the
    final coefficients are bit-identical."""
    train, val = _glmix(rng)
    straight = GameEstimator(_config(iters=3)).fit(
        train, val, timing_mode="strict")

    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(iters=1)).fit(train, val, checkpoint_dir=ckpt,
                                        timing_mode="pipelined")
    state = read_checkpoint(ckpt)
    assert state is not None and state.completed_iterations == 1
    resumed = GameEstimator(_config(iters=3)).fit(
        train, val, checkpoint_dir=ckpt, timing_mode="pipelined")
    assert len(resumed.objective_history) == len(straight.objective_history)
    np.testing.assert_allclose(resumed.objective_history,
                               straight.objective_history, rtol=0, atol=1e-9)
    da, db = tmp_path / "final-straight", tmp_path / "final-resumed"
    save_game_model(straight.descent.model, str(da))
    save_game_model(resumed.descent.model, str(db))
    _assert_model_dirs_bit_identical(str(da), str(db))


def test_pipelined_checkpoint_durable_after_fit(rng, tmp_path):
    """AsyncCheckpointer durability contract: after fit() returns, the
    LAST outer iteration's record is on disk and resumable."""
    train, val = _glmix(rng, n=600)
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(iters=2)).fit(train, val, checkpoint_dir=ckpt,
                                        timing_mode="pipelined")
    with open(os.path.join(ckpt, "state.json")) as f:
        state = json.load(f)
    assert state["completed_iterations"] == 2
    assert os.path.isdir(state["model_dir"])
    # replay is a no-op: the checkpoint already covers every iteration
    replay = GameEstimator(_config(iters=2)).fit(
        train, val, checkpoint_dir=ckpt, timing_mode="pipelined")
    assert replay.descent.total_iterations() == 0


class _CompileCounter(logging.Handler):
    """Counts XLA compile events via jax_log_compiles (each 'Compiling
    <name> with global shapes' record is one fresh trace+compile)."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _compile_counting:
    def __enter__(self):
        import jax
        self._jax = jax
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        self._jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_zero_new_traces_after_first_outer_iteration(rng):
    """Compile-count regression (ISSUE 2 satellite): once the first outer
    iteration of a 2-coordinate GAME fit has traced everything, later
    iterations, repeat fits of the same shapes, AND grid-sweep combos that
    only change regularization weights must hit the persistent caches
    (_cached_batched_solver / _cached_solver / module-level jits) without
    a single new trace."""
    train, val = _glmix(rng)
    # warm every program: compiles happen here (count unchecked)
    GameEstimator(_config(iters=1)).fit(train, val)

    with _compile_counting() as counter:
        GameEstimator(_config(iters=3)).fit(train, val)
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles after the warmup fit — a "
        "per-fit closure or unstable jit cache key crept into the loop")

    # same shapes, different lambdas: the grid sweep must reuse every trace
    grid = {"perUser": [
        GLMOptimizationConfig(regularization=L2, regularization_weight=w)
        for w in (10.0, 0.1)]}
    with _compile_counting() as counter:
        GameEstimator(_config(iters=1)).fit_grid(train, grid, val)
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles across grid combos of "
        "identical shapes — regularization weight leaked into a static "
        "cache key")


def test_async_checkpointer_coalesces_and_drains(tmp_path, monkeypatch):
    """Keep-latest semantics: snapshots superseded before their write
    starts are dropped; the final snapshot always lands; written +
    coalesced accounts for every submission."""
    from photon_ml_tpu.game import coordinate_descent as cd

    written = []

    def slow_write(directory, iteration, *rest):
        time.sleep(0.05)
        written.append(iteration)

    monkeypatch.setattr(cd, "_write_checkpoint", slow_write)
    ckpt = cd.AsyncCheckpointer(str(tmp_path))
    n = 8
    for it in range(n):
        ckpt.submit(it, None, [], {}, None, None, None)
    ckpt.shutdown()
    assert written[-1] == n - 1          # the newest record always lands
    assert written == sorted(written)    # submission order preserved
    assert ckpt.written == len(written)
    assert ckpt.written + ckpt.coalesced == n
    assert ckpt.coalesced > 0            # a 50ms writer must coalesce


def test_async_checkpointer_error_surfaces(tmp_path, monkeypatch):
    from photon_ml_tpu.game import coordinate_descent as cd

    def failing_write(directory, *rest):
        raise OSError("disk full")

    monkeypatch.setattr(cd, "_write_checkpoint", failing_write)
    ckpt = cd.AsyncCheckpointer(str(tmp_path))
    ckpt.submit(0, None, [], {}, None, None, None)
    with pytest.raises(RuntimeError, match="async checkpoint"):
        # the failure surfaces at the next submit or at shutdown
        for _ in range(50):
            time.sleep(0.01)
            ckpt.submit(1, None, [], {}, None, None, None)
        ckpt.shutdown()


def test_timing_mode_validated(rng):
    train, val = _glmix(rng, n=400)
    with pytest.raises(ValueError, match="timing_mode"):
        GameEstimator(_config(iters=1)).fit(train, val,
                                            timing_mode="eventually")


def test_host_blocked_accounting(rng, tmp_path):
    """Strict mode attributes its per-update syncs/readbacks as
    host-blocked; pipelined mode concentrates them in the boundary flush
    and a PhaseTimings copy keeps plain-dict compatibility."""
    train, val = _glmix(rng)
    strict = GameEstimator(_config(iters=2)).fit(train, val,
                                                 timing_mode="strict")
    sp = strict.descent.timings
    assert isinstance(sp, PhaseTimings)
    assert sp.host_blocked_total() > 0
    # strict blocked spans sit inside solve/objective/validation spans
    assert any(k.endswith("/objective") for k in sp.host_blocked)

    piped = GameEstimator(_config(iters=2)).fit(
        train, val, checkpoint_dir=str(tmp_path / "ckpt"),
        timing_mode="pipelined")
    pp = piped.descent.timings
    assert any(k.endswith("/flush") for k in pp)
    assert "checkpoint/join" in pp
    # every pipelined blocked second is attributed to a span
    for label in pp.host_blocked:
        assert label in pp
