"""Distributed paths on the 8-device virtual CPU mesh.

This is the TPU rebuild's replacement for the reference's Spark-local-mode
integration tests (SparkTestUtils.sparkTest; e.g. DistributedObjectiveFunctionTest,
RandomEffectCoordinateTest): every multi-device code path runs on 8 virtual
devices, and distributed results must match single-device results.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.models import train_glm
from photon_ml_tpu.ops import LOGISTIC, SQUARED, GLMObjective
from photon_ml_tpu.optim import (
    OptimizerConfig, OptimizerType, RegularizationContext, RegularizationType,
)
from photon_ml_tpu.parallel import (
    EntityBlocks, fit_fixed_effect, fit_random_effects, make_mesh,
    score_by_entity, score_entity_blocks, shard_objective,
)
from tests.synthetic import make_entity_data, make_glm_data


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


def test_mesh_layout(mesh):
    assert mesh.shape == {"data": 8, "feature": 1}


@pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON])
def test_fixed_effect_matches_single_device(opt, mesh, rng):
    x, y, w, _ = make_glm_data(rng, n=500, d=10, task="logistic",
                               weight_range=(0.5, 2.0))
    obj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y), weights=jnp.asarray(w))
    cfg = OptimizerConfig(optimizer=opt)
    reg = RegularizationContext(RegularizationType.L2)

    dist = fit_fixed_effect(obj, jnp.zeros(10), mesh, cfg, reg, 0.5)
    from photon_ml_tpu.optim import solve
    local = solve(obj, jnp.zeros(10), cfg, reg, 0.5)
    np.testing.assert_allclose(dist.x, local.x, rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(dist.value, local.value, rtol=1e-10)


def test_fixed_effect_uneven_batch_padding(mesh, rng):
    # n=503 not divisible by 8: padding rows must not change the optimum
    x, y, _, _ = make_glm_data(rng, n=503, d=6, task="logistic")
    obj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y))
    dist = fit_fixed_effect(obj, jnp.zeros(6), mesh,
                            reg=RegularizationContext(RegularizationType.L2),
                            reg_weight=0.1)
    from photon_ml_tpu.optim import solve
    local = solve(obj, jnp.zeros(6), OptimizerConfig(),
                  RegularizationContext(RegularizationType.L2), 0.1)
    np.testing.assert_allclose(dist.value, local.value, rtol=1e-9)


def test_fixed_effect_feature_sharded(mesh, rng):
    fmesh = make_mesh(num_data=1, num_feature=8)
    x, y, _, _ = make_glm_data(rng, n=128, d=64, task="linear")
    obj = GLMObjective(SQUARED, jnp.asarray(x), jnp.asarray(y))
    dist = fit_fixed_effect(obj, jnp.zeros(64), fmesh, shard_features=True,
                            reg=RegularizationContext(RegularizationType.L2),
                            reg_weight=0.2)
    from photon_ml_tpu.optim import solve
    local = solve(obj, jnp.zeros(64), OptimizerConfig(),
                  RegularizationContext(RegularizationType.L2), 0.2)
    np.testing.assert_allclose(dist.value, local.value, rtol=1e-8)


def _blocks_from_ragged(entity_data, S=None, dtype=jnp.float64):
    d = entity_data[0][0].shape[1]
    S = S or max(len(y) for _, y, _ in entity_data)
    E = len(entity_data)
    x = np.zeros((E, S, d)); yy = np.full((E, S), 0.5); mk = np.zeros((E, S))
    for e, (xe, ye, _) in enumerate(entity_data):
        k = min(len(ye), S)
        x[e, :k] = xe[:k]; yy[e, :k] = ye[:k]; mk[e, :k] = 1.0
    return EntityBlocks(jnp.asarray(x, dtype), jnp.asarray(yy, dtype),
                        jnp.asarray(mk, dtype))


def test_random_effects_match_per_entity_solves(mesh, rng):
    data = make_entity_data(rng, num_entities=16, samples_per_entity=(5, 40), d=4)
    blocks = _blocks_from_ragged(data)
    reg = RegularizationContext(RegularizationType.L2)
    res = fit_random_effects(blocks, LOGISTIC, mesh, reg=reg, reg_weight=1.0)
    assert res.x.shape == (16, 4)

    # every entity must match its own standalone (unpadded) solve
    from photon_ml_tpu.optim import solve
    for e in [0, 3, 7, 15]:
        xe, ye, _ = data[e]
        obj = GLMObjective(LOGISTIC, jnp.asarray(xe), jnp.asarray(ye))
        single = solve(obj, jnp.zeros(4), OptimizerConfig(), reg, 1.0)
        np.testing.assert_allclose(res.x[e], single.x, rtol=1e-6, atol=1e-8)


def test_random_effects_padding_entities(mesh, rng):
    """Entity lanes that are pure padding (mask all zero) yield zero coefs
    with L2 and don't disturb real entities."""
    data = make_entity_data(rng, num_entities=5, samples_per_entity=(5, 20), d=3)
    blocks5 = _blocks_from_ragged(data)
    # pad to 8 entities
    E, S, d = blocks5.x.shape
    pad = 3
    blocks8 = EntityBlocks(
        jnp.concatenate([blocks5.x, jnp.zeros((pad, S, d))]),
        jnp.concatenate([blocks5.labels, jnp.full((pad, S), 0.5)]),
        jnp.concatenate([blocks5.mask, jnp.zeros((pad, S))]))
    reg = RegularizationContext(RegularizationType.L2)
    r5 = fit_random_effects(blocks5, LOGISTIC, reg=reg, reg_weight=0.5)
    r8 = fit_random_effects(blocks8, LOGISTIC, mesh, reg=reg, reg_weight=0.5)
    np.testing.assert_allclose(r8.x[:5], r5.x, rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(r8.x[5:], 0.0, atol=1e-8)
    np.testing.assert_allclose(np.asarray(blocks8.entity_mask), [1]*5 + [0]*pad)


def test_scoring_paths(rng):
    data = make_entity_data(rng, num_entities=6, samples_per_entity=(3, 10), d=4)
    blocks = _blocks_from_ragged(data)
    coefs = jnp.asarray(rng.normal(size=(6, 4)))
    s = score_entity_blocks(coefs, blocks)
    assert s.shape == blocks.labels.shape
    # masked cells are zero
    assert bool(jnp.all(jnp.where(blocks.mask == 0, s == 0, True)))

    # flat scoring with entity gather, incl. unseen entity -> 0
    x = jnp.asarray(rng.normal(size=(5, 4)))
    idx = jnp.asarray([0, 2, 5, -1, 3])
    sf = score_by_entity(coefs, x, idx)
    np.testing.assert_allclose(sf[3], 0.0)
    np.testing.assert_allclose(sf[0], jnp.dot(x[0], coefs[0]), rtol=1e-12)


def test_residual_offsets_equal_explicit_offsets(rng):
    """with_offsets must behave exactly like building the dataset with those
    offsets (coordinate-descent residual exchange contract)."""
    data = make_entity_data(rng, num_entities=4, samples_per_entity=(5, 10), d=3)
    blocks = _blocks_from_ragged(data)
    off = jnp.asarray(rng.normal(size=blocks.labels.shape) * 0.2)
    r1 = fit_random_effects(blocks.with_offsets(off), LOGISTIC,
                            reg=RegularizationContext(RegularizationType.L2),
                            reg_weight=0.3)
    blocks2 = EntityBlocks(blocks.x, blocks.labels, blocks.mask, offsets=off)
    r2 = fit_random_effects(blocks2, LOGISTIC,
                            reg=RegularizationContext(RegularizationType.L2),
                            reg_weight=0.3)
    np.testing.assert_allclose(r1.x, r2.x, rtol=1e-12)


def test_initialize_multihost_topology(monkeypatch):
    """The multihost bring-up path (VERDICT r3 weak #6: untested): verify
    the distributed-init arguments are forwarded and the resulting GLOBAL
    mesh layout without real DCN — jax.distributed is faked, the global
    device list is the virtual 8-CPU set."""
    from photon_ml_tpu.parallel import mesh as mesh_mod
    from photon_ml_tpu.parallel import multihost

    # initialize_multihost records the run topology in multihost._STATE;
    # restore every key afterwards so the fake 2-process run can't leak
    # into later tests in this interpreter.
    for k, v in multihost._STATE.items():
        monkeypatch.setitem(multihost._STATE, k, v)

    calls = {}

    def fake_initialize(coordinator_address=None, num_processes=None,
                        process_id=None, initialization_timeout=None):
        calls.update(coordinator_address=coordinator_address,
                     num_processes=num_processes, process_id=process_id)

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    # Force CPU backend creation now: initialize_multihost flips the gloo
    # collectives config, which only a REAL distributed client can satisfy —
    # with jax.distributed faked, a fresh backend would fail to build.
    jax.devices()
    holder = jax.config._value_holders["jax_cpu_collectives_implementation"]
    prev_collectives = holder.value
    try:
        m = mesh_mod.initialize_multihost(
            coordinator_address="host0:1234", num_processes=2, process_id=0)
        assert calls == {"coordinator_address": "host0:1234",
                         "num_processes": 2, "process_id": 0}
        # the mesh spans the GLOBAL device list, data axis outermost
        assert m.axis_names == (mesh_mod.DATA_AXIS, mesh_mod.FEATURE_AXIS)
        assert dict(m.shape) == {"data": 8, "feature": 1}

        # hardened bring-up: a same-topology re-init is an idempotent no-op
        # (jax.distributed is NOT re-entered)...
        calls.clear()
        m2 = mesh_mod.initialize_multihost(
            coordinator_address="host0:1234", num_processes=2, process_id=0,
            num_feature=2)
        assert dict(m2.shape) == {"data": 4, "feature": 2}
        assert calls == {}
        # ...while a mismatched topology is refused outright
        with pytest.raises(multihost.MultihostInitError):
            mesh_mod.initialize_multihost(
                coordinator_address="other:9", num_processes=4, process_id=1)

        # pod-style bring-up: every argument falls back to the PHOTON_*
        # environment
        multihost._STATE.update(declared=False, initialized=False,
                                coordinator=None, num_processes=None,
                                process_id=None)
        monkeypatch.setenv(multihost.ENV_COORDINATOR, "env-host:4321")
        monkeypatch.setenv(multihost.ENV_NUM_PROCESSES, "2")
        monkeypatch.setenv(multihost.ENV_PROCESS_ID, "1")
        calls.clear()
        mesh_mod.initialize_multihost()
        assert calls == {"coordinator_address": "env-host:4321",
                         "num_processes": 2, "process_id": 1}
    finally:
        jax.config.update("jax_cpu_collectives_implementation",
                          prev_collectives)


def test_initialize_multihost_rejects_bad_factorization(monkeypatch):
    from photon_ml_tpu.parallel import mesh as mesh_mod

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: None)
    with pytest.raises(ValueError, match="mesh"):
        mesh_mod.initialize_multihost(num_feature=3)  # 8 % 3 != 0


def test_make_mesh_device_subsets():
    from photon_ml_tpu.parallel.mesh import make_mesh

    devs = jax.devices()
    m = make_mesh(num_data=2, num_feature=2, devices=devs[:4])
    assert dict(m.shape) == {"data": 2, "feature": 2}
    assert list(m.devices.ravel()) == devs[:4]
    with pytest.raises(ValueError, match="mesh"):
        make_mesh(num_data=3, num_feature=2, devices=devs[:4])


def test_score_by_entity_empty_coefficient_table(rng):
    """Satellite bugfix (ISSUE 2): num_entities == 0 (every entity of a
    type below passive_data_lower_bound) must score every row 0 — the
    general path clips indices to -1 and gathers from a zero-length axis."""
    import jax.numpy as jnp

    from photon_ml_tpu.parallel.random_effect import score_by_entity
    x = jnp.asarray(rng.normal(size=(7, 3)).astype(np.float32))
    empty = jnp.zeros((0, 3), jnp.float32)
    idx = jnp.asarray(np.full(7, -1, np.int32))
    s = np.asarray(score_by_entity(empty, x, idx))
    assert s.shape == (7,)
    assert (s == 0.0).all()
    # and it stays jittable with a zero-length entity axis
    jitted = jax.jit(score_by_entity)
    s2 = np.asarray(jitted(empty, x, idx))
    assert (s2 == 0.0).all()


def test_fit_random_effects_donated_x0_consumed(rng):
    """donate_buffers=True consumes x0 (in-place reuse): reading the
    donated buffer afterwards raises, and the solve result is identical to
    the non-donating path."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.optim import RegularizationContext, RegularizationType
    from photon_ml_tpu.parallel import EntityBlocks, fit_random_effects
    E, S, d = 6, 4, 3
    x = jnp.asarray(rng.normal(size=(E, S, d)).astype(np.float32))
    labels = jnp.asarray((rng.uniform(size=(E, S)) > 0.5).astype(np.float32))
    mask = jnp.ones((E, S), jnp.float32)
    blocks = EntityBlocks(x, labels, mask)
    reg = RegularizationContext(RegularizationType.L2)
    ref = fit_random_effects(blocks, LOGISTIC, reg=reg, reg_weight=1.0)
    x0 = jnp.zeros((E, d), jnp.float32)
    res = fit_random_effects(blocks, LOGISTIC, x0=x0, reg=reg,
                             reg_weight=1.0, donate_buffers=True)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(ref.x),
                               rtol=1e-6)
    with pytest.raises((RuntimeError, ValueError)):
        np.asarray(x0)  # donated: the buffer is gone
