"""PH006 fixture: host wall-clock and host RNG inside a jit-wrapped
function — both freeze at trace time."""
import random
import time

import jax


@jax.jit
def noisy(x):
    return x * random.random() + time.time()
