"""PH006 near-misses: keyed jax.random inside the trace (deterministic),
host timing outside it."""
import time

import jax
from jax import random as jrandom


@jax.jit
def stochastic(x, key):
    return x + jrandom.normal(key, x.shape)


def timed(fn, x):
    t0 = time.perf_counter()
    y = fn(x)
    return y, time.perf_counter() - t0
