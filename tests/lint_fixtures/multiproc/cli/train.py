"""PH014 violation fixture: a multi-process-reachable module (path ends
with cli/train.py) performing unguarded durable writes — every process of
a multi-host run would execute each of these against the SAME path."""
import json
import os
import shutil

from photon_ml_tpu.utils import durable


def write_summary(output_dir, summary):
    # both the open(w) and the json.dump are unguarded multi-writer races
    with open(os.path.join(output_dir, "training-summary.json"), "w") as f:
        json.dump(summary, f, indent=2)


def prune_failed_run(path):
    # destructive mutation with no primary guard: P processes racing rmtree
    shutil.rmtree(path, ignore_errors=True)


def heartbeat(path, payload):
    # all_process=True disables the helper's own primary guard — the
    # per-process intent must be annotated `# photonlint: all-process`
    durable.atomic_write_json(path, payload, all_process=True)
