"""PH004 fixture: an undeclared site, a dynamic site name, and an
undeclared context key (all checked against utils.faults.SITES)."""
from photon_ml_tpu.utils import faults


def stage(i, site_name):
    faults.fire("stage.bogus", chunk=i)
    faults.fire(site_name, chunk=i)
    faults.fire("stage.fetch", chunk_index=i)
