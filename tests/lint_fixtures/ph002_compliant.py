"""PH002 near-misses: static branches, structural `is None` tests, shape
metadata, traced `jnp.where` selection, and hashable static call args."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("use_l1",))
def step(x, use_l1):
    if use_l1:  # declared static: each value is its own compiled program
        x = jnp.abs(x)
    return x


@jax.jit
def masked(x, w):
    if w is None:  # structural test, resolved at trace time
        return x
    n = x.shape[0]
    if n > 3:  # shape metadata is static under the trace
        return x * w
    return jnp.where(w > 0, x, jnp.zeros_like(x))  # traced select


select = jax.jit(lambda table, cols: table, static_argnums=(1,))


def call_site(table):
    return select(table, (0, 1))  # hashable tuple: caches cleanly
