"""PH008 stale-entry fixture: an event constant whose fault site /
flight trigger no longer exists anywhere."""

EVENTS = {
    "serve.drain": "flight_dump",
    "ghost.trigger": "flight_dump",
}
