"""PH003 near-misses: the donate-and-rebind idiom, and the
copy-before-donate guard (the copy, not the live buffer, is donated)."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def axpy(x, g):
    return x - 0.1 * g


def run_rebound(x, g):
    x = axpy(x, g)  # rebinding the name: the dead buffer is unreachable
    return x + 1.0


def run_copied(x, g):
    y = axpy(jnp.array(x, copy=True), g)  # copy-on-alias guard
    return y + jnp.sum(x)
