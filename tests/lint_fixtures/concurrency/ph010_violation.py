"""PH010 fixture: unguarded access to guarded attributes.

`_level` is DECLARED guarded; `_total` is INFERRED guarded (3 of its 4
accesses hold the lock).  The stray read and writes outside the lock are
the violations (3 findings)."""
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._level = 0      # photonlint: guarded-by=_lock
        self._total = 0
        self._flow = 0.0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self._lock:
                self._level += 1
                self._total += 1
                self._flow = self._flow + 1.0

    def read(self):
        return self._level          # violation: declared guard, no lock

    def drain(self):
        self._level = 0             # violation: write outside the lock

    def totals(self):
        with self._lock:
            a = self._total
            b = self._total
        return a + b

    def skim(self):
        self._total -= 1            # violation: inferred guard, no lock

    def flow(self):
        with self._lock:
            return self._flow
