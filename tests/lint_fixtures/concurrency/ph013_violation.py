"""PH013 fixture: a bare check-then-act lazy init, and an attribute
published from the spawned thread with no lock (2 findings)."""
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = None
        self.generation = 0

    def start(self):
        threading.Thread(target=self._refresh, daemon=True).start()

    def table(self):
        if self._table is None:           # violation: two threads can
            self._table = self._build()   # both pass and double-build
        return self._table

    def _build(self):
        return {}

    def _refresh(self):
        while True:
            self.generation += 1          # violation: unguarded publish

    def age(self):
        return self.generation
