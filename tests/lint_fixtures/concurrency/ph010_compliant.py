"""PH010 near-miss: every guarded access holds the lock; the lock-free
snapshot tuple is explicitly declared `guarded-by=atomic` (the tuple-swap
publish idiom), so its cross-thread reads are sanctioned."""
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._level = 0      # photonlint: guarded-by=_lock
        self._snapshot = ()  # photonlint: guarded-by=atomic

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self._lock:
                self._level += 1
                level = self._level
            self._snapshot = (level,)

    def read(self):
        with self._lock:
            return self._level

    def last(self):
        return self._snapshot

    def drain(self):
        with self._lock:
            self._level = 0
