"""PH012 near-miss: the blocking work happens OUTSIDE the critical
section and only the reference swap runs under the lock; a condition
variable waiting on ITSELF is the sanctioned idiom, not a stall."""
import threading
import time

import jax


class Swapper:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._table = None
        self._busy = False

    def publish(self, x):
        fetched = jax.device_get(x)      # blocking work before the lock
        jax.block_until_ready(x)
        with self._lock:
            self._table = fetched        # only the swap is locked

    def throttle(self):
        time.sleep(0.01)                 # no lock held

    def drain(self):
        with self._cv:
            while self._busy:
                self._cv.wait(0.1)       # waits on the HELD cv: exempt
