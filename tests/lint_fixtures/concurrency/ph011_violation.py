"""PH011 fixture: two locks nested in opposite orders on two paths — a
cycle in the acquisition-order graph (1 finding, both witnesses)."""
import threading


class Ledger:
    def __init__(self):
        self._alpha = threading.Lock()
        self._beta = threading.Lock()
        self.credits = 0
        self.debits = 0

    def credit(self):
        with self._alpha:
            with self._beta:
                self.credits += 1

    def debit(self):
        with self._beta:
            with self._alpha:
                self.debits += 1
