"""PH013 near-miss: the locked-recheck (double-checked) lazy init, a
guarded publish, and an early-exit check-then-act held under the lock."""
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = None
        self.generation = 0   # photonlint: guarded-by=_lock
        self._thread = None

    def start(self):
        with self._lock:
            if self._thread is not None:
                return
            thread = threading.Thread(target=self._refresh, daemon=True)
            self._thread = thread
        thread.start()

    def table(self):
        if self._table is None:
            with self._lock:
                if self._table is None:
                    self._table = self._build()
        return self._table

    def _build(self):
        return {}

    def _refresh(self):
        while True:
            with self._lock:
                self.generation += 1

    def age(self):
        with self._lock:
            return self.generation
