"""PH012 fixture: blocking calls inside a lock region (3 findings) — a
device fetch, a host-side block-until-ready, and a sleep all stall every
thread contending for the lock."""
import threading
import time

import jax


class Swapper:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = None
        self._done = threading.Event()

    def publish(self, x):
        with self._lock:
            fetched = jax.device_get(x)       # violation: device sync
            jax.block_until_ready(x)          # violation: blocks on device
            time.sleep(0.01)                  # violation: sleeps
            self._table = fetched
