"""PH011 near-miss: both paths honor one global order (alpha before
beta), including through a helper call — no cycle."""
import threading


class Ledger:
    def __init__(self):
        self._alpha = threading.Lock()
        self._beta = threading.Lock()
        self.credits = 0
        self.debits = 0

    def credit(self):
        with self._alpha:
            with self._beta:
                self.credits += 1

    def debit(self):
        with self._alpha:
            self._locked_debit()

    def _locked_debit(self):
        with self._beta:
            self.debits += 1
