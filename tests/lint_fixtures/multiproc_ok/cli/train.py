"""PH014 compliant near-miss: the same writes as the violation fixture,
each carrying one of the accepted disciplines — a lexical primary guard
(direct, boolean-combined, process_index()==0, or early-return form), the
`# photonlint: all-process` annotation, or the self-guarded durable.*
helpers with their default primary-only behavior."""
import json
import os
import shutil

from photon_ml_tpu.parallel import multihost
from photon_ml_tpu.utils import durable


def write_summary(output_dir, summary):
    if multihost.is_primary():
        with open(os.path.join(output_dir,
                               "training-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)


def write_stats(output_dir, enabled, payload):
    # guard combined with an unrelated condition still counts
    if enabled and multihost.process_index() == 0:
        with open(os.path.join(output_dir, "stats.json"), "w") as f:
            json.dump(payload, f)


def prune_failed_run(path):
    # early-return form: everything below is primary-only
    if not multihost.is_primary():
        return
    shutil.rmtree(path, ignore_errors=True)


def heartbeat(path, payload):
    # deliberately per-process file — annotated multi-writer intent
    durable.atomic_write_json(  # photonlint: all-process
        path, payload, all_process=True)


def record(path, payload):
    # durable.* default behavior self-guards (no-op off process 0)
    durable.atomic_write_json(path, payload)
