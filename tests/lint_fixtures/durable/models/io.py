"""PH005 fixture: bare writes in a module whose path marks it durable
(suffix `models/io.py`) — a crash mid-write tears the metadata file."""
import json
import os


def save_metadata(directory, meta):
    with open(os.path.join(directory, "model-metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)
