"""PH003 fixture: `x` is read after being passed in a donate_argnums
position — the buffer was invalidated by the donating call."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def axpy(x, g):
    return x - 0.1 * g


def run(x, g):
    y = axpy(x, g)
    return y + jnp.sum(x)
