"""PH001 near-misses: everything here looks like the violation but is
fine — host values, host->device transfers, static metadata, and the one
designated flush point."""
import jax
import jax.numpy as jnp
import numpy as np


def host_side(cfg):
    return float(cfg["tolerance"])  # plain host value


def to_device(rows):
    return jnp.asarray(np.asarray(rows))  # host -> device: not a sync


def shape_metadata(x: jnp.ndarray):
    return x.shape, x.ndim, x.dtype  # static, resolves without a fetch


def flush(pending):  # photonlint: flush-point
    # THE designated batched readback: one fetch for the whole iteration
    return jax.device_get(pending)
