"""PH007 near-miss: telemetry-routed timing and non-span time calls are
fine in hot modules."""
import time

from photon_ml_tpu.telemetry.timings import PhaseTimings, clock


def timed_solve(run, spans: PhaseTimings):
    with spans.span("solve"):          # the sanctioned span path
        run()
    t0 = clock()                       # the sanctioned raw timestamp
    run()
    return clock() - t0


def wall_stamp():
    return time.time()                 # wall-clock stamps are not spans


def backoff(delay):
    time.sleep(delay)                  # not a timer at all


def queue_deadline(timeout):
    return time.monotonic() + timeout  # deadlines/uptime, not span timing
