"""PH007 fixture: raw perf_counter span timing in a hot-path module."""
import time
from time import perf_counter


def timed_solve(run):
    t0 = time.perf_counter()          # PH007: raw span timing
    run()
    return time.perf_counter() - t0   # PH007


def timed_stage_ns(stage):
    t0 = perf_counter()               # PH007: from-import form
    stage()
    return time.perf_counter_ns() - int(t0 * 1e9)  # PH007
