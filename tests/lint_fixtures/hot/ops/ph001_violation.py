"""PH001 fixture: host syncs in a hot-path module (the `ops/` path
component makes this file hot).  Four violations, one per sync spelling."""
import jax
import jax.numpy as jnp
import numpy as np


def objective_to_host(x):
    v = jnp.dot(x, x)
    return float(v)


def item_sync(x: jnp.ndarray):
    return x.item()


def hidden_transfer(x):
    y = jnp.exp(x)
    return np.asarray(y)


def unbatched_fetch(metrics):
    return jax.device_get(metrics)
