"""PH004 near-misses: registered literal sites with declared context
keys, through both the module-attribute and from-import spellings."""
from photon_ml_tpu.utils import faults
from photon_ml_tpu.utils.faults import fire


def stage(i):
    faults.fire("stage.fetch", chunk=i)


def save(directory):
    fire("model.save", directory=directory)
