"""PH008 compliant near-miss: the registry's names all have telemetry
event constants, and every trigger() reason is a literal registered
name."""
from photon_ml_tpu.telemetry import flight

TRIGGERS = {
    "serve.drain": "SIGTERM graceful drain",
    "serve.crash": "unhandled error on the serving path",
}


def fire():
    flight.trigger("serve.drain", mode="standalone")
    flight.trigger("serve.crash", error="boom")
