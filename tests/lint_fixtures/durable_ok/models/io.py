"""PH005 near-misses: the atomic helper for writes, bare open() only for
reads."""
import json
import os

from photon_ml_tpu.utils.durable import atomic_write_json


def save_metadata(directory, meta):
    atomic_write_json(os.path.join(directory, "model-metadata.json"), meta)


def load_metadata(directory):
    with open(os.path.join(directory, "model-metadata.json")) as f:
        return json.load(f)
