"""PH008 violation fixture: a flight-recorder trigger registry that
drifted from the telemetry event vocabulary, plus undisciplined
trigger() call sites."""
from photon_ml_tpu.telemetry import flight

# "fixture.phantom" has no telemetry event constant in
# telemetry/events.py -> registry-drift finding on this assignment
TRIGGERS = {
    "serve.drain": "SIGTERM graceful drain",
    "fixture.phantom": "a trigger nobody declared an event for",
}


def fire_dynamic(reason):
    # dynamic reason: plans/docs/greps cannot see what dumps exist
    flight.trigger(reason, note="dynamic")


def fire_unregistered():
    # literal, but not in TRIGGERS above
    flight.trigger("fixture.unregistered")
