"""PH002 fixture: retrace hazards — a Python branch and an f-string on
traced values inside jit-wrapped functions, and a non-hashable literal in
a static argument position at a call site."""
import functools

import jax
import jax.numpy as jnp


@jax.jit
def step(x, lr):
    if lr > 0.5:
        x = x * lr
    return x


@functools.partial(jax.jit, static_argnames=("tag",))
def fmt(x, tag):
    label = f"solve-{x}"
    del label
    return x


select = jax.jit(lambda table, cols: table, static_argnums=(1,))


def call_site(table):
    return select(table, [0, 1])
