"""Mesh-resident coordinate data (ISSUE 6): strict f64 mesh-vs-single-device
parity (including the mesh-streamed path), the warm-iteration no-retransfer
contract, per-coordinate invalidation, compile-count stability across mesh
shapes, and fault injection through the mesh.stage site.

The transfer contract: after a coordinate's static arrays are staged
(padded + sharded over the mesh "data" axis) once, a warm outer iteration
stages ZERO cold bytes — only per-visit operands (residual offsets, x0)
move, bounded by coefficients+offsets.  TransferStats makes this
observable; the tests gate on it so the re-transfer regression that
motivated the layer cannot creep back.
"""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FactoredRandomEffectCoordinateConfig, FixedEffectCoordinateConfig,
    GameEstimator, GameTrainingConfig, GLMOptimizationConfig,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, RegularizationType,
)
from photon_ml_tpu.parallel import make_mesh
from photon_ml_tpu.parallel.mesh_residency import (
    MeshStagingError, TransferStats, default_residency, transfer_snapshot,
)
from photon_ml_tpu.utils import faults
from test_pipeline import _compile_counting

L2 = RegularizationContext(RegularizationType.L2)


def _glmix(rng, n=1600, d_global=10, num_users=64, d_user=4, num_items=0,
           d_item=0):
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = np.arange(n) % num_users
    z = xg @ rng.normal(size=d_global) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(num_users, d_user))[users])
    shards = {"global": xg, "per_user": xu}
    entity_ids = {"userId": np.asarray([f"u{u:03d}" for u in users])}
    if num_items:
        xi = rng.normal(size=(n, d_item)); xi[:, -1] = 1.0
        items = np.arange(n) % num_items
        z = z + np.einsum("nd,nd->n", xi,
                          rng.normal(size=(num_items, d_item))[items])
        shards["per_item"] = xi
        entity_ids["itemId"] = np.asarray([f"i{i:03d}" for i in items])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ds = build_game_dataset(y, shards, entity_ids=entity_ids)
    rows = np.arange(n)
    cut = int(n * 0.9)
    return ds.subset(rows[:cut]), ds.subset(rows[cut:])


def _opt(w, iters=8):
    return GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=iters),
        regularization=L2, regularization_weight=w)


def _config(outer=2, iters=8, with_item=False, with_mf=False, budget=None):
    coords = {"fixed": FixedEffectCoordinateConfig("global", _opt(1.0, iters)),
              "perUser": RandomEffectCoordinateConfig(
                  "userId", "per_user", _opt(1.0, iters),
                  projector="identity")}
    seq = ["fixed", "perUser"]
    if with_item:
        coords["perItem"] = RandomEffectCoordinateConfig(
            "itemId", "per_item", _opt(1.0, iters), projector="identity")
        seq.append("perItem")
    if with_mf:
        coords["perUserMF"] = FactoredRandomEffectCoordinateConfig(
            "userId", "per_user", latent_dim=2, num_inner_iterations=1,
            optimization=_opt(1.0, iters), latent_optimization=_opt(0.5, iters))
        seq.append("perUserMF")
    return GameTrainingConfig(
        task_type="logistic_regression", coordinates=coords,
        updating_sequence=seq, num_outer_iterations=outer,
        hbm_budget_bytes=budget)


# -- strict f64 parity (ISSUE 6 satellite) ------------------------------------

def test_mesh_parity_fe_re_factored_strict(rng):
    """Mesh and single-device fits of the FULL surface (FE + RE + factored
    MF) produce numerically identical objective histories in f64 — GSPMD
    sharding + the residency layer's pad/shard must not change the math."""
    train, val = _glmix(rng)
    cfg = _config(with_mf=True)
    one = GameEstimator(cfg).fit(train, val)
    mesh = GameEstimator(cfg, mesh=make_mesh()).fit(train, val)
    assert len(one.objective_history) == len(mesh.objective_history)
    np.testing.assert_allclose(mesh.objective_history, one.objective_history,
                               rtol=1e-12, atol=0)
    assert mesh.mesh_transfer is not None
    assert mesh.mesh_transfer["cold_bytes"] > 0


def test_mesh_streamed_parity_and_per_device_budget(rng):
    """Mesh x out-of-core (the previously forbidden combination): a config
    whose per-device data exceeds the per-device budget trains on the
    8-device mesh with the FE shard chunk-streamed, matching the RESIDENT
    single-device reference in f64, with tracked per-device peak under the
    budget."""
    train, val = _glmix(rng, n=2400, d_global=96, num_users=80, d_user=4)
    resident = GameEstimator(_config(iters=6)).fit(train, val)

    acct = resident.residency
    fe_b = acct["resident_block_bytes"]["fixed"]
    re_b = sum(b for c, b in acct["resident_block_bytes"].items()
               if c != "fixed")
    flat = acct["flat_vector_bytes"]
    D = 8
    budget = int((flat + -(-re_b // D)) * 2.2)
    assert budget < 2 * fe_b // D, "shape cannot force streaming"
    streamed = GameEstimator(_config(iters=6, budget=budget),
                             mesh=make_mesh()).fit(train, val)

    assert len(streamed.objective_history) == len(resident.objective_history)
    np.testing.assert_allclose(streamed.objective_history,
                               resident.objective_history, rtol=1e-9)
    sacct = streamed.residency
    assert sacct["per_device"] is True and sacct["data_devices"] == 8
    assert sacct["streamed_chunk_bytes"], "FE coordinate did not stream"
    assert sacct["under_budget"] is True
    assert sacct["peak_tracked_bytes"] <= budget
    # the out-of-core claim: per-device data really exceeds the budget
    assert -(-(fe_b + re_b) // D) + flat > budget


# -- warm-iteration transfer contract -----------------------------------------

def test_warm_iterations_stage_zero_cold_bytes(rng):
    """The no-retransfer regression gate: a second descent over the SAME
    coordinates stages zero cold (static) bytes, and every visit's warm
    bytes stay within the coefficients+offsets bound — the dataset is
    d x bigger and cannot hide inside it."""
    train, val = _glmix(rng)
    cfg = _config(outer=2)
    mesh = make_mesh()
    est = GameEstimator(cfg, mesh=mesh)
    coords = est._build_coordinates(train)

    def run():
        return run_coordinate_descent(
            coords, cfg.updating_sequence, cfg.num_outer_iterations, train,
            cfg.task_type, residency=est._residency_manager(coords, train))

    cold_res = run()
    snap1 = transfer_snapshot()
    warm_res = run()
    delta = TransferStats.delta(snap1, transfer_snapshot())
    assert delta["cold_bytes"] == 0, (
        f"warm run re-staged {delta['cold_bytes']} static bytes — the mesh "
        "residency memo broke")
    assert delta["warm_bytes"] > 0  # offsets/x0 legitimately move
    assert warm_res.objective_history == cold_res.objective_history

    # per-visit accounting in the trackers: coefficients+offsets only
    item = 8  # f64
    for key, t in warm_res.trackers.items():
        coord = key.split("/", 1)[1]
        assert t.staged_bytes is not None
        assert t.staged_bytes["cold"] == 0, (key, t.staged_bytes)
        c = coords[coord]
        if hasattr(c, "red"):
            cells = sum((-(-b.num_entities // 8) * 8)
                        * (b.samples_per_entity + b.dim)
                        for b in c.red.buckets)
        else:
            cells = (-(-train.num_rows // 8) * 8) + c.dim
        assert t.staged_bytes["warm"] <= cells * item * 1.5, (
            key, t.staged_bytes, cells * item)


def test_solver_diagnostics_carry_staged_bytes(rng):
    train, val = _glmix(rng, n=800, num_users=32)
    res = GameEstimator(_config(), mesh=make_mesh()).fit(train, val)
    diag = res.descent.solver_diagnostics()
    for coord in ("fixed", "perUser"):
        assert "staged_bytes" in diag[coord]
        assert diag[coord]["staged_bytes"]["warm"] > 0


# -- per-coordinate invalidation (ISSUE 6 satellite) --------------------------

def test_eviction_invalidates_only_the_evicted_coordinate(rng):
    """The eviction sledgehammer fix: evicting one coordinate drops ONLY
    its staged mesh entries; the sibling's stay resident and its next
    update stages zero cold bytes."""
    train, _ = _glmix(rng, n=1200, num_users=32, num_items=16, d_item=4)
    cfg = _config(with_item=True)
    mesh = make_mesh()
    est = GameEstimator(cfg, mesh=mesh)
    coords = est._build_coordinates(train)
    zeros = jnp.zeros(train.num_rows)
    models = {n: coords[n].initial_model() for n in cfg.updating_sequence}
    for n in cfg.updating_sequence:
        models[n], _ = coords[n].update(models[n], zeros)

    reg = default_residency()
    user_prefix = coords["perUser"]._mesh_key()
    item_prefix = coords["perItem"]._mesh_key()
    has_prefix = lambda p: any(k[0][: len(p)] == p for k in reg.keys())
    assert has_prefix(user_prefix) and has_prefix(item_prefix)

    coords["perUser"].evict_device_blocks()
    assert not has_prefix(user_prefix), "evicted entries survived"
    assert has_prefix(item_prefix), (
        "evicting perUser dropped perItem's staged blocks — the global "
        "clear_mesh_block_cache sledgehammer is back")

    # the surviving coordinate's next update re-transfers nothing static
    before = transfer_snapshot()
    models["perItem"], _ = coords["perItem"].update(models["perItem"], zeros)
    delta = TransferStats.delta(before, transfer_snapshot())
    assert delta["cold_bytes"] == 0
    # the evicted one re-streams (cold) on its next visit
    before = transfer_snapshot()
    models["perUser"], _ = coords["perUser"].update(models["perUser"], zeros)
    delta = TransferStats.delta(before, transfer_snapshot())
    assert delta["cold_bytes"] > 0


def test_default_residency_singleton_under_thread_race():
    """Regression for the PH013 bare lazy init: racing first calls must
    all get ONE registry (two would split the TransferStats the mesh
    bench gates on).  Resets the module global to exercise the
    double-checked path, restoring it afterwards."""
    import threading

    from photon_ml_tpu.parallel import mesh_residency as mr

    prev = mr._DEFAULT
    try:
        mr._DEFAULT = None
        barrier = threading.Barrier(8)
        got = []

        def racer():
            barrier.wait(timeout=5)
            got.append(mr.default_residency())

        threads = [threading.Thread(target=racer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        assert len(got) == 8
        assert all(g is got[0] for g in got)
    finally:
        mr._DEFAULT = prev


def test_clear_mesh_block_cache_alias_retired():
    """ISSUE 14 satellite: the deprecated global-flush alias is GONE —
    invalidation routes through the tiered store's residency registry
    (per-coordinate `invalidate`, or `clear()` on the registry itself)."""
    import photon_ml_tpu.parallel.random_effect as re_mod
    assert not hasattr(re_mod, "clear_mesh_block_cache")
    # the registry's own clear() remains the sanctioned full flush
    from photon_ml_tpu.parallel import mesh_residency
    mesh_residency.clear()
    assert default_residency().num_entries() == 0


# -- compile-count stability across mesh shapes (ISSUE 6 satellite) -----------

@pytest.mark.parametrize("shape", [(8, 1), (4, 2)])
def test_zero_fresh_traces_across_warm_outer_iterations(rng, shape):
    """After one warm-up fit on a mesh shape, a refit traces NOTHING new —
    the staged shardings and budget-operand programs are stable.  Covers
    both the pure data mesh (8x1) and the feature-sharded 4x2 regime."""
    train, val = _glmix(rng, n=800, num_users=32)
    cfg = _config(iters=4)
    mesh = make_mesh(*shape)
    GameEstimator(cfg, mesh=mesh).fit(train, val)   # warm-up compiles all
    with _compile_counting() as counter:
        GameEstimator(cfg, mesh=mesh).fit(train, val)
    assert counter.count == 0, (
        f"{counter.count} fresh XLA traces on a warm {shape} mesh refit")


# -- fault injection through mesh staging (ISSUE 6 satellite) -----------------

def test_mesh_stage_transient_fault_is_retried(rng):
    train, val = _glmix(rng, n=800, num_users=32)
    before = transfer_snapshot()
    plan = faults.FaultPlan([{"site": "mesh.stage", "action": "transient",
                              "hits": [1, 3]}])
    with faults.injected(plan):
        res = GameEstimator(_config(iters=4), mesh=make_mesh()).fit(train,
                                                                    val)
    assert np.isfinite(res.objective_history).all()
    delta = TransferStats.delta(before, transfer_snapshot())
    assert delta["retries"] >= 2
    assert plan.report()["total_fired"] == 2


def test_mesh_stage_fatal_fault_propagates(rng):
    train, val = _glmix(rng, n=800, num_users=32)
    plan = faults.FaultPlan([{"site": "mesh.stage", "action": "fatal",
                              "hits": [1]}])
    with faults.injected(plan):
        with pytest.raises(MeshStagingError):
            GameEstimator(_config(iters=4), mesh=make_mesh()).fit(train, val)


def test_pad_and_shard_rows_fires_mesh_stage_site(rng):
    from photon_ml_tpu.parallel.mesh import pad_and_shard_rows
    mesh = make_mesh()
    x = rng.normal(size=(100, 4))
    plan = faults.FaultPlan([{"site": "mesh.stage", "action": "transient",
                              "hits": [1]}])
    with faults.injected(plan):
        n, (x_dev,) = pad_and_shard_rows(mesh, x)
    assert n == 100 and x_dev.shape[0] == 104  # padded to the 8-multiple
    assert plan.report()["total_fired"] == 1   # absorbed by the retry
