"""GAME end-to-end: coordinate descent on synthetic mixed-effect data.

Mirrors the reference's GameEstimatorTest + the GAME DriverTest e2e strategy
(train on a fixture, assert metric beats a captured threshold): here the
fixture is seeded synthetic GLMix data (global effect + per-user deviations),
and the captured truth is the generating model's own performance.
"""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.evaluation import AUC, RMSE
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig, RandomEffectCoordinateConfig, select_best_result,
)
from photon_ml_tpu.optim import (
    OptimizerConfig, OptimizerType, RegularizationContext, RegularizationType,
)


def glmix_data(rng, n=1200, d_global=8, num_users=30, d_user=4, task="linear"):
    """Global fixed effect + per-user random deviations on a user shard."""
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    w_global = rng.normal(size=d_global)
    w_user = rng.normal(size=(num_users, d_user)) * 0.8
    z = xg @ w_global + np.einsum("nd,nd->n", xu, w_user[users])
    if task == "linear":
        y = z + 0.1 * rng.normal(size=n)
    else:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ids = np.asarray([f"u{u:03d}" for u in users])
    return xg, xu, ids, y, z


def _dataset(rng, task="linear", **kw):
    xg, xu, ids, y, z = glmix_data(rng, task=task, **kw)
    ds = build_game_dataset(y, {"global": xg, "per_user": xu},
                            entity_ids={"userId": ids})
    return ds, z


L2 = RegularizationContext(RegularizationType.L2)


def _config(task="linear_regression", iters=2, re_opt=None, fe_opt=None):
    return GameTrainingConfig(
        task_type=task,
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                feature_shard="global",
                optimization=fe_opt or GLMOptimizationConfig(
                    regularization=L2, regularization_weight=0.1)),
            "perUser": RandomEffectCoordinateConfig(
                random_effect_type="userId", feature_shard="per_user",
                optimization=re_opt or GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=iters)


def test_game_linear_beats_fixed_only(rng):
    ds, z_true = _dataset(rng)
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:900]), ds.subset(rows[900:])

    est = GameEstimator(_config())
    res = est.fit(train, val)
    rmse_game = res.validation["RMSE"]

    fe_only = GameTrainingConfig(
        task_type="linear_regression",
        coordinates={"fixed": FixedEffectCoordinateConfig(
            "global", GLMOptimizationConfig(regularization=L2,
                                            regularization_weight=0.1))},
        updating_sequence=["fixed"])
    res_fe = GameEstimator(fe_only).fit(train, val)
    assert rmse_game < res_fe.validation["RMSE"] * 0.8, (
        "mixed model must clearly beat fixed-only on GLMix data")

    # objective decreases across coordinate updates
    hist = res.objective_history
    assert hist[-1] <= hist[0]
    # and the final RMSE approaches the generating model's noise floor
    assert rmse_game < 0.5


def test_game_logistic_auc(rng):
    ds, _ = _dataset(rng, task="logistic")
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:900]), ds.subset(rows[900:])
    res = GameEstimator(_config(task="logistic_regression")).fit(train, val)
    assert res.validation["AUC"] > 0.75


def test_game_multiple_outer_iterations_improve_or_hold(rng):
    ds, _ = _dataset(rng)
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:900]), ds.subset(rows[900:])
    r1 = GameEstimator(_config(iters=1)).fit(train, val)
    r3 = GameEstimator(_config(iters=3)).fit(train, val)
    assert r3.objective_history[-1] <= r1.objective_history[-1] * 1.001


def test_game_tron_random_effects(rng):
    ds, _ = _dataset(rng)
    cfg = _config(re_opt=GLMOptimizationConfig(
        optimizer=OptimizerConfig(optimizer=OptimizerType.TRON),
        regularization=L2, regularization_weight=1.0))
    res = GameEstimator(cfg).fit(ds)
    assert np.isfinite(res.objective_history[-1])


def test_grid_fit_and_selection(rng):
    ds, _ = _dataset(rng, n=600)
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:450]), ds.subset(rows[450:])
    grid = {"perUser": [
        GLMOptimizationConfig(regularization=L2, regularization_weight=w)
        for w in (100.0, 1.0)]}
    results = GameEstimator(_config(iters=1)).fit_grid(train, grid, val)
    assert len(results) == 2
    best = select_best_result(results)
    assert best.validation["RMSE"] == min(r.validation["RMSE"] for r in results)


def test_grid_warm_start_fewer_iterations_same_loss(rng):
    """reference: use-warm-start (GameTrainingParams.scala:197) — each grid
    combo initialized from the previous model must converge in fewer total
    inner iterations to an equal final loss."""
    ds, _ = _dataset(rng, n=600)
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:450]), ds.subset(rows[450:])
    # strongest-first lambda sweep on the FE coordinate (ModelTraining.scala
    # sorts descending for exactly this reason)
    grid = {"fixed": [
        GLMOptimizationConfig(regularization=L2, regularization_weight=w)
        for w in (10.0, 1.0, 0.1)]}
    est = GameEstimator(_config(iters=1))
    cold = est.fit_grid(train, grid, val, warm_start=False)
    warm = est.fit_grid(train, grid, val, warm_start=True)
    for c, w in zip(cold, warm):
        assert w.objective_history[-1] <= c.objective_history[-1] * (1 + 1e-6)
    cold_iters = sum(r.descent.total_iterations() for r in cold)
    warm_iters = sum(r.descent.total_iterations() for r in warm)
    assert warm_iters < cold_iters, (cold_iters, warm_iters)


def test_fit_initial_model_warm_start_converges_immediately(rng):
    ds, _ = _dataset(rng, n=600)
    est = GameEstimator(_config(iters=1))
    first = est.fit(ds)
    again = est.fit(ds, initial_model=first.model)
    # restarting from the solution: same objective, fewer iterations
    assert again.objective_history[-1] <= first.objective_history[-1] * (1 + 1e-6)
    assert again.descent.total_iterations() < first.descent.total_iterations()


def test_unseen_validation_entities_score_zero_contribution(rng):
    ds, _ = _dataset(rng, n=400, num_users=10)
    res = GameEstimator(_config(iters=1)).fit(ds)
    # validation data with an entirely new user: RE contributes 0, FE still scores
    xg = np.zeros((2, 8)); xg[:, -1] = 1.0
    xu = np.ones((2, 4))
    val = build_game_dataset(np.zeros(2), {"global": xg, "per_user": xu},
                             entity_ids={"userId": np.asarray(["zzz", "u000"])})
    re_model = res.model.coordinates["perUser"]
    s = np.asarray(re_model.score_dataset(val))
    assert s[0] == 0.0  # unseen entity
    total = np.asarray(res.model.score_dataset(val))
    assert np.isfinite(total).all()


def test_config_json_roundtrip():
    cfg = _config()
    j = cfg.to_json()
    back = GameTrainingConfig.from_json(j)
    assert back == cfg


def test_config_validation_errors():
    with pytest.raises(ValueError):
        GameTrainingConfig("linear_regression", {}, ["nope"])
    with pytest.raises(ValueError):
        GLMOptimizationConfig(regularization_weight=-1.0)
    with pytest.raises(ValueError):
        GLMOptimizationConfig(downsampling_rate=1.5)


def test_downsampling_fixed_effect(rng):
    ds, _ = _dataset(rng, task="logistic")
    cfg = _config(task="logistic_regression",
                  fe_opt=GLMOptimizationConfig(
                      regularization=L2, regularization_weight=0.1,
                      downsampling_rate=0.5))
    res = GameEstimator(cfg).fit(ds)
    assert np.isfinite(res.objective_history[-1])


@pytest.mark.parametrize("norm", ["none", "scale_with_standard_deviation",
                                  "scale_with_max_magnitude", "standardization"])
def test_game_normalization_invariance(norm, rng):
    """GAME-level normalization invariance (reference: GameEstimatorTest
    normalization sweep, GameEstimatorTest.scala:125-180): the fixed-effect
    coordinate trained in any normalized space must reach the same final
    objective, because margins are invariant under the factor/shift
    algebra."""
    from photon_ml_tpu.ops.normalization import NormalizationType

    ds, _ = _dataset(rng, task="logistic")
    results = {}
    for nt in ("none", norm):
        cfg = GameTrainingConfig(
            task_type="logistic_regression",
            coordinates={"fixed": FixedEffectCoordinateConfig(
                "global",
                GLMOptimizationConfig(regularization=L2,
                                      regularization_weight=0.0),
                normalization=NormalizationType(nt))},
            updating_sequence=["fixed"])
        results[nt] = GameEstimator(cfg).fit(ds).objective_history[-1]
    np.testing.assert_allclose(results[norm], results["none"], rtol=5e-5)


def test_checkpoint_resume_matches_straight_run(rng, tmp_path):
    """Checkpoint/resume (a capability the reference lacks: a failed Spark
    driver restarts from scratch, SURVEY §5.3): fitting one outer iteration
    with a checkpoint, then re-fitting with two from the same checkpoint
    dir, must reproduce the straight two-iteration run."""
    ds, _ = _dataset(rng, task="logistic")
    cfg2 = _config(task="logistic_regression", iters=2)
    straight = GameEstimator(cfg2).fit(ds)

    ckpt = str(tmp_path / "ckpt")
    cfg1 = _config(task="logistic_regression", iters=1)
    partial = GameEstimator(cfg1).fit(ds, checkpoint_dir=ckpt)
    assert (tmp_path / "ckpt" / "state.json").exists()

    resumed = GameEstimator(cfg2).fit(ds, checkpoint_dir=ckpt)
    # the resumed run executed only iteration 1 (2 coordinates), but its
    # history is continuous across the checkpoint boundary
    assert len(resumed.objective_history) == len(straight.objective_history)
    np.testing.assert_allclose(resumed.objective_history,
                               straight.objective_history, rtol=1e-5)
    np.testing.assert_allclose(resumed.objective_history[:2],
                               partial.objective_history, rtol=1e-7)
    # resume is a no-op when the checkpoint already covers every iteration
    done = GameEstimator(cfg2).fit(ds, checkpoint_dir=ckpt)
    np.testing.assert_allclose(done.objective_history,
                               resumed.objective_history, rtol=1e-7)


def test_checkpoint_resume_with_validation_preserves_best(rng, tmp_path):
    """Resume must restore best-model tracking and validation history, and
    a corrupt state file must mean fresh-start, not a crash."""
    ds, _ = _dataset(rng, task="logistic")
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:900]), ds.subset(rows[900:])

    cfg3 = _config(task="logistic_regression", iters=3)
    straight = GameEstimator(cfg3).fit(train, val)

    ckpt = str(tmp_path / "ckpt")
    cfg1 = _config(task="logistic_regression", iters=1)
    GameEstimator(cfg1).fit(train, val, checkpoint_dir=ckpt)
    resumed = GameEstimator(cfg3).fit(train, val, checkpoint_dir=ckpt)
    # continuous histories across the checkpoint boundary
    assert len(resumed.objective_history) == len(straight.objective_history)
    for name, hist in straight.descent.validation_history.items():
        assert len(resumed.descent.validation_history[name]) == len(hist)
    np.testing.assert_allclose(resumed.objective_history,
                               straight.objective_history, rtol=1e-5)
    # same best model as the uninterrupted run (scored on validation)
    s_best = np.asarray(straight.model.score_dataset(val))
    r_best = np.asarray(resumed.model.score_dataset(val))
    np.testing.assert_allclose(r_best, s_best, rtol=1e-4, atol=1e-5)

    # corrupt state -> warn + fresh start (never a crash)
    with open(str(tmp_path / "ckpt" / "state.json"), "w") as f:
        f.write("{not json")
    fresh = GameEstimator(cfg1).fit(train, val, checkpoint_dir=ckpt)
    assert len(fresh.objective_history) == 2


def test_grid_checkpoint_replays_as_noop(rng, tmp_path):
    """A checkpointed sweep re-run replays completed combos instantly with
    identical results (per-combo checkpoint subdirectories)."""
    ds, _ = _dataset(rng, n=600)
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:450]), ds.subset(rows[450:])
    grid = {"perUser": [
        GLMOptimizationConfig(regularization=L2, regularization_weight=w)
        for w in (100.0, 1.0)]}
    ckpt = str(tmp_path / "sweep")
    est = GameEstimator(_config(iters=1))
    first = est.fit_grid(train, grid, val, checkpoint_dir=ckpt)
    replay = est.fit_grid(train, grid, val, checkpoint_dir=ckpt)
    assert len(replay) == len(first) == 2
    for a, b in zip(first, replay):
        np.testing.assert_allclose(b.objective_history, a.objective_history,
                                   rtol=1e-7)
        np.testing.assert_allclose(b.validation["RMSE"],
                                   a.validation["RMSE"], rtol=1e-6)
        # the replayed combo ran no solves at all
        assert b.descent.total_iterations() == 0
    best_a = select_best_result(first)
    best_b = select_best_result(replay)
    assert best_a.validation["RMSE"] == pytest.approx(
        best_b.validation["RMSE"], rel=1e-6)


def test_checkpoint_rejects_changed_config(rng, tmp_path):
    """A checkpoint written under a different optimization config must not
    be resumed (it would return a model trained under other settings
    silently); the fit retrains fresh with a warning instead."""
    ds, _ = _dataset(rng, task="logistic")
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(task="logistic_regression", iters=1)).fit(
        ds, checkpoint_dir=ckpt)

    changed = _config(task="logistic_regression", iters=1,
                      re_opt=GLMOptimizationConfig(
                          regularization=L2, regularization_weight=50.0))
    res = GameEstimator(changed).fit(ds, checkpoint_dir=ckpt)
    # fresh fit: full history (2 coordinate updates), not a no-op replay
    assert len(res.objective_history) == 2
    assert res.descent.total_iterations() > 0


def test_checkpoint_edge_cases(rng, tmp_path):
    """Over-complete checkpoints are ignored with a warning (not silently
    returned as an over-trained 'shorter' fit); changed evaluator specs
    reject the record; partial-but-parseable state falls back to fresh."""
    import json

    ds, _ = _dataset(rng, task="logistic")
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:900]), ds.subset(rows[900:])
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(task="logistic_regression", iters=2)).fit(
        train, val, checkpoint_dir=ckpt)

    # fewer iterations than the checkpoint covers -> fresh 1-iteration fit
    res = GameEstimator(_config(task="logistic_regression", iters=1)).fit(
        train, val, checkpoint_dir=ckpt)
    assert len(res.objective_history) == 2  # 1 iter x 2 coordinates

    # different evaluator specs -> fingerprint mismatch -> fresh fit
    res2 = GameEstimator(_config(task="logistic_regression", iters=1)).fit(
        train, val, evaluator_specs=["LOGISTIC_LOSS"], checkpoint_dir=ckpt)
    assert res2.descent.total_iterations() > 0

    # parseable state missing required keys -> fresh start, no crash
    state_path = tmp_path / "ckpt" / "state.json"
    with open(state_path) as f:
        st = json.load(f)
    del st["completed_iterations"]
    with open(state_path, "w") as f:
        json.dump(st, f)
    res3 = GameEstimator(_config(task="logistic_regression", iters=1)).fit(
        train, val, checkpoint_dir=ckpt)
    assert res3.descent.total_iterations() > 0


def test_checkpoint_prune_refuses_foreign_paths(rng, tmp_path):
    """A corrupt/foreign state.json pointing outside the checkpoint dir must
    never be rmtree'd (ADVICE r3 medium): the prune step only deletes paths
    contained in the checkpoint directory."""
    import json

    ds, _ = _dataset(rng, task="logistic")
    ckpt = tmp_path / "ckpt"
    victim = tmp_path / "victim"
    victim.mkdir()
    (victim / "precious.txt").write_text("do not delete")
    ckpt.mkdir()
    # forge a state record whose model_dir points OUTSIDE the checkpoint dir
    with open(ckpt / "state.json", "w") as f:
        json.dump({"completed_iterations": 0, "model_dir": str(victim),
                   "best_model_dir": None, "best_metric": None,
                   "objective_history": [], "validation_history": {}}, f)
    GameEstimator(_config(task="logistic_regression", iters=1)).fit(
        ds, checkpoint_dir=str(ckpt))
    assert (victim / "precious.txt").exists()


def test_checkpoint_corrupt_npz_falls_back_fresh(rng, tmp_path):
    """A truncated coefficients archive in the checkpointed model raises
    BadZipFile on load; read_checkpoint must treat it as no-checkpoint
    (fresh retrain), not crash (ADVICE r3 low)."""
    import glob

    ds, _ = _dataset(rng, task="logistic")
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(task="logistic_regression", iters=1)).fit(
        ds, checkpoint_dir=ckpt)
    for npz in glob.glob(f"{ckpt}/iter-*/**/*.npz", recursive=True):
        with open(npz, "wb") as f:
            f.write(b"PK\x03\x04 truncated")
    res = GameEstimator(_config(task="logistic_regression", iters=1)).fit(
        ds, checkpoint_dir=ckpt)
    assert res.descent.total_iterations() > 0  # retrained, no crash


def test_phase_timings_cover_fit_wall_clock(rng, tmp_path):
    """Every stage of a fit is inside a named span (VERDICT r3 weak #1:
    65% of the flagship bench wall-clock was unattributed): the span sum
    must account for >=90% of the measured fit wall clock, and all span
    families must be present."""
    import time as _time

    ds, _ = _dataset(rng, task="logistic")
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:900]), ds.subset(rows[900:])
    est = GameEstimator(_config(task="logistic_regression", iters=2))
    t0 = _time.perf_counter()
    res = est.fit(train, val, checkpoint_dir=str(tmp_path / "ckpt"))
    wall = _time.perf_counter() - t0
    spans = res.descent.timings
    for family in ("build/coordinates", "init/transfer", "init/score",
                   "0/fixed/solve", "0/fixed/objective",
                   "0/fixed/validation", "0/checkpoint",
                   "1/perUser/solve"):
        assert family in spans, sorted(spans)
    coverage = sum(spans.values()) / wall
    assert coverage >= 0.9, (coverage, dict(spans))
