"""Seeded synthetic data generators for tests.

Role of the reference's SparkTestUtils generators (reference:
photon-test-utils/.../test/SparkTestUtils.scala:85+, e.g.
drawBalancedSampleFromNumericallyBenignDenseFeaturesForBinaryClassifierLocal)
and GameTestUtils (photon-api/.../util/GameTestUtils.scala:61-296).
"""
from __future__ import annotations

import numpy as np


def make_glm_data(rng, n=256, d=10, task="logistic", noise=0.1, weight_range=None):
    """Well-conditioned GLM data with known true coefficients."""
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0  # intercept column
    w_true = rng.normal(size=d)
    z = x @ w_true
    if task == "logistic":
        p = 1.0 / (1.0 + np.exp(-z))
        y = (rng.uniform(size=n) < p).astype(float)
    elif task == "linear":
        y = z + noise * rng.normal(size=n)
    elif task == "poisson":
        z = 0.3 * z  # keep rates sane
        w_true = 0.3 * w_true
        y = rng.poisson(np.exp(z)).astype(float)
    elif task == "hinge":
        y = (z > 0).astype(float)
    else:
        raise ValueError(task)
    weights = None
    if weight_range is not None:
        weights = rng.uniform(*weight_range, size=n)
    return x, y, weights, w_true


def make_entity_data(rng, num_entities=16, samples_per_entity=(5, 40), d=4, task="logistic"):
    """Ragged per-entity datasets for random-effect tests."""
    out = []
    for _ in range(num_entities):
        n = int(rng.integers(*samples_per_entity))
        x, y, _, w = make_glm_data(rng, n=n, d=d, task=task)
        out.append((x, y, w))
    return out
