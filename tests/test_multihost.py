"""Multi-host distributed training (ISSUE 19): 2-process CPU bring-up via
subprocess workers, exercised end to end through the real CLI.

Topology trick that makes every gate BIT-EXACT instead of approximate: the
GLOBAL mesh shape is held constant across process counts — 2 processes x 1
virtual device each and 1 process x 2 virtual devices both build the same
(data=2, feature=1) mesh, so GSPMD emits identical reductions and the
objective histories match to the last bit.

Legs (one shared fixture runs the subprocess fleet once):
  * f64 objective-history parity + bit-identical model: 2proc x 1dev vs
    1proc x 2dev
  * zero fresh traces across warm outer iterations: compile_count is
    identical between a short and a long run of the same shapes
  * per-process data plane: each host stages ~1/P of the dataset cold and
    warm bytes stay bounded (no per-iteration restage)
  * lost-worker containment: SIGKILL one worker mid-run -> the survivor
    exits 75 (EXIT_PREEMPTED) with checkpoint-consistent state -> a
    relaunch at --num-processes 1 (2 local devices, same global mesh)
    resumes from the manifest-verified checkpoint and finishes BIT-EXACT
    vs an uninterrupted reference
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RUN_TIMEOUT = 240  # per-worker hard wall, generous for cold jax imports

HEARTBEAT_ENV = {
    "PHOTON_HEARTBEAT_INTERVAL": "0.2",
    "PHOTON_HEARTBEAT_TIMEOUT": "2",
    "PHOTON_HEARTBEAT_ESCALATE": "5",
}


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_inputs(root, n=512, d=8, outer=8, seed=3):
    from photon_ml_tpu.data import build_game_dataset
    from photon_ml_tpu.data.game_data import save_game_dataset

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-x @ w))).astype(
        np.float64)
    data = os.path.join(root, "data.npz")
    save_game_dataset(build_game_dataset(y, {"global": x}), data)
    config = os.path.join(root, f"game-{outer}.json")
    with open(config, "w") as f:
        json.dump({
            "task_type": "logistic_regression",
            "coordinates": {
                "fixed": {
                    "kind": "fixed_effect",
                    "feature_shard": "global",
                    "optimization": {
                        "optimizer": {"optimizer": "lbfgs",
                                      "max_iterations": 3},
                        "regularization": {"type": "l2"},
                        "regularization_weight": 1.0,
                    },
                }
            },
            "updating_sequence": ["fixed"],
            "num_outer_iterations": outer,
        }, f)
    return data, config


def _spawn(data, config, out_dir, *, devices, coordinator=None,
           num_processes=None, process_id=None, extra_env=None):
    """One CLI worker as a subprocess; stdout/stderr land in out_dir."""
    cmd = [sys.executable, "-m", "photon_ml_tpu.cli.train",
           "--train-data", data, "--config", config, "--x64",
           "--mesh", "auto", "--no-compile-cache",
           "--checkpoint-dir", os.path.join(out_dir, "ckpt"),
           "--output-dir", out_dir]
    if coordinator is not None:
        cmd += ["--coordinator", coordinator,
                "--num-processes", str(num_processes),
                "--process-id", str(process_id)]
    env = dict(os.environ)
    env.pop("PHOTON_COORDINATOR", None)
    env.pop("PHOTON_NUM_PROCESSES", None)
    env.pop("PHOTON_PROCESS_ID", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env.update(extra_env or {})
    tag = "" if process_id is None else f".proc{process_id}"
    out = open(os.path.join(out_dir, f"worker{tag}.out"), "w")
    err = open(os.path.join(out_dir, f"worker{tag}.err"), "w")
    proc = subprocess.Popen(cmd, cwd=_REPO, env=env, stdout=out,
                            stderr=err)
    proc._photon_streams = (out, err)  # closed in _finish
    proc._photon_out_path = out.name
    return proc


def _finish(proc, timeout=_RUN_TIMEOUT):
    try:
        rc = proc.wait(timeout=timeout)
    finally:
        for h in proc._photon_streams:
            h.close()
    return rc


def _last_json(path):
    lines = [ln for ln in open(path).read().splitlines() if ln.strip()]
    for ln in reversed(lines):
        try:
            return json.loads(ln)
        except ValueError:
            continue
    raise AssertionError(f"no JSON line in {path}")


def _run_pair(data, config, out_dir, extra_env=None):
    """2 processes x 1 virtual device each over a localhost coordinator."""
    os.makedirs(out_dir, exist_ok=True)
    port = _free_port()
    workers = [
        _spawn(data, config, out_dir, devices=1,
               coordinator=f"localhost:{port}", num_processes=2,
               process_id=pid, extra_env=extra_env)
        for pid in (0, 1)
    ]
    return [(_finish(w), w._photon_out_path) for w in workers]


def _read_history(out_dir):
    with open(os.path.join(out_dir, "ckpt", "state.json")) as f:
        return json.load(f)["objective_history"]


def _model_files(out_dir):
    best = os.path.join(out_dir, "best")
    out = {}
    for root, _, names in os.walk(best):
        for fn in names:
            p = os.path.join(root, fn)
            out[os.path.relpath(p, best)] = open(p, "rb").read()
    return out


@pytest.fixture(scope="module")
def mh(tmp_path_factory):
    """The subprocess fleet, run once: a 2-process run, a short 2-process
    run (trace gate), a single-process reference, and the kill/resume
    scenario."""
    root = str(tmp_path_factory.mktemp("multihost"))
    data, config = _write_inputs(root, outer=8)
    _, config_short = _write_inputs(root, outer=3)

    two = os.path.join(root, "two")          # 2 proc x 1 dev
    ref = os.path.join(root, "ref")          # 1 proc x 2 dev (same mesh)
    short = os.path.join(root, "short")      # 2 proc, fewer outers
    results = {"root": root, "data": data, "config": config}

    results["two"] = _run_pair(data, config, two, HEARTBEAT_ENV)
    results["short"] = _run_pair(data, config_short, short, HEARTBEAT_ENV)
    os.makedirs(ref, exist_ok=True)
    p = _spawn(data, config, ref, devices=2)
    results["ref"] = (_finish(p), p._photon_out_path)
    results["dirs"] = {"two": two, "ref": ref, "short": short}
    return results


def test_two_process_run_completes(mh):
    for rc, _ in mh["two"]:
        assert rc == 0
    rc, out_path = mh["ref"]
    assert rc == 0
    # primary owns the durable artifacts; the secondary writes none
    two = mh["dirs"]["two"]
    assert os.path.exists(os.path.join(two, "training-summary.json"))
    summary = _last_json(mh["two"][0][1])
    assert summary["multihost"] == {"num_processes": 2, "process_id": 0}
    # per-process log files, heartbeats for both workers
    assert os.path.exists(os.path.join(two, "training.log"))
    assert os.path.exists(os.path.join(two, "training.proc1.log"))
    for pid in (0, 1):
        beat = json.load(open(os.path.join(
            two, "heartbeats", f"proc-{pid}.json")))
        assert beat["done"] is True


def test_objective_history_parity_bit_exact(mh):
    """Same global mesh => same GSPMD program => f64 histories match to
    the last bit (the gate requirement is <= 1e-8; expect 0)."""
    h2 = np.asarray(_read_history(mh["dirs"]["two"]), dtype=np.float64)
    h1 = np.asarray(_read_history(mh["dirs"]["ref"]), dtype=np.float64)
    assert h2.shape == h1.shape
    np.testing.assert_allclose(h2, h1, rtol=0.0, atol=1e-8)
    assert float(np.max(np.abs(h2 - h1))) == 0.0


def test_final_model_bit_identical(mh):
    a = _model_files(mh["dirs"]["two"])
    b = _model_files(mh["dirs"]["ref"])
    assert sorted(a) == sorted(b) and a
    for name in a:
        if name == "model-metadata.json":
            continue  # carries timestamps
        assert a[name] == b[name], f"{name} differs across process counts"


def test_zero_fresh_traces_warm(mh):
    """All compiles happen in the cold iterations: a 3-outer and an
    8-outer run of identical shapes trace the same program set."""
    long_run = _last_json(mh["two"][0][1])
    short_run = _last_json(mh["short"][0][1])
    assert long_run["compile_count"] == short_run["compile_count"]
    # and on the secondary process too
    long_1 = _last_json(mh["two"][1][1])
    short_1 = _last_json(mh["short"][1][1])
    assert long_1["compile_count"] == short_1["compile_count"]


def test_per_process_staging_bounded(mh):
    """Each host stages only its shard: cold bytes are symmetric across
    processes, and warm per-iteration traffic is vectors (coefficients +
    local residual rows), never a dataset restage."""
    s0 = _last_json(mh["two"][0][1])
    s1 = _last_json(mh["two"][1][1])
    n, d, outer, procs = 512, 8, 8, 2
    for s in (s0, s1):
        mt = s["mesh_transfer"]
        assert mt["cold_bytes"] > 0
        # warm traffic per outer iteration: a few vectors of the LOCAL
        # row count plus coefficients, with generous slack — far below
        # restaging the local dataset shard every iteration
        per_iter = mt["warm_bytes"] / outer
        assert per_iter <= 8 * (n // procs + d) * 8
    ratio = (max(s0["mesh_transfer"]["cold_bytes"],
                 s1["mesh_transfer"]["cold_bytes"])
             / max(1, min(s0["mesh_transfer"]["cold_bytes"],
                          s1["mesh_transfer"]["cold_bytes"])))
    assert ratio <= 1.5


def test_lost_worker_survivor_exits_75_and_resume_is_bit_exact(mh, tmp_path):
    """SIGKILL worker 1 mid-run: the survivor detects the silence via the
    heartbeat watchdog, exits 75 (resumable, checkpoint-consistent), and
    a single-process relaunch over the same global mesh resumes from the
    checkpoint and finishes bit-exact vs the uninterrupted reference."""
    from photon_ml_tpu.utils import faults

    data, config = mh["data"], mh["config"]
    out = str(tmp_path / "kill")
    os.makedirs(out)
    port = _free_port()
    w0 = _spawn(data, config, out, devices=1,
                coordinator=f"localhost:{port}", num_processes=2,
                process_id=0, extra_env=HEARTBEAT_ENV)
    w1 = _spawn(data, config, out, devices=1,
                coordinator=f"localhost:{port}", num_processes=2,
                process_id=1, extra_env=HEARTBEAT_ENV)

    # wait for the first durable checkpoint record, then kill worker 1
    state = os.path.join(out, "ckpt", "state.json")
    deadline = time.time() + _RUN_TIMEOUT
    while not os.path.exists(state) and time.time() < deadline:
        time.sleep(0.1)
    assert os.path.exists(state), "no checkpoint appeared before timeout"
    os.kill(w1.pid, signal.SIGKILL)
    _finish(w1)

    rc0 = _finish(w0)
    assert rc0 == faults.EXIT_PREEMPTED == 75
    payload = _last_json(w0._photon_out_path)
    assert payload["preempted"] is True
    assert payload["lost_worker"] == 1

    # relaunch over the survivor alone: 1 process x 2 devices keeps the
    # global mesh, so the resumed math is the same program
    r = _spawn(data, config, out, devices=2)
    assert _finish(r) == 0
    resumed = _last_json(r._photon_out_path)
    # the kill can land right after the FIRST durable record (completed
    # iteration 1 -> resumed_from_iteration 0), so assert only that a real
    # checkpoint was recovered; the bit-exactness checks below do the rest
    assert resumed["checkpoint_recovery"]["resumed_from_iteration"] >= 0
    assert resumed["checkpoint_recovery"]["fallback"] is False

    reference = _last_json(mh["ref"][1])
    assert resumed["final_objective"] == reference["final_objective"]
    a, b = _model_files(out), _model_files(mh["dirs"]["ref"])
    assert sorted(a) == sorted(b) and a
    for name in a:
        if name == "model-metadata.json":
            continue
        assert a[name] == b[name], f"{name} differs after resume"
