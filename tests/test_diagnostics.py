"""Diagnostics: extended metrics, bootstrap CIs, HL calibration, Kendall-tau,
feature importance, fitting curves, report rendering.

Mirrors photon-diagnostics test strategy: closed-form/sklearn-free checks on
small fixtures with seeded RNGs, plus a CLI smoke test emitting the report.
"""
import json
import math

import numpy as np
import pytest

from photon_ml_tpu.diagnostics import (
    BootstrapReport, DiagnosticReport, bootstrap_training, evaluate_glm,
    evaluate_scores, feature_importance, fitting_diagnostic, hosmer_lemeshow,
    kendall_tau_analysis, render_markdown,
)
from photon_ml_tpu.diagnostics import metrics as M
from photon_ml_tpu.optim import RegularizationContext, RegularizationType

L2 = RegularizationContext(RegularizationType.L2)


# -- metrics ------------------------------------------------------------------

def test_binary_metrics_perfect_classifier():
    preds = np.asarray([0.9, 0.8, 0.2, 0.1])
    labels = np.asarray([1.0, 1.0, 0.0, 0.0])
    m = evaluate_scores("logistic_regression", preds, np.log(preds / (1 - preds)),
                        labels, coefficients=np.ones(3))
    assert m[M.AREA_UNDER_ROC] == pytest.approx(1.0)
    assert m[M.AREA_UNDER_PRECISION_RECALL] == pytest.approx(1.0)
    assert m[M.PEAK_F1_SCORE] == pytest.approx(1.0)
    assert M.AKAIKE_INFORMATION_CRITERION in m


def test_auc_matches_rank_formulation(rng):
    from photon_ml_tpu.evaluation.evaluators import area_under_roc_curve
    preds = rng.uniform(size=200)
    labels = (rng.uniform(size=200) > 0.6).astype(float)
    assert M.area_under_roc(preds, labels) == pytest.approx(
        area_under_roc_curve(preds, labels), abs=1e-9)


def test_regression_metrics():
    preds = np.asarray([1.0, 2.0, 3.0])
    labels = np.asarray([1.5, 2.0, 2.0])
    m = evaluate_scores("linear_regression", preds, preds, labels)
    assert m[M.MEAN_ABSOLUTE_ERROR] == pytest.approx(0.5)
    assert m[M.MEAN_SQUARE_ERROR] == pytest.approx((0.25 + 0 + 1) / 3)
    assert m[M.ROOT_MEAN_SQUARE_ERROR] == pytest.approx(
        math.sqrt((0.25 + 0 + 1) / 3))


def test_logistic_log_likelihood_clamps():
    # exact 0/1 predictions must not produce -inf (reference epsilon clamp)
    ll = M.logistic_log_likelihood(np.asarray([0.0, 1.0]), np.asarray([0.0, 1.0]))
    assert np.isfinite(ll)


def test_poisson_log_likelihood():
    margins = np.asarray([0.0, 1.0])
    labels = np.asarray([1.0, 2.0])
    expect = np.mean(labels * margins - np.exp(margins)
                     - np.asarray([math.lgamma(2.0), math.lgamma(3.0)]))
    assert M.poisson_log_likelihood(margins, labels) == pytest.approx(expect)


def test_evaluate_glm_end_to_end(rng):
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import model_for_task
    import jax.numpy as jnp
    x = rng.normal(size=(300, 5)); x[:, -1] = 1.0
    w = rng.normal(size=5)
    y = (rng.uniform(size=300) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    model = model_for_task("logistic_regression", Coefficients(jnp.asarray(w)))
    m = evaluate_glm(model, x, y)
    assert m[M.AREA_UNDER_ROC] > 0.7
    assert m[M.DATA_LOG_LIKELIHOOD] < 0


# -- bootstrap ----------------------------------------------------------------

def test_bootstrap_cis_cover_truth(rng):
    n, d = 600, 4
    x = rng.normal(size=(n, d)); x[:, -1] = 1.0
    w_true = np.asarray([1.0, -2.0, 0.0, 0.5])
    y = x @ w_true + 0.1 * rng.normal(size=n)
    rep = bootstrap_training(x, y, "linear_regression",
                             num_bootstrap_samples=8, training_portion=0.75,
                             regularization=L2, regularization_weight=1e-6,
                             seed=5)
    assert len(rep.coefficient_summaries) == d
    for j, s in enumerate(rep.coefficient_summaries):
        assert s.min <= w_true[j] + 0.15 and s.max >= w_true[j] - 0.15
    # strong coefficients are significant; the zero one is near zero (its
    # IQR may still exclude 0 — replicas share 75% of rows, so estimates of
    # a tiny OLS artifact are tightly correlated)
    assert rep.significant_mask[0] and rep.significant_mask[1]
    assert abs(rep.coefficient_summaries[2].median) < 0.05
    assert M.ROOT_MEAN_SQUARE_ERROR in rep.metric_summaries
    assert rep.metric_summaries[M.ROOT_MEAN_SQUARE_ERROR].median < 0.2


def test_bootstrap_validates_args(rng):
    x, y = rng.normal(size=(20, 2)), rng.normal(size=20)
    with pytest.raises(ValueError):
        bootstrap_training(x, y, "linear_regression", num_bootstrap_samples=1)
    with pytest.raises(ValueError):
        bootstrap_training(x, y, "linear_regression", training_portion=1.5)


# -- Hosmer-Lemeshow ----------------------------------------------------------

def test_hl_well_calibrated_vs_miscalibrated(rng):
    n = 4000
    p = rng.uniform(0.05, 0.95, size=n)
    y_good = (rng.uniform(size=n) < p).astype(float)
    good = hosmer_lemeshow(p, y_good, num_dimensions=8)
    # miscalibrated: probabilities systematically overconfident
    p_bad = np.clip(p ** 3, 0.01, 0.99)
    bad = hosmer_lemeshow(p_bad, y_good, num_dimensions=8)
    assert good.chi_squared < bad.chi_squared
    assert good.prob_at_chi_square < 0.99
    assert bad.prob_at_chi_square > 0.999
    assert bad.degrees_of_freedom == len(bad.bins) - 2
    assert len(good.cutoffs) == 15


# -- Kendall tau --------------------------------------------------------------

def test_kendall_tau_dependent_vs_independent(rng):
    n = 300
    a = rng.normal(size=n)
    dep = kendall_tau_analysis(a, a + 0.1 * rng.normal(size=n))
    ind = kendall_tau_analysis(a, rng.normal(size=n))
    assert dep.tau_alpha > 0.8
    assert abs(ind.tau_alpha) < 0.1
    assert dep.p_value > 0.99       # two-sided mass inside |z|: dependence
    assert ind.p_value < dep.p_value
    assert dep.num_concordant + dep.num_discordant == dep.effective_pairs


def test_kendall_tau_perfect_and_ties():
    a = np.asarray([1.0, 2.0, 3.0, 4.0])
    r = kendall_tau_analysis(a, a * 2)
    assert r.tau_alpha == pytest.approx(1.0)
    rt = kendall_tau_analysis(np.asarray([1.0, 1.0, 2.0]),
                              np.asarray([1.0, 2.0, 3.0]))
    assert "ties" in rt.message


# -- feature importance -------------------------------------------------------

def test_feature_importance_rankings(rng):
    from photon_ml_tpu.data.stats import BasicStatisticalSummary
    x = rng.normal(size=(100, 3)) * np.asarray([1.0, 10.0, 0.1])
    summary = BasicStatisticalSummary.from_features(x)
    c = np.asarray([1.0, 1.0, 1.0])
    rep = feature_importance(c, summary, ["a", "b", "c"], "expected_magnitude")
    assert rep.ranked[0][0] == "b"          # largest scale dominates
    assert rep.ranked[-1][0] == "c"
    rep_v = feature_importance(c, summary, ["a", "b", "c"], "variance")
    assert rep_v.ranked[0][0] == "b"
    no_sum = feature_importance(np.asarray([3.0, 1.0]), None, None)
    assert no_sum.ranked[0][1] == 0          # falls back to |c|


# -- fitting ------------------------------------------------------------------

def test_fitting_curves_improve_with_data(rng):
    n, d = 2000, 4
    x = rng.normal(size=(n, d)); x[:, -1] = 1.0
    y = x @ rng.normal(size=d) + 0.2 * rng.normal(size=n)
    rep = fitting_diagnostic(x, y, "linear_regression",
                             regularization=L2, regularization_weight=1e-6,
                             seed=3)
    assert M.ROOT_MEAN_SQUARE_ERROR in rep.metrics
    curve = rep.metrics[M.ROOT_MEAN_SQUARE_ERROR]
    assert len(curve["portions"]) == 9
    assert curve["portions"] == sorted(curve["portions"])
    # holdout error with 9x data <= error with 1x data (allow noise wiggle)
    assert curve["test"][-1] <= curve["test"][0] * 1.05


def test_fitting_requires_enough_data(rng):
    rep = fitting_diagnostic(rng.normal(size=(30, 5)), rng.normal(size=30),
                             "linear_regression")
    assert rep.metrics == {} and "not enough data" in rep.message


# -- report + CLI -------------------------------------------------------------

def test_report_rendering(rng):
    metrics = {"Area under ROC": 0.91, "Peak F1 score": 0.8}
    rep = DiagnosticReport("logistic_regression", metrics)
    md = render_markdown(rep)
    assert "# Model diagnostic report" in md and "0.91" in md
    d = rep.to_dict()
    json.dumps(d)  # serializable


def test_diagnose_cli_end_to_end(rng, tmp_path):
    import jax.numpy as jnp
    from photon_ml_tpu.data import build_game_dataset
    from photon_ml_tpu.data.game_data import save_game_dataset
    from photon_ml_tpu.game import (
        FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
        GLMOptimizationConfig,
    )
    from photon_ml_tpu.models.io import save_game_model
    from photon_ml_tpu.cli.diagnose import main

    n, d = 900, 5
    x = rng.normal(size=(n, d)); x[:, -1] = 1.0
    w = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    ds = build_game_dataset(y, {"global": x})
    save_game_dataset(ds, str(tmp_path / "data.npz"))

    cfg = GameTrainingConfig(
        "logistic_regression",
        {"fixed": FixedEffectCoordinateConfig(
            "global", GLMOptimizationConfig(regularization=L2,
                                            regularization_weight=0.01))},
        ["fixed"])
    res = GameEstimator(cfg).fit(ds)
    save_game_model(res.model, str(tmp_path / "model"), config=cfg)

    rc = main(["--model-dir", str(tmp_path / "model"),
               "--data", str(tmp_path / "data.npz"),
               "--output-dir", str(tmp_path / "diag"),
               "--bootstrap-samples", "4"])
    assert rc == 0
    report = json.loads((tmp_path / "diag" / "report.json").read_text())
    assert report["task_type"] == "logistic_regression"
    assert report["metrics"]["Area under ROC"] > 0.7
    assert "hosmer_lemeshow" in report
    assert "bootstrap" in report
    assert "fitting" in report
    md = (tmp_path / "diag" / "report.md").read_text()
    assert "Hosmer-Lemeshow" in md and "Learning curves" in md
    # self-contained HTML: inline CSS + inline SVG charts, no external
    # resources (VERDICT r4 coverage item #95)
    html = (tmp_path / "diag" / "report.html").read_text()
    assert "<style>" in html and html.count("<svg") >= 2
    assert "Hosmer-Lemeshow" in html and "Learning curves" in html
    assert "http://" not in html and "https://" not in html
    assert "<script" not in html


def test_diagnose_cli_avro_input(rng, tmp_path):
    """Avro diagnostics data resolves in the MODEL's feature space (same
    pinning as scoring) and produces the same metrics as the npz path."""
    from photon_ml_tpu.cli.diagnose import main
    from photon_ml_tpu.data.avro_game import write_game_examples
    from photon_ml_tpu.data.game_data import save_game_dataset
    from photon_ml_tpu.data.index_map import build_index_map
    from photon_ml_tpu.game import (
        FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
        GLMOptimizationConfig,
    )
    from photon_ml_tpu.models.io import save_game_model

    n = 400
    imap = build_index_map([(f"f{i}", "") for i in range(4)])
    # values exact in BOTH f32 (avro read) and f64 (npz): generate at f32
    # precision, store f64
    x = np.zeros((n, imap.size), np.float64)
    x[:, :-1] = rng.normal(size=(n, 4)).astype(np.float32).astype(np.float64)
    x[:, -1] = 1.0
    w = rng.normal(size=imap.size)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)

    from photon_ml_tpu.data import build_game_dataset
    ds = build_game_dataset(y, {"global": x}, index_maps={"global": imap})
    cfg = GameTrainingConfig(
        "logistic_regression",
        {"fixed": FixedEffectCoordinateConfig(
            "global", GLMOptimizationConfig(regularization=L2,
                                            regularization_weight=0.01))},
        ["fixed"])
    res = GameEstimator(cfg).fit(ds)
    save_game_model(res.model, str(tmp_path / "model"), config=cfg,
                    index_maps=ds.index_maps)

    avro_p = str(tmp_path / "data.avro")
    write_game_examples(avro_p, y, bags={"features": (x, imap)})
    rc = main(["--model-dir", str(tmp_path / "model"),
               "--data", avro_p,
               "--output-dir", str(tmp_path / "diag-avro"),
               "--skip-bootstrap", "--skip-fitting"])
    assert rc == 0
    rep_avro = json.loads((tmp_path / "diag-avro" / "report.json").read_text())

    save_game_dataset(ds, str(tmp_path / "data.npz"))
    rc = main(["--model-dir", str(tmp_path / "model"),
               "--data", str(tmp_path / "data.npz"),
               "--output-dir", str(tmp_path / "diag-npz"),
               "--skip-bootstrap", "--skip-fitting"])
    assert rc == 0
    rep_npz = json.loads((tmp_path / "diag-npz" / "report.json").read_text())
    for k, v in rep_npz["metrics"].items():
        np.testing.assert_allclose(rep_avro["metrics"][k], v, rtol=1e-5,
                                   err_msg=k)
