"""Pallas fused value+gradient kernel == XLA aggregator.

The kernel is a measured experiment (see its module docstring: XLA's own
fusion wins on these shapes, so the product path stays on the XLA
aggregator) — but it must stay CORRECT so the recipe remains trustworthy.
Runs in interpreter mode on the CPU test mesh.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.ops import LOGISTIC, POISSON, SQUARED, aggregators
from photon_ml_tpu.ops.pallas_kernels import available, fused_value_and_gradient

pytestmark = pytest.mark.skipif(not available(),
                                reason="jax.experimental.pallas unavailable")


@pytest.mark.parametrize("loss", [LOGISTIC, SQUARED, POISSON],
                         ids=lambda l: l.name)
@pytest.mark.parametrize("with_wo", [False, True], ids=["plain", "w+o"])
def test_matches_xla_aggregator(loss, with_wo, rng):
    n, d = 700, 37   # deliberately unaligned: exercises row/column padding
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    c = (rng.normal(size=d) * 0.1).astype(np.float32)
    w = rng.uniform(0.5, 2.0, size=n).astype(np.float32) if with_wo else None
    o = (rng.normal(size=n) * 0.1).astype(np.float32) if with_wo else None

    v, g = fused_value_and_gradient(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(c),
        None if w is None else jnp.asarray(w),
        None if o is None else jnp.asarray(o), True)
    v2, g2 = aggregators.value_and_gradient(
        loss, jnp.asarray(x), jnp.asarray(y), jnp.asarray(c),
        weights=None if w is None else jnp.asarray(w),
        offsets=None if o is None else jnp.asarray(o))
    np.testing.assert_allclose(float(v), float(v2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)
