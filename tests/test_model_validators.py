"""Composable trained-model validity checks.

reference: photon-api/src/integTest/.../supervised/*Validator.scala — the
model-validity suite the reference's integration tests compose (finite
predictions, binary class labels, non-negative means, max error bound,
minimum AUC).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.models import (
    BinaryClassifierAUCValidator, BinaryPredictionValidator, Coefficients,
    CompositeModelValidator, MaximumDifferenceValidator, ModelValidationError,
    NonNegativePredictionValidator, PredictionFiniteValidator,
)
from photon_ml_tpu.models.glm import model_for_task


def _model(task, w):
    return model_for_task(task, Coefficients(jnp.asarray(w, jnp.float32)))


def test_finite_validator(rng):
    x = rng.normal(size=(50, 3)).astype(np.float32)
    m = _model("linear_regression", [1.0, -2.0, 0.5])
    PredictionFiniteValidator().validate(m, jnp.asarray(x))
    bad = _model("linear_regression", [np.inf, 0.0, 0.0])
    with pytest.raises(ModelValidationError, match="NaN or \\+/-Inf"):
        PredictionFiniteValidator().validate(bad, jnp.asarray(x))


def test_binary_prediction_validator(rng):
    x = rng.normal(size=(40, 2)).astype(np.float32)
    m = _model("logistic_regression", [1.0, -1.0])
    BinaryPredictionValidator().validate(m, jnp.asarray(x))
    reg = _model("linear_regression", [1.0, -1.0])
    with pytest.raises(ModelValidationError, match="requires a classifier"):
        BinaryPredictionValidator().validate(reg, jnp.asarray(x))
    # smoothed hinge is a raw-margin classifier but still emits {0, 1}
    svm = _model("smoothed_hinge_loss_linear_svm", [1.0, -1.0])
    BinaryPredictionValidator().validate(svm, jnp.asarray(x))


def test_non_negative_validator(rng):
    x = np.abs(rng.normal(size=(30, 2))).astype(np.float32)
    poisson = _model("poisson_regression", [0.1, 0.2])
    NonNegativePredictionValidator().validate(poisson, jnp.asarray(x))
    linear = _model("linear_regression", [-1.0, -1.0])
    with pytest.raises(ModelValidationError, match="negative predictions"):
        NonNegativePredictionValidator().validate(linear, jnp.asarray(x))


def test_maximum_difference_validator(rng):
    x = rng.normal(size=(60, 2)).astype(np.float32)
    w = np.asarray([1.5, -0.7])
    y = x @ w
    m = _model("linear_regression", w)
    MaximumDifferenceValidator(0.01).validate(m, jnp.asarray(x), y)
    with pytest.raises(ModelValidationError, match="prediction error"):
        MaximumDifferenceValidator(0.01).validate(m, jnp.asarray(x), y + 1.0)
    with pytest.raises(ValueError, match="must be > 0"):
        MaximumDifferenceValidator(0.0)


def test_auc_validator(rng):
    n = 400
    x = rng.normal(size=(n, 3)).astype(np.float32)
    w = np.asarray([2.0, -1.0, 0.5])
    y = (x @ w > 0).astype(np.float64)
    m = _model("logistic_regression", w)
    BinaryClassifierAUCValidator(0.95).validate(m, jnp.asarray(x), y)
    anti = _model("logistic_regression", -w)
    with pytest.raises(ModelValidationError, match="AUROC"):
        BinaryClassifierAUCValidator(0.95).validate(anti, jnp.asarray(x), y)
    with pytest.raises(ValueError, match="minimum_auc"):
        BinaryClassifierAUCValidator(0.3)


def test_composite_validator_shares_predictions(rng, monkeypatch):
    import photon_ml_tpu.models.validators as mv
    x = rng.normal(size=(50, 2)).astype(np.float32)
    w = np.asarray([1.0, 1.0])
    y = x @ w
    m = _model("linear_regression", w)
    calls = {"n": 0}
    orig = mv._predictions

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(mv, "_predictions", counting)
    CompositeModelValidator(
        PredictionFiniteValidator(),
        MaximumDifferenceValidator(0.5),
    ).validate(m, jnp.asarray(x), y)
    assert calls["n"] == 1  # one shared device round trip
    with pytest.raises(ModelValidationError):
        CompositeModelValidator(
            PredictionFiniteValidator(),
            MaximumDifferenceValidator(0.5),
        ).validate(m, jnp.asarray(x), y + 3.0)
    # iterable form + dataclasses.replace both work
    import dataclasses
    c = CompositeModelValidator([PredictionFiniteValidator()])
    dataclasses.replace(c, validators=[PredictionFiniteValidator()]) \
        .validate(m, jnp.asarray(x), y)
    # label-requiring validators are named clearly when labels are missing
    with pytest.raises(ModelValidationError, match="require labels"):
        CompositeModelValidator(MaximumDifferenceValidator(1.0)) \
            .validate(m, jnp.asarray(x))


def test_binary_validator_reuses_shared_predictions(rng, monkeypatch):
    """Mean-threshold classifiers derive classes from the shared prediction
    array inside a composite (no second forward pass)."""
    import photon_ml_tpu.models.validators as mv
    x = rng.normal(size=(40, 2)).astype(np.float32)
    m = _model("logistic_regression", [1.0, -1.0])
    calls = {"n": 0}
    orig = mv._predictions

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(mv, "_predictions", counting)
    CompositeModelValidator(PredictionFiniteValidator(),
                            BinaryPredictionValidator()) \
        .validate(m, jnp.asarray(x))
    assert calls["n"] == 1
