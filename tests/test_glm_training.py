"""End-to-end single-device GLM training: sweep, warm start, normalization
invariance, variances.

Mirrors the reference's ModelTraining + GameEstimator normalization-invariance
tests (GameEstimatorTest.scala:125-180): the final loss must be identical (to
tolerance) across all normalization types because margins are invariant.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.models import train_glm
from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective, build_normalization_context
from photon_ml_tpu.optim import (
    OptimizerConfig, OptimizerType, RegularizationContext, RegularizationType,
)
from tests.synthetic import make_glm_data

TASKS = ["logistic_regression", "linear_regression", "poisson_regression"]


@pytest.mark.parametrize("task", TASKS)
def test_sweep_monotone_loss_in_lambda(task, rng):
    gen = {"logistic_regression": "logistic", "linear_regression": "linear",
           "poisson_regression": "poisson"}[task]
    x, y, _, _ = make_glm_data(rng, n=300, d=8, task=gen)
    trained = train_glm(jnp.asarray(x), jnp.asarray(y), task,
                        regularization=RegularizationContext(RegularizationType.L2),
                        regularization_weights=[10.0, 1.0, 0.1])
    assert [t.reg_weight for t in trained] == [10.0, 1.0, 0.1]
    # unregularized data loss must decrease as lambda decreases
    loss = TASK_LOSSES[task]
    obj = GLMObjective(loss, jnp.asarray(x), jnp.asarray(y))
    data_losses = [float(obj.value(t.model.coefficients.means)) for t in trained]
    assert data_losses[0] >= data_losses[1] >= data_losses[2]
    for t in trained:
        assert t.model.validate_coefficients()


@pytest.mark.parametrize("norm_type", ["none", "scale_with_standard_deviation",
                                       "scale_with_max_magnitude", "standardization"])
def test_normalization_invariance(norm_type, rng):
    """Final original-space loss must agree across normalization types
    (reference: GameEstimatorTest normalization invariance)."""
    x, y, _, _ = make_glm_data(rng, n=400, d=6, task="logistic")
    xj, yj = jnp.asarray(x), jnp.asarray(y)
    norm = None
    if norm_type != "none":
        norm = build_normalization_context(
            norm_type, mean=xj.mean(0), variance=xj.var(0, ddof=1),
            max_magnitude=jnp.abs(xj).max(0), intercept_index=5)
    trained = train_glm(xj, yj, "logistic_regression",
                        normalization=norm,
                        regularization_weights=[0.0],
                        optimizer_config=OptimizerConfig(tolerance=1e-10,
                                                         max_iterations=300))
    obj = GLMObjective(TASK_LOSSES["logistic_regression"], xj, yj)
    final = float(obj.value(trained[0].model.coefficients.means))
    # the unregularized optimum is normalization-independent
    baseline = float(obj.value(train_glm(xj, yj, "logistic_regression",
                                         regularization_weights=[0.0],
                                         optimizer_config=OptimizerConfig(
                                             tolerance=1e-10, max_iterations=300)
                                         )[0].model.coefficients.means))
    assert abs(final - baseline) / max(1.0, abs(baseline)) < 1e-6


def test_warm_start_reduces_iterations(rng):
    x, y, _, _ = make_glm_data(rng, n=400, d=10, task="logistic")
    kw = dict(regularization=RegularizationContext(RegularizationType.L2),
              regularization_weights=[10.0, 5.0, 1.0, 0.5, 0.1])
    warm = train_glm(jnp.asarray(x), jnp.asarray(y), "logistic_regression",
                     warm_start=True, **kw)
    cold = train_glm(jnp.asarray(x), jnp.asarray(y), "logistic_regression",
                     warm_start=False, **kw)
    # same optima
    for w, c in zip(warm, cold):
        np.testing.assert_allclose(w.result.value, c.result.value, rtol=1e-5)
    assert (sum(int(t.result.iterations) for t in warm)
            <= sum(int(t.result.iterations) for t in cold))


def test_variances_match_inverse_hessian_diagonal(rng):
    x, y, _, _ = make_glm_data(rng, n=300, d=5, task="linear")
    trained = train_glm(jnp.asarray(x), jnp.asarray(y), "linear_regression",
                        regularization_weights=[0.0], compute_variances=True)
    v = trained[0].model.coefficients.variances
    assert v is not None and v.shape == (5,)
    # linear regression: diag(H) = diag(X^T X); variances ~ 1/diag
    want = 1.0 / (np.sum(np.asarray(x) ** 2, axis=0) + 1e-12)
    np.testing.assert_allclose(np.asarray(v), want, rtol=1e-10)


def test_tron_and_lbfgs_reach_same_optimum(rng):
    x, y, _, _ = make_glm_data(rng, n=300, d=6, task="poisson")
    kw = dict(regularization=RegularizationContext(RegularizationType.L2),
              regularization_weights=[1.0])
    a = train_glm(jnp.asarray(x), jnp.asarray(y), "poisson_regression",
                  optimizer_config=OptimizerConfig(optimizer=OptimizerType.LBFGS,
                                                   tolerance=1e-9), **kw)
    b = train_glm(jnp.asarray(x), jnp.asarray(y), "poisson_regression",
                  optimizer_config=OptimizerConfig(optimizer=OptimizerType.TRON), **kw)
    assert abs(float(a[0].result.value) - float(b[0].result.value)) < 1e-4


def test_prediction_api(rng):
    x, y, _, w_true = make_glm_data(rng, n=200, d=4, task="logistic")
    m = train_glm(jnp.asarray(x), jnp.asarray(y), "logistic_regression",
                  regularization_weights=[0.01],
                  regularization=RegularizationContext(RegularizationType.L2))[0].model
    p = np.asarray(m.predict(jnp.asarray(x)))
    assert p.min() >= 0 and p.max() <= 1
    acc = ((p > 0.5) == (y > 0.5)).mean()
    bayes_acc = (((x @ w_true) > 0) == (y > 0.5)).mean()  # true-model accuracy
    assert acc >= bayes_acc - 0.02
    cls = np.asarray(m.predict_class(jnp.asarray(x)))
    assert set(np.unique(cls)) <= {0, 1}
