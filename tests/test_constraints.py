"""Named-feature box-constraint maps.

reference: GLMSuite.createConstraintFeatureMap (photon-client/.../io/
deprecated/GLMSuite.scala:206-280) + ConstraintMapKeys.scala — JSON
{name, term, lowerBound, upperBound} entries resolved through the index
map into positional per-coefficient bounds.
"""
import json
import math

import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset, build_index_map
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.constraints import (constraints_to_json,
                                             normalize_constraints,
                                             resolve_constraints)

INF = math.inf


def _imap():
    return build_index_map([("age", ""), ("age", "young"), ("height", ""),
                            ("weight", "kg")])  # + intercept, sorted


# -- normalize ----------------------------------------------------------------

def test_normalize_defaults_missing_bounds_to_inf():
    (entry,) = normalize_constraints([{"name": "age", "term": "",
                                       "lowerBound": -1.0}])
    assert entry == ("age", "", -1.0, INF)
    (entry,) = normalize_constraints([{"name": "age", "term": "",
                                       "upperBound": 2.5}])
    assert entry == ("age", "", -INF, 2.5)


def test_normalize_rejects_bad_entries():
    with pytest.raises(ValueError, match="unconstrained entry is invalid"):
        normalize_constraints([{"name": "a", "term": ""}])
    with pytest.raises(ValueError, match="must be < upper"):
        normalize_constraints([{"name": "a", "term": "", "lowerBound": 2.0,
                                "upperBound": 1.0}])
    with pytest.raises(ValueError, match="wildcard in feature name alone"):
        normalize_constraints([{"name": "*", "term": "t",
                                "lowerBound": 0.0}])
    with pytest.raises(ValueError, match="must specify 'name' and 'term'"):
        normalize_constraints([{"name": "a", "lowerBound": 0.0}])
    with pytest.raises(ValueError, match="unknown constraint keys"):
        normalize_constraints([{"name": "a", "term": "", "lower": 0.0}])
    with pytest.raises(ValueError, match="only entry"):
        normalize_constraints([
            {"name": "*", "term": "*", "lowerBound": 0.0},
            {"name": "a", "term": "", "upperBound": 1.0}])


# -- resolve ------------------------------------------------------------------

def test_resolve_specific_and_unseen():
    imap = _imap()
    lower, upper = resolve_constraints(
        normalize_constraints([
            {"name": "age", "term": "young", "lowerBound": -1, "upperBound": 1},
            {"name": "ghost", "term": "", "lowerBound": 0.0}]),  # unseen: skipped
        imap)
    j = imap.index_of("age", "young")
    assert (lower[j], upper[j]) == (-1.0, 1.0)
    for k in range(imap.size):
        if k != j:
            assert (lower[k], upper[k]) == (-INF, INF)


def test_resolve_wildcard_all_excludes_intercept():
    imap = _imap()
    lower, upper = resolve_constraints(
        normalize_constraints([{"name": "*", "term": "*",
                                "lowerBound": -0.5, "upperBound": 0.5}]),
        imap)
    for k in range(imap.size):
        if k == imap.intercept_index:
            assert (lower[k], upper[k]) == (-INF, INF)
        else:
            assert (lower[k], upper[k]) == (-0.5, 0.5)


def test_resolve_term_wildcard_and_conflict():
    imap = _imap()
    lower, upper = resolve_constraints(
        normalize_constraints([{"name": "age", "term": "*",
                                "upperBound": 3.0}]), imap)
    for name, term in [("age", ""), ("age", "young")]:
        j = imap.index_of(name, term)
        assert (lower[j], upper[j]) == (-INF, 3.0)
    assert upper[imap.index_of("height")] == INF
    with pytest.raises(ValueError, match="conflicting bounds"):
        resolve_constraints(
            normalize_constraints([
                {"name": "age", "term": "*", "upperBound": 3.0},
                {"name": "age", "term": "young", "lowerBound": 0.0}]),
            imap)


def test_constraints_json_roundtrip():
    entries = normalize_constraints([
        {"name": "age", "term": "young", "lowerBound": -1, "upperBound": 1},
        {"name": "height", "term": "", "upperBound": 2}])
    js = constraints_to_json(entries)
    assert js == [{"name": "age", "term": "young",
                   "lowerBound": -1.0, "upperBound": 1.0},
                  {"name": "height", "term": "", "upperBound": 2.0}]
    assert normalize_constraints(js) == entries


# -- OptimizerConfig integration ---------------------------------------------

def test_optimizer_config_normalizes_and_resolves():
    cfg = OptimizerConfig(constraints=[{"name": "age", "term": "",
                                        "lowerBound": 0.0}])
    assert cfg.constraints == (("age", "", 0.0, INF),)
    imap = _imap()
    r = cfg.resolved_constraints(imap)
    assert r.constraints is None
    assert r.box_lower[imap.index_of("age")] == 0.0
    assert r.box_upper[imap.index_of("age")] == INF
    with pytest.raises(ValueError, match="exclusive"):
        OptimizerConfig(constraints=[{"name": "a", "term": "",
                                      "lowerBound": 0.0}],
                        box_lower=(0.0,), box_upper=(1.0,))
    with pytest.raises(ValueError, match="index map"):
        cfg.resolved_constraints(None)


def test_solve_rejects_unresolved_constraints():
    import jax.numpy as jnp
    from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
    from photon_ml_tpu.optim import solve
    obj = GLMObjective(TASK_LOSSES["linear_regression"],
                       jnp.ones((4, 2)), jnp.zeros(4))
    with pytest.raises(ValueError, match="unresolved"):
        solve(obj, jnp.zeros(2),
              OptimizerConfig(constraints=[{"name": "a", "term": "",
                                            "lowerBound": 0.0}]))


def test_train_glm_named_equals_positional(rng):
    """train_glm with named constraints == the positional-bounds path."""
    import jax.numpy as jnp
    from photon_ml_tpu.models.training import train_glm
    imap = _imap()
    d = imap.size
    x = rng.normal(size=(300, d))
    w = rng.normal(size=d)
    y = x @ w + 0.05 * rng.normal(size=300)
    con = [{"name": "age", "term": "", "lowerBound": -0.1, "upperBound": 0.1},
           {"name": "height", "term": "", "upperBound": 0.0}]
    lower = [-INF] * d
    upper = [INF] * d
    lower[imap.index_of("age")], upper[imap.index_of("age")] = -0.1, 0.1
    upper[imap.index_of("height")] = 0.0
    named = train_glm(jnp.asarray(x), jnp.asarray(y), "linear_regression",
                      optimizer_config=OptimizerConfig(constraints=con),
                      index_map=imap)
    positional = train_glm(jnp.asarray(x), jnp.asarray(y), "linear_regression",
                           optimizer_config=OptimizerConfig(
                               box_lower=tuple(lower), box_upper=tuple(upper)))
    cn = np.asarray(named[0].model.coefficients.means)
    cp = np.asarray(positional[0].model.coefficients.means)
    np.testing.assert_allclose(cn, cp, rtol=1e-6)
    assert -0.1 - 1e-6 <= cn[imap.index_of("age")] <= 0.1 + 1e-6
    assert cn[imap.index_of("height")] <= 1e-6


def test_game_estimator_resolves_constraints(rng):
    """Named constraints on the fixed-effect coordinate flow through
    GameEstimator.fit; random-effect coordinates reject them."""
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig, GameEstimator,
                                    GameTrainingConfig, GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import RegularizationContext, RegularizationType
    L2 = RegularizationContext(RegularizationType.L2)
    n = 400
    imap = build_index_map([(f"g{i}", "") for i in range(5)])
    x = rng.normal(size=(n, imap.size))
    y = x @ rng.normal(size=imap.size) + 0.1 * rng.normal(size=n)
    users = np.asarray([f"u{i % 5}" for i in range(n)])
    xu = rng.normal(size=(n, 3))
    ds = build_game_dataset(y, {"global": x, "per_user": xu},
                            entity_ids={"userId": users},
                            index_maps={"global": imap})
    con = [{"name": "g1", "term": "", "lowerBound": -0.05,
            "upperBound": 0.05}]
    cfg = GameTrainingConfig(
        task_type="linear_regression",
        coordinates={"fixed": FixedEffectCoordinateConfig(
            "global", GLMOptimizationConfig(
                optimizer=OptimizerConfig(constraints=con),
                regularization=L2, regularization_weight=0.01))},
        updating_sequence=["fixed"], num_outer_iterations=1)
    res = GameEstimator(cfg).fit(ds)
    c = np.asarray(
        res.model.coordinates["fixed"].glm.coefficients.means)
    assert -0.05 - 1e-6 <= c[imap.index_of("g1")] <= 0.05 + 1e-6
    # config JSON round-trips the constraint entries
    cfg2 = GameTrainingConfig.from_json(cfg.to_json())
    assert cfg2 == cfg

    bad = GameTrainingConfig(
        task_type="linear_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global"),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", GLMOptimizationConfig(
                    optimizer=OptimizerConfig(constraints=con)))},
        updating_sequence=["fixed", "perUser"], num_outer_iterations=1)
    with pytest.raises(ValueError, match="fixed-effect coordinates only"):
        GameEstimator(bad).fit(ds)


def test_cli_constraints_e2e(tmp_path, rng):
    """Constraints in the config JSON flow through the train CLI and bind
    the saved model's coefficients."""
    from tests.test_io_cli import _run_cli
    from photon_ml_tpu.data.avro_game import write_game_examples
    from photon_ml_tpu.models.io import load_game_model, load_model_index_maps

    n = 300
    imap = build_index_map([(f"g{i}", "") for i in range(5)])
    x = (rng.uniform(size=(n, imap.size)) < 0.6).astype(float)
    y = x @ rng.normal(size=imap.size) + 0.1 * rng.normal(size=n)
    data_p = str(tmp_path / "train.avro")
    write_game_examples(data_p, y, bags={"features": (x, imap)})
    cfg = {
        "task_type": "linear_regression",
        "coordinates": {"fixed": {
            "kind": "fixed_effect", "feature_shard": "global",
            "optimization": {
                "optimizer": {
                    "optimizer": "lbfgs",
                    "constraints": [{"name": "g2", "term": "",
                                     "lowerBound": -0.02,
                                     "upperBound": 0.02}]},
                "regularization": {"type": "l2"},
                "regularization_weight": 0.01}}},
        "updating_sequence": ["fixed"], "num_outer_iterations": 1}
    cfg_p = str(tmp_path / "game.json")
    with open(cfg_p, "w") as f:
        json.dump(cfg, f)
    out_dir = str(tmp_path / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", data_p, "--task", "linear_regression",
                  "--config", cfg_p, "--output-dir", out_dir])
    assert r.returncode == 0, r.stderr[-2000:]
    model, _ = load_game_model(out_dir + "/best")
    maps = load_model_index_maps(out_dir + "/best")
    gmap = maps["global"]
    c = np.asarray(model.coordinates["fixed"].glm.coefficients.means)
    assert -0.02 - 1e-6 <= c[gmap.index_of("g2")] <= 0.02 + 1e-6
