"""Entity-sharded serving tests (fleet/shards.py + the sharded fleet
stack) — ISSUE 20.

Covers the deterministic shard map (stability, version round-trip,
spec_id agreement checks), the fan-out margin merge's BIT-PARITY with a
monolithic scorer (in-process and over the real HTTP front), per-shard
sha256-exact audits (sharded replica vs the publisher's filtered full
model), the shard.route / shard.merge fault sites, degradation policies
when a shard goes dark, the subprocess SIGKILL -> survivors keep serving
-> rejoin -> exact-audit lifecycle, armed-locktrace concurrent failover
stress, and the ISSUE 20 satellite: non-idempotent publisher routes are
never hedged or blindly retried by the front.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import photon_ml_tpu

from photon_ml_tpu.fleet import (Front, FrontConfig, NoReadyReplica,
                                 ShardAssignment, ShardMergeError,
                                 ShardSpec, merge_margins, shards_touched)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.models.io import save_game_model
from photon_ml_tpu.serving import ScoringService, ServingConfig
from photon_ml_tpu.utils import faults, locktrace

D_G, D_U, N_ENT = 6, 4, 30
TASK = "logistic_regression"
PACKAGE_DIR = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _make_model(rng, coef_scale=1.0):
    fe = FixedEffectModel(
        model_for_task(TASK, Coefficients(
            jnp.asarray(coef_scale * rng.normal(size=D_G)))), "global")
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=TASK,
        coefficients=jnp.asarray(coef_scale * rng.normal(size=(N_ENT, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(N_ENT)], dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re}, TASK)


def _save_model(rng, tmp_path, name="model", coef_scale=1.0):
    mdir = str(tmp_path / name)
    save_game_model(_make_model(rng, coef_scale), mdir)
    return mdir


def _service(mdir):
    return ScoringService(
        model_dir=mdir, config=ServingConfig(max_batch=64, min_bucket=4))


def _shard_service(mdir, index, count):
    return ScoringService(
        model_dir=mdir,
        config=ServingConfig(max_batch=64, min_bucket=4,
                             shard_index=index, shard_count=count))


def _request(rng, n=12, users=None):
    feats = {"global": rng.normal(size=(n, D_G)),
             "per_user": rng.normal(size=(n, D_U))}
    if users is None:
        users = [f"u{rng.integers(0, N_ENT)}" for _ in range(n)]
    ids = {"userId": np.asarray(users, dtype=object)}
    return feats, ids


def _users_of_shard(spec, shard, count):
    """`count` entity ids owned by `shard` (model entities u0..u29)."""
    owned = [f"u{i}" for i in range(N_ENT)
             if spec.shard_of(f"u{i}") == shard]
    assert len(owned) >= count, "seeded partition left a shard too empty"
    return owned[:count]


def _serve_http(service):
    """A real serve-CLI HTTP server around an in-process service."""
    from photon_ml_tpu.cli.serve import _make_http_server
    httpd = _make_http_server(service, "127.0.0.1", 0)
    thread = threading.Thread(target=httpd.serve_forever,
                              kwargs={"poll_interval": 0.05}, daemon=True)
    thread.start()
    return httpd, thread, f"http://127.0.0.1:{httpd.server_address[1]}"


def _get(url, timeout=15):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# --------------------------------------------------------------------------
# the shard map
# --------------------------------------------------------------------------

def test_shard_spec_deterministic_and_total():
    spec = ShardSpec(num_shards=4)
    ids = [f"u{i}" for i in range(200)] + [123, "z", ""]
    owners = [spec.shard_of(e) for e in ids]
    assert owners == [spec.shard_of(e) for e in ids]   # stable
    assert set(owners) == set(range(4))                # every shard used
    for e, o in zip(ids, owners):
        assert 0 <= o < 4
    # owned masks partition the id space: each id owned exactly once
    masks = np.stack([spec.owned_mask(ids, k) for k in range(4)])
    assert (masks.sum(axis=0) == 1).all()


def test_shard_spec_version_and_salt_change_the_partition():
    a = ShardSpec(num_shards=4)
    b = ShardSpec(num_shards=4, version=2)
    c = ShardSpec(num_shards=4, salt="other")
    ids = [f"u{i}" for i in range(100)]
    assert [a.shard_of(e) for e in ids] != [b.shard_of(e) for e in ids]
    assert [a.shard_of(e) for e in ids] != [c.shard_of(e) for e in ids]
    assert len({a.spec_id(), b.spec_id(), c.spec_id()}) == 3


def test_shard_spec_roundtrip_and_spec_id_mismatch():
    spec = ShardSpec(num_shards=3, salt="s", version=7)
    assert ShardSpec.from_dict(spec.to_dict()) == spec
    bad = dict(spec.to_dict(), spec_id="0" * 16)
    with pytest.raises(ValueError, match="spec_id mismatch"):
        ShardSpec.from_dict(bad)
    with pytest.raises(ValueError, match="out of range"):
        ShardAssignment(spec=spec, index=3)
    with pytest.raises(ValueError, match="num_shards"):
        ShardSpec(num_shards=0)


def test_shards_touched_only_names_owning_shards():
    spec = ShardSpec(num_shards=4)
    meta = [{"name": "fixed", "kind": "fixed"},
            {"name": "perUser", "kind": "random", "entity_type": "userId"}]
    users = ["u1", "u2", "u3"]
    touched = shards_touched(spec, meta, {"userId": users})
    assert touched == sorted({spec.shard_of(u) for u in users})
    assert shards_touched(spec, meta, {}) == []
    assert shards_touched(spec, [meta[0]], {"userId": users}) == []


# --------------------------------------------------------------------------
# fan-out merge: bit-parity with the monolithic scorer
# --------------------------------------------------------------------------

def test_fanout_merge_bit_parity_and_per_shard_audits(tmp_path, rng):
    """The tentpole invariant, in-process: per-shard margin legs re-fold
    to the monolithic scorer's scores EXACTLY (same bits), and each
    sharded replica's table hashes equal the full model filtered to its
    owned rows."""
    mdir = _save_model(rng, tmp_path)
    n_shards = 3
    spec = ShardSpec(num_shards=n_shards)
    mono = _service(mdir)
    svcs = [_shard_service(mdir, k, n_shards) for k in range(n_shards)]
    try:
        # rows spread over every shard, plus an unseen entity (scores
        # with a zero RE contribution on every leg)
        users = [f"u{i}" for i in range(10)] + ["nobody", "u1"]
        feats, ids = _request(rng, n=len(users), users=users)
        expected = np.asarray(mono.score(feats, ids), np.float64)
        legs = {k: svcs[k].score_margins(feats, ids)["margins"]
                for k in range(n_shards)}
        meta = svcs[0].registry.scorer.coordinate_meta()
        out = merge_margins(spec, meta, ids, legs, primary=0)
        got = np.asarray(out["scores"], np.float64)
        assert got.tobytes() == expected.tobytes()
        assert out["partial_rows"] == [] and out["missing_shards"] == []
        # any healthy primary leg gives the same bits (FE/MF replicate)
        got2 = merge_margins(spec, meta, ids, legs, primary=2)["scores"]
        assert np.asarray(got2).tobytes() == expected.tobytes()
        # per-shard audits: sharded replica's resident tables ARE the
        # publisher's full tables filtered to its owned rows
        full = mono.registry.scorer
        for k in range(n_shards):
            assert svcs[k].registry.scorer.table_hashes() == \
                full.shard_table_hashes(spec, k)
        # the shard gauges landed on the replica metric surface
        snap = svcs[0].metrics_snapshot()
        assert snap["fleet"]["shard_index"] == 0
        assert snap["fleet"]["shard_count"] == n_shards
        assert snap["fleet"]["shard_owned_rows"] >= 1
    finally:
        mono.close()
        for s in svcs:
            s.close()


def test_merge_missing_owner_policies(tmp_path, rng):
    mdir = _save_model(rng, tmp_path)
    spec = ShardSpec(num_shards=2)
    svcs = [_shard_service(mdir, k, 2) for k in range(2)]
    try:
        users = _users_of_shard(spec, 0, 2) + _users_of_shard(spec, 1, 2)
        feats, ids = _request(rng, n=4, users=users)
        legs = {1: svcs[1].score_margins(feats, ids)["margins"]}
        meta = svcs[1].registry.scorer.coordinate_meta()
        with pytest.raises(ShardMergeError, match="no healthy replica"):
            merge_margins(spec, meta, ids, legs, primary=1)
        out = merge_margins(spec, meta, ids, legs, primary=1,
                            missing_policy="partial")
        assert out["missing_shards"] == [0]
        assert out["partial_rows"] == [0, 1]     # only shard-0 rows
        # and the primary leg itself must be present
        with pytest.raises(ShardMergeError, match="primary"):
            merge_margins(spec, meta, ids, legs, primary=0,
                          missing_policy="partial")
    finally:
        for s in svcs:
            s.close()


# --------------------------------------------------------------------------
# the front over sharded HTTP replicas
# --------------------------------------------------------------------------

@pytest.fixture
def sharded_http(tmp_path, rng):
    """3 sharded services behind real serve-CLI HTTP servers, plus a
    monolithic reference service."""
    mdir = _save_model(rng, tmp_path)
    mono = _service(mdir)
    svcs = [_shard_service(mdir, k, 3) for k in range(3)]
    servers = [_serve_http(s) for s in svcs]
    yield {"mono": mono, "svcs": svcs, "servers": servers,
           "spec": ShardSpec(num_shards=3)}
    for httpd, thread, _url in servers:
        try:
            httpd.shutdown()
            httpd.server_close()
            thread.join(timeout=5)
        except Exception:
            pass
    mono.close()
    for s in svcs:
        s.close()


def _sharded_front(sharded_http, **cfg_kw):
    cfg_kw.setdefault("probe_interval_s", 0.05)
    cfg_kw.setdefault("unhealthy_after", 1)
    cfg_kw.setdefault("hedge_after_s", 5.0)
    cfg_kw.setdefault("request_timeout_s", 15.0)
    front = Front([url for _h, _t, url in sharded_http["servers"]],
                  config=FrontConfig(**cfg_kw), start_probes=False)
    front.probe_once()
    return front


def test_front_sharded_scoring_bit_parity_http(sharded_http, rng):
    front = _sharded_front(sharded_http)
    mono = sharded_http["mono"]
    try:
        users = [f"u{i}" for i in range(8)] + ["ghost"]
        feats, ids = _request(rng, n=len(users), users=users)
        body = {"features": {k: v.tolist() for k, v in feats.items()},
                "ids": {"userId": users}}
        status, payload = front.route("/score", body)
        assert status == 200
        assert payload["sharded"] is True
        assert "degraded" not in payload
        expected = np.asarray(mono.score(feats, ids), np.float64)
        got = np.asarray(payload["scores"], np.float64)
        assert got.tobytes() == expected.tobytes()
        # /predict applies the identical host-side inverse link
        status, payload = front.route("/predict", body)
        assert status == 200
        exp_pred = np.asarray(mono.predict(feats, ids), np.float64)
        got_pred = np.asarray(payload["predictions"], np.float64)
        assert got_pred.tobytes() == exp_pred.tobytes()
        # fan-out accounting landed on the front surface
        snap = front.front_snapshot()
        assert snap["shard_coverage"] == 1.0
        assert any(v > 0 for v in snap["shard_requests"].values())
        assert front.status()["shards"]["shards_down"] == []
    finally:
        front.close()


def test_front_sharded_fault_sites_absorbed(sharded_http, rng):
    """shard.route / shard.merge transient faults are absorbed by the
    leg retry and merge retry loops — the response stays bit-exact."""
    front = _sharded_front(sharded_http)
    mono = sharded_http["mono"]
    try:
        users = [f"u{i}" for i in range(6)]
        feats, ids = _request(rng, n=len(users), users=users)
        body = {"features": {k: v.tolist() for k, v in feats.items()},
                "ids": {"userId": users}}
        plan = faults.FaultPlan([
            {"site": "shard.route", "action": "transient", "hits": [1]},
            {"site": "shard.merge", "action": "transient", "hits": [1]},
        ])
        with faults.injected(plan):
            status, payload = front.route("/score", body)
        assert status == 200
        assert plan.report()["total_fired"] == 2
        expected = np.asarray(mono.score(feats, ids), np.float64)
        assert np.asarray(payload["scores"],
                          np.float64).tobytes() == expected.tobytes()
    finally:
        front.close()


def test_front_lost_shard_degrades_only_that_shard(sharded_http, rng):
    """Robustness core: killing every replica of ONE shard degrades only
    requests touching that shard's entities; under policy 'error' those
    requests 503; everything else stays bit-exact."""
    spec = sharded_http["spec"]
    mono = sharded_http["mono"]
    front = _sharded_front(sharded_http)
    front_err = _sharded_front(sharded_http, degraded_policy="error")
    lost = 1
    try:
        # prime the fold-order cache while all shards are up
        warm_users = [f"u{i}" for i in range(6)]
        wfeats, _ = _request(rng, n=len(warm_users), users=warm_users)
        warm_body = {"features": {k: v.tolist()
                                  for k, v in wfeats.items()},
                     "ids": {"userId": warm_users}}
        assert front.route("/score", warm_body)[0] == 200
        assert front_err.route("/score", warm_body)[0] == 200
        httpd, thread, _url = sharded_http["servers"][lost]
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)
        front.probe_once()
        front_err.probe_once()
        assert front.status()["shards"]["shards_down"] == [lost]
        assert front.front_snapshot()["shard_coverage"] == 0.0
        # a request that avoids the lost shard: exact, not degraded
        safe = (_users_of_shard(spec, (lost + 1) % 3, 2)
                + _users_of_shard(spec, (lost + 2) % 3, 2))
        feats, ids = _request(rng, n=len(safe), users=safe)
        body = {"features": {k: v.tolist() for k, v in feats.items()},
                "ids": {"userId": safe}}
        status, payload = front.route("/score", body)
        assert status == 200 and "degraded" not in payload
        expected = np.asarray(mono.score(feats, ids), np.float64)
        assert np.asarray(payload["scores"],
                          np.float64).tobytes() == expected.tobytes()
        # a request touching the lost shard: partial under the default
        # policy, 503 under 'error' — and the partial rows are exactly
        # the rows owned by the lost shard
        mixed = safe[:2] + _users_of_shard(spec, lost, 2)
        mfeats, mids = _request(rng, n=len(mixed), users=mixed)
        mbody = {"features": {k: v.tolist() for k, v in mfeats.items()},
                 "ids": {"userId": mixed}}
        status, payload = front.route("/score", mbody)
        assert status == 200
        assert payload["degraded"] is True
        assert payload["missing_shards"] == [lost]
        assert payload["partial_rows"] == [2, 3]
        # the surviving rows still carry the exact monolithic bits
        expected_mixed = np.asarray(mono.score(mfeats, mids), np.float64)
        got = np.asarray(payload["scores"], np.float64)
        assert got[:2].tobytes() == expected_mixed[:2].tobytes()
        status, payload = front_err.route("/score", mbody)
        assert status == 503
        assert payload["missing_shards"] == [lost]
        assert front.front_snapshot()["shard_degraded"] >= 1
    finally:
        front.close()
        front_err.close()


def test_front_rejects_mismatched_shard_spec(sharded_http, tmp_path, rng):
    """A replica on an incompatible partition (same version, different
    salt -> different spec_id) is treated as a failed probe and leaves
    rotation — its margins are never merged."""
    front = _sharded_front(sharded_http)
    mdir = _save_model(rng, tmp_path, name="model_alt")
    alien = ScoringService(
        model_dir=mdir,
        config=ServingConfig(max_batch=64, min_bucket=4, shard_index=0,
                             shard_count=3, shard_salt="other"))
    httpd, thread, url = _serve_http(alien)
    try:
        front.attach(url)
        for _ in range(3):
            results = front.probe_once()
        assert results[url] is False
        state = [h for h in front.status()["replicas"]
                 if h["url"] == url][0]
        assert "does not match the fleet partition" in state["last_error"]
    finally:
        front.close()
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)
        alien.close()


def test_front_locktrace_armed_concurrent_failover_stress(tmp_path, rng):
    """ISSUE 20 satellite: concurrent sharded scoring + a mid-stress
    shard loss under the ARMED lock tracker; every observed acquisition
    order must be an edge consistent with the static lock graph.  The
    whole stack is built INSIDE the armed tracker — locks constructed
    before arming stay raw and would go unobserved."""
    spec = ShardSpec(num_shards=3)
    with locktrace.enabled() as tracker:
        mdir = _save_model(rng, tmp_path)
        svcs = [_shard_service(mdir, k, 3) for k in range(3)]
        servers = [_serve_http(s) for s in svcs]
        front = Front([url for _h, _t, url in servers],
                      config=FrontConfig(probe_interval_s=0.05,
                                         unhealthy_after=1,
                                         hedge_after_s=5.0,
                                         request_timeout_s=15.0),
                      start_probes=False)
        front.probe_once()
        errors, stop = [], threading.Event()
        safe = (_users_of_shard(spec, 0, 2)
                + _users_of_shard(spec, 2, 2))
        feats, _ = _request(rng, n=len(safe), users=safe)
        body = {"features": {k: v.tolist() for k, v in feats.items()},
                "ids": {"userId": safe}}

        def score_loop():
            while not stop.is_set():
                try:
                    status, payload = front.route("/score", body)
                    if status != 200 or payload.get("degraded"):
                        errors.append(f"http {status}: {payload}")
                except Exception as e:
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=score_loop, daemon=True)
                   for _ in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.3)
            httpd, th, _url = servers[1]           # lose shard 1
            httpd.shutdown()
            httpd.server_close()
            th.join(timeout=5)
            front.probe_once()
            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            front.close()
            for httpd, th, _url in servers[:1] + servers[2:]:
                httpd.shutdown()
                httpd.server_close()
                th.join(timeout=5)
            for s in svcs:
                s.close()
        assert errors == []     # shard-1 loss never touched these rows
    from photon_ml_tpu.analysis.concurrency import lock_order_edges
    tracker.assert_consistent(lock_order_edges([PACKAGE_DIR]))
    acq = tracker.acquisitions()
    assert acq.get("Front._lock", 0) > 0
    assert acq.get("ScoringService._margins_lock", 0) > 0


# --------------------------------------------------------------------------
# satellite: non-idempotent publisher routes are never hedged/retried
# --------------------------------------------------------------------------

class _SlowPublisherStub:
    """One stub replica that counts /feedback hits and can sleep through
    the front's timeout — the probe for blind-retry bugs."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *a):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._reply(200, {"status": "ok"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(length)
                stub.hits += 1
                if stub.delay_s:
                    time.sleep(stub.delay_s)
                self._reply(202, {"ok": True})

        self.hits = 0
        self.delay_s = 0.0
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        kwargs={"poll_interval": 0.05},
                                        daemon=True)
        self._thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)


def test_route_never_hedges_or_retries_publisher_paths():
    stub = _SlowPublisherStub()
    front = Front([stub.url], config=FrontConfig(
        probe_interval_s=0.05, hedge_after_s=0.01,
        request_timeout_s=0.3), start_probes=False)
    try:
        front.probe_once()
        # the scoring router refuses model-state paths outright
        for path in ("/feedback", "/swap", "/rollback"):
            with pytest.raises(ValueError, match="route_publisher"):
                front.route(path, {})
        assert stub.hits == 0
        # route_publisher sends EXACTLY ONCE even when the publisher
        # sleeps through the timeout: an ambiguous timeout must never
        # become a duplicate feedback batch / double swap
        stub.delay_s = 1.0
        with pytest.raises(NoReadyReplica):
            front.route_publisher("POST", "/feedback", {"labels": [1.0]},
                                  timeout=0.2)
        time.sleep(1.2)          # let the slow handler finish counting
        assert stub.hits == 1
        snap = front.front_snapshot()
        assert snap["hedges"] == 0 and snap["retries"] == 0
    finally:
        front.close()
        stub.close()


# --------------------------------------------------------------------------
# subprocess fleet: SIGKILL a shard's replica, survive, rejoin, audit
# --------------------------------------------------------------------------

def _spawn_serve(extra, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.cli.serve",
         "--port", "0", "--max-batch", "32", "--min-bucket", "4"] + extra,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    return proc


def _read_startup(proc, timeout=180):
    line = [None]

    def read():
        line[0] = proc.stdout.readline()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    if line[0] is None or not line[0].strip():
        raise AssertionError("serve subprocess produced no startup line")
    return json.loads(line[0])


def test_shard_fleet_sigkill_rejoin_sha256_audit(tmp_path, rng):
    """ISSUE 20 acceptance core, end to end over subprocesses: a 2-shard
    fleet serves exactly; SIGKILL of one shard's replica leaves the
    OTHER shard serving bit-exact scores; the restarted replica catches
    up from the shard-filtered log to a sha256-exact per-shard audit
    against the publisher's filtered full model."""
    mdir = _save_model(rng, tmp_path)
    log_dir = str(tmp_path / "log")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # conftest enables x64 in THIS process; the spawned fleet must score
    # in the same compute dtype or bit-parity against the in-process
    # monolithic reference is impossible by construction
    env["JAX_ENABLE_X64"] = "1"
    spec = ShardSpec(num_shards=2)
    procs = {}
    front = None

    def spawn_replica(k):
        return _spawn_serve(
            ["--model-dir", mdir, "--replica", "--shard", f"{k}/2",
             "--replication-log", log_dir,
             "--replica-state", str(tmp_path / f"s{k}"),
             "--replica-poll-ms", "25"], env)

    try:
        procs["pub"] = _spawn_serve(
            ["--model-dir", mdir, "--replica", "--publish",
             "--shard-count", "2", "--replication-log", log_dir,
             "--replica-state", str(tmp_path / "sp"),
             "--enable-updates", "--update-interval-ms", "50",
             # keep the updater's warmup cheap: 2 small solver buckets
             "--update-micro-batch", "4",
             "--update-max-rows-per-entity", "8"], env)
        procs[0] = spawn_replica(0)
        procs[1] = spawn_replica(1)
        urls = {}
        for key in ("pub", 0, 1):
            info = _read_startup(procs[key])
            urls[key] = info["serving"]
            if key != "pub":
                assert info["shard"]["index"] == key
                assert info["shard"]["num_shards"] == 2
        front = Front([urls["pub"], urls[0], urls[1]],
                      publisher_url=urls["pub"],
                      config=FrontConfig(probe_interval_s=0.05,
                                         unhealthy_after=1,
                                         request_timeout_s=30.0,
                                         hedge_after_s=10.0),
                      start_probes=False)
        deadline = time.time() + 120
        while time.time() < deadline:
            if all(front.probe_once().values()):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("fleet never became ready")
        # push online deltas through the publisher so the replicas have
        # shard-filtered log state to converge on
        n = 16
        fb = {"features": {
            "global": rng.normal(size=(n, D_G)).tolist(),
            "per_user": rng.normal(size=(n, D_U)).tolist()},
            "ids": {"userId": [f"u{i % N_ENT}" for i in range(n)]},
            "labels": [0.0] * n}
        status, _p, _h = front.route_publisher("POST", "/feedback", fb)
        assert status == 202
        # first drain the publisher's updater COMPLETELY (more delta
        # records may trail the first one), then wait for every replica
        # to reach the settled head — scoring before the log stops
        # growing would race the monolithic reference below
        deadline = time.time() + 120
        while time.time() < deadline:
            _s, snap = _get(urls["pub"] + "/metrics.json")
            online = snap.get("online") or {}
            if online.get("pending_rows") == 0 and \
                    online.get("deltas_published", 0) > 0:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("publisher never drained its updater")
        # pending_rows hits 0 when the LAST cycle drains the buffer —
        # before that cycle's delta lands on the log.  Wait until the
        # head stops moving for a full settle window AND every replica
        # has applied it, else the monolithic reference below (which
        # reads the log later) would see one more delta than the fleet.
        deadline = time.time() + 60
        head, stable_since = None, time.time()
        while time.time() < deadline:
            front.probe_once()
            lag = front._fleet_lag()
            if lag["publisher_head_seq"] != head:
                head, stable_since = lag["publisher_head_seq"], time.time()
            elif head is not None and head >= 3 and \
                    time.time() - stable_since > 1.0 and all(
                        st["lag_records"] == 0
                        for st in lag["replicas"].values()):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("replicas never caught up")
        # bit-parity vs a local monolithic follower of the SAME log
        from photon_ml_tpu.fleet import Replica, ReplicaConfig, \
            ReplicationLog
        mono = _service(mdir)
        rep = Replica(mono, ReplicationLog(log_dir),
                      str(tmp_path / "s_mono"), ReplicaConfig())
        rep.join()
        users = [f"u{i}" for i in range(8)]
        feats, ids = _request(rng, n=len(users), users=users)
        body = {"features": {k: v.tolist() for k, v in feats.items()},
                "ids": {"userId": users}}
        status, payload = front.route("/score", body)
        assert status == 200 and payload["sharded"] is True
        expected = np.asarray(mono.score(feats, ids), np.float64)
        assert np.asarray(payload["scores"],
                          np.float64).tobytes() == expected.tobytes()
        # sha256-exact per-shard audits: replica vs publisher's filter
        for k in (0, 1):
            _s, mine = _get(urls[k] + "/fleet/audit")
            _s, theirs = _get(urls["pub"] + f"/fleet/audit?shard={k}")
            assert mine["table_hashes"] == theirs["table_hashes"]
            assert mine["version_vector"] == theirs["version_vector"]
        # SIGKILL shard 0's only replica: shard 1 keeps serving exactly
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=30)
        deadline = time.time() + 30
        while time.time() < deadline:
            front.probe_once()
            if front.status()["shards"]["shards_down"] == [0]:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("front never noticed the lost shard")
        safe = _users_of_shard(spec, 1, 4)
        sfeats, sids = _request(rng, n=len(safe), users=safe)
        sbody = {"features": {k: v.tolist() for k, v in sfeats.items()},
                 "ids": {"userId": safe}}
        status, payload = front.route("/score", sbody)
        assert status == 200 and "degraded" not in payload
        sexp = np.asarray(mono.score(sfeats, sids), np.float64)
        assert np.asarray(payload["scores"],
                          np.float64).tobytes() == sexp.tobytes()
        # errors confined: only requests touching shard 0 degrade
        touch0 = _users_of_shard(spec, 0, 2) + safe[:2]
        tfeats, _tids = _request(rng, n=len(touch0), users=touch0)
        tbody = {"features": {k: v.tolist() for k, v in tfeats.items()},
                 "ids": {"userId": touch0}}
        status, payload = front.route("/score", tbody)
        assert status == 200 and payload["degraded"] is True
        assert payload["missing_shards"] == [0]
        # rejoin: the restarted replica catches up from the
        # shard-filtered log and audits sha256-exact again
        procs[0] = spawn_replica(0)
        urls[0] = _read_startup(procs[0])["serving"]
        front.attach(urls[0])
        deadline = time.time() + 120
        while time.time() < deadline:
            front.probe_once()
            if front.status()["shards"]["shards_down"] == []:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("rejoined replica never became ready")
        _s, mine = _get(urls[0] + "/fleet/audit")
        _s, theirs = _get(urls["pub"] + "/fleet/audit?shard=0")
        assert mine["table_hashes"] == theirs["table_hashes"]
        status, payload = front.route("/score", tbody)
        assert status == 200 and "degraded" not in payload
        rep.close()
        mono.close()
    finally:
        if front is not None:
            front.close()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                try:
                    proc.communicate(timeout=15)
                except Exception:
                    pass
