"""photonlint: per-rule fixture tests, suppression round-trips, baseline
workflow, CLI contract, and the tier-1 self-check that the shipped
package lints clean against the committed baseline.

Each rule PH001–PH006 is demonstrated by one minimal violating fixture
and one compliant near-miss fixture (tests/lint_fixtures/); the
suppression test rewrites every flagged line with its `# photonlint:
disable=...` comment and asserts the findings vanish — proving both that
the rule fires and that its suppression works.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

import photon_ml_tpu
from photon_ml_tpu.analysis.engine import Baseline, lint_paths
from photon_ml_tpu.analysis.lint import DEFAULT_BASELINE, main as lint_main

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
PACKAGE_DIR = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))

# (rule, violating fixture, compliant near-miss fixture, finding count)
CASES = [
    ("PH001", "hot/ops/ph001_violation.py",
     "hot/ops/ph001_compliant.py", 4),
    ("PH002", "ph002_violation.py", "ph002_compliant.py", 3),
    ("PH003", "ph003_violation.py", "ph003_compliant.py", 1),
    ("PH004", "ph004_violation.py", "ph004_compliant.py", 3),
    ("PH005", "durable/models/io.py", "durable_ok/models/io.py", 2),
    ("PH006", "ph006_violation.py", "ph006_compliant.py", 2),
    ("PH007", "hot/ops/ph007_violation.py",
     "hot/ops/ph007_compliant.py", 4),
]


def _lint(path, **kw):
    return lint_paths([os.path.join(FIXTURES, path)], **kw)


# --------------------------------------------------------------------------
# per-rule fixtures
# --------------------------------------------------------------------------

@pytest.mark.parametrize("rule,violation,compliant,count",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_fires_on_violation_fixture(rule, violation, compliant,
                                         count):
    findings = _lint(violation)
    assert [f.rule for f in findings] == [rule] * count
    assert all(f.line > 0 and f.col > 0 and f.text for f in findings)


@pytest.mark.parametrize("rule,violation,compliant,count",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_quiet_on_compliant_near_miss(rule, violation, compliant,
                                           count):
    assert _lint(compliant) == []


@pytest.mark.parametrize("rule,violation,compliant,count",
                         CASES, ids=[c[0] for c in CASES])
def test_line_suppression_silences_each_finding(rule, violation,
                                                compliant, count,
                                                tmp_path):
    src_path = os.path.join(FIXTURES, violation)
    findings = lint_paths([src_path])
    lines = open(src_path, encoding="utf-8").read().splitlines()
    for f in findings:
        lines[f.line - 1] += f"  # photonlint: disable={rule}"
    # mirror the fixture's subpath so path-gated rules (hot-path dirs,
    # durable-module suffixes) still classify the file the same way
    dest = tmp_path / violation
    dest.parent.mkdir(parents=True, exist_ok=True)
    dest.write_text("\n".join(lines) + "\n")
    assert lint_paths([str(dest)]) == []


def test_file_level_suppression(tmp_path):
    src = open(os.path.join(FIXTURES, "hot/ops/ph001_violation.py"),
               encoding="utf-8").read()
    dest = tmp_path / "hot" / "ops" / "mod.py"
    dest.parent.mkdir(parents=True)
    dest.write_text("# photonlint: disable-file=PH001\n" + src)
    assert lint_paths([str(dest)]) == []


def test_ph001_is_hot_path_scoped(tmp_path):
    # the same syncs OUTSIDE ops/optim/game/parallel/serving are fine:
    # cold paths may sync freely
    shutil.copy(os.path.join(FIXTURES, "hot/ops/ph001_violation.py"),
                tmp_path / "coldpath.py")
    assert lint_paths([str(tmp_path / "coldpath.py")]) == []


def test_ph005_is_durable_module_scoped(tmp_path):
    shutil.copy(os.path.join(FIXTURES, "durable/models/io.py"),
                tmp_path / "scratch_writer.py")
    assert lint_paths([str(tmp_path / "scratch_writer.py")]) == []


def test_select_filters_rules():
    findings = _lint("hot/ops/ph001_violation.py", select=["PH005"])
    assert findings == []


def test_ph004_registry_docs_drift(tmp_path):
    # when the linted tree carries its own faults.py registry, every
    # SITES entry must appear in the module docstring
    (tmp_path / "faults.py").write_text(
        '"""Docs mention stage.fetch only."""\n'
        'SITES = {"stage.fetch": ("chunk",),\n'
        '         "undocumented.site": ()}\n')
    findings = lint_paths([str(tmp_path / "faults.py")])
    assert [f.rule for f in findings] == ["PH004"]
    assert "undocumented.site" in findings[0].message


def test_unparseable_module_is_reported_not_fatal(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    findings = lint_paths([str(tmp_path / "broken.py")])
    assert [f.rule for f in findings] == ["PH000"]


# --------------------------------------------------------------------------
# baseline workflow
# --------------------------------------------------------------------------

def test_baseline_grandfathers_and_goes_stale(tmp_path):
    viol = os.path.join(FIXTURES, "hot/ops/ph001_violation.py")
    baseline_path = str(tmp_path / "baseline.json")
    rc = lint_main([viol, "--baseline", baseline_path,
                    "--write-baseline"])
    assert rc == 0
    # all findings grandfathered -> clean exit
    assert lint_main([viol, "--baseline", baseline_path]) == 0
    # --no-baseline still reports them
    assert lint_main([viol, "--no-baseline"]) == 1
    # baseline identity survives line drift but not text changes
    findings = lint_paths([viol])
    baseline = Baseline.load(baseline_path)
    new, old, stale = baseline.split(findings)
    assert not new and len(old) == len(findings) and stale == 0


def test_baseline_multiset_matching(tmp_path):
    # two identical violating lines need two baseline entries
    dest = tmp_path / "hot" / "ops" / "twice.py"
    dest.parent.mkdir(parents=True)
    dest.write_text("import jax.numpy as jnp\n"
                    "def f(x):\n"
                    "    return float(jnp.sum(x))\n"
                    "def g(x):\n"
                    "    return float(jnp.sum(x))\n")
    findings = lint_paths([str(dest)])
    assert len(findings) == 2
    baseline = Baseline([findings[0].to_dict()
                         | {"path": findings[0].baseline_path}])
    new, old, stale = baseline.split(findings)
    assert len(new) == 1 and len(old) == 1


# --------------------------------------------------------------------------
# CLI contract (standalone / CI usage)
# --------------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis.lint", *args],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_cli_json_output_and_exit_codes():
    bad = _run_cli("tests/lint_fixtures/hot/ops/ph001_violation.py",
                   "--no-baseline", "--json")
    assert bad.returncode == 1
    report = json.loads(bad.stdout)
    assert report["counts"]["new"] == 4
    assert {f["rule"] for f in report["findings"]} == {"PH001"}
    assert all(not f["baselined"] for f in report["findings"])

    ok = _run_cli("tests/lint_fixtures/hot/ops/ph001_compliant.py",
                  "--no-baseline", "--json")
    assert ok.returncode == 0
    assert json.loads(ok.stdout)["counts"]["new"] == 0


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("PH001", "PH002", "PH003", "PH004", "PH005", "PH006",
                    "PH007"):
        assert rule_id in out


# --------------------------------------------------------------------------
# tier-1 gate: the shipped tree lints clean against the baseline
# --------------------------------------------------------------------------

def test_package_lints_clean_against_baseline():
    findings = lint_paths([PACKAGE_DIR])
    baseline = Baseline.load(DEFAULT_BASELINE)
    new, _, _ = baseline.split(findings)
    assert new == [], ("photonlint found non-baseline violations:\n"
                       + "\n".join(f.render() for f in new))


def test_baseline_stays_small():
    # acceptance: <= 5 grandfathered findings, and it should only shrink
    baseline = Baseline.load(DEFAULT_BASELINE)
    assert baseline.total <= 5


def test_linter_package_lints_itself_clean():
    analysis_dir = os.path.join(PACKAGE_DIR, "analysis")
    assert lint_paths([analysis_dir]) == []
