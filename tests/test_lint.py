"""photonlint: per-rule fixture tests, suppression round-trips, baseline
workflow, CLI contract, and the tier-1 self-check that the shipped
package lints clean against the committed baseline.

Each rule PH001–PH006 is demonstrated by one minimal violating fixture
and one compliant near-miss fixture (tests/lint_fixtures/); the
suppression test rewrites every flagged line with its `# photonlint:
disable=...` comment and asserts the findings vanish — proving both that
the rule fires and that its suppression works.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

import photon_ml_tpu
from photon_ml_tpu.analysis.engine import Baseline, lint_paths
from photon_ml_tpu.analysis.lint import DEFAULT_BASELINE, main as lint_main

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
PACKAGE_DIR = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))

# (rule, violating fixture, compliant near-miss fixture, finding count)
CASES = [
    ("PH001", "hot/ops/ph001_violation.py",
     "hot/ops/ph001_compliant.py", 4),
    ("PH002", "ph002_violation.py", "ph002_compliant.py", 3),
    ("PH003", "ph003_violation.py", "ph003_compliant.py", 1),
    ("PH004", "ph004_violation.py", "ph004_compliant.py", 3),
    ("PH005", "durable/models/io.py", "durable_ok/models/io.py", 2),
    ("PH006", "ph006_violation.py", "ph006_compliant.py", 2),
    ("PH007", "hot/ops/ph007_violation.py",
     "hot/ops/ph007_compliant.py", 4),
    ("PH008", "telemetryreg/telemetry/flight.py",
     "telemetryreg_ok/telemetry/flight.py", 3),
    ("PH014", "multiproc/cli/train.py",
     "multiproc_ok/cli/train.py", 4),
    ("PH010", "concurrency/ph010_violation.py",
     "concurrency/ph010_compliant.py", 3),
    ("PH011", "concurrency/ph011_violation.py",
     "concurrency/ph011_compliant.py", 1),
    ("PH012", "concurrency/ph012_violation.py",
     "concurrency/ph012_compliant.py", 3),
    ("PH013", "concurrency/ph013_violation.py",
     "concurrency/ph013_compliant.py", 2),
]


def _lint(path, **kw):
    return lint_paths([os.path.join(FIXTURES, path)], **kw)


# --------------------------------------------------------------------------
# per-rule fixtures
# --------------------------------------------------------------------------

@pytest.mark.parametrize("rule,violation,compliant,count",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_fires_on_violation_fixture(rule, violation, compliant,
                                         count):
    findings = _lint(violation)
    assert [f.rule for f in findings] == [rule] * count
    assert all(f.line > 0 and f.col > 0 and f.text for f in findings)


@pytest.mark.parametrize("rule,violation,compliant,count",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_quiet_on_compliant_near_miss(rule, violation, compliant,
                                           count):
    assert _lint(compliant) == []


@pytest.mark.parametrize("rule,violation,compliant,count",
                         CASES, ids=[c[0] for c in CASES])
def test_line_suppression_silences_each_finding(rule, violation,
                                                compliant, count,
                                                tmp_path):
    src_path = os.path.join(FIXTURES, violation)
    findings = lint_paths([src_path])
    lines = open(src_path, encoding="utf-8").read().splitlines()
    for f in findings:
        lines[f.line - 1] += f"  # photonlint: disable={rule}"
    # mirror the fixture's subpath so path-gated rules (hot-path dirs,
    # durable-module suffixes) still classify the file the same way
    dest = tmp_path / violation
    dest.parent.mkdir(parents=True, exist_ok=True)
    dest.write_text("\n".join(lines) + "\n")
    assert lint_paths([str(dest)]) == []


def test_file_level_suppression(tmp_path):
    src = open(os.path.join(FIXTURES, "hot/ops/ph001_violation.py"),
               encoding="utf-8").read()
    dest = tmp_path / "hot" / "ops" / "mod.py"
    dest.parent.mkdir(parents=True)
    dest.write_text("# photonlint: disable-file=PH001\n" + src)
    assert lint_paths([str(dest)]) == []


def test_ph001_is_hot_path_scoped(tmp_path):
    # the same syncs OUTSIDE ops/optim/game/parallel/serving are fine:
    # cold paths may sync freely
    shutil.copy(os.path.join(FIXTURES, "hot/ops/ph001_violation.py"),
                tmp_path / "coldpath.py")
    assert lint_paths([str(tmp_path / "coldpath.py")]) == []


def test_ph005_is_durable_module_scoped(tmp_path):
    shutil.copy(os.path.join(FIXTURES, "durable/models/io.py"),
                tmp_path / "scratch_writer.py")
    assert lint_paths([str(tmp_path / "scratch_writer.py")]) == []


def test_select_filters_rules():
    findings = _lint("hot/ops/ph001_violation.py", select=["PH005"])
    assert findings == []


def test_select_prefix_and_range():
    # prefix: PH01 selects exactly the concurrency family
    findings = _lint("concurrency/ph010_violation.py", select=["PH01"])
    assert [f.rule for f in findings] == ["PH010"] * 3
    assert _lint("hot/ops/ph001_violation.py", select=["PH01"]) == []
    # inclusive range
    findings = _lint("concurrency/ph012_violation.py",
                     select=["PH010-PH013"])
    assert [f.rule for f in findings] == ["PH012"] * 3
    assert _lint("concurrency/ph012_violation.py",
                 select=["PH010-PH011"]) == []


# --------------------------------------------------------------------------
# concurrency pass (PH010–PH013) semantics
# --------------------------------------------------------------------------

def test_guarded_by_annotation_round_trip(tmp_path):
    # the declared guard drives the finding; adding the lock silences it
    src = ("import threading\n"
           "class Box:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._v: int = 0   # photonlint: guarded-by=_lock\n"
           "    def bump(self):\n"
           "        with self._lock:\n"
           "            self._v += 1\n"
           "    def peek(self):\n"
           "        return self._v\n")
    bad = tmp_path / "box.py"
    bad.write_text(src)
    findings = lint_paths([str(bad)])
    assert [f.rule for f in findings] == ["PH010"]
    assert "declared guarded-by=_lock" in " ".join(findings[0].evidence)
    good = tmp_path / "box_ok.py"
    good.write_text(src.replace(
        "    def peek(self):\n        return self._v\n",
        "    def peek(self):\n        with self._lock:\n"
        "            return self._v\n"))
    assert lint_paths([str(good)]) == []


def test_guarded_by_unknown_lock_is_loud(tmp_path):
    (tmp_path / "box.py").write_text(
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._v = 0   # photonlint: guarded-by=_mutex\n")
    findings = lint_paths([str(tmp_path / "box.py")])
    assert [f.rule for f in findings] == ["PH010"]
    assert "names no lock attribute" in findings[0].message


def test_ph011_reports_both_witness_paths():
    findings = _lint("concurrency/ph011_violation.py", select=["PH011"])
    assert len(findings) == 1
    evidence = "\n".join(findings[0].evidence)
    assert "witness Ledger._alpha -> Ledger._beta" in evidence
    assert "witness Ledger._beta -> Ledger._alpha" in evidence
    assert "Ledger.credit" in evidence and "Ledger.debit" in evidence


def test_ph011_interprocedural_inversion(tmp_path):
    # the reverse arc only exists through a helper call chain
    (tmp_path / "ledger.py").write_text(
        "import threading\n"
        "class Ledger:\n"
        "    def __init__(self):\n"
        "        self._alpha = threading.Lock()\n"
        "        self._beta = threading.Lock()\n"
        "    def credit(self):\n"
        "        with self._alpha:\n"
        "            self._under_alpha()\n"
        "    def _under_alpha(self):\n"
        "        with self._beta:\n"
        "            pass\n"
        "    def debit(self):\n"
        "        with self._beta:\n"
        "            with self._alpha:\n"
        "                pass\n")
    findings = lint_paths([str(tmp_path / "ledger.py")])
    assert [f.rule for f in findings] == ["PH011"]
    assert "_under_alpha" in "\n".join(findings[0].evidence)


def test_ph012_flush_style_suppression(tmp_path):
    # the documented escape hatch: `# photonlint: disable=PH012` on the
    # blocking line (for a measured, accepted stall)
    src = open(os.path.join(FIXTURES, "concurrency/ph012_violation.py"),
               encoding="utf-8").read()
    src = src.replace("time.sleep(0.01)",
                      "time.sleep(0.01)  # photonlint: disable=PH012")
    dest = tmp_path / "swapper.py"
    dest.write_text(src)
    findings = lint_paths([str(dest)], select=["PH012"])
    assert len(findings) == 2  # the two device blocks remain
    assert all("time.sleep" not in f.text for f in findings)


def test_ph013_locked_recheck_is_compliant():
    assert _lint("concurrency/ph013_compliant.py") == []


def test_evidence_lands_in_json_report():
    findings = _lint("concurrency/ph011_violation.py")
    d = findings[0].to_dict()
    assert isinstance(d["evidence"], list) and len(d["evidence"]) >= 2


def test_lock_order_edges_exports_static_graph():
    from photon_ml_tpu.analysis.concurrency import lock_order_edges
    edges = lock_order_edges(
        [os.path.join(FIXTURES, "concurrency/ph011_violation.py")])
    assert ("Ledger._alpha", "Ledger._beta") in edges
    assert ("Ledger._beta", "Ledger._alpha") in edges


def test_ph004_registry_docs_drift(tmp_path):
    # when the linted tree carries its own faults.py registry, every
    # SITES entry must appear in the module docstring
    (tmp_path / "faults.py").write_text(
        '"""Docs mention stage.fetch only."""\n'
        'SITES = {"stage.fetch": ("chunk",),\n'
        '         "undocumented.site": ()}\n')
    findings = lint_paths([str(tmp_path / "faults.py")])
    # PH008 fires too: the synthetic site has no telemetry event constant
    assert sorted(f.rule for f in findings) == ["PH004", "PH008"]
    ph004 = next(f for f in findings if f.rule == "PH004")
    assert "undocumented.site" in ph004.message


def test_unparseable_module_is_reported_not_fatal(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    findings = lint_paths([str(tmp_path / "broken.py")])
    assert [f.rule for f in findings] == ["PH000"]


# --------------------------------------------------------------------------
# baseline workflow
# --------------------------------------------------------------------------

def test_baseline_grandfathers_and_goes_stale(tmp_path):
    viol = os.path.join(FIXTURES, "hot/ops/ph001_violation.py")
    baseline_path = str(tmp_path / "baseline.json")
    rc = lint_main([viol, "--baseline", baseline_path,
                    "--write-baseline"])
    assert rc == 0
    # all findings grandfathered -> clean exit
    assert lint_main([viol, "--baseline", baseline_path]) == 0
    # --no-baseline still reports them
    assert lint_main([viol, "--no-baseline"]) == 1
    # baseline identity survives line drift but not text changes
    findings = lint_paths([viol])
    baseline = Baseline.load(baseline_path)
    new, old, stale = baseline.split(findings)
    assert not new and len(old) == len(findings) and stale == 0


def test_baseline_multiset_matching(tmp_path):
    # two identical violating lines need two baseline entries
    dest = tmp_path / "hot" / "ops" / "twice.py"
    dest.parent.mkdir(parents=True)
    dest.write_text("import jax.numpy as jnp\n"
                    "def f(x):\n"
                    "    return float(jnp.sum(x))\n"
                    "def g(x):\n"
                    "    return float(jnp.sum(x))\n")
    findings = lint_paths([str(dest)])
    assert len(findings) == 2
    baseline = Baseline([findings[0].to_dict()
                         | {"path": findings[0].baseline_path}])
    new, old, stale = baseline.split(findings)
    assert len(new) == 1 and len(old) == 1


# --------------------------------------------------------------------------
# CLI contract (standalone / CI usage)
# --------------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis.lint", *args],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_cli_json_output_and_exit_codes():
    bad = _run_cli("tests/lint_fixtures/hot/ops/ph001_violation.py",
                   "--no-baseline", "--json")
    assert bad.returncode == 1
    report = json.loads(bad.stdout)
    assert report["counts"]["new"] == 4
    assert {f["rule"] for f in report["findings"]} == {"PH001"}
    assert all(not f["baselined"] for f in report["findings"])

    ok = _run_cli("tests/lint_fixtures/hot/ops/ph001_compliant.py",
                  "--no-baseline", "--json")
    assert ok.returncode == 0
    assert json.loads(ok.stdout)["counts"]["new"] == 0


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("PH001", "PH002", "PH003", "PH004", "PH005", "PH006",
                    "PH007", "PH008", "PH010", "PH011", "PH012", "PH013"):
        assert rule_id in out


def test_ph008_stale_event_constant_fixture():
    """A telemetry event constant whose site/trigger no longer exists is
    itself a drift finding (the registry diff cuts both ways)."""
    findings = _lint("telemetryreg_stale/telemetry/events.py")
    assert [f.rule for f in findings] == ["PH008"]
    assert "ghost.trigger" in findings[0].message


def test_ph008_package_registries_agree():
    """ISSUE 13 satellite: the SHIPPED registries — utils.faults.SITES,
    telemetry.flight.TRIGGERS, telemetry.events.EVENTS — agree exactly
    (checked at runtime here, statically by photonlint in CI), and the
    committed baseline carries no PH008 grandfathering."""
    from photon_ml_tpu.telemetry.events import EVENTS
    from photon_ml_tpu.telemetry.flight import TRIGGERS
    from photon_ml_tpu.utils.faults import SITES
    assert set(SITES) | set(TRIGGERS) == set(EVENTS), (
        "telemetry/events.py EVENTS must cover every fault site and "
        "flight trigger, with no stale extras")
    findings = lint_paths([PACKAGE_DIR], select=["PH008"])
    assert findings == []
    baseline = Baseline.load(DEFAULT_BASELINE)
    assert baseline.total == 0  # the committed baseline stays empty


def test_cli_select_concurrency_gate():
    # the CI gate spelling: the whole package must be clean under
    # `--select PH01` with the (empty) committed baseline
    proc = _run_cli("--select", "PH01", "photon_ml_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # and the evidence chain reaches the JSON report
    bad = _run_cli("tests/lint_fixtures/concurrency/ph011_violation.py",
                   "--no-baseline", "--select", "PH010-PH013", "--json")
    assert bad.returncode == 1
    report = json.loads(bad.stdout)
    assert report["counts"]["new"] == 1
    assert report["findings"][0]["rule"] == "PH011"
    assert any("witness" in e for e in report["findings"][0]["evidence"])


def test_cli_diff_mode_filters_to_changed_files(tmp_path):
    import textwrap
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", "-C", str(repo), *args], check=True,
                       capture_output=True,
                       env=dict(os.environ,
                                GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                                GIT_COMMITTER_NAME="t",
                                GIT_COMMITTER_EMAIL="t@t"))

    violating = textwrap.dedent("""\
        import threading
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._v = 0   # photonlint: guarded-by=_lock
            def peek(self):
                return self._v
    """)
    clean = "def nothing():\n    return 1\n"
    (repo / "old.py").write_text(violating)   # committed violation
    (repo / "new.py").write_text(clean)
    git("init", "-q")
    git("add", ".")
    git("commit", "-qm", "seed")
    # change only new.py to a violation; old.py stays dirty-but-committed
    (repo / "new.py").write_text(violating)
    full = lint_paths([str(repo)])
    assert {os.path.basename(f.path) for f in full} == {"old.py", "new.py"}
    rc = lint_main([str(repo), "--diff", "HEAD", "--no-baseline"])
    assert rc == 1
    # --diff vs HEAD must only report new.py (capture via --json)
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis.lint", str(repo),
         "--diff", "HEAD", "--no-baseline", "--json"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    report = json.loads(proc.stdout)
    assert {os.path.basename(f["path"]) for f in report["findings"]} \
        == {"new.py"}
    # an untracked file counts as changed
    (repo / "fresh.py").write_text(violating)
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis.lint", str(repo),
         "--diff", "HEAD", "--no-baseline", "--json"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    report = json.loads(proc.stdout)
    assert {os.path.basename(f["path"]) for f in report["findings"]} \
        == {"new.py", "fresh.py"}


def test_cli_diff_outside_git_is_usage_error(tmp_path):
    (tmp_path / "mod.py").write_text("x = 1\n")
    proc = _run_cli(str(tmp_path), "--diff", "HEAD")
    assert proc.returncode == 2
    assert "--diff" in proc.stderr


# --------------------------------------------------------------------------
# tier-1 gate: the shipped tree lints clean against the baseline
# --------------------------------------------------------------------------

def test_package_lints_clean_against_baseline():
    findings = lint_paths([PACKAGE_DIR])
    baseline = Baseline.load(DEFAULT_BASELINE)
    new, _, _ = baseline.split(findings)
    assert new == [], ("photonlint found non-baseline violations:\n"
                       + "\n".join(f.render() for f in new))


def test_baseline_stays_small():
    # acceptance: <= 5 grandfathered findings, and it should only shrink
    baseline = Baseline.load(DEFAULT_BASELINE)
    assert baseline.total <= 5


def test_concurrency_rules_are_never_grandfathered():
    # ISSUE 10 acceptance: PH010–PH013 ship with an EMPTY baseline —
    # concurrency findings get FIXED, not grandfathered
    with open(DEFAULT_BASELINE, encoding="utf-8") as f:
        entries = json.load(f).get("findings", [])
    assert [e for e in entries if e["rule"].startswith("PH01")] == []


def test_linter_package_lints_itself_clean():
    analysis_dir = os.path.join(PACKAGE_DIR, "analysis")
    assert lint_paths([analysis_dir]) == []
