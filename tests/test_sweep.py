"""Vectorized hyperparameter sweeps (hyperparameter/vectorized.py).

The contract under test: K regularization candidates cost one compiled
program (vmap lane) or one warm-started regularization path (sequential
lane), never K cold fits — regularization weights are TRACED OPERANDS
(optim.schedule.RegWeights), so changing lambda never retraces; per-candidate
results match isolated full f64 fits; the GP search chain is bit-identical
under a fixed seed.
"""
import dataclasses
import logging

import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig, RandomEffectCoordinateConfig,
)
from photon_ml_tpu.hyperparameter import (
    GameEstimatorEvaluationFunction, SweepEvaluator,
)
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, RegularizationType, RegWeights,
    solve,
)

L2 = RegularizationContext(RegularizationType.L2)
EN = RegularizationContext(RegularizationType.ELASTIC_NET,
                           elastic_net_alpha=0.5)


class _CompileCounter(logging.Handler):
    """Counts XLA compile events via jax_log_compiles (each 'Compiling
    <name> with global shapes' record is one fresh trace+compile)."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _compile_counting:
    def __enter__(self):
        import jax
        self._jax = jax
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        self._jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def _game_data(rng, n=400, d=5, users=12):
    xg = rng.normal(size=(n, d))
    xg[:, -1] = 1.0
    xu = rng.normal(size=(n, 3))
    u = rng.integers(0, users, size=n)
    z = xg @ rng.normal(size=d) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(users, 3))[u] * 0.7)
    y = z + 0.15 * rng.normal(size=n)
    ds = build_game_dataset(
        y, {"g": xg, "u": xu},
        entity_ids={"userId": np.asarray([f"u{i}" for i in u])})
    rows = np.arange(n)
    return ds.subset(rows[:300]), ds.subset(rows[300:])


def _config(w_fe=1.0, w_re=1.0, iters=2):
    return GameTrainingConfig(
        "linear_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "g", GLMOptimizationConfig(regularization=L2,
                                           regularization_weight=w_fe)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "u", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=w_re)),
        },
        updating_sequence=["fixed", "perUser"], num_outer_iterations=iters)


# -- RegWeights: lambda as a traced operand -----------------------------------

def test_regweights_matches_static_split(rng):
    """solve() with RegWeights reproduces the static reg.split arithmetic
    bit-for-bit — same objective, same solution."""
    from tests.synthetic import make_glm_data
    from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
    x, y, _, _ = make_glm_data(rng, n=120, d=6, task="logistic")
    obj = GLMObjective(TASK_LOSSES["logistic_regression"], x, y)
    x0 = np.zeros(6)
    for reg, w in ((L2, 0.7), (EN, 0.3)):
        static = solve(obj, x0, OptimizerConfig(), reg, w)
        traced = solve(obj, x0, OptimizerConfig(), reg,
                       RegWeights.from_context(reg, w))
        np.testing.assert_array_equal(np.asarray(static.x),
                                      np.asarray(traced.x))


def test_regweights_elastic_net_mix_is_traced(rng):
    """Varying the elastic-net MIX via RegWeights.from_context(alpha)
    re-dispatches one compiled program: zero fresh traces after warmup."""
    import jax
    from tests.synthetic import make_glm_data
    from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
    x, y, _, _ = make_glm_data(rng, n=120, d=6, task="logistic")
    obj = GLMObjective(TASK_LOSSES["logistic_regression"], x, y)
    x0 = np.zeros(6)

    solver = jax.jit(lambda o, x0, rw: solve(x0=x0, objective=o,
                                             config=OptimizerConfig(),
                                             reg=EN, reg_weight=rw))
    sols = []
    rws = [RegWeights.from_context(EN, w, elastic_net_alpha=a)
           for w, a in ((1.0, 0.5), (0.1, 0.9), (3.0, 0.1), (1.0, 0.0))]
    solver(obj, x0, rws[0])  # warmup trace
    with _compile_counting() as compiles:
        for rw in rws[1:]:
            sols.append(np.asarray(solver(obj, x0, rw).x))
    assert compiles.count == 0, (
        f"{compiles.count} fresh traces while sweeping (lambda, alpha) — "
        "regularization weights must be traced operands")
    # the solutions genuinely differ (the sweep is not a no-op)
    assert not np.allclose(sols[0], sols[1])
    # traced alpha == 0 under has_l1=True reaches the same smooth optimum
    # as the pure-L2 solve (same limit; iterates differ mid-path because
    # OWLQN's orthant projection stays compiled in)
    pure_l2 = solve(obj, x0, OptimizerConfig(), L2, 1.0)
    np.testing.assert_allclose(sols[2], np.asarray(pure_l2.x), atol=1e-4)


# -- vmap lane ----------------------------------------------------------------

def test_vmapped_sweep_parity_vs_isolated_fits(rng):
    """Per-candidate f64 parity <= 1e-6: every candidate of the vmapped
    sweep matches its isolated full fit — objective trajectory, final
    coefficients, and validation metric."""
    train, val = _game_data(rng)
    candidates = [_config(10.0, 5.0), _config(1.0, 1.0), _config(0.1, 0.3)]
    sweep = SweepEvaluator(GameEstimator(_config()), train, val)
    ok, why = sweep.vmap_eligible()
    assert ok, why
    results = sweep.evaluate_vmapped(candidates)
    assert len(results) == 3
    for cand, res in zip(candidates, results):
        iso = GameEstimator(cand).fit(train, val)
        np.testing.assert_allclose(res.objective_history,
                                   iso.objective_history, rtol=1e-6)
        np.testing.assert_allclose(res.validation["RMSE"],
                                   iso.validation["RMSE"], rtol=1e-6)
        for name in ("fixed", "perUser"):
            a = res.model.coordinates[name]
            b = iso.model.coordinates[name]
            va = (a.glm.coefficients.means if name == "fixed"
                  else a.coefficients)
            vb = (b.glm.coefficients.means if name == "fixed"
                  else b.coefficients)
            np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                       atol=1e-6)
        # per-candidate diagnostics flow through solver_diagnostics()
        diag = res.descent.solver_diagnostics()
        assert diag["fixed"]["solves"] == 2
        assert diag["fixed"]["iterations"] > 0
        assert diag["perUser"]["reasons"]


def test_vmapped_sweep_ineligible_shapes_are_refused(rng):
    """Streamed coordinates fall off the vmap lane with a reason (the
    caller then routes to the warm-start path)."""
    train, val = _game_data(rng)
    cfg = _config()
    streamed = dataclasses.replace(cfg, coordinates={
        **cfg.coordinates,
        "fixed": dataclasses.replace(cfg.coordinates["fixed"],
                                     memory_mode="streamed",
                                     chunk_rows=128)})
    sweep = SweepEvaluator(GameEstimator(streamed), train, val)
    ok, why = sweep.vmap_eligible()
    assert not ok and "streamed" in why
    with pytest.raises(ValueError, match="vmap lane ineligible"):
        sweep.evaluate_vmapped([_config(), _config(0.1, 0.1)])
    # evaluate() falls back instead of raising
    results = sweep.evaluate([streamed, dataclasses.replace(
        streamed, coordinates={**streamed.coordinates,
                               "perUser": dataclasses.replace(
                                   streamed.coordinates["perUser"],
                                   optimization=GLMOptimizationConfig(
                                       regularization=L2,
                                       regularization_weight=0.1))})])
    assert len(results) == 2 and all(r.validation for r in results)


def test_sweep_rejects_structural_config_changes(rng):
    """Only regularization weights may vary across candidates — anything
    else must not silently reuse the prepared state."""
    train, val = _game_data(rng)
    sweep = SweepEvaluator(GameEstimator(_config()), train, val)
    structural = _config(iters=3)          # different outer iterations
    assert not sweep.compatible(structural)
    with pytest.raises(ValueError, match="more than regularization"):
        sweep.evaluate_config(structural)


# -- warm-start path lane -----------------------------------------------------

def test_path_sweep_cold_parity_and_warm_ordering(rng):
    """The sequential lane with warm_start=False IS the isolated fit (same
    solvers over the shared prepared coordinates); with warm starts the
    path runs strong-to-weak and each candidate still converges (objective
    within the solver's tolerance band of the cold fit)."""
    train, val = _game_data(rng)
    candidates = [_config(0.1, 0.3), _config(10.0, 5.0), _config(1.0, 1.0)]
    sweep = SweepEvaluator(GameEstimator(_config()), train, val)
    cold = sweep.evaluate_path(candidates, warm_start=False)
    for cand, res in zip(candidates, cold):
        iso = GameEstimator(cand).fit(train, val)
        np.testing.assert_allclose(res.objective_history,
                                   iso.objective_history, rtol=1e-6)
        np.testing.assert_allclose(res.validation["RMSE"],
                                   iso.validation["RMSE"], rtol=1e-6)
    warm = sweep.evaluate_path(candidates, warm_start=True)
    # results come back in CALLER order regardless of path order
    for cand, res in zip(candidates, warm):
        assert res.config is cand
    # a warm-started solve still reaches (or beats) the neighborhood of
    # the cold solution — the path changes trajectories, not the limit
    for c, w in zip(cold, warm):
        assert w.objective_history[-1] <= c.objective_history[-1] * 1.02


def test_path_sweep_zero_fresh_traces_after_first_candidate(rng):
    """Candidates 2..N of the path lane re-dispatch the first candidate's
    compiled programs — lambda is a traced operand everywhere."""
    train, val = _game_data(rng)
    sweep = SweepEvaluator(GameEstimator(_config()), train, val)
    sweep.evaluate_config(_config(5.0, 2.0))   # warmup: compiles everything
    lams = np.logspace(1, -2, 15)
    with _compile_counting() as compiles:
        sweep.evaluate_path([_config(l, l) for l in lams])
    assert compiles.count == 0, (
        f"{compiles.count} fresh traces across a 15-candidate path sweep")


def test_vmapped_sweep_zero_fresh_traces_after_warmup(rng):
    """The 16-point compile-count regression: after one warmup sweep of
    the same candidate count, a full 16-point vmapped sweep triggers ZERO
    fresh XLA traces."""
    train, val = _game_data(rng)
    sweep = SweepEvaluator(GameEstimator(_config()), train, val)
    lams = np.logspace(1.5, -2, 16)
    sweep.evaluate_vmapped([_config(l, 2 * l) for l in lams])   # warmup
    with _compile_counting() as compiles:
        results = sweep.evaluate_vmapped(
            [_config(0.7 * l, l) for l in lams])
    assert compiles.count == 0, (
        f"{compiles.count} fresh traces across a warm 16-point sweep")
    assert len(results) == 16
    objs = [r.objective_history[-1] for r in results]
    assert len(set(round(o, 6) for o in objs)) > 1


# -- shared prepared state + GP integration -----------------------------------

def test_evaluation_function_shares_prepared_state(rng, monkeypatch):
    """GameEstimatorEvaluationFunction builds the GAME dataset/coordinate
    state ONCE: repeated candidate evaluations hit the same
    SweepEvaluator, never a per-candidate rebuild."""
    train, val = _game_data(rng)
    est = GameEstimator(_config())
    builds = []
    orig = GameEstimator._build_coordinates

    def counting(self, dataset):
        builds.append(dataset)
        return orig(self, dataset)

    monkeypatch.setattr(GameEstimator, "_build_coordinates", counting)
    fn = GameEstimatorEvaluationFunction(est, train, val, scale="log")
    v1, r1 = fn(np.asarray([0.5, 0.5]))
    v2, r2 = fn(np.asarray([-0.5, 0.0]))
    assert len(builds) == 1, (
        f"{len(builds)} coordinate builds for 2 candidate evaluations — "
        "the prepared dataset must be shared")
    assert fn.sweep is fn.sweep
    assert r1.config is not r2.config and v1 != v2

    batch = fn.evaluate_all([np.asarray([1.0, 1.0]),
                             np.asarray([0.0, -1.0])])
    assert len(builds) == 1 and len(batch) == 2


def test_sweep_telemetry_counters(rng):
    """sweep.candidates / sweep.dispatches land on the global registry;
    the vmap lane's dispatch count is SUBLINEAR in candidates (that is
    the point)."""
    from photon_ml_tpu import telemetry
    train, val = _game_data(rng)
    sweep = SweepEvaluator(GameEstimator(_config()), train, val)
    c0 = telemetry.counter("sweep.candidates").value
    d0 = telemetry.counter("sweep.dispatches").value
    K = 8
    sweep.evaluate_vmapped([_config(l, l) for l in np.logspace(1, -2, K)])
    candidates = telemetry.counter("sweep.candidates").value - c0
    dispatches = telemetry.counter("sweep.dispatches").value - d0
    assert candidates == K
    # 2 outer iters x (1 FE + <=bucket+1 RE) programs + validation: far
    # fewer dispatches than K isolated fits would have issued
    assert 0 < dispatches <= 16
    snap = telemetry.snapshot()
    assert "sweep.candidates" in snap["metrics"]["counters"]
    assert "sweep.dispatches" in snap["metrics"]["counters"]


def test_gp_sweep_seed_reproduces_candidate_sequence():
    """Fixed seed -> bit-identical candidate sequences through the full GP
    chain (candidate init + GP estimator + slice sampler)."""
    from photon_ml_tpu.evaluation.evaluators import RMSE
    from photon_ml_tpu.hyperparameter import GaussianProcessSearch
    from photon_ml_tpu.hyperparameter.search import EvaluationFunction

    class Quad(EvaluationFunction[tuple]):
        def __init__(self):
            self.seen = []

        def __call__(self, candidate):
            v = float(np.sum((np.asarray(candidate) - 0.3) ** 2))
            obs = (np.asarray(candidate, dtype=float).copy(), v)
            self.seen.append(obs[0])
            return v, obs

        def vectorize_params(self, observation):
            return observation[0]

        def get_evaluation_value(self, observation):
            return observation[1]

    def run(seed):
        fn = Quad()
        GaussianProcessSearch([(-2.0, 2.0)] * 2, fn, RMSE,
                              candidate_pool_size=40, seed=seed).find(6)
        return np.asarray(fn.seen)

    a, b, c = run(11), run(11), run(12)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_cli_exposes_sweep_seed():
    from photon_ml_tpu.cli.train import build_parser
    args = build_parser().parse_args(
        ["--train-data", "x.avro", "--task", "logistic_regression",
         "--output-dir", "/tmp/o", "--sweep-seed", "123"])
    assert args.sweep_seed == 123
    args = build_parser().parse_args(
        ["--train-data", "x.avro", "--task", "logistic_regression",
         "--output-dir", "/tmp/o"])
    assert args.sweep_seed is None
