"""End-to-end runs on the reference repo's OWN checked-in data files.

These fixtures were written by the actual photon-ml toolchain (heart-scale
TrainingExampleAvro data, renamed-column and bad-weight variants —
reference: DriverIntegTest/input, used by its DriverTest e2e and negative
tests).  Gated on the reference checkout being present.
"""
import json
import os

import numpy as np
import pytest

_BASE = ("/root/reference/photon-client/src/integTest/resources/"
         "DriverIntegTest/input")

pytestmark = pytest.mark.skipif(not os.path.isdir(_BASE),
                                reason="reference checkout not present")


def _read(path, **kw):
    from photon_ml_tpu.data.avro_game import read_game_examples
    return read_game_examples([path], {"global": ["features"]}, **kw)


def test_heart_data_trains_end_to_end(tmp_path):
    """The reference's heart-scale logistic fixture ingests through the
    native decoder and trains through the full CLI with a sane AUC —
    the reference's own DriverTest flow, minus Spark."""
    from tests.test_io_cli import _run_cli
    from photon_ml_tpu.data.game_data import save_game_dataset

    res = _read(os.path.join(_BASE, "heart.avro"))
    ds = res.dataset
    assert ds.num_rows == 250
    assert set(np.unique(ds.response)) <= {0.0, 1.0}
    ds_p = str(tmp_path / "heart.npz")
    save_game_dataset(ds, ds_p)
    out = str(tmp_path / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", ds_p, "--validation-data", ds_p,
                  "--task", "logistic_regression", "--reg-weights", "1.0",
                  "--evaluators", "AUC", "--output-dir", out])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["validation"]["AUC"] > 0.85


def test_different_column_names_remap():
    """The renamed-column fixture (the_label/w/intercept/metadata) reads
    through --input-columns remapping (reference: its
    different-column-names negative/positive tests)."""
    from photon_ml_tpu.data.game_data import InputColumnNames

    res = _read(os.path.join(_BASE,
                             "different-column-names/diff-col-names.avro"),
                columns=InputColumnNames(response="the_label", weight="w",
                                         offset="intercept"))
    ds = res.dataset
    assert ds.num_rows == 250
    assert set(np.unique(ds.response)) <= {0.0, 1.0}
    assert ds.weights is not None and (np.asarray(ds.weights) == 1.0).all()
    # the heart fixture's columns under default names must match this one
    heart = _read(os.path.join(_BASE, "heart.avro")).dataset
    np.testing.assert_allclose(np.sort(ds.response),
                               np.sort(heart.response))


_GAME_IN = ("/root/reference/photon-client/src/integTest/resources/"
            "GameIntegTest/input")


@pytest.mark.skipif(not os.path.isdir(_GAME_IN),
                    reason="reference checkout not present")
def test_yahoo_duplicate_features_fixture_rejected():
    """The reference's REAL Yahoo-music duplicate-features fixture must be
    rejected at ingest, like its AvroDataReader ('Duplicate features
    found', AvroDataReaderIntegTest.scala:75-88).  The merged multi-bag
    read (shard1 = userFeatures + songFeatures, the reference's own
    featureSectionMap) also ingests the clean records per shard."""
    from photon_ml_tpu.data.avro_game import read_game_examples
    p = os.path.join(_GAME_IN, "duplicateFeatures", "yahoo-music-train.avro")
    with pytest.raises(ValueError, match="[Dd]uplicate feature"):
        read_game_examples([p], {"global": ["features"]},
                           id_columns=["userId", "songId"])
    # the userFeatures/songFeatures bags carry no duplicates: the
    # reference's shard map reads fine and ids extract from int columns
    res = read_game_examples(
        [p], {"shard1": ["userFeatures", "songFeatures"],
              "shard2": ["userFeatures"], "shard3": ["songFeatures"]},
        id_columns=["userId", "songId"])
    ds = res.dataset
    assert ds.num_rows == 6
    assert ds.feature_shards["shard1"].shape[1] > \
        ds.feature_shards["shard2"].shape[1]
    assert (ds.entity_indices["userId"] >= 0).all()


_SELECTED = ("/root/reference/photon-client/src/integTest/resources/"
             "GLMSuiteIntegTest/selectedFeatures.avro")


@pytest.mark.skipif(not os.path.exists(_SELECTED),
                    reason="reference checkout not present")
def test_selected_features_fixture_restricts_space(tmp_path, rng):
    """--selected-features with the reference's REAL FeatureAvro fixture
    (f1.t1, f4.t2) freezes the feature space to those keys + intercept
    (reference: GLMSuite selectedFeaturesFile)."""
    from tests.test_io_cli import _run_cli
    from photon_ml_tpu.data import build_index_map
    from photon_ml_tpu.data.avro_game import write_game_examples
    from photon_ml_tpu.models.io import load_game_model, load_model_index_maps

    n = 80
    imap = build_index_map([("f1", "t1"), ("f2", ""), ("f4", "t2"),
                            ("f5", "")])
    x = (rng.uniform(size=(n, imap.size)) < 0.6).astype(float)
    y = x @ rng.normal(size=imap.size) + 0.1 * rng.normal(size=n)
    data_p = str(tmp_path / "train.avro")
    write_game_examples(data_p, y, bags={"features": (x, imap)})
    out = str(tmp_path / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", data_p, "--task", "linear_regression",
                  "--reg-weights", "1.0", "--output-dir", out,
                  "--selected-features", _SELECTED])
    assert r.returncode == 0, r.stderr[-2000:]
    model, _ = load_game_model(out + "/best")
    means = np.asarray(model.coordinates["fixed"].glm.coefficients.means)
    assert len(means) == 3  # f1.t1 + f4.t2 + intercept
    maps = load_model_index_maps(out + "/best")
    m = maps["global"]
    assert m.index_of("f1", "t1") >= 0 and m.index_of("f4", "t2") >= 0
    assert m.index_of("f2") == -1


@pytest.mark.parametrize("fixture", ["zero-weights.avro",
                                     "negative-weights.avro"])
def test_bad_weights_rejected(fixture):
    """Non-positive sample weights are verified-and-rejected, matching the
    GAME driver's checkData (reference DriverTest.testBadSampleWeights
    expects IllegalArgumentException on these exact files)."""
    from photon_ml_tpu.data.validators import (DataValidationError,
                                               validate_game_dataset)
    res = _read(os.path.join(_BASE, "bad-weights", fixture))
    with pytest.raises(DataValidationError, match="weights <= 0"):
        validate_game_dataset(res.dataset, "linear_regression")
    # the cheap rejection survives --data-validation disabled, matching
    # the reference's SEPARATE always-on checkData flag
    with pytest.raises(DataValidationError, match="weights <= 0"):
        validate_game_dataset(res.dataset, "linear_regression", "disabled")
