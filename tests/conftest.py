"""Test fixture: CPU backend with 8 virtual devices + float64.

This is the TPU-rebuild equivalent of the reference's `sparkTest` local-mode
fixture (reference: photon-test-utils/.../test/SparkTestUtils.scala:31-77):
all distributed code paths run on an 8-device virtual CPU mesh, and parity
math runs in float64 to match the all-double JVM reference.
"""
import os

# Force CPU with 8 virtual devices: the session environment pins
# JAX_PLATFORMS to the (single, tunneled) TPU chip, which would make every
# test a remote TPU compile.  NOTE: a pytest plugin imports jax before this
# conftest runs, so the env var alone is too late — use jax.config as well
# (safe because no backend has been initialized yet at collection time).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: only the XLA_FLAGS path above exists (and suffices as long
    # as no plugin initialized a backend before this conftest ran)
    pass
jax.config.update("jax_enable_x64", True)
# Persistent compilation cache: repeated test runs skip recompilation.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(7)
