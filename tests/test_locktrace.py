"""utils/locktrace.py: the runtime lock-order tracker.

Covers the disarm contract (tracked() returns the RAW lock — zero
wrapper overhead on every acquisition), armed edge recording with
first-witness stacks, condition-variable semantics, and the
static-vs-dynamic cross-validation both ways (consistent set passes,
reversed/unknown orders fail loudly)."""
import threading

import pytest

from photon_ml_tpu.utils import locktrace


def test_disarmed_tracked_is_identity():
    lock = threading.Lock()
    assert locktrace.tracked(lock, "X._lock") is lock
    cv = threading.Condition()
    assert locktrace.tracked(cv, "X._cv") is cv
    assert locktrace.active() is None


def test_armed_wrapping_and_edge_recording():
    with locktrace.enabled() as tracker:
        a = locktrace.tracked(threading.Lock(), "A._lock")
        b = locktrace.tracked(threading.Lock(), "B._lock")
        assert isinstance(a, locktrace.TracedLock)
        with a:
            with b:
                pass
        with b:
            pass  # no a under b: no reverse edge
    assert locktrace.active() is None
    edges = tracker.edges()
    assert ("A._lock", "B._lock") in edges
    assert ("B._lock", "A._lock") not in edges
    thread, stack = edges[("A._lock", "B._lock")]
    assert stack  # witness captured on first observation
    assert tracker.acquisitions()["A._lock"] == 1
    assert tracker.acquisitions()["B._lock"] == 2
    assert tracker.report()["locks_wrapped"] == 2


def test_condition_wrap_keeps_cv_protocol():
    with locktrace.enabled() as tracker:
        cv = locktrace.tracked(threading.Condition(), "C._cv")
        assert isinstance(cv, locktrace.TracedCondition)
        done = []

        def worker():
            with cv:
                done.append(1)
                cv.notify_all()

        t = threading.Thread(target=worker)
        with cv:
            t.start()
            while not done:
                assert cv.wait(timeout=5.0)
        t.join(timeout=5.0)
    assert tracker.acquisitions()["C._cv"] >= 2


def test_validation_passes_on_consistent_orders():
    with locktrace.enabled() as tracker:
        a = locktrace.tracked(threading.Lock(), "A._lock")
        b = locktrace.tracked(threading.Lock(), "B._lock")
        with a:
            with b:
                pass
    tracker.assert_consistent({("A._lock", "B._lock")})
    assert tracker.validate_against({("A._lock", "B._lock")}) == []


def test_validation_flags_reversed_order():
    with locktrace.enabled() as tracker:
        a = locktrace.tracked(threading.Lock(), "A._lock")
        b = locktrace.tracked(threading.Lock(), "B._lock")
        with b:
            with a:
                pass
    problems = tracker.validate_against({("A._lock", "B._lock")})
    assert len(problems) == 1 and "REVERSES" in problems[0]
    with pytest.raises(locktrace.LockOrderViolation):
        tracker.assert_consistent({("A._lock", "B._lock")})


def test_validation_flags_unknown_edge_as_call_graph_gap():
    with locktrace.enabled() as tracker:
        a = locktrace.tracked(threading.Lock(), "A._lock")
        b = locktrace.tracked(threading.Lock(), "B._lock")
        with a:
            with b:
                pass
    problems = tracker.validate_against(set())
    assert len(problems) == 1 and "call-graph gap" in problems[0]


def test_acquire_release_protocol_and_reentrancy():
    with locktrace.enabled() as tracker:
        r = locktrace.tracked(threading.RLock(), "R._lock")
        a = locktrace.tracked(threading.Lock(), "A._lock")
        assert r.acquire()
        assert r.acquire()       # re-entrant: no self-edge
        with a:
            pass
        r.release()
        r.release()
    assert ("R._lock", "R._lock") not in tracker.edges()
    assert ("R._lock", "A._lock") in tracker.edges()


def test_per_thread_held_stacks_do_not_cross():
    with locktrace.enabled() as tracker:
        a = locktrace.tracked(threading.Lock(), "A._lock")
        b = locktrace.tracked(threading.Lock(), "B._lock")
        holding_a = threading.Event()
        release_a = threading.Event()

        def hold_a():
            with a:
                holding_a.set()
                release_a.wait(timeout=5.0)

        t = threading.Thread(target=hold_a)
        t.start()
        assert holding_a.wait(timeout=5.0)
        with b:   # this thread holds nothing else: no A->B edge
            pass
        release_a.set()
        t.join(timeout=5.0)
    assert ("A._lock", "B._lock") not in tracker.edges()
