"""Feature-axis consensus-ADMM lane (optim/admm.py + the staging,
dispatch and telemetry wiring around it).

Covers the PR's acceptance gates as unit tests:
  - f64 objective parity of the PURE consensus solve (polish off) against
    the monolithic host-stepped solver on 1x1 / 1x2 / 2x2 / 4x2 meshes;
  - zero fresh XLA traces across warm ADMM solves, including rho sweeps,
    tolerance/budget changes and adaptive-rho runs (rho and the budget are
    traced operands, never trace keys);
  - L1 sparsity-pattern agreement with the monolithic OWLQN lane;
  - checkpoint-resume through GameEstimator while the ADMM lane is the
    fixed-effect solver;
  - one feature-axis vector all-reduce (plus one data-axis block
    all-reduce) per compiled iteration, by HLO collective accounting;
  - make_mesh feature-axis construction, shardings and the fail-loud /
    warn-once eligibility rules on FixedEffectCoordinate.
"""
import json
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig,
)
from photon_ml_tpu.ops.losses import LOGISTIC, SQUARED
from photon_ml_tpu.ops.normalization import NormalizationType
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import (
    ADMMConfig, OptimizerConfig, RegularizationContext, RegularizationType,
    SolverSchedule,
)
from photon_ml_tpu.optim.admm import (
    cached_step_probe, collective_summary, make_init, ADMMOperands,
)
from photon_ml_tpu.parallel import make_mesh
from photon_ml_tpu.parallel.fixed_effect import (
    _fold_x0, _stage_admm_operands, fit_fixed_effect, fit_fixed_effect_admm,
    stage_admm_grid,
)
from photon_ml_tpu.parallel.mesh import (
    DATA_AXIS, FEATURE_AXIS, feature_sharding, grid_sharding,
)

L1 = RegularizationContext(RegularizationType.L1)
L2 = RegularizationContext(RegularizationType.L2)


def _mesh(num_data, num_feature):
    return make_mesh(num_data, num_feature,
                     devices=jax.devices()[:num_data * num_feature])


def _problem(rng, loss, n=240, d=17):
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d)
    z = x @ w
    if loss is LOGISTIC:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(np.float64)
    else:
        y = z + 0.1 * rng.normal(size=n)
    return GLMObjective(loss, x, y)


def _penalized(obj, x, l1_w=0.0, l2_w=0.0):
    x = np.asarray(x)
    return (float(obj.value(jnp.asarray(x)))
            + 0.5 * l2_w * float(x @ x) + l1_w * float(np.abs(x).sum()))


# ---------------------------------------------------------------------------
# f64 parity: pure consensus solve vs the monolithic solver
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape", [(1, 1), (1, 2), (2, 2), (4, 2)])
@pytest.mark.parametrize("loss", [LOGISTIC, SQUARED], ids=["logistic", "sq"])
def test_pure_admm_matches_monolithic(rng, shape, loss):
    """Polish OFF: the consensus iterate itself must land on the monolithic
    optimum to f64 working precision (acceptance gate: rel <= 1e-6)."""
    obj = _problem(rng, loss)
    x0 = np.zeros(obj.dim)
    mesh = _mesh(*shape)
    res = fit_fixed_effect_admm(
        obj, x0, mesh,
        ADMMConfig(max_iterations=800, tolerance=1e-10, polish=False),
        reg=L2, reg_weight=0.3,
        residency_key=("admm-parity", shape, loss.name))
    ref = fit_fixed_effect(
        obj, x0, _mesh(shape[0] * shape[1], 1),
        OptimizerConfig(max_iterations=500, tolerance=1e-12),
        reg=L2, reg_weight=0.3)
    v_admm = _penalized(obj, res.x, l2_w=0.3)
    v_ref = _penalized(obj, ref.x, l2_w=0.3)
    assert abs(v_admm - v_ref) <= 1e-6 * abs(v_ref), (shape, v_admm, v_ref)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(ref.x),
                               rtol=1e-4, atol=1e-6)


def test_admm_polish_pins_exact_parity(rng):
    """Polish ON (the default): the returned iterate IS a monolithic
    solution warm-started at consensus — bit-for-bit the strict lane's
    fixed point, with both phases' iterations summed."""
    obj = _problem(rng, LOGISTIC)
    x0 = np.zeros(obj.dim)
    mesh = _mesh(2, 2)
    res = fit_fixed_effect_admm(
        obj, x0, mesh, ADMMConfig(max_iterations=60, tolerance=1e-4),
        config=OptimizerConfig(max_iterations=200, tolerance=1e-9),
        reg=L2, reg_weight=0.3, residency_key=("admm-polish",))
    ref = fit_fixed_effect(
        obj, x0, mesh, OptimizerConfig(max_iterations=400, tolerance=1e-9),
        reg=L2, reg_weight=0.3, shard_features=False)
    v = _penalized(obj, res.x, l2_w=0.3)
    v_ref = _penalized(obj, ref.x, l2_w=0.3)
    assert abs(v - v_ref) <= 1e-9 * abs(v_ref)
    assert res.iterations > 0


# ---------------------------------------------------------------------------
# zero fresh traces across warm solves: rho, tolerance and x0 are operands
# ---------------------------------------------------------------------------

class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _compile_counting:
    def __enter__(self):
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_zero_fresh_traces_across_warm_admm_solves(rng):
    """rho sweeps, tolerance/budget changes, warm starts and in-loop
    adaptive rho all re-dispatch ONE executable — the program's trace keys
    are (loss, has_l1, ceiling, adapt_rho, newton_steps, rho_tau, rho_mu)
    and nothing else."""
    obj = _problem(rng, LOGISTIC)
    mesh = _mesh(2, 2)
    key = ("admm-traces",)

    def run(cfg, x0):
        return fit_fixed_effect_admm(obj, x0, mesh, cfg, reg=L2,
                                     reg_weight=0.3, residency_key=key)

    base = dict(max_iterations=120, polish=False)
    # warm EVERY shape-distinct path: cold staging + program, then a warm
    # start from a device-resident x (the jnp _fold_x0 branch)
    first = run(ADMMConfig(tolerance=1e-8, **base), np.zeros(obj.dim))
    run(ADMMConfig(tolerance=1e-8, **base), first.x)
    with _compile_counting() as counter:
        warm = run(ADMMConfig(tolerance=1e-8, **base), np.zeros(obj.dim))
        run(ADMMConfig(tolerance=1e-6, rho=0.25, **base), warm.x)
        run(ADMMConfig(tolerance=1e-10, rho=4.0, **base), warm.x)
        run(ADMMConfig(tolerance=1e-8, rho=1.0, adapt_rho=True, **base),
            np.zeros(obj.dim))
    assert counter.count == 0


# ---------------------------------------------------------------------------
# L1: per-shard soft-thresholding agrees with the monolithic OWLQN lane
# ---------------------------------------------------------------------------

def test_l1_sparsity_pattern_matches_owlqn(rng):
    n, d = 320, 12
    x = rng.normal(size=(n, d))
    w_true = np.zeros(d)
    w_true[:4] = [3.0, -2.0, 1.5, 2.5]
    y = x @ w_true + 0.05 * rng.normal(size=n)
    obj = GLMObjective(SQUARED, x, y)
    lam = 30.0
    mesh = _mesh(2, 4)
    res = fit_fixed_effect_admm(
        obj, np.zeros(d), mesh,
        ADMMConfig(max_iterations=1500, tolerance=1e-11, polish=False),
        reg=L1, reg_weight=lam, residency_key=("admm-l1",))
    ref = fit_fixed_effect(
        obj, np.zeros(d), mesh,
        OptimizerConfig(max_iterations=600, tolerance=1e-12),
        reg=L1, reg_weight=lam, shard_features=False)
    xa, xr = np.asarray(res.x), np.asarray(ref.x)
    pat_a, pat_r = np.abs(xa) > 1e-6, np.abs(xr) > 1e-6
    # the regulariser must actually bite AND leave signal, else the test
    # proves nothing
    assert pat_r.any() and not pat_r.all()
    np.testing.assert_array_equal(pat_a, pat_r)
    v_a = _penalized(obj, xa, l1_w=lam)
    v_r = _penalized(obj, xr, l1_w=lam)
    assert abs(v_a - v_r) <= 1e-6 * abs(v_r)


# ---------------------------------------------------------------------------
# GameEstimator integration: checkpoint-resume while ADMM drives the FE
# ---------------------------------------------------------------------------

def _fe_dataset(rng, n=640, d=8):
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(float)
    return build_game_dataset(y, {"global": x})


def _fe_config(outer=2, schedule=None, **fe_kw):
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={"fixed": FixedEffectCoordinateConfig(
            "global",
            GLMOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=30),
                regularization=L2, regularization_weight=0.1),
            **fe_kw)},
        updating_sequence=["fixed"], num_outer_iterations=outer,
        solver_schedule=schedule)


def test_checkpoint_resume_mid_admm(rng, tmp_path):
    """Killing a feature-sharded fit between outer iterations and resuming
    from the checkpoint must reproduce the straight run — the ADMM lane's
    state fully round-trips through the coordinate checkpoint."""
    ds = _fe_dataset(rng)
    ckpt = tmp_path / "ckpt"
    straight = GameEstimator(_fe_config(2), mesh=make_mesh(4, 2)).fit(ds)
    partial = GameEstimator(_fe_config(1), mesh=make_mesh(4, 2)).fit(
        ds, checkpoint_dir=ckpt)
    resumed = GameEstimator(_fe_config(2), mesh=make_mesh(4, 2)).fit(
        ds, checkpoint_dir=ckpt)
    np.testing.assert_allclose(partial.objective_history,
                               straight.objective_history[:1], rtol=1e-7)
    np.testing.assert_allclose(resumed.objective_history,
                               straight.objective_history, rtol=1e-5)


def test_scheduled_admm_polish_gating():
    """With a SolverSchedule, only the trailing admm_polish_iterations
    outer visits run the monolithic polish."""
    sched = SolverSchedule(admm_polish_iterations=2)
    assert [sched.admm_polish(t, 5) for t in range(5)] == [
        False, False, False, True, True]
    rt = SolverSchedule.from_dict(sched.to_dict())
    assert rt.admm_polish_iterations == 2
    # default stays out of the encoded dict (stable configs don't churn)
    assert "admm_polish_iterations" not in SolverSchedule().to_dict()
    with pytest.raises(ValueError):
        SolverSchedule(admm_polish_iterations=0)


# ---------------------------------------------------------------------------
# collective accounting: ONE feature-axis vector psum per iteration
# ---------------------------------------------------------------------------

def test_one_feature_axis_reduction_per_iteration(rng):
    """Lower the exact while_loop body with the production shardings and
    count all-reduces in the compiled HLO: one [n_local] vector reduction
    over the FEATURE groups, one [F_local, d_F] block reduction over DATA,
    everything else scalar residual bookkeeping."""
    n, d = 256, 64
    obj = GLMObjective(LOGISTIC, rng.normal(size=(n, d)),
                       (rng.uniform(size=n) < 0.5).astype(np.float64))
    mesh = _mesh(2, 4)
    staged, n_, d_, bw = _stage_admm_operands(obj, mesh, ("admm-hlo",))
    ops = ADMMOperands(
        x_grid=staged["x_grid"], q_eig=staged["q_eig"],
        lam_eig=staged["lam_eig"], labels=staged["labels"],
        kappa=staged["mask"], offsets=staged["offsets"],
        l1_weight=jnp.asarray(0.0, jnp.float64),
        l2_weight=jnp.asarray(0.1, jnp.float64))
    with mesh:
        w0 = jax.device_put(jnp.zeros((4, bw)), feature_sharding(mesh, 2))
        carry = make_init(LOGISTIC, False, ops, w0,
                          jnp.asarray(1.0, jnp.float64), 8)
        txt = cached_step_probe(LOGISTIC, False, True, 8).lower(
            ops, carry).compile().as_text()
    summary = collective_summary(txt, mesh)
    n_local = staged["labels"].shape[0] // mesh.shape[DATA_AXIS]
    feature_vectors = [e for e in summary["feature"] if e[0] >= 1]
    assert feature_vectors == [(1, n_local * 8)], summary
    data_blocks = [e for e in summary["data"] if e[0] >= 1]
    assert len(data_blocks) == 1 and data_blocks[0][0] >= 2, summary
    assert not summary["other"], summary
    assert all(e[0] == 0 for e in summary["global"]), summary


# ---------------------------------------------------------------------------
# make_mesh feature axis + shardings (satellite: direct unit tests)
# ---------------------------------------------------------------------------

def test_make_mesh_feature_axis():
    mesh = make_mesh(2, 4)
    assert mesh.axis_names == (DATA_AXIS, FEATURE_AXIS)  # data OUTERMOST
    assert mesh.shape[DATA_AXIS] == 2 and mesh.shape[FEATURE_AXIS] == 4
    assert mesh.devices.shape == (2, 4)
    # num_data inferred from the device count
    assert make_mesh(num_feature=4).shape[DATA_AXIS] == 2
    with pytest.raises(ValueError) as ei:
        make_mesh(3, 3)
    msg = str(ei.value)
    assert "data=3 x feature=3" in msg and "8-device" in msg
    assert "outermost" in msg


def test_feature_and_grid_shardings_place_blocks():
    mesh = make_mesh(2, 4)
    w = jax.device_put(np.arange(8.0).reshape(4, 2),
                       feature_sharding(mesh, 2))
    assert w.addressable_shards[0].data.shape == (1, 2)
    g = jax.device_put(np.zeros((8, 4, 2)), grid_sharding(mesh))
    assert g.addressable_shards[0].data.shape == (4, 1, 2)
    # row r of the device grid holds all feature shards of data block r
    for shard in g.addressable_shards:
        row = shard.device.id // 4
        assert shard.index[0] == slice(4 * row, 4 * (row + 1))


def test_grid_staging_pads_and_splits(rng):
    """d not divisible by F zero-pads the tail column block; scoring and
    solving slice it back off."""
    x = rng.normal(size=(50, 10))
    mesh = _mesh(2, 4)
    n, d, bw, x_grid = stage_admm_grid(("admm-pad",), mesh, x)
    assert (n, d, bw) == (50, 10, 3)
    assert x_grid.shape[1:] == (4, 3)
    assert x_grid.shape[0] % mesh.shape[DATA_AXIS] == 0
    host = np.asarray(x_grid)[:50].reshape(50, 12)
    np.testing.assert_array_equal(host[:, :10], x)
    np.testing.assert_array_equal(host[:, 10:], 0.0)
    w0 = _fold_x0(np.arange(10.0), 4, 3)
    assert w0.shape == (4, 3)
    np.testing.assert_array_equal(w0.reshape(-1)[:10], np.arange(10.0))


# ---------------------------------------------------------------------------
# eligibility: fail loud / warn once instead of silently not sharding
# ---------------------------------------------------------------------------

def test_shard_features_without_mesh_raises(rng):
    ds = _fe_dataset(rng, n=160)
    with pytest.raises(ValueError, match="nothing consumes the feature"):
        GameEstimator(_fe_config(1, shard_features=True)).fit(ds)


def test_blocked_lane_warns_and_falls_back(rng, caplog):
    ds = _fe_dataset(rng, n=160)
    cfg = _fe_config(1, shard_features=True,
                     normalization=NormalizationType.STANDARDIZATION)
    with caplog.at_level(logging.WARNING,
                         logger="photon_ml_tpu.game.coordinates"):
        res = GameEstimator(cfg, mesh=make_mesh(4, 2)).fit(ds)
    assert any("ADMM lane is blocked" in r.getMessage()
               and "normalization" in r.getMessage()
               for r in caplog.records)
    assert np.isfinite(res.objective_history).all()


def test_width1_feature_axis_warns(rng, caplog):
    ds = _fe_dataset(rng, n=160)
    with caplog.at_level(logging.WARNING,
                         logger="photon_ml_tpu.game.coordinates"):
        GameEstimator(_fe_config(1, shard_features=True),
                      mesh=make_mesh(8, 1)).fit(ds)
    assert any("width 1" in r.getMessage() for r in caplog.records)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def test_admm_config_json_roundtrip():
    admm = ADMMConfig(max_iterations=123, tolerance=2.5e-9, rho=0.5,
                      adapt_rho=False, rho_tau=3.0, rho_mu=5.0,
                      newton_steps=4, polish=False)
    cfg = GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={"fixed": FixedEffectCoordinateConfig(
            "global", GLMOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=30),
                regularization=L2, regularization_weight=0.1, admm=admm))},
        updating_sequence=["fixed"], num_outer_iterations=2)
    rt = GameTrainingConfig.from_dict(json.loads(cfg.to_json()))
    assert rt.coordinates["fixed"].optimization.admm == admm
    # absent stays absent (lane defaults, not an encoded block of defaults)
    plain = GameTrainingConfig.from_dict(json.loads(
        _fe_config(1).to_json()))
    assert plain.coordinates["fixed"].optimization.admm is None


def test_admm_config_validation():
    with pytest.raises(ValueError):
        ADMMConfig(rho=0.0)
    with pytest.raises(ValueError):
        ADMMConfig(rho_tau=1.0)
    with pytest.raises(ValueError):
        ADMMConfig(rho_mu=0.5)
    with pytest.raises(ValueError):
        ADMMConfig(newton_steps=0)
    r = ADMMConfig().resolved()
    assert r.max_iterations == 200 and r.tolerance == 1e-8
    assert isinstance(ADMMConfig(rho=np.float64(2)).rho, float)


def test_stage_derived_reanchors_on_new_source(rng):
    """The Gram eigendecomposition is memoized against the staged grid's
    identity: same source -> cached, re-staged source -> re-derived (one
    counted invalidation)."""
    from photon_ml_tpu.parallel.mesh_residency import MeshResidency
    res = MeshResidency()
    mesh = _mesh(2, 2)
    key = ("derived-test",)
    calls = []

    def build(grid):
        def _b():
            calls.append(1)
            return jnp.sum(grid)
        return _b

    _, _, _, g1 = stage_admm_grid(key, mesh, rng.normal(size=(40, 8)),
                                  residency=res)
    res.stage_derived(key, "eig", mesh, g1, build(g1))
    res.stage_derived(key, "eig", mesh, g1, build(g1))
    assert len(calls) == 1
    inv_before = res.stats.invalidations
    _, _, _, g2 = stage_admm_grid(key, mesh, rng.normal(size=(40, 8)),
                                  residency=res)
    assert g2 is not g1
    res.stage_derived(key, "eig", mesh, g2, build(g2))
    assert len(calls) == 2
    assert res.stats.invalidations > inv_before


# ---------------------------------------------------------------------------
# feature-wide meshes: row-sharded concatenate workaround (regression)
# ---------------------------------------------------------------------------

def test_concat_rows_safe_on_feature_mesh(rng):
    """concat_rows_safe must be exact for P("data")-sharded operands on a
    mesh with a >1 feature axis — the layout where a direct jnp.concatenate
    miscompiles under this build's GSPMD (values interleave across shards).
    """
    from photon_ml_tpu.parallel.mesh import concat_rows_safe, data_sharding
    mesh = _mesh(4, 2)
    a, b = rng.normal(size=(36, 5)), rng.normal(size=(24, 5))
    ad = jax.device_put(jnp.asarray(a), data_sharding(mesh, 2))
    bd = jax.device_put(jnp.asarray(b), data_sharding(mesh, 2))
    out = concat_rows_safe(mesh, [ad, bd], axis=0)
    np.testing.assert_array_equal(np.asarray(out), np.concatenate([a, b]))
    # 60 rows tile the 4-wide data axis -> result lands back row-sharded
    assert out.sharding.spec[0] == DATA_AXIS
    # 1-D leaves (per-entity value/iterations) take the same route
    v1 = jax.device_put(jnp.asarray(a[:, 0]), data_sharding(mesh, 1))
    v2 = jax.device_put(jnp.asarray(b[:, 0]), data_sharding(mesh, 1))
    v = concat_rows_safe(mesh, [v1, v2], axis=0)
    np.testing.assert_array_equal(np.asarray(v),
                                  np.concatenate([a[:, 0], b[:, 0]]))
    # mesh-less callers keep the plain concatenate
    w = concat_rows_safe(None, [jnp.asarray(a), jnp.asarray(b)], axis=0)
    np.testing.assert_array_equal(np.asarray(w), np.concatenate([a, b]))


def test_multibucket_re_training_on_feature_mesh(rng):
    """A GAME fit whose random effect spans multiple size buckets must
    reproduce the single-device objective history on a feature-wide mesh
    (regression: the cross-bucket result concatenate at the end of
    RandomEffectCoordinate.update silently corrupted the coefficient table
    on feature>1 meshes, making the objective diverge)."""
    from photon_ml_tpu.game import RandomEffectCoordinateConfig
    # entity counts per size bucket (36 and 24) tile the 4-wide data axis:
    # the per-bucket results then come back still row-sharded, the exact
    # layout whose concatenate miscompiled
    sizes = np.concatenate([np.full(36, 3), np.full(24, 8)])
    users = np.repeat(np.arange(sizes.size), sizes)
    n, d = users.size, 5
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d)
    u = 0.5 * rng.normal(size=(sizes.size, d))
    z = np.einsum("nd,nd->n", x, w + u[users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ds = build_game_dataset(y, {"global": x},
                            entity_ids={"per_user": users})
    cfg = GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(
                    optimizer=OptimizerConfig(max_iterations=30),
                    regularization=L2, regularization_weight=0.1)),
            "perUser": RandomEffectCoordinateConfig(
                random_effect_type="per_user", feature_shard="global",
                optimization=GLMOptimizationConfig(
                    optimizer=OptimizerConfig(max_iterations=30),
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"], num_outer_iterations=2)
    one = GameEstimator(cfg, mesh=None).fit(ds)
    meshed = GameEstimator(cfg, mesh=make_mesh(4, 2)).fit(ds)
    h1 = np.asarray(one.objective_history)
    hm = np.asarray(meshed.objective_history)
    np.testing.assert_allclose(hm, h1, rtol=1e-5)
    assert (np.diff(hm) <= 1e-6 * np.abs(hm[:-1])).all()
