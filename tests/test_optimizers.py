"""Optimizer correctness: closed-form quadratics, GLM fits vs scipy,
L1 sparsity, box constraints, jit/vmap compatibility.

Mirrors the reference's optimizer suite (photon-lib/src/test/.../optimization/
{OptimizerTest,LBFGSTest,OWLQNTest}.scala against TestObjective closed forms),
plus TPU-specific requirements the reference never had: the whole solve must
run under jit and vmap.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import minimize

from photon_ml_tpu.ops import LOGISTIC, POISSON, SQUARED, GLMObjective
from photon_ml_tpu.optim import (
    ConvergenceReason, OptimizerConfig, OptimizerType, RegularizationContext,
    RegularizationType, lbfgs, solve, tron,
)
from tests.synthetic import make_glm_data


def _quad(center, scales):
    """f(x) = 0.5 sum scales_i (x_i - center_i)^2 — the reference's
    TestObjective style closed form."""
    center = jnp.asarray(center)
    scales = jnp.asarray(scales)

    def vg(x):
        return 0.5 * jnp.sum(scales * (x - center) ** 2), scales * (x - center)

    def hv(x, v):
        return scales * v

    return vg, hv


def test_lbfgs_quadratic_exact():
    vg, _ = _quad([1.0, -2.0, 3.0], [1.0, 4.0, 0.5])
    res = lbfgs(vg, jnp.zeros(3))
    np.testing.assert_allclose(res.x, [1.0, -2.0, 3.0], atol=1e-5)
    assert int(res.reason) in (ConvergenceReason.GRADIENT_CONVERGED,
                               ConvergenceReason.FUNCTION_VALUES_CONVERGED)
    # tracker: loss history is monotone non-increasing over recorded iters
    lh = np.asarray(res.loss_history)[: int(res.iterations) + 1]
    assert np.all(np.diff(lh) <= 1e-12)


def test_tron_quadratic_exact():
    vg, hv = _quad([1.0, -2.0, 3.0], [1.0, 4.0, 0.5])
    res = tron(vg, hv, jnp.zeros(3))
    np.testing.assert_allclose(res.x, [1.0, -2.0, 3.0], atol=1e-6)


@pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON])
@pytest.mark.parametrize("loss,task", [(LOGISTIC, "logistic"), (SQUARED, "linear"),
                                       (POISSON, "poisson")])
def test_glm_fit_matches_scipy(opt, loss, task, rng):
    x, y, w, _ = make_glm_data(rng, n=300, d=8, task=task, weight_range=(0.5, 2.0))
    obj = GLMObjective(loss, jnp.asarray(x), jnp.asarray(y),
                       weights=jnp.asarray(w), l2_weight=0.1)
    res = solve(obj, jnp.zeros(8), OptimizerConfig(optimizer=opt),
                RegularizationContext(RegularizationType.L2), 0.1)

    ref = minimize(lambda c: tuple(np.asarray(v) for v in
                                   obj.value_and_gradient(jnp.asarray(c))),
                   np.zeros(8), jac=True, method="L-BFGS-B",
                   options={"ftol": 1e-14, "gtol": 1e-10})
    # same optimum, loss parity well within the 1e-4 target
    assert abs(float(res.value) - ref.fun) / max(1.0, abs(ref.fun)) < 1e-6
    np.testing.assert_allclose(res.x, ref.x, rtol=1e-3, atol=1e-4)


def test_owlqn_produces_sparse_solution(rng):
    x, y, _, _ = make_glm_data(rng, n=400, d=20, task="logistic")
    # make half the features pure noise
    x[:, 10:19] = rng.normal(size=(400, 9)) * 0.01
    obj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y))
    res = solve(obj, jnp.zeros(20), OptimizerConfig(),
                RegularizationContext(RegularizationType.L1), 5.0)
    assert int(jnp.sum(res.x == 0.0)) >= 5, "L1 at lambda=5 should zero noise features"

    # sanity: the L1 objective value must beat the zero vector
    l1_obj = float(obj.value(res.x) + 5.0 * jnp.sum(jnp.abs(res.x)))
    assert l1_obj < float(obj.value(jnp.zeros(20)))


def test_owlqn_matches_unregularized_when_lambda_zero(rng):
    x, y, _, _ = make_glm_data(rng, n=200, d=6, task="logistic")
    obj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y), l2_weight=0.05)
    a = lbfgs(obj.value_and_gradient, jnp.zeros(6))
    b = lbfgs(obj.value_and_gradient, jnp.zeros(6), l1_weight=0.0)
    np.testing.assert_allclose(a.value, b.value, rtol=1e-8)


def test_elastic_net_split(rng):
    x, y, _, _ = make_glm_data(rng, n=200, d=10, task="logistic")
    obj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y))
    reg = RegularizationContext(RegularizationType.ELASTIC_NET, elastic_net_alpha=0.5)
    res = solve(obj, jnp.zeros(10), OptimizerConfig(), reg, 2.0)
    # elastic net with alpha=.5, lambda=2: l1=1, l2=1 — compare against
    # solving the same composite directly
    res2 = lbfgs(obj.with_l2(1.0).value_and_gradient, jnp.zeros(10), l1_weight=1.0)
    np.testing.assert_allclose(res.value, res2.value, rtol=1e-10)


def test_box_constraints_respected_and_optimal(rng):
    x, y, _, _ = make_glm_data(rng, n=300, d=5, task="linear")
    obj = GLMObjective(SQUARED, jnp.asarray(x), jnp.asarray(y), l2_weight=0.01)
    lower = jnp.asarray([-0.1, -0.1, -0.1, -0.1, -0.1])
    upper = jnp.asarray([0.1, 0.1, 0.1, 0.1, 0.1])
    res = lbfgs(obj.value_and_gradient, jnp.zeros(5), lower=lower, upper=upper)
    assert bool(jnp.all(res.x >= lower - 1e-12)) and bool(jnp.all(res.x <= upper + 1e-12))

    ref = minimize(lambda c: tuple(np.asarray(v) for v in
                                   obj.value_and_gradient(jnp.asarray(c))),
                   np.zeros(5), jac=True, method="L-BFGS-B",
                   bounds=[(-0.1, 0.1)] * 5, options={"ftol": 1e-14})
    assert float(res.value) <= ref.fun * (1 + 1e-5) + 1e-8


def test_solve_under_jit_and_vmap(rng):
    """The TPU contract: whole solves compile and batch.  This is what
    replaces the reference's per-entity executor tasks."""
    d = 4
    xs, ys = [], []
    for _ in range(8):
        x, y, _, _ = make_glm_data(rng, n=50, d=d, task="logistic")
        xs.append(x); ys.append(y)
    xb = jnp.asarray(np.stack(xs))   # [8, 50, d]
    yb = jnp.asarray(np.stack(ys))

    def solve_one(x, y):
        obj = GLMObjective(LOGISTIC, x, y, l2_weight=0.1)
        return lbfgs(obj.value_and_gradient, jnp.zeros(d), max_iterations=50)

    batched = jax.jit(jax.vmap(solve_one))(xb, yb)
    assert batched.x.shape == (8, d)
    # each batched solve must match its standalone solve
    for i in range(8):
        single = solve_one(xb[i], yb[i])
        np.testing.assert_allclose(batched.x[i], single.x, rtol=1e-6, atol=1e-8)

    # TRON under vmap too
    def tron_one(x, y):
        obj = GLMObjective(LOGISTIC, x, y, l2_weight=0.1)
        return tron(obj.value_and_gradient, obj.hessian_vector, jnp.zeros(d))

    tb = jax.jit(jax.vmap(tron_one))(xb, yb)
    np.testing.assert_allclose(tb.x, batched.x, rtol=1e-3, atol=1e-4)


def test_tron_rejects_l1_and_nonsmooth(rng):
    from photon_ml_tpu.ops import SMOOTHED_HINGE
    x, y, _, _ = make_glm_data(rng, n=50, d=3, task="logistic")
    obj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y))
    with pytest.raises(ValueError):
        solve(obj, jnp.zeros(3), OptimizerConfig(optimizer=OptimizerType.TRON),
              RegularizationContext(RegularizationType.L1), 1.0)
    obj_h = GLMObjective(SMOOTHED_HINGE, jnp.asarray(x), jnp.asarray(y))
    with pytest.raises(ValueError):
        solve(obj_h, jnp.zeros(3), OptimizerConfig(optimizer=OptimizerType.TRON))


def test_smoothed_hinge_with_box_constraints(rng):
    """BASELINE config #3: smoothed-hinge SVM with box-constrained coefs."""
    from photon_ml_tpu.ops import SMOOTHED_HINGE
    x, y, _, _ = make_glm_data(rng, n=300, d=6, task="hinge")
    obj = GLMObjective(SMOOTHED_HINGE, jnp.asarray(x), jnp.asarray(y), l2_weight=0.01)
    cfg = OptimizerConfig(box_lower=jnp.full(6, -0.5), box_upper=jnp.full(6, 0.5))
    res = solve(obj, jnp.zeros(6), cfg)
    assert bool(jnp.all(jnp.abs(res.x) <= 0.5 + 1e-12))
    assert float(res.value) < float(obj.value(jnp.zeros(6)))


def test_coefficient_history_tracking(rng):
    """track_coefficients snapshots every iterate (reference: ModelTracker
    per-iteration models); the last snapshot equals the solution and the
    history reproduces the loss table."""
    import jax.numpy as jnp
    from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
    from tests.synthetic import make_glm_data

    x, y, _, _ = make_glm_data(rng, n=300, d=6)
    obj = GLMObjective(TASK_LOSSES["logistic_regression"],
                       jnp.asarray(x), jnp.asarray(y))
    for opt in (OptimizerType.LBFGS, OptimizerType.TRON):
        cfg = OptimizerConfig(optimizer=opt, max_iterations=30,
                              track_coefficients=True)
        res = solve(obj, jnp.zeros(6), cfg,
                    RegularizationContext(RegularizationType.L2), 0.1)
        hist = np.asarray(res.coefficient_history)
        it = int(res.iterations)
        assert hist.shape[1] == 6
        np.testing.assert_allclose(hist[it], np.asarray(res.x), rtol=1e-7)
        # snapshot i re-evaluates to the recorded loss (accepted iterates)
        l2 = 0.1
        for i in (0, it):
            w = hist[i]
            z = x @ w
            nll = np.logaddexp(0, -np.where(y > 0.5, 1, -1) * z).sum() \
                + 0.5 * l2 * w @ w
            np.testing.assert_allclose(nll, np.asarray(res.loss_history)[i],
                                       rtol=1e-5)
        # default: no history
        res2 = solve(obj, jnp.zeros(6), OptimizerConfig(optimizer=opt),
                     RegularizationContext(RegularizationType.L2), 0.1)
        assert res2.coefficient_history is None


def test_lbfgs_fg_count_counts_every_evaluation():
    """fg_count = initial eval + first trial per iteration + every
    line-search backtrack; it is the honest data-pass count for
    throughput accounting (round-3 bench treated backtracks as free)."""
    import numpy as np

    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.normal(size=(100, 10)))
    b = jnp.asarray(rng.normal(size=100))

    def f(x):
        r = A @ x - b
        return 0.5 * jnp.sum(r * r), A.T @ r

    calls = []

    def counted(x):
        calls.append(1)
        return f(x)

    res = lbfgs(counted, jnp.zeros(10), max_iterations=50)
    # traced once -> can't compare against `calls`; instead check the
    # structural invariant: at least 1 + iterations evaluations, and the
    # count is exact on a rerun with an eval-counting pure_callback-free
    # reference: iterations first trials + initial + backtracks
    assert int(res.fg_count) >= int(res.iterations) + 1
    assert int(res.fg_count) <= int(res.iterations) * (1 + 30) + 1
