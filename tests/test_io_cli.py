"""Model save->load->score round-trips + CLI end-to-end.

Mirrors reference: ModelProcessingUtilsTest (save/load/compare GAME models)
and the cli DriverTest e2e pattern (run the driver, assert outputs + metric
thresholds).
"""
import json
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset, build_index_map
from photon_ml_tpu.data.game_data import load_game_dataset, save_game_dataset
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig, RandomEffectCoordinateConfig,
)
from photon_ml_tpu.models.io import load_game_model, save_game_model
from photon_ml_tpu.optim import RegularizationContext, RegularizationType
from tests.test_game import _config, _dataset

L2 = RegularizationContext(RegularizationType.L2)


def test_game_model_roundtrip(tmp_path, rng):
    ds, _ = _dataset(rng, n=400)
    res = GameEstimator(_config(iters=1)).fit(ds)
    d = str(tmp_path / "model")
    save_game_model(res.model, d, config=res.config)
    loaded, cfg = load_game_model(d)
    assert cfg == res.config
    np.testing.assert_allclose(np.asarray(loaded.score_dataset(ds)),
                               np.asarray(res.model.score_dataset(ds)),
                               rtol=1e-12)
    re = loaded.coordinates["perUser"]
    assert re.num_entities == res.model.coordinates["perUser"].num_entities


def test_dataset_npz_roundtrip(tmp_path, rng):
    ds, _ = _dataset(rng, n=100)
    p = str(tmp_path / "ds.npz")
    save_game_dataset(ds, p)
    back = load_game_dataset(p)
    np.testing.assert_allclose(back.response, ds.response)
    np.testing.assert_allclose(back.feature_shards["global"],
                               ds.feature_shards["global"])
    assert (back.entity_vocabs["userId"] == ds.entity_vocabs["userId"]).all()
    assert (back.entity_indices["userId"] == ds.entity_indices["userId"]).all()


@pytest.fixture
def cli_env(tmp_path, rng):
    """Train+val npz files on disk."""
    ds, _ = _dataset(rng, n=800, task="logistic")
    rows = np.arange(800)
    train_p = str(tmp_path / "train.npz")
    val_p = str(tmp_path / "val.npz")
    save_game_dataset(ds.subset(rows[:600]), train_p)
    save_game_dataset(ds.subset(rows[600:]), val_p)
    return train_p, val_p, tmp_path


def _run_cli(module, argv, extra_env=None):
    cmd = [sys.executable, "-m", module] + argv
    # 8 virtual devices so `--mesh auto` exercises the REAL multi-device
    # product path end-to-end (VERDICT r2 item 8: CLI e2e must not silently
    # collapse to one device)
    env = {"PYTHONPATH": "/root/repo", "PATH": "/usr/bin:/bin:/usr/local/bin",
           "JAX_PLATFORMS": "cpu", "HOME": "/root",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           **(extra_env or {})}
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          timeout=420)


def test_cli_train_and_score_legacy_path(cli_env):
    train_p, val_p, tmp = cli_env
    out_dir = str(tmp / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", train_p, "--validation-data", val_p,
                  "--task", "logistic_regression", "--output-dir", out_dir,
                  "--reg-weights", "10,0.1", "--evaluators", "AUC,LOGISTIC_LOSS"])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["num_configs"] == 2
    assert summary["validation"]["AUC"] > 0.6

    score_p = str(tmp / "scores.npz")
    r2 = _run_cli("photon_ml_tpu.cli.score",
                  ["--model-dir", summary["output"], "--data", val_p,
                   "--output", score_p, "--evaluators", "AUC", "--predict"])
    assert r2.returncode == 0, r2.stderr[-2000:]
    res = json.loads(r2.stdout.strip().splitlines()[-1])
    assert abs(res["evaluation"]["AUC"] - summary["validation"]["AUC"]) < 0.05
    z = np.load(score_p)
    assert z["scores"].shape == (200,)
    assert ((z["predictions"] >= 0) & (z["predictions"] <= 1)).all()


def test_cli_game_config_path(cli_env):
    train_p, val_p, tmp = cli_env
    cfg = GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(regularization=L2,
                                                regularization_weight=0.1)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user",
                GLMOptimizationConfig(regularization=L2,
                                      regularization_weight=2.0)),
        },
        updating_sequence=["fixed", "perUser"], num_outer_iterations=2)
    cfg_p = str(tmp / "game.json")
    with open(cfg_p, "w") as f:
        f.write(cfg.to_json())
    out_dir = str(tmp / "out_game")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", train_p, "--validation-data", val_p,
                  "--task", "logistic_regression", "--output-dir", out_dir,
                  "--config", cfg_p, "--evaluators", "AUC,AUC:userId"])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert "AUC:userId" in summary["validation"]
    # model dir exists with both coordinate kinds
    loaded, cfg_back = load_game_model(summary["output"])
    assert set(loaded.coordinates) == {"fixed", "perUser"}
    assert cfg_back == cfg


def test_cli_bad_args(cli_env):
    train_p, _, tmp = cli_env
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", train_p, "--task", "not_a_task",
                  "--output-dir", str(tmp / "x")])
    assert r.returncode != 0
    assert "invalid choice" in r.stderr


def test_cli_sparse_train_and_score(tmp_path, rng):
    """Sparse (CSR) feature shards flow through BOTH CLIs end-to-end on the
    8-device mesh: npz round-trip, mesh training, model save, scoring with
    evaluation (the wide-FE product path, VERDICT r2 item 4)."""
    import scipy.sparse as sp

    n, d = 600, 50
    x = sp.random(n, d, density=0.2, format="csr", random_state=2)
    w = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(np.float32)
    ds = build_game_dataset(y, {"global": x})
    train_p = str(tmp_path / "sp_train.npz")
    save_game_dataset(ds, train_p)

    out_dir = str(tmp_path / "sp_out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", train_p, "--validation-data", train_p,
                  "--output-dir", out_dir, "--reg-weights", "0.1",
                  "--evaluators", "AUC"])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["validation"]["AUC"] > 0.75

    score_p = str(tmp_path / "sp_scores.npz")
    r2 = _run_cli("photon_ml_tpu.cli.score",
                  ["--model-dir", summary["output"], "--data", train_p,
                   "--output", score_p, "--evaluators", "AUC"])
    assert r2.returncode == 0, r2.stderr[-2000:]
    res = json.loads(r2.stdout.strip().splitlines()[-1])
    assert abs(res["evaluation"]["AUC"] - summary["validation"]["AUC"]) < 1e-6


def test_cli_tuning_random_e2e(cli_env):
    """--tuning random drives the search -> refit -> select-best pipeline
    end-to-end (reference: Driver.runHyperparameterTuning,
    cli/game/training/Driver.scala:337-373), with warm start."""
    train_p, val_p, tmp = cli_env
    out_dir = str(tmp / "out_tuning")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", train_p, "--validation-data", val_p,
                  "--output-dir", out_dir, "--reg-weights", "1.0",
                  "--evaluators", "AUC", "--tuning", "random",
                  "--tuning-iterations", "2", "--warm-start"])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    # 1 grid config + 2 tuning iterations, best-by-AUC selected and saved
    assert summary["num_configs"] == 3
    assert summary["validation"]["AUC"] > 0.6
    loaded, cfg_back = load_game_model(summary["output"])
    assert "fixed" in loaded.coordinates


def test_cli_tuning_bayesian_e2e(cli_env):
    """--tuning bayesian: GP search seeded with the grid result."""
    train_p, val_p, tmp = cli_env
    out_dir = str(tmp / "out_bayes")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", train_p, "--validation-data", val_p,
                  "--output-dir", out_dir, "--reg-weights", "1.0",
                  "--evaluators", "AUC", "--tuning", "bayesian",
                  "--tuning-iterations", "2"])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["num_configs"] == 3
    assert summary["validation"]["AUC"] > 0.6


def test_game_model_avro_roundtrip(tmp_path, rng):
    """save_game_model(format='avro') -> load -> score equals the npz path
    (VERDICT r3 missing #2: reference interchange artifacts on disk)."""
    ds, _ = _dataset(rng, n=300)
    res = GameEstimator(_config(iters=1)).fit(ds)
    d_npz, d_avro = str(tmp_path / "npz"), str(tmp_path / "avro")
    imaps = {"global": build_index_map([(f"g{i}", "") for i in range(7)]),
             "per_user": build_index_map([(f"u{i}", "") for i in range(3)])}
    save_game_model(res.model, d_npz, config=res.config, index_maps=imaps)
    save_game_model(res.model, d_avro, config=res.config, index_maps=imaps,
                    format="avro")
    import os
    assert os.path.exists(
        os.path.join(d_avro, "fixed-effect", "fixed", "coefficients.avro"))
    assert os.path.exists(
        os.path.join(d_avro, "random-effect", "perUser", "coefficients.avro"))
    m_npz, cfg_npz = load_game_model(d_npz)
    m_avro, cfg_avro = load_game_model(d_avro)
    assert cfg_avro == cfg_npz
    np.testing.assert_allclose(np.asarray(m_avro.score_dataset(ds)),
                               np.asarray(m_npz.score_dataset(ds)),
                               rtol=1e-6)


def test_factored_and_mf_avro_roundtrip(tmp_path, rng):
    """Factored RE materializes to per-entity original-space Avro models;
    MF round-trips through LatentFactorAvro files."""
    import jax.numpy as jnp
    from photon_ml_tpu.models.game import (FactoredRandomEffectModel,
                                           GameModel,
                                           MatrixFactorizationModel)
    E, k, d = 6, 2, 5
    fre = FactoredRandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type="linear_regression",
        latent_coefficients=jnp.asarray(rng.normal(size=(E, k)),
                                        jnp.float32),
        projection=jnp.asarray(rng.normal(size=(k, d)), jnp.float32),
        entity_ids=np.asarray([f"u{i}" for i in range(E)]),
        global_dim=d)
    mf = MatrixFactorizationModel(
        row_effect_type="userId", col_effect_type="itemId",
        row_factors=jnp.asarray(rng.normal(size=(4, k)), jnp.float32),
        row_ids=np.asarray([f"u{i}" for i in range(4)]),
        col_factors=jnp.asarray(rng.normal(size=(3, k)), jnp.float32),
        col_ids=np.asarray([f"it{i}" for i in range(3)]),
        task_type="linear_regression")
    model = GameModel({"fre": fre, "mf": mf}, "linear_regression")
    d_avro = str(tmp_path / "avro")
    save_game_model(model, d_avro, format="avro")
    loaded, _ = load_game_model(d_avro)
    # factored comes back as its original-space materialization
    np.testing.assert_allclose(
        np.asarray(loaded.coordinates["fre"].coefficients),
        np.asarray(fre.to_random_effect_model().coefficients), atol=1e-5)
    np.testing.assert_allclose(np.asarray(loaded.coordinates["mf"].row_factors),
                               np.asarray(mf.row_factors), rtol=1e-6)
    assert (loaded.coordinates["mf"].col_ids == mf.col_ids).all()


def test_random_projection_re_avro_roundtrip(tmp_path, rng):
    """Avro save of a random-projection RE model writes ORIGINAL-space
    coefficients (P^T c), not projected-space slots keyed as feature j
    (ADVICE r4 high finding)."""
    import jax.numpy as jnp
    from photon_ml_tpu.models.game import GameModel, RandomEffectModel
    E, k, d = 5, 3, 8
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type="linear_regression",
        coefficients=jnp.asarray(rng.normal(size=(E, k)), jnp.float32),
        entity_ids=np.asarray([f"u{i}" for i in range(E)]),
        projection=None, global_dim=d,
        variances=jnp.ones((E, k)),
        projection_matrix=jnp.asarray(rng.normal(size=(k, d)), jnp.float32))
    model = GameModel({"perUser": re}, "linear_regression")
    d_avro = str(tmp_path / "avro")
    save_game_model(model, d_avro, format="avro")
    loaded, _ = load_game_model(d_avro)
    got = loaded.coordinates["perUser"]
    assert got.projection_matrix is None
    np.testing.assert_allclose(np.asarray(got.coefficients),
                               np.asarray(re.global_coefficients()),
                               atol=1e-5)
    ds = build_game_dataset(
        np.zeros(3), {"per_user": rng.normal(size=(3, d))},
        entity_ids={"userId": np.asarray(["u0", "u3", "nope"])})
    np.testing.assert_allclose(np.asarray(loaded.score_dataset(ds)),
                               np.asarray(model.score_dataset(ds)),
                               atol=1e-5)


def test_cli_score_avro_output_and_input(tmp_path, rng):
    """Train from Avro, save the model as Avro, score Avro data back out to
    ScoringResultAvro — the full reference-format loop."""
    from photon_ml_tpu.data.avro_game import write_game_examples
    from photon_ml_tpu.data.avro_io import read_scores_avro
    from tests.test_avro_game import _bag_matrix

    n = 240
    xg, gm = _bag_matrix(rng, n, [(f"g{i}", "") for i in range(6)])
    xu, um = _bag_matrix(rng, n, [(f"u{i}", "") for i in range(3)])
    users = np.asarray([f"u{i % 8}" for i in range(n)])
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    data_p = str(tmp_path / "train.avro")
    write_game_examples(data_p, y, bags={"features": (xg, gm),
                                         "userFeatures": (xu, um)},
                        id_values={"userId": users},
                        uids=[f"row{i}" for i in range(n)])
    shard_map = json.dumps({"global": ["features"],
                            "per_user": ["userFeatures"]})
    cfg = _config(task="logistic_regression", iters=1)
    cfg_p = str(tmp_path / "game.json")
    with open(cfg_p, "w") as f:
        f.write(cfg.to_json())
    out_dir = str(tmp_path / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", data_p, "--feature-shard-map", shard_map,
                  "--id-columns", "userId", "--task", "logistic_regression",
                  "--config", cfg_p, "--output-dir", out_dir,
                  "--model-format", "avro"])
    assert r.returncode == 0, r.stderr[-2000:]

    score_avro = str(tmp_path / "scores.avro")
    r2 = _run_cli("photon_ml_tpu.cli.score",
                  ["--model-dir", f"{out_dir}/best", "--data", data_p,
                   "--feature-shard-map", shard_map,
                   "--output", score_avro, "--format", "avro",
                   "--model-id", "gameModel", "--evaluators", "AUC"])
    assert r2.returncode == 0, r2.stderr[-2000:]
    res = json.loads(r2.stdout.strip().splitlines()[-1])
    assert res["evaluation"]["AUC"] > 0.5

    # npz-output scoring of the same data must agree with the Avro records
    score_npz = str(tmp_path / "scores.npz")
    r3 = _run_cli("photon_ml_tpu.cli.score",
                  ["--model-dir", f"{out_dir}/best", "--data", data_p,
                   "--feature-shard-map", shard_map, "--output", score_npz])
    assert r3.returncode == 0, r3.stderr[-2000:]
    scores, labels, recs = read_scores_avro(score_avro)
    np.testing.assert_allclose(scores, np.load(score_npz)["scores"],
                               rtol=1e-6)
    np.testing.assert_allclose(labels, y)
    assert recs[0]["uid"] == "row0" and recs[0]["modelId"] == "gameModel"


def test_cli_compile_cache_cold_vs_warm(cli_env):
    """The persistent compile cache is ON for the product CLI (VERDICT r3
    weak #2): a second identical invocation skips XLA backend compiles, and
    training-summary.json's compile_s proves it."""
    train_p, val_p, tmp = cli_env
    cache = str(tmp / "jax-cache")
    argv = ["--train-data", train_p, "--task", "logistic_regression",
            "--reg-weights", "1.0"]
    runs = []
    for label in ("cold", "warm"):
        out_dir = str(tmp / f"out-{label}")
        r = _run_cli("photon_ml_tpu.cli.train",
                     argv + ["--output-dir", out_dir],
                     extra_env={"PHOTON_JAX_CACHE": cache})
        assert r.returncode == 0, r.stderr[-2000:]
        runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    cold, warm = runs
    assert cold["compile_cache"] == cache
    assert cold["compile_s"] > 0.0, cold
    # warm run: every program comes from the persistent cache
    assert warm["compile_s"] <= max(0.1 * cold["compile_s"], 0.05), (cold, warm)
