"""Inexact coordinate descent (ISSUE 4): dynamic inner-solve budgets.

The contract: (iteration cap, tolerance) are OPERANDS of the compiled
solver programs — sweeping a budget schedule across outer iterations
compiles nothing new — and a schedule whose final outer iteration runs at
the full configured tolerance lands the scheduled fit on the strict
full-solve optimum (convex configs), including across a checkpoint/resume
boundary mid-schedule.
"""
import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from test_pipeline import _compile_counting, _glmix

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FactoredRandomEffectCoordinateConfig, FixedEffectCoordinateConfig,
    GameEstimator, GameTrainingConfig, GLMOptimizationConfig,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
from photon_ml_tpu.optim import (
    ConvergenceReason, OptimizerConfig, OptimizerType, RegularizationContext,
    RegularizationType, SolveBudget, SolverSchedule, solve, solve_streamed,
)

LOGISTIC = TASK_LOSSES["logistic_regression"]
L2 = RegularizationContext(RegularizationType.L2)
L1 = RegularizationContext(RegularizationType.L1)


def _logistic_problem(rng, n=300, d=8):
    x = rng.normal(size=(n, d))
    z = x @ rng.normal(size=d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    return jnp.asarray(x), jnp.asarray(y)


# -- schedule semantics -------------------------------------------------------

def test_schedule_plan_tightens_and_finishes_full():
    s = SolverSchedule(initial_iterations=4, iteration_growth=2.0,
                      initial_tolerance_factor=1e3, tolerance_decay=0.1)
    plans = [s.plan(t, 5, 100, 1e-7) for t in range(5)]
    caps = [c for c, _ in plans]
    tols = [t for _, t in plans]
    assert caps == [4, 8, 16, 32, 100]          # growth, final = full
    assert tols[-1] == 1e-7                     # final at full tolerance
    assert all(a >= b for a, b in zip(tols, tols[1:]))  # monotone tightening
    assert all(t >= 1e-7 for t in tols)         # floored at configured tol
    # caps clip to the configured ceiling
    assert SolverSchedule(initial_iterations=500).plan(0, 3, 100, 1e-7)[0] == 100


def test_schedule_validation():
    with pytest.raises(ValueError, match="initial_iterations"):
        SolverSchedule(initial_iterations=0)
    with pytest.raises(ValueError, match="iteration_growth"):
        SolverSchedule(iteration_growth=0.5)
    with pytest.raises(ValueError, match="tolerance_decay"):
        SolverSchedule(tolerance_decay=0.0)


def test_schedule_json_round_trip():
    cfg = GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={"fixed": FixedEffectCoordinateConfig(
            "global", solver_schedule=SolverSchedule(initial_iterations=2))},
        updating_sequence=["fixed"],
        solver_schedule=SolverSchedule(tolerance_decay=0.5))
    back = GameTrainingConfig.from_json(cfg.to_json())
    assert back == cfg
    assert back.solver_schedule == SolverSchedule(tolerance_decay=0.5)
    assert back.coordinates["fixed"].solver_schedule == \
        SolverSchedule(initial_iterations=2)


# -- budget semantics in the solvers -----------------------------------------

def test_full_budget_matches_static_solve_bitwise(rng):
    """budget=(max_iterations, configured tolerance) is the identical
    arithmetic to the static no-budget program — histories match bitwise."""
    x, y = _logistic_problem(rng)
    obj = GLMObjective(LOGISTIC, x, y)
    for opt_cfg, reg in (
            (OptimizerConfig(max_iterations=60, tolerance=1e-9), L2),
            (OptimizerConfig(max_iterations=60, tolerance=1e-9), L1),
            (OptimizerConfig(optimizer=OptimizerType.TRON,
                             max_iterations=15, tolerance=1e-9), L2)):
        r = opt_cfg.resolved()
        static = solve(obj, jnp.zeros(x.shape[1]), opt_cfg, reg, 1.0)
        budgeted = solve(obj, jnp.zeros(x.shape[1]), opt_cfg, reg, 1.0,
                         budget=SolveBudget.make(r.max_iterations,
                                                 r.tolerance))
        assert int(static.iterations) == int(budgeted.iterations)
        np.testing.assert_array_equal(np.asarray(static.loss_history),
                                      np.asarray(budgeted.loss_history))
        np.testing.assert_array_equal(np.asarray(static.x),
                                      np.asarray(budgeted.x))


def test_budget_caps_iterations(rng):
    x, y = _logistic_problem(rng)
    obj = GLMObjective(LOGISTIC, x, y)
    cfg = OptimizerConfig(max_iterations=100, tolerance=1e-12)
    res = solve(obj, jnp.zeros(x.shape[1]), cfg, L2, 1.0,
                budget=SolveBudget.make(3, 1e-12))
    assert int(res.iterations) == 3
    assert int(res.reason) == ConvergenceReason.MAX_ITERATIONS
    # loose tolerance stops early with a convergence reason
    loose = solve(obj, jnp.zeros(x.shape[1]), cfg, L2, 1.0,
                  budget=SolveBudget.make(100, 1e-2))
    assert int(loose.iterations) < int(
        solve(obj, jnp.zeros(x.shape[1]), cfg, L2, 1.0).iterations)
    # history buffers stay sized by the static ceiling whatever the cap
    assert res.loss_history.shape == loose.loss_history.shape == (101,)


def test_streamed_budget_matches_resident(rng):
    """solve_streamed honors the budget and stays on the resident solver's
    trajectory for the shared iterations (f64, single-chunk parity)."""
    from photon_ml_tpu.data.streaming import ChunkPlan
    from photon_ml_tpu.ops.chunked import ChunkedGLMObjective
    x, y = _logistic_problem(rng, n=256, d=6)
    xn, yn = np.asarray(x), np.asarray(y)
    plan = ChunkPlan.build(xn.shape[0], chunk_rows=64)
    cobj = ChunkedGLMObjective(LOGISTIC, xn, yn, plan)
    cfg = OptimizerConfig(max_iterations=50, tolerance=1e-12)
    budget = SolveBudget.make(4, 1e-12)
    ss = solve_streamed(cobj, jnp.zeros(6), cfg, L2, 1.0, budget=budget)
    rr = solve(GLMObjective(LOGISTIC, x, y), jnp.zeros(6), cfg, L2, 1.0,
               budget=budget)
    assert int(ss.iterations) == int(rr.iterations) == 4
    np.testing.assert_allclose(np.asarray(ss.x), np.asarray(rr.x),
                               rtol=1e-10)


# -- compile-count regression (ISSUE 4 satellite) ----------------------------

def test_budget_sweep_zero_recompiles_resident(rng):
    """Sweeping (cap, tolerance) across outer iterations must hit ONE
    compiled program per solver: LBFGS, OWLQN, TRON, and the vmapped
    batched RE solver."""
    from photon_ml_tpu.parallel.fixed_effect import _cached_solver
    from photon_ml_tpu.parallel.random_effect import EntityBlocks, \
        fit_random_effects
    x, y = _logistic_problem(rng, n=200, d=6)
    obj = GLMObjective(LOGISTIC, x, y)
    lam = jnp.asarray(1.0)
    solvers = [
        (_cached_solver(OptimizerConfig(max_iterations=50), L2), obj),
        (_cached_solver(OptimizerConfig(max_iterations=50), L1), obj),
        (_cached_solver(OptimizerConfig(optimizer=OptimizerType.TRON,
                                        max_iterations=15), L2), obj),
    ]
    # warm every program with ONE budget (compiles happen here)
    for run, o in solvers:
        float(run(o, jnp.zeros(6), lam, SolveBudget.make(5, 1e-3)).value)
    E, S, d = 12, 16, 4
    blocks = EntityBlocks(x=jnp.asarray(rng.normal(size=(E, S, d))),
                          labels=jnp.asarray(
                              (rng.uniform(size=(E, S)) < 0.5).astype(float)),
                          mask=jnp.ones((E, S)))
    re_cfg = OptimizerConfig(max_iterations=40)
    float(fit_random_effects(blocks, LOGISTIC, config=re_cfg, reg=L2,
                             reg_weight=1.0,
                             budget=SolveBudget.make(5, 1e-3)).value[0])

    with _compile_counting() as counter:
        for cap, tol in ((2, 1e-1), (7, 1e-5), (50, 1e-9), (13, 1e-7)):
            b = SolveBudget.make(cap, tol)
            for run, o in solvers:
                float(run(o, jnp.zeros(6), lam, b).value)
            float(fit_random_effects(blocks, LOGISTIC, config=re_cfg, reg=L2,
                                     reg_weight=1.0, budget=b).value[0])
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles across a budget sweep — the "
        "cap or tolerance leaked into a trace constant")


def test_budget_sweep_zero_recompiles_streamed(rng):
    """The host-stepped streamed solvers must also compile nothing new
    across a budget sweep (their jitted helpers are keyed on shapes)."""
    from photon_ml_tpu.data.streaming import ChunkPlan
    from photon_ml_tpu.ops.chunked import ChunkedGLMObjective
    x, y = _logistic_problem(rng, n=256, d=6)
    xn, yn = np.asarray(x), np.asarray(y)
    plan = ChunkPlan.build(xn.shape[0], chunk_rows=64)
    cobj = ChunkedGLMObjective(LOGISTIC, xn, yn, plan)
    lcfg = OptimizerConfig(max_iterations=30)
    tcfg = OptimizerConfig(optimizer=OptimizerType.TRON, max_iterations=10)
    # warmup traces every [d]-keyed helper + chunk kernel
    solve_streamed(cobj, jnp.zeros(6), lcfg, L2, 1.0,
                   budget=SolveBudget.make(5, 1e-3))
    solve_streamed(cobj, jnp.zeros(6), tcfg, L2, 1.0,
                   budget=SolveBudget.make(3, 1e-3))
    with _compile_counting() as counter:
        for cap, tol in ((2, 1e-1), (9, 1e-6), (30, 1e-9)):
            solve_streamed(cobj, jnp.zeros(6), lcfg, L2, 1.0,
                           budget=SolveBudget.make(cap, tol))
            solve_streamed(cobj, jnp.zeros(6), tcfg, L2, 1.0,
                           budget=SolveBudget.make(min(cap, 10), tol))
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles across a streamed budget sweep")


def test_scheduled_game_fit_zero_recompiles_across_outer_iterations(rng):
    """End-to-end: a scheduled GAME fit changes budgets every outer
    iteration; after a 1-outer warmup fit (which runs the full budget),
    a longer scheduled fit must trace nothing new."""
    train, val = _glmix(rng)
    sched = SolverSchedule(initial_iterations=3)

    def config(iters):
        return GameTrainingConfig(
            task_type="logistic_regression",
            coordinates={
                "fixed": FixedEffectCoordinateConfig(
                    "global", GLMOptimizationConfig(
                        regularization=L2, regularization_weight=0.1)),
                "perUser": RandomEffectCoordinateConfig(
                    "userId", "per_user", GLMOptimizationConfig(
                        regularization=L2, regularization_weight=1.0)),
            },
            updating_sequence=["fixed", "perUser"],
            num_outer_iterations=iters, solver_schedule=sched)

    GameEstimator(config(1)).fit(train, val)   # warmup (compiles uncounted)
    with _compile_counting() as counter:
        GameEstimator(config(4)).fit(train, val)
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles in a scheduled fit after "
        "warmup — a budget value leaked into a static cache key")


# -- strict-vs-scheduled parity (ISSUE 4 satellite) --------------------------

def _convex_config(iters, sched=None):
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=0.1)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=iters, solver_schedule=sched)


def test_strict_vs_scheduled_final_parity_f64(rng):
    """Convex config in float64: the scheduled fit's FINAL objective (full
    tolerance on the last outer iteration) matches the strict full-solve
    fit within the 1e-4 gate — and far tighter in practice — while using
    fewer inner iterations."""
    train, val = _glmix(rng)
    strict = GameEstimator(_convex_config(5)).fit(train, val)
    sched = GameEstimator(_convex_config(
        5, SolverSchedule(initial_iterations=3))).fit(train, val)
    a, b = strict.objective_history[-1], sched.objective_history[-1]
    # the existing 1e-4 bench parity gate; measured ~5e-6 here — the
    # residual is outer-loop fixed-point convergence, not solver error
    assert abs(a - b) / abs(a) <= 1e-4
    assert sched.descent.total_iterations() < strict.descent.total_iterations()
    # the budget trajectory is recorded: capped early, full (None cap =
    # clipped to max_iterations is still an int) on the final iteration
    diag = sched.descent.solver_diagnostics()
    caps = diag["fixed"]["iteration_caps"]
    assert caps[0] == 3 and caps[-1] == 100
    assert diag["fixed"]["reasons"]  # ConvergenceReason counts surfaced


def test_scheduled_resume_reproduces_trajectory(rng, tmp_path):
    """A scheduled fit interrupted mid-schedule (after outer iteration 0's
    checkpoint) and resumed reproduces the uninterrupted trajectory —
    budgets depend only on (outer iteration, total), which resume
    preserves."""
    from photon_ml_tpu.game.coordinate_descent import (
        read_checkpoint, run_coordinate_descent)

    train, val = _glmix(rng)
    cfg = _convex_config(3, SolverSchedule(initial_iterations=3))
    est = GameEstimator(cfg)
    coords = est._build_coordinates(train)
    schedules = {n: cfg.solver_schedule for n in cfg.updating_sequence}

    straight = run_coordinate_descent(
        coords, cfg.updating_sequence, 3, train, cfg.task_type,
        solver_schedules=schedules)

    class _Interrupt(Exception):
        pass

    class _Bomb:
        """Delegating wrapper that raises at a chosen outer iteration."""

        def __init__(self, inner, at):
            self._inner, self._at = inner, at

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def update(self, model, offsets, **kw):
            if kw.get("outer_iteration") == self._at:
                raise _Interrupt()
            return self._inner.update(model, offsets, **kw)

    ckpt = str(tmp_path / "ckpt")
    bombed = {n: _Bomb(c, 1) for n, c in coords.items()}
    with pytest.raises(_Interrupt):
        run_coordinate_descent(
            bombed, cfg.updating_sequence, 3, train, cfg.task_type,
            checkpoint_dir=ckpt, solver_schedules=schedules)
    state = read_checkpoint(ckpt)
    assert state is not None and state.completed_iterations == 1
    resumed = run_coordinate_descent(
        coords, cfg.updating_sequence, 3, train, cfg.task_type,
        checkpoint_dir=ckpt, resume=state, solver_schedules=schedules)
    assert len(resumed.objective_history) == len(straight.objective_history)
    np.testing.assert_allclose(resumed.objective_history,
                               straight.objective_history, rtol=0, atol=1e-9)


# -- warm latent init --------------------------------------------------------

def _mf_dataset(rng, n=1500, d_user=6, num_users=40):
    xg = rng.normal(size=(n, 4)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    # user effects confined to a 2-dim subspace: the principal-subspace
    # warm start has something real to find
    basis = rng.normal(size=(2, d_user))
    w_u = rng.normal(size=(num_users, 2)) @ basis
    z = xg @ rng.normal(size=4) + np.einsum("nd,nd->n", xu, w_u[users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ids = np.asarray([f"u{u:03d}" for u in users])
    return build_game_dataset(y, {"global": xg, "per_user": xu},
                              entity_ids={"userId": ids})


def _mf_config(iters, latent_dim=2, with_re=True):
    opt = GLMOptimizationConfig(regularization=L2, regularization_weight=1.0)
    coords = {"fixed": FixedEffectCoordinateConfig(
        "global", GLMOptimizationConfig(regularization=L2,
                                        regularization_weight=0.1))}
    seq = ["fixed"]
    if with_re:
        coords["perUser"] = RandomEffectCoordinateConfig(
            "userId", "per_user", opt)
        seq.append("perUser")
    coords["perUserMF"] = FactoredRandomEffectCoordinateConfig(
        "userId", "per_user", latent_dim=latent_dim, optimization=opt,
        latent_optimization=opt)
    seq.append("perUserMF")
    return GameTrainingConfig(task_type="logistic_regression",
                              coordinates=coords, updating_sequence=seq,
                              num_outer_iterations=iters)


def test_warm_latent_init_uses_sibling_subspace(rng):
    ds = _mf_dataset(rng)
    cfg = _mf_config(1)
    coords = GameEstimator(cfg)._build_coordinates(ds)
    re_coord, mf = coords["perUser"], coords["perUserMF"]
    re_model, _ = re_coord.update(re_coord.initial_model(),
                                  jnp.zeros(ds.num_rows))
    cold = mf.initial_model()
    warm = mf.warm_start_latent(cold, {"perUser": re_model})
    assert warm is not None
    # latent factors stay zero: the initial score (and the descent state)
    # is unperturbed
    np.testing.assert_array_equal(np.asarray(warm.latent_coefficients), 0.0)
    # the warm projection's row space captures the sibling solution better
    # than the Gaussian start: smaller out-of-subspace residual
    w = np.asarray(re_model.global_coefficients())

    def resid(p):
        p = np.asarray(p, np.float64)
        proj = p.T @ np.linalg.solve(p @ p.T, p)
        return float(np.linalg.norm(w - w @ proj))

    # the warm projection IS the optimal rank-k subspace of the sibling
    # solution (row permutation cannot change singular subspaces), and
    # strictly better than the Gaussian cold start
    s = np.linalg.svd(w, compute_uv=False)
    optimal = float(np.sqrt((s[2:] ** 2).sum()))
    np.testing.assert_allclose(resid(warm.projection), optimal, rtol=1e-3)
    assert resid(warm.projection) < resid(cold.projection)
    # no compatible sibling -> None (cold start preserved)
    assert mf.warm_start_latent(cold, {"fixed": object()}) is None


def test_warm_latent_init_applies_only_to_cold_first_visit(rng, tmp_path):
    """E2E: the descent warm-inits a cold factored coordinate at its first
    visit; a PROVIDED initial model is never overridden (resume safety)."""
    ds = _mf_dataset(rng)
    cfg = _mf_config(2)
    est = GameEstimator(cfg)
    fit = est.fit(ds)
    assert np.isfinite(fit.objective_history).all()
    # provided initial models (the resume path) keep their projection
    coords = GameEstimator(cfg)._build_coordinates(ds)
    provided = coords["perUserMF"].initial_model()
    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
    res = run_coordinate_descent(
        coords, cfg.updating_sequence, 1, ds, cfg.task_type,
        initial_models={"perUserMF": provided})
    assert np.isfinite(res.objective_history).all()