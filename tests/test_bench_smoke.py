"""bench.py --smoke end-to-end in the tier-1 suite (ISSUE 2 satellite):
bench-harness regressions (broken entry plumbing, pipeline parity drift)
surface in the normal test run instead of only at bench time.
"""
import importlib.util
import json
import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_smoke_under_test", os.path.join(_REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_smoke_end_to_end(tmp_path):
    bench = _load_bench()
    out = tmp_path / "BENCH_smoke.json"
    result = bench.smoke_bench(str(out))

    # the kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    on_disk = json.loads(out.read_text())
    assert on_disk == json.loads(json.dumps(result))

    glm = result["detail"]["glm"]
    assert glm["final_value_finite"] is True
    assert glm["n"] > 0 and glm["d"] > 0 and glm["wall_s"] > 0

    game = result["detail"]["game_pipeline"]
    # the strict-vs-pipelined smoke pair is a REAL parity gate: identical
    # objective histories (1e-9) and bit-identical final model directories
    assert game["parity_ok"] is True
    assert game["objective_history_max_abs_gap"] <= 1e-9
    assert game["final_model_bit_identical"] is True
    for mode in ("strict", "pipelined"):
        stats = game[mode]
        assert stats["fit_s"] > 0
        assert 0.0 <= stats["host_blocked_frac"] <= 1.0


def test_stoch_smoke(tmp_path):
    """bench.py --stoch --smoke end-to-end in tier-1 (ISSUE 15 satellite):
    the stochastic solver lane's hard gates — examples_per_staged_byte >=
    1.5x the host-stepped LBFGS mirror on an out-of-core shape, f64
    fixed-point parity <= 1e-6 after the polish, zero fresh traces across
    warm epochs, and mesh objective-history parity — run on every tier-1
    pass, so the lane cannot silently regress into re-staging or
    divergence."""
    bench = _load_bench()
    out = tmp_path / "BENCH_stoch.json"
    result = bench.stoch_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["all_gates_ok"] is True
    assert detail["ratio_ok"] and result["value"] >= 1.5
    assert detail["parity_ok"] and detail["traces_ok"]
    assert detail["data_exceeds_budget"] and detail["under_budget"]
    oc = next(e for e in detail["entries"]
              if e["name"] == "stoch_out_of_core")
    assert oc["fixed_point_rel_gap"] <= 1e-6
    # the pinned chunks really did multiple local epochs per staging
    sp = oc["stochastic_polish"]
    assert sp["local_epochs"] > sp["chunks_staged"]
    if detail["mesh_parity_ok"] is not None:
        assert detail["mesh_parity_ok"] is True


def test_sweep_smoke(tmp_path):
    """bench.py --sweep --smoke end-to-end in tier-1 (ISSUE 17 satellite):
    the vectorized-sweep gates — zero fresh XLA traces across a 16-point
    sweep after warmup (lambda is a traced operand of the compiled
    solvers), per-candidate objective parity <= 1e-6 vs isolated f64
    fits, sublinear sweep wall-clock, and zero fresh traces along the
    warm-start path after the first candidate — run on every tier-1 pass,
    so the sweep lane cannot silently regress into per-lambda retracing
    or diverge from the isolated-fit arithmetic."""
    bench = _load_bench()
    out = tmp_path / "BENCH_sweep.json"
    result = bench.sweep_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_gates_ok"] is True
    assert detail["traces_ok"] and detail["parity_ok"]
    assert detail["sublinear_ok"] and detail["path_traces_ok"]
    vm = next(e for e in detail["entries"] if e["name"] == "sweep_vmap")
    assert vm["candidates"] == 16
    assert vm["fresh_traces_after_warmup"] == 0
    assert vm["objective_parity_rel"] <= 1e-6
    assert vm["wall_ratio_vs_one_fit"] <= vm["candidates"] / 2.0
    pa = next(e for e in detail["entries"] if e["name"] == "sweep_path")
    assert pa["fresh_traces_after_first_candidate"] == 0
    assert pa["warm_start_quality_ok"] is True
    # the sweep counters rode into the embedded telemetry snapshot
    counters = detail["telemetry"]["metrics"]["counters"]
    assert counters["sweep.candidates"] >= 2 * vm["candidates"]
    assert counters["sweep.dispatches"] > 0


def test_admm_smoke(tmp_path):
    """bench.py --admm --smoke end-to-end in tier-1 (ISSUE 18 satellite):
    the feature-axis consensus-ADMM gates — f64 parity <= 1e-6 of the
    pure consensus solve vs monolithic LBFGS across 1x1/1x2/2x2/4x2
    meshes, near-linear per-device aggregator memory reduction as the
    feature axis widens (with the monolithic layout busting the
    per-device budget and the widest mesh training inside it), zero
    fresh XLA traces across warm solves and rho sweeps, and exactly one
    feature-axis vector all-reduce per compiled iteration — run on every
    tier-1 pass, so the lane cannot silently regress into retracing,
    extra collectives or divergence."""
    bench = _load_bench()
    out = tmp_path / "BENCH_admm.json"
    result = bench.admm_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_gates_ok"] is True
    assert detail["parity_ok"] and detail["memory_ok"]
    assert detail["traces_ok"] and detail["collectives_ok"]
    par = next(e for e in detail["entries"] if e["name"] == "admm_parity")
    assert par["worst_rel_gap"] <= 1e-6
    assert {c["mesh"] for c in par["cells"]} == {"1x1", "1x2", "2x2", "4x2"}
    mem = next(e for e in detail["entries"] if e["name"] == "admm_memory")
    assert mem["monolithic_busts_budget"] and mem["wide_fits_budget"]
    assert mem["wide_trains"] and result["value"] >= 2.0
    tr = next(e for e in detail["entries"]
              if e["name"] == "admm_warm_traces")
    assert tr["fresh_traces"] == 0
    col = next(e for e in detail["entries"]
               if e["name"] == "admm_collectives")
    assert col["feature_vector_allreduces"] == 1
    assert col["data_block_allreduces"] == 1


def test_stream_smoke(tmp_path):
    """bench.py --stream --smoke end-to-end in tier-1 (ISSUE 3 satellite):
    the out-of-core harness — ChunkedGLMObjective streaming, HBM-budgeted
    residency rotation, parity gating, transfer-size accounting — cannot
    rot without failing the normal test run.  Timing numbers are smoke
    signals only; the >= 0.7x throughput bar is enforced by the full
    (accelerator) bench, not here."""
    bench = _load_bench()
    out = tmp_path / "BENCH_stream.json"
    result = bench.stream_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_parity_ok"] is True
    (entry,) = detail["entries"]
    # the out-of-core claim, gated: the streamed fit trained a config whose
    # coordinate data exceeds the budget while tracked peak stayed under it
    assert entry["data_exceeds_budget"] is True
    assert entry["streamed"]["under_budget"] is True
    assert entry["streamed"]["peak_tracked_bytes"] <= entry["hbm_budget_bytes"]
    assert entry["coordinate_data_bytes"] > entry["hbm_budget_bytes"]
    assert entry["streamed"]["streamed_coordinates"] == ["fixed"]
    # parity: identical history length, relative gap within the gate
    assert entry["parity_ok"] is True
    assert entry["objective_history_max_rel_gap"] <= entry["parity_gate"]
    for mode in ("resident", "streamed"):
        assert entry[mode]["fit_s"] > 0


def test_inexact_smoke(tmp_path):
    """bench.py --inexact --smoke end-to-end in tier-1 (ISSUE 4 satellite):
    the strict-vs-scheduled harness — budget plumbing, warm latent init,
    per-solve diagnostics, parity gating — cannot rot without failing the
    normal test run.  Timing is a smoke signal; the >= 2x speedup bar is
    enforced by the full bench leg, not here."""
    bench = _load_bench()
    out = tmp_path / "BENCH_inexact.json"
    result = bench.inexact_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    # the convex entry is the hard parity gate (unique optimum: the final
    # full-tolerance visit must land strict and scheduled together)
    assert detail["all_parity_ok"] is True
    convex = next(e for e in detail["entries"] if "convex" in e["name"])
    assert convex["parity_ok"] is True
    assert convex["final_rel_gap_vs_strict"] <= convex["parity_gate"]
    # every entry actually ran INEXACTLY: fewer inner iterations than the
    # strict full-solve leg, capped early visits, full final visit
    assert detail["all_iterations_saved"] is True
    for e in detail["entries"]:
        assert e["iterations_saved"] > 0
        for coord, caps in e["scheduled"]["iteration_caps"].items():
            assert caps[0] is not None and caps[0] <= 4
        assert all(c is None for caps in
                   e["strict"]["iteration_caps"].values() for c in caps)
        assert e["strict"]["fit_s"] > 0 and e["scheduled"]["fit_s"] > 0
    mf = next(e for e in detail["entries"] if "mf" in e["name"])
    assert "perUserMF" in mf["coordinates"]


def test_faults_smoke(tmp_path, monkeypatch):
    """bench.py --faults --smoke end-to-end in tier-1 (ISSUE 5 satellite):
    the chaos harness — injected staging faults absorbed by retry/backoff,
    SIGKILL mid-checkpoint-fsync recovered by the manifest-verified resume,
    a poisoned coordinate quarantined and re-run — cannot rot without
    failing the normal test run.  Every leg is parity-gated against its
    fault-free trajectory at the 1e-4 gate."""
    monkeypatch.setenv("PHOTON_JAX_CACHE", str(tmp_path / "jaxcache"))
    bench = _load_bench()
    out = tmp_path / "BENCH_faults.json"
    result = bench.faults_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_parity_ok"] is True
    assert result["value"] <= 1e-4

    staging = next(e for e in detail["entries"] if "staging" in e["name"])
    assert staging["retries"] >= 4 and staging["gave_up"] == 0
    assert staging["injected"]["total_fired"] >= 4
    assert staging["objective_history_max_abs_gap"] == 0.0

    kill = next(e for e in detail["entries"] if "kill" in e["name"])
    assert kill["killed_returncode"] not in (0, 1)  # actually SIGKILLed
    assert kill["stale_tmp_left_by_kill"] is True
    assert kill["pruned_on_resume"] >= 1
    assert kill["objective_history_max_rel_gap"] <= kill["parity_gate"]

    poisoned = next(e for e in detail["entries"] if "poison" in e["name"])
    actions = [ev["action"] for ev in poisoned["containment_events"]]
    assert "rolled_back" in actions
    assert poisoned["history_finite"] is True
    assert poisoned["final_rel_gap_vs_fault_free"] <= \
        poisoned["parity_gate"]


def test_mesh_smoke(tmp_path):
    """bench.py --mesh --smoke end-to-end in tier-1 (ISSUE 6 satellite):
    the multi-chip harness — mesh-resident staging, per-device budgets,
    mesh-streamed out-of-core, transfer + compile gates — cannot rot
    without failing the normal test run.  This is ALSO the tier-1
    multichip coverage that replaces the ad-hoc dryrun_multichip entry
    (which now drives this same path).  Wall-clock is a smoke signal only:
    virtual CPU devices share cores, so the honest gates are parity,
    transfer behavior, and compile stability."""
    bench = _load_bench()
    out = tmp_path / "BENCH_mesh.json"
    result = bench.mesh_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["devices"] >= 8
    # f64 parity, hard-gated on EVERY leg (FE, RE, factored, streamed)
    assert detail["all_parity_ok"] is True
    assert result["value"] <= 1e-4
    names = {e["name"] for e in detail["entries"]}
    assert {"mesh_fe", "mesh_re", "mesh_factored", "mesh_streamed"} <= names
    # warm iterations move only coefficients+offsets — never the dataset
    # (the factored leg's latent blocks legitimately re-project per visit,
    # so only its plain coordinates enter the warm gate)
    assert detail["all_warm_transfer_ok"] is True
    for e in detail["entries"]:
        if e["name"] in ("mesh_fe", "mesh_re"):
            assert e["warm_run_staged"]["cold_bytes"] == 0
        if "warm_run_bit_identical_history" in e:
            assert e["warm_run_bit_identical_history"] is True
    re_leg = next(e for e in detail["entries"] if e["name"] == "mesh_re")
    assert re_leg["warm_run_staged"]["warm_bytes"] > 0
    # zero fresh traces across warm outer iterations
    assert detail["all_zero_fresh_traces"] is True
    # mesh x streaming: per-device data > per-device budget, peak under it
    stream = next(e for e in detail["entries"] if e["name"] == "mesh_streamed")
    assert stream["data_exceeds_budget"] is True
    assert stream["streamed_engaged_ok"] is True
    assert stream["under_budget_ok"] is True
    assert stream["per_device_accounting"]["data_devices"] >= 8


def test_trace_smoke(tmp_path):
    """bench.py --trace --smoke end-to-end in tier-1 (ISSUE 8 satellite):
    the telemetry harness — disarmed zero-overhead contract, zero fresh
    XLA traces on a warm fit armed or disarmed, cli.train --trace-out
    emitting valid Chrome-trace JSON with a correctly nested span tree and
    fault/quarantine events attached to the right spans — cannot rot
    without failing the normal test run."""
    bench = _load_bench()
    out = tmp_path / "BENCH_trace.json"
    result = bench.trace_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    # every bench mode embeds the telemetry snapshot (ISSUE 8 satellite)
    assert "metrics" in detail["telemetry"]

    overhead = next(e for e in detail["entries"]
                    if e["name"] == "disarmed_overhead")
    # disarmed AND armed warm fits: zero fresh XLA traces
    assert overhead["fresh_traces_disarmed_warm"] == 0
    assert overhead["fresh_traces_armed_warm"] == 0
    # the 1%-of-wall-clock gate on the disarmed instrumentation
    assert overhead["overhead_frac_estimate"] <= overhead["overhead_gate"]
    assert overhead["span_calls_per_fit"] > 0

    cli = next(e for e in detail["entries"] if e["name"] == "cli_trace")
    assert cli["returncode"] == 0
    # the emitted trace validates against the Chrome trace format's
    # required keys (name/ph/ts/pid/tid, dur on complete events)
    assert cli["trace_valid"] is True and cli["trace_problems"] == []
    # span tree: outer iterations -> coordinate visits -> solves
    assert cli["nesting_ok"] is True
    assert cli["solves_nest_in_visits"] is True
    # the injected solve.poison landed on the perUser visit's spans and
    # its quarantine containment recovered
    assert cli["fault_attributed_coordinates"] == ["perUser"]
    assert cli["quarantine_recovered"] is True
    assert cli["run_log_records"] > 0


def test_online_smoke(tmp_path):
    """bench.py --online --smoke end-to-end in tier-1 (ISSUE 9 satellite):
    the online-learning harness — feedback intake, anchored micro-batch
    solves, delta swaps into the live scorer, offline-refit parity, the
    steady-state compile gate, and delta-aware rollback — cannot rot
    without failing the normal test run.  The scoring-p99-under-update
    gate is a smoke SIGNAL here (shared-core CI); the full bench run
    enforces it hard."""
    bench = _load_bench()
    out = tmp_path / "BENCH_online.json"
    result = bench.online_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    # online-updated rows match the offline refit of the same entities
    parity = next(e for e in detail["entries"]
                  if e["name"] == "online_parity")
    assert parity["parity_ok"] is True
    assert parity["max_rel_gap_vs_offline_refit"] <= parity["parity_gate"]
    assert max(parity["scipy_oracle_rel_gaps"]) <= 1e-4
    assert parity["deltas"] >= 1
    # warm serve loop absorbing deltas: zero fresh XLA traces
    traces = next(e for e in detail["entries"]
                  if e["name"] == "online_steady_state_traces")
    assert traces["fresh_traces_steady_state"] == 0
    assert traces["deltas_absorbed"] >= traces["steady_rounds"]
    # delta-aware rollback round-trips bit-exact + durable persistence
    rollback = next(e for e in detail["entries"]
                    if e["name"] == "online_rollback")
    assert rollback["rollback_bit_exact"] is True
    assert rollback["delta_durable_roundtrip_ok"] is True
    assert rollback["deltas_applied"] >= 3
    # updates actually ran concurrent with scoring traffic
    latency = next(e for e in detail["entries"]
                   if e["name"] == "online_latency")
    assert latency["under_updates"]["entities_updated"] > 0
    assert latency["under_updates"]["deltas_published"] > 0
    assert latency["baseline"]["errors"] == 0
    assert latency["under_updates"]["errors"] == 0


def test_health_smoke(tmp_path):
    """bench.py --health --smoke end-to-end in tier-1 (ISSUE 11
    satellite): the model-health harness — streaming calibration windows,
    drift baselines, gate trips on injected label-flip and covariate
    shift, pause + delta rollback, the armed/disarmed compile gate —
    cannot rot without failing the normal test run.  The p99 gate is a
    smoke SIGNAL here (shared-core CI); the full bench run enforces it
    hard."""
    bench = _load_bench()
    out = tmp_path / "BENCH_health.json"
    result = bench.health_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    # zero false alarms across the stationary leg (deltas flowing live)
    stationary = next(e for e in detail["entries"]
                      if e["name"] == "health_stationary")
    assert stationary["gate_trips"] == 0
    assert stationary["deltas_published"] > 0
    assert stationary["status"] == "ok"
    # injected label flip: calibration gate trips within <= 3 windows,
    # updater pauses, the pending deltas roll back bit-exact
    flip = next(e for e in detail["entries"]
                if e["name"] == "health_label_flip")
    assert flip["windows_to_trip"] is not None
    assert flip["windows_to_trip"] <= 3
    assert flip["status"] == "degraded" and flip["updater_paused"]
    assert flip["deltas_published_while_paused"] == 0
    assert flip["rollback_restored_pre_delta_rows"] is True
    # injected covariate shift: a drift gate trips within <= 3 windows
    covariate = next(e for e in detail["entries"]
                     if e["name"] == "health_covariate_shift")
    assert covariate["windows_to_trip"] is not None
    assert covariate["windows_to_trip"] <= 3
    assert covariate["tripped_gates"]
    # zero fresh traces armed AND disarmed, with windows closing inside
    # the counted region
    traces = next(e for e in detail["entries"]
                  if e["name"] == "health_steady_state_traces")
    assert traces["armed"]["fresh_traces"] == 0
    assert traces["disarmed"]["fresh_traces"] == 0
    assert traces["armed"]["label_windows"] >= 3
    # the latency leg ran without errors on both sides (ratio is gated
    # by the full bench, not here)
    latency = next(e for e in detail["entries"]
                   if e["name"] == "health_latency")
    assert latency["disarmed"]["errors"] == 0
    assert latency["armed"]["errors"] == 0
    assert latency["armed"]["score_windows"] > 0


def test_refit_smoke(tmp_path):
    """bench.py --refit --smoke end-to-end in tier-1 (ISSUE 16
    satellite): the continuous-training harness — f64 refit-from-log
    parity, the drift-trip -> compact -> warm refit -> validate -> swap
    -> recovery loop, and the zero-fresh-traces-across-the-swap gate —
    cannot rot without failing the normal test run.  The p99 gate is a
    smoke SIGNAL here (shared-core CI; the nice'd cli.refit child
    competes with the whole suite); the full bench run enforces it
    hard."""
    bench = _load_bench()
    out = tmp_path / "BENCH_refit.json"
    result = bench.refit_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    # refitting from the log is the IDENTICAL fit as from memory (f64)
    parity = next(e for e in detail["entries"]
                  if e["name"] == "refit_parity")
    assert parity["parity_ok"] is True
    assert parity["history_max_abs_diff"] <= parity["parity_gate"]
    assert parity["sealed_chunks"] >= 1
    # the closed loop: trip -> pause -> refit -> swap -> gates reset ->
    # resume -> zero trips across a post-swap stationary window
    loop = next(e for e in detail["entries"] if e["name"] == "refit_loop")
    assert loop["loop_ok"] is True
    assert loop["windows_to_trip"] is not None
    assert loop["updater_paused_on_trip"] is True
    assert loop["swapped"] is True
    assert loop["candidate_version"] != loop["incumbent_version"]
    assert loop["candidate"]["loss"] < loop["incumbent"]["loss"]
    assert loop["gates_reset"] and loop["updater_resumed"]
    assert loop["post_swap_trips"] == 0
    assert loop["post_swap_status"] == "ok"
    assert loop["refit_metrics"]["swaps"] >= 1
    # zero fresh XLA traces in the serving path on BOTH sides of the swap
    traces = next(e for e in detail["entries"]
                  if e["name"] == "refit_traces")
    assert traces["zero_traces_ok"] is True
    assert traces["fresh_traces_before_swap"] == 0
    assert traces["fresh_traces_after_swap"] == 0
    assert traces["version_after"] != traces["version_before"]
    # the latency leg's subprocess refit ran cycles and exited cleanly
    # (the 1.2x ratio is the full bench's hard gate, not smoke's)
    latency = next(e for e in detail["entries"]
                   if e["name"] == "refit_latency")
    assert latency["child_rc"] == 0
    assert latency["first_cycle_before_measurement"] is True
    assert latency["refit_cycles"] >= 1 or latency["refit_swap_dirs"] >= 1
    assert latency["overlapped_reps"] == latency["reps"]


def test_fleet_smoke(tmp_path):
    """bench.py --fleet --smoke end-to-end in tier-1 (ISSUE 12
    satellite): the replicated-serving harness — log replay with zero
    fresh traces, mid-stream rollback convergence, transient-fault
    trajectory parity, and the subprocess crash/catch-up leg with a real
    SIGKILL — cannot rot without failing the normal test run.  The
    1->2-replica throughput-scaling gate is a smoke SIGNAL here
    (shared-core CI; on a single-core host it is measured and reported
    ungated); the full bench run enforces it hard on multi-core hosts."""
    bench = _load_bench()
    out = tmp_path / "BENCH_fleet.json"
    result = bench.fleet_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    # (d) zero fresh traces on the replica during steady-state replay
    traces = next(e for e in detail["entries"]
                  if e["name"] == "fleet_replay_traces")
    assert traces["fresh_traces_replay"] == 0
    assert traces["records_applied"] >= traces["steady_rounds"]
    assert traces["converged"] is True
    # (b) a mid-stream rollback converges identically on every replica
    rollback = next(e for e in detail["entries"]
                    if e["name"] == "fleet_rollback_convergence")
    assert rollback["rollback_ok"] is True
    assert rollback["publisher_restored_pre_delta_rows"] is True
    assert rollback["deltas_rolled_back"] >= 1
    # (e) injected transient replog/replica faults absorbed with
    # exact-trajectory parity vs the fault-free run
    parity = next(e for e in detail["entries"]
                  if e["name"] == "fleet_fault_parity")
    assert parity["fault_parity_ok"] is True
    assert parity["faults_fired"] >= 4
    assert parity["fault_free_vv"] == parity["faulted_vv"]
    # (a) SIGKILLed follower restarts from durable state and the whole
    # fleet reports bit-identical version vectors + table hashes
    crash = next(e for e in detail["entries"]
                 if e["name"] == "fleet_crash_catchup")
    assert crash["killed_returncode"] not in (0, 1)   # actually SIGKILLed
    assert crash["rejoined_ready"] is True
    assert crash["bit_identical"] is True
    assert crash["rows_scored"] > 0 and crash["feedback_rows"] > 0
    assert crash["deltas_published"] > 0
    # (c) both scaling phases served their full stream error-free (the
    # ratio is the full bench's hard gate on multi-core hosts)
    scaling = next(e for e in detail["entries"]
                   if e["name"] == "fleet_scaling")
    assert scaling["one_replica"]["errors"] == 0
    assert scaling["two_replicas"]["errors"] == 0
    assert scaling["throughput_ratio"] > 0


def test_fleetobs_smoke(tmp_path):
    """bench.py --fleetobs --smoke end-to-end in tier-1 (ISSUE 13
    satellite): the fleet-observability harness — cross-process trace
    merge (one connected tree per request id, feedback flow crossing
    front -> publisher -> follower), clock-probe alignment keeping
    children inside parents, federated per-replica lag that goes
    0 -> >0 -> 0 around a SIGKILL + catch-up, correlated flight-recorder
    bundles on the crash and on a health-gate trip, and the zero-fresh-
    traces contract — cannot rot without failing the normal test run.
    The armed-vs-disarmed p99 ratio is a smoke SIGNAL here (shared-core
    CI); the full bench run gates it at 1.1x."""
    bench = _load_bench()
    out = tmp_path / "BENCH_fleetobs.json"
    result = bench.fleetobs_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    fleet = next(e for e in detail["entries"]
                 if e["name"] == "fleetobs_fleet")
    # the merged Perfetto export validates and every sampled request id
    # is ONE connected tree
    assert fleet["merge_valid"] is True and fleet["merge_problems"] == []
    assert fleet["score_trees_ok"] is True
    # the feedback flow crosses >= 3 processes with the full span chain
    assert fleet["feedback_tree_ok"] is True
    assert {"front_request", "serve_request", "online_update",
            "replica_apply"} <= set(fleet["feedback_tree"]["span_names"])
    assert len(fleet["feedback_tree"]["processes"]) >= 3
    # clock alignment keeps children inside their parents
    assert fleet["containment"]["checked"] > 0
    assert fleet["containment_violations"] == 0
    # federated lag: 0 converged -> >0 while the follower is down and
    # the publisher advances -> 0 after restart + catch-up
    assert fleet["killed_returncode"] not in (0, 1)
    assert fleet["lag_at_converged"]["lag_records"] == 0
    assert fleet["lag_while_down"]["lag_records"] > 0
    assert fleet["lag_after_catchup"]["lag_seq"] == 0
    assert fleet["federated_ok"] is True
    # the crash produced correlated bundles from >= 2 live processes
    assert fleet["flight_ok"] is True
    assert "front" in fleet["flight_bundle_procs"]
    # a health-gate trip dumps the triggering window
    health = next(e for e in detail["entries"]
                  if e["name"] == "fleetobs_health_flight")
    assert health["gate_trips"] >= 1
    assert health["trip_event_in_bundle"] is True
    assert health["evaluate_span_in_bundle"] is True
    # zero fresh XLA traces armed AND disarmed
    overhead = next(e for e in detail["entries"]
                    if e["name"] == "fleetobs_overhead")
    assert overhead["fresh_traces_disarmed"] == 0
    assert overhead["fresh_traces_armed"] == 0
    assert overhead["p99_ratio_armed_vs_disarmed"] > 0


def test_store_smoke(tmp_path):
    """bench.py --store --smoke end-to-end in tier-1 (ISSUE 14
    satellite): the tiered-entity-store harness — budgeted-vs-all-
    resident serving through the store, hot+warm delta swaps with
    bit-exact rollback, the budgeted training parity gate, and the
    zero-fresh-traces regression — cannot rot without failing the
    normal test run.  The p99 latency half of the serving gate is a
    smoke signal here (shared CPUs); it is HARD on the committed full
    bench run."""
    bench = _load_bench()
    out = tmp_path / "BENCH_store.json"
    result = bench.store_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    by_name = {e["name"]: e for e in detail["entries"]}
    serving = by_name["store_serving"]
    # the residency claim: far more entities than device-resident rows,
    # served at >= 90% hot hit rate
    assert serving["hot_rows"] < serving["entities"]
    assert serving["hit_rate_ok"] is True
    assert serving["budgeted"]["hit_rate"] >= 0.90
    # promotions flush BETWEEN measurement windows (the off-peak pacing
    # the bench documents), so assert on the store's cumulative counter
    assert serving["budgeted"]["residency"]["promotions"] > 0
    delta = by_name["store_delta"]
    assert delta["rollback_bit_exact"] is True
    assert delta["durable_round_trip_exact"] is True
    assert delta["delta_rows_hot_tier"] > 0
    assert delta["delta_rows_warm_tier"] > 0
    training = by_name["store_training"]
    assert training["objective_history_max_rel_gap"] <= 1e-10
    assert training["evictions"] > 0 and training["store_fetches"] > 0
    traces = by_name["store_traces"]
    assert traces["serving_fresh_traces"] == 0
    assert traces["training_fresh_traces"] == 0
    assert traces["serving_exercised"] is True


def test_multihost_smoke(tmp_path):
    """bench.py --multihost --smoke (ISSUE 19 satellite): the 2-subprocess
    jax.distributed pair on tiny shapes, with the parity + staging gates
    asserted and the wall budget honored — an exhausted --max-wall skips
    the trace-differential and kill/resume legs with explicit "truncated"
    markers instead of blowing the suite budget."""
    bench = _load_bench()
    out = tmp_path / "BENCH_multihost.json"
    result = bench.multihost_bench(str(out), smoke=True, max_wall=0.05)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    d = result["detail"]
    # the 2proc x 1dev vs 1proc x 2dev pair keeps the global mesh, so
    # parity is bit-exact, not approximate
    assert d["parity_ok"] is True and d["parity_gap_abs"] == 0.0
    assert d["model_bit_identical"] is True
    # per-process staging: symmetric cold shards, bounded warm traffic
    assert d["staging_ok"] is True
    assert len(d["cold_bytes_per_process"]) == 2
    # the wall budget was exhausted after the parity leg: the remaining
    # legs are skipped WITH markers, and the skipped gates stay non-False
    assert set(d["truncated"]) == {"multihost_traces",
                                   "multihost_kill_resume"}
    assert d["max_wall_s"] == 0.05
    assert d["zero_fresh_traces_ok"] is None
    assert d["kill_resume"] is None
    assert d["gates_green"] is True


def test_shards_smoke(tmp_path):
    """bench.py --shards --smoke end-to-end in tier-1 (ISSUE 20
    satellite): the entity-sharded-serving harness — deterministic/total
    shard map with spec_id rejection, fan-out merge bit-parity vs the
    monolithic scorer with zero fresh traces, shard-filtered replay to
    sha256-exact per-shard audits, the 4x-store-budget capacity claim,
    and the subprocess SIGKILL/degrade/rejoin leg — cannot rot without
    failing the normal test run.  The surviving-shard p99 gate is a
    smoke SIGNAL here (shared-core CI); the committed full bench run
    gates it hard at 1.2x."""
    bench = _load_bench()
    out = tmp_path / "BENCH_shards.json"
    result = bench.shards_bench(str(out), smoke=True)

    # kill-safe contract: the file on disk IS the returned result
    assert out.exists()
    assert json.loads(out.read_text()) == json.loads(json.dumps(result))

    detail = result["detail"]
    assert detail["smoke"] is True
    assert detail["all_ok"] is True
    by_name = {e["name"]: e for e in detail["entries"]}
    smap = by_name["shards_map"]
    assert smap["deterministic"] and smap["total"] and smap["roundtrip"]
    assert smap["spec_id_mismatch_rejected"] is True
    parity = by_name["shards_parity"]
    assert parity["rounds_bit_exact"] == parity["rounds"]
    assert parity["fresh_traces_fanout"] == 0
    assert parity["all_primaries_exact"] is True
    replay = by_name["shards_replay"]
    assert replay["fresh_traces_replay"] == 0
    assert replay["per_shard_audits_sha256_exact"] is True
    capacity = by_name["shards_capacity"]
    assert capacity["rounds_bit_exact"] == capacity["rounds"]
    assert result["value"] >= 4.0
    failover = by_name["shards_failover"]
    assert failover["killed_returncode"] not in (0, 1)  # real SIGKILL
    assert failover["baseline"]["errors"] == 0
    assert failover["baseline"]["inexact"] == 0
    assert failover["one_shard_down"]["errors"] == 0
    assert failover["one_shard_down"]["inexact"] == 0
    assert failover["errors_confined_to_lost_shard"] is True
    assert failover["rejoin_audit_sha256_exact"] is True
    assert failover["rejoin_heals_degraded_request"] is True

    # --max-wall is honored: an exhausted budget skips the heavy legs
    # with explicit "truncated" markers instead of blowing the suite
    # budget (the JSON still lands atomically, exit stays clean)
    out2 = tmp_path / "BENCH_shards_wall.json"
    result2 = bench.shards_bench(str(out2), smoke=True, max_wall=0.0)
    assert out2.exists()
    d2 = result2["detail"]
    assert set(d2["truncated"]) == {
        "shards_map", "shards_parity", "shards_replay",
        "shards_capacity", "shards_failover"}
    assert d2["all_ok"] is False


def test_max_wall_truncates_and_exits_cleanly(tmp_path, monkeypatch):
    """--max-wall budget (ISSUE 4 satellite): an exhausted wall budget
    SKIPS the remaining configs, writes the partial JSON with a
    "truncated" marker, and returns normally (exit 0) — instead of the
    harness timeout killing the run at rc=124 with the JSON lost."""
    bench = _load_bench()
    monkeypatch.chdir(tmp_path)
    result = bench.main(max_wall=0.0)
    assert result["detail"]["truncated"]          # every config skipped
    assert result["detail"]["configs"] == {}
    assert result["detail"]["max_wall_s"] == 0.0
    on_disk = json.loads((tmp_path / "BENCH.json").read_text())
    assert on_disk["detail"]["truncated"] == result["detail"]["truncated"]
    # the inexact leg honors the same budget
    out = tmp_path / "BENCH_inexact.json"
    r = bench.inexact_bench(str(out), smoke=False, max_wall=0.0)
    assert r["detail"]["truncated"]
    assert r["detail"]["entries"] == []


def test_bench_smoke_writes_no_repo_state(tmp_path, monkeypatch):
    """Smoke mode must not touch the committed bench caches (it is run by
    the tier-1 suite, which may not write repo files)."""
    bench = _load_bench()
    before = os.path.getmtime(os.path.join(_REPO, "bench_ref_cache.json"))
    monkeypatch.chdir(tmp_path)
    bench.smoke_bench(str(tmp_path / "s.json"))
    assert os.path.getmtime(
        os.path.join(_REPO, "bench_ref_cache.json")) == before
    assert not os.path.exists(os.path.join(_REPO, "BENCH_smoke.json"))
