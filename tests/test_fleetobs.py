"""Fleet-wide observability tests (ISSUE 13): cross-process trace
propagation (telemetry/distributed), the multi-process run-log merge,
the front's federated metric surfaces + parity contract, and the
dump-on-anomaly flight recorder (telemetry/flight).

The subprocess leg is the ISSUE 13 satellite: two subprocess replicas
and a front under load, every process tracing to its own run log, the
merged trace passing `validate_chrome_trace`, every sampled request id
one connected tree, and clock-offset alignment keeping child spans
inside their parents.
"""
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import distributed, flight
from photon_ml_tpu.telemetry.distributed import (PARENT_HEADER,
                                                 TRACE_HEADER,
                                                 merge_run_logs,
                                                 parse_run_log)
from photon_ml_tpu.fleet import FRONT_SNAPSHOT_PATHS, Front, FrontConfig
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.models.io import save_game_model
from photon_ml_tpu.utils import faults

D_G, D_U, N_ENT = 6, 4, 30
TASK = "logistic_regression"
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_model(rng):
    fe = FixedEffectModel(
        model_for_task(TASK, Coefficients(
            jnp.asarray(rng.normal(size=D_G)))), "global")
    re_m = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=TASK,
        coefficients=jnp.asarray(rng.normal(size=(N_ENT, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(N_ENT)],
                              dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re_m}, TASK)


# --------------------------------------------------------------------------
# trace context + propagation primitives
# --------------------------------------------------------------------------

def test_server_span_adopts_headers_and_scopes_context(tmp_path):
    log = str(tmp_path / "run.jsonl")
    with telemetry.enabled(run_log=log, watch_compiles=False,
                           proc="testproc"):
        assert distributed.current_request_id() is None
        headers = {TRACE_HEADER: "aaaabbbbccccdddd",
                   PARENT_HEADER: "777:3"}
        with distributed.server_span("serve_request", headers,
                                     path="/score") as scope:
            assert scope.request_id == "aaaabbbbccccdddd"
            assert distributed.current_request_id() == scope.request_id
            out = distributed.outbound_headers()
            assert out[TRACE_HEADER] == "aaaabbbbccccdddd"
            # the outbound parent is THIS span's ref, not the incoming
            assert out[PARENT_HEADER] == \
                f"{os.getpid()}:{telemetry.current_span_id()}"
        assert distributed.current_request_id() is None
    records = [json.loads(l) for l in open(log)]
    assert records[0]["kind"] == "meta"
    assert records[0]["proc"] == "testproc"
    span = next(r for r in records if r["kind"] == "span")
    assert span["attrs"]["request_id"] == "aaaabbbbccccdddd"
    assert span["attrs"]["remote_parent"] == "777:3"


def test_server_span_mints_when_no_header_and_disarmed_tracer():
    with distributed.server_span("serve_request", None) as scope:
        assert len(scope.request_id) == 16
        assert distributed.current_request_id() == scope.request_id
    assert distributed.current_request_id() is None


# --------------------------------------------------------------------------
# merge: synthetic multi-process run logs (deterministic)
# --------------------------------------------------------------------------

def _write_log(path, meta, records):
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "meta", "name": "process_meta",
                            "span": None, **meta}) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")


def _span(sid, name, t0, dur, parent=None, attrs=None, tid=1):
    return {"kind": "span", "name": name, "span": sid, "parent": parent,
            "tid": tid, "thread": "main", "t0_s": t0, "dur_s": dur,
            "attrs": attrs or {}}


def test_merge_connectivity_and_clock_alignment(tmp_path):
    """A front + remote process whose wall anchor is 0.5s off: the
    clock_probe event corrects it, the request is one connected tree,
    and the child lands inside its parent.  Without the probe the child
    would sit half a second outside."""
    rid = "feed000000000001"
    front_log = str(tmp_path / "front.jsonl")
    rep_log = str(tmp_path / "rep.jsonl")
    _write_log(front_log, {"proc": "front", "pid": 100,
                           "wall0_unix_s": 1000.0}, [
        _span(1, "front_request", 1.0, 0.2,
              attrs={"request_id": rid, "path": "/score"}),
        {"kind": "event", "name": "clock_probe", "span": None, "tid": 1,
         "t_s": 0.5,
         "attrs": {"pid": 200, "proc": "replica", "offset_s": 0.5,
                   "rtt_s": 0.002}},
    ])
    # the replica's own anchor claims wall0=1000.5 (0.5s fast); its span
    # at rel t0=1.05 is REALLY at front-time 1001.05
    _write_log(rep_log, {"proc": "replica", "pid": 200,
                         "wall0_unix_s": 1000.5}, [
        _span(7, "serve_request", 1.05, 0.1,
              attrs={"request_id": rid, "remote_parent": "100:1"}),
    ])
    report = merge_run_logs([front_log, rep_log],
                            out_path=str(tmp_path / "merged.json"))
    assert report["problems"] == []
    assert report["clock_offsets"]["200"]["offset_s"] == 0.5
    tree = report["requests"][rid]
    assert tree["connected"] is True
    assert tree["processes"] == [100, 200]
    assert report["containment"]["checked"] == 1
    assert report["containment"]["violations"] == []
    # the written trace validates and carries both process tracks
    payload = json.load(open(tmp_path / "merged.json"))
    assert telemetry.validate_chrome_trace(payload) == []
    names = {e["args"]["name"] for e in payload["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"front (100)", "replica (200)"}
    # ... and WITHOUT the probe, the same logs violate containment
    _write_log(front_log, {"proc": "front", "pid": 100,
                           "wall0_unix_s": 1000.0}, [
        _span(1, "front_request", 1.0, 0.2,
              attrs={"request_id": rid, "path": "/score"}),
    ])
    report2 = merge_run_logs([front_log, rep_log])
    assert report2["containment"]["violations"]


def test_merge_async_feedback_chain_connects(tmp_path):
    """The asynchronous half: serve_request -> (same-process flow) ->
    online_update -> (record trace parent) -> replica_apply on another
    process, all joined under one request id."""
    rid = "feed000000000002"
    pub = str(tmp_path / "pub.jsonl")
    rep = str(tmp_path / "rep.jsonl")
    _write_log(pub, {"proc": "publisher", "pid": 300,
                     "wall0_unix_s": 2000.0}, [
        _span(1, "serve_request", 1.0, 0.01,
              attrs={"request_id": rid, "path": "/feedback"}),
        _span(2, "online_update", 2.0, 0.5,
              attrs={"request_ids": rid + ",otherid", "coordinate": "x"}),
    ])
    _write_log(rep, {"proc": "replica", "pid": 301,
                     "wall0_unix_s": 2000.0}, [
        _span(9, "replica_apply", 3.0, 0.05,
              attrs={"request_ids": rid, "remote_parent": "300:2"}),
    ])
    report = merge_run_logs([pub, rep])
    tree = report["requests"][rid]
    assert tree["connected"] is True
    assert tree["processes"] == [300, 301]
    assert set(tree["span_names"]) == {"serve_request", "online_update",
                                       "replica_apply"}
    # async cross-process links are NOT containment-checked
    assert report["containment"]["checked"] == 0


def test_merge_torn_tail_and_missing_meta(tmp_path):
    good = str(tmp_path / "good.jsonl")
    _write_log(good, {"proc": "p", "pid": 1, "wall0_unix_s": 0.0},
               [_span(1, "a", 0.0, 1.0)])
    with open(good, "a") as f:
        f.write('{"kind": "span", "name": "torn')  # SIGKILL mid-write
    parsed = parse_run_log(good)
    assert [s["name"] for s in parsed["spans"]] == ["a"]
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps(_span(1, "a", 0.0, 1.0)) + "\n")
    with pytest.raises(ValueError, match="process_meta"):
        parse_run_log(bad)


# --------------------------------------------------------------------------
# front: metric-surface parity (satellite) + labeled counters
# --------------------------------------------------------------------------

def _flatten_paths(d, prefix=()):
    out = set()
    for k, v in d.items():
        out.add(prefix + (k,))
        if isinstance(v, dict):
            out |= _flatten_paths(v, prefix + (k,))
    return out


def test_front_metric_surface_parity_prometheus_vs_json():
    """ISSUE 13 satellite: the front's registry rides the same
    SNAPSHOT_PATHS parity contract as ServingMetrics — every instrument
    has a declared front_snapshot() path, every path resolves, and every
    instrument renders in the Prometheus exposition (labeled families
    included)."""
    front = Front(["http://127.0.0.1:1"], start_probes=False)
    try:
        front._m_by_replica.inc(replica="http://127.0.0.1:1",
                                outcome="ok")
        names = set(front.registry.names())
        assert names == set(FRONT_SNAPSHOT_PATHS), (
            "every front instrument needs a FRONT_SNAPSHOT_PATHS entry "
            f"(missing: {sorted(names - set(FRONT_SNAPSHOT_PATHS))}, "
            f"stale: {sorted(set(FRONT_SNAPSHOT_PATHS) - names)})")
        snap = front.front_snapshot()
        paths = _flatten_paths(snap)
        for name, path in FRONT_SNAPSHOT_PATHS.items():
            assert path in paths, (
                f"instrument {name!r} declares JSON path {path} but "
                "front_snapshot() has no such key")
        reg = front.registry.snapshot()
        prom = front.prometheus_metrics()
        series = set(re.findall(r"^photon_[a-zA-Z0-9_]+", prom,
                                flags=re.M))
        clean = lambda n: "photon_" + re.sub(r"[^a-zA-Z0-9_]", "_", n)
        for name in reg["counters"]:
            assert clean(name) + "_total" in series, name
        for name in reg["gauges"]:
            assert clean(name) in series, name
        for name in reg["labeled"]:
            assert clean(name) + "_total" in series, name
        # the labeled family renders per-(replica, outcome) series
        assert ('photon_front_requests_total{outcome="ok",'
                'replica="http://127.0.0.1:1"} 1') in prom
    finally:
        front.close()


def test_front_outcome_counters_and_hedge_wins():
    """front.requests{replica,outcome} separates ok / error / abandoned
    hedges, and a hedge that beats the original counts as a win."""
    class Stub:
        def __init__(self, delay_s=0.0):
            from http.server import (BaseHTTPRequestHandler,
                                     ThreadingHTTPServer)
            stub = self

            class H(BaseHTTPRequestHandler):
                protocol_version = "HTTP/1.1"

                def log_message(self, fmt, *a):
                    pass

                def do_POST(self):
                    n = int(self.headers.get("Content-Length") or 0)
                    self.rfile.read(n)
                    stub.trace_headers.append(
                        self.headers.get(TRACE_HEADER))
                    if stub.delay_s:
                        time.sleep(stub.delay_s)
                    body = b'{"scores": [0.0]}'
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):
                    body = b'{"status": "ok"}'
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            self.delay_s = delay_s
            self.trace_headers = []
            self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
            self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
            self._t = threading.Thread(target=self.httpd.serve_forever,
                                       kwargs={"poll_interval": 0.05},
                                       daemon=True)
            self._t.start()

        def close(self):
            self.httpd.shutdown()
            self.httpd.server_close()
            self._t.join(timeout=5)

    slow, fast = Stub(delay_s=1.0), Stub()
    front = Front([slow.url, fast.url],
                  config=FrontConfig(hedge_after_s=0.1,
                                     request_timeout_s=5.0),
                  start_probes=False)
    try:
        front.probe_once()
        status, _ = front.route("/score", {})
        assert status == 200
        by = front.front_snapshot()["requests_by_replica"]
        assert by.get(f"replica={fast.url},outcome=ok") == 1
        # the slow original was abandoned, and the hedge won
        assert by.get(f"replica={slow.url},outcome=abandoned") == 1
        assert front.front_snapshot()["hedge_wins"] == 1
        assert front.front_snapshot()["hedges"] == 1
        # both attempts carried the SAME propagated request id
        deadline = time.time() + 5
        while len(slow.trace_headers) < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert fast.trace_headers[0] is not None
        assert slow.trace_headers[0] == fast.trace_headers[0]
    finally:
        front.close()
        slow.close()
        fast.close()


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

def test_flight_ring_bounded_and_dump_bundle(tmp_path):
    with telemetry.enabled(watch_compiles=False):
        with flight.enabled(str(tmp_path / "dumps"), proc="t",
                            ring_records=16) as rec:
            for k in range(64):
                telemetry.event("tick", k=k)
            assert len(rec.snapshot()) == 16   # bounded: newest-N
            path = flight.trigger("serve.drain", note="test")
    bundle = json.load(open(path))
    assert bundle["reason"] == "serve.drain"
    assert bundle["proc"] == "t"
    assert bundle["window_s"][0] <= bundle["window_s"][1]
    names = [r.get("name") for r in bundle["records"]]
    assert "flight_dump" in names          # the trigger itself is in-ring
    assert bundle["attrs"]["note"] == "test"
    assert "metrics" in bundle


def test_flight_trigger_disarmed_is_noop_and_unknown_raises(tmp_path):
    assert not flight.armed()
    assert flight.trigger("serve.drain") is None   # disarmed: no-op
    with flight.enabled(str(tmp_path)):
        with pytest.raises(ValueError, match="unknown flight trigger"):
            flight.trigger("not.a.trigger")


def test_flight_triggers_have_event_constants():
    from photon_ml_tpu.telemetry.events import EVENTS
    assert set(flight.TRIGGERS) <= set(EVENTS)
    assert set(faults.SITES) <= set(EVENTS)


def test_replica_failure_dumps_flight_bundle(tmp_path, rng=None):
    """A fatal apply marks the replica failed AND dumps its ring — the
    replica.failed trigger wired in fleet/replica.py."""
    from photon_ml_tpu.fleet import (FleetPublisher, Replica,
                                     ReplicaConfig, ReplicationLog)
    from photon_ml_tpu.online import OnlineUpdateConfig
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    r = np.random.default_rng(17)
    mdir = str(tmp_path / "model")
    save_game_model(_make_model(r), mdir)

    def service(updates):
        return ScoringService(
            model_dir=mdir, config=ServingConfig(max_batch=64,
                                                 min_bucket=4),
            updates=OnlineUpdateConfig(micro_batch=8) if updates
            else None, start_updater=False)

    dumps = str(tmp_path / "dumps")
    svc = service(updates=True)
    log = ReplicationLog(str(tmp_path / "log"))
    FleetPublisher(svc, log, model_dir=mdir)
    rep = Replica(service(updates=False), log, str(tmp_path / "s0"),
                  ReplicaConfig())
    rep.join()
    try:
        feats = {"global": r.normal(size=(8, D_G)),
                 "per_user": r.normal(size=(8, D_U))}
        ids = {"userId": np.asarray([f"u{i}" for i in range(8)],
                                    dtype=object)}
        svc.feedback(feats, ids, np.zeros(8))
        svc.updater.flush()
        with flight.enabled(dumps, proc="replica"):
            plan = faults.FaultPlan([{"site": "replica.apply",
                                      "action": "fatal",
                                      "probability": 1.0}])
            with faults.injected(plan):
                assert rep.poll_once() == 0
        assert not rep.healthy()
        bundles = [json.load(open(os.path.join(dumps, f)))
                   for f in os.listdir(dumps)]
        assert len(bundles) == 1
        assert bundles[0]["reason"] == "replica.failed"
        assert "FatalFault" in bundles[0]["attrs"]["error"]
    finally:
        svc.close()
        rep.service.close()


# --------------------------------------------------------------------------
# the subprocess merge satellite: 2 replicas + front under load
# --------------------------------------------------------------------------

def _spawn_serve(args, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.cli.serve"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    line = proc.stdout.readline()
    if not line:
        proc.wait(timeout=10)
        raise RuntimeError(f"serve child died rc={proc.returncode}")
    return proc, json.loads(line)["serving"]


def _http(url, path, body=None, headers=None, timeout=20.0):
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url + path, data=data,
        method="POST" if data is not None else "GET",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait(pred, timeout_s=120.0, step_s=0.2):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        try:
            if pred():
                return True
        except Exception:
            pass
        time.sleep(step_s)
    return False


def test_two_subprocess_replicas_front_merge(tmp_path):
    """ISSUE 13 satellite: two subprocess replicas + a front under load;
    the merged trace validates, every sampled request id is one
    connected tree crossing processes, the feedback flow reaches the
    follower's apply, and clock alignment keeps children inside their
    parents."""
    r = np.random.default_rng(23)
    mdir = str(tmp_path / "model")
    save_game_model(_make_model(r), mdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    logs = {n: str(tmp_path / f"{n}.jsonl")
            for n in ("front", "pub", "f0")}
    common = ["--model-dir", mdir, "--port", "0", "--max-batch", "32",
              "--min-bucket", "4",
              "--replication-log", str(tmp_path / "replog")]
    pub, pub_url = _spawn_serve(
        common + ["--replica", "--publish", "--enable-updates",
                  "--update-interval-ms", "10",
                  "--replica-state", str(tmp_path / "pub"),
                  "--run-log", logs["pub"]], env)
    f0, f0_url = _spawn_serve(
        common + ["--replica", "--replica-poll-ms", "20",
                  "--replica-state", str(tmp_path / "f0"),
                  "--run-log", logs["f0"]], env)
    front = None
    try:
        assert _wait(lambda: _http(pub_url, "/healthz")[0] == 200)
        assert _wait(lambda: _http(f0_url, "/healthz")[0] == 200)
        front, front_url = _spawn_serve(
            ["--front", "--replica-url", pub_url,
             "--replica-url", f0_url, "--port", "0",
             "--probe-interval-ms", "100",
             "--run-log", logs["front"]], env)
        assert _wait(lambda: _http(front_url, "/healthz")[0] == 200)
        score_ids = [f"{k:016x}" for k in range(1, 7)]
        for rid in score_ids:
            body = {"features": {
                "global": r.normal(size=(2, D_G)).tolist(),
                "per_user": r.normal(size=(2, D_U)).tolist()},
                "ids": {"userId": ["u1", "u2"]}}
            status, _ = _http(front_url, "/score", body,
                              headers={TRACE_HEADER: rid})
            assert status == 200
        fb_rid = "fb00000000000001"
        n = 8
        body = {"features": {
            "global": r.normal(size=(n, D_G)).tolist(),
            "per_user": r.normal(size=(n, D_U)).tolist()},
            "ids": {"userId": [f"u{i}" for i in range(n)]},
            "labels": [0.0, 1.0] * (n // 2)}
        applied0 = _http(f0_url, "/metrics.json")[1]["fleet"][
            "records_applied"]
        status, _ = _http(front_url, "/feedback", body,
                          headers={TRACE_HEADER: fb_rid})
        assert status == 202
        # the feedback's DELTA must land on the follower before we drain
        # (>= applied0 + 1: the bootstrap swap record already counts
        # toward records_applied, so an absolute >= 1 races the drain)
        assert _wait(lambda: _http(f0_url, "/metrics.json")[1]
                     ["fleet"]["records_applied"] >= applied0 + 1)
    finally:
        for proc in (front, pub, f0):
            if proc is not None:
                p = proc[0] if isinstance(proc, tuple) else proc
                p.send_signal(signal.SIGTERM)
        for proc in (front, pub, f0):
            if proc is not None:
                p = proc[0] if isinstance(proc, tuple) else proc
                try:
                    p.communicate(timeout=60)
                except subprocess.TimeoutExpired:
                    p.kill()
    report = merge_run_logs(list(logs.values()),
                            out_path=str(tmp_path / "merged.json"))
    assert report["problems"] == []
    assert len(report["processes"]) == 3
    # clock probes produced offsets for both probed replicas
    assert len(report["clock_offsets"]) >= 2
    for rid in score_ids:
        tree = report["requests"][rid]
        assert tree["connected"] is True, rid
        assert len(tree["processes"]) >= 2
        assert {"front_request", "serve_request"} <= \
            set(tree["span_names"])
    fb = report["requests"][fb_rid]
    assert fb["connected"] is True
    assert len(fb["processes"]) == 3
    assert {"front_request", "serve_request", "online_update",
            "replica_apply"} <= set(fb["span_names"])
    # alignment: synchronous children inside their front parents
    assert report["containment"]["checked"] >= len(score_ids)
    assert report["containment"]["violations"] == []
    # the new replica-side instruments made it to both surfaces (the
    # run happened over HTTP, so check the merged JSON snapshot shape
    # via a fresh ServingMetrics instead)
    from photon_ml_tpu.serving.metrics import SNAPSHOT_PATHS
    assert "fleet.apply_latency_s" in SNAPSHOT_PATHS
    assert "fleet.feedback_visible_s" in SNAPSHOT_PATHS
