"""Aggregator kernels vs autodiff, dense vs sparse, and normalization algebra.

Mirrors reference tests for the aggregators / objective functions
(photon-api/src/test/.../function/glm/SingleNodeObjectiveFunctionTest.scala)
plus the normalization-invariance checks from GameEstimatorTest.scala:125-180.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import sparse as jsparse

from photon_ml_tpu.ops import aggregators as agg
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.normalization import build_normalization_context
from photon_ml_tpu.ops.objective import GLMObjective
from tests.synthetic import make_glm_data

LOSS_TASK = [(losses.LOGISTIC, "logistic"), (losses.SQUARED, "linear"),
             (losses.POISSON, "poisson"), (losses.SMOOTHED_HINGE, "hinge")]


def _norm_ctx(x, kind, intercept=None):
    xm = jnp.asarray(x)
    return build_normalization_context(
        kind,
        mean=xm.mean(0),
        variance=xm.var(0, ddof=1),
        max_magnitude=jnp.abs(xm).max(0),
        intercept_index=intercept,
    )


@pytest.mark.parametrize("loss,task", LOSS_TASK, ids=lambda p: str(p))
def test_value_and_gradient_matches_autodiff(loss, task, rng):
    x, y, w, _ = make_glm_data(rng, n=128, d=7, task=task, weight_range=(0.5, 2.0))
    offsets = rng.normal(size=128) * 0.3
    c = jnp.asarray(rng.normal(size=7))
    x, y, w, offsets = map(jnp.asarray, (x, y, w, offsets))

    def f(c):
        return agg.value_only(loss, x, y, c, weights=w, offsets=offsets)

    v, g = agg.value_and_gradient(loss, x, y, c, weights=w, offsets=offsets)
    np.testing.assert_allclose(v, f(c), rtol=1e-12)
    np.testing.assert_allclose(g, jax.grad(f)(c), rtol=1e-9, atol=1e-10)


@pytest.mark.parametrize("loss,task", [p for p in LOSS_TASK if p[0].twice_differentiable],
                         ids=lambda p: str(p))
def test_hessian_vector_matches_autodiff(loss, task, rng):
    x, y, w, _ = make_glm_data(rng, n=96, d=6, task=task, weight_range=(0.5, 2.0))
    c = jnp.asarray(rng.normal(size=6) * 0.5)
    v = jnp.asarray(rng.normal(size=6))
    x, y, w = map(jnp.asarray, (x, y, w))

    def f(c):
        return agg.value_only(loss, x, y, c, weights=w)

    got = agg.hessian_vector(loss, x, y, c, v, weights=w)
    want = jax.jvp(jax.grad(f), (c,), (v,))[1]
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-9)


def test_hessian_diagonal_matches_autodiff(rng):
    x, y, w, _ = make_glm_data(rng, n=96, d=6, task="logistic", weight_range=(0.5, 2.0))
    c = jnp.asarray(rng.normal(size=6) * 0.5)
    x, y, w = map(jnp.asarray, (x, y, w))

    def f(c):
        return agg.value_only(losses.LOGISTIC, x, y, c, weights=w)

    got = agg.hessian_diagonal(losses.LOGISTIC, x, y, c, weights=w)
    want = jnp.diag(jax.hessian(f)(c))
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-9)


@pytest.mark.parametrize("kind", ["scale_with_standard_deviation",
                                  "scale_with_max_magnitude", "standardization"])
def test_normalized_kernels_equal_materialized(kind, rng):
    """The shift/factor algebra must equal actually normalizing the features.

    reference: ValueAndGradientAggregator.scala:35-79 contract."""
    x, y, w, _ = make_glm_data(rng, n=128, d=5, task="logistic", weight_range=(0.5, 2.0))
    c = jnp.asarray(rng.normal(size=5))
    norm = _norm_ctx(x, kind, intercept=4)
    xj, yj, wj = map(jnp.asarray, (x, y, w))

    factors = norm.factors if norm.factors is not None else jnp.ones(5)
    shifts = norm.shifts if norm.shifts is not None else jnp.zeros(5)
    x_mat = (xj - shifts) * factors  # materialized normalized features

    v_alg, g_alg = agg.value_and_gradient(losses.LOGISTIC, xj, yj, c, weights=wj, norm=norm)
    v_mat, g_mat = agg.value_and_gradient(losses.LOGISTIC, x_mat, yj, c, weights=wj)
    np.testing.assert_allclose(v_alg, v_mat, rtol=1e-10)
    np.testing.assert_allclose(g_alg, g_mat, rtol=1e-9, atol=1e-10)

    hv_alg = agg.hessian_vector(losses.LOGISTIC, xj, yj, c, g_alg, weights=wj, norm=norm)
    hv_mat = agg.hessian_vector(losses.LOGISTIC, x_mat, yj, c, g_mat, weights=wj)
    np.testing.assert_allclose(hv_alg, hv_mat, rtol=1e-9, atol=1e-10)


def test_sparse_matches_dense(rng):
    x, y, _, _ = make_glm_data(rng, n=64, d=20, task="logistic")
    x[np.abs(x) < 0.8] = 0.0  # sparsify
    c = jnp.asarray(rng.normal(size=20))
    xd = jnp.asarray(x)
    xs = jsparse.BCOO.fromdense(xd)
    y = jnp.asarray(y)

    vd, gd = agg.value_and_gradient(losses.LOGISTIC, xd, y, c)
    vs, gs = agg.value_and_gradient(losses.LOGISTIC, xs, y, c)
    np.testing.assert_allclose(vs, vd, rtol=1e-12)
    np.testing.assert_allclose(gs, gd, rtol=1e-10, atol=1e-12)

    hvd = agg.hessian_vector(losses.LOGISTIC, xd, y, c, gd)
    hvs = agg.hessian_vector(losses.LOGISTIC, xs, y, c, gd)
    np.testing.assert_allclose(hvs, hvd, rtol=1e-10, atol=1e-12)

    hdd = agg.hessian_diagonal(losses.LOGISTIC, xd, y, c)
    hds = agg.hessian_diagonal(losses.LOGISTIC, xs, y, c)
    np.testing.assert_allclose(hds, hdd, rtol=1e-10, atol=1e-12)


def test_mask_equals_subset(rng):
    """Padded rows with mask=0 must contribute nothing (TPU raggedness story)."""
    x, y, w, _ = make_glm_data(rng, n=50, d=4, task="logistic", weight_range=(0.5, 2.0))
    c = jnp.asarray(rng.normal(size=4))
    mask = np.zeros(50); mask[:37] = 1.0
    v_m, g_m = agg.value_and_gradient(losses.LOGISTIC, jnp.asarray(x), jnp.asarray(y), c,
                                      weights=jnp.asarray(w), mask=jnp.asarray(mask))
    v_s, g_s = agg.value_and_gradient(losses.LOGISTIC, jnp.asarray(x[:37]), jnp.asarray(y[:37]),
                                      c, weights=jnp.asarray(w[:37]))
    np.testing.assert_allclose(v_m, v_s, rtol=1e-12)
    np.testing.assert_allclose(g_m, g_s, rtol=1e-12)


def test_objective_l2_and_pytree(rng):
    x, y, w, _ = make_glm_data(rng, n=64, d=5, task="logistic")
    obj = GLMObjective(losses.LOGISTIC, jnp.asarray(x), jnp.asarray(y), l2_weight=0.7)
    c = jnp.asarray(rng.normal(size=5))

    v, g = obj.value_and_gradient(c)
    np.testing.assert_allclose(v, obj.value(c), rtol=1e-12)
    np.testing.assert_allclose(g, jax.grad(obj.value)(c), rtol=1e-9, atol=1e-10)
    hv = obj.hessian_vector(c, g)
    np.testing.assert_allclose(hv, jax.jvp(jax.grad(obj.value), (c,), (g,))[1],
                               rtol=1e-8, atol=1e-9)

    # must survive a jit round-trip as an argument (pytree correctness)
    @jax.jit
    def run(o, c):
        return o.value(c)
    np.testing.assert_allclose(run(obj, c), obj.value(c), rtol=1e-12)
