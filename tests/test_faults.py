"""Fault-contained GAME training (ISSUE 5): the fault-injection registry,
streaming retry/backoff, crash-safe manifest checkpoints + verified
fallback, graceful preemption, and the non-finite solve quarantine."""
import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.data.streaming import (ChunkPlan, ChunkStagingError,
                                          Prefetcher)
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig, RandomEffectCoordinateConfig,
)
from photon_ml_tpu.game.coordinate_descent import (read_checkpoint,
                                                   verify_checkpoint_dir)
from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                 RegularizationType)
from photon_ml_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
L2 = RegularizationContext(RegularizationType.L2)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """No fault plan or preemption flag leaks between tests."""
    faults.install_plan(None)
    faults.clear_preemption()
    yield
    faults.install_plan(None)
    faults.clear_preemption()


def _glmix(rng, n=900, n_users=30):
    xg = rng.normal(size=(n, 8)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, 4)); xu[:, -1] = 1.0
    users = np.arange(n) % n_users
    z = xg @ rng.normal(size=8)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    return build_game_dataset(
        y, {"g": xg, "u": xu},
        entity_ids={"userId": np.asarray([f"u{i:04d}" for i in users])})


def _opt(iters=15):
    return GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=iters),
        regularization=L2, regularization_weight=1.0)


def _config(iters=3, coords=("fixed", "perUser")):
    cmap = {}
    if "fixed" in coords:
        cmap["fixed"] = FixedEffectCoordinateConfig("g", _opt())
    if "perUser" in coords:
        cmap["perUser"] = RandomEffectCoordinateConfig(
            "userId", "u", _opt(), projector="identity")
    return GameTrainingConfig(task_type="logistic_regression",
                              coordinates=cmap,
                              updating_sequence=list(coords),
                              num_outer_iterations=iters)


# --------------------------------------------------------------------------
# FaultPlan registry
# --------------------------------------------------------------------------

def test_fault_plan_hits_and_match():
    plan = faults.FaultPlan([
        {"site": "solve.poison", "action": "transient", "hits": [2],
         "match": {"coordinate": "a"}}])
    with faults.injected(plan):
        assert faults.fire("solve.poison", coordinate="b",
                           iteration=0) is None  # no match
        assert faults.fire("solve.poison", coordinate="a",
                           iteration=0) is None  # hit 1
        with pytest.raises(faults.TransientFault):
            faults.fire("solve.poison", coordinate="a",
                        iteration=1)             # hit 2 fires
        assert faults.fire("solve.poison", coordinate="a",
                           iteration=2) is None  # hit 3
    rep = plan.report()
    assert rep["sites"]["solve.poison"] == {"calls": 3, "fired": 1}
    assert rep["total_fired"] == 1


def test_fault_plan_probability_is_seeded():
    def fires(seed):
        plan = faults.FaultPlan(
            [{"site": "stage.fetch", "probability": 0.5,
              "max_fires": 100}], seed=seed)
        out = []
        for i in range(50):
            try:
                plan.fire("stage.fetch", chunk=i)
                out.append(False)
            except faults.TransientFault:
                out.append(True)
        return out
    assert fires(7) == fires(7)          # deterministic per seed
    assert any(fires(7)) and not all(fires(7))


def test_fault_plan_json_round_trip_and_env(monkeypatch):
    plan = faults.FaultPlan([{"site": "model.save", "action": "fatal",
                              "hits": [1]}], seed=3)
    monkeypatch.setenv("PHOTON_FAULT_PLAN", json.dumps(plan.to_dict()))
    installed = faults.install_from_env()
    assert installed is not None and faults.active_plan() is installed
    assert installed.to_dict() == plan.to_dict()
    with pytest.raises(faults.FatalFault):
        faults.fire("model.save", directory="x")


def test_fire_without_plan_is_noop():
    assert faults.active_plan() is None
    assert faults.fire("stage.fetch", chunk=1) is None


def test_transient_classification():
    assert faults.is_transient(OSError("flaky disk"))
    assert faults.is_transient(TimeoutError())
    assert faults.is_transient(faults.TransientFault("x"))
    assert not faults.is_transient(faults.FatalFault("x"))
    assert not faults.is_transient(KeyboardInterrupt())
    assert not faults.is_transient(SystemExit())
    assert not faults.is_transient(MemoryError())
    assert not faults.is_transient(ValueError("bug"))


def test_unknown_action_rejected():
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.FaultPlan([{"site": "stage.fetch", "action": "explode",
                           "hits": [1]}])
    with pytest.raises(ValueError, match="never fires"):
        faults.FaultPlan([{"site": "stage.fetch"}])


def test_unknown_site_rejected_at_install_time():
    # a typo'd site would arm a fault that silently never fires — the
    # registry (utils.faults.SITES) rejects it up front, by name
    with pytest.raises(ValueError, match="unknown fault site 'stage.ftch'"):
        faults.FaultPlan([{"site": "stage.ftch", "hits": [1]}])
    with pytest.raises(ValueError, match="stage.fetch"):  # helpful listing
        faults.FaultPlan([{"site": "nope", "hits": [1]}])


def test_unknown_match_key_rejected_at_install_time():
    with pytest.raises(ValueError, match=r"unknown context key\(s\) "
                                         r"\['chunk_index'\]"):
        faults.FaultPlan([{"site": "stage.fetch", "hits": [1],
                           "match": {"chunk_index": 3}}])


def test_match_key_missing_from_fire_context_is_an_error():
    # the site declares the key but the fire() call didn't pass it: that
    # is a real bug at the site, not a silent no-match
    plan = faults.FaultPlan([{"site": "solve.poison", "hits": [1],
                              "match": {"coordinate": "a"}}])
    with faults.injected(plan):
        with pytest.raises(ValueError, match="did not pass"):
            faults.fire("solve.poison", iteration=0)


# --------------------------------------------------------------------------
# Prefetcher retry / backoff / classification (tentpole part 3 + satellite)
# --------------------------------------------------------------------------

def _flaky_fetch(fail_on, kind=OSError, times=1):
    failures = {}

    def fetch(spec):
        if spec.index in fail_on and failures.get(spec.index, 0) < times:
            failures[spec.index] = failures.get(spec.index, 0) + 1
            raise kind(f"flaky chunk {spec.index}")
        return {"v": np.full(spec.padded_rows, float(spec.index))}
    return fetch


def test_prefetcher_retries_transient_and_counts():
    plan = ChunkPlan.build(2048, chunk_rows=256)
    pf = Prefetcher(plan, _flaky_fetch({1, 3, 5}), backoff_s=0.001)
    chunks = list(pf.stream())
    assert len(chunks) == plan.num_chunks
    snap = pf.stats.snapshot()
    assert snap["retries"] == 3 and snap["gave_up"] == 0
    # retried chunks carry the SAME data the clean path would have staged
    for spec, dev in chunks:
        np.testing.assert_array_equal(np.asarray(dev["v"]),
                                      float(spec.index))


def test_prefetcher_exhausted_budget_names_chunk():
    plan = ChunkPlan.build(2048, chunk_rows=256)
    pf = Prefetcher(plan, _flaky_fetch({3}, times=99), max_attempts=3,
                    backoff_s=0.001)
    with pytest.raises(ChunkStagingError,
                       match=r"chunk staging failed for chunk 3 of 8 "
                             r"after 3 attempt"):
        list(pf.stream())
    assert pf.stats.snapshot()["gave_up"] == 1
    assert pf.stats.snapshot()["retries"] == 2


def test_prefetcher_fatal_skips_retry():
    plan = ChunkPlan.build(1024, chunk_rows=256)
    pf = Prefetcher(plan, _flaky_fetch({2}, kind=ValueError),
                    backoff_s=0.001)
    with pytest.raises(ChunkStagingError, match="fatal ValueError"):
        list(pf.stream())
    assert pf.stats.snapshot()["retries"] == 0


def test_prefetcher_interrupt_not_laundered():
    """KeyboardInterrupt/SystemExit in the staging thread must re-raise AS
    THEMSELVES in the consumer (ISSUE 5 satellite: not swallowed into a
    RuntimeError('chunk staging failed'))."""
    plan = ChunkPlan.build(1024, chunk_rows=256)
    for kind in (KeyboardInterrupt, SystemExit):
        pf = Prefetcher(plan, _flaky_fetch({1}, kind=kind), backoff_s=0.001)
        with pytest.raises(kind):
            list(pf.stream())


def test_injected_staging_faults_keep_streamed_fit_exact(rng):
    """Transient staging faults under a streamed FE solve change NOTHING
    about the math: identical objective history, retries accounted."""
    import dataclasses as _dc
    n = 2048
    x = rng.normal(size=(n, 8)); x[:, -1] = 1.0
    y = (rng.uniform(size=n) < 0.5).astype(float)
    fe = FixedEffectCoordinateConfig("g", _opt(8), memory_mode="streamed",
                                     chunk_rows=256)
    cfg = _dc.replace(_config(2, coords=("fixed",)),
                      coordinates={"fixed": fe})

    def run(plan):
        ds = build_game_dataset(y, {"g": x})
        est = GameEstimator(cfg)
        coords = est._build_coordinates(ds)
        from photon_ml_tpu.game.coordinate_descent import \
            run_coordinate_descent
        if plan is None:
            res = run_coordinate_descent(coords, ["fixed"], 2, ds,
                                         cfg.task_type)
        else:
            with faults.injected(plan):
                res = run_coordinate_descent(coords, ["fixed"], 2, ds,
                                             cfg.task_type)
        return res, coords["fixed"]._stream.stats.snapshot()

    ref, _ = run(None)
    plan = faults.FaultPlan([{"site": "stage.fetch", "action": "transient",
                              "hits": [1, 4]}])
    faulted, stats = run(plan)
    assert stats["retries"] == 2 and stats["gave_up"] == 0
    np.testing.assert_array_equal(ref.objective_history,
                                  faulted.objective_history)


# --------------------------------------------------------------------------
# non-finite solve quarantine
# --------------------------------------------------------------------------

def test_guard_rolls_back_nonfinite_coefficients():
    from photon_ml_tpu.game import quarantine
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import FixedEffectModel
    from photon_ml_tpu.models.glm import model_for_task
    import jax.numpy as jnp
    good = FixedEffectModel(model_for_task(
        "logistic_regression", Coefficients(jnp.asarray([1.0, 2.0]))), "g")
    bad = FixedEffectModel(model_for_task(
        "logistic_regression",
        Coefficients(jnp.asarray([jnp.nan, 3.0]))), "g")
    guarded, flag = quarantine.guard(bad, good)
    assert not bool(flag)
    np.testing.assert_array_equal(
        np.asarray(guarded.glm.coefficients.means), [1.0, 2.0])
    # healthy solve passes through bitwise
    guarded2, flag2 = quarantine.guard(good, bad)
    assert bool(flag2)
    np.testing.assert_array_equal(
        np.asarray(guarded2.glm.coefficients.means), [1.0, 2.0])


@pytest.mark.parametrize("timing_mode", ["pipelined", "strict"])
def test_poisoned_solve_quarantined_and_recovered(rng, timing_mode):
    """One poisoned solve: the device-side guard rolls it back (history
    stays finite), the tightened-budget retry recovers, and the fit lands
    on the fault-free trajectory."""
    ds = _glmix(rng)
    ref = GameEstimator(_config(4)).fit(ds, timing_mode=timing_mode)
    plan = faults.FaultPlan([
        {"site": "solve.poison", "action": "poison", "hits": [2],
         "match": {"coordinate": "perUser"}}])
    with faults.injected(plan):
        poisoned = GameEstimator(_config(4)).fit(ds, timing_mode=timing_mode)
    assert plan.report()["total_fired"] == 1
    hist = poisoned.objective_history
    assert len(hist) == len(ref.objective_history)
    assert np.all(np.isfinite(hist))
    actions = [e["action"] for e in poisoned.descent.containment_events]
    assert actions == ["rolled_back", "retry_ok"]
    assert poisoned.descent.frozen_coordinates == []
    # recovered: final objective back on the fault-free trajectory
    rel = abs(hist[-1] - ref.objective_history[-1]) \
        / abs(ref.objective_history[-1])
    assert rel < 1e-4
    diag = poisoned.descent.solver_diagnostics()
    assert diag["perUser"]["containment"] == {"retry_ok": 1}


def test_repeated_divergence_freezes_coordinate(rng):
    """Two strikes: a coordinate that diverges again after a successful
    quarantine retry is frozen for the rest of the fit while the other
    coordinate keeps descending."""
    ds = _glmix(rng)
    plan = faults.FaultPlan([
        {"site": "solve.poison", "action": "poison", "hits": [2, 3, 4, 5],
         "match": {"coordinate": "perUser"}}])
    with faults.injected(plan):
        res = GameEstimator(_config(5)).fit(ds)
    assert res.descent.frozen_coordinates == ["perUser"]
    assert np.all(np.isfinite(res.objective_history))
    assert len(res.objective_history) == 10  # canonical length kept
    actions = [e["action"] for e in res.descent.containment_events]
    assert "frozen" in actions
    diag = res.descent.solver_diagnostics()
    assert diag["perUser"]["containment"].get("frozen", 0) >= 1
    # the OTHER coordinate kept making progress after the freeze
    assert res.objective_history[-1] < res.objective_history[1]


class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _compile_counting:
    def __enter__(self):
        import jax
        self._jax = jax
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        self._jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_health_flag_adds_zero_traces_to_warm_fit(rng):
    """ISSUE 5 satellite: the per-update health flag + where-guard are
    module-level jits — a warm fit (same shapes) compiles NOTHING new."""
    ds = _glmix(rng)
    GameEstimator(_config(1)).fit(ds)  # warmup traces everything
    with _compile_counting() as counter:
        GameEstimator(_config(3)).fit(ds)
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles after warmup — the health "
        "flag / rollback guard broke the trace cache")


# --------------------------------------------------------------------------
# crash-safe checkpoints: manifests, fallback, pruning
# --------------------------------------------------------------------------

def test_checkpoint_dirs_carry_verifying_manifests(rng, tmp_path):
    ds = _glmix(rng)
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(2)).fit(ds, checkpoint_dir=ckpt)
    iter_dirs = sorted(p for p in os.listdir(ckpt) if p.startswith("iter-"))
    assert iter_dirs
    for d in iter_dirs:
        ok, reason = verify_checkpoint_dir(os.path.join(ckpt, d))
        assert ok is True, reason
        record = json.load(open(os.path.join(ckpt, d, "record.json")))
        assert record["model_dir"] == d  # self-contained, relative
    state = json.load(open(os.path.join(ckpt, "state.json")))
    assert state["completed_iterations"] == 2


def test_corrupt_primary_falls_back_to_verified_record(rng, tmp_path):
    """Torn/corrupt newest record -> resume from the RETAINED previous
    verified record, with the corrupt directory pruned."""
    import glob
    ds = _glmix(rng)
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(3, coords=("fixed",))).fit(ds, checkpoint_dir=ckpt)
    newest = sorted(glob.glob(os.path.join(ckpt, "iter-*")))[-1]
    npz = glob.glob(os.path.join(newest, "**", "*.npz"), recursive=True)[0]
    with open(npz, "wb") as f:
        f.write(b"torn write")
    state = read_checkpoint(ckpt)
    assert state is not None
    assert state.recovery["fallback"] is True
    assert state.completed_iterations >= 1
    assert not os.path.exists(newest)  # corrupt record pruned
    # and the resumed fit completes + matches the straight run's tail
    resumed = GameEstimator(_config(3, coords=("fixed",))).fit(
        ds, checkpoint_dir=ckpt)
    straight = GameEstimator(_config(3, coords=("fixed",))).fit(ds)
    np.testing.assert_allclose(resumed.objective_history,
                               straight.objective_history, rtol=0,
                               atol=1e-9)


def test_stale_tmp_and_orphan_partials_pruned(rng, tmp_path):
    ds = _glmix(rng)
    ckpt = str(tmp_path / "ckpt")
    GameEstimator(_config(2, coords=("fixed",))).fit(ds, checkpoint_dir=ckpt)
    (tmp_path / "ckpt" / "state.json.tmp").write_text("partial")
    orphan = tmp_path / "ckpt" / "iter-0099"
    orphan.mkdir()
    (orphan / "half.npz").write_bytes(b"partial write")
    state = read_checkpoint(ckpt)
    assert state is not None and state.recovery["fallback"] is False
    assert not (tmp_path / "ckpt" / "state.json.tmp").exists()
    assert not orphan.exists()
    assert len(state.recovery["pruned"]) == 2


def test_fallback_record_respects_fingerprint(rng, tmp_path):
    ds = _glmix(rng)
    ckpt = str(tmp_path / "ckpt")
    est = GameEstimator(_config(2, coords=("fixed",)))
    est.fit(ds, checkpoint_dir=ckpt)
    os.remove(os.path.join(ckpt, "state.json"))  # force the fallback path
    assert read_checkpoint(ckpt, fingerprint="different") is None
    good = read_checkpoint(
        ckpt, fingerprint=est._config_fingerprint(None))
    assert good is not None and good.recovery["fallback"] is True


def test_async_checkpointer_final_record_failure_surfaces(rng, tmp_path):
    """ISSUE 5 satellite: a failed fit-end durable record raises
    immediately at fit end with the ORIGINAL exception as __cause__."""
    ds = _glmix(rng)
    plan = faults.FaultPlan([{"site": "model.save", "action": "fatal",
                              "hits": [1]}])
    with faults.injected(plan):
        with pytest.raises(RuntimeError,
                           match="final fit-end record") as err:
            GameEstimator(_config(1)).fit(
                ds, checkpoint_dir=str(tmp_path / "ckpt"),
                timing_mode="pipelined")
    assert isinstance(err.value.__cause__, faults.FatalFault)


# --------------------------------------------------------------------------
# graceful preemption
# --------------------------------------------------------------------------

def test_preemption_writes_durable_checkpoint_and_resumes(rng, tmp_path):
    """A preemption request stops the fit AFTER the in-flight update with
    a durable record; clearing the flag and re-running reproduces the
    uninterrupted trajectory."""
    ds = _glmix(rng)
    straight = GameEstimator(_config(3, coords=("fixed",))).fit(ds)
    ckpt = str(tmp_path / "ckpt")
    faults.request_preemption()
    with pytest.raises(faults.Preempted) as err:
        GameEstimator(_config(3, coords=("fixed",))).fit(
            ds, checkpoint_dir=ckpt)
    assert err.value.completed_iterations == 1
    assert err.value.checkpointed is True
    faults.clear_preemption()
    state = read_checkpoint(ckpt)
    assert state is not None and state.completed_iterations == 1
    resumed = GameEstimator(_config(3, coords=("fixed",))).fit(
        ds, checkpoint_dir=ckpt)
    np.testing.assert_allclose(resumed.objective_history,
                               straight.objective_history, rtol=0,
                               atol=1e-9)


def test_preemption_mid_iteration_finishes_inflight_update(rng):
    """Multi-coordinate fit: the preemption lands after the FIRST
    coordinate's update of the iteration (finished, not aborted)."""
    ds = _glmix(rng)
    faults.request_preemption()
    with pytest.raises(faults.Preempted) as err:
        GameEstimator(_config(3)).fit(ds)
    # no checkpoint dir -> not resumable, but the update still finished
    assert err.value.completed_iterations == 0
    assert err.value.checkpointed is False


def test_sigterm_handler_sets_flag_then_escalates():
    import signal
    with faults.GracefulPreemption():
        assert not faults.preemption_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert faults.preemption_requested()
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGTERM)
    assert not faults.preemption_requested()  # cleared on exit


def test_exit_preempted_is_distinct():
    assert faults.EXIT_PREEMPTED == 75  # EX_TEMPFAIL: retry the job


# --------------------------------------------------------------------------
# kill-during-checkpoint crash test (satellite: subprocess SIGKILL at the
# injected fsync site -> resume from last verified record -> fault-free
# f64 trajectory)
# --------------------------------------------------------------------------

def _run_child(tmp_path, ckpt=None, plan=None, expect_kill=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1",
               PHOTON_JAX_CACHE=str(tmp_path / "jaxcache"))
    env.pop("XLA_FLAGS", None)
    env.pop("PHOTON_FAULT_PLAN", None)
    if plan is not None:
        env["PHOTON_FAULT_PLAN"] = json.dumps(plan)
    cmd = [sys.executable, os.path.join(_REPO, "bench.py"), "--faults-child",
           "--n", "700", "--outer", "3", "--iters", "6", "--seed", "31",
           "--timing-mode", "strict"]
    if ckpt:
        cmd += ["--ckpt", ckpt]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=420, cwd=_REPO)
    if expect_kill:
        assert p.returncode not in (0, 1), (p.returncode, p.stderr[-500:])
        return p.returncode
    assert p.returncode == 0, p.stderr[-800:]
    return json.loads(p.stdout.strip().splitlines()[-1])


def test_sigkill_during_checkpoint_then_resume_reproduces_f64(tmp_path):
    ref = _run_child(tmp_path)
    ckpt = str(tmp_path / "ckpt")
    plan = {"seed": 0, "faults": [
        {"site": "checkpoint.fsync", "action": "kill", "hits": [2]}]}
    rc = _run_child(tmp_path, ckpt=ckpt, plan=plan, expect_kill=True)
    assert rc == -9  # SIGKILL mid-fsync
    # the torn write left a stale tmp; the sealed-but-unreferenced record
    # and the previous verified record are both on disk
    assert os.path.exists(os.path.join(ckpt, "state.json.tmp"))
    resumed = _run_child(tmp_path, ckpt=ckpt)
    recovery = resumed["checkpoint_recovery"]
    assert recovery is not None
    assert any(p.endswith("state.json.tmp") for p in recovery["pruned"])
    # resume reproduced the fault-free float64 trajectory exactly
    np.testing.assert_allclose(resumed["objective_history"],
                               ref["objective_history"], rtol=0, atol=1e-9)
