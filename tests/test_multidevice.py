"""The full product surface on the 8-device mesh, as a pytest.

VERDICT r2 item 8: the dryrun logic (GameEstimator.fit with fixed + random +
factored coordinates over a real Mesh) must live in the test suite with real
assertions — per-update objective decrease, and distributed == single-device
parity.  This is the "Spark local mode exercises all distributed paths"
posture of the reference's sparkTest fixture
(photon-test-utils/.../test/SparkTestUtils.scala:31-77) on the virtual
8-device CPU mesh from conftest.py.
"""
import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FactoredRandomEffectCoordinateConfig, FixedEffectCoordinateConfig,
    GameEstimator, GameTrainingConfig, GLMOptimizationConfig,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, RegularizationType,
)
from photon_ml_tpu.parallel import make_mesh

L2 = RegularizationContext(RegularizationType.L2)


def _glmix_logistic(rng, n=1600, d_global=8, num_users=40, d_user=5):
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    w_g = rng.normal(size=d_global)
    w_u = rng.normal(size=(num_users, d_user))
    z = xg @ w_g + np.einsum("nd,nd->n", xu, w_u[users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ids = np.asarray([f"u{u:03d}" for u in users])
    return build_game_dataset(y, {"global": xg, "per_user": xu},
                              entity_ids={"userId": ids})


def _full_config(outer=2):
    opt = lambda w: GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=25),
        regularization=L2, regularization_weight=w)
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", opt(0.1)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", opt(1.0)),
            "perUserMF": FactoredRandomEffectCoordinateConfig(
                "userId", "per_user", latent_dim=3,
                optimization=opt(1.0), latent_optimization=opt(1.0)),
        },
        updating_sequence=["fixed", "perUser", "perUserMF"],
        num_outer_iterations=outer)


@pytest.fixture(scope="module")
def glmix_splits():
    rng = np.random.default_rng(5)
    ds = _glmix_logistic(rng)
    rows = np.arange(ds.num_rows)
    return ds.subset(rows[:1200]), ds.subset(rows[1200:])


def test_full_surface_on_mesh(glmix_splits):
    """FE + RE + factored coordinates + grouped validation on 8 devices."""
    train, val = glmix_splits
    mesh = make_mesh()
    assert int(np.prod(list(mesh.shape.values()))) == 8
    res = GameEstimator(_full_config()).fit(
        train, val, evaluator_specs=["AUC", "AUC:userId"])
    hist = res.objective_history
    assert len(hist) == 2 * 3
    # every coordinate update must not increase the total objective
    drops = np.diff(np.asarray(hist))
    assert (drops <= 1e-6 * np.abs(np.asarray(hist[:-1]))).all(), hist
    assert res.validation["AUC"] > 0.75
    # grouped per-user AUC exists and is a sane probability-ranking score
    assert 0.4 < res.validation["AUC:userId"] <= 1.0


def test_mesh_matches_single_device(glmix_splits):
    """GSPMD sharding must not change the math: same fit on the mesh and on
    one device, objective histories and validation metrics equal to
    tolerance (reference posture: distributed == local, e.g.
    DistributedObjectiveFunctionTest vs SingleNodeObjectiveFunctionTest)."""
    train, val = glmix_splits
    cfg = _full_config()
    res_mesh = GameEstimator(cfg, mesh=make_mesh()).fit(train, val)
    res_one = GameEstimator(cfg, mesh=None).fit(train, val)
    np.testing.assert_allclose(res_mesh.objective_history,
                               res_one.objective_history,
                               rtol=1e-6, atol=1e-8)
    assert abs(res_mesh.validation["AUC"] - res_one.validation["AUC"]) < 1e-6


def test_feature_sharded_fixed_effect_on_mesh(glmix_splits):
    """--mesh 4x2 regime: coefficients sharded over the feature axis must
    reproduce the data-parallel result (VERDICT r2 item 4: shard_features
    as a product path, auto-enabled by a 2-wide feature axis)."""
    train, val = glmix_splits
    cfg = _full_config()
    res_42 = GameEstimator(cfg, mesh=make_mesh(4, 2)).fit(train, val)
    res_8 = GameEstimator(cfg, mesh=make_mesh()).fit(train, val)
    np.testing.assert_allclose(res_42.objective_history,
                               res_8.objective_history,
                               rtol=1e-6, atol=1e-8)
