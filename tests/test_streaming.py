"""Out-of-core GAME training (ISSUE 3): chunk planning, double-buffered
prefetch, ChunkedGLMObjective oracle parity, host-stepped solver parity,
HBM-budgeted fits (streamed FE + eviction rotation), peak-memory
accounting, and the compile-count regression across chunk counts.

Parity contract: the streamed oracle computes each chunk with the SAME
fused aggregators the resident path runs on that (padded, masked) row
range, accumulated in chunk order — so it matches a chunk-wise resident
evaluation bit-for-bit, and a full streamed fit matches the resident fit
to ~1e-6 relative objective (float summation order is the only residual;
in this suite's float64 it is typically exact).
"""
import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.data.batching import (
    RandomEffectDataConfig, build_random_effect_dataset,
)
from photon_ml_tpu.data.streaming import (
    ChunkPlan, Prefetcher, StreamStats, MIN_CHUNK_ROWS,
)
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig, RandomEffectCoordinateConfig,
)
from photon_ml_tpu.models.io import save_game_model
from photon_ml_tpu.ops import ChunkedGLMObjective, GLMObjective, TASK_LOSSES
from photon_ml_tpu.optim import (
    OptimizerConfig, OptimizerType, RegularizationContext, RegularizationType,
    solve, solve_streamed,
)

L2 = RegularizationContext(RegularizationType.L2)
LOGISTIC = TASK_LOSSES["logistic_regression"]


# --------------------------------------------------------------------------
# ChunkPlan
# --------------------------------------------------------------------------

def test_chunk_plan_pow2_and_coverage():
    plan = ChunkPlan.build(10_000, chunk_rows=1000)   # rounds up to 1024
    assert plan.chunk_rows == 1024
    assert sum(c.rows for c in plan.chunks) == 10_000
    assert plan.chunks[0].start == 0 and plan.chunks[-1].stop == 10_000
    for c in plan.chunks:
        assert c.padded_rows & (c.padded_rows - 1) == 0   # pow2
        assert c.padded_rows >= c.rows
    # one program per chunk SHAPE: full shape + at most one tail shape
    assert len(plan.chunk_shapes) <= 2


def test_chunk_plan_budget_sizing():
    # two chunks must fit in the budget
    plan = ChunkPlan.build(1_000_000, hbm_budget_bytes=8 << 20,
                           bytes_per_row=1024)
    assert 2 * plan.chunk_rows * 1024 <= 8 << 20
    # a budget larger than the data degenerates to one chunk == resident
    small = ChunkPlan.build(500, hbm_budget_bytes=1 << 30, bytes_per_row=8)
    assert small.num_chunks == 1
    assert small.chunks[0].padded_rows == 512


def test_chunk_plan_floor():
    plan = ChunkPlan.build(100_000, hbm_budget_bytes=10, bytes_per_row=1024)
    assert plan.chunk_rows == MIN_CHUNK_ROWS  # dispatch-overhead floor


# --------------------------------------------------------------------------
# Prefetcher: double buffer bound + ordering + error propagation
# --------------------------------------------------------------------------

def test_prefetcher_bounded_double_buffer():
    plan = ChunkPlan.build(4096, chunk_rows=256)
    stats = StreamStats()
    fetched = []
    pf = Prefetcher(plan, lambda spec: {"v": np.full(spec.padded_rows,
                                                     spec.index, np.float64)},
                    depth=2, stats=stats)
    for _ in range(3):  # several passes over the same plan
        order = [spec.index for spec, _ in pf.stream()]
        assert order == list(range(plan.num_chunks))
    snap = stats.snapshot()
    assert snap["passes"] == 3
    assert snap["chunks_staged"] == 3 * plan.num_chunks
    # the double-buffer invariant: never more than `depth` chunks resident
    assert snap["peak_resident_chunks"] <= 2
    assert snap["total_bytes"] == 3 * plan.num_chunks * 256 * 8
    del fetched


def test_prefetcher_error_propagates():
    plan = ChunkPlan.build(2048, chunk_rows=256)

    def bad_fetch(spec):
        if spec.index == 3:
            raise OSError("host read failed")
        return {"v": np.zeros(spec.padded_rows)}

    with pytest.raises(RuntimeError, match="chunk staging failed"):
        list(Prefetcher(plan, bad_fetch).stream())


# --------------------------------------------------------------------------
# ChunkedGLMObjective: oracle parity
# --------------------------------------------------------------------------

def _problem(rng, n=3000, d=10):
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-x @ w))).astype(float)
    weights = rng.uniform(0.5, 1.5, size=n)
    offsets = rng.normal(size=n) * 0.1
    return x, y, weights, offsets


def test_chunked_oracle_bitwise_given_same_chunking(rng):
    """The streamed oracle == a chunk-wise resident evaluation (same padded
    chunks, same masks) BIT-FOR-BIT, for value, gradient, and Hv."""
    x, y, w, off = _problem(rng)
    plan = ChunkPlan.build(len(y), chunk_rows=1024)
    assert plan.num_chunks == 3
    cobj = ChunkedGLMObjective(LOGISTIC, x, y, plan, weights=w, offsets=off,
                               l2_weight=0.3)
    c = jnp.asarray(rng.normal(size=x.shape[1]))
    v_c, g_c = cobj.value_and_gradient(c)
    hv_c = cobj.hessian_vector(c, 0.5 * c)

    # manual chunk-wise resident evaluation through GLMObjective on the
    # SAME padded+masked row ranges, accumulated in the same order
    acc_v = jnp.zeros(())
    acc_g = jnp.zeros_like(c)
    acc_hv = jnp.zeros_like(c)
    for spec in plan.chunks:
        sl = slice(spec.start, spec.stop)
        pad = spec.padded_rows - spec.rows
        pd = lambda a, fill: np.concatenate(
            [a[sl], np.full((pad,) + a.shape[1:], fill, a.dtype)])
        mask = np.concatenate([np.ones(spec.rows), np.zeros(pad)])
        o = GLMObjective(LOGISTIC, jnp.asarray(pd(x, 0.0)),
                         jnp.asarray(pd(y, 0.5)),
                         weights=jnp.asarray(pd(w, 0.0)),
                         offsets=jnp.asarray(pd(off, 0.0)),
                         mask=jnp.asarray(mask))
        v_i, g_i = o.value_and_gradient(c)
        acc_v = acc_v + v_i
        acc_g = acc_g + g_i
        acc_hv = acc_hv + o.hessian_vector(c, 0.5 * c)
    acc_v = acc_v + 0.5 * 0.3 * jnp.dot(c, c)
    acc_g = acc_g + 0.3 * c
    acc_hv = acc_hv + 0.3 * (0.5 * c)

    assert float(v_c) == float(acc_v)
    np.testing.assert_array_equal(np.asarray(g_c), np.asarray(acc_g))
    np.testing.assert_array_equal(np.asarray(hv_c), np.asarray(acc_hv))


def test_chunked_oracle_close_to_resident_single_sum(rng):
    """vs the resident single-sum oracle only float summation order
    differs (~1e-12 relative in f64)."""
    x, y, w, off = _problem(rng)
    plan = ChunkPlan.build(len(y), chunk_rows=512)
    cobj = ChunkedGLMObjective(LOGISTIC, x, y, plan, weights=w, offsets=off,
                               l2_weight=0.3)
    robj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y),
                        weights=jnp.asarray(w), offsets=jnp.asarray(off),
                        l2_weight=0.3)
    c = jnp.asarray(rng.normal(size=x.shape[1]))
    v_c, g_c = cobj.value_and_gradient(c)
    v_r, g_r = robj.value_and_gradient(c)
    np.testing.assert_allclose(float(v_c), float(v_r), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_r), rtol=1e-9,
                               atol=1e-12)
    # streamed scoring == resident matvec
    np.testing.assert_allclose(np.asarray(cobj.scores(c)),
                               np.asarray(jnp.asarray(x) @ c), rtol=1e-12)


def test_chunked_rejects_sparse(rng):
    import scipy.sparse as sp
    x = sp.random(100, 20, density=0.1, format="csr", random_state=0)
    with pytest.raises(TypeError, match="dense host feature block"):
        ChunkedGLMObjective(LOGISTIC, x, np.zeros(100),
                            ChunkPlan.build(100, chunk_rows=256))


# --------------------------------------------------------------------------
# host-stepped solvers: parity with the resident lax.while_loop solvers
# --------------------------------------------------------------------------

@pytest.mark.parametrize("opt,reg,weight", [
    (OptimizerConfig(max_iterations=100, tolerance=1e-9), L2, 1.0),
    (OptimizerConfig(optimizer=OptimizerType.TRON, max_iterations=30,
                     tolerance=1e-9), L2, 1.0),
    (OptimizerConfig(max_iterations=150, tolerance=1e-10),
     RegularizationContext(RegularizationType.ELASTIC_NET,
                           elastic_net_alpha=0.5), 0.1),
])
def test_solve_streamed_matches_resident(rng, opt, reg, weight):
    x, y, _, _ = _problem(rng)
    d = x.shape[1]
    plan = ChunkPlan.build(len(y), chunk_rows=1024)
    cobj = ChunkedGLMObjective(LOGISTIC, x, y, plan)
    robj = GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y))
    rs = solve(robj, jnp.zeros(d), opt, reg, weight)
    ss = solve_streamed(cobj, jnp.zeros(d), opt, reg, weight)
    # identical iteration trajectory in f64 (same algorithm, same
    # constants; the streamed oracle differs only by summation order)
    assert int(ss.iterations) == int(rs.iterations)
    np.testing.assert_allclose(float(ss.value), float(rs.value), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(ss.x), np.asarray(rs.x),
                               rtol=1e-6, atol=1e-9)
    if rs.fg_count is not None:
        assert int(ss.fg_count) == int(rs.fg_count)
    if rs.hv_count is not None:
        assert int(ss.hv_count) == int(rs.hv_count)


def test_solve_streamed_box_constraints(rng):
    x, y, _, _ = _problem(rng, n=2000, d=6)
    d = x.shape[1]
    cfg = OptimizerConfig(max_iterations=100, tolerance=1e-9,
                          box_lower=(-0.2,) * d, box_upper=(0.2,) * d)
    plan = ChunkPlan.build(len(y), chunk_rows=1024)
    ss = solve_streamed(ChunkedGLMObjective(LOGISTIC, x, y, plan),
                        jnp.zeros(d), cfg, L2, 1.0)
    rs = solve(GLMObjective(LOGISTIC, jnp.asarray(x), jnp.asarray(y)),
               jnp.zeros(d), cfg, L2, 1.0)
    assert float(jnp.max(jnp.abs(ss.x))) <= 0.2 + 1e-12
    np.testing.assert_allclose(float(ss.value), float(rs.value), rtol=1e-9)


# --------------------------------------------------------------------------
# full GAME fit: streamed vs resident parity, determinism, peak memory
# --------------------------------------------------------------------------

def _glmix(rng, n=4000, d_global=12, num_users=80, d_user=4):
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    z = xg @ rng.normal(size=d_global) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(num_users, d_user))[users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ds = build_game_dataset(y, {"global": xg, "per_user": xu},
                            entity_ids={"userId": np.asarray(
                                [f"u{u:03d}" for u in users])})
    rows = np.arange(n)
    return ds.subset(rows[: int(n * 0.9)]), ds.subset(rows[int(n * 0.9):])


def _config(iters=3, budget=None, chunk_rows=None, memory_mode="auto"):
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=0.1),
                memory_mode=memory_mode, chunk_rows=chunk_rows),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=iters,
        hbm_budget_bytes=budget)


def _fe_shard_bytes(train):
    x = train.feature_shards["global"]
    itemsize = np.dtype(jax.dtypes.canonicalize_dtype(x.dtype)).itemsize
    return x.shape[0] * x.shape[1] * itemsize


def test_streamed_fit_parity_and_determinism(rng, tmp_path):
    """Strict parity gate (ISSUE 3): streamed objective history matches
    resident to ~1e-6 relative (exact here in f64), final models within
    gate, and the same chunking gives an IDENTICAL history on a re-run."""
    train, val = _glmix(rng)
    resident = GameEstimator(_config()).fit(train, val)
    # budget below the FE shard -> auto-streams; below total -> rotation
    budget = int(_fe_shard_bytes(train) * 0.6)
    streamed = GameEstimator(_config(budget=budget)).fit(train, val)
    assert len(streamed.objective_history) == len(resident.objective_history)
    np.testing.assert_allclose(streamed.objective_history,
                               resident.objective_history, rtol=1e-6)
    # streamed mode actually engaged
    acct = streamed.residency
    assert acct["streamed_chunk_bytes"], "FE coordinate did not stream"
    # final models within gate (every persisted array)
    save_game_model(resident.descent.model, str(tmp_path / "r"))
    save_game_model(streamed.descent.model, str(tmp_path / "s"))
    import glob
    files_r = sorted(glob.glob(str(tmp_path / "r" / "**" / "*.npz"),
                               recursive=True))
    for fr in files_r:
        fs = fr.replace(str(tmp_path / "r"), str(tmp_path / "s"))
        with np.load(fr, allow_pickle=True) as zr, \
                np.load(fs, allow_pickle=True) as zs:
            for k in zr.files:
                if zr[k].dtype == object:
                    assert np.array_equal(zr[k], zs[k]), (fr, k)
                else:
                    np.testing.assert_allclose(zr[k], zs[k], rtol=1e-6,
                                               atol=1e-8, err_msg=f"{fr}:{k}")

    # same chunking => identical objective history (bit-for-bit determinism)
    streamed2 = GameEstimator(_config(budget=budget)).fit(train, val)
    assert streamed.objective_history == streamed2.objective_history


def test_streamed_fit_peak_memory_under_budget(rng):
    """The acceptance accounting: the streamed fit trains a config whose
    coordinate data EXCEEDS the budget, while tracked peak residency stays
    UNDER it — and the prefetcher held at most 2 chunks at once."""
    train, val = _glmix(rng, n=6000, num_users=120)
    resident = GameEstimator(_config(iters=2)).fit(train, val)
    r_acct = resident.residency
    data_bytes = (r_acct["resident_block_total"]
                  + r_acct["flat_vector_bytes"])
    floor = (max(r_acct["resident_block_bytes"].values())
             + r_acct["flat_vector_bytes"])
    budget = max(int(data_bytes * 0.6), int(floor * 1.05))
    assert budget < data_bytes, "test shape cannot demonstrate out-of-core"

    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
    est = GameEstimator(_config(iters=2, budget=budget))
    coords = est._build_coordinates(train)
    fe = coords["fixed"]
    assert fe.streamed
    manager = est._residency_manager(coords, train)
    run_coordinate_descent(coords, est.config.updating_sequence, 2, train,
                           est.config.task_type, residency=manager)
    acct = manager.accounting()
    assert acct["budget_bytes"] == budget
    # impossible before this PR: total coordinate data > budget...
    assert data_bytes > budget
    # ...while the fit never held more than the budget resident
    assert acct["under_budget"], acct
    assert acct["peak_tracked_bytes"] <= budget
    # the double buffer held <= 2 chunks at any moment
    snap = fe._stream.stats.snapshot()
    assert snap["passes"] > 0
    assert snap["peak_resident_chunks"] <= 2
    # two chunks of the plan fit the coordinate's budget share
    assert fe.streaming_buffer_bytes() <= budget


def test_memory_mode_forced_and_validated(rng):
    train, val = _glmix(rng, n=2000, num_users=40)
    # explicit streamed without any budget
    forced = GameEstimator(_config(iters=1, memory_mode="streamed",
                                   chunk_rows=512)).fit(train, val)
    assert forced.residency["streamed_chunk_bytes"]
    # explicit resident under a tiny budget: no streaming, rotation only
    budget = int(_fe_shard_bytes(train) * 0.8)
    res = GameEstimator(_config(iters=1, budget=budget,
                                memory_mode="resident")).fit(train, val)
    assert not res.residency["streamed_chunk_bytes"]
    with pytest.raises(ValueError, match="memory_mode"):
        FixedEffectCoordinateConfig("global", memory_mode="sometimes")


def test_config_round_trip_memory_fields():
    cfg = _config(budget=123_456, chunk_rows=2048, memory_mode="streamed")
    back = GameTrainingConfig.from_json(cfg.to_json())
    assert back.hbm_budget_bytes == 123_456
    fe = back.coordinates["fixed"]
    assert fe.memory_mode == "streamed" and fe.chunk_rows == 2048
    # "auto" encodes as absent so pre-existing checkpoint fingerprints
    # (estimator strips None-valued keys) stay valid
    d = _config().to_dict()
    assert d["coordinates"]["fixed"]["memory_mode"] is None


# --------------------------------------------------------------------------
# compile-count regression: zero fresh traces across chunk COUNTS
# --------------------------------------------------------------------------

class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _compile_counting:
    def __enter__(self):
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_zero_new_traces_across_chunk_counts(rng):
    """ISSUE 3 satellite (mirroring tests/test_pipeline.py's warm-fit
    tracker): every compiled program in the chunked solve path is keyed on
    the CHUNK shape, never the row count — so a dataset 1.5x larger with
    the same chunk shape must not trace a single new program, for LBFGS
    and TRON, oracle and scoring."""
    d, C = 8, 512

    def make(n, seed):
        x = rng.normal(size=(n, d)); x[:, -1] = 1.0
        y = (rng.uniform(size=n) < 0.5).astype(float)
        return ChunkedGLMObjective(LOGISTIC, x, y,
                                   ChunkPlan.build(n, chunk_rows=C))

    lbfgs_cfg = OptimizerConfig(max_iterations=8, tolerance=1e-9)
    tron_cfg = OptimizerConfig(optimizer=OptimizerType.TRON,
                               max_iterations=5, tolerance=1e-9)
    warm = make(2 * C, 0)        # 2 chunks: warm every program
    for cfg in (lbfgs_cfg, tron_cfg):
        solve_streamed(warm, jnp.zeros(d), cfg, L2, 1.0)
    warm.scores(jnp.zeros(d))

    bigger = make(3 * C, 1)      # 3 chunks, SAME chunk shape
    with _compile_counting() as counter:
        for cfg in (lbfgs_cfg, tron_cfg):
            solve_streamed(bigger, jnp.zeros(d), cfg, L2, 1.0)
        bigger.scores(jnp.zeros(d))
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles across differing chunk counts "
        "of the same chunk shape — a program keyed on the row count crept "
        "into the streamed solve path")


# --------------------------------------------------------------------------
# eviction / re-stream + release_host_shards
# --------------------------------------------------------------------------

def test_entity_bucket_evict_and_restream(rng):
    train, _ = _glmix(rng, n=1500, num_users=50)
    cfg = RandomEffectDataConfig("userId", "per_user", keep_host_blocks=True)
    red = build_random_effect_dataset(train, cfg)
    for b in red.buckets:
        b.blocks  # materialize every bucket's device copy
    first = np.asarray(red.buckets[0].blocks.x)
    assert red.device_bytes() > 0
    assert all(b.is_resident for b in red.buckets)
    red.evict_device_blocks()
    assert not any(b.is_resident for b in red.buckets)
    # re-stream gives back the same values
    np.testing.assert_array_equal(np.asarray(red.buckets[0].blocks.x), first)
    # without host copies, evict is a safe no-op
    red2 = build_random_effect_dataset(
        train, RandomEffectDataConfig("userId", "per_user"))
    assert all(b.host_blocks is None for b in red2.buckets)
    red2.evict_device_blocks()
    assert all(b.is_resident for b in red2.buckets)


def test_coordinate_evict_restream_same_result(rng):
    """An evicted coordinate's next update/score re-streams from host and
    produces bit-identical results."""
    train, val = _glmix(rng, n=1500, num_users=50)
    budget = int(_fe_shard_bytes(train) * 10)  # roomy: accounting only
    est = GameEstimator(_config(iters=1, budget=budget))
    coords = est._build_coordinates(train)
    re = coords["perUser"]
    model = re.initial_model()
    offsets = jnp.zeros(train.num_rows)
    m1, _ = re.update(model, offsets)
    s1 = np.asarray(re.score(m1))
    re.evict_device_blocks()
    m2, _ = re.update(model, offsets)
    s2 = np.asarray(re.score(m2))
    np.testing.assert_array_equal(np.asarray(m1.coefficients),
                                  np.asarray(m2.coefficients))
    np.testing.assert_array_equal(s1, s2)


def test_release_host_shards(rng):
    from photon_ml_tpu.data.game_data import ReleasedHostShard
    train, _ = _glmix(rng, n=500, num_users=10)
    with pytest.raises(ValueError, match="no device copy"):
        train.release_host_shard("global")
    dev = train.device_shard("global", release_host=True)
    assert isinstance(train.feature_shards["global"], ReleasedHostShard)
    # metadata (shard_dim) survives; the cached device copy is returned
    assert train.shard_dim("global") == 12
    assert train.device_shard("global") is dev
    # array reads fail loudly, and a dropped device copy is unrecoverable
    with pytest.raises(ValueError, match="released"):
        np.asarray(train.feature_shards["global"])
    train.release_device_shard("global")
    with pytest.raises(ValueError, match="released"):
        train.device_shard("global")


def test_parse_byte_size():
    from photon_ml_tpu.cli.train import parse_byte_size
    assert parse_byte_size("8GB") == 8_000_000_000
    assert parse_byte_size("512mb") == 512_000_000
    assert parse_byte_size("1.5g") == 1_500_000_000
    assert parse_byte_size("4096") == 4096
    assert parse_byte_size(None) is None
    with pytest.raises(SystemExit):
        parse_byte_size("eight gigs")
    with pytest.raises(SystemExit):
        parse_byte_size("-1GB")
