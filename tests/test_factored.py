"""Factored random effects + matrix factorization.

Mirrors the reference's FactoredRandomEffectCoordinate / MatrixFactorization
integration tests: a GAME fit with a factored coordinate must beat a
fixed-effect-only model on synthetic low-rank mixed data, the alternation
must decrease the objective, save->load->score must round-trip, and the
KroneckerDesign implicit feature matrix must agree with the materialized
Kronecker product.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import build_game_dataset
from photon_ml_tpu.game import (
    FactoredRandomEffectCoordinateConfig, FixedEffectCoordinateConfig,
    GameEstimator, GameTrainingConfig, GLMOptimizationConfig,
)
from photon_ml_tpu.models import (
    FactoredRandomEffectModel, MatrixFactorizationModel,
)
from photon_ml_tpu.models.io import load_game_model, save_game_model
from photon_ml_tpu.ops import GLMObjective, LOGISTIC, SQUARED, features as fops
from photon_ml_tpu.optim import RegularizationContext, RegularizationType
from photon_ml_tpu.parallel import (
    gaussian_projection_matrix, fit_factored_random_effects, project_blocks,
)
from photon_ml_tpu.parallel.random_effect import EntityBlocks

L2 = RegularizationContext(RegularizationType.L2)


def lowrank_data(rng, n=1500, d_global=6, num_users=40, d_user=12, k_true=2):
    """Global effect + per-user deviations that live on a shared rank-k_true
    subspace — the regime factored RE is built for (many entities, few
    samples each, shared structure)."""
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user))
    users = rng.integers(0, num_users, size=n)
    w_global = rng.normal(size=d_global)
    basis = rng.normal(size=(k_true, d_user))          # shared latent basis
    c_user = rng.normal(size=(num_users, k_true))      # per-user factors
    w_user = c_user @ basis
    z = xg @ w_global + np.einsum("nd,nd->n", xu, w_user[users])
    y = z + 0.1 * rng.normal(size=n)
    ids = np.asarray([f"u{u:03d}" for u in users])
    return xg, xu, ids, y


def _dataset(rng, **kw):
    xg, xu, ids, y = lowrank_data(rng, **kw)
    return build_game_dataset(y, {"global": xg, "per_user": xu},
                              entity_ids={"userId": ids})


def _factored_config(latent_dim=4, inner=2, iters=2):
    return GameTrainingConfig(
        task_type="linear_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(regularization=L2,
                                                regularization_weight=0.1)),
            "perUserMF": FactoredRandomEffectCoordinateConfig(
                random_effect_type="userId", feature_shard="per_user",
                latent_dim=latent_dim, num_inner_iterations=inner,
                optimization=GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0),
                latent_optimization=GLMOptimizationConfig(
                    regularization=L2, regularization_weight=0.1)),
        },
        updating_sequence=["fixed", "perUserMF"],
        num_outer_iterations=iters)


# -- KroneckerDesign kernel identities ---------------------------------------

def test_kronecker_design_matches_materialized(rng):
    n, d, k = 40, 5, 3
    x = jnp.asarray(rng.normal(size=(n, d)))
    c = jnp.asarray(rng.normal(size=(n, k)))
    design = fops.KroneckerDesign(x, c)
    dense = fops.densify(design)
    assert dense.shape == (n, k * d)
    v = jnp.asarray(rng.normal(size=(k * d,)))
    u = jnp.asarray(rng.normal(size=(n,)))
    np.testing.assert_allclose(fops.matvec(design, v), dense @ v, rtol=1e-5)
    np.testing.assert_allclose(fops.rmatvec(design, u), dense.T @ u, rtol=1e-5)
    np.testing.assert_allclose(fops.sq_rmatvec(design, u),
                               (dense * dense).T @ u, rtol=1e-5)


def test_kronecker_objective_gradient_finite_difference(rng):
    n, d, k = 30, 4, 2
    x = jnp.asarray(rng.normal(size=(n, d)))
    c = jnp.asarray(rng.normal(size=(n, k)))
    y = jnp.asarray((rng.uniform(size=n) > 0.5).astype(float))
    obj = GLMObjective(LOGISTIC, fops.KroneckerDesign(x, c), y)
    p = jnp.asarray(rng.normal(size=(k * d,)) * 0.1)
    v, g = obj.value_and_gradient(p)
    g_auto = jax.grad(obj.value)(p)
    np.testing.assert_allclose(g, g_auto, rtol=1e-4, atol=1e-6)


def test_gaussian_projection_matrix_properties():
    p = gaussian_projection_matrix(5, 20, keep_intercept=False, seed=3)
    assert p.shape == (5, 20)
    assert float(jnp.max(jnp.abs(p))) <= 1.0
    # std ~ 1/k (reference deliberately uses std=k not sqrt(k))
    assert float(jnp.std(p)) < 2.5 / 5
    pi = gaussian_projection_matrix(5, 20, keep_intercept=True, seed=3)
    assert pi.shape == (6, 20)
    np.testing.assert_array_equal(np.asarray(pi[-1]),
                                  np.eye(20)[-1])  # intercept selector row


# -- alternation solver -------------------------------------------------------

def test_alternation_decreases_objective(rng):
    E, S, d, k = 12, 20, 8, 3
    x = rng.normal(size=(E, S, d))
    basis = rng.normal(size=(k, d))
    c_true = rng.normal(size=(E, k))
    z = np.einsum("esd,ed->es", x, c_true @ basis)
    y = z + 0.05 * rng.normal(size=(E, S))
    blocks = EntityBlocks(x=jnp.asarray(x), labels=jnp.asarray(y),
                          mask=jnp.ones((E, S)))
    C0 = jnp.zeros((E, k))
    P0 = gaussian_projection_matrix(k, d, seed=11, dtype=jnp.float64)

    def total_loss(C, P):
        lat = project_blocks(blocks, P)
        z_hat = jnp.einsum("esk,ek->es", lat.x, C)
        return float(jnp.mean((z_hat - blocks.labels) ** 2))

    loss0 = total_loss(C0, P0)
    res1 = fit_factored_random_effects(
        blocks, SQUARED, latent_coefficients=C0, projection=P0,
        num_inner_iterations=1, re_reg=L2, re_reg_weight=1e-3,
        latent_reg=L2, latent_reg_weight=1e-3)
    loss1 = total_loss(res1.latent_coefficients, res1.projection)
    res3 = fit_factored_random_effects(
        blocks, SQUARED, latent_coefficients=C0, projection=P0,
        num_inner_iterations=3, re_reg=L2, re_reg_weight=1e-3,
        latent_reg=L2, latent_reg_weight=1e-3)
    loss3 = total_loss(res3.latent_coefficients, res3.projection)
    assert loss1 < loss0 * 0.5
    assert loss3 <= loss1 * 1.001
    # the rank-k structure is recoverable: near the noise floor
    assert loss3 < 0.02


# -- GAME integration ---------------------------------------------------------

def test_game_factored_beats_fixed_only(rng):
    ds = _dataset(rng)
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:1100]), ds.subset(rows[1100:])

    res = GameEstimator(_factored_config()).fit(train, val)
    rmse_mf = res.validation["RMSE"]

    fe_only = GameTrainingConfig(
        task_type="linear_regression",
        coordinates={"fixed": FixedEffectCoordinateConfig(
            "global", GLMOptimizationConfig(regularization=L2,
                                            regularization_weight=0.1))},
        updating_sequence=["fixed"])
    rmse_fe = GameEstimator(fe_only).fit(train, val).validation["RMSE"]
    assert rmse_mf < rmse_fe * 0.8, (
        "factored RE must clearly beat fixed-only on low-rank mixed data "
        f"(got {rmse_mf:.4f} vs {rmse_fe:.4f})")
    hist = res.objective_history
    assert hist[-1] <= hist[0]
    model = res.model.coordinates["perUserMF"]
    assert isinstance(model, FactoredRandomEffectModel)
    assert model.latent_dim == 4


def test_factored_save_load_score_roundtrip(rng, tmp_path):
    ds = _dataset(rng, n=600, num_users=15)
    res = GameEstimator(_factored_config(iters=1)).fit(ds)
    save_game_model(res.model, str(tmp_path / "m"), config=res.config)
    loaded, cfg = load_game_model(str(tmp_path / "m"))
    np.testing.assert_allclose(np.asarray(loaded.score_dataset(ds)),
                               np.asarray(res.model.score_dataset(ds)),
                               rtol=1e-6)
    assert cfg == res.config  # config JSON round-trip incl. factored kind


def test_factored_unseen_entity_scores_zero(rng):
    ds = _dataset(rng, n=400, num_users=10)
    res = GameEstimator(_factored_config(iters=1)).fit(ds)
    m = res.model.coordinates["perUserMF"]
    val = build_game_dataset(
        np.zeros(2),
        {"global": np.ones((2, 6)), "per_user": np.ones((2, 12))},
        entity_ids={"userId": np.asarray(["zzz", "u000"])})
    s = np.asarray(m.score_dataset(val))
    assert s[0] == 0.0
    assert s[1] != 0.0 or np.allclose(np.asarray(m.latent_coefficients), 0)


# -- Gaussian random-projection projector for plain random effects -----------

def test_random_projection_projector_random_effects(rng):
    """reference: ProjectorType.RandomProjection(dim) — per-entity problems
    solved in a shared k-dim Gaussian-projected space; coefficients map back
    to the original space via P^T c."""
    from photon_ml_tpu.data.batching import (
        RandomEffectDataConfig, build_random_effect_dataset)
    from photon_ml_tpu.game import RandomEffectCoordinateConfig

    ds = _dataset(rng, n=800, num_users=20)
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("userId", "per_user",
                                   projector="random_projection:5"))
    assert red.blocks.dim == 6          # k + intercept selector row
    assert red.projection_matrix.shape == (6, 12)
    assert red.projection is None

    cfg = GameTrainingConfig(
        task_type="linear_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(regularization=L2,
                                                regularization_weight=0.1)),
            "perUser": RandomEffectCoordinateConfig(
                random_effect_type="userId", feature_shard="per_user",
                projector="random_projection:5",
                optimization=GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"], num_outer_iterations=2)
    rows = np.arange(ds.num_rows)
    train, val = ds.subset(rows[:600]), ds.subset(rows[600:])
    res = GameEstimator(cfg).fit(train, val)
    assert np.isfinite(res.validation["RMSE"])
    m = res.model.coordinates["perUser"]
    assert m.projection_matrix is not None
    assert m.global_coefficients().shape == (m.num_entities, 12)


# -- matrix factorization -----------------------------------------------------

def test_matrix_factorization_model_scoring(rng):
    R, C, k = 6, 5, 3
    rf = rng.normal(size=(R, k))
    cf = rng.normal(size=(C, k))
    row_ids = np.asarray([f"r{i}" for i in range(R)])
    col_ids = np.asarray([f"c{j}" for j in range(C)])
    mf = MatrixFactorizationModel("memberId", "itemId",
                                  jnp.asarray(rf), row_ids,
                                  jnp.asarray(cf), col_ids)
    assert mf.num_latent_factors == k
    rows = np.asarray(["r0", "r3", "r5", "nope"])
    cols = np.asarray(["c1", "c4", "nope", "c0"])
    ds = build_game_dataset(
        np.zeros(4), {"dummy": np.ones((4, 1))},
        entity_ids={"memberId": rows, "itemId": cols})
    s = np.asarray(mf.score_dataset(ds))
    np.testing.assert_allclose(s[0], rf[0] @ cf[1], rtol=1e-6)
    np.testing.assert_allclose(s[1], rf[3] @ cf[4], rtol=1e-6)
    assert s[2] == 0.0 and s[3] == 0.0  # either side unseen -> 0


def test_matrix_factorization_from_factored_one_hot(rng):
    """One-hot col-indicator features make factored RE == MF exactly."""
    num_rows_e, num_cols_e, k, n = 8, 6, 3, 300
    r_idx = rng.integers(0, num_rows_e, size=n)
    c_idx = rng.integers(0, num_cols_e, size=n)
    x = np.eye(num_cols_e)[c_idx]                    # one-hot, no intercept
    C = jnp.asarray(rng.normal(size=(num_rows_e, k)))
    P = jnp.asarray(rng.normal(size=(k, num_cols_e)))
    row_ids = np.asarray([f"m{i}" for i in range(num_rows_e)])
    col_ids = np.asarray([f"i{j}" for j in range(num_cols_e)])
    fre = FactoredRandomEffectModel(
        random_effect_type="memberId", feature_shard="items",
        task_type="linear_regression", latent_coefficients=C, projection=P,
        entity_ids=row_ids, global_dim=num_cols_e)
    mf = MatrixFactorizationModel.from_factored(fre, "itemId", col_ids)
    ds = build_game_dataset(
        np.zeros(n), {"items": x},
        entity_ids={"memberId": row_ids[r_idx], "itemId": col_ids[c_idx]})
    np.testing.assert_allclose(np.asarray(mf.score_dataset(ds)),
                               np.asarray(fre.score_dataset(ds)), rtol=1e-5)


def test_mf_save_load_roundtrip(rng, tmp_path):
    from photon_ml_tpu.models.game import GameModel
    mf = MatrixFactorizationModel(
        "memberId", "itemId",
        jnp.asarray(rng.normal(size=(4, 2))), np.asarray(["a", "b", "c", "d"]),
        jnp.asarray(rng.normal(size=(3, 2))), np.asarray(["x", "y", "z"]))
    gm = GameModel({"mf": mf}, task_type="linear_regression")
    save_game_model(gm, str(tmp_path / "mf"))
    loaded, _ = load_game_model(str(tmp_path / "mf"))
    lm = loaded.coordinates["mf"]
    np.testing.assert_allclose(np.asarray(lm.row_factors),
                               np.asarray(mf.row_factors))
    np.testing.assert_allclose(np.asarray(lm.col_factors),
                               np.asarray(mf.col_factors))
    assert list(lm.row_ids) == list(mf.row_ids)
