"""Date-partitioned input resolution + per-shard feature-stats persistence
(reference: GameDriver.pathsForDateRange, DateRange.fromDates/fromDaysAgo,
Driver.calculateAndSaveFeatureShardStats)."""
import datetime
import json
import os

import numpy as np
import pytest

from photon_ml_tpu.data.date_range import (
    parse_date_range, parse_days_ago, paths_for_date_range,
)


def _mk_days(tmp_path, days):
    for y, m, d in days:
        (tmp_path / "daily" / f"{y:04d}" / f"{m:02d}" / f"{d:02d}").mkdir(
            parents=True)


def test_parse_specs():
    assert parse_date_range("20170101-20170103") == (
        datetime.date(2017, 1, 1), datetime.date(2017, 1, 3))
    with pytest.raises(ValueError, match="ends before"):
        parse_date_range("20170105-20170101")
    with pytest.raises(ValueError, match="yyyyMMdd"):
        parse_date_range("2017-01-01")
    today = datetime.date(2017, 1, 10)
    assert parse_days_ago("9-7", today) == (
        datetime.date(2017, 1, 1), datetime.date(2017, 1, 3))


def test_paths_for_date_range(tmp_path):
    _mk_days(tmp_path, [(2017, 1, 1), (2017, 1, 3), (2017, 2, 1)])
    # missing middle day skipped; range endpoints inclusive
    got = paths_for_date_range(str(tmp_path), "20170101-20170131")
    assert [p.split("daily/")[1] for p in got] == ["2017/01/01", "2017/01/03"]
    # both specs -> reference's IllegalArgument error
    with pytest.raises(ValueError, match="only one format"):
        paths_for_date_range(str(tmp_path), "20170101-20170102", "9-1")
    # neither -> base dirs unchanged
    assert paths_for_date_range(str(tmp_path)) == [str(tmp_path)]
    # empty range -> error naming the daily dir
    with pytest.raises(FileNotFoundError, match="No data folder"):
        paths_for_date_range(str(tmp_path), "20180101-20180102")
    # days-ago flavour
    today = datetime.date(2017, 1, 4)
    got2 = paths_for_date_range(str(tmp_path), days_ago="3-1", today=today)
    assert [p.split("daily/")[1] for p in got2] == ["2017/01/01", "2017/01/03"]


def test_cli_date_range_and_feature_stats(tmp_path, rng):
    """CLI end-to-end: date-partitioned Avro ingest + per-shard feature
    stats persisted next to the output."""
    from photon_ml_tpu.data.avro_game import write_game_examples
    from tests.test_avro_game import _bag_matrix
    from tests.test_io_cli import _run_cli

    n = 120
    x, imap = _bag_matrix(rng, n, [(f"f{i}", "") for i in range(4)])
    users = np.asarray([f"u{i % 5}" for i in range(n)])
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    days = [(2017, 1, 1), (2017, 1, 2), (2017, 1, 5)]
    _mk_days(tmp_path, days + [(2017, 1, 31)])
    # an empty day dir inside the range (e.g. only a _SUCCESS marker) is
    # skipped, not fatal
    (tmp_path / "daily" / "2017" / "01" / "31" / "_SUCCESS").write_text("")
    third = n // 3
    for k, (yy, mm, dd) in enumerate(days):
        sl = slice(k * third, (k + 1) * third)
        write_game_examples(
            str(tmp_path / "daily" / f"{yy:04d}" / f"{mm:02d}" / f"{dd:02d}"
                / "part.avro"),
            y[sl], bags={"features": (x[sl], imap)},
            id_values={"userId": users[sl]})

    out_dir = str(tmp_path / "out")
    r = _run_cli("photon_ml_tpu.cli.train",
                 ["--train-data", str(tmp_path),
                  "--input-date-range", "20170101-20170131",
                  "--id-columns", "userId",
                  "--task", "logistic_regression",
                  "--reg-weights", "1.0",
                  "--save-feature-stats",
                  "--output-dir", out_dir])
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["train_rows"] == 3 * third  # empty Jan 31 dir skipped

    stats_p = os.path.join(out_dir, "feature-stats", "global.json")
    with open(stats_p) as f:
        stats = json.load(f)
    assert stats["count"] == 3 * third
    assert len(stats["mean"]) == imap.size
    assert len(stats["feature_keys"]) == imap.size
    # the reference's FeatureSummarizationResultAvro interchange records
    # are written alongside the JSON, one per feature, matching its values
    from photon_ml_tpu.data.avro_io import read_feature_stats_avro
    recs = read_feature_stats_avro(os.path.join(
        out_dir, "feature-stats", "global", "part-00000.avro"))
    assert len(recs) == len(stats["feature_keys"])
    by_key = {(n_, t): m for n_, t, m in recs}
    j = stats["feature_keys"].index("f1\x01")
    np.testing.assert_allclose(by_key[("f1", "")]["mean"], stats["mean"][j])
    assert {"max", "min", "mean", "normL1", "normL2", "numNonzeros",
            "variance"} <= set(recs[0][2])
