"""Avro codec + reference-schema I/O round-trips.

Mirrors reference: AvroUtils / ModelProcessingUtils / GLMSuite round-trip
tests.  Also validates the container format self-consistently (magic, sync,
deflate) and the union/array/map encoding against tricky values.
"""
import numpy as np
import pytest

from photon_ml_tpu.data.avro_codec import read_container, write_container
from photon_ml_tpu.data.avro_io import (
    TRAINING_EXAMPLE_AVRO, read_glm_avro, read_scores_avro,
    read_training_examples, read_latent_factors_avro, write_glm_avro,
    write_latent_factors_avro, write_scores_avro, write_training_examples,
)
from photon_ml_tpu.data.index_map import IndexMap, build_index_map


def test_container_roundtrip_tricky_values(tmp_path):
    schema = {"name": "T", "type": "record", "fields": [
        {"name": "l", "type": "long"},
        {"name": "s", "type": "string"},
        {"name": "o", "type": ["null", "double"], "default": None},
        {"name": "m", "type": {"type": "map", "values": "long"}},
        {"name": "a", "type": {"type": "array", "items": "string"}},
        {"name": "b", "type": "boolean"},
    ]}
    recs = [
        {"l": 0, "s": "", "o": None, "m": {}, "a": [], "b": False},
        {"l": -1, "s": "héllo ☃", "o": -0.0, "m": {"k": 2**40}, "a": ["x", ""], "b": True},
        {"l": 2**62, "s": "y", "o": 1e300, "m": {"a": -5, "b": 7}, "a": ["z"] * 5, "b": False},
        {"l": -(2**62), "s": "n", "o": float("inf"), "m": {}, "a": [], "b": True},
    ]
    p = str(tmp_path / "t.avro")
    for codec in ("null", "deflate"):
        write_container(p, schema, recs, codec=codec)
        back = list(read_container(p))
        assert back == recs, codec


def test_container_many_blocks(tmp_path):
    schema = {"name": "R", "type": "record",
              "fields": [{"name": "i", "type": "long"}]}
    recs = [{"i": i} for i in range(10000)]
    p = str(tmp_path / "many.avro")
    write_container(p, schema, recs, block_records=512)
    assert list(read_container(p)) == recs


def test_corrupt_file_detected(tmp_path):
    p = str(tmp_path / "bad.avro")
    with open(p, "wb") as f:
        f.write(b"NOTAVRO")
    with pytest.raises(ValueError, match="not an Avro container"):
        list(read_container(p))


def test_training_examples_roundtrip(tmp_path, rng):
    imap = build_index_map([("age", ""), ("height", "cm"), ("clicks", "7d")])
    n, d = 40, imap.size
    x = np.zeros((n, d))
    x[:, :3] = rng.normal(size=(n, 3)) * (rng.uniform(size=(n, 3)) > 0.4)
    x[:, imap.intercept_index] = 1.0
    y = (rng.uniform(size=n) > 0.5).astype(float)
    w = rng.uniform(0.5, 2, size=n)
    o = rng.normal(size=n)
    p = str(tmp_path / "train.avro")
    write_training_examples(p, x, y, imap, weights=w, offsets=o,
                            uids=[f"uid{i}" for i in range(n)])

    x2, y2, w2, o2, uids, imap2 = read_training_examples(p, imap)
    np.testing.assert_allclose(x2, x)
    np.testing.assert_allclose(y2, y)
    np.testing.assert_allclose(w2, w)
    np.testing.assert_allclose(o2, o)
    assert uids[0] == "uid0"

    # auto-built index map path (reference FeatureIndexingJob role)
    x3, y3, _, _, _, imap3 = read_training_examples(p)
    assert imap3.size <= imap.size  # only observed features
    np.testing.assert_allclose(y3, y)


def test_glm_avro_roundtrip(tmp_path, rng):
    imap = build_index_map([("f", str(i)) for i in range(6)])
    means = rng.normal(size=imap.size)
    means[2] = 0.0  # zero coefficients are dropped (sparse record)
    var = rng.uniform(0.1, 1.0, size=imap.size)
    p = str(tmp_path / "glm.avro")
    write_glm_avro(p, "my-model", "logistic_regression", means, imap, var)
    mid, task, means2, var2, _ = read_glm_avro(p, imap)
    assert mid == "my-model" and task == "logistic_regression"
    np.testing.assert_allclose(means2, means)
    np.testing.assert_allclose(var2, var)


def test_scores_avro_roundtrip(tmp_path, rng):
    s = rng.normal(size=25)
    y = (rng.uniform(size=25) > 0.5).astype(float)
    p = str(tmp_path / "scores.avro")
    write_scores_avro(p, "m1", s, labels=y)
    s2, y2, recs = read_scores_avro(p)
    np.testing.assert_allclose(s2, s)
    np.testing.assert_allclose(y2, y)
    assert recs[0]["modelId"] == "m1"


def test_latent_factors_roundtrip(tmp_path, rng):
    f = rng.normal(size=(8, 4))
    ids = [f"item{i}" for i in range(8)]
    p = str(tmp_path / "lf.avro")
    write_latent_factors_avro(p, ids, f)
    ids2, f2 = read_latent_factors_avro(p)
    assert ids2 == ids
    np.testing.assert_allclose(f2, f)
