"""Hyperparameter tuning stack: kernels, slice sampler, GP, searchers.

Mirrors the reference's deterministic-seed statistical tests
(photon-lib/src/test/.../hyperparameter/*: SliceSamplerTest,
GaussianProcessEstimatorTest, kernel tests, search tests) plus the
GAME-integration criterion: tuning must find a lambda at least as good as a
coarse grid on a synthetic problem.
"""
import math

import numpy as np
import pytest

from photon_ml_tpu.evaluation.evaluators import AUC, RMSE
from photon_ml_tpu.hyperparameter import (
    RBF, ConfidenceBound, ExpectedImprovement, GaussianProcessEstimator,
    GaussianProcessSearch, Matern52, RandomSearch, SliceSampler,
    cholesky_solve,
)
from photon_ml_tpu.hyperparameter.search import EvaluationFunction


# -- kernels ------------------------------------------------------------------

def test_rbf_kernel_basics():
    x = np.asarray([[0.0, 0.0], [1.0, 0.0], [0.0, 2.0]])
    k = RBF()(x)
    assert k.shape == (3, 3)
    np.testing.assert_allclose(np.diag(k), 1.0)
    np.testing.assert_allclose(k, k.T)
    np.testing.assert_allclose(k[0, 1], math.exp(-0.5))
    np.testing.assert_allclose(k[0, 2], math.exp(-2.0))


def test_matern52_kernel_basics():
    x = np.asarray([[0.0], [1.0]])
    k = Matern52()(x)
    f = math.sqrt(5.0)
    np.testing.assert_allclose(k[0, 1], (1 + f + 5.0 / 3.0) * math.exp(-f))
    np.testing.assert_allclose(np.diag(k), 1.0)


def test_kernel_length_scale_and_params_roundtrip():
    x = np.asarray([[0.0], [2.0]])
    k_wide = RBF(length_scale=np.asarray([2.0]))(x)
    k_narrow = RBF(length_scale=np.asarray([0.5]))(x)
    assert k_wide[0, 1] > k_narrow[0, 1]  # longer scale -> higher covariance
    kern = Matern52(length_scale=np.asarray([3.0]))
    back = kern.with_params(kern.get_params())
    np.testing.assert_allclose(back.length_scale, kern.length_scale)


def test_cross_kernel_shape():
    x1 = np.random.default_rng(0).normal(size=(4, 3))
    x2 = np.random.default_rng(1).normal(size=(6, 3))
    assert RBF()(x1, x2).shape == (4, 6)


def test_cholesky_solve():
    rng = np.random.default_rng(2)
    a = rng.normal(size=(5, 5))
    a = a @ a.T + 5 * np.eye(5)
    b = rng.normal(size=5)
    l = np.linalg.cholesky(a)
    np.testing.assert_allclose(cholesky_solve(l, b), np.linalg.solve(a, b),
                               rtol=1e-10)


# -- slice sampler ------------------------------------------------------------

def test_slice_sampler_standard_normal_moments():
    """Samples from log N(0,1) should reproduce mean/std (reference:
    SliceSamplerTest's seeded distribution checks)."""
    logp = lambda x: float(-0.5 * x @ x)
    s = SliceSampler(logp, value_range=(-10.0, 10.0), seed=13)
    x = np.zeros(1)
    draws = []
    for _ in range(200):
        x = s.draw(x)
        draws.append(x[0])
    draws = np.asarray(draws[50:])
    assert abs(np.mean(draws)) < 0.35
    assert 0.6 < np.std(draws) < 1.5


def test_slice_sampler_respects_multimodal_support():
    # two well-separated modes: the sampler must visit both
    logp = lambda x: float(np.logaddexp(-0.5 * (x[0] - 3) ** 2,
                                        -0.5 * (x[0] + 3) ** 2))
    s = SliceSampler(logp, value_range=(-10.0, 10.0), seed=7)
    x = np.zeros(1)
    draws = [s.draw(x := s.draw(x))[0] for _ in range(150)]
    assert any(d > 1 for d in draws) and any(d < -1 for d in draws)


# -- GP regression ------------------------------------------------------------

def test_gp_interpolates_smooth_function():
    """reference: GaussianProcessEstimatorTest — fit on a smooth function,
    prediction error at held-out points small, variance shrinks near data."""
    rng = np.random.default_rng(3)
    x = rng.uniform(-2, 2, size=(25, 1))
    y = np.sin(x[:, 0]) + 0.01 * rng.normal(size=25)
    est = GaussianProcessEstimator(kernel=Matern52(), normalize_labels=True,
                                   num_burn_in_samples=20, num_samples=20, seed=5)
    model = est.fit(x, y)
    xq = np.linspace(-1.5, 1.5, 11)[:, None]
    mean, var = model.predict(xq)
    np.testing.assert_allclose(mean, np.sin(xq[:, 0]), atol=0.15)
    # variance far from data >> variance at data
    m_far, v_far = model.predict(np.asarray([[6.0]]))
    assert v_far[0] > np.mean(var) * 3


def test_gp_predict_transformed_applies_acquisition():
    rng = np.random.default_rng(4)
    x = rng.uniform(0, 1, size=(12, 1))
    y = x[:, 0] ** 2
    acq = ConfidenceBound(RMSE, exploration_factor=2.0)  # smaller-better -> LCB
    est = GaussianProcessEstimator(kernel=RBF(), normalize_labels=True,
                                   prediction_transformation=acq,
                                   num_burn_in_samples=10, num_samples=10, seed=6)
    model = est.fit(x, y)
    xq = np.asarray([[0.5]])
    mean, var = model.predict(xq)
    lcb = model.predict_transformed(xq)
    assert lcb[0] <= mean[0] + 1e-12  # lower bound for smaller-is-better


# -- acquisition criteria -----------------------------------------------------

def test_expected_improvement_directions():
    means, variances = np.asarray([1.0, 2.0]), np.asarray([0.04, 0.04])
    ei_up = ExpectedImprovement(AUC, best_evaluation=1.5)(means, variances)
    assert ei_up[1] > ei_up[0]           # larger-is-better prefers mean 2.0
    ei_dn = ExpectedImprovement(RMSE, best_evaluation=1.5)(means, variances)
    assert ei_dn[0] > ei_dn[1]           # smaller-is-better prefers mean 1.0
    # EI is non-negative
    assert (ei_up >= 0).all() and (ei_dn >= 0).all()


def test_confidence_bound_directions():
    means, variances = np.asarray([1.0]), np.asarray([0.25])
    assert ConfidenceBound(AUC, 2.0)(means, variances)[0] == pytest.approx(2.0)
    assert ConfidenceBound(RMSE, 2.0)(means, variances)[0] == pytest.approx(0.0)


# -- searchers ----------------------------------------------------------------

class QuadraticEval(EvaluationFunction):
    """Payload = (params, value); minimum at center."""

    def __init__(self, center):
        self.center = np.asarray(center, dtype=float)
        self.calls = 0

    def __call__(self, candidate):
        self.calls += 1
        value = float(np.sum((np.asarray(candidate) - self.center) ** 2))
        return value, (np.asarray(candidate, dtype=float), value)

    def vectorize_params(self, observation):
        return observation[0]

    def get_evaluation_value(self, observation):
        return observation[1]


def test_random_search_finds_points_in_range():
    fn = QuadraticEval([0.0, 0.0])
    rs = RandomSearch([(-1.0, 1.0), (2.0, 3.0)], fn, seed=9)
    results = rs.find(8)
    assert len(results) == 8 and fn.calls == 8
    for params, _ in results:
        assert -1 <= params[0] <= 1 and 2 <= params[1] <= 3


def test_gp_search_beats_random_on_quadratic():
    """Seeded head-to-head (reference: GaussianProcessSearchTest spirit):
    with the same budget, GP search's best value should be at least as good
    as random search's on a smooth 2-d bowl."""
    center = [0.3, -0.8]
    ranges = [(-2.0, 2.0), (-2.0, 2.0)]
    budget = 18

    fn_r = QuadraticEval(center)
    best_random = min(v for _, v in RandomSearch(ranges, fn_r, seed=11).find(budget))

    fn_g = QuadraticEval(center)
    gp = GaussianProcessSearch(ranges, fn_g, RMSE,  # smaller-is-better metric
                               candidate_pool_size=120, seed=11)
    best_gp = min(v for _, v in gp.find(budget))
    assert best_gp <= best_random * 1.05
    assert best_gp < 0.3  # actually converges toward the bowl's bottom


def test_gp_search_expected_improvement_mode():
    fn = QuadraticEval([0.5, 0.5])
    gp = GaussianProcessSearch([(-2.0, 2.0), (-2.0, 2.0)], fn, RMSE,
                               candidate_pool_size=80,
                               acquisition="expected_improvement", seed=21)
    best = min(v for _, v in gp.find(15))
    assert best < 0.5  # EI mode also converges toward the bowl


def test_gp_search_uses_prior_observations():
    fn = QuadraticEval([0.0, 0.0])
    gp = GaussianProcessSearch([(-1.0, 1.0), (-1.0, 1.0)], fn, RMSE, seed=12)
    prior = [(np.asarray([0.5, 0.5]), 0.5), (np.asarray([-0.5, 0.2]), 0.29),
             (np.asarray([0.1, -0.1]), 0.02)]
    results = gp.find(3, observations=prior)
    assert len(results) == 3
    # prior observations registered: 2 immediately + 1 via the first next()
    assert len(gp._points) >= 5


# -- GAME integration ---------------------------------------------------------

def test_game_tuning_finds_good_lambda(rng):
    """Tuning must match or beat a coarse grid (reference criterion for the
    tuning stack; Driver.runHyperparameterTuning wiring)."""
    from photon_ml_tpu.data import build_game_dataset
    from photon_ml_tpu.game import (
        FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
        GLMOptimizationConfig, RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.hyperparameter import GameEstimatorEvaluationFunction
    from photon_ml_tpu.optim import RegularizationContext, RegularizationType

    L2 = RegularizationContext(RegularizationType.L2)
    n, d, users = 700, 6, 25
    xg = rng.normal(size=(n, d)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, 3))
    u = rng.integers(0, users, size=n)
    z = xg @ rng.normal(size=d) + np.einsum(
        "nd,nd->n", xu, rng.normal(size=(users, 3))[u] * 0.7)
    y = z + 0.15 * rng.normal(size=n)
    ds = build_game_dataset(y, {"g": xg, "u": xu},
                            entity_ids={"userId": np.asarray([f"u{i}" for i in u])})
    rows = np.arange(n)
    train, val = ds.subset(rows[:550]), ds.subset(rows[550:])

    cfg = GameTrainingConfig(
        "linear_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "g", GLMOptimizationConfig(regularization=L2,
                                           regularization_weight=1.0)),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "u", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"], num_outer_iterations=1)
    est = GameEstimator(cfg)
    fn = GameEstimatorEvaluationFunction(est, train, val, scale="log")
    assert fn.num_params == 2

    # coarse grid baseline: lambda in {100, 1} for the RE coordinate
    grid = est.fit_grid(train, {"perUser": [
        GLMOptimizationConfig(regularization=L2, regularization_weight=w)
        for w in (100.0, 1.0)]}, val)
    best_grid = min(r.validation["RMSE"] for r in grid)

    search = GaussianProcessSearch(
        [(-2.0, 2.0)] * fn.num_params, fn, RMSE, candidate_pool_size=60, seed=3)
    results = search.find(6, observations=grid)
    best_tuned = min(fn.get_evaluation_value(r) for r in results)
    assert best_tuned <= best_grid * 1.02, (
        f"tuning ({best_tuned:.4f}) must be competitive with grid ({best_grid:.4f})")

    # round-trip: vector -> config -> vector
    v = fn.vectorize_params(results[0])
    cfg2 = fn._vector_to_config(v)
    np.testing.assert_allclose(fn._config_to_vector(cfg2), v)
