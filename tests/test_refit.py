"""Continuous-training tier tests (photon_ml_tpu/refit/).

Covers the ISSUE 16 acceptance scenarios: f64 refit-from-log parity
(the log -> chunk -> dataset path produces the IDENTICAL fit as the same
rows in memory), the losing-candidate path (no swap, the incumbent keeps
serving), subprocess SIGKILL mid-compaction -> resume converges to
bit-identical chunk files, the durable feedback lane's torn-tail and
retention discipline, the trigger state machine (manual / interval /
on_trip with an injected clock), the refit.validate / refit.swap fault
sites, and the refit.* metrics surface.
"""
import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import photon_ml_tpu  # noqa: F401  (conftest configures the backend)

from photon_ml_tpu.fleet.replog import (FeedbackLog, feedback_from_record,
                                        record_for_feedback)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.online import OnlineUpdateConfig
from photon_ml_tpu.refit import (CompactorConfig, LogCompactor, RefitConfig,
                                 RefitDriver, RefitError, RefitTrigger,
                                 TriggerConfig)
from photon_ml_tpu.serving import ScoringService, ServingConfig
from photon_ml_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
D_G, D_U, N_ENT = 6, 4, 30
TASK = "logistic_regression"


def _make_model(rng, coef_scale=1.0):
    fe = FixedEffectModel(
        model_for_task(TASK, Coefficients(
            jnp.asarray(coef_scale * rng.normal(size=D_G)))), "global")
    re_ = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=TASK,
        coefficients=jnp.asarray(coef_scale * rng.normal(size=(N_ENT, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(N_ENT)], dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re_}, TASK)


def _service(rng, tmp_path, **kw):
    kw.setdefault("config", ServingConfig(max_batch=64, min_bucket=4))
    kw.setdefault("updates", OnlineUpdateConfig(micro_batch=8))
    return ScoringService(model=_make_model(rng), start_updater=False,
                          feedback_log_dir=str(tmp_path / "fb"), **kw)


def _feedback(svc, rng, n, flip=False):
    """Labels drawn from the live model's own probabilities; `flip`
    inverts them (the label-flip drift the refit must learn)."""
    feats = {"global": rng.normal(size=(n, D_G)),
             "per_user": rng.normal(size=(n, D_U))}
    ids = {"userId": np.asarray(
        [f"u{rng.integers(0, N_ENT)}" for _ in range(n)], dtype=object)}
    z = svc.registry.scorer.score(feats, ids).scores
    p = 0.5 * (1.0 + np.tanh(0.5 * z))
    y = (rng.uniform(size=n) < p).astype(float)
    if flip:
        y = 1.0 - y
    return feats, ids, y


def _driver(svc, tmp_path, chunk_rows=64, **cfg_kw):
    comp = LogCompactor(svc.feedback_log, str(tmp_path / "chunks"),
                        CompactorConfig(chunk_rows=chunk_rows))
    svc.feedback_log.register_consumer("refit-compactor",
                                       comp.checkpoint_seq)
    cfg_kw.setdefault("outer_iterations", 1)
    cfg_kw.setdefault("fe_iterations", 15)
    cfg_kw.setdefault("re_iterations", 20)
    driver = RefitDriver(svc.registry, comp, str(tmp_path / "models"),
                         RefitConfig(**cfg_kw), metrics=svc.metrics)
    return driver, comp


# -- f64 refit-from-log parity ------------------------------------------------

def test_refit_from_log_f64_parity(rng, tmp_path):
    """A refit FROM THE LOG (append -> compact -> chunk files -> merged
    dataset) is the same fit as one from the identical rows in memory:
    the objective histories and final coefficients agree to <= 1e-6 in
    f64 (transport is raw-byte exact, so they are in fact identical)."""
    svc = _service(rng, tmp_path)
    try:
        batches = []
        for _ in range(5):
            f, i, y = _feedback(svc, rng, 32, flip=True)
            svc.feedback(f, i, y)
            batches.append((f, i, y))
        driver, comp = _driver(svc, tmp_path)
        m = comp.compact()
        assert m["sealed_rows"] == 128 and len(m["chunks"]) == 2

        fit_log = driver.fit_candidate(driver.gather_rows())
        n = 5 * 32
        rows_mem = {
            "features": {s: np.concatenate([b[0][s] for b in batches])
                         for s in batches[0][0]},
            "ids": {"userId": np.concatenate(
                [b[1]["userId"] for b in batches])},
            "labels": np.concatenate([b[2] for b in batches]),
            "weights": np.ones(n), "offsets": np.zeros(n),
            "wall": np.zeros(n)}
        fit_mem = driver.fit_candidate(rows_mem)

        hist_log = np.asarray(fit_log.objective_history, np.float64)
        hist_mem = np.asarray(fit_mem.objective_history, np.float64)
        assert hist_log.shape == hist_mem.shape and hist_log.size > 0
        np.testing.assert_allclose(hist_log, hist_mem, rtol=0, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(fit_log.model.coordinates["fixed"]
                       .glm.coefficients.means, np.float64),
            np.asarray(fit_mem.model.coordinates["fixed"]
                       .glm.coefficients.means, np.float64),
            rtol=0, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(fit_log.model.coordinates["perUser"].coefficients,
                       np.float64),
            np.asarray(fit_mem.model.coordinates["perUser"].coefficients,
                       np.float64),
            rtol=0, atol=1e-6)
    finally:
        svc.close()


# -- the cycle's verdicts -----------------------------------------------------

def test_winning_candidate_swaps_and_records_metrics(rng, tmp_path):
    svc = _service(rng, tmp_path)
    try:
        for _ in range(5):
            f, i, y = _feedback(svc, rng, 32, flip=True)
            svc.feedback(f, i, y)
        driver, _ = _driver(svc, tmp_path)
        before = svc.registry.version
        result = driver.run_once()
        assert result.swapped and result.version != before
        assert svc.registry.version == result.version
        assert result.candidate["loss"] < result.incumbent["loss"]
        assert os.path.isdir(str(tmp_path / "models" / result.version))
        snap = svc.metrics_snapshot()["refit"]
        assert snap["runs"] == 1 and snap["swaps"] == 1
        assert snap["failures"] == 0
        assert snap["last_success_age_s"] is not None
    finally:
        svc.close()


def test_losing_candidate_keeps_incumbent(rng, tmp_path):
    """An impossible win margin forces the loss: no swap, no version
    directory, the registry keeps serving the incumbent."""
    svc = _service(rng, tmp_path)
    try:
        for _ in range(5):
            f, i, y = _feedback(svc, rng, 32, flip=True)
            svc.feedback(f, i, y)
        driver, _ = _driver(svc, tmp_path, min_loss_improvement=1e6)
        before = svc.registry.version
        result = driver.run_once()
        assert not result.swapped
        assert "incumbent" in result.reason
        assert svc.registry.version == before
        models = str(tmp_path / "models")
        assert not os.path.isdir(models) or not os.listdir(models)
        snap = svc.metrics_snapshot()["refit"]
        assert snap["runs"] == 1 and snap["swaps"] == 0
    finally:
        svc.close()


def test_tail_only_refit_without_sealed_chunks(rng, tmp_path):
    """Fewer rows than one chunk: nothing seals, the refit still trains
    on the lane's unsealed tail."""
    svc = _service(rng, tmp_path)
    try:
        f, i, y = _feedback(svc, rng, 48, flip=True)
        svc.feedback(f, i, y)
        driver, comp = _driver(svc, tmp_path, chunk_rows=64)
        result = driver.run_once()
        assert comp.manifest()["sealed_rows"] == 0
        assert result.sealed_rows == 0 and result.tail_rows == 48
        assert result.swapped
    finally:
        svc.close()


def test_empty_lane_is_a_clean_noop(rng, tmp_path):
    svc = _service(rng, tmp_path)
    try:
        driver, _ = _driver(svc, tmp_path)
        result = driver.run_once()
        assert not result.swapped and "not enough" in result.reason
        assert svc.metrics_snapshot()["refit"]["failures"] == 0
    finally:
        svc.close()


# -- fault sites --------------------------------------------------------------

def test_validate_fatal_fault_raises_and_keeps_incumbent(rng, tmp_path):
    svc = _service(rng, tmp_path)
    try:
        for _ in range(3):
            f, i, y = _feedback(svc, rng, 32, flip=True)
            svc.feedback(f, i, y)
        driver, _ = _driver(svc, tmp_path)
        plan = faults.FaultPlan([{"site": "refit.validate",
                                  "action": "fatal", "hits": [1]}])
        before = svc.registry.version
        with faults.injected(plan):
            with pytest.raises(RefitError, match="validation"):
                driver.run_once()
        assert svc.registry.version == before
        snap = svc.metrics_snapshot()["refit"]
        assert snap["runs"] == 1 and snap["failures"] == 1
    finally:
        svc.close()


def test_swap_transient_fault_retried_to_success(rng, tmp_path):
    svc = _service(rng, tmp_path)
    try:
        for _ in range(3):
            f, i, y = _feedback(svc, rng, 32, flip=True)
            svc.feedback(f, i, y)
        driver, _ = _driver(svc, tmp_path, backoff_s=0.001)
        plan = faults.FaultPlan([{"site": "refit.swap",
                                  "action": "transient", "hits": [1]}])
        with faults.injected(plan):
            result = driver.run_once()
        assert result.swapped and svc.registry.version == result.version
    finally:
        svc.close()


# -- trigger state machine ----------------------------------------------------

class _FakeDriver:
    def __init__(self, fail=False):
        self.calls = 0
        self.fail = fail

    def run_once(self, version=None):
        self.calls += 1
        if self.fail:
            raise ValueError("boom")
        from photon_ml_tpu.refit.driver import RefitResult
        return RefitResult(swapped=True, version=f"v{self.calls}",
                           reason="ok", train_rows=1, holdout_rows=1,
                           sealed_rows=0, tail_rows=2, checkpoint_seq=0,
                           objective_history=[], candidate={},
                           incumbent={})


class _FakeHealth:
    degraded = False


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_trigger_manual_never_fires_on_poll():
    drv = _FakeDriver()
    trig = RefitTrigger(drv, config=TriggerConfig(mode="manual"))
    assert trig.poll() is None and drv.calls == 0
    result = trig.run_once()
    assert result.swapped and drv.calls == 1
    assert trig.state()["fires"] == 1 and trig.state()["swaps"] == 1


def test_trigger_interval_respects_spacing():
    drv, clock = _FakeDriver(), _Clock()
    trig = RefitTrigger(drv, config=TriggerConfig(mode="interval",
                                                  interval_s=100.0),
                        clock=clock)
    assert trig.poll() is not None          # first poll fires immediately
    clock.t = 50.0
    assert trig.poll() is None              # inside the interval
    clock.t = 100.0
    assert trig.poll() is not None
    assert drv.calls == 2
    assert trig.state()["last_reason"] == "interval"


def test_trigger_on_trip_debounces_and_cools_off():
    drv, clock, health = _FakeDriver(), _Clock(), _FakeHealth()
    trig = RefitTrigger(drv, health=health,
                        config=TriggerConfig(mode="on_trip", trip_polls=2,
                                             cooloff_s=60.0),
                        clock=clock)
    assert trig.poll() is None              # healthy
    health.degraded = True
    assert trig.poll() is None              # 1 degraded poll < trip_polls
    assert trig.poll() is not None          # sustained -> fires
    assert trig.state()["last_reason"] == "health_trip"
    # still degraded but inside the cooloff: de-bounce counts, no fire
    assert trig.poll() is None and trig.poll() is None
    clock.t = 60.0
    assert trig.poll() is not None          # cooled off -> fires again
    health.degraded = False
    trig.poll()
    assert trig.state()["degraded_polls"] == 0   # healthy poll resets
    assert drv.calls == 2


def test_trigger_on_trip_debounce_resets_on_healthy_poll():
    drv, health = _FakeDriver(), _FakeHealth()
    trig = RefitTrigger(drv, health=health,
                        config=TriggerConfig(mode="on_trip", trip_polls=2,
                                             cooloff_s=0.0),
                        clock=_Clock())
    health.degraded = True
    assert trig.poll() is None
    health.degraded = False
    assert trig.poll() is None              # resets the counter
    health.degraded = True
    assert trig.poll() is None              # back to 1, not 2
    assert drv.calls == 0


def test_trigger_records_cycle_errors_and_keeps_going():
    drv = _FakeDriver(fail=True)
    trig = RefitTrigger(drv, config=TriggerConfig(mode="interval",
                                                  interval_s=1.0),
                        clock=_Clock())
    assert trig.poll() is None              # the failure is swallowed
    assert drv.calls == 1
    state = trig.state()
    assert state["fires"] == 1 and "boom" in state["last_error"]


def test_trigger_on_trip_requires_health():
    with pytest.raises(ValueError, match="health"):
        RefitTrigger(_FakeDriver(),
                     config=TriggerConfig(mode="on_trip"))


def test_trigger_config_rejects_bad_modes():
    with pytest.raises(ValueError, match="mode"):
        TriggerConfig(mode="cron")
    with pytest.raises(ValueError):
        TriggerConfig(trip_polls=0)


# -- the durable feedback lane ------------------------------------------------

def test_feedback_record_round_trip_is_bit_exact(rng):
    feats = {"global": rng.normal(size=(7, D_G))}
    ids = {"userId": np.asarray([f"u{i}" for i in range(7)], dtype=object)}
    labels = rng.uniform(size=7)
    rec = record_for_feedback(feats, ids, labels, wall_s=123.0)
    back = feedback_from_record(rec)
    np.testing.assert_array_equal(back["features"]["global"],
                                  feats["global"])
    np.testing.assert_array_equal(back["labels"], labels)
    np.testing.assert_array_equal(back["ids"]["userId"], ids["userId"])
    assert back["wall_s"] == 123.0


def test_feedback_lane_truncates_torn_tail(rng, tmp_path):
    log = FeedbackLog(str(tmp_path / "fb"))
    for k in range(3):
        feats = {"global": rng.normal(size=(4, D_G))}
        ids = {"userId": np.asarray(["u1"] * 4, dtype=object)}
        log.append(record_for_feedback(feats, ids, np.ones(4), wall_s=k))
    seg = sorted(p for p in os.listdir(str(tmp_path / "fb"))
                 if p.startswith("feedback-") and p.endswith(".seg"))[-1]
    path = str(tmp_path / "fb" / seg)
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[:-20])                # tear the newest record
    log2 = FeedbackLog(str(tmp_path / "fb"))
    assert log2.recover() > 0
    seqs = [int(env["log_seq"]) for env in log2.read(0)]
    assert seqs == [1, 2]                  # the torn record is gone


def test_feedback_retention_clamped_by_compactor_checkpoint(rng, tmp_path):
    log = FeedbackLog(str(tmp_path / "fb"), segment_records=1)
    for k in range(6):
        feats = {"global": rng.normal(size=(4, D_G))}
        ids = {"userId": np.asarray(["u1"] * 4, dtype=object)}
        log.append(record_for_feedback(feats, ids, np.ones(4),
                                       wall_s=float(k)))
    comp = LogCompactor(log, str(tmp_path / "chunks"),
                        CompactorConfig(chunk_rows=8))
    log.register_consumer("refit-compactor", comp.checkpoint_seq)
    # nothing sealed yet: the clamp pins retention at seq 0
    assert log.compact(6) is None or log.compact(6)["upto_seq"] == 0
    assert [int(e["log_seq"]) for e in log.read(0)] == list(range(1, 7))
    comp.compact()                          # seals 24 rows = seqs 1..6
    ckpt = comp.checkpoint_seq()
    assert ckpt >= 4
    before = log.live_records()
    snap = log.compact(10_000)              # still clamped to the ckpt
    assert snap is not None and snap["upto_seq"] == ckpt
    assert log.live_records() < before
    # every surviving row is still readable past the pruned horizon
    assert all(int(e["log_seq"]) > ckpt for e in log.read(ckpt))


# -- SIGKILL mid-compaction -> bit-identical resume ---------------------------

_CHILD = """\
import sys
sys.path.insert(0, {repo!r})
from photon_ml_tpu.utils import faults
faults.install_from_env()
from photon_ml_tpu.fleet.replog import FeedbackLog
from photon_ml_tpu.refit import CompactorConfig, LogCompactor
log = FeedbackLog({fb!r})
log.recover()
LogCompactor(log, {chunks!r}, CompactorConfig(chunk_rows=64)).compact()
print("OK")
"""


def _compact_child(tmp_path, chunks, plan=None, expect_kill=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PHOTON_FAULT_PLAN", None)
    if plan is not None:
        env["PHOTON_FAULT_PLAN"] = json.dumps(plan)
    code = _CHILD.format(repo=_REPO, fb=str(tmp_path / "fb"),
                         chunks=chunks)
    p = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=_REPO)
    if expect_kill:
        assert p.returncode == -9, (p.returncode, p.stderr[-500:])
        return None
    assert p.returncode == 0, p.stderr[-800:]
    return p.stdout


def test_sigkill_mid_compaction_resumes_bit_identical(rng, tmp_path):
    """SIGKILL at the refit.compact fault site while sealing chunk 1 ->
    a fresh process converges to chunk files BIT-IDENTICAL to an
    uninterrupted compaction of the same lane (sha-checked resume over
    the already-sealed prefix; deterministic replay of the rest)."""
    log = FeedbackLog(str(tmp_path / "fb"))
    for k in range(5):
        feats = {"global": rng.normal(size=(32, D_G)),
                 "per_user": rng.normal(size=(32, D_U))}
        ids = {"userId": np.asarray(
            [f"u{rng.integers(0, N_ENT)}" for _ in range(32)],
            dtype=object)}
        log.append(record_for_feedback(feats, ids, rng.uniform(size=32),
                                       wall_s=1000.0 + k))

    ref_dir = str(tmp_path / "chunks_ref")
    _compact_child(tmp_path, ref_dir)       # uninterrupted reference
    ref_chunks = sorted(p for p in os.listdir(ref_dir)
                        if p.startswith("chunk-"))
    assert len(ref_chunks) == 2             # 160 rows / 64 -> 2 sealed

    kill_dir = str(tmp_path / "chunks")
    plan = {"seed": 0, "faults": [{"site": "refit.compact",
                                   "action": "kill", "hits": [2]}]}
    _compact_child(tmp_path, kill_dir, plan=plan, expect_kill=True)
    # chunk 0 survived the kill; chunk 1 never sealed
    sealed = sorted(p for p in os.listdir(kill_dir)
                    if p.startswith("chunk-"))
    assert len(sealed) == 1

    _compact_child(tmp_path, kill_dir)      # resume, no faults
    for name in ref_chunks:
        with open(os.path.join(ref_dir, name), "rb") as f:
            want = f.read()
        with open(os.path.join(kill_dir, name), "rb") as f:
            got = f.read()
        assert got == want, f"{name} differs after kill+resume"
    with open(os.path.join(ref_dir, "manifest.json")) as f:
        want_m = json.load(f)
    with open(os.path.join(kill_dir, "manifest.json")) as f:
        got_m = json.load(f)
    assert got_m == want_m
