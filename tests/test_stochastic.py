"""Stochastic single-pass solver lane (ISSUE 15): per-chunk local
coordinate descent with hierarchical merge, SolverSchedule lane selection,
Prefetcher pinning accounting, the solve.local fault site, and the
compile-count regressions (chunk counts + 8x1/4x2 meshes).

The contract under test:

  * fixed-point parity — stochastic-early + strict-LBFGS-polish converges
    to the SAME minimizer as strict streamed LBFGS (f64, <= 1e-6; the
    lane is a warm-start generator, the polish pins the fixed point);
  * seeded determinism — a given (plan, seed, chunking) replays
    bit-for-bit across runs;
  * staging amortization — a pinned chunk runs K local epochs for ONE
    staging pass, so examples_per_staged_byte rises by ~K;
  * zero fresh traces across chunk counts of one chunk shape and across
    8x1 / 4x2 meshes (every kernel keyed on the chunk shape only).
"""
import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.data.streaming import ChunkPlan, Prefetcher, StreamStats
from photon_ml_tpu.game import (
    FixedEffectCoordinateConfig, GameEstimator, GameTrainingConfig,
    GLMOptimizationConfig, RandomEffectCoordinateConfig,
)
from photon_ml_tpu.ops.chunked import ChunkedGLMObjective, LocalSolveError
from photon_ml_tpu.ops.losses import LOGISTIC, POISSON, SQUARED
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, RegularizationType,
    SolverSchedule, StochasticPlan, solve_stochastic, solve_streamed,
)
from photon_ml_tpu.utils import faults

L2 = RegularizationContext(RegularizationType.L2)


def _problem(rng, n=6000, d=12, loss="logistic"):
    x = rng.normal(size=(n, d))
    x[:, -1] = 1.0
    w = rng.normal(size=d) * 0.5
    z = x @ w
    if loss == "logistic":
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    elif loss == "squared":
        y = z + rng.normal(size=n) * 0.1
    else:  # poisson
        y = rng.poisson(np.exp(np.clip(z, None, 3.0))).astype(float)
    return x, y


def _chunked(x, y, loss=LOGISTIC, chunk_rows=1024, l2=1.0, **kw):
    plan = ChunkPlan.build(len(y), chunk_rows=chunk_rows,
                           row_multiple=kw.pop("row_multiple", 1))
    return ChunkedGLMObjective(loss, x, y, plan, l2_weight=l2, **kw)


# --------------------------------------------------------------------------
# schedule / plan plumbing
# --------------------------------------------------------------------------

def test_stochastic_plan_validation():
    with pytest.raises(ValueError, match="local_epochs"):
        StochasticPlan(local_epochs=0)
    with pytest.raises(ValueError, match="merge"):
        StochasticPlan(merge="parallel")
    with pytest.raises(ValueError, match="stochastic_polish_iterations"):
        SolverSchedule(stochastic_passes=1, stochastic_polish_iterations=0)
    with pytest.raises(ValueError, match="stochastic_merge"):
        SolverSchedule(stochastic_passes=1, stochastic_merge="nope")


def test_schedule_lane_selection_and_polish():
    """Early outer iterations get the lane; the final
    stochastic_polish_iterations are strict; disabled = always strict."""
    sched = SolverSchedule(stochastic_passes=2, stochastic_local_epochs=3,
                           stochastic_polish_iterations=2)
    plans = [sched.stochastic_plan(it, 5) for it in range(5)]
    assert all(p is not None for p in plans[:3])
    assert plans[0].passes == 2 and plans[0].local_epochs == 3
    assert plans[3] is None and plans[4] is None
    # a 1-iteration fit is ALL polish
    assert sched.stochastic_plan(0, 1) is None
    assert SolverSchedule().stochastic_plan(0, 5) is None


def test_schedule_json_round_trip_and_fingerprint_stability():
    on = SolverSchedule(stochastic_passes=3, stochastic_local_epochs=8,
                        stochastic_merge="average", stochastic_seed=5,
                        stochastic_polish_iterations=2)
    assert SolverSchedule.from_dict(on.to_dict()) == on
    # strict-only schedules encode EXACTLY as before this PR, so existing
    # checkpoint fingerprints stay valid
    off = SolverSchedule()
    assert set(off.to_dict()) == {"initial_iterations", "iteration_growth",
                                  "initial_tolerance_factor",
                                  "tolerance_decay"}
    assert SolverSchedule.from_dict(off.to_dict()) == off


# --------------------------------------------------------------------------
# fixed-point parity + determinism (the core numerical contract)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("loss,lname", [(LOGISTIC, "logistic"),
                                        (SQUARED, "squared"),
                                        (POISSON, "poisson")])
def test_fixed_point_parity_stochastic_plus_polish(rng, loss, lname):
    """stochastic-early + strict-polish lands on the SAME f64 fixed point
    as strict streamed LBFGS, <= 1e-6 (measured: machine precision)."""
    x, y = _problem(rng, loss=lname)
    d = x.shape[1]
    cfg = OptimizerConfig(max_iterations=300, tolerance=1e-10)

    strict = solve_streamed(_chunked(x, y, loss=loss), jnp.zeros(d),
                            cfg, L2, 1.0)

    obj = _chunked(x, y, loss=loss)
    coarse = solve_streamed(obj, jnp.zeros(d), cfg, L2, 1.0,
                            stochastic=StochasticPlan(passes=2,
                                                      local_epochs=4))
    polished = solve_streamed(obj, coarse.x, cfg, L2, 1.0)
    rel = abs(float(polished.value) - float(strict.value)) / max(
        abs(float(strict.value)), 1e-12)
    assert rel <= 1e-6, (lname, rel)
    np.testing.assert_allclose(np.asarray(polished.x), np.asarray(strict.x),
                               rtol=1e-5, atol=1e-7)
    if loss.d2z_bound is not None:
        # majorized steps descend monotonically, so the warm start does
        # real work: the polish needs no more iterations than a cold
        # strict solve (Poisson's clipped steps carry no such guarantee)
        assert int(polished.iterations) <= int(strict.iterations)


def test_seeded_determinism_across_runs(rng):
    x, y = _problem(rng)
    d = x.shape[1]
    plan = StochasticPlan(passes=3, local_epochs=4, seed=11)
    runs = [solve_stochastic(_chunked(x, y), jnp.zeros(d), plan)
            for _ in range(2)]
    assert np.array_equal(np.asarray(runs[0].loss_history),
                          np.asarray(runs[1].loss_history), equal_nan=True)
    assert bool(jnp.all(runs[0].x == runs[1].x))
    # a different seed visits coordinates in different orders
    other = solve_stochastic(
        _chunked(x, y), jnp.zeros(d),
        StochasticPlan(passes=3, local_epochs=4, seed=12))
    assert not bool(jnp.all(other.x == runs[0].x))


def test_average_merge_descends_and_is_order_free(rng):
    """The CoCoA-safe averaging merge still makes progress (entry
    objective strictly decreases over passes)."""
    x, y = _problem(rng)
    d = x.shape[1]
    res = solve_stochastic(
        _chunked(x, y), jnp.zeros(d),
        StochasticPlan(passes=3, local_epochs=4, merge="average"))
    hist = np.asarray(res.loss_history)[:3]
    assert np.all(np.isfinite(hist))
    assert hist[1] < hist[0] and hist[2] < hist[1]


def test_lane_respects_normalization(rng):
    """A normalized streamed coordinate runs the lane in normalized
    space via the margin-invariant column algebra — the polished fit
    matches the strict one."""
    from photon_ml_tpu.ops.normalization import (
        NormalizationType, build_normalization_context)
    x, y = _problem(rng, n=4000, d=8)
    d = x.shape[1]
    mean = x.mean(axis=0)
    var = x.var(axis=0)
    norm = build_normalization_context(
        NormalizationType.STANDARDIZATION, mean=jnp.asarray(mean),
        variance=jnp.asarray(var), intercept_index=d - 1)
    cfg = OptimizerConfig(max_iterations=300, tolerance=1e-10)
    strict = solve_streamed(_chunked(x, y, norm=norm), jnp.zeros(d),
                            cfg, L2, 1.0)
    obj = _chunked(x, y, norm=norm)
    coarse = solve_streamed(obj, jnp.zeros(d), cfg, L2, 1.0,
                            stochastic=StochasticPlan(passes=2,
                                                      local_epochs=4))
    polished = solve_streamed(obj, coarse.x, cfg, L2, 1.0)
    rel = abs(float(polished.value) - float(strict.value)) / max(
        abs(float(strict.value)), 1e-12)
    assert rel <= 1e-6


def test_l1_and_box_fall_through_to_strict_lane(rng):
    """OWLQN / box-constrained solves ignore the stochastic plan (their
    prox/projection structure is the host-stepped solver's job)."""
    x, y = _problem(rng, n=3000, d=6)
    d = x.shape[1]
    en = RegularizationContext(RegularizationType.ELASTIC_NET,
                               elastic_net_alpha=0.5)
    plan = StochasticPlan(passes=2, local_epochs=2)
    res = solve_streamed(_chunked(x, y), jnp.zeros(d),
                         OptimizerConfig(max_iterations=50),
                         en, 0.5, stochastic=plan)
    ref = solve_streamed(_chunked(x, y), jnp.zeros(d),
                         OptimizerConfig(max_iterations=50), en, 0.5)
    assert np.array_equal(np.asarray(res.loss_history),
                          np.asarray(ref.loss_history), equal_nan=True)
    box = OptimizerConfig(max_iterations=50, box_lower=(-0.1,) * d,
                          box_upper=(0.1,) * d)
    res_box = solve_streamed(_chunked(x, y), jnp.zeros(d), box, L2, 1.0,
                             stochastic=plan)
    assert float(jnp.max(jnp.abs(res_box.x))) <= 0.1 + 1e-12


# --------------------------------------------------------------------------
# Prefetcher pinning + StreamStats accounting
# --------------------------------------------------------------------------

def test_prefetcher_pinning_accounting():
    """pin_epochs stages each chunk ONCE and books rows*epochs of work:
    examples_per_staged_byte scales with the pin count."""
    plan = ChunkPlan.build(1000, chunk_rows=256)
    fetch = lambda spec: {"x": np.zeros((spec.padded_rows, 4))}
    stats = StreamStats()
    pf = Prefetcher(plan, fetch, stats=stats)
    chunks = sum(1 for _ in pf.stream(pin_epochs=5))
    snap = stats.snapshot()
    assert chunks == plan.num_chunks
    assert snap["chunks_staged"] == plan.num_chunks     # staged ONCE each
    assert snap["local_epochs"] == 5 * plan.num_chunks
    assert snap["examples_processed"] == 5 * 1000
    assert snap["peak_resident_chunks"] <= 2            # double buffer held
    base = snap["total_bytes"]
    assert snap["examples_per_staged_byte"] == 5 * 1000 / base

    # a plain pass books one epoch per chunk
    for _ in pf.stream():
        pass
    snap2 = stats.snapshot()
    assert snap2["local_epochs"] == snap["local_epochs"] + plan.num_chunks
    assert snap2["examples_processed"] == snap["examples_processed"] + 1000
    with pytest.raises(ValueError, match="pin_epochs"):
        next(iter(pf.stream(pin_epochs=0)))


# --------------------------------------------------------------------------
# solve.local fault site
# --------------------------------------------------------------------------

def test_solve_local_transient_retried_bit_exact(rng):
    x, y = _problem(rng, n=3000, d=6)
    d = x.shape[1]
    plan = StochasticPlan(passes=2, local_epochs=2)
    ref = solve_stochastic(_chunked(x, y), jnp.zeros(d), plan)
    spec = faults.FaultSpec(site="solve.local", hits=(1, 3),
                            action="transient")
    with faults.injected(faults.FaultPlan([spec], seed=3)):
        hit = solve_stochastic(_chunked(x, y), jnp.zeros(d), plan)
    assert spec.fired >= 1
    assert bool(jnp.all(hit.x == ref.x))
    assert np.array_equal(np.asarray(hit.loss_history),
                          np.asarray(ref.loss_history), equal_nan=True)


def test_solve_local_fatal_names_the_chunk(rng):
    x, y = _problem(rng, n=3000, d=6)
    d = x.shape[1]
    spec = faults.FaultSpec(site="solve.local", hits=(2,), action="fatal")
    with faults.injected(faults.FaultPlan([spec], seed=3)):
        with pytest.raises(LocalSolveError, match="chunk 1") as err:
            solve_stochastic(_chunked(x, y), jnp.zeros(d),
                             StochasticPlan(passes=1, local_epochs=2))
    assert err.value.chunk_index == 1


# --------------------------------------------------------------------------
# GAME integration: lane engages early, polish final, diagnostics land
# --------------------------------------------------------------------------

def _glmix(rng, n=4000, d_global=12, num_users=80, d_user=4):
    xg = rng.normal(size=(n, d_global)); xg[:, -1] = 1.0
    xu = rng.normal(size=(n, d_user)); xu[:, -1] = 1.0
    users = rng.integers(0, num_users, size=n)
    z = xg @ rng.normal(size=d_global)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
    ds = build_game_dataset(y, {"global": xg, "per_user": xu},
                            entity_ids={"userId": np.asarray(
                                [f"u{u:03d}" for u in users])})
    rows = np.arange(n)
    return ds.subset(rows[: int(n * 0.9)]), ds.subset(rows[int(n * 0.9):])


def _game_config(outer=3, schedule=None, chunk_rows=1024):
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig(
                "global", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=0.1),
                memory_mode="streamed", chunk_rows=chunk_rows),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", GLMOptimizationConfig(
                    regularization=L2, regularization_weight=1.0)),
        },
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=outer, solver_schedule=schedule)


def test_game_fit_stochastic_schedule_engages_and_converges(rng):
    """A streamed-FE GAME fit with a stochastic schedule: the lane's
    local epochs show up in the diagnostics (examples_per_staged_byte
    above the strict fit's), the run is deterministic, and the final
    objective tracks the strict fit's closely.  (The <= 1e-6 fixed-point
    parity contract is the SOLVER-level gate above — at fit level both
    runs are still contracting toward the joint optimum at the outer-CD
    rate, so only a coarse gate is honest at small iteration counts.)"""
    train, val = _glmix(rng)
    sched = SolverSchedule(stochastic_passes=2, stochastic_local_epochs=6)
    stoch = GameEstimator(_game_config(6, sched)).fit(train, val)
    strict = GameEstimator(_game_config(6)).fit(train, val)
    rel = abs(stoch.objective_history[-1] - strict.objective_history[-1]) \
        / abs(strict.objective_history[-1])
    assert rel <= 1e-2, rel
    # the coarse iterations made real progress: the stochastic fit ends
    # below the strict fit's first full outer iteration
    assert stoch.objective_history[-1] < strict.objective_history[1]

    d_stoch = stoch.descent.solver_diagnostics()["fixed"]["stream"]
    d_strict = strict.descent.solver_diagnostics()["fixed"]["stream"]
    assert d_stoch["local_epochs"] > d_stoch["chunks_staged"]
    assert d_stoch["examples_per_staged_byte"] \
        > 1.3 * d_strict["examples_per_staged_byte"]
    # residency accounting mirrors the same snapshot per streamed coord
    assert "fixed" in stoch.residency["stream"]
    assert stoch.residency["stream"]["fixed"]["local_epochs"] > 0

    again = GameEstimator(_game_config(6, sched)).fit(train, val)
    assert again.objective_history == stoch.objective_history


# --------------------------------------------------------------------------
# compile-count regressions
# --------------------------------------------------------------------------

class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _compile_counting:
    def __enter__(self):
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_zero_new_traces_across_chunk_counts(rng):
    """The local-epoch program is keyed on the chunk SHAPE (and the
    static epoch count) — never the chunk index, chunk count, pass index,
    or seed — so a dataset with more chunks of the same shape traces
    nothing new."""
    d, C = 8, 512
    plan = StochasticPlan(passes=2, local_epochs=3)

    def make(n, seed):
        r = np.random.default_rng(seed)
        x = r.normal(size=(n, d)); x[:, -1] = 1.0
        y = (r.uniform(size=n) < 0.5).astype(float)
        return _chunked(x, y, chunk_rows=C)

    warm = make(2 * C, 0)
    solve_stochastic(warm, jnp.zeros(d), plan)
    with _compile_counting() as counter:
        solve_stochastic(warm, jnp.zeros(d), plan)            # warm passes
        solve_stochastic(make(4 * C, 1), jnp.zeros(d),        # more chunks
                         StochasticPlan(passes=1, local_epochs=3, seed=9))
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles across chunk counts of one "
        "chunk shape — a program keyed on chunk count/index/seed crept in")


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2)])
def test_zero_new_traces_warm_mesh(rng, mesh_shape):
    """Each mesh shape compiles the kernel once; warm passes (and more
    chunks of the same shape) trace nothing new — on 8x1 AND 4x2."""
    from photon_ml_tpu.parallel import make_mesh
    nd, nf = mesh_shape
    mesh = make_mesh(nd, nf)
    d, C = 8, 512
    plan = StochasticPlan(passes=1, local_epochs=2)

    def make(n, seed):
        r = np.random.default_rng(seed)
        x = r.normal(size=(n, d)); x[:, -1] = 1.0
        y = (r.uniform(size=n) < 0.5).astype(float)
        return _chunked(x, y, chunk_rows=C, row_multiple=nd, mesh=mesh)

    warm = make(2 * C, 0)
    res = solve_stochastic(warm, jnp.zeros(d), plan)
    # second warm round: the carried iterate comes back with the mesh's
    # output sharding (vs the unsharded x0), which is its own cache key
    res = solve_stochastic(warm, res.x, plan)
    with _compile_counting() as counter:
        solve_stochastic(warm, res.x, plan)
        solve_stochastic(make(3 * C, 1), jnp.zeros(d), plan)
    assert counter.count == 0, (
        f"{counter.count} fresh XLA compiles on warm {nd}x{nf} mesh passes")


def test_mesh_history_parity_vs_single_device(rng):
    """The SAME plan + seed on one device and on an 8x1 data mesh gives
    the same per-pass objective history (float-summation-order residual
    only) and the same final coefficients."""
    from photon_ml_tpu.parallel import make_mesh
    x, y = _problem(rng, n=4096, d=10)
    d = x.shape[1]
    plan = StochasticPlan(passes=2, local_epochs=3)
    single = solve_stochastic(_chunked(x, y, chunk_rows=1024,
                                       row_multiple=8),
                              jnp.zeros(d), plan)
    mesh = solve_stochastic(_chunked(x, y, chunk_rows=1024, row_multiple=8,
                                     mesh=make_mesh(8, 1)),
                            jnp.zeros(d), plan)
    h1 = np.asarray(single.loss_history)
    h2 = np.asarray(mesh.loss_history)
    mask = np.isfinite(h1)
    np.testing.assert_allclose(h2[mask], h1[mask], rtol=1e-9)
    np.testing.assert_allclose(np.asarray(mesh.x), np.asarray(single.x),
                               rtol=1e-9, atol=1e-12)
