"""Online learning tier tests (photon_ml_tpu/online/).

Covers the ISSUE 9 acceptance scenarios: online-updated entity
coefficients match an offline refit of the same entities (f64, through the
training-side block build AND an independent scipy oracle), feedback
buffer backpressure/dedup/coalescing, delta durability (atomic writes via
utils/durable.py), delta-aware rollback interleaved with full-model swaps
under concurrent scoring, the compile-count regression (a warm serve loop
absorbing a delta stream traces NOTHING new), and the containment
discipline on the `online.solve`/`online.publish` fault sites (transient
retry, non-finite freeze — never a poisoned live table).
"""
import logging
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import minimize

import photon_ml_tpu

from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.game.anchored import (anchored_objective_np, entity_rows,
                                         offline_anchored_refit,
                                         solve_anchored,
                                         sub_dataset_for_entities)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.models.io import load_model_delta, save_model_delta
from photon_ml_tpu.online import (FeedbackBuffer, ModelDelta, Observation,
                                  OnlineUpdateConfig)
from photon_ml_tpu.online.delta import CoordinateDelta
from photon_ml_tpu.ops import TASK_LOSSES
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.parallel.random_effect import EntityBlocks
from photon_ml_tpu.serving import (Overloaded, ScoringService, ServingConfig,
                                   StaleDeltaError)
from photon_ml_tpu.utils import faults, locktrace

D_G, D_U, N_ENT = 6, 4, 30
TASK = "logistic_regression"


def _make_model(rng, coef_scale=1.0):
    fe = FixedEffectModel(
        model_for_task(TASK, Coefficients(
            jnp.asarray(coef_scale * rng.normal(size=D_G)))), "global")
    re = RandomEffectModel(
        random_effect_type="userId", feature_shard="per_user",
        task_type=TASK,
        coefficients=jnp.asarray(coef_scale * rng.normal(size=(N_ENT, D_U))),
        entity_ids=np.asarray([f"u{i}" for i in range(N_ENT)], dtype=object),
        projection=None, global_dim=D_U)
    return GameModel({"fixed": fe, "perUser": re}, TASK)


def _service(rng, *, updates=None, start_updater=False, **svc_kw):
    svc_kw.setdefault("config", ServingConfig(max_batch=64, min_bucket=4))
    return ScoringService(model=_make_model(rng), updates=updates,
                          start_updater=start_updater, **svc_kw)


def _feedback(rng, n, ids=None):
    feats = {"global": rng.normal(size=(n, D_G)),
             "per_user": rng.normal(size=(n, D_U))}
    if ids is None:
        ids = np.asarray([f"u{rng.integers(0, N_ENT)}" for _ in range(n)],
                         dtype=object)
    labels = (rng.uniform(size=n) < 0.5).astype(float)
    return feats, {"userId": ids}, labels


def _obs(rng, entity="u0", event_id=None, t=0.0):
    return Observation(
        features={"global": rng.normal(size=D_G),
                  "per_user": rng.normal(size=D_U)},
        ids={"userId": entity}, label=1.0, weight=1.0, offset=0.0,
        enqueued_at=t, event_id=event_id)


# -- the anchored solve math ------------------------------------------------

def test_anchored_solve_matches_scipy_oracle(rng):
    """solve_anchored lands on the scipy L-BFGS-B optimum of the anchored
    objective (independent implementation: host numpy, no shared code)."""
    E, S = 3, 12
    x = rng.normal(size=(E, S, D_U))
    labels = (rng.uniform(size=(E, S)) < 0.5).astype(float)
    mask = np.ones((E, S))
    mask[1, 8:] = 0.0
    offsets = rng.normal(size=(E, S)) * 0.3
    prior = rng.normal(size=(E, D_U))
    blocks = EntityBlocks(
        x=jnp.asarray(x), labels=jnp.asarray(labels), mask=jnp.asarray(mask),
        weights=jnp.asarray(mask), offsets=jnp.asarray(offsets * mask))
    lam = 0.8
    new_rows, res = solve_anchored(
        blocks, jnp.asarray(prior), TASK_LOSSES[TASK],
        OptimizerConfig(max_iterations=200, tolerance=1e-12), lam)
    new_rows = np.asarray(new_rows)
    for e in range(E):
        keep = mask[e] > 0
        f = lambda c: anchored_objective_np(
            x[e][keep], labels[e][keep], None, offsets[e][keep], c,
            prior[e], TASK, lam)
        ref = minimize(f, prior[e], method="L-BFGS-B", tol=1e-14).x
        assert np.max(np.abs(new_rows[e] - ref)) <= 1e-5 * max(
            1.0, np.max(np.abs(ref)))


def test_entity_sub_dataset_extraction(rng):
    feats, ids, labels = _feedback(rng, 40)
    ds = build_game_dataset(labels, feats, entity_ids=ids)
    wanted = ["u1", "u3"]
    rows = entity_rows(ds, "userId", wanted)
    got = set(np.asarray(ids["userId"])[rows].tolist())
    assert got <= set(wanted)
    n_expected = int(np.isin(ids["userId"].astype(str),
                             np.asarray(wanted, str)).sum())
    assert len(rows) == n_expected
    sub = sub_dataset_for_entities(ds, "userId", wanted)
    assert sub.num_rows == n_expected


# -- parity with an offline refit -------------------------------------------

def test_online_update_parity_with_offline_refit(rng):
    """The tentpole guarantee: the online path (FeedbackBuffer blocks,
    micro-batched padded lanes, warm-started delta-space solve) and an
    offline refit of the same entities through build_random_effect_dataset
    land on the same coefficients in f64 (<= 1e-6 rel)."""
    anchor = 0.6
    svc = _service(rng, updates=OnlineUpdateConfig(
        micro_batch=4, anchor_weight=anchor, max_iterations=200,
        tolerance=1e-12))
    try:
        scorer = svc.registry.scorer
        touched = ["u0", "u1", "u2", "u5", "u7", "u9", "u11"]
        n = 35
        feats, ids, labels = _feedback(
            rng, n, ids=np.asarray([touched[i % len(touched)]
                                    for i in range(n)], dtype=object))
        table0 = np.asarray(scorer.re_table("perUser"))
        prior = {u: table0[scorer.entity_row("perUser", u)].copy()
                 for u in touched}
        margins = scorer.score(feats, ids).scores
        svc.feedback(feats, ids, labels)
        out = svc.updater.flush()
        assert out["entities"] == len(touched)
        assert out["deltas"] >= 2     # micro_batch 4 < 7 touched entities
        table1 = np.asarray(scorer.re_table("perUser"))
        ds = build_game_dataset(labels, feats, offsets=margins,
                                entity_ids=ids)
        offline = offline_anchored_refit(
            ds, "userId", "per_user", prior, TASK_LOSSES[TASK],
            OptimizerConfig(max_iterations=200, tolerance=1e-12),
            anchor_weight=anchor)
        for u in touched:
            row = table1[scorer.entity_row("perUser", u)]
            denom = max(float(np.max(np.abs(offline[u]))), 1e-12)
            assert np.max(np.abs(row - offline[u])) / denom <= 1e-6, u
            # the update MOVED the row (fresh labels carry signal)
            assert not np.array_equal(row, prior[u])
        # untouched entities' rows are bit-identical
        untouched = [i for i in range(N_ENT)
                     if f"u{i}" not in set(touched)]
        assert np.array_equal(table1[untouched], table0[untouched])
    finally:
        svc.close()


# -- feedback buffer --------------------------------------------------------

def test_buffer_backpressure_overloaded(rng):
    buf = FeedbackBuffer(max_rows=8, entity_window=8)
    entries = [("perUser", f"u{i}", i, _obs(rng, f"u{i}")) for i in range(8)]
    buf.offer_batch(entries)
    with pytest.raises(Overloaded):
        buf.offer_batch([("perUser", "u9", 9, _obs(rng, "u9"))])
    assert buf.stats()["shed"] == 1
    # rejection is all-or-nothing: nothing from the failed batch landed
    assert buf.pending_rows == 8
    # draining frees capacity again
    buf.drain("perUser", 8)
    out = buf.offer_batch([("perUser", "u9", 9, _obs(rng, "u9"))])
    assert out["accepted"] == 1


def test_buffer_event_dedup_and_entity_window(rng):
    buf = FeedbackBuffer(max_rows=100, entity_window=3, dedup_window=10)
    a = _obs(rng, "u0", event_id="ev-1")
    out = buf.offer_batch([("perUser", "u0", 0, a)])
    assert out["accepted"] == 1
    # a client retry with the same event id is dropped
    out = buf.offer_batch([("perUser", "u0", 0, _obs(rng, "u0",
                                                     event_id="ev-1"))])
    assert out["accepted"] == 0 and out["deduped"] == 1
    # one event fanning out to two lanes is NOT a duplicate
    b = _obs(rng, "u1", event_id="ev-2")
    out = buf.offer_batch([("perUser", "u1", 1, b),
                           ("perItem", "i1", 0, b)])
    assert out["accepted"] == 2 and out["deduped"] == 0
    # per-entity window: only the newest 3 observations survive
    obs = [_obs(rng, "u0", t=float(i)) for i in range(6)]
    buf.offer_batch([("perUser", "u0", 0, o) for o in obs])
    drained = buf.drain("perUser", 10)
    u0 = next(ef for ef in drained if ef.entity_id == "u0")
    assert len(u0.observations) == 3
    assert [o.enqueued_at for o in u0.observations] == [3.0, 4.0, 5.0]


def test_unseen_entity_feedback_dropped(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=4))
    try:
        feats, _ids, labels = _feedback(rng, 4)
        ids = {"userId": np.asarray(["u0", "ghost1", "ghost2", "u1"],
                                    dtype=object)}
        out = svc.feedback(feats, ids, labels)
        assert out["dropped_unseen"] == 2
        assert out["accepted"] == 2
        snap = svc.metrics_snapshot()
        assert snap["online"]["dropped_unseen"] == 2
    finally:
        svc.close()


# -- delta durability --------------------------------------------------------

def test_delta_durability_roundtrip_and_verification(rng, tmp_path):
    delta = ModelDelta(
        base_version="v1", seq=3,
        coordinates={"perUser": CoordinateDelta(
            rows=np.asarray([4, 9, 2]),
            values=rng.normal(size=(3, D_U)),
            prior=rng.normal(size=(3, D_U)))},
        created_at=123.5)
    ddir = tmp_path / "delta"
    save_model_delta(delta, str(ddir))
    # durable layout: manifest.json written LAST vouches for completeness
    assert (ddir / "manifest.json").exists()
    assert (ddir / "delta.npz").exists()
    assert not list(ddir.glob("*.tmp*"))        # no torn temporaries
    loaded = load_model_delta(str(ddir))
    assert loaded.base_version == "v1" and loaded.seq == 3
    cd, lcd = delta.coordinates["perUser"], loaded.coordinates["perUser"]
    assert np.array_equal(cd.rows, lcd.rows)
    assert np.array_equal(cd.values, lcd.values)
    assert np.array_equal(cd.prior, lcd.prior)
    # a tampered file must be refused (manifest sha mismatch)
    (ddir / "delta.npz").write_bytes(b"corrupt")
    with pytest.raises(ValueError, match="manifest"):
        load_model_delta(str(ddir))
    # a directory without a completed write must be refused
    with pytest.raises(FileNotFoundError):
        load_model_delta(str(tmp_path / "nowhere"))


def test_delta_validation():
    with pytest.raises(ValueError, match="unique"):
        CoordinateDelta(rows=np.asarray([1, 1]), values=np.zeros((2, 3)),
                        prior=np.zeros((2, 3)))
    with pytest.raises(ValueError, match="at least one"):
        ModelDelta(base_version="v", seq=1, coordinates={})


# -- delta swaps, staleness, rollback ---------------------------------------

def test_stale_delta_refused_and_reenqueued(rng, monkeypatch):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    try:
        registry = svc.registry
        delta = ModelDelta(
            base_version="not-the-live-version", seq=1,
            coordinates={"perUser": CoordinateDelta(
                rows=np.asarray([0]), values=np.zeros((1, D_U)),
                prior=np.zeros((1, D_U)))})
        with pytest.raises(StaleDeltaError):
            registry.apply_delta(delta)
        # updater-level: a swap racing the publish re-enqueues the rows
        feats, ids, labels = _feedback(rng, 6)
        svc.feedback(feats, ids, labels)
        real_apply = registry.apply_delta
        calls = {"n": 0}

        def flaky_apply(d, publish_s=0.0):
            calls["n"] += 1
            if calls["n"] == 1:
                raise StaleDeltaError("simulated swap race")
            return real_apply(d, publish_s=publish_s)

        monkeypatch.setattr(registry, "apply_delta", flaky_apply)
        out1 = svc.updater.run_once()
        assert out1["deltas"] == 0          # first publish hit the race
        assert svc.updater.buffer.pending_rows > 0   # re-enqueued
        out2 = svc.updater.flush()
        assert out2["deltas"] >= 1          # re-solved and published
        assert svc.metrics_snapshot()["online"]["stale_deltas"] == 1
    finally:
        svc.close()


def test_rollback_interleaved_swaps_and_deltas_under_scoring(rng):
    """ISSUE 9 satellite: interleave full-model swaps, delta swaps and
    rollbacks while a scoring thread hammers the service — rollback after
    N delta swaps restores the exact pre-delta rows, and the full-model
    rollback still works beneath it.  Runs under the ARMED lock-order
    tracker (ISSUE 10): every acquisition order this concurrency test
    actually takes is validated against photonlint's static graph at the
    end."""
    tracker = locktrace.install()
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    stop = threading.Event()
    errors = []

    def scorer_loop():
        r = np.random.default_rng(11)
        while not stop.is_set():
            feats, ids, _ = _feedback(r, 3)
            try:
                svc.score(feats, ids)
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=scorer_loop, daemon=True)
    t.start()
    try:
        v1 = svc.model_version
        table_v1 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
        # deltas on v1
        feats, ids, labels = _feedback(rng, 20)
        svc.feedback(feats, ids, labels)
        svc.updater.flush()
        assert svc.registry.pending_deltas() >= 1
        table_v1_deltas = np.asarray(
            svc.registry.scorer.re_table("perUser")).copy()
        assert not np.array_equal(table_v1_deltas, table_v1)
        # full swap to v2 (fresh random model), then deltas on v2
        from photon_ml_tpu.serving import CompiledScorer
        r2 = np.random.default_rng(123)
        scorer2 = CompiledScorer(_make_model(r2), max_batch=64, min_bucket=4)
        scorer2.warmup()
        svc.registry.install(scorer2, "v2")
        assert svc.registry.pending_deltas() == 0    # log belongs to v1
        table_v2 = np.asarray(scorer2.re_table("perUser")).copy()
        feats, ids, labels = _feedback(rng, 20)
        svc.feedback(feats, ids, labels)
        svc.updater.flush()
        n_deltas = svc.registry.pending_deltas()
        assert n_deltas >= 1
        assert svc.version_vector()["delta_seq"] >= 1
        # rollback 1: delta-aware — v2's exact pre-delta rows return
        assert svc.rollback() == "v2"
        assert np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")), table_v2)
        assert svc.registry.pending_deltas() == 0
        assert svc.version_vector() == {"version": "v2", "delta_seq": 0}
        # rollback 2: full-model — back to v1 AS LAST SERVED (its deltas
        # stayed in its tables when it was swapped out)
        assert svc.rollback() == v1
        assert np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")),
            table_v1_deltas)
    finally:
        stop.set()
        t.join(timeout=5)
        svc.close()
        locktrace.shutdown()
    assert errors == []
    # static/dynamic cross-validation: every lock order this test took
    # must be an edge of the static acquisition-order graph
    tracker.assert_consistent(lock_order_edges_cached())


PACKAGE_DIR = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))


def test_lock_tracker_stress_scoring_deltas_rollback(rng):
    """ISSUE 10 acceptance: sustained scorer traffic + delta publishes +
    full swap + delta-aware rollback under the ARMED lock tracker.  Every
    observed acquisition order must be an edge consistent with the static
    lock-order graph, and the serving metrics path must actually have
    been observed nesting (the test would silently prove nothing if no
    two locks ever nested)."""
    with locktrace.enabled() as tracker:
        svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
        stop = threading.Event()
        errors = []

        def scorer_loop(seed):
            r = np.random.default_rng(seed)
            while not stop.is_set():
                feats, ids, _ = _feedback(r, 3)
                try:
                    svc.score(feats, ids)
                except Exception as e:  # pragma: no cover
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=scorer_loop, args=(s,),
                                    daemon=True) for s in (11, 13)]
        for t in threads:
            t.start()
        try:
            for _ in range(3):
                feats, ids, labels = _feedback(rng, 16)
                svc.feedback(feats, ids, labels)
                svc.updater.flush()
            assert svc.registry.pending_deltas() >= 1
            from photon_ml_tpu.serving import CompiledScorer
            scorer2 = CompiledScorer(_make_model(np.random.default_rng(7)),
                                     max_batch=64, min_bucket=4)
            scorer2.warmup()
            svc.registry.install(scorer2, "v2")
            feats, ids, labels = _feedback(rng, 16)
            svc.feedback(feats, ids, labels)
            svc.updater.flush()
            svc.rollback()          # delta-aware
            svc.rollback()          # full-model
            svc.metrics_snapshot()
            svc.prometheus_metrics()
            svc.updater.stats()
            svc.updater.frozen_entities()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            svc.close()
    assert errors == []
    static = lock_order_edges_cached()
    tracker.assert_consistent(static)
    observed = set(tracker.edges())
    # the nesting the serving hot path is KNOWN to take — proves the
    # tracker saw real contention-relevant structure, not an empty run
    assert ("ServingMetrics._lock", "Counter._lock") in observed
    assert observed <= static
    acq = tracker.acquisitions()
    assert acq.get("ModelRegistry._lock", 0) > 0
    assert acq.get("FeedbackBuffer._lock", 0) > 0
    assert acq.get("OnlineUpdater._state_lock", 0) > 0
    assert acq.get("MicroBatcher._cv", 0) > 0


_STATIC_EDGES = None


def lock_order_edges_cached():
    """The package's static lock-order graph, computed once per test
    session (the interprocedural pass costs ~1s)."""
    global _STATIC_EDGES
    if _STATIC_EDGES is None:
        from photon_ml_tpu.analysis.concurrency import lock_order_edges
        _STATIC_EDGES = lock_order_edges([PACKAGE_DIR])
    return _STATIC_EDGES


def test_updater_start_close_race_spawns_one_thread(rng):
    """Regression for the PH013 check-then-act in OnlineUpdater.start():
    N racing start() calls must launch exactly ONE loop thread, and
    close() must join it without deadlocking (it joins OUTSIDE the state
    lock the loop thread takes — the PH012 hazard)."""
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8),
                   start_updater=False)
    try:
        before = {t.ident for t in threading.enumerate()}
        barrier = threading.Barrier(8)

        def racer():
            barrier.wait(timeout=5)
            svc.updater.start()

        racers = [threading.Thread(target=racer) for _ in range(8)]
        for t in racers:
            t.start()
        for t in racers:
            t.join(timeout=5)
        spawned = [t for t in threading.enumerate()
                   if t.ident not in before
                   and t.name == "photon-online-updater"]
        assert len(spawned) == 1
        svc.updater.close(timeout=5)
        assert not spawned[0].is_alive()
        # restartable after close
        svc.updater.start()
        svc.updater.close(timeout=5)
    finally:
        svc.close()


def test_batcher_shed_callback_runs_outside_the_condition(rng):
    """Regression for the shed callback being invoked under _cv: a
    callback that itself touches the batcher (as ServingMetrics-style
    hooks legitimately may) must not deadlock."""
    from photon_ml_tpu.serving.batcher import BatcherConfig, MicroBatcher

    release = threading.Event()
    calls = []

    def slow_score(feats, ids, num_requests, queue_wait_s):
        release.wait(timeout=10)

        class R:
            scores = np.zeros(int(next(iter(feats.values())).shape[0]))
        return R()

    def on_shed():
        # re-enters the batcher: deadlocks if invoked while _cv is held
        calls.append(batcher.pending)

    batcher = MicroBatcher(slow_score,
                           BatcherConfig(max_wait_s=0.001, max_batch=4,
                                         max_queue=1),
                           on_shed=on_shed)
    try:
        import time as _time
        feats = {"global": np.zeros((1, D_G))}
        ids = {"userId": np.asarray(["u0"], dtype=object)}

        def submit():
            try:
                batcher.score(feats, ids, 1)
            except Exception:
                pass

        def wait_pending(n):
            deadline = _time.monotonic() + 5
            while _time.monotonic() < deadline:
                if batcher.pending == n:
                    return True
                _time.sleep(0.005)
            return False

        # 1st request: picked up by the worker, which blocks in score_fn
        threading.Thread(target=submit, daemon=True).start()
        assert wait_pending(0)
        # 2nd request fills the queue (max_queue=1) behind the stuck worker
        threading.Thread(target=submit, daemon=True).start()
        assert wait_pending(1)
        # 3rd request must shed IMMEDIATELY — and the callback re-enters
        # the batcher, which deadlocks if it ran under _cv
        with pytest.raises(Overloaded):
            batcher.score(feats, ids, 1)
        assert calls and all(isinstance(c, int) for c in calls)
    finally:
        release.set()
        batcher.close()


def test_disarmed_tracker_is_pass_through_on_live_service(rng):
    """ISSUE 10 acceptance (zero overhead disarmed): with no tracker
    installed the serving stack builds on RAW threading primitives — the
    module-global None check returns the lock unchanged, so the warm
    serve loop pays nothing (its zero-fresh-traces gate lives in
    test_zero_fresh_traces_warm_delta_stream)."""
    assert locktrace.active() is None
    svc = _service(rng)
    try:
        assert type(svc.registry._lock) is type(threading.Lock())
        assert isinstance(svc._batcher._cv, threading.Condition)
        assert not isinstance(svc.registry._lock, locktrace.TracedLock)
    finally:
        svc.close()


def test_delta_rollback_bit_exact_multiple_overlapping(rng):
    """Rows touched by SEVERAL deltas restore their original bits
    (newest-first revert)."""
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    try:
        table0 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
        fixed_ids = np.asarray(["u0", "u1", "u2", "u0", "u1", "u2"],
                               dtype=object)
        for s in range(3):  # 3 deltas over the SAME rows
            r = np.random.default_rng(100 + s)
            feats, ids, labels = _feedback(r, 6, ids=fixed_ids)
            svc.feedback(feats, ids, labels)
            svc.updater.flush()
        assert svc.registry.pending_deltas() == 3
        svc.rollback()
        assert np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")), table0)
    finally:
        svc.close()


# -- compile-count regression (satellite) -----------------------------------

class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0
        self.messages = []

    def emit(self, record):
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            self.count += 1
            self.messages.append(msg[:120])


class _compile_counting:
    def __enter__(self):
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_zero_fresh_traces_warm_delta_stream(rng):
    """ISSUE 9 satellite: a WARM serve loop absorbing a stream of deltas
    while scoring causes zero fresh XLA traces — scorer buckets, the
    anchored batched solver, and the gather/scatter delta programs all
    stay cached."""
    svc = _service(rng, updates=OnlineUpdateConfig(
        micro_batch=4, max_rows_per_entity=8))
    try:
        svc.updater.warmup()

        def one_round(seed):
            r = np.random.default_rng(seed)
            feats, ids, labels = _feedback(r, 12)
            svc.feedback(feats, ids, labels)
            svc.updater.flush()
            f2, i2, _ = _feedback(r, 5)
            svc.score(f2, i2)

        one_round(0)  # device_put paths
        with _compile_counting() as counter:
            for s in range(1, 6):
                one_round(s)
        assert counter.count == 0, counter.messages
        assert svc.registry.scorer.deltas_applied >= 6
    finally:
        svc.close()


# -- fault containment (satellite) ------------------------------------------

def test_transient_solve_fault_retried(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    try:
        feats, ids, labels = _feedback(rng, 8)
        svc.feedback(feats, ids, labels)
        plan = faults.FaultPlan([{"site": "online.solve",
                                  "action": "transient", "hits": [1]}])
        with faults.injected(plan):
            out = svc.updater.flush()
        assert out["deltas"] >= 1            # the retry absorbed the fault
        assert plan.report()["total_fired"] == 1
        snap = svc.metrics_snapshot()
        assert snap["online"]["solve_retries"] >= 1
        assert snap["online"]["deltas_published"] >= 1
    finally:
        svc.close()


def test_transient_publish_fault_retried(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    try:
        feats, ids, labels = _feedback(rng, 8)
        svc.feedback(feats, ids, labels)
        plan = faults.FaultPlan([{"site": "online.publish",
                                  "action": "transient", "hits": [1]}])
        with faults.injected(plan):
            out = svc.updater.flush()
        assert out["deltas"] >= 1
        assert plan.report()["total_fired"] == 1
    finally:
        svc.close()


def test_nonfinite_solve_freezes_entity_not_table(rng):
    """ISSUE 9 satellite: a non-finite online solve FREEZES the entity —
    the live table row is untouched (scoring continues on the batch
    solution) and later feedback for the frozen entity is dropped."""
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=4))
    try:
        table0 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
        feats, ids, labels = _feedback(
            rng, 4, ids=np.asarray(["u3", "u3", "u4", "u4"], dtype=object))
        svc.feedback(feats, ids, labels)
        plan = faults.FaultPlan([{"site": "online.solve",
                                  "action": "poison", "hits": [1]}])
        with faults.injected(plan):
            out = svc.updater.flush()
        assert out["deltas"] == 0            # nothing publishable survived
        # the live table is bit-identical: the poison never landed
        assert np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")), table0)
        frozen = svc.updater.frozen_entities()
        assert {e for _l, e in frozen} == {"u3", "u4"}
        # later feedback for a frozen entity is dropped and counted
        f2, i2, l2 = _feedback(rng, 2,
                               ids=np.asarray(["u3", "u5"], dtype=object))
        out2 = svc.feedback(f2, i2, l2)
        assert out2["dropped_frozen"] == 1 and out2["accepted"] == 1
        snap = svc.metrics_snapshot()
        assert snap["online"]["frozen_entities"] == 2
        # healthy entities keep updating
        assert svc.updater.flush()["deltas"] >= 1
    finally:
        svc.close()


def test_fatal_solve_fault_drops_batch_without_poisoning(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=4))
    try:
        table0 = np.asarray(svc.registry.scorer.re_table("perUser")).copy()
        feats, ids, labels = _feedback(rng, 4)
        svc.feedback(feats, ids, labels)
        plan = faults.FaultPlan([{"site": "online.solve",
                                  "action": "fatal", "hits": [1]}])
        with faults.injected(plan):
            out = svc.updater.flush()
        assert out["deltas"] == 0
        assert np.array_equal(
            np.asarray(svc.registry.scorer.re_table("perUser")), table0)
        assert svc.metrics_snapshot()["online"]["solve_failures"] == 1
    finally:
        svc.close()


# -- metrics / observability -------------------------------------------------

def test_staleness_and_latency_surfaces(rng):
    svc = _service(rng, updates=OnlineUpdateConfig(micro_batch=8))
    try:
        snap0 = svc.metrics_snapshot()
        assert snap0["model_age_s"] >= 0.0
        assert snap0["online"]["feedback_to_publish_ms"] is None
        feats, ids, labels = _feedback(rng, 10)
        svc.feedback(feats, ids, labels)
        svc.updater.flush()
        snap = svc.metrics_snapshot()
        # a delta publish resets model age
        assert snap["model_age_s"] <= snap0["model_age_s"] + 0.5
        f2p = snap["online"]["feedback_to_publish_ms"]
        assert f2p is not None and f2p["p50"] >= 0.0 and \
            f2p["p99"] >= f2p["p50"]
        assert snap["version_vector"]["delta_seq"] >= 1
        # Prometheus text exposition carries the new surfaces
        text = svc.prometheus_metrics()
        assert "photon_serve_model_age_s" in text
        assert 'photon_online_feedback_to_publish_s{quantile="0.99"}' in text
        assert "photon_online_deltas_published_total" in text
    finally:
        svc.close()


def test_background_updater_end_to_end(rng):
    """The real deployment shape: background loop armed, feedback arrives,
    deltas land without any manual flush."""
    svc = _service(rng, updates=OnlineUpdateConfig(
        micro_batch=8, interval_s=0.01), start_updater=True)
    try:
        feats, ids, labels = _feedback(rng, 12)
        svc.feedback(feats, ids, labels)
        import time as _time
        deadline = _time.time() + 60
        while _time.time() < deadline:
            if svc.metrics_snapshot()["online"]["deltas_published"] >= 1 \
                    and svc.updater.buffer.pending_rows == 0:
                break
            _time.sleep(0.02)
        snap = svc.metrics_snapshot()
        assert snap["online"]["deltas_published"] >= 1
        assert snap["online"]["entities_updated"] >= 1
    finally:
        svc.close()


def test_feedback_requires_updates_enabled(rng):
    svc = _service(rng)   # no updates config
    try:
        feats, ids, labels = _feedback(rng, 2)
        with pytest.raises(RuntimeError, match="--enable-updates"):
            svc.feedback(feats, ids, labels)
    finally:
        svc.close()


def test_http_feedback_endpoint(rng):
    """cli.serve's POST /feedback and version-vector /healthz, against an
    in-thread HTTP server (no subprocess: the serve CLI's handler wiring
    is what is under test)."""
    import json as _json
    import time as _time
    import urllib.request

    from photon_ml_tpu.cli.serve import _make_http_server
    svc = _service(rng, updates=OnlineUpdateConfig(
        micro_batch=8, interval_s=0.01), start_updater=True)
    httpd = _make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever,
                         kwargs={"poll_interval": 0.05}, daemon=True)
    t.start()

    def post(path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            return resp.status, _json.loads(resp.read())

    try:
        feats, ids, labels = _feedback(rng, 6)
        status, out = post("/feedback", {
            "features": {s: x.tolist() for s, x in feats.items()},
            "ids": {t_: v.tolist() for t_, v in ids.items()},
            "labels": labels.tolist(),
            "event_ids": [f"ev{i}" for i in range(6)]})
        assert status == 202
        assert out["accepted"] == 6
        assert "version_vector" in out
        # same event ids again: all deduped
        status, out2 = post("/feedback", {
            "features": {s: x.tolist() for s, x in feats.items()},
            "ids": {t_: v.tolist() for t_, v in ids.items()},
            "labels": labels.tolist(),
            "event_ids": [f"ev{i}" for i in range(6)]})
        assert status == 202 and out2["accepted"] == 0
        deadline = _time.time() + 60
        while _time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz") as resp:
                health = _json.loads(resp.read())
            if health["version_vector"]["delta_seq"] >= 1:
                break
            _time.sleep(0.02)
        assert health["updates_enabled"] is True
        assert health["version_vector"]["delta_seq"] >= 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as resp:
            text = resp.read().decode()
        assert "photon_serve_model_age_s" in text
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)
        svc.close()
