"""Unified telemetry (ISSUE 8): span tracer disarm semantics, thread-aware
hierarchy, metrics registry, exporters, run-log correlation, EventEmitter
routing/isolation, and the compile-count + sync-point regression gates
that keep the instrumentation off the device hot path.
"""
import json
import logging
import threading

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry.export import prometheus_text
from photon_ml_tpu.telemetry.metrics import MetricsRegistry
from photon_ml_tpu.utils.events import (
    EventEmitter, EventListener, ScoringBatchEvent, TrainingStartEvent,
)


# --------------------------------------------------------------------------
# disarm semantics
# --------------------------------------------------------------------------

def test_disarmed_span_is_the_shared_noop_singleton():
    """faults.fire()-style disarm: a module-global None check returning
    ONE shared object — no span allocation, no record, no tracer."""
    assert not telemetry.armed()
    a = telemetry.span("anything", attr=1)
    b = telemetry.span("other")
    assert a is b is telemetry.NOOP_SPAN
    with a:
        assert telemetry.current_span_id() is None
    assert telemetry.push("x") is None
    telemetry.pop(None)                    # no-op, no error
    telemetry.event("nothing", k=2)        # no-op


def test_enabled_scope_arms_and_disarms():
    assert not telemetry.armed()
    with telemetry.enabled(watch_compiles=False) as tracer:
        assert telemetry.armed()
        assert telemetry.active_tracer() is tracer
    assert not telemetry.armed()
    assert telemetry.last_tracer() is tracer  # still exportable


# --------------------------------------------------------------------------
# span hierarchy
# --------------------------------------------------------------------------

def test_span_nesting_parents_and_attrs():
    with telemetry.enabled(watch_compiles=False) as tracer:
        with telemetry.span("outer", iteration=3) as outer:
            assert telemetry.current_span_id() == outer.span_id
            with telemetry.span("inner", coordinate="perUser") as inner:
                pass
        assert telemetry.current_span_id() is None
    assert inner.parent_id == outer.span_id
    assert outer.parent_id is None
    assert inner.attrs == {"coordinate": "perUser"}
    assert all(s.dur_s is not None and s.dur_s >= 0 for s in tracer.spans)


def test_push_pop_self_heals_abandoned_spans():
    with telemetry.enabled(watch_compiles=False) as tracer:
        a = telemetry.push("a")
        telemetry.push("b")  # never popped explicitly
        telemetry.pop(a)     # closes b, then a
        assert telemetry.current_span_id() is None
    names = [s.name for s in tracer.spans]
    assert names == ["b", "a"]


def test_finish_closes_spans_left_open_by_an_exception():
    with telemetry.enabled(watch_compiles=False) as tracer:
        telemetry.push("leaked")
    # enabled.__exit__ -> shutdown -> finish heals the stack
    assert [s.name for s in tracer.spans] == ["leaked"]
    assert tracer.stats()["open_spans"] == 0


def test_threads_get_their_own_span_roots():
    with telemetry.enabled(watch_compiles=False) as tracer:
        with telemetry.span("main_root"):
            def work():
                with telemetry.span("bg_root"):
                    pass
            t = threading.Thread(target=work, name="photon-test-bg")
            t.start()
            t.join()
    bg = next(s for s in tracer.spans if s.name == "bg_root")
    main = next(s for s in tracer.spans if s.name == "main_root")
    assert bg.parent_id is None          # thread root, not nested in main
    assert bg.tid != main.tid
    assert bg.thread_name == "photon-test-bg"


def test_event_attaches_to_current_span():
    with telemetry.enabled(watch_compiles=False) as tracer:
        with telemetry.span("visit") as visit:
            telemetry.event("fault", site="solve.poison")
        telemetry.event("orphan")
    assert tracer.events[0]["span"] == visit.span_id
    assert tracer.events[1]["span"] is None


# --------------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------------

def test_counters_gauges_and_type_collision():
    r = MetricsRegistry()
    r.counter("c").inc()
    r.counter("c").inc(4)
    assert r.counter("c").value == 5
    with pytest.raises(ValueError):
        r.counter("c").inc(-1)
    r.gauge("g").set(2.5)
    assert r.gauge("g").value == 2.5
    with pytest.raises(TypeError):
        r.gauge("c")  # name already a counter


def test_histogram_reservoir_is_bounded_and_exact_counts():
    r = MetricsRegistry()
    h = r.histogram("lat", reservoir=64)
    for i in range(10_000):
        h.observe(i)
    snap = h.snapshot()
    assert snap["count"] == 10_000          # exact
    assert snap["max"] == 9_999.0           # exact
    assert snap["window"] == 64             # bounded
    # the reservoir is a newest-N window, so percentiles track the tail
    assert snap["p50"] >= 9_900
    assert snap["p99"] >= snap["p95"] >= snap["p50"]
    assert json.dumps(r.snapshot())         # JSON-safe


def test_snapshot_includes_collectors():
    telemetry.register_collector("test_collector", lambda: {"x": 1})
    try:
        snap = telemetry.snapshot()
        assert snap["test_collector"] == {"x": 1}
        assert "metrics" in snap
        json.dumps(snap)
    finally:
        telemetry.unregister_collector("test_collector")
    assert "test_collector" not in telemetry.snapshot()


def test_prometheus_text_exposition():
    r = MetricsRegistry()
    r.counter("serving.requests").inc(7)
    r.gauge("train.host_blocked_frac").set(0.25)
    h = r.histogram("serving.latency_s", reservoir=16)
    h.observe(0.01)
    h.observe(0.02)
    text = prometheus_text(r, extra_info={"model_version": "v3"})
    assert "# TYPE photon_serving_requests_total counter" in text
    assert "photon_serving_requests_total 7" in text
    assert "photon_train_host_blocked_frac 0.25" in text
    assert "# TYPE photon_serving_latency_s summary" in text
    assert 'photon_serving_latency_s{quantile="0.99"}' in text
    assert "photon_serving_latency_s_count 2" in text
    assert 'photon_info{model_version="v3"} 1' in text
    assert text.endswith("\n")


# --------------------------------------------------------------------------
# exporters + run log
# --------------------------------------------------------------------------

def test_chrome_trace_export_required_keys_and_tree(tmp_path):
    with telemetry.enabled(watch_compiles=False):
        with telemetry.span("outer_iteration", iteration=0):
            with telemetry.span("coordinate_visit", coordinate="fixed"):
                telemetry.event("fault", site="stage.fetch")
    out = tmp_path / "trace.json"
    info = telemetry.write_chrome_trace(str(out))
    assert info["events"] >= 3
    payload = json.loads(out.read_text())
    assert telemetry.validate_chrome_trace(payload) == []
    events = payload["traceEvents"]
    spans = {e["args"]["span"]: e for e in events if e["ph"] == "X"}
    visit = next(e for e in events if e["name"] == "coordinate_visit")
    assert spans[visit["args"]["parent"]]["name"] == "outer_iteration"
    instant = next(e for e in events if e["name"] == "fault")
    assert instant["ph"] == "i"
    assert instant["args"]["span"] == visit["args"]["span"]


def test_validate_chrome_trace_flags_missing_keys():
    bad = {"traceEvents": [{"name": "x", "ph": "X", "ts": 0, "pid": 1}]}
    problems = telemetry.validate_chrome_trace(bad)
    assert any("tid" in p for p in problems)
    assert any("dur" in p for p in problems)
    assert telemetry.validate_chrome_trace({"traceEvents": []})


def test_run_log_correlates_spans_and_events(tmp_path):
    log_path = tmp_path / "run.jsonl"
    with telemetry.enabled(run_log=str(log_path), watch_compiles=False):
        with telemetry.span("coordinate_visit", coordinate="perUser"):
            telemetry.event("quarantine", action="rolled_back")
    records = [json.loads(line) for line in log_path.read_text().splitlines()]
    ev = next(r for r in records if r["kind"] == "event")
    span = next(r for r in records if r["kind"] == "span")
    assert ev["span"] == span["span"]
    assert span["attrs"]["coordinate"] == "perUser"
    assert ev["attrs"]["action"] == "rolled_back"


# --------------------------------------------------------------------------
# EventEmitter routing + listener isolation (ISSUE 8 satellite)
# --------------------------------------------------------------------------

class _Boom(EventListener):
    def handle(self, event):
        raise RuntimeError("listener exploded")


class _Sink(EventListener):
    def __init__(self):
        self.got = []

    def handle(self, event):
        self.got.append(event)


def test_listener_exception_is_isolated_from_remaining_listeners(caplog):
    emitter = EventEmitter()
    first, last = _Sink(), _Sink()
    emitter.register_listener(first)
    emitter.register_listener(_Boom())
    emitter.register_listener(last)
    with caplog.at_level(logging.ERROR, "photon_ml_tpu.utils.events"):
        emitter.send_event(TrainingStartEvent(time=1.0))
    # the raising listener neither killed emission nor starved the
    # listeners registered AFTER it
    assert len(first.got) == 1 and len(last.got) == 1
    assert any("event listener failed" in r.message for r in caplog.records)


def test_emitted_events_route_into_run_log_with_span_id(tmp_path):
    log_path = tmp_path / "run.jsonl"
    emitter = EventEmitter()
    emitter.register_listener(_Sink())
    with telemetry.enabled(run_log=str(log_path), watch_compiles=False):
        with telemetry.span("serve_batch") as batch_span:
            emitter.send_event(ScoringBatchEvent(
                time=1.0, num_requests=3, num_rows=7, bucket_size=8,
                queue_wait_s=0.001, score_s=0.002, model_version="v1"))
    records = [json.loads(line) for line in log_path.read_text().splitlines()]
    ev = next(r for r in records
              if r["name"] == "emitted.ScoringBatchEvent")
    assert ev["span"] == batch_span.span_id
    assert ev["attrs"]["num_rows"] == 7
    assert ev["attrs"]["model_version"] == "v1"


def test_emitter_without_tracer_stays_silent():
    emitter = EventEmitter()
    sink = _Sink()
    emitter.register_listener(sink)
    emitter.send_event(TrainingStartEvent(time=2.0))  # disarmed: no crash
    assert len(sink.got) == 1


def test_clear_listeners_close_hooks_run_outside_the_lock():
    """Regression for listener close hooks running under the emitter
    lock: a close() that re-enters the emitter (registering a
    replacement, clearing again) must not deadlock — the listener list
    is swapped under the lock and closed OUTSIDE it."""
    emitter = EventEmitter()
    closed = []

    class Reentrant(EventListener):
        def handle(self, event):
            pass

        def close(self):
            closed.append(True)
            emitter.register_listener(_Sink())   # takes the emitter lock

    emitter.register_listener(Reentrant())
    emitter.clear_listeners()                    # deadlocked before fix
    assert closed == [True]
    # the re-registered sink survived the clear (it landed after swap)
    emitter.send_event(TrainingStartEvent(time=3.0))


# --------------------------------------------------------------------------
# hot-path regression gates
# --------------------------------------------------------------------------

def _tiny_game(rng):
    from photon_ml_tpu.data.game_data import build_game_dataset
    n, E = 400, 20
    xg = rng.normal(size=(n, 5))
    xu = rng.normal(size=(n, 3))
    users = np.asarray([f"u{i % E}" for i in range(n)], dtype=object)
    y = (rng.uniform(size=n) < 0.5).astype(float)
    return build_game_dataset(y, {"global": xg, "per_user": xu},
                              entity_ids={"userId": users})


def _tiny_config(outer=2):
    from photon_ml_tpu.game import (FixedEffectCoordinateConfig,
                                    GameTrainingConfig,
                                    GLMOptimizationConfig,
                                    RandomEffectCoordinateConfig)
    from photon_ml_tpu.optim import (OptimizerConfig, RegularizationContext,
                                     RegularizationType)
    l2 = RegularizationContext(RegularizationType.L2)
    opt = GLMOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=4),
        regularization=l2, regularization_weight=1.0)
    return GameTrainingConfig(
        task_type="logistic_regression",
        coordinates={
            "fixed": FixedEffectCoordinateConfig("global", opt),
            "perUser": RandomEffectCoordinateConfig(
                "userId", "per_user", opt, projector="identity")},
        updating_sequence=["fixed", "perUser"],
        num_outer_iterations=outer)


class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if record.getMessage().startswith("Compiling "):
            self.count += 1


class _compile_counting:
    def __enter__(self):
        import jax
        self._jax = jax
        self.handler = _CompileCounter()
        self.logger = logging.getLogger("jax._src.interpreters.pxla")
        self._level = self.logger.level
        self.logger.addHandler(self.handler)
        self.logger.setLevel(logging.WARNING)
        jax.config.update("jax_log_compiles", True)
        return self.handler

    def __exit__(self, *exc):
        self._jax.config.update("jax_log_compiles", False)
        self.logger.removeHandler(self.handler)
        self.logger.setLevel(self._level)


def test_armed_telemetry_adds_zero_fresh_traces_to_a_warm_fit(rng):
    """The compile-count regression the tentpole promises: once a fit's
    shapes are warm, running the SAME fit with the tracer armed must not
    introduce a single fresh XLA trace (span names/attrs never reach a
    jit boundary), and disarmed instrumentation obviously must not
    either."""
    from photon_ml_tpu.game import GameEstimator
    ds = _tiny_game(rng)
    GameEstimator(_tiny_config()).fit(ds)  # warm every program

    with _compile_counting() as counter:
        GameEstimator(_tiny_config()).fit(ds)
    assert counter.count == 0, (
        f"{counter.count} fresh traces on a warm DISARMED fit")

    with _compile_counting() as counter:
        with telemetry.enabled(watch_compiles=False) as tracer:
            result = GameEstimator(_tiny_config()).fit(ds)
    assert counter.count == 0, (
        f"{counter.count} fresh traces on a warm ARMED fit — telemetry "
        "leaked into a trace cache key or forced a retrace")
    # the armed fit actually traced spans (it wasn't a silent no-op)
    names = {s.name for s in tracer.spans}
    assert {"fit", "outer_iteration", "coordinate_visit", "solve"} <= names
    # and the per-coordinate retrace surface reports zero everywhere
    for diag in result.descent.solver_diagnostics().values():
        assert diag["retraces"] == 0
        assert "host_blocked_s" in diag


def test_retrace_counter_counts_fresh_compiles_with_signature():
    """The PH002 runtime counterpart: a genuinely fresh compile under an
    armed compile watch increments jax.retraces and records a compile
    event carrying the triggering signature."""
    import jax
    import jax.numpy as jnp
    before = telemetry.retrace_count()
    with telemetry.enabled() as tracer:  # watch_compiles=True default
        with telemetry.span("coordinate_visit", coordinate="fresh"):
            # a shape this process has never traced (odd prime size)
            f = jax.jit(lambda x: (x * 1.000173).sum())
            float(f(jnp.zeros(1913)))
    assert telemetry.retrace_count() > before
    compiles = [e for e in tracer.events if e["name"] == "compile"]
    assert compiles, "no compile events recorded by the watch"
    assert any("1913" in e["attrs"].get("signature", "")
               for e in compiles)
    # attribution: the compile event is attached to the span that
    # triggered the trace
    visit = next(s for s in tracer.spans
                 if s.name == "coordinate_visit")
    assert any(e["span"] == visit.span_id for e in compiles)
    assert not jax.config.jax_log_compiles  # restored on disarm


def test_phase_timings_bridges_to_telemetry_spans():
    from photon_ml_tpu.telemetry.timings import PhaseTimings
    spans = PhaseTimings()
    with telemetry.enabled(watch_compiles=False) as tracer:
        with spans.span("0/fixed/solve", name="solve", coordinate="fixed",
                        iteration=0):
            pass
        with spans.blocked("0/fixed/solve"):
            pass
    assert "0/fixed/solve" in spans               # dict accounting intact
    assert spans.host_blocked["0/fixed/solve"] >= 0
    solve = next(s for s in tracer.spans if s.name == "solve")
    assert solve.attrs == {"coordinate": "fixed", "iteration": 0}
    # disarmed: the dict side keeps working with zero tracer records
    with spans.span("1/fixed/solve"):
        pass
    assert "1/fixed/solve" in spans


def test_fired_fault_lands_in_trace_with_site(tmp_path):
    from photon_ml_tpu.utils import faults
    plan = faults.FaultPlan([{"site": "stage.fetch", "action": "transient",
                              "hits": [1]}])
    before = telemetry.counter("faults.fired").value
    with telemetry.enabled(watch_compiles=False) as tracer:
        with telemetry.span("stage", chunk=0):
            with faults.injected(plan):
                with pytest.raises(faults.TransientFault):
                    faults.fire("stage.fetch", chunk=0)
    fault = next(e for e in tracer.events if e["name"] == "fault")
    assert fault["attrs"]["site"] == "stage.fetch"
    assert fault["attrs"]["action"] == "transient"
    stage = next(s for s in tracer.spans if s.name == "stage")
    assert fault["span"] == stage.span_id
    assert telemetry.counter("faults.fired").value == before + 1


def test_instrumented_hot_modules_stay_ph001_clean():
    """Armed tracing must stay off the device hot path: photonlint PH001
    (host-sync rule) over exactly the modules this PR instrumented."""
    import photon_ml_tpu
    from photon_ml_tpu.analysis.engine import lint_paths
    import os
    pkg = os.path.dirname(os.path.abspath(photon_ml_tpu.__file__))
    instrumented = [
        os.path.join(pkg, "game", "coordinate_descent.py"),
        os.path.join(pkg, "game", "quarantine.py"),
        os.path.join(pkg, "parallel", "mesh_residency.py"),
        os.path.join(pkg, "serving", "service.py"),
        os.path.join(pkg, "serving", "metrics.py"),
        os.path.join(pkg, "serving", "scorer.py"),
    ]
    findings = lint_paths(instrumented, select=["PH001", "PH007"])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_serving_metrics_latency_reservoir_is_bounded():
    """ISSUE 8 satellite: ServingMetrics percentiles come from the
    registry's bounded reservoir — 100k observations cost a fixed window,
    and p50/p95/p99 all surface in snapshot()."""
    from photon_ml_tpu.serving.metrics import ServingMetrics
    m = ServingMetrics(latency_window=128)
    for i in range(100_000):
        m.observe_request(latency_s=0.001 + (i % 10) * 1e-4, rows=1)
    snap = m.snapshot(model_version="vX")
    assert snap["requests"] == 100_000
    assert snap["latency_ms"]["window"] == 128
    for key in ("p50", "p90", "p95", "p99", "max"):
        assert snap["latency_ms"][key] >= 0
    assert snap["latency_ms"]["p99"] >= snap["latency_ms"]["p50"]
    assert snap["model_version"] == "vX"
    prom = m.prometheus(model_version="vX")
    assert "photon_serving_requests_total 100000" in prom
    assert 'photon_serving_latency_s{quantile="0.95"}' in prom
